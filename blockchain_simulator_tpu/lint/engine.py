"""jaxlint engine: AST walker, suppressions, baseline, CLI.

``python -m blockchain_simulator_tpu.lint [paths...]`` parses every ``.py``
file under the given paths (never importing them — rules police import-time
behavior, so the linter must not trigger it), runs every registered rule
(rules/__init__.py), and reports findings that are neither

- **suppressed** — an inline ``# jaxlint: disable=<rule>[,<rule>...]``
  comment on any line the offending node spans (use for sites whose
  justification belongs next to the code, e.g. obs.py's guarded backend
  read), nor
- **baselined** — grandfathered in ``LINT_BASELINE.json`` at the repo root:
  entries keyed by (rule, path, stripped source line) with a count and a
  one-line justification.  Keying on line TEXT instead of line numbers keeps
  the baseline stable across unrelated edits.  ``--write-baseline``
  regenerates the file, preserving existing justifications.

Exit codes: 0 = clean vs the baseline, 1 = new findings, 2 = a file failed
to parse (or usage error).  When ``$BLOCKSIM_RUNS_JSONL`` is set the run is
recorded through utils/obs.py like every other entrypoint, so the findings
trajectory charts in ``tools/bench_compare.py`` next to the perf history.
"""

from __future__ import annotations

import argparse
import ast
import io
import json
import os
import re
import sys
import tokenize
from collections import Counter

from blockchain_simulator_tpu.lint import common
from blockchain_simulator_tpu.lint.rules import ALL_RULES, RULES_BY_ID

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BASELINE_NAME = "LINT_BASELINE.json"

SUPPRESS_RE = re.compile(r"#\s*jaxlint:\s*disable=([A-Za-z0-9_, \-]+)")


def rel_path(path: str, root: str = REPO_ROOT) -> str:
    """Repo-relative posix path (the identity used in findings, baseline
    entries and suppressions); absolute if outside the repo."""
    ap = os.path.abspath(path)
    try:
        rp = os.path.relpath(ap, root)
    except ValueError:
        return ap.replace(os.sep, "/")
    if rp.startswith(".."):
        return ap.replace(os.sep, "/")
    return rp.replace(os.sep, "/")


def parse_suppressions(src: str) -> dict[int, set[str]]:
    """Per-line suppression directives, read from COMMENT tokens only — a
    ``# jaxlint: disable=`` sequence inside a string literal is content,
    not a directive."""
    sup: dict[int, set[str]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(src).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = SUPPRESS_RE.search(tok.string)
            if m:
                sup.setdefault(tok.start[0], set()).update(
                    r.strip() for r in m.group(1).split(",") if r.strip()
                )
    except tokenize.TokenError:  # ast.parse succeeded; be permissive
        for i, line in enumerate(src.splitlines(), start=1):
            m = SUPPRESS_RE.search(line)
            if m:
                sup[i] = {r.strip() for r in m.group(1).split(",")
                          if r.strip()}
    return sup


def lint_source(
    src: str, path: str = "<memory>", rules=None, stale_sup_out=None
) -> tuple[list[common.Finding], int]:
    """Lint one source blob; returns (findings, n_suppressed).

    Raises ``SyntaxError`` for unparseable source — callers decide whether
    that is exit-2 (CLI) or a test failure (fixtures).

    ``stale_sup_out`` (a list) collects ``(path, line, rule)`` for inline
    ``# jaxlint: disable=`` directives that suppressed nothing — dead
    suppressions that would silently swallow a future real finding.  Only
    populated on full-rule runs (``rules=None``): a subset run cannot decide
    that a directive for an un-run rule is dead.
    """
    tree = ast.parse(src)
    common.annotate_parents(tree)
    src_lines = src.splitlines()
    ctx = common.RuleContext(
        path=path,
        tree=tree,
        src_lines=src_lines,
        aliases=common.import_aliases(tree),
        functions=common.FunctionIndex(tree),
    )
    findings: list[common.Finding] = []
    for rule in (rules if rules is not None else ALL_RULES):
        findings.extend(rule.check(ctx))

    sup = parse_suppressions(src)
    kept: list[common.Finding] = []
    n_suppressed = 0
    used: set[tuple[int, str]] = set()
    for f in findings:
        span = range(f.line, (f.end_line or f.line) + 1)
        directives: set[str] = set()
        for ln in span:
            directives |= sup.get(ln, set())
        if f.rule in directives or "all" in directives:
            n_suppressed += 1
            match = f.rule if f.rule in directives else "all"
            for ln in span:
                if match in sup.get(ln, set()):
                    used.add((ln, match))
        else:
            kept.append(f)
    if stale_sup_out is not None and rules is None:
        for ln in sorted(sup):
            for rule_id in sorted(sup[ln]):
                if (ln, rule_id) not in used:
                    stale_sup_out.append((path, ln, rule_id))
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return kept, n_suppressed


def resolve_path_args(raw: list[str]) -> list[str]:
    """CLI path args are repo-root-relative by contract (SKILL.md/README):
    a relative arg resolves against REPO_ROOT first and falls back to the
    cwd only when the rooted path does not exist.  Root-FIRST, not
    cwd-presence-dependent — a foreign cwd that happens to contain its own
    ``tools/`` must not hijack the documented invocation."""
    out = []
    for p in raw:
        if not os.path.isabs(p):
            rooted = os.path.join(REPO_ROOT, p)
            if os.path.exists(rooted):
                out.append(rooted)
                continue
        out.append(p)
    return out


def iter_py_files(paths: list[str]):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            else:
                # an explicit non-.py file arg is a misconfiguration: a CI
                # gate that typo'd its target must fail loudly, not lint
                # nothing and exit 0
                raise FileNotFoundError(f"not a Python file: {p}")
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d != "__pycache__" and not d.startswith(".")
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)
        else:
            raise FileNotFoundError(f"no such path: {p}")


def lint_paths(
    paths: list[str], rules=None, stale_sup_out=None
) -> tuple[list[common.Finding], dict[str, list[str]], int, list[str]]:
    """Lint every file under ``paths``; returns
    (findings, {linted_rel_path: src_lines}, n_suppressed, parse_errors).
    The returned sources are THE text the findings were computed against —
    baseline keying reuses them instead of re-reading from disk.
    ``stale_sup_out`` aggregates dead inline suppressions per
    :func:`lint_source`."""
    findings: list[common.Finding] = []
    files: dict[str, list[str]] = {}
    n_suppressed = 0
    errors: list[str] = []
    for fp in iter_py_files(paths):
        rp = rel_path(fp)
        if rp in files:
            continue  # overlapping path args must not double-count findings
        try:
            with open(fp, encoding="utf-8") as f:
                src = f.read()
        except OSError as e:
            errors.append(f"{fp}: {e}")
            continue
        files[rp] = src.splitlines()
        try:
            fs, ns = lint_source(src, path=rp, rules=rules,
                                 stale_sup_out=stale_sup_out)
        except SyntaxError as e:
            errors.append(f"{fp}: syntax error: {e}")
            continue
        findings.extend(fs)
        n_suppressed += ns
    return findings, files, n_suppressed, errors


# ---------------------------------------------------------------- baseline

def load_baseline(path: str) -> dict[tuple[str, str, str], dict]:
    """Baseline file -> {(rule, path, line_text): entry}."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    out = {}
    for e in doc.get("entries", []):
        out[(e["rule"], e["path"], e["text"])] = {
            "count": int(e.get("count", 1)),
            "justification": e.get("justification", ""),
        }
    return out


def split_by_baseline(
    findings: list[common.Finding],
    baseline: dict[tuple[str, str, str], dict],
    line_text_of,
    used_out: Counter | None = None,
) -> tuple[list[common.Finding], int, list[tuple[str, str, str]]]:
    """(new findings, n_baselined, stale baseline keys).  ``used_out``
    receives the per-key consumed counts (``--prune-baseline`` rewrites
    entries down to exactly these)."""
    used: Counter = used_out if used_out is not None else Counter()
    new: list[common.Finding] = []
    for f in findings:
        key = f.key(line_text_of(f))
        allowed = baseline.get(key, {}).get("count", 0)
        if used[key] < allowed:
            used[key] += 1
        else:
            new.append(f)
    stale = [k for k, e in baseline.items() if used[k] < e["count"]]
    return new, sum(used.values()), stale


def write_baseline(
    path: str,
    findings: list[common.Finding],
    line_text_of,
    old: dict[tuple[str, str, str], dict] | None = None,
    linted_paths: list[str] | None = None,
) -> None:
    """Write findings as the new baseline.  Old entries keep their
    justifications; old entries for paths OUTSIDE ``linted_paths`` are
    preserved wholesale, so re-baselining one file never silently drops the
    grandfathered findings (and hand-written justifications) of the rest of
    the tree."""
    counts: Counter = Counter()
    for f in findings:
        counts[f.key(line_text_of(f))] += 1
    if old and linted_paths is not None:
        in_scope = set(linted_paths)
        for (rule, fpath, text), entry in old.items():
            if fpath in in_scope or (rule, fpath, text) in counts:
                continue
            # entries for files that no longer exist are droppable here —
            # otherwise a deleted/renamed file's entry would survive every
            # --write-baseline and warn as stale forever
            fp = fpath if os.path.isabs(fpath) \
                else os.path.join(REPO_ROOT, fpath)
            if os.path.exists(fp):
                counts[(rule, fpath, text)] = entry["count"]
    entries = []
    for (rule, fpath, text), count in sorted(counts.items()):
        just = (old or {}).get((rule, fpath, text), {}).get(
            "justification", "TODO: justify or fix"
        )
        entries.append({
            "rule": rule, "path": fpath, "text": text, "count": count,
            "justification": just,
        })
    doc = {
        "jaxlint_baseline": 1,
        "comment": (
            "Grandfathered findings: (rule, path, stripped source line) -> "
            "count + one-line justification.  Regenerate with `python -m "
            "blockchain_simulator_tpu.lint --write-baseline` (existing "
            "justifications are preserved); new code must come in clean."
        ),
        "entries": entries,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")


def prune_baseline(
    path: str,
    findings: list[common.Finding],
    line_text_of,
    old: dict[tuple[str, str, str], dict],
    linted_paths,
) -> tuple[list[tuple[str, str, str]], int]:
    """Baseline hygiene (``--prune-baseline``): rewrite the baseline with
    each in-scope entry's count reduced to what actually still fires —
    justifications preserved, fully-fixed entries dropped.  Entries for
    files outside ``linted_paths`` are preserved wholesale (the
    ``write_baseline`` subset contract).  Returns (dropped keys,
    n_reduced)."""
    used: Counter = Counter()
    split_by_baseline(findings, old, line_text_of, used_out=used)
    in_scope = set(linted_paths)
    counts: Counter = Counter()
    dropped: list[tuple[str, str, str]] = []
    n_reduced = 0
    for key, entry in old.items():
        if key[1] not in in_scope:
            # entries for files that no longer exist ARE decidable — a
            # deleted/renamed file's entry is exactly the staleness this
            # command exists to clean (the write_baseline contract)
            fp = key[1] if os.path.isabs(key[1]) \
                else os.path.join(REPO_ROOT, key[1])
            if os.path.exists(fp):
                counts[key] = entry["count"]  # not linted: not decidable
            else:
                dropped.append(key)
            continue
        still = used[key]
        if still == 0:
            dropped.append(key)
        else:
            if still < entry["count"]:
                n_reduced += 1
            counts[key] = still
    entries = []
    for (rule, fpath, text), count in sorted(counts.items()):
        entries.append({
            "rule": rule, "path": fpath, "text": text, "count": count,
            "justification": old[(rule, fpath, text)]["justification"],
        })
    doc = {
        "jaxlint_baseline": 1,
        "comment": (
            "Grandfathered findings: (rule, path, stripped source line) -> "
            "count + one-line justification.  Regenerate with `python -m "
            "blockchain_simulator_tpu.lint --write-baseline` (existing "
            "justifications are preserved); new code must come in clean."
        ),
        "entries": entries,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    return dropped, n_reduced


# --------------------------------------------------------------------- CLI

def _default_paths() -> list[str]:
    out = [os.path.join(REPO_ROOT, "blockchain_simulator_tpu")]
    for extra in ("tools", "bench.py"):
        p = os.path.join(REPO_ROOT, extra)
        if os.path.exists(p):
            out.append(p)
    return out


def _line_text_reader(sources: dict[str, list[str]] | None = None):
    """Baseline keying: finding -> stripped source-line text.  ``sources``
    (lint_paths' output) is the text the findings were computed against;
    disk reads are only a fallback for findings from other runs."""
    cache: dict[str, list[str]] = dict(sources or {})

    def line_text_of(f: common.Finding) -> str:
        lines = cache.get(f.path)
        if lines is None:
            fp = f.path if os.path.isabs(f.path) \
                else os.path.join(REPO_ROOT, f.path)
            try:
                with open(fp, encoding="utf-8") as fh:
                    lines = fh.read().splitlines()
            except OSError:
                lines = []
            cache[f.path] = lines
        if 1 <= f.line <= len(lines):
            return lines[f.line - 1].strip()
        return ""

    return line_text_of


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="blockchain_simulator_tpu.lint",
        description="jaxlint: repo-specific traced-purity / PRNG / "
                    "backend-safety static analysis",
    )
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: the package + tools "
                        "+ bench.py)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: {BASELINE_NAME} at the "
                        "repo root when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, grandfathered or not")
    p.add_argument("--write-baseline", action="store_true",
                   help="write the current findings as the new baseline "
                        "(preserves existing justifications) and exit 0")
    p.add_argument("--prune-baseline", action="store_true",
                   help="baseline hygiene: drop/shrink baseline entries "
                        "that no longer fire (justifications preserved), "
                        "report dead inline suppressions, and exit 0")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        for rid, mod in sorted(RULES_BY_ID.items()):
            print(f"{rid:<32} {mod.SUMMARY}")
        return 0

    paths = resolve_path_args(args.paths) if args.paths \
        else _default_paths()
    stale_sups: list[tuple[str, int, str]] = []
    try:
        findings, files, n_suppressed, errors = lint_paths(
            paths, stale_sup_out=stale_sups
        )
    except FileNotFoundError as e:
        print(f"jaxlint: {e}", file=sys.stderr)
        return 2
    if errors:
        for e in errors:
            print(f"jaxlint: {e}", file=sys.stderr)
        return 2

    line_text_of = _line_text_reader(files)
    baseline_path = args.baseline or os.path.join(REPO_ROOT, BASELINE_NAME)

    if args.write_baseline:
        old = load_baseline(baseline_path) \
            if os.path.exists(baseline_path) else {}
        write_baseline(baseline_path, findings, line_text_of, old,
                       linted_paths=files)
        print(f"jaxlint: wrote {len(findings)} finding(s) to "
              f"{baseline_path}")
        return 0

    if args.prune_baseline:
        if not os.path.exists(baseline_path):
            print(f"jaxlint: no baseline at {baseline_path}",
                  file=sys.stderr)
            return 2
        try:
            old = load_baseline(baseline_path)
        except (json.JSONDecodeError, KeyError, TypeError) as e:
            print(f"jaxlint: bad baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
        dropped, n_reduced = prune_baseline(
            baseline_path, findings, line_text_of, old, linted_paths=files
        )
        for rule, fpath, text in dropped:
            print(f"jaxlint: pruned fixed entry {rule} @ {fpath}: {text!r}")
        for fpath, ln, rule in stale_sups:
            print(f"jaxlint: stale suppression {fpath}:{ln}: "
                  f"`# jaxlint: disable={rule}` no longer fires — remove it")
        print(f"jaxlint: pruned {len(dropped)} entr(ies), reduced "
              f"{n_reduced}, {len(stale_sups)} stale suppression(s) in "
              f"{baseline_path}")
        return 0

    baseline: dict = {}
    if not args.no_baseline and os.path.exists(baseline_path):
        try:
            baseline = load_baseline(baseline_path)
        except (json.JSONDecodeError, KeyError, TypeError) as e:
            print(f"jaxlint: bad baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
    new, n_baselined, stale = split_by_baseline(
        findings, baseline, line_text_of
    )
    # staleness is only decidable for files this run actually linted: a
    # subset invocation must not claim entries for un-linted files are fixed
    stale = [k for k in stale if k[1] in files]

    if args.format == "json":
        print(json.dumps({
            "jaxlint_schema": 1,
            "files": len(files),
            "new_findings": [f.to_dict() for f in new],
            "baselined": n_baselined,
            "suppressed": n_suppressed,
            "stale_baseline": [
                {"rule": r, "path": pp, "text": t} for r, pp, t in stale
            ],
            "stale_suppressions": [
                {"path": pp, "line": ln, "rule": r}
                for pp, ln, r in stale_sups
            ],
            "rules": sorted(RULES_BY_ID),
        }, indent=1))
    else:
        for f in new:
            fn = f" [{f.function}]" if f.function else ""
            print(f"{f.path}:{f.line}:{f.col + 1}: {f.rule}{fn}: "
                  f"{f.message}")
        for r, pp, t in stale:
            print(f"jaxlint: stale baseline entry {r} @ {pp}: {t!r} "
                  "(fixed? regenerate with --write-baseline)",
                  file=sys.stderr)
        for pp, ln, r in stale_sups:
            print(f"jaxlint: stale suppression {pp}:{ln}: "
                  f"`# jaxlint: disable={r}` no longer fires "
                  "(remove it, or --prune-baseline for a report)",
                  file=sys.stderr)
        print(f"jaxlint: {len(files)} files, {len(new)} new finding(s), "
              f"{n_baselined} baselined, {n_suppressed} suppressed")

    # leave the lint trail in runs.jsonl like every other entrypoint (no-op
    # unless $BLOCKSIM_RUNS_JSONL is set; obs never imports jax) — but ONLY
    # for gate-equivalent runs: a --no-baseline or partial-path invocation
    # counts a different population, and charting it into the same
    # jaxlint_new_findings series would make the trajectory reflect
    # invocation scope instead of code health
    gate_equivalent = (
        not args.no_baseline
        and args.baseline is None  # a custom baseline counts differently
        and sorted(os.path.abspath(p) for p in paths)
        == sorted(os.path.abspath(p) for p in _default_paths())
    )
    if gate_equivalent:
        from blockchain_simulator_tpu.utils import obs

        obs.record_run({
            "metric": "jaxlint_new_findings",
            "value": len(new),
            "unit": "findings",
            "files": len(files),
            "baselined": n_baselined,
            "suppressed": n_suppressed,
        })
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
