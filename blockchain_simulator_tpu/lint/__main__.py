import sys

from blockchain_simulator_tpu.lint.engine import main

sys.exit(main())
