"""jaxlint: repo-specific static analysis for the invariants that keep this
simulator correct and this environment alive (see README "Static analysis"
and each rule module's docstring for the KNOWN_ISSUES / PR cross-reference).

Run: ``python -m blockchain_simulator_tpu.lint [paths...]``.
"""

from blockchain_simulator_tpu.lint.common import Finding  # noqa: F401
