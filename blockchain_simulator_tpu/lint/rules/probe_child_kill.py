"""probe-child-kill: bench/health code must abandon children, not kill them.

KNOWN_ISSUES #3: a TPU client hard-killed mid-compile wedged the tunnel for
HOURS (observed rounds 3 and 4 — the round-4 wedge was never recovered), and
every later backend init stalls ~25 minutes.  The repo's defense is the
abandon-don't-kill rule: a probe/bench child that has not produced output is
presumed hung in backend init and must be LEFT RUNNING (utils/health.py's
supervised mode, bench.py's probe-patience path).  Signaling a subprocess —
``os.kill``/``os.killpg``, ``proc.terminate()``, ``proc.kill()``,
``proc.send_signal()`` — in bench/health/tools code is therefore a reviewed
exception, never a default: the only sanctioned use is bench.py's last-
resort escalation of a child that ALREADY probed healthy and then overran
(by then it is hung in device work, not tunnel init).
"""

from __future__ import annotations

import ast

from blockchain_simulator_tpu.lint import common

RULE_ID = "probe-child-kill"
SUMMARY = ("os.kill/.terminate()/.send_signal() on subprocess handles in "
           "bench/health/tools code (abandon-don't-kill, KNOWN_ISSUES #3)")

OS_KILLS = frozenset({
    "os.kill", "os.killpg", "signal.pthread_kill",
})
KILL_METHODS = frozenset({"terminate", "kill", "send_signal"})


def in_scope(path: str) -> bool:
    return (
        path.rsplit("/", 1)[-1] == "bench.py"
        or path.startswith("tools/") or "/tools/" in path
        or path.endswith("utils/health.py")
    )


def check(ctx: common.RuleContext) -> list[common.Finding]:
    if not in_scope(ctx.path):
        return []
    findings: list[common.Finding] = []
    for call in ast.walk(ctx.tree):
        if not isinstance(call, ast.Call):
            continue
        r = common.resolve(call.func, ctx.aliases)
        if r in OS_KILLS:
            what = r
        elif (
            isinstance(call.func, ast.Attribute)
            and call.func.attr in KILL_METHODS
            and (r is None or not r.startswith(("os.", "signal.")))
        ):
            what = f".{call.func.attr}()"
        else:
            continue
        findings.append(common.Finding(
            rule=RULE_ID, path=ctx.path, line=call.lineno,
            col=call.col_offset,
            message=(
                f"`{what}` signals a child process in bench/health code: "
                "killing a client hung in backend init is what wedges the "
                "single-client TPU tunnel for hours (KNOWN_ISSUES #3) — "
                "abandon the child (utils/health.py supervised mode) or "
                "justify a post-probe last-resort escalation inline"
            ),
            end_line=getattr(call, "end_lineno", None),
        ))
    return findings
