"""host-sync-in-traced: no host round-trips inside traced code.

The PR 1 regression class: raft_hb's original handoff did
``bool(jax.device_get(ok))`` on the host between two jitted programs, which
blocked jit/vmap/shard_map composition of the whole fast path (the fix — a
traced ``lax.cond`` — is what made sharded round-schedule raft and vmapped
sweeps real).  Any ``jax.device_get`` / ``.item()`` / ``float()`` / ``int()``
/ ``np.asarray`` reachable from a jit/vmap/pmap-decorated function or a
scan/cond/while body either breaks tracing outright (ConcretizationTypeError)
or, worse, silently forces a device sync per call.

Detection is intra-module: traced ROOTS are functions carrying a jit/vmap/
pmap decorator (including ``functools.partial(jax.jit, ...)`` forms) and
functions passed as callables to ``jax.jit`` / ``jax.vmap`` / ``jax.pmap`` /
``jax.lax.{scan,cond,switch,while_loop,fori_loop,map}`` / ``shard_map``
(directly, as a lambda, or through ``functools.partial``).  Reachability
propagates through same-module references: a traced function that mentions a
local function name makes that function traced too (it will be called — or
``partial``-ed into a scan — during the trace).

Static casts are exempted: ``int(cfg.x)`` on a config read is a Python-level
constant under trace, and ``int()`` of a literal or ``len()`` is static.
"""

from __future__ import annotations

import ast

from blockchain_simulator_tpu.lint import common

RULE_ID = "host-sync-in-traced"
SUMMARY = ("device_get/.item()/float()/int()/np.asarray reachable from "
           "jit/vmap/scan-body code (PR 1 regression class)")

# decorators / callable-taking transforms that put a function under trace
JIT_DECORATORS = frozenset({"jax.jit", "jax.vmap", "jax.pmap"})
TRACING_CALLS = frozenset({
    "jax.jit", "jax.vmap", "jax.pmap", "jax.checkpoint", "jax.remat",
    "jax.lax.scan", "jax.lax.cond", "jax.lax.switch", "jax.lax.while_loop",
    "jax.lax.fori_loop", "jax.lax.map", "jax.lax.associative_scan",
    "jax.experimental.shard_map.shard_map",
})

# host-sync callables (canonical dotted names).  The whole numpy as*
# coercion family is here: np.asanyarray/ascontiguousarray force the same
# device->host materialization np.asarray does (the round-8 audit gap).
SYNC_CALLS = frozenset({
    "jax.device_get", "numpy.asarray", "numpy.array", "numpy.frombuffer",
    "numpy.asanyarray", "numpy.ascontiguousarray", "numpy.asfortranarray",
})
SYNC_METHODS = frozenset({"item", "tolist"})
CAST_BUILTINS = frozenset({"float", "int", "bool"})


def _is_tracing_callee(callee: ast.AST, aliases: dict[str, str]) -> bool:
    r = common.resolve(callee, aliases)
    if r in TRACING_CALLS:
        return True
    # local shard_map compat wrappers (parallel/partition.py::_shard_map) keep
    # their callable-arg position; match by trailing name
    d = common.dotted(callee)
    return bool(d) and d.split(".")[-1].lstrip("_") == "shard_map"


def _decorated_traced(fn: ast.AST, aliases: dict[str, str]) -> bool:
    return common.decorated_with(fn, JIT_DECORATORS, aliases)


def _callable_args(call: ast.Call, aliases: dict[str, str]):
    """Yield (name-or-Lambda) callables handed to a tracing transform,
    looking through ``functools.partial``."""
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        if isinstance(arg, (ast.Name, ast.Lambda)):
            yield arg
        elif isinstance(arg, ast.Call) and common.resolve(
            arg.func, aliases
        ) == "functools.partial" and arg.args:
            inner = arg.args[0]
            if isinstance(inner, (ast.Name, ast.Lambda)):
                yield inner


def _resolve_local(name: str, scope: common.FunctionInfo | None,
                   idx: common.FunctionIndex) -> list[common.FunctionInfo]:
    """Lexical resolution of a function name as seen FROM ``scope``: walk
    the scope chain innermost-out (module scope last) and return the
    nearest level's definitions.  Prevents an unrelated same-named function
    in a different scope (this codebase names every scan body ``body``)
    from being dragged under the trace."""
    levels: list[common.FunctionInfo | None] = []
    fi = scope
    while fi is not None:
        levels.append(fi)
        fi = fi.parent
    levels.append(None)  # module scope
    for level in levels:
        hits = [f for f in idx.by_name.get(name, []) if f.parent is level]
        if hits:
            return hits
    return []


def _enclosing_info(node: ast.AST, idx: common.FunctionIndex
                    ) -> common.FunctionInfo | None:
    for anc in common.parent_chain(node):
        info = idx.infos.get(anc)
        if info is not None:
            return info
    return None


def traced_functions(ctx: common.RuleContext) -> set[ast.AST]:
    """All function/lambda nodes in the module that run under trace."""
    idx = ctx.functions
    traced: set[ast.AST] = set()

    for node, info in idx.infos.items():
        if _decorated_traced(node, ctx.aliases):
            traced.add(node)

    for call in ast.walk(ctx.tree):
        if not isinstance(call, ast.Call):
            continue
        if not _is_tracing_callee(call.func, ctx.aliases):
            continue
        call_scope = _enclosing_info(call, idx)
        for target in _callable_args(call, ctx.aliases):
            if isinstance(target, ast.Lambda):
                traced.add(target)
            else:
                for fi in _resolve_local(target.id, call_scope, idx):
                    traced.add(fi.node)

    # nested defs inside a traced function are defined during the trace
    changed = True
    while changed:
        changed = False
        for node, info in idx.infos.items():
            if node in traced:
                continue
            if info.parent is not None and info.parent.node in traced:
                traced.add(node)
                changed = True
        # reachability: a traced function mentioning a local function name
        # (call, partial, scan arg) pulls that function under the trace —
        # resolved lexically from the traced function's own scope
        for node in list(traced):
            scope = idx.infos.get(node)
            for sub in _own_nodes(node):
                if isinstance(sub, ast.Name) and isinstance(
                    sub.ctx, ast.Load
                ):
                    for fi in _resolve_local(sub.id, scope, idx):
                        if fi.node not in traced:
                            traced.add(fi.node)
                            changed = True
    return traced


def _own_nodes(fn: ast.AST):
    """Walk a function's body WITHOUT descending into nested functions
    (each traced nested function is analyzed as its own unit)."""
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


STATIC_ATTRS = frozenset({"shape", "ndim", "size", "dtype"})


def _static_cast_arg(arg: ast.AST) -> bool:
    """Casts whose argument is static under trace: literals, ``len()``,
    shape/ndim/size reads (Python values even on tracers), and
    config-attribute reads (SimConfig fields are Python scalars baked into
    the trace — the whole codebase names them ``cfg``/``rcfg``/...)."""
    if isinstance(arg, ast.Constant):
        return True
    if isinstance(arg, ast.Call) and isinstance(arg.func, ast.Name) \
            and arg.func.id == "len":
        return True
    # int(x.shape[0]) / int(x.ndim): static metadata, not a device sync
    probe = arg
    if isinstance(probe, ast.Subscript):
        probe = probe.value
    if isinstance(probe, ast.Attribute) and probe.attr in STATIC_ATTRS:
        return True
    # NOT `self`: a traced flax-struct state method's `int(self.field)` is
    # a real host sync — only config-named roots are static by convention
    d = common.dotted(arg)
    if d:
        root = d.split(".")[0]
        if root.endswith("cfg") or root == "config":
            return True
    return False


def check(ctx: common.RuleContext) -> list[common.Finding]:
    traced = traced_functions(ctx)
    findings: list[common.Finding] = []
    seen: set[tuple[int, int]] = set()
    for fn in traced:
        qual = ctx.functions.infos[fn].qualname if fn in ctx.functions.infos \
            else "<lambda>"
        for node in _own_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            what = None
            r = common.resolve(node.func, ctx.aliases)
            if r in SYNC_CALLS:
                what = r
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in SYNC_METHODS:
                what = f".{node.func.attr}()"
            elif isinstance(node.func, ast.Name) \
                    and node.func.id in CAST_BUILTINS:
                # positional only: float/int/bool reject keyword arguments
                # in Python 3, so there is no keyword form to police
                if node.args and not _static_cast_arg(node.args[0]):
                    what = f"{node.func.id}()"
            else:
                # a sync callable handed INTO a traced call by reference
                # (jax.tree.map(np.asarray, x)) syncs exactly like calling
                # it — flag the reference (the round-8 audit gap)
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    ra = common.resolve(arg, ctx.aliases)
                    if ra in SYNC_CALLS:
                        what = f"{ra} (passed as callable)"
                        break
            if what is None or (node.lineno, node.col_offset) in seen:
                continue
            seen.add((node.lineno, node.col_offset))
            findings.append(common.Finding(
                rule=RULE_ID, path=ctx.path, line=node.lineno,
                col=node.col_offset,
                message=(
                    f"host sync `{what}` reachable from traced function "
                    f"`{qual}`: host round-trips break jit/vmap/shard_map "
                    "composition (the PR 1 raft_hb device_get handoff "
                    "regression class) — keep the branch traced "
                    "(lax.cond) or move the readback outside the jit"
                ),
                end_line=getattr(node, "end_lineno", None),
                function=qual,
            ))
    return findings
