"""prng-key-reuse: the same PRNG key must not feed two consumers.

BFT simulation results are only trustworthy if the state machine is
deterministic AND its randomness is independent across use sites (the
consensus-correctness argument hinges on it — arXiv:1807.04938; measurement
validity on controlled execution — arXiv:2007.12637).  The repo's PRNG
discipline (utils/prng.py) is fold-in-per-use: every draw keys off
``fold_in(key, channel)``.  Passing the SAME key variable directly to two
``jax.random.*`` consumers silently correlates the two draws — a
nondeterminism-adjacent bug that no test catches unless the correlation
happens to shift a pinned metric.

Detection: per function scope, straight-line order with branch-aware merging
— a name first consumed by ``jax.random.X(name, ...)`` is poisoned until
reassigned (``key, sub = split(key)`` / ``key = fold_in(key, c)``).  Both
arms of an ``if`` may consume the same key (exclusive paths); loop bodies
are processed twice so a key consumed in a loop without reassignment is
caught (every iteration would see the same key).  ``fold_in``/``split``/
key constructors are non-consuming.
"""

from __future__ import annotations

import ast

from blockchain_simulator_tpu.lint import common

RULE_ID = "prng-key-reuse"
SUMMARY = ("same key passed to two jax.random consumers without an "
           "intervening split/fold_in (utils/prng.py discipline)")

NON_CONSUMING = frozenset({
    "fold_in", "split", "key", "PRNGKey", "key_data", "wrap_key_data",
    "key_impl", "clone",
})

State = dict  # name -> (consumer, lineno)


def _terminates(stmts: list[ast.stmt]) -> bool:
    """Does this (possibly empty) block unconditionally leave the scope?"""
    if not stmts:
        return False
    return isinstance(stmts[-1], (ast.Return, ast.Raise, ast.Continue,
                                  ast.Break))


def _consumer(call: ast.Call, aliases: dict[str, str]) -> str | None:
    r = common.resolve(call.func, aliases)
    if not r or not r.startswith("jax.random."):
        return None
    tail = r.rsplit(".", 1)[-1]
    return None if tail in NON_CONSUMING else tail


class _Scope:
    def __init__(self, ctx: common.RuleContext, qual: str):
        self.ctx = ctx
        self.qual = qual
        self.findings: list[common.Finding] = []
        self.seen: set[tuple[int, int]] = set()

    # ---- expressions --------------------------------------------------
    def do_expr(self, node: ast.AST, state: State) -> None:
        if node is None or isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                   ast.ClassDef)
        ):
            return  # separate scope
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            # comprehensions are loops: process the element twice so a key
            # consumed per iteration without rebinding is caught, clearing
            # per-iteration targets before each pass
            for gen in node.generators:
                self.do_expr(gen.iter, state)
            body = [node.key, node.value] if isinstance(node, ast.DictComp) \
                else [node.elt]
            for _ in range(2):
                for gen in node.generators:
                    self._clear_targets(gen.target, state)
                    for cond in gen.ifs:
                        self.do_expr(cond, state)
                for b in body:
                    self.do_expr(b, state)
            return
        if isinstance(node, ast.IfExp):
            # ternary arms are exclusive paths, same as ast.If
            self.do_expr(node.test, state)
            s_body, s_else = dict(state), dict(state)
            self.do_expr(node.body, s_body)
            self.do_expr(node.orelse, s_else)
            state.update(s_body)
            state.update(s_else)
            return
        if isinstance(node, ast.Call):
            for child in ast.iter_child_nodes(node):
                self.do_expr(child, state)
            name = _consumer(node, self.ctx.aliases)
            if name and node.args and isinstance(node.args[0], ast.Name):
                key = node.args[0].id
                if key in state:
                    prev_name, prev_line = state[key]
                    loc = (node.lineno, node.col_offset)
                    if loc not in self.seen:
                        self.seen.add(loc)
                        self.findings.append(common.Finding(
                            rule=RULE_ID, path=self.ctx.path,
                            line=node.lineno, col=node.col_offset,
                            message=(
                                f"PRNG key `{key}` consumed by jax.random."
                                f"{name} was already consumed by jax.random."
                                f"{prev_name} (line {prev_line}) with no "
                                "intervening split/fold_in: the two draws "
                                "are identical bit streams (utils/prng.py "
                                "fold-in-per-use discipline)"
                            ),
                            end_line=getattr(node, "end_lineno", None),
                            function=self.qual,
                        ))
                else:
                    state[key] = (name, node.lineno)
            return
        for child in ast.iter_child_nodes(node):
            self.do_expr(child, state)

    # ---- statements ---------------------------------------------------
    def _clear_targets(self, target: ast.AST, state: State) -> None:
        for node in ast.walk(target):
            if isinstance(node, ast.Name):
                state.pop(node.id, None)

    def do_stmts(self, stmts: list[ast.stmt], state: State) -> State:
        for stmt in stmts:
            state = self.do_stmt(stmt, state)
        return state

    def do_stmt(self, stmt: ast.stmt, state: State) -> State:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return state  # nested scopes analyzed separately
        if isinstance(stmt, ast.Assign):
            self.do_expr(stmt.value, state)
            for t in stmt.targets:
                self._clear_targets(t, state)
            return state
        if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            if stmt.value is not None:
                self.do_expr(stmt.value, state)
            self._clear_targets(stmt.target, state)
            return state
        if isinstance(stmt, ast.If):
            self.do_expr(stmt.test, state)
            s_body = self.do_stmts(stmt.body, dict(state))
            s_else = self.do_stmts(stmt.orelse, dict(state))
            # a terminating arm (guard clause: return/raise/...) never
            # reaches the code after the if — only fall-through arms merge
            body_falls = not _terminates(stmt.body)
            else_falls = not _terminates(stmt.orelse)
            merged: State = {}
            if body_falls:
                merged.update(s_body)
            if else_falls:
                merged.update(s_else)
            if not (body_falls or else_falls):
                merged = dict(state)  # code after the if is unreachable
            return merged
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self.do_expr(stmt.iter, state)
            # two passes: a key consumed in the body without reassignment
            # sees the SAME bits every iteration — the second pass flags it
            self._clear_targets(stmt.target, state)
            state = self.do_stmts(stmt.body, state)
            self._clear_targets(stmt.target, state)
            state = self.do_stmts(stmt.body, state)
            return self.do_stmts(stmt.orelse, state)
        if isinstance(stmt, ast.While):
            self.do_expr(stmt.test, state)
            state = self.do_stmts(stmt.body, state)
            state = self.do_stmts(stmt.body, state)
            return self.do_stmts(stmt.orelse, state)
        if isinstance(stmt, ast.Try):
            s = self.do_stmts(stmt.body, dict(state))
            for h in stmt.handlers:
                s.update(self.do_stmts(h.body, dict(state)))
            s = self.do_stmts(stmt.orelse, s)
            return self.do_stmts(stmt.finalbody, s)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self.do_expr(item.context_expr, state)
                if item.optional_vars is not None:
                    self._clear_targets(item.optional_vars, state)
            return self.do_stmts(stmt.body, state)
        if isinstance(stmt, (ast.Return, ast.Expr)):
            if stmt.value is not None:
                self.do_expr(stmt.value, state)
            return state
        # default: process any embedded expressions conservatively
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self.do_expr(child, state)
        return state


def check(ctx: common.RuleContext) -> list[common.Finding]:
    findings: list[common.Finding] = []
    for node, info in ctx.functions.infos.items():
        scope = _Scope(ctx, info.qualname)
        if isinstance(node, ast.Lambda):
            scope.do_expr(node.body, {})  # lambdas consume keys too
        else:
            scope.do_stmts(node.body, {})
        findings.extend(scope.findings)
    return findings
