"""module-scope-backend-touch: importing must never initialize a backend.

KNOWN_ISSUES #3/#4: this environment's single-client TPU tunnel turns a
backend init into a ~25-minute stall when wedged, and the sitecustomize
plugin registration routes even ``JAX_PLATFORMS=cpu`` inits through plugin
discovery.  The defense has two layers, both enforced here:

- NOWHERE in the tree may module scope (import time) execute a
  ``jnp.*`` / ``jax.random.*`` call or a backend introspection call
  (``jax.devices`` / ``jax.default_backend`` / ...): importing a module for
  its config types must stay free of device work;
- the GUARDED modules — ``utils/obs.py`` and ``utils/health.py``, which by
  contract must work with a wedged tunnel (the PR 2 "manifest never
  triggers backend init" guard) — may not make backend-touching calls
  *anywhere*, not just at module scope.  The two deliberate exceptions
  (obs.py's ``_backends``-guarded read, health.py's probe whose JOB is the
  init, run only in a supervised child) carry inline
  ``# jaxlint: disable=`` suppressions with their justification.
"""

from __future__ import annotations

import ast

from blockchain_simulator_tpu.lint import common

RULE_ID = "module-scope-backend-touch"
SUMMARY = ("jnp/jax.random/jax.devices at import time anywhere; any "
           "backend-touching call inside utils/obs.py + utils/health.py "
           "(KNOWN_ISSUES #3/#4, PR 2 manifest guard)")

# introspection / placement calls that force a backend init
BACKEND_CALLS = frozenset({
    "jax.devices", "jax.local_devices", "jax.device_count",
    "jax.local_device_count", "jax.default_backend", "jax.process_index",
    "jax.process_count", "jax.device_put", "jax.device_get",
    "jax.live_arrays", "jax.block_until_ready",
})

GUARDED_SUFFIXES = (
    "blockchain_simulator_tpu/utils/obs.py",
    "blockchain_simulator_tpu/utils/health.py",
)


# jnp calls that only read dtype METADATA — no device array is created and
# no backend is initialized (verified: jnp.iinfo leaves xla_bridge._backends
# empty); exempting them keeps the rule from forcing churn on harmless code
METADATA_CALLS = frozenset({
    "jax.numpy.iinfo", "jax.numpy.finfo", "jax.numpy.dtype",
    "jax.numpy.issubdtype", "jax.numpy.promote_types",
    "jax.numpy.result_type",
})


def _touch(callee: ast.AST, aliases: dict[str, str]) -> str | None:
    """Canonical name of a backend-touching callable, or None."""
    r = common.resolve(callee, aliases)
    if not r:
        return None
    if r in BACKEND_CALLS:
        return r
    if r in METADATA_CALLS:
        return None
    if r.startswith("jax.numpy.") or r.startswith("jax.random."):
        return r
    return None


def _module_scope_calls(tree: ast.Module):
    """(node, callee_expr) pairs executed at import time: module body,
    descending through If/Try/For/While/With and CLASS bodies (executed at
    import).  Function BODIES are skipped, but their decorators and
    default-argument values DO run at def time, so those subtrees stay in
    scope — and a bare ``@jax.device_put``-style decorator is itself a call
    at def time even though the AST has no Call node for it."""
    stack: list[ast.AST] = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            for dec in getattr(node, "decorator_list", []):
                if isinstance(dec, (ast.Name, ast.Attribute)):
                    yield dec, dec  # decorator application IS a call
                else:
                    stack.append(dec)
            a = node.args
            stack.extend(a.defaults)
            stack.extend(d for d in a.kw_defaults if d is not None)
            continue
        if isinstance(node, ast.Call):
            yield node, node.func
        stack.extend(ast.iter_child_nodes(node))


def check(ctx: common.RuleContext) -> list[common.Finding]:
    findings: list[common.Finding] = []
    seen: set[tuple[int, int]] = set()

    def add(node: ast.AST, what: str, why: str) -> None:
        loc = (node.lineno, node.col_offset)
        if loc in seen:
            return
        seen.add(loc)
        findings.append(common.Finding(
            rule=RULE_ID, path=ctx.path, line=node.lineno,
            col=node.col_offset, message=f"`{what}` {why}",
            end_line=getattr(node, "end_lineno", None),
        ))

    for node, callee in _module_scope_calls(ctx.tree):
        what = _touch(callee, ctx.aliases)
        if what:
            add(node, what,
                "runs at import time: importing this module would touch "
                "the backend — a wedged TPU tunnel turns that into a "
                "~25-minute stall (KNOWN_ISSUES #3/#4); move it inside "
                "the function that needs it")

    if ctx.path.endswith(GUARDED_SUFFIXES):
        for call in ast.walk(ctx.tree):
            if isinstance(call, ast.Call):
                what = _touch(call.func, ctx.aliases)
                if what:
                    add(call, what,
                        "inside a guarded module (utils/obs.py / "
                        "utils/health.py must work with a wedged tunnel — "
                        "the PR 2 'manifest never triggers backend init' "
                        "contract); guard it or justify with an inline "
                        "suppression")
    return findings
