"""static-arg-recompile-hazard: per-call jit wrappers over closure captures.

``jax.jit`` caches compiled programs PER WRAPPER OBJECT.  A jit created
inside a plain function — a nested ``@jax.jit def`` or a ``jax.jit(...)``
call — that closes over the enclosing function's parameters or locals builds
a FRESH wrapper (and therefore a fresh XLA compile) on every call of the
enclosing function; the captured Python scalars are baked into each trace,
so nothing is ever reused.  On this repo's configs a single wasted recompile
is minutes of XLA:CPU time (the 100k-node program alone is ~7 min,
bench.py's fallback notes), which is why every real factory in the tree
(``runner.make_sim_fn``, ``utils/trace.py``'s traced fns,
``parallel/sweep.py``'s batched builders) is memoized on a hashable
SimConfig — today through the unified executable registry
(``utils/aotcache.cached_factory``), historically ``functools.lru_cache``
(``parallel/shard.py`` still uses it); both count as sanctioned cache
decorators here.

The rule flags jit application inside a function whose enclosing chain has
no ``lru_cache``/``cache`` decorator when the jitted callable (or the jit
call's argument expression) captures names bound in the enclosing scopes.
A jit over a no-capture lambda (utils/health.py's probe matmul) is clean:
there is nothing cacheable to lose.
"""

from __future__ import annotations

import ast

from blockchain_simulator_tpu.lint import common

RULE_ID = "static-arg-recompile-hazard"
SUMMARY = ("jit built per call over enclosing-scope captures without an "
           "lru_cache factory: every call recompiles "
           "(runner.make_sim_fn is the sanctioned pattern)")

JIT_NAMES = frozenset({"jax.jit", "jax.pmap"})
# Sanctioned cache decorators: functools' memoizers, plus the unified
# executable registry's factory decorator (utils/aotcache.cached_factory —
# the keyed LRU store that replaced the per-module lru_caches; it memoizes
# on the same hashable-args contract, with hit/miss stats on the manifest).
CACHED_DECOS = frozenset({
    "functools.lru_cache",
    "functools.cache",
    "aotcache.cached_factory",
    "blockchain_simulator_tpu.utils.aotcache.cached_factory",
})


def _is_cached(fn: ast.AST, aliases: dict[str, str]) -> bool:
    return common.decorated_with(fn, CACHED_DECOS, aliases)


def _jit_decorator(fn: ast.AST, aliases: dict[str, str]) -> bool:
    return common.decorated_with(fn, JIT_NAMES, aliases)


def _ancestor_bound(info: common.FunctionInfo | None) -> set[str]:
    names: set[str] = set()
    while info is not None:
        names |= common.bound_names(info.node)
        info = info.parent
    return names


def _chain_cached(info: common.FunctionInfo | None,
                  aliases: dict[str, str]) -> bool:
    while info is not None:
        if _is_cached(info.node, aliases):
            return True
        info = info.parent
    return False


def check(ctx: common.RuleContext) -> list[common.Finding]:
    findings: list[common.Finding] = []
    mod_names = common.module_level_names(ctx.tree)

    def add(node: ast.AST, captures: set[str], encl: str) -> None:
        shown = ", ".join(sorted(captures))
        findings.append(common.Finding(
            rule=RULE_ID, path=ctx.path, line=node.lineno,
            col=node.col_offset,
            message=(
                f"jit built inside `{encl}` captures per-call values "
                f"({{{shown}}}): each call creates a fresh wrapper and "
                "recompiles from scratch — hoist into an "
                "functools.lru_cache factory keyed on the hashable config "
                "(runner.make_sim_fn pattern) or pass the values as traced "
                "arguments"
            ),
            end_line=getattr(node, "end_lineno", None),
            function=encl,
        ))

    # (a) nested `@jax.jit def` under an uncached enclosing function
    for node, info in ctx.functions.infos.items():
        if isinstance(node, ast.Lambda) or info.parent is None:
            continue
        if not _jit_decorator(node, ctx.aliases):
            continue
        if _chain_cached(info.parent, ctx.aliases):
            continue
        captures = (
            common.loaded_names(node) - common.bound_names(node)
            - mod_names - common.BUILTIN_NAMES - set(ctx.aliases)
        ) & _ancestor_bound(info.parent)
        if captures:
            add(node, captures, info.parent.qualname)

    # (b) `jax.jit(...)` called inside an uncached function body
    for call in ast.walk(ctx.tree):
        if not isinstance(call, ast.Call):
            continue
        if common.resolve(call.func, ctx.aliases) not in JIT_NAMES:
            continue
        parent = getattr(call, "_jaxlint_parent", None)
        if parent is not None and call in getattr(
            parent, "decorator_list", ()
        ):
            continue  # decorator form: handled by (a)
        encl_node = None
        for anc in common.parent_chain(call):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                encl_node = anc
                break
        if encl_node is None:
            continue  # module-scope jit over module-level fn: one wrapper
        info = ctx.functions.infos.get(encl_node)
        if info is None or _chain_cached(info, ctx.aliases):
            continue
        names: set[str] = set()
        lambda_bound: set[str] = set()
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Name) and isinstance(
                    sub.ctx, ast.Load
                ):
                    names.add(sub.id)
                elif isinstance(sub, ast.Lambda):
                    lambda_bound |= common.bound_names(sub)
        # import-bound names (module aliases, function-local `import jax`)
        # are process-stable, not per-call values
        captures = (
            (names - lambda_bound - mod_names - common.BUILTIN_NAMES
             - set(ctx.aliases))
            & _ancestor_bound(info)
        )
        if captures:
            add(call, captures, info.qualname)
    return findings
