"""slow-cpu-lowering: scatter-add and cumsum are measured XLA:CPU traps.

KNOWN_ISSUES #0b (measured end-to-end on the 2-core driver box): a
scatter-add commit-wave variant ran 2.6x SLOWER than padded shifted adds,
and a ``jnp.cumsum`` crossing loop cost +2.5 ms/round vs an unrolled running
sum.  The CPU fallback bench (the only number a wedged tunnel leaves us) is
a first-class deliverable, so hot-path code in ``models/`` and ``ops/`` must
not reach for ``.at[...].add`` or ``cumsum`` casually.

The rule is allowlist-aware: sites measured acceptable (cold paths, small
static axes, ``mode="drop"`` windowed accumulators whose vectorized
alternative was worse) are listed in :data:`ALLOWLIST` as
``"<basename>::<function>"`` — add an entry ONLY with a measurement, or
grandfather via LINT_BASELINE.json with a justification.
"""

from __future__ import annotations

import ast

from blockchain_simulator_tpu.lint import common

RULE_ID = "slow-cpu-lowering"
SUMMARY = (".at[].add / cumsum in models/ and ops/ hot paths "
           "(KNOWN_ISSUES #0b: 2.6x slower scatter, +2.5 ms/round cumsum "
           "on XLA:CPU); allowlist-aware")

SCOPES = ("/models/", "/ops/")

CUMSUM_CALLS = frozenset({
    "jax.numpy.cumsum", "jax.lax.cumsum", "jax.lax.associative_scan",
})

# "<basename>::<enclosing function>" sites measured acceptable.  Every entry
# needs a measurement or a structural argument in the comment.
ALLOWLIST = frozenset({
    # windowed vote-table accumulators: O(N*W) drop-mode scatters over the
    # small static window axis, measured as part of the tick engine (the
    # round fast path that owns the perf target has no vote table at all)
    "pbft.py::_scatter_window_events",
})


def _enclosing_fn_name(node: ast.AST) -> str | None:
    for parent in common.parent_chain(node):
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return parent.name
    return None


def _is_scatter_add(call: ast.Call) -> bool:
    f = call.func
    return (
        isinstance(f, ast.Attribute) and f.attr == "add"
        and isinstance(f.value, ast.Subscript)
        and isinstance(f.value.value, ast.Attribute)
        and f.value.value.attr == "at"
    )


def _is_cumsum(call: ast.Call, aliases: dict[str, str]) -> bool:
    r = common.resolve(call.func, aliases)
    if r in CUMSUM_CALLS:
        return True
    return isinstance(call.func, ast.Attribute) and call.func.attr == "cumsum"


def check(ctx: common.RuleContext) -> list[common.Finding]:
    if not any(scope in f"/{ctx.path}" for scope in SCOPES):
        return []
    findings: list[common.Finding] = []
    basename = ctx.path.rsplit("/", 1)[-1]
    for call in ast.walk(ctx.tree):
        if not isinstance(call, ast.Call):
            continue
        if _is_scatter_add(call):
            what = ".at[...].add scatter-add"
            hint = ("lowers to a serialized generic scatter on XLA:CPU "
                    "(measured 2.6x slower than padded shifted adds end-to-"
                    "end, KNOWN_ISSUES #0b)")
        elif _is_cumsum(call, ctx.aliases):
            what = "cumsum"
            hint = ("lowers pathologically on XLA:CPU (+2.5 ms/round vs an "
                    "unrolled running-sum chain, KNOWN_ISSUES #0b; see "
                    "models/pbft_round.py's crossing latch)")
        else:
            continue
        fn = _enclosing_fn_name(call)
        if fn and f"{basename}::{fn}" in ALLOWLIST:
            continue
        remedy = (
            f"vectorize differently, or add \"{basename}::{fn}\" to the "
            "rule allowlist WITH a measurement"
            if fn else
            # module-scope sites have no allowlist key: only an inline
            # suppression or a baseline entry can exempt them
            "vectorize differently, or suppress inline / baseline with a "
            "justification"
        )
        findings.append(common.Finding(
            rule=RULE_ID, path=ctx.path, line=call.lineno,
            col=call.col_offset,
            message=f"`{what}` in a models/ops hot path {hint} — {remedy}",
            end_line=getattr(call, "end_lineno", None),
            function=fn,
        ))
    return findings
