"""hardcoded-mesh-axis: mesh vocabulary belongs to parallel/partition.py.

The shardlint comms audit (lint/comms) pins WHAT the partitioner does to
each mesh program; this rule pins WHERE the sharding vocabulary may be
spelled.  The repo's contract is that mesh axis names live in
``parallel/mesh.py`` (``NODES_AXIS``/``SWEEP_AXIS``) and PartitionSpec
construction is partition-layer business (``parallel/partition.py``
rules, ``node_dim_rules``, ``batched_out_shardings``): a ``P("nodes")``
inlined in a model or engine file bypasses ``match_partition_rules``, so
renaming an axis or reshaping the mesh silently strands it — the comms
audit then reports the resulting replication as a table-regather, one PR
too late.

Two triggers, outside the allowed partition-layer files:

- constructing ``jax.sharding.PartitionSpec`` (any alias, incl. the
  conventional ``P``) — declare a rule in partition.py and match it;
- passing a mesh axis-name string literal ("nodes"/"sweep") to a
  sharding-vocabulary call (``PartitionSpec``/``NamedSharding``/
  ``Mesh``/``shard_map``/``psum``-family) or ``axis_name=``-style
  kwargs — import the constant from parallel/mesh.py instead.

Existing partition-adjacent sites (parallel/shard.py's hand-written
in_specs, sweep.py's overlay table specs, obsim's probe shardings) are
grandfathered in LINT_BASELINE.json with justifications.
"""

from __future__ import annotations

import ast

from blockchain_simulator_tpu.lint import common

RULE_ID = "hardcoded-mesh-axis"
SUMMARY = ("mesh axis-name literal or inline PartitionSpec outside "
           "parallel/partition.py|mesh.py (bypasses match_partition_rules; "
           "shardlint sees the fallout one PR late)")

# The partition layer itself, where the vocabulary is DEFINED.
ALLOWED_PATH_PARTS = (
    "parallel/partition.py",
    "parallel/mesh.py",
)

# The repo's mesh axis names (parallel/mesh.py NODES_AXIS / SWEEP_AXIS).
AXIS_LITERALS = frozenset({"nodes", "sweep"})

# Dotted call targets that consume sharding vocabulary.
SPEC_CALLS = frozenset({
    "jax.sharding.PartitionSpec",
    "jax.sharding.NamedSharding",
    "jax.sharding.Mesh",
})
AXIS_CONSUMER_ATTRS = frozenset({
    "PartitionSpec", "NamedSharding", "Mesh", "shard_map",
    "psum", "pmax", "pmin", "pmean", "all_gather", "ppermute",
    "axis_index", "make_mesh",
})
AXIS_KWARGS = frozenset({
    "axis_name", "axis_names", "spmd_axis_name", "mesh_axis",
})


def _call_name(call: ast.Call, aliases: dict[str, str]) -> str:
    resolved = common.resolve(call.func, aliases)
    if resolved:
        return resolved
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return ""


def _is_spec_ctor(call: ast.Call, aliases: dict[str, str]) -> bool:
    name = _call_name(call, aliases)
    if name in SPEC_CALLS:
        return True
    # the conventional `from jax.sharding import PartitionSpec as P`
    return name.rsplit(".", 1)[-1] == "PartitionSpec"


def _axis_literals_in(call: ast.Call) -> list[ast.Constant]:
    hits = []
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        for node in ast.walk(arg):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and node.value in AXIS_LITERALS):
                hits.append(node)
    return hits


def check(ctx: common.RuleContext) -> list[common.Finding]:
    if any(part in ctx.path for part in ALLOWED_PATH_PARTS):
        return []
    findings: list[common.Finding] = []

    def fn_of(node):
        for parent in common.parent_chain(node):
            if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return parent.name
        return None

    for call in ast.walk(ctx.tree):
        if not isinstance(call, ast.Call):
            continue
        if _is_spec_ctor(call, ctx.aliases):
            findings.append(common.Finding(
                rule=RULE_ID, path=ctx.path, line=call.lineno,
                col=call.col_offset,
                message=(
                    "inline PartitionSpec construction outside the "
                    "partition layer: declare a rule in parallel/"
                    "partition.py (match_partition_rules / node_dim_rules) "
                    "so axis renames and mesh reshapes stay one-file "
                    "changes"
                ),
                end_line=getattr(call, "end_lineno", None),
                function=fn_of(call),
            ))
            continue
        name = _call_name(call, ctx.aliases)
        consumes_axis = (
            name in SPEC_CALLS
            or name.rsplit(".", 1)[-1] in AXIS_CONSUMER_ATTRS
            or any(kw.arg in AXIS_KWARGS for kw in call.keywords
                   if kw.arg)
        )
        if not consumes_axis:
            continue
        for lit in _axis_literals_in(call):
            findings.append(common.Finding(
                rule=RULE_ID, path=ctx.path, line=lit.lineno,
                col=lit.col_offset,
                message=(
                    f"mesh axis name {lit.value!r} hardcoded at a "
                    f"sharding call ({name.rsplit('.', 1)[-1]}): import "
                    "NODES_AXIS/SWEEP_AXIS from parallel/mesh.py — a "
                    "renamed axis strands string literals silently"
                ),
                end_line=getattr(lit, "end_lineno", None),
                function=fn_of(call),
            ))
    return findings
