"""jaxlint rule registry: one module per rule.

Each rule module exposes ``RULE_ID`` (the kebab-case id used in findings,
``# jaxlint: disable=<id>`` comments and the baseline file), ``SUMMARY``
(one line, with the KNOWN_ISSUES / PR reference that motivated the rule)
and ``check(ctx: common.RuleContext) -> list[common.Finding]``.
"""

from __future__ import annotations

from blockchain_simulator_tpu.lint.rules import (  # noqa: F401
    hardcoded_mesh_axis,
    host_sync_in_traced,
    module_scope_backend_touch,
    probe_child_kill,
    prng_key_reuse,
    slow_cpu_lowering,
    static_arg_recompile_hazard,
    unused_import,
)

ALL_RULES = [
    host_sync_in_traced,
    prng_key_reuse,
    module_scope_backend_touch,
    slow_cpu_lowering,
    probe_child_kill,
    static_arg_recompile_hazard,
    unused_import,
    hardcoded_mesh_axis,
]

RULES_BY_ID = {mod.RULE_ID: mod for mod in ALL_RULES}
