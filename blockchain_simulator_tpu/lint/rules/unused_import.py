"""unused-import: dead imports are noise and, for jax, latency.

Mostly hygiene (PR 1 already dropped one stray numpy import from a model),
but with one repo-specific edge: an unused ``import jax`` is ~2 s of wasted
interpreter start on this box and — through the sitecustomize PJRT plugin
registration — one more module whose import order can interact with backend
selection (tests/conftest.py's two-layer forcing exists for exactly that).

``__init__.py`` files are exempt wholesale (re-export surfaces), and any
line carrying a ``noqa`` comment is honored in addition to the standard
``# jaxlint: disable=`` mechanism.
"""

from __future__ import annotations

import ast

from blockchain_simulator_tpu.lint import common

RULE_ID = "unused-import"
SUMMARY = "imports never referenced in the module (F401-class hygiene)"


def _quoted_annotation_names(tree: ast.Module) -> set[str]:
    """Names referenced inside STRING annotations (``x: "List[int]"`` —
    forward references evaluate lazily but still use the import)."""
    anns: list[ast.AST | None] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = node.args
            for arg in (
                list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
                + ([a.vararg] if a.vararg else [])
                + ([a.kwarg] if a.kwarg else [])
            ):
                anns.append(arg.annotation)
            anns.append(node.returns)
        elif isinstance(node, ast.AnnAssign):
            anns.append(node.annotation)
    names: set[str] = set()
    for ann in anns:
        if ann is None:
            continue
        for sub in ast.walk(ann):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                try:
                    expr = ast.parse(sub.value, mode="eval")
                except SyntaxError:
                    continue
                for n in ast.walk(expr):
                    if isinstance(n, ast.Name):
                        names.add(n.id)
    return names


def check(ctx: common.RuleContext) -> list[common.Finding]:
    if ctx.path.endswith("__init__.py"):
        return []
    # (name, shown, line, col, end_line) — end_line makes the engine's
    # span-based suppression (and the noqa check below) cover continuation
    # lines of parenthesized multiline imports
    bindings: list[tuple[str, str, int, int, int]] = []
    for node in ast.walk(ctx.tree):
        end = getattr(node, "end_lineno", None) or node.lineno \
            if isinstance(node, (ast.Import, ast.ImportFrom)) else 0
        if isinstance(node, ast.Import):
            for a in node.names:
                name = (a.asname or a.name).split(".")[0]
                bindings.append((name, a.name if not a.asname
                                 else f"{a.name} as {a.asname}",
                                 node.lineno, node.col_offset, end))
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                name = a.asname or a.name
                shown = f"from {'.' * node.level}{node.module or ''} " \
                        f"import {a.name}" + (
                            f" as {a.asname}" if a.asname else "")
                bindings.append((name, shown, node.lineno,
                                 node.col_offset, end))

    used: set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            d = common.dotted(node)
            if d:
                used.add(d.split(".")[0])
    used |= _quoted_annotation_names(ctx.tree)
    # names exported via __all__ count as used
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "__all__"
            for t in node.targets
        ):
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Constant) and isinstance(
                    sub.value, str
                ):
                    used.add(sub.value)

    findings = []
    for name, shown, line, col, end in bindings:
        if name in used:
            continue
        if any("noqa" in ctx.line_text(ln) for ln in range(line, end + 1)):
            continue
        findings.append(common.Finding(
            rule=RULE_ID, path=ctx.path, line=line, col=col,
            message=f"unused import `{shown}`", end_line=end,
        ))
    return findings
