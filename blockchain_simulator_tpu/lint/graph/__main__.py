"""CLI: ``python -m blockchain_simulator_tpu.lint.graph``.

Flags mirror jaxlint's where the concept is shared (``--format``,
``--baseline``, ``--no-baseline``, ``--write-baseline``,
``--prune-baseline``, ``--list-rules``) plus graph-only ones
(``--list-programs``, ``--only``, ``--tolerance``).
Exit codes: 0 = clean vs baseline, 1 = new findings, 2 = a program failed
to trace / bad baseline / usage error.

The audit runs on the CPU backend by default regardless of this
environment's TPU-tunnel plugin: a CI lint gate must never hang on a
wedged tunnel (KNOWN_ISSUES.md #3), and the IR contracts it checks are
backend-independent.  Override with ``$BLOCKSIM_GRAPH_PLATFORM``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _force_platform() -> None:
    """Pin the audit backend BEFORE any jax import/backend init.  Mirrors
    tests/conftest.py: env for the host-device-count flag, config for this
    environment's sitecustomize (which forces jax_platforms='axon,cpu' at
    the config level, so the env var alone is not enough)."""
    platform = os.environ.get("BLOCKSIM_GRAPH_PLATFORM", "cpu")
    if "jax" not in sys.modules:
        os.environ.setdefault("JAX_PLATFORMS", platform)
    # the host-device-count flag is read at backend INIT, not jax import —
    # this environment's sitecustomize imports jax at interpreter start, so
    # gate on backend state rather than sys.modules
    backend_up = False
    if "jax" in sys.modules:
        try:
            from jax._src import xla_bridge

            backend_up = bool(getattr(xla_bridge, "_backends", None))
        except Exception:
            pass
    flags = os.environ.get("XLA_FLAGS", "")
    if not backend_up and "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", platform)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="blockchain_simulator_tpu.lint.graph",
        description="jaxgraph: IR-level audit of every registered "
                    "executable factory (jaxpr rules + FLOP/byte budget "
                    "gate)",
    )
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default: GRAPH_BASELINE.json at the "
                        "repo root when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding and skip the budget gate")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings + measured budgets as the "
                        "new baseline (preserves justifications) and exit 0")
    p.add_argument("--prune-baseline", action="store_true",
                   help="baseline hygiene: drop finding entries the audit "
                        "no longer produces and budgets for programs no "
                        "longer in the catalog (retired factories); never "
                        "re-pins live budgets or touches justifications")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--list-programs", action="store_true")
    p.add_argument("--only", nargs="*", default=None, metavar="PROGRAM",
                   help="audit only these programs (disables the "
                        "completeness rule and runs.jsonl recording)")
    p.add_argument("--tolerance", type=float, default=None,
                   help="budget growth fraction that fails the gate "
                        "(default: the baseline file's, else 0.25)")
    args = p.parse_args(argv)

    from blockchain_simulator_tpu.lint.graph import audit as audit_mod
    from blockchain_simulator_tpu.lint.graph import programs as prog_mod

    if args.list_rules:
        for rid, summary in sorted(audit_mod.RULE_SUMMARIES.items()):
            print(f"{rid:<28} {summary}")
        return 0

    specs = prog_mod.build_catalog()
    if args.list_programs:
        for s in specs:
            extra = f"  [group {s.divergence_group}]" if s.divergence_group \
                else ""
            print(f"{s.program:<28} factory={s.factory}{extra}")
        return 0

    subset = args.only is not None
    if subset:
        known = {s.program for s in specs}
        unknown = [x for x in args.only if x not in known]
        if unknown:
            print(f"jaxgraph: unknown program(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2
        specs = [s for s in specs if s.program in args.only]

    if args.prune_baseline:
        # guard BEFORE the (minutes-long) audit: a subset run cannot
        # distinguish retired from out-of-scope, and pruning needs a file
        if subset:
            print("jaxgraph: --prune-baseline needs a full catalog run "
                  "(drop --only)", file=sys.stderr)
            return 2
        prune_path = args.baseline or audit_mod.default_baseline_path()
        if args.no_baseline or not os.path.exists(prune_path):
            print(f"jaxgraph: --prune-baseline needs an existing baseline "
                  f"({prune_path})", file=sys.stderr)
            return 2

    _force_platform()

    factories = prog_mod.discover_factories()
    if subset:
        # a subset run cannot claim completeness — silence the rule by
        # scoping discovery to the covered factories
        factories = {k: v for k, v in factories.items()
                     if k in {s.factory for s in specs}}
    result = audit_mod.run_audit(specs, factories)

    baseline_path = args.baseline or audit_mod.default_baseline_path()
    baseline = {"budgets": {}, "entries": {},
                "tolerance": audit_mod.DEFAULT_TOLERANCE}
    if not args.no_baseline and os.path.exists(baseline_path):
        try:
            baseline = audit_mod.load_baseline(baseline_path)
        except (json.JSONDecodeError, KeyError, TypeError, ValueError) as e:
            print(f"jaxgraph: bad baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
    tolerance = args.tolerance if args.tolerance is not None \
        else baseline["tolerance"]

    if args.write_baseline:
        # budgets must exist to be written; missing cost is an error either way
        audit_mod.apply_budgets(result, {}, tolerance)
        result.findings = [
            f for f in result.findings if f.rule != "budget-missing"
        ]
        if result.errors:
            for e in result.errors:
                print(f"jaxgraph: {e}", file=sys.stderr)
            return 2
        # load old from disk regardless of --no-baseline: a rewrite must
        # never lose hand-written justifications (jaxlint's write path)
        old = None
        if os.path.exists(baseline_path):
            try:
                old = audit_mod.load_baseline(baseline_path)
            except (json.JSONDecodeError, KeyError, TypeError, ValueError):
                old = None  # corrupt: regenerate from scratch
        doc = audit_mod.write_baseline(baseline_path, result, old,
                                       tolerance=args.tolerance,
                                       full=not subset)
        print(f"jaxgraph: wrote {len(doc['budgets'])} budget(s) and "
              f"{len(doc['entries'])} finding entr(ies) to "
              f"{baseline_path}")
        return 0

    if args.prune_baseline:
        if result.errors:
            for e in result.errors:
                print(f"jaxgraph: {e}", file=sys.stderr)
            return 2
        info = audit_mod.prune_baseline(baseline_path, result, baseline)
        for r, pr, d in info["dropped_entries"]:
            print(f"jaxgraph: pruned fixed entry {r} @ {pr}: {d!r}")
        for r, pr, d in info["shrunk_entries"]:
            print(f"jaxgraph: shrank overcounted entry {r} @ {pr}: {d!r}")
        for pr in info["dropped_budgets"]:
            print(f"jaxgraph: dropped retired budget {pr}")
        print(f"jaxgraph: pruned {len(info['dropped_entries'])} entr(ies), "
              f"shrank {len(info['shrunk_entries'])}, dropped "
              f"{len(info['dropped_budgets'])} retired budget(s) in "
              f"{baseline_path}")
        return 0

    if not args.no_baseline:
        audit_mod.apply_budgets(result, baseline["budgets"], tolerance)
    new, n_baselined, stale = audit_mod.split_by_baseline(
        result.findings, {} if args.no_baseline else baseline["entries"]
    )
    # entries for programs a subset run did not trace are not stale
    if subset:
        stale = [k for k in stale if k[1] in result.reports]

    if args.format == "json":
        print(json.dumps({
            "jaxgraph_schema": 1,
            "programs": {k: r.to_dict() for k, r in
                         sorted(result.reports.items())},
            "new_findings": [f.to_dict() for f in new],
            "baselined": n_baselined,
            "stale_baseline": [
                {"rule": r, "program": pr, "detail": d} for r, pr, d in stale
            ],
            "stale_budgets": [
                {"program": pr, "axis": ax, "measured": m, "pinned": pin}
                for pr, ax, m, pin in result.stale_budgets
            ],
            "errors": result.errors,
            "factories": result.factories,
            "rules": sorted(audit_mod.RULE_SUMMARIES),
        }, indent=1))
    else:
        for name in sorted(result.reports):
            r = result.reports[name]
            cost = (f"gflops={r.cost['flops'] / 1e9:.6f} "
                    f"mbytes={r.cost['bytes'] / 1e6:.3f}"
                    if r.cost else "cost=n/a")
            prims = (" " + ",".join(f"{k}x{v}" for k, v in
                                    sorted(r.prims.items()))
                     if r.prims else "")
            print(f"{name:<28} [{r.factory}] {r.fingerprint[:12]} "
                  f"eqns={r.n_eqns} {cost}{prims}")
        for f in new:
            print(f"{f.program}: {f.rule}: {f.message}")
        for r, pr, d in stale:
            print(f"jaxgraph: stale baseline entry {r} @ {pr}: {d!r} "
                  "(fixed? regenerate with --write-baseline)",
                  file=sys.stderr)
        for pr, ax, m, pin in result.stale_budgets:
            print(f"jaxgraph: stale budget {pr}.{ax}: measured {m:.0f} well "
                  f"under pin {pin:.0f} (improvement — re-pin with "
                  "--write-baseline)", file=sys.stderr)
        for e in result.errors:
            print(f"jaxgraph: ERROR {e}", file=sys.stderr)
        print(f"jaxgraph: {len(result.reports)} programs, "
              f"{len(result.factories)} factories, {len(new)} new "
              f"finding(s), {n_baselined} baselined, "
              f"{len(result.errors)} error(s)")

    # gate-equivalent runs leave the trail in runs.jsonl next to jaxlint's
    # (no-op unless $BLOCKSIM_RUNS_JSONL is set; obs never inits a backend)
    gate_equivalent = (
        not subset and not args.no_baseline and args.baseline is None
    )
    if gate_equivalent:
        from blockchain_simulator_tpu.utils import obs

        obs.record_run({
            "metric": "jaxgraph_new_findings",
            "value": len(new),
            "unit": "findings",
            "programs": len(result.reports),
            "baselined": n_baselined,
            "errors": len(result.errors),
        })
        for name in sorted(result.reports):
            r = result.reports[name]
            if not (r.budget and r.cost):
                continue
            safe = name.replace(".", "_").replace("-", "_")
            obs.record_run({
                "metric": f"graph_{safe}_gflops",
                "value": round(r.cost["flops"] / 1e9, 9),
                "unit": "gflops",
            })
            obs.record_run({
                "metric": f"graph_{safe}_bytes",
                "value": r.cost["bytes"],
                "unit": "bytes",
            })

    if result.errors:
        return 2
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
