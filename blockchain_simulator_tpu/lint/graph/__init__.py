"""jaxgraph: IR-level audit of every registered executable factory.

``jaxlint`` (the sibling AST layer, ``lint/engine.py``) polices the Python
that *produces* programs; this package audits the programs themselves.  The
north star lifts consensus state machines into batched XLA executables, so
the artifact that must stay correct and fast is the compiled graph — and the
switch-consensus line this repo tracks ("Paxos Made Switch-y", 1511.04985;
"Network Hardware-Accelerated Consensus", 1605.05619) wins precisely by
knowing statically what the dataplane will execute.  Here that means: trace
every ``aotcache.cached_factory`` program (round + tick engines, raft_hb,
mixed, sweep batched fns, shard wrappers, traced probes) to its jaxpr and
check IR-level contracts AST rules can only approximate:

- no host callbacks / infeed / debug prints inside sim programs
  (``host-callback-in-program``);
- no 64-bit dtypes and no weak-type drift across program boundaries
  (``f64-in-program``, ``weak-type-boundary``);
- no large constants baked into the jaxpr — they bloat
  ``$BLOCKSIM_COMPILE_CACHE`` payloads and defeat the one-executable-per-
  fault-structure contract (``large-jaxpr-constant``);
- confirmed-slow CPU lowerings found post-trace, replacing the AST
  ``slow-cpu-lowering`` allowlist guesswork with ground truth
  (``slow-lowering-confirmed``);
- registry-key divergence: one registry key producing multiple distinct
  jaxprs across a sweep is a silent recompile leak
  (``registry-key-divergence``);
- every ``cached_factory`` name discovered in source has at least one audit
  program covering it (``unaudited-factory``).

On the same traces, per-program ``cost_analysis()`` FLOP/byte budgets are
pinned in ``GRAPH_BASELINE.json`` and gated like ``LINT_BASELINE.json``
gates findings (``budget-missing`` / ``budget-regression``): a static
perf-regression gate that fires in CI without running a bench.  The
``*_gflops`` / ``*_bytes`` trajectories are charted — never hard-gated — by
``tools/bench_compare.py``.

Run ``python -m blockchain_simulator_tpu.lint.graph`` (text/JSON output,
baseline mechanics mirroring jaxlint's); ``tools/lint.sh`` chains it after
the AST gate.
"""

from __future__ import annotations
