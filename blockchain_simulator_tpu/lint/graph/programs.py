"""The audit surface: every registered executable factory, as traceable specs.

Two halves keep each other honest:

- :func:`discover_factories` finds every ``@aotcache.cached_factory("name")``
  registration in the source tree by AST (reusing the jaxlint alias
  machinery — the same no-import contract: discovery must not trigger what
  it polices);
- :func:`build_catalog` constructs one or more :class:`ProgramSpec` per
  factory name — tiny audit-scale configs (n=8, a few hundred ticks) chosen
  so every engine arm the factory can dispatch to gets traced: tick engines
  for all four protocols, the round/heartbeat fast paths, the vmapped sweep
  programs (static and dynamic-fault-operand), the shard_map wrappers, and
  the probe-traced variants.

A factory name discovered in source with no covering spec is an
``unaudited-factory`` finding (lint/graph/audit.py), so growing a new
factory without growing its audit fails the gate — the completeness
analog of jaxlint's whole-repo sweep.

Specs are traced at aval level only (``jax.eval_shape`` for states,
``ShapeDtypeStruct`` keys): building the catalog never runs a simulation.
Configs deliberately pin ``stat_sampler="exact"`` where sampling appears so
the traced IR is identical across the jax float-path variations the normal
CLT sampler is allowed (parallel/sweep.py bit-equality caveat).
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Callable

from blockchain_simulator_tpu.lint import common

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

# cached_factory resolutions the discovery matcher accepts (the same set the
# AST static-arg-recompile-hazard rule sanctions).
_FACTORY_CALLS = frozenset({
    "aotcache.cached_factory",
    "blockchain_simulator_tpu.utils.aotcache.cached_factory",
    "utils.aotcache.cached_factory",
    "cached_factory",
})


def discover_factories(paths: list[str] | None = None) -> dict[str, list[str]]:
    """{factory name: [repo-relative files registering it]} over ``paths``
    (default: the package tree).  Pure AST — nothing is imported."""
    if paths is None:
        paths = [os.path.join(REPO_ROOT, "blockchain_simulator_tpu")]
    found: dict[str, list[str]] = {}
    for root in paths:
        files = []
        if os.path.isfile(root):
            files = [root]
        else:
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d != "__pycache__" and not d.startswith(".")
                )
                files.extend(
                    os.path.join(dirpath, fn)
                    for fn in sorted(filenames) if fn.endswith(".py")
                )
        for fp in files:
            try:
                with open(fp, encoding="utf-8") as f:
                    tree = ast.parse(f.read())
            except (OSError, SyntaxError):
                continue
            aliases = common.import_aliases(tree)
            rel = os.path.relpath(fp, REPO_ROOT).replace(os.sep, "/")
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call) and node.args):
                    continue
                r = common.resolve(node.func, aliases)
                if r not in _FACTORY_CALLS:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    found.setdefault(arg.value, [])
                    if rel not in found[arg.value]:
                        found[arg.value].append(rel)
    return found


def _walk_py_files(paths: list[str] | None) -> list[str]:
    if paths is None:
        paths = [os.path.join(REPO_ROOT, "blockchain_simulator_tpu")]
    files = []
    for root in paths:
        if os.path.isfile(root):
            files.append(root)
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(
                d for d in dirnames
                if d != "__pycache__" and not d.startswith(".")
            )
            files.extend(
                os.path.join(dirpath, fn)
                for fn in sorted(filenames) if fn.endswith(".py")
            )
    return files


def discover_mesh_factories(paths: list[str] | None = None) -> dict:
    """{factory name: [repo-relative files]} of every ``cached_factory``
    registration whose decorated function takes a ``mesh`` parameter —
    the mesh-capable subset of :func:`discover_factories`, and the
    completeness surface of the comms audit (lint/comms): a mesh factory
    with no comms spec is an ``unaudited-mesh-factory`` finding, the
    post-SPMD analog of ``unaudited-factory``.  Pure AST, same no-import
    contract."""
    found: dict = {}
    for fp in _walk_py_files(paths):
        try:
            with open(fp, encoding="utf-8") as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            continue
        aliases = common.import_aliases(tree)
        rel = os.path.relpath(fp, REPO_ROOT).replace(os.sep, "/")
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            a = node.args
            params = {
                arg.arg for arg in
                list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
            }
            if "mesh" not in params:
                continue
            for dec in node.decorator_list:
                if not (isinstance(dec, ast.Call) and dec.args):
                    continue
                if common.resolve(dec.func, aliases) not in _FACTORY_CALLS:
                    continue
                arg = dec.args[0]
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ):
                    found.setdefault(arg.value, [])
                    if rel not in found[arg.value]:
                        found[arg.value].append(rel)
    return found


@dataclasses.dataclass
class ProgramSpec:
    """One traceable program of the audit surface.

    ``build()`` (lazy — first jax touch) returns ``(fn, example_args)``
    where ``fn`` is jitted or plain and ``example_args`` may be aval-level
    (``ShapeDtypeStruct`` pytrees).  ``factory`` is the registry name this
    spec covers; specs sharing a ``divergence_group`` must trace to ONE
    fingerprint (the registry-key-divergence contract — one key, one
    executable).  ``budget=False`` skips the FLOP/byte pin (divergence
    twins re-measure a primary program's graph).  ``memory=True``
    additionally COMPILES the program and pins its memory_analysis axes
    (peak temp + argument bytes) — compilation costs real minutes across
    the catalog, so only the representative programs whose RSS stories
    the ROADMAP tracks opt in."""

    program: str
    factory: str
    build: Callable[[], tuple]
    divergence_group: str | None = None
    budget: bool = True
    memory: bool = False


# ------------------------------------------------------------- aval helpers

def _key_sds():
    import jax

    return jax.eval_shape(lambda: jax.random.key(0))


def _keys_sds(b: int):
    import jax
    import jax.numpy as jnp

    return jax.eval_shape(
        lambda: jax.vmap(jax.random.key)(jnp.arange(b, dtype=jnp.uint32))
    )


def _i32_sds(shape=()):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(shape, jnp.int32)


def _raw(factory_wrapper):
    """The undecorated factory (``functools.wraps`` sets ``__wrapped__``):
    audit builds must not populate the process-wide executable registry —
    registry hit/miss stats land on run manifests, and an audit is not a
    run."""
    return getattr(factory_wrapper, "__wrapped__", factory_wrapper)


# ------------------------------------------------------------ audit configs

def audit_configs() -> dict[str, "object"]:
    """The named audit-scale SimConfigs, one per engine arm.  Centralized so
    tests and the catalog agree on the exact traced surface."""
    from blockchain_simulator_tpu.utils.config import SimConfig

    return {
        # tick engines, one per protocol (schedule resolves to 'tick' at n=8)
        "pbft_tick": SimConfig(protocol="pbft", n=8, sim_ms=200,
                               stat_sampler="exact"),
        "raft_tick": SimConfig(protocol="raft", n=8, sim_ms=200,
                               stat_sampler="exact"),
        "paxos_tick": SimConfig(protocol="paxos", n=8, sim_ms=200,
                                stat_sampler="exact"),
        "mixed_tick": SimConfig(protocol="mixed", n=8, mixed_shards=2,
                                sim_ms=200, schedule="tick",
                                stat_sampler="exact"),
        # topology axis (topo/): kregular gather overlays — edge and stat
        # delivery — and the two-level committee hierarchy.  Degree 3 keeps
        # K = 4 < N = 8 so the traced gathers are REAL sparse gathers, not
        # the identity full-overlay case the bit-equality tests pin.
        "pbft_kreg": SimConfig(protocol="pbft", n=8, sim_ms=200,
                               fidelity="clean", topology="kregular",
                               degree=3, stat_sampler="exact"),
        "pbft_kreg_stat": SimConfig(protocol="pbft", n=8, sim_ms=200,
                                    fidelity="clean", topology="kregular",
                                    degree=3, delivery="stat",
                                    stat_sampler="exact"),
        "raft_kreg": SimConfig(protocol="raft", n=8, sim_ms=200,
                               fidelity="clean", topology="kregular",
                               degree=3, stat_sampler="exact"),
        "raft_kreg_stat": SimConfig(protocol="raft", n=8, sim_ms=200,
                                    fidelity="clean", topology="kregular",
                                    degree=3, delivery="stat",
                                    stat_sampler="exact"),
        "paxos_kreg": SimConfig(protocol="paxos", n=8, sim_ms=200,
                                fidelity="clean", topology="kregular",
                                degree=3, stat_sampler="exact"),
        "pbft_comm": SimConfig(protocol="pbft", n=8, sim_ms=200,
                               topology="committee", committees=2,
                               stat_sampler="exact"),
        # fast paths, explicitly scheduled (eligibility asserted in tests)
        "pbft_round": SimConfig(protocol="pbft", n=8, sim_ms=200,
                                delivery="stat", schedule="round",
                                model_serialization=False,
                                stat_sampler="exact"),
        "raft_hb": SimConfig(protocol="raft", n=8, sim_ms=400,
                             delivery="stat", schedule="round",
                             stat_sampler="exact"),
        "mixed_fast": SimConfig(protocol="mixed", n=8, mixed_shards=2,
                                sim_ms=400, delivery="stat",
                                schedule="round", stat_sampler="exact"),
    }


def _audit_mesh():
    """A 2-device nodes mesh for the shard_map wrappers (the degenerate
    sweep axis matches parallel/mesh.make_mesh's layout)."""
    from blockchain_simulator_tpu.parallel.mesh import make_mesh

    return make_mesh(n_node_shards=2, n_sweep=1)


# ---------------------------------------------------------------- catalog

def build_catalog() -> list[ProgramSpec]:
    """Every audited program.  Lazy throughout: importing this module (or
    calling this function) touches no backend — each spec's ``build`` does,
    on first trace."""
    cfgs = audit_configs()
    specs: list[ProgramSpec] = []

    # --- runner.make_sim_fn ("sim"): every engine arm -------------------
    def sim_spec(arm):
        def build():
            from blockchain_simulator_tpu import runner

            return _raw(runner.make_sim_fn)(cfgs[arm]), (_key_sds(),)

        return ProgramSpec(f"sim.{arm}", "sim", build)

    for arm in ("pbft_tick", "pbft_round", "raft_tick", "raft_hb",
                "paxos_tick", "mixed_tick", "mixed_fast",
                # the topology axis: every gather-overlay arm (edge + stat
                # per protocol) and the committee lax.map body — the new
                # programs must come in budgeted, and their gather bodies
                # scatter-free beyond the dense engines' baselined [W]-fold
                # accumulators (tests/test_zztopo.py counts them)
                "pbft_kreg", "pbft_kreg_stat", "raft_kreg",
                "raft_kreg_stat", "paxos_kreg", "pbft_comm"):
        specs.append(sim_spec(arm))

    # --- runner.make_segment_fn ("segment") -----------------------------
    def build_segment():
        import jax

        from blockchain_simulator_tpu import runner
        from blockchain_simulator_tpu.models.base import get_protocol

        cfg = cfgs["pbft_tick"]
        proto = get_protocol(cfg.protocol)
        state, bufs = jax.eval_shape(
            lambda k: proto.init(cfg, jax.random.fold_in(k, 0x1217)),
            _key_sds(),
        )
        seg = _raw(runner.make_segment_fn)(cfg, 50)
        return seg, (_key_sds(), state, bufs, _i32_sds())

    specs.append(ProgramSpec("segment.pbft_tick", "segment", build_segment))

    # --- parallel/sweep._batched_fn ("sweep-batched") -------------------
    def build_batched():
        from blockchain_simulator_tpu.parallel import sweep

        return _raw(sweep._batched_fn)(cfgs["pbft_tick"], None), (_keys_sds(2),)

    specs.append(ProgramSpec(
        "sweep_batched.pbft_tick", "sweep-batched", build_batched
    ))

    # --- parallel/sweep.dyn_batched_fn ("sweep-batched-dynf") -----------
    # Divergence twins: fault configs that differ only in COUNTS must trace
    # to ONE jaxpr after canonicalization — otherwise run_fault_sweep's
    # same-structure grouping silently recompiles per point (the leak the
    # registry-key-divergence rule exists to catch).
    def dynf_spec(name, base_arm, fc_kw, group, budget):
        def build():
            import dataclasses as _dc

            import jax

            from blockchain_simulator_tpu import runner

            cfg = cfgs[base_arm]
            cfg = cfg.with_(faults=_dc.replace(cfg.faults, **fc_kw))
            # make_dyn_sim_fn canonicalizes internally — the twins' traces
            # must come out identical, which is exactly what the
            # registry-key-divergence rule asserts.  Per-call jit is fine:
            # audit builds trace once and never execute.
            fn = jax.jit(jax.vmap(runner.make_dyn_sim_fn(cfg)))  # jaxlint: disable=static-arg-recompile-hazard
            return fn, (_keys_sds(2), _i32_sds((2,)), _i32_sds((2,)))

        return ProgramSpec(name, "sweep-batched-dynf", build,
                           divergence_group=group, budget=budget)

    specs.append(dynf_spec("sweep_dynf.pbft", "pbft_tick",
                           {"n_byzantine": 1}, "dynf:pbft_tick", True))
    specs.append(dynf_spec("sweep_dynf.pbft_b2", "pbft_tick",
                           {"n_byzantine": 2}, "dynf:pbft_tick", False))
    specs.append(dynf_spec("sweep_dynf.raft", "raft_tick",
                           {"n_crashed": 1}, "dynf:raft_tick", True))
    specs.append(dynf_spec("sweep_dynf.raft_c2", "raft_tick",
                           {"n_crashed": 2}, "dynf:raft_tick", False))
    # topology-axis twins: ONE executable per (protocol, topology, fault
    # structure) — fault counts over one kregular overlay / committee
    # hierarchy must trace to one fingerprint, or topology sweeps silently
    # recompile per fault level (the ISSUE 15 registry pin)
    specs.append(dynf_spec("sweep_dynf.pbft_kreg", "pbft_kreg",
                           {"n_crashed": 1}, "dynf:pbft_kreg", True))
    specs.append(dynf_spec("sweep_dynf.pbft_kreg_c2", "pbft_kreg",
                           {"n_crashed": 2}, "dynf:pbft_kreg", False))
    specs.append(dynf_spec("sweep_dynf.pbft_comm", "pbft_comm",
                           {"n_crashed": 1}, "dynf:pbft_comm", True))
    specs.append(dynf_spec("sweep_dynf.pbft_comm_c2", "pbft_comm",
                           {"n_crashed": 2}, "dynf:pbft_comm", False))

    # --- parallel/sweep.mesh_dyn_batched_fn ("partition-dyn-sweep") -----
    # The mesh-partitioned sweep executable (parallel/partition.py layer):
    # shard_map over the batch axis, per-device lax.map of the unvmapped
    # dyn sim.  Divergence twins mirror the dynf pair — fault configs
    # differing only in counts must trace to ONE fingerprint per mesh, or
    # a mesh sweep silently recompiles per point.  The nodes arm traces
    # the explicit-sharding pjit path (node axis sharded for large n).
    def partition_dynf_spec(name, fc_kw, sweep_n, node_n, group, budget):
        def build():
            import dataclasses as _dc

            from blockchain_simulator_tpu.parallel import sweep
            from blockchain_simulator_tpu.parallel.mesh import make_mesh

            cfg = cfgs["pbft_tick"]
            cfg = cfg.with_(faults=_dc.replace(cfg.faults, **fc_kw))
            mesh = make_mesh(n_node_shards=node_n, n_sweep=sweep_n)
            fn = _raw(sweep.mesh_dyn_batched_fn)(cfg, mesh)
            b = max(sweep_n, 2)
            return fn, (_keys_sds(b), _i32_sds((b,)), _i32_sds((b,)))

        return ProgramSpec(name, "partition-dyn-sweep", build,
                           divergence_group=group, budget=budget)

    specs.append(partition_dynf_spec(
        "partition_dynf.pbft", {"n_byzantine": 1}, 2, 1,
        "partition-dynf:pbft_tick", True))
    specs.append(partition_dynf_spec(
        "partition_dynf.pbft_b2", {"n_byzantine": 2}, 2, 1,
        "partition-dynf:pbft_tick", False))
    specs.append(partition_dynf_spec(
        "partition_dynf.pbft_nodes", {"n_byzantine": 1}, 1, 2,
        None, True))

    # --- parallel/sweep.multi_seed_fn ("multi-seed-tick") ---------------
    # The single-device multi-seed Monte Carlo executable: lax.map over the
    # UNVMAPPED dyn sim (ISSUE 13).  Its whole reason to exist is the
    # scatter-free body (#0i), so its budget entry carries NO baselined
    # scatter findings — any scatter lowering in this program is a NEW
    # slow-lowering-confirmed finding and fails the gate.  Divergence
    # twins: fault-count (and seed — canonical_fault_cfg normalizes it)
    # changes at one seed count must share ONE fingerprint, so a sweep
    # tile's level never mints a second executable.
    def multi_seed_spec(name, arm, fc_kw, seed, group, budget):
        def build():
            import dataclasses as _dc

            from blockchain_simulator_tpu.parallel import sweep

            cfg = cfgs[arm].with_(seed=seed)
            cfg = cfg.with_(faults=_dc.replace(cfg.faults, **fc_kw))
            from blockchain_simulator_tpu.models.base import canonical_fault_cfg

            fn = _raw(sweep.multi_seed_fn)(canonical_fault_cfg(cfg), 2)
            return fn, (_keys_sds(2), _i32_sds((2,)), _i32_sds((2,)))

        return ProgramSpec(name, "multi-seed-tick", build,
                           divergence_group=group, budget=budget)

    specs.append(multi_seed_spec("multi_seed.pbft", "pbft_tick",
                                 {"n_byzantine": 1}, 0,
                                 "multi-seed:pbft_tick", True))
    specs.append(multi_seed_spec("multi_seed.pbft_b2_s7", "pbft_tick",
                                 {"n_byzantine": 2}, 7,
                                 "multi-seed:pbft_tick", False))
    specs.append(multi_seed_spec("multi_seed.raft", "raft_tick",
                                 {"n_crashed": 1}, 0, None, True))

    # --- serve/dispatch._solo_fn ("serve-solo") -------------------------
    # The scenario server's un-vmapped degrade/solo path.  Divergence
    # twins mirror the dynf pair: requests differing only in fault counts
    # (or seed — canonical_fault_cfg normalizes both) must trace to ONE
    # fingerprint, or the server silently recompiles per request.
    def serve_solo_spec(name, fc_kw, seed, budget):
        def build():
            import dataclasses as _dc

            from blockchain_simulator_tpu.serve import dispatch

            cfg = cfgs["pbft_tick"].with_(seed=seed)
            cfg = cfg.with_(faults=_dc.replace(cfg.faults, **fc_kw))
            fn = _raw(dispatch._solo_fn)(cfg)
            return fn, (_key_sds(), _i32_sds(), _i32_sds())

        return ProgramSpec(name, "serve-solo", build,
                           divergence_group="serve-solo:pbft_tick",
                           budget=budget)

    specs.append(serve_solo_spec("serve_solo.pbft", {"n_byzantine": 1}, 0,
                                 True))
    specs.append(serve_solo_spec("serve_solo.pbft_b2_s7", {"n_byzantine": 2},
                                 7, False))

    # --- parallel/shard.py factories ------------------------------------
    def shard_spec(program, factory, fget, arm):
        def build():
            fn = fget()(cfgs[arm], _audit_mesh())
            return fn, (_key_sds(),)

        return ProgramSpec(program, factory, build)

    def _shard_mod():
        from blockchain_simulator_tpu.parallel import shard

        return shard

    specs.append(shard_spec(
        "shard.sim_tick", "shard-sim",
        lambda: _raw(_shard_mod().make_sharded_sim_fn), "pbft_tick"))
    specs.append(shard_spec(
        "shard.pbft_round", "shard-round",
        lambda: _raw(_shard_mod()._make_sharded_round_fn), "pbft_round"))
    specs.append(shard_spec(
        "shard.raft_hb", "shard-raft-hb",
        lambda: _raw(_shard_mod()._make_sharded_raft_hb_fn), "raft_hb"))
    specs.append(shard_spec(
        "shard.mixed_fast", "shard-mixed",
        lambda: _raw(_shard_mod()._make_sharded_mixed_fast_fn), "mixed_fast"))

    # --- parallel/sweep.sharded_topo_sim_fn ("shard-topo-sim") ----------
    # The node-dim-sharded overlay programs (ISSUE 16).  The kregular arm
    # is audited through ``sim.partitioned`` + ``sim.table_avals`` — the
    # pjit callable with the [N, K+1] overlay tables as OPERANDS — so the
    # traced jaxpr proves the tables stopped being baked constants
    # (large-jaxpr-constant stays clean by construction, not by waiver).
    # Divergence twins: fault counts over one kregular overlay must trace
    # to ONE fingerprint per mesh (the one-executable-per-(protocol,
    # topology, fault structure, mesh) registry pin).
    def shard_topo_spec(name, arm, fc_kw, group, budget):
        def build():
            import dataclasses as _dc

            from blockchain_simulator_tpu.models.base import canonical_fault_cfg
            from blockchain_simulator_tpu.parallel import sweep

            cfg = cfgs[arm]
            if fc_kw:
                cfg = cfg.with_(faults=_dc.replace(cfg.faults, **fc_kw))
            sim = _raw(sweep.sharded_topo_sim_fn)(
                canonical_fault_cfg(cfg), _audit_mesh()
            )
            args = (_key_sds(), _i32_sds(), _i32_sds())
            if hasattr(sim, "partitioned"):
                return sim.partitioned, args + tuple(sim.table_avals)
            return sim, args

        return ProgramSpec(name, "shard-topo-sim", build,
                           divergence_group=group, budget=budget)

    specs.append(shard_topo_spec("shard_topo.pbft_kreg", "pbft_kreg",
                                 {"n_crashed": 1}, "shard-topo:pbft_kreg",
                                 True))
    specs.append(shard_topo_spec("shard_topo.pbft_kreg_c2", "pbft_kreg",
                                 {"n_crashed": 2}, "shard-topo:pbft_kreg",
                                 False))
    specs.append(shard_topo_spec("shard_topo.raft_kreg", "raft_kreg",
                                 {}, None, True))
    specs.append(shard_topo_spec("shard_topo.pbft_comm", "pbft_comm",
                                 {"n_crashed": 1}, None, True))

    # --- utils/trace.py factories ---------------------------------------
    def build_trace_tick():
        from blockchain_simulator_tpu.utils import trace

        return _raw(trace._tick_traced_fn)(cfgs["pbft_tick"]), (_key_sds(),)

    specs.append(ProgramSpec("trace.tick", "trace-tick", build_trace_tick))

    def build_trace_round():
        from blockchain_simulator_tpu.utils import trace

        return (_raw(trace._pbft_round_traced_fn)(cfgs["pbft_round"]),
                (_key_sds(),))

    specs.append(ProgramSpec(
        "trace.pbft_round", "trace-pbft-round", build_trace_round
    ))

    # The raft_hb / mixed trace factories return several programs (the host
    # drives the phase split); every one of them is an executable the
    # registry serves, so every one is audited.  Downstream example args
    # come from eval_shape chains — still nothing executes.
    def _raft_hb_fns():
        from blockchain_simulator_tpu.utils import trace

        return _raw(trace._raft_hb_traced_fns)(cfgs["raft_hb"])

    def build_hb_prefix():
        return _raft_hb_fns()[0], (_key_sds(),)

    def build_hb_steady():
        import jax

        prefix, steady, _ = _raft_hb_fns()
        carry, _ys, _ok, h = jax.eval_shape(prefix, _key_sds())
        return steady, (carry[0], h, _key_sds())

    def build_hb_cont():
        import jax

        prefix, _, cont = _raft_hb_fns()
        carry, _ys, _ok, _h = jax.eval_shape(prefix, _key_sds())
        return cont, (carry, _key_sds())

    specs.append(ProgramSpec(
        "trace.raft_hb_prefix", "trace-raft-hb", build_hb_prefix))
    specs.append(ProgramSpec(
        "trace.raft_hb_steady", "trace-raft-hb", build_hb_steady))
    specs.append(ProgramSpec(
        "trace.raft_hb_cont", "trace-raft-hb", build_hb_cont))

    def _mixed_fns():
        from blockchain_simulator_tpu.utils import trace

        return _raw(trace._mixed_traced_fns)(cfgs["mixed_fast"])

    def build_mx_prefix():
        return _mixed_fns()[0], (_key_sds(),)

    def build_mx_finish():
        import jax

        prefix, finish, _, _ = _mixed_fns()
        carry, _ok, h_s = jax.eval_shape(prefix, _key_sds())
        return finish, (carry, h_s, _key_sds())

    def build_mx_prefix_probed():
        return _mixed_fns()[2], (_key_sds(),)

    def build_mx_cont():
        import jax

        _, _, prefix_probed, cont = _mixed_fns()
        carry, _ys = jax.eval_shape(prefix_probed, _key_sds())
        return cont, (carry, _key_sds())

    specs.append(ProgramSpec(
        "trace.mixed_prefix", "trace-mixed", build_mx_prefix))
    specs.append(ProgramSpec(
        "trace.mixed_finish", "trace-mixed", build_mx_finish))
    specs.append(ProgramSpec(
        "trace.mixed_prefix_probed", "trace-mixed", build_mx_prefix_probed))
    specs.append(ProgramSpec(
        "trace.mixed_cont", "trace-mixed", build_mx_cont))

    # --- utils/trace._committee_traced_fn ("trace-committee") -----------
    # The committee --trace arm (ISSUE 17 satellite: the old typed refusal
    # became a stacked [C, T] probe program).  Taps ride inside the jit, so
    # the host-callback rule audits it like every consensus program.
    def build_trace_committee():
        from blockchain_simulator_tpu.utils import trace

        return (_raw(trace._committee_traced_fn)(cfgs["pbft_comm"]),
                (_key_sds(),))

    specs.append(ProgramSpec(
        "trace.committee", "trace-committee", build_trace_committee))

    # --- obsim/build.py factories ("consobs-*") -------------------------
    # The armed twins of the dyn-fault programs (ISSUE 17): probe taps +
    # monitors as extra scan outputs.  Audited for the same contracts as
    # their disarmed twins — no host callback in the HLO (the taps are
    # traced data, the telemetry hook is host-side in obsim/host.py), no
    # scatter in the batched bodies — plus divergence twins pinning ONE
    # executable per (fault structure, probe config): arming probes must
    # not reintroduce the per-fault-level recompile leak.
    def _pcfg():
        from blockchain_simulator_tpu.obsim import schema as obsim_schema

        return obsim_schema.ProbeConfig()

    def consobs_solo_spec(name, arm, fc_kw, group, budget):
        def build():
            import dataclasses as _dc

            from blockchain_simulator_tpu.obsim import build as obsim_build

            cfg = cfgs[arm]
            if fc_kw:
                cfg = cfg.with_(faults=_dc.replace(cfg.faults, **fc_kw))
            fn = _raw(obsim_build.probed_solo_fn)(cfg, _pcfg())
            return fn, (_key_sds(), _i32_sds(), _i32_sds())

        return ProgramSpec(name, "consobs-solo", build,
                           divergence_group=group, budget=budget)

    specs.append(consobs_solo_spec("consobs.solo_pbft", "pbft_tick",
                                   {"n_byzantine": 1},
                                   "consobs-solo:pbft_tick", True))
    specs.append(consobs_solo_spec("consobs.solo_pbft_b2", "pbft_tick",
                                   {"n_byzantine": 2},
                                   "consobs-solo:pbft_tick", False))
    specs.append(consobs_solo_spec("consobs.solo_comm", "pbft_comm",
                                   {}, None, True))
    specs.append(consobs_solo_spec("consobs.solo_raft_hb", "raft_hb",
                                   {}, None, True))
    specs.append(consobs_solo_spec("consobs.solo_pbft_round", "pbft_round",
                                   {}, None, True))

    def consobs_batched_spec(name, fc_kw, multi_seed, group, budget):
        def build():
            import dataclasses as _dc

            from blockchain_simulator_tpu.obsim import build as obsim_build

            cfg = cfgs["pbft_tick"]
            cfg = cfg.with_(faults=_dc.replace(cfg.faults, **fc_kw))
            fn = _raw(obsim_build.probed_batched_fn)(
                cfg, _pcfg(), multi_seed=multi_seed
            )
            return fn, (_keys_sds(2), _i32_sds((2,)), _i32_sds((2,)))

        return ProgramSpec(name, "consobs-batched", build,
                           divergence_group=group, budget=budget)

    specs.append(consobs_batched_spec(
        "consobs.batched_pbft", {"n_byzantine": 1}, False,
        "consobs-batched:pbft_tick", True))
    specs.append(consobs_batched_spec(
        "consobs.batched_pbft_b2", {"n_byzantine": 2}, False,
        "consobs-batched:pbft_tick", False))
    # the multi-seed lax.map arm inherits the scatter-free-body contract
    # of multi-seed-tick (#0i): probes must not smuggle a scatter in
    specs.append(consobs_batched_spec(
        "consobs.batched_multi_seed", {"n_byzantine": 1}, True,
        None, True))

    def consobs_mesh_spec(name, sweep_n, node_n, budget):
        def build():
            from blockchain_simulator_tpu.obsim import build as obsim_build
            from blockchain_simulator_tpu.parallel.mesh import make_mesh

            mesh = make_mesh(n_node_shards=node_n, n_sweep=sweep_n)
            fn = _raw(obsim_build.probed_mesh_fn)(
                cfgs["pbft_tick"], _pcfg(), mesh
            )
            b = max(sweep_n, 2)
            return fn, (_keys_sds(b), _i32_sds((b,)), _i32_sds((b,)))

        return ProgramSpec(name, "consobs-mesh", build, budget=budget)

    specs.append(consobs_mesh_spec("consobs.mesh_sweep", 2, 1, True))
    specs.append(consobs_mesh_spec("consobs.mesh_nodes", 1, 2, True))

    for s in specs:
        if s.program in MEMORY_PINNED:
            s.memory = True
    return specs


# The memory-pinned subset: one program per RSS story the ROADMAP tracks
# (dense tick/round engines, the gather-overlay arms behind the 1M/4M-node
# RSS numbers, the batched sweep, the sharded overlay, the serving solo
# path).  Compiling is the expensive step — ~8 compiles keeps the gate
# under a minute where pinning all ~34 budgeted programs costs 10+.
MEMORY_PINNED = frozenset({
    "sim.pbft_tick",
    "sim.pbft_round",
    "sim.raft_tick",
    "sim.pbft_kreg",
    "sim.pbft_comm",
    "sweep_dynf.pbft",
    "shard_topo.pbft_kreg",
    "serve_solo.pbft",
})
