"""jaxgraph audit engine: trace the catalog, run IR rules, gate budgets.

Mechanics deliberately mirror ``lint/engine.py``: findings are grandfathered
in a committed baseline (``GRAPH_BASELINE.json``) keyed on stable identities
with per-entry justifications; ``--write-baseline`` regenerates the file
preserving them; the CLI exits 1 on any non-baselined finding and 2 on
infrastructure errors (a factory that stopped tracing IS an infrastructure
error — the acceptance contract is that every registered executable stays
auditable).

The baseline file carries a second section jaxlint has no analog for:
``budgets`` pins each program's analytical FLOP/byte cost
(``Lowered.cost_analysis()``, bit-stable run to run).  The gate fires when a
program's measured cost grows beyond ``tolerance`` over its pin — a static
perf regression caught in CI without running a bench.  Shrinking beyond
tolerance is reported as a stale budget (refresh with ``--write-baseline``),
never gated: getting faster is the goal, same as the bench_compare
``_compile_s`` carve-out.
"""

from __future__ import annotations

import dataclasses
import json
import os

from blockchain_simulator_tpu.lint import baseline as baseline_mod
from blockchain_simulator_tpu.lint.graph import ir
from blockchain_simulator_tpu.lint.graph import programs as prog_mod

BASELINE_NAME = "GRAPH_BASELINE.json"
REPO_ROOT = prog_mod.REPO_ROOT

# Constants below this many bytes are normal trace residue (fault masks,
# iota seeds); at or above it they bloat every serialized
# $BLOCKSIM_COMPILE_CACHE entry and — when derived from per-point values a
# sweep varies — defeat the one-executable-per-fault-structure contract.
LARGE_CONST_BYTES = 1 << 16  # 64 KiB

# Budget growth beyond this fraction of the pinned value fails the gate.
DEFAULT_TOLERANCE = 0.25


@dataclasses.dataclass
class GraphFinding:
    """One IR-contract violation for one program (or factory/group)."""

    rule: str
    program: str   # program name, factory name, or divergence group
    detail: str    # stable identity within (rule, program)
    message: str
    count: int = 1

    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.program, self.detail)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


RULE_SUMMARIES = {
    "host-callback-in-program": (
        "pure_callback/io_callback/debug/infeed primitives traced into a "
        "sim program (breaks serialized executables + vmap composition)"
    ),
    "f64-in-program": (
        "64-bit dtype aval in the trace (x64 leak: doubles memory traffic, "
        "breaks 32-bit engine-boundary contracts)"
    ),
    "weak-type-boundary": (
        "weak-typed program input/output (re-specializes per caller "
        "context: one registry key, many executables)"
    ),
    "large-jaxpr-constant": (
        f"constant >= {LARGE_CONST_BYTES} bytes baked into the jaxpr "
        "(bloats $BLOCKSIM_COMPILE_CACHE payloads; should be an operand)"
    ),
    "slow-lowering-confirmed": (
        "scatter/sort/cum* primitive confirmed in the traced IR (the "
        "ground-truth replacement for the AST slow-cpu-lowering allowlist)"
    ),
    "registry-key-divergence": (
        "one registry key traced to multiple distinct jaxprs across sweep "
        "points (silent recompile leak)"
    ),
    "unaudited-factory": (
        "cached_factory registration with no covering audit program "
        "(grow lint/graph/programs.py with the factory)"
    ),
    "budget-missing": (
        "program has no pinned FLOP/byte/memory budget in "
        "GRAPH_BASELINE.json (pin with --write-baseline)"
    ),
    "budget-regression": (
        "program's analytical FLOP/byte cost or compiled memory footprint "
        "(peak temp + argument bytes) grew beyond tolerance over its "
        "pinned budget (static perf regression)"
    ),
}

# The pinned budget axes: flops/bytes come from the analytical cost model
# (Lowered.cost_analysis), temp_bytes/argument_bytes from the compiled
# executable's memory_analysis() — peak XLA temp allocation and total
# argument bytes per device.  Memory axes turn the RSS stories (7.4 GB @1M
# nodes, 12.4 GB @4M — ROADMAP item 3) into pinned numbers instead of lore.
BUDGET_AXES = ("flops", "bytes", "temp_bytes", "argument_bytes")


@dataclasses.dataclass
class ProgramReport:
    """Everything measured about one traced program."""

    program: str
    factory: str
    fingerprint: str
    cost: dict | None            # {"flops", "bytes"} or None
    memory: dict | None          # {"temp_bytes", "argument_bytes"} or None
    prims: dict                  # {primitive: count} (flagged subset)
    n_eqns: int
    const_bytes: int
    divergence_group: str | None
    budget: bool

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class AuditResult:
    reports: dict                 # {program: ProgramReport}
    findings: list                # [GraphFinding], pre-baseline
    errors: list                  # ["spec: message"] — exit-2 material
    factories: dict               # discovered {factory: [files]}
    uncovered: list               # factory names with no spec
    stale_budgets: list           # [(program, axis, measured, pinned)]


def _check_program(rep: ProgramReport, closed) -> list[GraphFinding]:
    """The per-program IR rules (everything not needing cross-program or
    baseline context)."""
    findings: list[GraphFinding] = []
    counts = ir.primitive_counts(closed)

    for prim in sorted(ir.HOST_CALLBACK_PRIMS & counts.keys()):
        findings.append(GraphFinding(
            rule="host-callback-in-program", program=rep.program, detail=prim,
            count=counts[prim],
            message=(
                f"host-callback primitive `{prim}` x{counts[prim]} traced "
                f"into `{rep.program}`: the program is no longer a "
                "self-contained executable (serialization, vmap/shard_map "
                "sweeps and wedged-tunnel hangs all regress)"
            ),
        ))

    for dtype, n in sorted(ir.wide_dtypes(closed).items()):
        findings.append(GraphFinding(
            rule="f64-in-program", program=rep.program, detail=dtype, count=n,
            message=(
                f"{n} aval(s) of 64-bit dtype `{dtype}` in `{rep.program}`: "
                "an x64 leak (numpy float64 constant or flipped flag) — the "
                "repo's engines are 32-bit end to end"
            ),
        ))

    for desc in ir.boundary_weak_types(closed):
        findings.append(GraphFinding(
            rule="weak-type-boundary", program=rep.program, detail=desc,
            message=(
                f"weak-typed boundary aval {desc} on `{rep.program}`: weak "
                "types re-specialize on caller literal context, so one "
                "registry key can silently compile multiple executables"
            ),
        ))

    for shape, dtype, nbytes in ir.const_leaves(closed):
        if nbytes >= LARGE_CONST_BYTES:
            findings.append(GraphFinding(
                rule="large-jaxpr-constant", program=rep.program,
                detail=f"{shape}:{dtype}",
                message=(
                    f"constant {shape}:{dtype} ({nbytes} bytes) baked into "
                    f"`{rep.program}`'s jaxpr: serialized cache entries "
                    "carry it verbatim and sweep points that vary it split "
                    "the executable; pass it as an operand"
                ),
            ))

    for prim in sorted(ir.SLOW_PRIMS & counts.keys()):
        findings.append(GraphFinding(
            rule="slow-lowering-confirmed", program=rep.program, detail=prim,
            count=counts[prim],
            message=(
                f"confirmed-slow lowering `{prim}` x{counts[prim]} in "
                f"`{rep.program}` (XLA:CPU serializes scatter/sort/cum* — "
                "KNOWN_ISSUES #0b); measured-acceptable sites belong in "
                "GRAPH_BASELINE.json with their measurement"
            ),
        ))
    return findings


def run_audit(specs=None, factories=None) -> AuditResult:
    """Trace every spec and run every rule that needs no baseline.

    Budget findings are attached separately (:func:`apply_budgets`) because
    they compare against the baseline file, which callers may be rewriting.
    """
    if specs is None:
        specs = prog_mod.build_catalog()
    if factories is None:
        factories = prog_mod.discover_factories()

    reports: dict[str, ProgramReport] = {}
    findings: list[GraphFinding] = []
    errors: list[str] = []
    closed_by_program: dict[str, object] = {}

    for spec in specs:
        try:
            fn, example_args = spec.build()
            closed, lowered = ir.trace_program(fn, example_args)
        except Exception as e:  # exit-2 material: factories must stay traceable
            errors.append(f"{spec.program}: {type(e).__name__}: {e}")
            continue
        counts = ir.primitive_counts(closed)
        flagged = {
            p: c for p, c in counts.items()
            if p in ir.SLOW_PRIMS or p in ir.HOST_CALLBACK_PRIMS
        }
        rep = ProgramReport(
            program=spec.program,
            factory=spec.factory,
            fingerprint=ir.fingerprint(closed),
            cost=ir.cost_summary(lowered),
            # compiling is the expensive step — only the MEMORY_PINNED
            # subset pays it (programs.py: the RSS-story representatives)
            memory=ir.memory_summary(lowered)
            if (spec.budget and getattr(spec, "memory", False)) else None,
            prims=flagged,
            n_eqns=sum(counts.values()),
            const_bytes=sum(b for _, _, b in ir.const_leaves(closed)),
            divergence_group=spec.divergence_group,
            budget=spec.budget,
        )
        reports[spec.program] = rep
        closed_by_program[spec.program] = closed
        findings.extend(_check_program(rep, closed))

    # registry-key divergence: specs sharing a group must share a jaxpr
    groups: dict[str, list[ProgramReport]] = {}
    for rep in reports.values():
        if rep.divergence_group:
            groups.setdefault(rep.divergence_group, []).append(rep)
    for group, reps in sorted(groups.items()):
        prints = sorted({r.fingerprint for r in reps})
        if len(prints) > 1:
            members = ", ".join(
                f"{r.program}={r.fingerprint[:8]}" for r in reps
            )
            findings.append(GraphFinding(
                rule="registry-key-divergence", program=group,
                detail="+".join(p[:8] for p in prints),
                message=(
                    f"registry key group `{group}` traced to "
                    f"{len(prints)} distinct jaxprs ({members}): sweep "
                    "points that should share one executable will silently "
                    "recompile per point (canonical_fault_cfg regression)"
                ),
            ))

    # completeness: every discovered factory registration is covered
    covered = {s.factory for s in specs}
    uncovered = sorted(set(factories) - covered)
    for name in uncovered:
        findings.append(GraphFinding(
            rule="unaudited-factory", program=name,
            detail=(factories[name] or ["?"])[0],
            message=(
                f"cached_factory(\"{name}\") registered in "
                f"{', '.join(factories[name])} has no audit program — add a "
                "ProgramSpec in lint/graph/programs.py so its IR stays "
                "under contract"
            ),
        ))

    return AuditResult(
        reports=reports, findings=findings, errors=errors,
        factories=factories, uncovered=uncovered, stale_budgets=[],
    )


# ---------------------------------------------------------------- baseline

def load_baseline(path: str) -> dict:
    """GRAPH_BASELINE.json -> {"budgets": {...}, "entries": {key: entry},
    "tolerance": float}."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    return {
        "budgets": doc.get("budgets", {}),
        "entries": baseline_mod.load_entries(doc),
        "tolerance": float(doc.get("tolerance", DEFAULT_TOLERANCE)),
    }


def _measured_budget(rep: ProgramReport) -> dict:
    """The measurable budget axes of one report, merged (cost axes +
    compiled memory axes; absent surfaces simply omit their keys)."""
    merged = dict(rep.cost or {})
    if rep.memory:
        merged.update(rep.memory)
    return merged


def apply_budgets(result: AuditResult, budgets: dict, tolerance: float) -> None:
    """Attach budget-missing / budget-regression findings (and stale-budget
    notes) to ``result`` by comparing measured costs against the pins."""
    for name in sorted(result.reports):
        rep = result.reports[name]
        if not rep.budget:
            continue
        if rep.cost is None:
            result.errors.append(
                f"{name}: backend returned no cost analysis "
                "(budget gate needs Lowered.cost_analysis())"
            )
            continue
        measured_all = _measured_budget(rep)
        pin = budgets.get(name)
        if pin is None:
            result.findings.append(GraphFinding(
                rule="budget-missing", program=name, detail="budget",
                message=(
                    f"`{name}` has no pinned FLOP/byte/memory budget "
                    f"(measured flops={rep.cost['flops']:.0f} "
                    f"bytes={rep.cost['bytes']:.0f}); pin with "
                    "--write-baseline"
                ),
            ))
            continue
        for axis in BUDGET_AXES:
            measured, pinned = measured_all.get(axis), float(
                pin.get(axis, 0.0)
            )
            if pinned <= 0:
                continue
            if measured is None:
                result.errors.append(
                    f"{name}: budget axis {axis} is pinned but the backend "
                    "measured nothing for it (compiled memory_analysis "
                    "unavailable?)"
                )
                continue
            if measured > pinned * (1.0 + tolerance):
                result.findings.append(GraphFinding(
                    rule="budget-regression", program=name, detail=axis,
                    message=(
                        f"`{name}` {axis} grew {measured / pinned:.2f}x over "
                        f"its pin ({measured:.0f} vs {pinned:.0f}, tolerance "
                        f"+{tolerance:.0%}): a static perf regression — "
                        "shrink the program or re-pin with --write-baseline "
                        "and a justification in the PR"
                    ),
                ))
            elif measured < pinned * (1.0 - tolerance):
                result.stale_budgets.append((name, axis, measured, pinned))


def split_by_baseline(
    findings: list[GraphFinding], entries: dict
) -> tuple[list[GraphFinding], int, list[tuple]]:
    """(new findings, n_baselined, stale entry keys) — the shared count
    semantics (lint/baseline.py): an entry absorbs findings up to its
    count; a finding whose count GREW past the entry's stays new (a
    program gaining scatters is a change, not grandfather)."""
    return baseline_mod.split_by_baseline(findings, entries)


def write_baseline(
    path: str, result: AuditResult, old: dict | None = None,
    tolerance: float | None = None, full: bool = True,
) -> dict:
    """Write measured budgets + current findings as the new baseline,
    preserving old justifications (the lint/engine.py contract).  Budget
    findings are represented by the refreshed budgets, not entries.

    ``full=False`` (a ``--only`` subset run): old budgets and entries for
    programs OUTSIDE this run's reports are preserved wholesale, so
    re-baselining one program never silently drops the pins (and
    hand-written justifications) of the rest — the same subset contract as
    jaxlint's ``write_baseline(linted_paths=...)``."""
    old = old or {"budgets": {}, "entries": {}, "tolerance": DEFAULT_TOLERANCE}
    budgets = {
        name: _measured_budget(rep)
        for name, rep in sorted(result.reports.items())
        if rep.budget and rep.cost is not None
    }
    counts = baseline_mod.collapse_counts(
        result.findings, skip_rules=("budget-missing", "budget-regression")
    )
    if not full:
        audited = set(result.reports)
        for name, pin in old["budgets"].items():
            if name not in audited:
                budgets[name] = pin
        for key, entry in old["entries"].items():
            if key[1] not in audited and key not in counts:
                counts[key] = entry["count"]
        budgets = dict(sorted(budgets.items()))
    doc = {
        "jaxgraph_baseline": 1,
        "comment": (
            "IR-level grandfathered findings + per-program budgets: "
            "analytical FLOP/byte cost (Lowered.cost_analysis) and "
            "compiled memory footprint (memory_analysis peak temp + "
            "argument bytes), all bit-stable.  Regenerate with `python -m "
            "blockchain_simulator_tpu.lint.graph --write-baseline` "
            "(justifications preserved); new programs must come in clean "
            "and budgeted."
        ),
        "tolerance": tolerance if tolerance is not None
        else old.get("tolerance", DEFAULT_TOLERANCE),
        "budgets": budgets,
        "entries": baseline_mod.merge_entries(counts, old["entries"]),
    }
    baseline_mod.dump_doc(path, doc)
    return doc


def prune_baseline(path: str, result: AuditResult, old: dict) -> dict:
    """Baseline hygiene (``--prune-baseline``, the jaxlint analog): rewrite
    the baseline keeping only what the current catalog still justifies —
    finding entries shrink to the count actually consumed by ``result``'s
    findings (fixed entries drop entirely) and budgets whose program is no
    longer in the catalog drop (retired programs must not linger as stale
    pins).  Live budget VALUES and all justifications are preserved
    untouched: pruning never re-pins — that is ``--write-baseline``'s job.

    Returns ``{"dropped_entries": [...], "shrunk_entries": [...],
    "dropped_budgets": [...]}``.  ``result`` must come from a FULL audit
    run (a subset run cannot distinguish retired from out-of-scope)."""
    consumed = baseline_mod.collapse_counts(
        result.findings, skip_rules=("budget-missing", "budget-regression")
    )
    audited = set(result.reports)
    dropped_budgets = sorted(set(old["budgets"]) - audited)
    budgets = {name: pin for name, pin in sorted(old["budgets"].items())
               if name in audited}
    entries, dropped_entries, shrunk_entries = baseline_mod.prune_entries(
        old["entries"], consumed
    )
    doc = {
        "jaxgraph_baseline": 1,
        "comment": (
            "IR-level grandfathered findings + per-program budgets: "
            "analytical FLOP/byte cost (Lowered.cost_analysis) and "
            "compiled memory footprint (memory_analysis peak temp + "
            "argument bytes), all bit-stable.  Regenerate with `python -m "
            "blockchain_simulator_tpu.lint.graph --write-baseline` "
            "(justifications preserved); new programs must come in clean "
            "and budgeted."
        ),
        "tolerance": old.get("tolerance", DEFAULT_TOLERANCE),
        "budgets": budgets,
        "entries": entries,
    }
    baseline_mod.dump_doc(path, doc)
    return {
        "dropped_entries": dropped_entries,
        "shrunk_entries": shrunk_entries,
        "dropped_budgets": dropped_budgets,
    }


def default_baseline_path() -> str:
    return os.path.join(REPO_ROOT, BASELINE_NAME)
