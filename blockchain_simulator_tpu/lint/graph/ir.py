"""jaxpr-walking primitives for the graph auditor.

Everything here is aval-level: programs are *traced* (``jit(f).trace`` /
``jax.eval_shape``), never executed, and cost comes from
``Lowered.cost_analysis()`` — XLA's analytical model on the lowered module —
so a whole-repo audit touches no simulation data and stays deterministic
(the bit-stability the budget gate relies on; pinned in tests).

The walkers duck-type jaxprs (``.eqns`` / ``.jaxpr`` attributes) instead of
importing ``jax._src`` internals, so they keep working across the jax
versions this repo straddles (0.4.x container, current releases on TPU).
"""

from __future__ import annotations

import hashlib
from collections import Counter

# Primitives that hand control back to the host mid-program.  Any of these
# inside a sim program breaks the "compiled graph is the artifact" contract:
# serialized executables stop being self-contained, vmap/shard_map sweeps
# serialize on the callback, and a wedged tunnel can hang mid-step
# (KNOWN_ISSUES.md #3).  debug prints/callbacks count: they are host
# round-trips with the same composition hazards.
HOST_CALLBACK_PRIMS = frozenset({
    "pure_callback",
    "io_callback",
    "debug_callback",
    "debug_print",
    "callback",
    "infeed",
    "outfeed",
    "host_local_array_to_global_array",
    "global_array_to_host_local_array",
})

# Confirmed-slow XLA:CPU lowerings (KNOWN_ISSUES.md #0b: scatter-add runs as
# a serialized per-index loop on CPU; sort and the cum* family lower to
# O(n log n)/sequential loops).  The AST `slow-cpu-lowering` rule guesses at
# these from `.at[].add`/`jnp.cumsum` spellings behind an allowlist; here
# the primitive either IS in the trace or is not.
SLOW_PRIMS = frozenset({
    "scatter",
    "scatter-add",
    "scatter-mul",
    "scatter-min",
    "scatter-max",
    "cumsum",
    "cumprod",
    "cummax",
    "cummin",
    "cumlogsumexp",
    "sort",
})

# 64-bit dtypes: the repo runs everything in 32-bit (jax_enable_x64 off);
# a 64-bit aval in a trace means a numpy float64/int64 leaked in as a
# constant or an x64 flag flipped somewhere — either way the program
# silently doubles its memory traffic on TPU or fails to lower.
_WIDE_DTYPES = frozenset({"float64", "int64", "uint64", "complex128"})


def _inner_jaxprs(value):
    """Yield jaxpr objects hiding in one eqn param value (Jaxpr,
    ClosedJaxpr, or tuples/lists of them — lax.cond branches)."""
    vals = value if isinstance(value, (tuple, list)) else (value,)
    for v in vals:
        if hasattr(v, "eqns"):  # Jaxpr
            yield v
        elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):  # ClosedJaxpr
            yield v.jaxpr


def iter_eqns(jaxpr):
    """Every eqn in ``jaxpr`` and all nested sub-jaxprs (scan/cond/while
    bodies, pjit calls), depth-first."""
    stack = [jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr]
    while stack:
        j = stack.pop()
        for eqn in j.eqns:
            yield eqn
            for v in eqn.params.values():
                stack.extend(_inner_jaxprs(v))


def primitive_counts(closed) -> Counter:
    """{primitive name: occurrence count} over the whole (nested) jaxpr."""
    counts: Counter = Counter()
    for eqn in iter_eqns(closed):
        counts[eqn.primitive.name] += 1
    return counts


def _aval_of(var):
    """aval of a Var or Literal (both carry .aval), else None."""
    return getattr(var, "aval", None)


def iter_avals(closed):
    """Every aval mentioned by the (nested) jaxpr: eqn in/outvars plus the
    top-level consts.  Yields avals (possibly repeated)."""
    top = closed.jaxpr if hasattr(closed, "jaxpr") else closed
    for v in list(top.invars) + list(top.outvars) + list(top.constvars):
        a = _aval_of(v)
        if a is not None:
            yield a
    for eqn in iter_eqns(closed):
        for v in list(eqn.invars) + list(eqn.outvars):
            a = _aval_of(v)
            if a is not None:
                yield a


def wide_dtypes(closed) -> Counter:
    """{64-bit dtype name: aval count} found anywhere in the trace."""
    counts: Counter = Counter()
    for a in iter_avals(closed):
        name = str(getattr(a, "dtype", ""))
        if name in _WIDE_DTYPES:
            counts[name] += 1
    return counts


def boundary_weak_types(closed) -> list[str]:
    """Descriptions of weak-typed program inputs/outputs.  A weak-typed
    boundary aval re-specializes on the caller's literal dtype context —
    the same registry key can then produce distinct executables (a silent
    recompile leak at engine boundaries)."""
    top = closed.jaxpr if hasattr(closed, "jaxpr") else closed
    out = []
    for kind, vs in (("in", top.invars), ("out", top.outvars)):
        for i, v in enumerate(vs):
            a = _aval_of(v)
            if a is not None and getattr(a, "weak_type", False):
                out.append(f"{kind}[{i}]:{getattr(a, 'dtype', '?')}")
    return out


def const_leaves(closed) -> list[tuple[str, str, int]]:
    """(shape, dtype, nbytes) of every top-level constant baked into the
    closed jaxpr."""
    out = []
    for c in getattr(closed, "consts", ()):
        nbytes = getattr(c, "nbytes", None)
        if nbytes is None:
            size = getattr(c, "size", 1)
            itemsize = getattr(getattr(c, "dtype", None), "itemsize", 8)
            nbytes = int(size) * int(itemsize)
        out.append((
            str(getattr(c, "shape", ())),
            str(getattr(c, "dtype", type(c).__name__)),
            int(nbytes),
        ))
    return out


def fingerprint(closed) -> str:
    """Stable identity of a traced program: sha256 of the pretty-printed
    jaxpr.  Two traces that print identically lower identically (trace-time
    var names are assigned deterministically), so sweeps whose points share
    a fingerprint share one executable — the registry-key-divergence rule's
    ground truth."""
    return hashlib.sha256(str(closed).encode()).hexdigest()[:24]


def cost_summary(lowered) -> dict | None:
    """{"flops", "bytes"} from a Lowered's analytical cost model, or None
    when the backend provides none.  Delegates to
    ``utils/aotcache.cost_of`` — the budget gate and the AOT compile path
    must read the same normalized record."""
    from blockchain_simulator_tpu.utils import aotcache

    return aotcache.cost_of(lowered)


def memory_summary(lowered) -> dict | None:
    """{"temp_bytes", "argument_bytes"} from the COMPILED executable's
    ``memory_analysis()`` — peak XLA temp allocation and total argument
    bytes per device — or None when the backend provides none.  This is
    the one audit step that pays a real compile (still nothing executes);
    the budget gate pins it next to flops/bytes so the RSS stories
    (7.4 GB @1M, 12.4 GB @4M nodes — ROADMAP item 3) regress loudly."""
    try:
        stats = lowered.compile().memory_analysis()
        return {
            "temp_bytes": float(stats.temp_size_in_bytes),
            "argument_bytes": float(stats.argument_size_in_bytes),
        }
    except Exception:
        return None


def trace_program(fn, example_args: tuple):
    """Trace ``fn`` (jitted or plain) on aval-level ``example_args``;
    returns ``(closed_jaxpr, lowered)``.  Nothing executes: plain callables
    are wrapped in a fresh ``jax.jit`` first, and args may be
    ``ShapeDtypeStruct`` pytrees (``jax.eval_shape`` products)."""
    import jax

    # per-call jit is the point here: an audit traces each program exactly
    # once and executes nothing, so there is no recompile to hazard
    jitted = fn if hasattr(fn, "trace") else jax.jit(fn)  # jaxlint: disable=static-arg-recompile-hazard
    traced = jitted.trace(*example_args)
    return traced.jaxpr, traced.lower()
