"""Mixed-protocol shard simulation (BASELINE config 5).

``S`` Raft shards of ``m`` nodes each (``n = S·m``) run leader election +
heartbeat replication *internally*, while a cross-shard PBFT instance over the
``S`` shard representatives finalizes global blocks.  This is the hierarchical
composition named in BASELINE.json ("256 Raft shards × 1k nodes with
cross-shard PBFT finality") — a capability with no reference counterpart (the
reference runs exactly one protocol per compiled binary, SURVEY.md §1).

Composition is pure function reuse, the payoff of the protocol-backend API
(models/base.py): the Raft backend's ``step`` is ``jax.vmap``-ed over the
shard axis (every leaf ``[m, ...]`` → ``[S, m, ...]``, per-shard PRNG streams
via ``fold_in(shard)``), and the PBFT backend runs unchanged over ``S``
virtual nodes whose ``alive`` mask is recomputed *every tick* as "shard has an
elected leader" — a shard only participates in cross-shard consensus while
its Raft layer is healthy.  Faults (crash/Byzantine/drop) apply within each
shard; a shard whose leader crashes drops out of the PBFT quorum until
re-election (clean fidelity re-arms election timers, so representation
recovers).

Scale-out: the shard axis is embarrassingly parallel; ``parallel.shard``
row-shards the raft leaves over the mesh's ``nodes`` axis (the S-node PBFT
layer is replicated per device — it is O(S) tiny), which is how BASELINE
config 5's 256 shards x 1k nodes = 256k simulated nodes run on one mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from blockchain_simulator_tpu.models import pbft, raft
from blockchain_simulator_tpu.utils import prng
from blockchain_simulator_tpu.utils.config import FaultConfig


@struct.dataclass
class MixedState:
    raft: raft.RaftState  # leaves [S, m, ...]
    pbft: pbft.PbftState  # leaves [S, ...]


@struct.dataclass
class MixedBufs:
    raft: raft.RaftBufs  # leaves [S, D_raft, m, ...]
    pbft: pbft.PbftBufs  # leaves [D_pbft, S, ...]


def sub_configs(cfg):
    """(raft_cfg for one m-node shard, pbft_cfg over S representatives).

    The RAFT sub-config resolves ``stat_sampler="auto"`` at the PARENT scale
    (cfg.n = S·m), not the shard size: under the shard vmap, ``gated()``
    branches lower to select — every shard pays the sampler on every tick —
    and the auto heuristic's n >= 4096 cutoff is about total per-tick
    sampler work.  At config-5 scale (256k rows) this swaps the ~40-pass
    BTRS exact binomial for the ~6-pass normal approximation in all 256
    shards (the approximation error is O(1/sqrt(count)) per bucket —
    negligible at 1k-node shards), a severalfold cut in the per-tick cost
    that dominated the r4 artifact's 2348 s run (ARTIFACT_config5.json;
    VERDICT r4 weak-#3).  The S-representative PBFT layer keeps its own
    "auto" resolution: it steps ONCE, un-vmapped, so the override would
    trade accuracy (S is small — per-bucket counts ~S/3) for nothing."""
    s = cfg.mixed_shards
    m = cfg.n // s
    rcfg = cfg.with_(
        protocol="raft", n=m, mesh_axis=None, stat_sampler=cfg.eff_stat_sampler
    )
    # faults live at the raft level; representatives fail by losing their
    # leader, not by an independent fault mask
    pcfg = cfg.with_(
        protocol="pbft", n=s, mesh_axis=None, faults=FaultConfig()
    )
    return rcfg, pcfg


def init(cfg, key=None):
    s = cfg.mixed_shards
    if cfg.n % s != 0:
        raise ValueError(f"n={cfg.n} not divisible into {s} shards")
    if cfg.n // s < 3:
        raise ValueError("shard size must be >= 3 for a meaningful raft quorum")
    rcfg, pcfg = sub_configs(cfg)
    k = jax.random.key(cfg.seed) if key is None else key
    shard_keys = jax.vmap(lambda i: jax.random.fold_in(k, i))(jnp.arange(s))
    r_state, r_bufs = jax.vmap(lambda kk: raft.init(rcfg, kk))(shard_keys)
    p_state, p_bufs = pbft.init(pcfg, jax.random.fold_in(k, 0x5AFE))
    # no representative is alive until its shard elects a leader
    p_state = p_state.replace(alive=jnp.zeros((s,), bool))
    return MixedState(raft=r_state, pbft=p_state), MixedBufs(raft=r_bufs, pbft=p_bufs)


def step(cfg, state: MixedState, bufs: MixedBufs, t, tkey):
    """One tick.  Sharded (cfg.mesh_axis set): raft shards are row-sharded
    over the mesh axis (embarrassingly parallel — per-shard PRNG streams key
    on the GLOBAL shard id), while the S-representative PBFT instance is
    replicated on every device: its inputs (the [S] has-leader mask) are
    all-gathered, so each device steps an identical copy with identical keys
    and the replicated state never diverges."""
    axis = cfg.mesh_axis
    rcfg, pcfg = sub_configs(cfg)
    s_loc = state.raft.block_num.shape[0]  # local shard rows
    base = 0 if axis is None else jax.lax.axis_index(axis) * s_loc
    shard_keys = jax.vmap(lambda i: jax.random.fold_in(tkey, 0x0C0C + base + i))(
        jnp.arange(s_loc)
    )
    r_state, r_bufs = jax.vmap(
        functools.partial(raft.step, rcfg, t=t)
    )(state.raft, bufs.raft, tkey=shard_keys)
    # cross-shard membership: a representative is alive iff its shard
    # currently has an elected, alive leader
    has_leader = (r_state.is_leader & r_state.alive).any(axis=1)
    if axis is not None:
        has_leader = jax.lax.all_gather(has_leader, axis, tiled=True)
    p_state = state.pbft.replace(alive=has_leader)
    p_state, p_bufs = pbft.step(
        pcfg, p_state, bufs.pbft, t, jax.random.fold_in(tkey, 0x9B9B)
    )
    return MixedState(raft=r_state, pbft=p_state), MixedBufs(raft=r_bufs, pbft=p_bufs)


def fast_eligible(cfg) -> bool:
    """Can the raft shards ride the heartbeat-blocked steady scan
    (models/raft_hb.py)?  The shard sub-config must satisfy the same
    eligibility as a standalone round-schedule raft — the shards ARE
    standalone raft instances under the vmap."""
    if cfg.protocol != "mixed":
        return False
    if cfg.n % cfg.mixed_shards != 0 or cfg.n // cfg.mixed_shards < 3:
        return False  # init rejects these with a better message
    from blockchain_simulator_tpu.models import raft_hb

    rcfg, _ = sub_configs(cfg)
    return raft_hb.eligible(rcfg)


def prefix_handoff(cfg, state, bufs, key):
    """Per-tick mixed prefix through the raft election phase, then the
    checked handoff (models/raft_hb.handoff) in EVERY shard.  Returns
    ``(carry, ok_all, h_s)`` — shared by ``scan_fast`` (which conds on
    ``ok_all`` inside the trace) and utils/trace.run_traced (which branches
    on the host to record the phase that actually ran)."""
    from blockchain_simulator_tpu.models import raft_hb

    axis = cfg.mesh_axis
    rcfg, _ = sub_configs(cfg)
    t_e = raft_hb.prefix_ticks(rcfg)

    def tick_body(carry, t):
        st, bf = carry
        st, bf = step(cfg, st, bf, t, prng.tick_key(key, t))
        return (st, bf), ()

    carry, _ = jax.lax.scan(tick_body, (state, bufs), jnp.arange(t_e))
    ok_s, h_s = jax.vmap(lambda st: raft_hb.handoff(rcfg, st))(carry[0].raft)
    bad = (~ok_s).sum()
    if axis is not None:
        bad = jax.lax.psum(bad, axis)
    return carry, bad == 0, h_s


def fast_finish(cfg, carry, h_s, key, with_probe: bool = False):
    """The heartbeat-scheduled steady phase from a quiet handoff: vmapped
    O(1)-per-heartbeat raft scans + the per-tick S-representative PBFT layer
    with its ``alive`` mask pinned all-true.  Returns the final MixedState;
    with ``with_probe`` (utils/trace.run_traced) also per-shard heartbeat
    series and per-tick global-layer series:
    ``(state, (raft_ys [S?, K] leaves, pbft_ys [ticks - t_e] leaves))``."""
    from blockchain_simulator_tpu.models import raft_hb

    axis = cfg.mesh_axis
    rcfg, pcfg = sub_configs(cfg)
    t_e = raft_hb.prefix_ticks(rcfg)
    s = cfg.mixed_shards
    st, bf = carry
    s_loc = st.raft.block_num.shape[0]
    base = 0 if axis is None else jax.lax.axis_index(axis) * s_loc
    # per-shard steady-scan streams key on the GLOBAL shard id, so the
    # sharded run is bit-identical to the single-device run (the same
    # convention as step's per-tick shard keys)
    hb_keys = jax.vmap(
        lambda i: jax.random.fold_in(key, 0x4BB7 + base + i)
    )(jnp.arange(s_loc))
    if with_probe:
        res, raft_ys = jax.vmap(
            lambda k, hh: raft_hb.steady_scan(rcfg, k, hh, with_probe=True)
        )(hb_keys, h_s)
    else:
        res = jax.vmap(
            lambda k, hh: raft_hb.steady_scan(rcfg, k, hh)
        )(hb_keys, h_s)
        raft_ys = None
    raft_final = jax.vmap(
        lambda rst, hh, r: raft_hb.materialize(rcfg, rst, hh, r)
    )(st.raft, h_s, res)
    ones = jnp.ones((s,), bool)

    def p_body(pcarry, t):
        ps, pb = pcarry
        ps = ps.replace(alive=ones)
        ps, pb = pbft.step(
            pcfg, ps, pb, t,
            jax.random.fold_in(prng.tick_key(key, t), 0x9B9B),
        )
        ys = (
            {"global_blocks": ps.block_num.max(),
             "global_commit_events": ps.slot_commits.sum()}
            if with_probe
            else ()
        )
        return (ps, pb), ys

    (p_state, _), pbft_ys = jax.lax.scan(
        p_body, (st.pbft, bf.pbft),
        t_e + jnp.arange(max(cfg.ticks - t_e, 0)),
    )
    final = MixedState(raft=raft_final, pbft=p_state)
    return (final, (raft_ys, pbft_ys)) if with_probe else final


def scan_fast(cfg, state: MixedState, bufs: MixedBufs, key):
    """Heartbeat-scheduled mixed simulation (BASELINE config 5's wall-clock
    lever): run the full per-tick mixed engine for the raft election prefix,
    evaluate the checked handoff (models/raft_hb.handoff) in EVERY shard,
    then ``lax.cond`` on all-shards-quiet:

    - fast branch (``fast_finish``): the S raft shards collapse to vmapped
      O(1)-per-heartbeat steady scans (256 shards x 1k nodes stop paying
      256k rows of per-tick sampler work), while the S-representative PBFT
      layer — the only part with genuine per-tick cross-shard dynamics —
      keeps stepping every tick with its ``alive`` mask pinned all-true
      (every shard has a live, undeposable leader post-handoff, which is
      exactly what the per-tick engine would recompute).  PBFT keys/
      evolution are bit-identical to the per-tick engine; raft milestones
      follow the raft_hb count contract.
    - slow branch: any shard failed the handoff (split election, crashed
      majority) — CONTINUE the per-tick mixed scan from the prefix carry,
      bit-identical to an uninterrupted tick run.

    Works unsharded, under vmap, and inside shard_map (cfg.mesh_axis row-
    shards the shard axis; the handoff verdict is psum-agreed)."""
    from blockchain_simulator_tpu.models import raft_hb

    rcfg, _ = sub_configs(cfg)
    t_e = raft_hb.prefix_ticks(rcfg)

    def tick_body(carry, t):
        st, bf = carry
        st, bf = step(cfg, st, bf, t, prng.tick_key(key, t))
        return (st, bf), ()

    carry, ok_all, h_s = prefix_handoff(cfg, state, bufs, key)

    def fast_branch(carry):
        return fast_finish(cfg, carry, h_s, key)

    def tick_branch(carry):
        (st, _), _ = jax.lax.scan(
            tick_body, carry, t_e + jnp.arange(max(cfg.ticks - t_e, 0))
        )
        return st

    return jax.lax.cond(ok_all, fast_branch, tick_branch, carry)


def metrics(cfg, state: MixedState) -> dict:
    s = cfg.mixed_shards
    rcfg, pcfg = sub_configs(cfg)
    is_leader = np.asarray(state.raft.is_leader) & np.asarray(state.raft.alive)
    has_leader = is_leader.any(axis=1)
    block_num = np.asarray(state.raft.block_num)
    leader_tick = np.asarray(state.raft.leader_tick)
    # per-shard raft blocks: the earliest-elected current leader's count
    # (raft.metrics' convention — a deposed ex-leader keeps a stale count)
    lt = np.where(is_leader, leader_tick, np.iinfo(np.int32).max)
    lead_idx = lt.argmin(axis=1)
    shard_blocks = np.where(
        has_leader, block_num[np.arange(s), lead_idx], 0
    )
    pm = pbft.metrics(pcfg, state.pbft)
    return {
        "protocol": "mixed",
        "n": cfg.n,
        "shards": s,
        "shard_size": cfg.n // s,
        "shards_with_leader": int(has_leader.sum()),
        "raft_blocks_total": int(shard_blocks.sum()),
        "raft_blocks_min": int(shard_blocks[has_leader].min()) if has_leader.any() else 0,
        "global_blocks_final": pm["blocks_final_all_nodes"],
        "global_rounds_sent": pm["rounds_sent"],
        "global_mean_ttf_ms": pm["mean_time_to_finality_ms"],
        "agreement_ok": pm["agreement_ok"],
    }
