"""Raft(-like) consensus — tensorized state machine.

Re-design of the reference's ``RaftNode`` (raft/raft-node.h:19, raft-node.cc):
randomized-timeout leader election (150-300 ms, raft-node.cc:69-72,114),
50 ms heartbeats (raft-node.cc:80,405-429), proposal-carrying heartbeats as log
replication (SendTX, raft-node.cc:340-365), majority acks advance ``blockNum``
(raft-node.cc:234-251), stop at 50 blocks / 50 proposal rounds.  As SURVEY.md
§2 notes, the reference has no terms, no log array, no commit index — it is
Raft-flavored leader election + heartbeat replication, and this backend
reproduces exactly that protocol.

Reference call stack being tensorized (SURVEY.md §3.3):

- election timer U[150,300) ms → ``sendVote`` (raft-node.cc:114,392-401):
  self-vote latch ``has_voted=1``, VOTE_REQ broadcast, timer re-armed.
- VOTE_REQ at a peer: grant iff ``has_voted==0`` (consuming the vote), unicast
  VOTE_RES SUCCESS/FAILED back (raft-node.cc:154-167).
- VOTE_RES at a candidate (raft-node.cc:196-232): per-arrival majority check
  ``vote_success + 1 > N/2`` → become leader (cancel own timer, schedule
  ``setProposal`` +1 s, send first heartbeat immediately); minority check
  ``vote_failed >= N/2`` → reset counters and ``has_voted=0`` (retry on the
  re-armed timer).
- leader every 50 ms: plain HEARTBEAT, or 20 KB proposal block once
  ``add_change_value`` is set (raft-node.cc:405-433); ``round==50`` clears
  ``add_change_value`` (raft-node.cc:361-365); ``blockNum>=50`` cancels the
  heartbeat (raft-node.cc:248-251).
- follower: heartbeat cancels the election timer; proposal also stores
  ``m_value``; always replies HEARTBEAT_RES SUCCESS (raft-node.cc:170-193).
- leader counts proposal acks; exactly when ``vote_success+vote_failed==N-1``
  it checks ``vote_success+1 > N/2`` → ``blockNum++`` (raft-node.cc:234-247).

Tensorization: one tick = 1 ms for all N nodes.  Timers become per-node
deadline registers compared against the tick counter (SURVEY.md §7).  Vote
requests need receiver state at arrival (the ``has_voted`` latch), so they ride
an identity-preserving matrix channel in ``edge`` mode, or a max-combined
candidate-id channel in ``stat`` mode (ties between candidates arriving at the
same receiver in the same tick resolve to one candidate — a documented
large-N simplification).  Heartbeat acks never depend on follower state, so
they are short-circuited round trips.  Echo-back (quirk #1) is not modeled.

Fidelity modes:
- ``reference``: a plain heartbeat cancels the election timer *permanently*
  (the re-arm is commented out, raft-node.cc:177-178 — quirk #5), and a block
  commits only when exactly all N-1 acks arrive (stalls under drops, as the
  reference would).
- ``clean``: heartbeats re-arm the election timer (real failure detection) and
  a block commits as soon as acks reach the majority, latched once per round.

Gossip topology (``topology="gossip"``, clean + stat only): the three
broadcast channels — VOTE_REQ, plain HEARTBEAT, proposal HEARTBEAT — flood
over a random k-out digraph with a hop TTL (time-monotone value encodings,
per-channel ``seen`` dedup registers, same overlay as models/paxos.py);
votes and proposal acks stay direct unicast to the decoded originator, with
acks generated at flood arrival (the full-mesh short-circuited round trip
has no meaning over multi-hop paths).  Clean-mode majority counting is
arrival-time based, so multi-hop ack latency only shifts commit times.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from blockchain_simulator_tpu.models.base import fault_masks, gated
from blockchain_simulator_tpu.ops import delay as delay_ops
from blockchain_simulator_tpu.ops import delivery as dv
from blockchain_simulator_tpu.ops import gatherdeliv as gd
from blockchain_simulator_tpu.ops import topology
from blockchain_simulator_tpu.ops.ring import ring_pop, ring_push_add, ring_push_max
from blockchain_simulator_tpu.utils.prng import Channel, chan_key

# Timer sentinel: "canceled" (Simulator::Cancel).  Any tick comparison against
# it is false for the whole simulation horizon.  np, not jnp: a jnp scalar
# here would create a device array AT IMPORT TIME — a backend init that can
# stall ~25 min on a wedged tunnel (jaxlint module-scope-backend-touch,
# KNOWN_ISSUES #3/#4); the np.int32 promotes identically inside traces.
DISARM = np.int32(1 << 30)


@struct.dataclass
class RaftState:
    is_leader: jax.Array      # [N] bool
    has_voted: jax.Array      # [N] bool — single vote latch (no terms, quirk #6)
    election_deadline: jax.Array  # [N] tick of next sendVote; DISARM = canceled
    vote_success: jax.Array   # [N] election SUCCESS replies received
    vote_failed: jax.Array    # [N] election FAILED replies received
    next_hb: jax.Array        # [N] next heartbeat tick (leader); DISARM = off
    proposal_tick: jax.Array  # [N] when setProposal fires; DISARM = unscheduled
    add_change_value: jax.Array  # [N] bool — heartbeats carry proposals
    m_value: jax.Array        # [N] last proposal value stored (-1 = unset)
    block_num: jax.Array      # [N] blocks committed (leader counts)
    round: jax.Array          # [N] proposal rounds broadcast (leader)
    hb_succ: jax.Array        # [N] proposal-ack SUCCESS count, current round
    hb_cnt: jax.Array         # [N] proposal-ack total count, current round
    hb_open: jax.Array        # [N] bool — current round not yet committed
    leader_tick: jax.Array    # [N] tick this node became leader (-1 = never)
    elections: jax.Array      # [N] sendVote firings (metrics)
    block_tick: jax.Array     # [N, B] commit tick per block at the leader (-1)
    alive: jax.Array          # [N] bool fault mask
    honest: jax.Array         # [N] bool fault mask
    # gossip (topology="gossip") dedup registers: highest TTL-encoded copy
    # seen per flooded channel (vote requests / plain heartbeats / proposals);
    # zeros and unused on the full mesh
    seen_vreq: jax.Array      # [N]
    seen_hb: jax.Array        # [N]
    seen_prop: jax.Array      # [N]
    # gossip elections: multi-hop flood latency (~hops*delay) spans many
    # nodes' election deadlines, so votes fragment across a storm of
    # concurrent candidates; with the reference's permanent single-vote
    # latch (quirk #6) nobody ever reaches a majority and elections deadlock
    # at n >~ 100.  Clean-fidelity gossip therefore votes for the NEWEST
    # election seen — the ``seen_vreq`` dedup register IS the term
    # comparison (bases are time-monotone and a node only processes strictly
    # newer ones), so every processed request is granted; a candidate
    # restarts its count at each fire (reply horizons << election timeouts,
    # so stale replies drain first — the models/paxos.py temporal-separation
    # argument).  Stale in-flight grants can still hand majorities to
    # SEVERAL storm candidates, so leaders also step down on observing a
    # newer election than their own (real Raft's step-down-on-higher-term)
    # — ``my_base`` remembers the election a leader won.
    my_base: jax.Array        # [N] last election base this node fired with
    # queued-link transport (cfg.queued_links; zeros when off): per-
    # destination busy-until register for the CURRENT leader's serial links
    # (same design as models/pbft.py — blocks only flow leader -> follower,
    # so the busy state is [N] by destination, reset on leadership change; a
    # 20 KB proposal serializes ~54 ms against the 50 ms heartbeat, so the
    # backlog grows ~4 ms/round, bounded by (ser - hb) * raft_max_rounds —
    # small enough that queued deliveries stay ON the rings, whose depth
    # config.ring_depth widens accordingly; engine.cpp:198-215 is the twin).
    link_busy: jax.Array      # [N]


@struct.dataclass
class RaftBufs:
    # vote requests: edge mode keeps sender identity [D, N_recv, N_glob];
    # stat mode max-combines candidate id + 1 into [D, N_recv].
    vreq: jax.Array
    vres_ok: jax.Array   # [D, N] granted-vote arrivals at the candidate (add)
    vres_no: jax.Array   # [D, N] denial arrivals at the candidate (add)
    hb_plain: jax.Array  # [D, N] plain-heartbeat arrival counts (add)
    hb_prop: jax.Array   # [D, N] proposal value + 1, max-combined (0 = empty)
    hb_ok: jax.Array     # [D, N] proposal-ack SUCCESS arrivals at leader (add)
    hb_bad: jax.Array    # [D, N] proposal-ack FAILED arrivals (Byzantine
    # repliers flip to FAILED; disjoint peer set from hb_ok, so the two
    # channels' independent delay draws cover disjoint edges)


def init(cfg, key=None):
    n, d = cfg.n, cfg.ring_depth
    b = cfg.raft_max_blocks
    alive, honest = fault_masks(cfg, n)
    zi = lambda *sh: jnp.zeros(sh, jnp.int32)
    zb = lambda *sh: jnp.zeros(sh, bool)
    # initial election timeouts U[150,300) ms (raft-node.cc:69-72,114), drawn
    # from the *init* key so the schedule is part of the state, not the tick
    # stream
    k = jax.random.key(cfg.seed) if key is None else key
    deadline = jax.random.randint(
        jax.random.fold_in(k, Channel.ELECTION),
        (n,),
        cfg.raft_election_lo_ms,
        cfg.raft_election_hi_ms,
        dtype=jnp.int32,
    )
    # crashed nodes never start an election
    deadline = jnp.where(alive, deadline, DISARM)
    state = RaftState(
        is_leader=zb(n),
        has_voted=zb(n),
        election_deadline=deadline,
        vote_success=zi(n),
        vote_failed=zi(n),
        next_hb=jnp.full((n,), DISARM),
        proposal_tick=jnp.full((n,), DISARM),
        add_change_value=zb(n),
        m_value=jnp.full((n,), -1, jnp.int32),
        block_num=zi(n),
        round=zi(n),
        hb_succ=zi(n),
        hb_cnt=zi(n),
        hb_open=zb(n),
        leader_tick=jnp.full((n,), -1, jnp.int32),
        elections=zi(n),
        block_tick=jnp.full((n, b), -1, jnp.int32),
        alive=alive,
        honest=honest,
        seen_vreq=zi(n),
        seen_hb=zi(n),
        seen_prop=zi(n),
        my_base=zi(n),
        link_busy=zi(n),
    )
    if cfg.delivery == "stat":
        vreq = zi(d, n)
    elif cfg.topology == "kregular":
        # edge-mode overlay: sender identity is the IN-slot, not a global
        # column — [D, N, K] instead of [D, N, N] (the O(N*k) memory win)
        vreq = zi(d, n, cfg.degree + 1)
    else:
        vreq = zi(d, n, n)
    bufs = RaftBufs(
        vreq=vreq,
        vres_ok=zi(d, n),
        vres_no=zi(d, n),
        hb_plain=zi(d, n),
        hb_prop=zi(d, n),
        hb_ok=zi(d, n),
        hb_bad=zi(d, n),
    )
    return state, bufs




def step(cfg, state: RaftState, bufs: RaftBufs, t, tkey, *, topo_tables=None,
         exchange=None):
    n = cfg.n
    axis = cfg.mesh_axis
    lo, hi = cfg.one_way_range()
    rt_lo, rt_hi = cfg.roundtrip_range()
    drop = cfg.faults.drop_prob
    clean = cfg.fidelity == "clean"
    stat = cfg.delivery == "stat"
    smode = cfg.eff_stat_sampler
    eimpl = cfg.eff_edge_sampler
    ow_probs = delay_ops.uniform_probs(lo, hi)
    rt_probs = delay_ops.roundtrip_probs(lo, hi)
    n_loc = state.is_leader.shape[0]
    ids = dv._global_ids(n_loc, axis)
    zeros_flat = jnp.zeros((hi - lo, n_loc), jnp.int32)
    zeros_rt = jnp.zeros((len(rt_probs), n_loc), jnp.int32)
    ser = cfg.serialization_ticks(cfg.raft_block_bytes)
    # queued-link transport (see RaftState.link_busy): with ser == 0 the pipe
    # is never busy and queued == constant-latency, so the plain path runs
    queued = cfg.queued_links and ser > 0

    # ---- pop arrivals; crashed nodes process nothing ------------------------
    vreq_t, vreq = ring_pop(bufs.vreq, t)
    ok_t, vres_ok = ring_pop(bufs.vres_ok, t)
    no_t, vres_no = ring_pop(bufs.vres_no, t)
    plain_t, hb_plain = ring_pop(bufs.hb_plain, t)
    prop_t, hb_prop = ring_pop(bufs.hb_prop, t)
    hbok_t, hb_ok = ring_pop(bufs.hb_ok, t)
    hbbad_t, hb_bad = ring_pop(bufs.hb_bad, t)
    am = state.alive.astype(jnp.int32)
    ok_t, no_t = ok_t * am, no_t * am
    plain_t, prop_t = plain_t * am, prop_t * am
    hbok_t, hbbad_t = hbok_t * am, hbbad_t * am
    hbtot_t = hbok_t + hbbad_t
    if stat:
        vreq_t = vreq_t * am
    else:
        vreq_t = vreq_t * am[:, None]

    # ---- gossip decode (topology="gossip"): the three broadcast channels
    # (VOTE_REQ, plain HEARTBEAT, proposal HEARTBEAT) flood over the k-out
    # digraph with a hop TTL; replies (votes, proposal acks) stay direct
    # unicast to the decoded originator — the same overlay as models/paxos.py.
    # Flood values are time-monotone encodings (dedup by per-channel ``seen``
    # register): vreq (t+1)*n + cand + 1; plain hb t+1; proposal
    # (t+1)*(n+1) + leader + 1 (the +1 keeps 0 = empty).  A node processes
    # each base value once (first sighting) but forwards any strictly better
    # TTL copy, so a nearly-expired first arrival cannot truncate the flood.
    gossip = cfg.topology == "gossip"
    # kregular gather overlay (topo/spec.py + ops/gatherdeliv.py): every
    # channel delivers DIRECT over the circulant in/out tables — broadcasts
    # reach out-neighbors, replies gather back requester-side through the
    # inslot cross-index (scatter-free) — O(N*K) per tick, bit-equal to the
    # dense arms at degree k = N-1.  A candidate only ever hears its
    # in-neighbors' votes, so elections need k >= majority_need - 1 to be
    # winnable (stalling below that is a valid modeled outcome).
    kreg = cfg.topology == "kregular"
    nbr_in_loc = nbr_out_loc = inslot_loc = None
    if kreg:
        # exchange mode: operands are already this trace's rows (ids=None
        # pass-through — re-taking a sharded operand would regather it)
        nbr_in_loc, nbr_out_loc, inslot_loc = gd.local_tables(
            cfg, None if exchange is not None else ids, inslot=True,
            tables=topo_tables)
    seen_vreq, seen_hb, seen_prop = state.seen_vreq, state.seen_hb, state.seen_prop
    vreq_fwd = hb_fwd = prop_fwd = None
    nbrs_loc = None
    if gossip:
        h_enc = cfg.gossip_hops + 1
        nbrs_loc = jnp.take(
            jnp.asarray(topology.kregular_out_neighbors(n, cfg.degree, cfg.seed)),
            ids, axis=0,
        )

        def _decode(arr, seen):
            base, hops = arr // h_enc, arr % h_enc
            new = (base > seen // h_enc) & state.alive
            better = (arr > seen) & state.alive
            seen = jnp.maximum(seen, arr * better)
            fwd = (base * h_enc + jnp.maximum(hops - 1, 0)) * (better & (hops > 0))
            return base * new, seen, fwd

        vreq_t, seen_vreq, vreq_fwd = _decode(vreq_t, seen_vreq)
        plain_t, seen_hb, hb_fwd = _decode(plain_t, seen_hb)
        prop_t, seen_prop, prop_fwd = _decode(prop_t, seen_prop)

    # ---- heartbeat arrivals (follower side, raft-node.cc:170-193) -----------
    got_hb = (plain_t > 0) | (prop_t > 0)
    if gossip:
        # proposal value = the leader id riding the flood encoding
        m_value = jnp.where(prop_t > 0, (prop_t - 1) % (n + 1), state.m_value)
    else:
        m_value = jnp.where(prop_t > 0, prop_t - 1, state.m_value)
    if clean:
        # re-arm the election timer: real failure detection
        k_e = chan_key(tkey, Channel.ELECTION)
        if axis is not None:
            k_e = jax.random.fold_in(k_e, jax.lax.axis_index(axis))
        rearm = t + jax.random.randint(
            k_e, (n_loc,), cfg.raft_election_lo_ms, cfg.raft_election_hi_ms,
            dtype=jnp.int32,
        )
        election_deadline = jnp.where(got_hb, rearm, state.election_deadline)
    else:
        # quirk #5: Simulator::Cancel with the re-arm commented out
        # (raft-node.cc:177-178) — one heartbeat pacifies a follower forever
        election_deadline = jnp.where(got_hb, DISARM, state.election_deadline)

    # ---- gossip proposal acks: a follower acks the proposal when the flood
    # lands (direct unicast to the decoded leader); replaces the full-mesh
    # short-circuited round trip, which has no meaning over multi-hop paths
    if gossip:
        got_prop = prop_t > 0
        ack_to = jnp.where(got_prop, (prop_t - 1) % (n + 1), n)  # n = drop
        k_ack = chan_key(tkey, Channel.DELAY_REPLY2)

        def _ack_counts(wire):
            c = jnp.zeros((n,), jnp.int32).at[ack_to].add(
                wire.astype(jnp.int32), mode="drop"
            )
            if axis is not None:
                c = jax.lax.psum(c, axis)
                start = jax.lax.axis_index(axis) * n_loc
                c = jax.lax.dynamic_slice_in_dim(c, start, n_loc)
            return c

        def _push_acks():
            # fused chain-into-ring (ops/delivery.push_bucket_counts):
            # bit-equal to the former stacked sample → ring_push_add pair
            # (same keys, same chain, same adds), minus the [2, B, N]
            # intermediate; the gated fallback leaves the rings untouched,
            # which is what pushing all-zero contributions produced
            mok = _ack_counts(got_prop & state.honest & state.alive)
            mbad = _ack_counts(got_prop & ~state.honest & state.alive)
            if drop > 0.0:
                kd = jax.random.fold_in(k_ack, 0x0D18)
                mok = jnp.round(delay_ops.binom(
                    kd, mok, 1.0 - drop, smode)).astype(jnp.int32)
                mbad = jnp.round(delay_ops.binom(
                    jax.random.fold_in(kd, 1), mbad, 1.0 - drop,
                    smode)).astype(jnp.int32)
            return (
                dv.push_bucket_counts(
                    hb_ok, t, lo, jax.random.fold_in(k_ack, 1), mok,
                    ow_probs, smode),
                dv.push_bucket_counts(
                    hb_bad, t, lo, jax.random.fold_in(k_ack, 2), mbad,
                    ow_probs, smode),
            )

        hb_ok, hb_bad = gated(
            got_prop.any(), _push_acks, (hb_ok, hb_bad), axis,
        )

    # ---- vote requests (acceptor side, raft-node.cc:154-167) ---------------
    can_grant = ~state.has_voted & state.alive
    my_base = state.my_base
    if stat:
        # full mesh: vreq_t[i] = max candidate id + 1 seen this tick (the
        # stat broadcast reaches the sender too — drop the self-request);
        # gossip: the candidate id rides the flood encoding
        grant_to = (vreq_t - 1) % n if gossip else vreq_t - 1
        has_req = (vreq_t > 0) & (grant_to != ids)
        if gossip:
            # term-style release: the dedup register admits only strictly
            # newer elections (see the my_base field comment), so every
            # processed request is a grant — the permanent latch would
            # deadlock the storm
            grant = has_req & state.alive
            # granting a vote resets the election timeout (standard Raft):
            # during the candidacy storm every node keeps re-arming, so no
            # timer fires into the winner's first heartbeat window and the
            # post-storm leader is not spuriously deposed
            k_gr = chan_key(tkey, Channel.ELECTION + 300)
            if axis is not None:
                k_gr = jax.random.fold_in(k_gr, jax.lax.axis_index(axis))
            rearm_gr = t + jax.random.randint(
                k_gr, (n_loc,), cfg.raft_election_lo_ms,
                cfg.raft_election_hi_ms, dtype=jnp.int32,
            )
            election_deadline = jnp.where(grant, rearm_gr, election_deadline)
        else:
            grant = has_req & can_grant
        deny = has_req & ~grant
        has_voted = state.has_voted | grant
        # Byzantine receivers flip their replies (grant<->deny on the wire)
        ok_wire = (grant & state.honest) | (deny & ~state.honest)
        no_wire = (deny & state.honest) | (grant & ~state.honest)
        # per-candidate reply counts, multinomially spread: a global
        # scatter-add on the full mesh; the overlay routes them
        # requester-side instead — candidate c gathers its out-neighbors'
        # wires and keeps those addressed to it (ops/gatherdeliv.
        # reply_counts_by_target_kreg: equal counts at k = N-1, and the
        # kregular program stays scatter-free, KNOWN_ISSUES #0i)
        def reply_counts(wire):
            if kreg:
                return gd.reply_counts_by_target_kreg(
                    wire, grant_to, nbr_out_loc, ids, axis, exchange
                )
            c = jnp.zeros((n,), jnp.int32).at[grant_to].add(
                wire.astype(jnp.int32), mode="drop"
            )
            if axis is not None:
                c = jax.lax.psum(c, axis)
                start = jax.lax.axis_index(axis) * n_loc
                c = jax.lax.dynamic_slice_in_dim(c, start, n_loc)
            return c

        any_req = has_req.any()
        k_vr = chan_key(tkey, Channel.DELAY_REPLY)

        def push_replies():
            # fused chain-into-ring — see the gossip ack block above
            mok = reply_counts(ok_wire)
            mno = reply_counts(no_wire)
            if drop > 0.0:
                kd = jax.random.fold_in(k_vr, 0x0D17)
                mok = jnp.round(delay_ops.binom(
                    kd, mok, 1.0 - drop, smode)).astype(jnp.int32)
                mno = jnp.round(delay_ops.binom(
                    jax.random.fold_in(kd, 1), mno, 1.0 - drop,
                    smode)).astype(jnp.int32)
            return (
                dv.push_bucket_counts(
                    vres_ok, t, lo, jax.random.fold_in(k_vr, 7), mok,
                    ow_probs, smode),
                dv.push_bucket_counts(
                    vres_no, t, lo, jax.random.fold_in(k_vr, 8), mno,
                    ow_probs, smode),
            )

        vres_ok, vres_no = gated(
            any_req, push_replies, (vres_ok, vres_no), axis,
        )
    else:
        # vreq_t[i, j] = 1 iff candidate j's request reaches i this tick.
        # Concurrent same-tick requests: the vote goes to the lowest candidate
        # id (the reference grants in serial arrival order; within one tick the
        # order is undefined, so we fix a deterministic choice).
        has_req = vreq_t > 0
        any_req = has_req.any(axis=1)
        first = jnp.argmax(has_req, axis=1)  # lowest j with a request
        grant_mask = (
            jax.nn.one_hot(first, vreq_t.shape[1], dtype=jnp.int32)
            * (any_req & can_grant).astype(jnp.int32)[:, None]
        )
        deny_mask = has_req.astype(jnp.int32) - grant_mask
        has_voted = state.has_voted | (any_req & can_grant)
        hn = state.honest.astype(jnp.int32)[:, None]
        ok_wire = grant_mask * hn + deny_mask * (1 - hn)
        no_wire = deny_mask * hn + grant_mask * (1 - hn)
        k_vr = chan_key(tkey, Channel.DELAY_REPLY)
        if kreg:
            # slot-indexed wires route back requester-side through the
            # inslot cross-index gather — no scatter, same keys/folds as
            # the dense unicast (bit-equal at k = N-1)
            def _unicast(kk, wire):
                return gd.unicast_reply_counts_kreg(
                    kk, wire, nbr_in_loc, nbr_out_loc, inslot_loc, ids,
                    lo, hi, drop, axis=axis, impl=eimpl, xg=exchange)
        else:
            def _unicast(kk, wire):
                return dv.unicast_reply_counts_dense(
                    kk, wire, lo, hi, drop, axis=axis, impl=eimpl)
        both = gated(
            any_req.any(),
            lambda: jnp.stack([
                _unicast(jax.random.fold_in(k_vr, 7), ok_wire),
                _unicast(jax.random.fold_in(k_vr, 8), no_wire),
            ]),
            jnp.zeros((2, hi - lo, n_loc), jnp.int32),
            axis,
        )
        vres_ok = ring_push_add(vres_ok, t, lo, both[0])
        vres_no = ring_push_add(vres_no, t, lo, both[1])

    # ---- vote responses (candidate side, raft-node.cc:196-232) --------------
    vs = state.vote_success + ok_t * (~state.is_leader)
    vf = state.vote_failed + no_t * (~state.is_leader)
    win = ~state.is_leader & (ok_t > 0) & (vs + 1 >= cfg.majority_need) & state.alive
    lose = ~win & (no_t > 0) & (vf >= cfg.raft_lose_need) & ~state.is_leader
    vote_success = jnp.where(win | lose, 0, vs)
    vote_failed = jnp.where(win | lose, 0, vf)
    # winner: cancel own timer, first heartbeat NOW, proposals in +1 s
    is_leader = state.is_leader | win
    election_deadline = jnp.where(win, DISARM, election_deadline)
    next_hb = jnp.where(win, jnp.int32(t), state.next_hb)
    proposal_tick = jnp.where(
        win, jnp.int32(t) + cfg.raft_proposal_delay_ms, state.proposal_tick
    )
    leader_tick = jnp.where(win & (state.leader_tick < 0), jnp.int32(t),
                            state.leader_tick)
    # loser: majority denied — release the vote latch and retry on the timer
    has_voted = has_voted & ~lose
    if queued:
        # leadership changed: the new leader's links are vote-only, hence
        # free, in both engines (votes never occupy the pipe); its busy
        # registers start fresh.  Already-scheduled deliveries from the old
        # leader keep their ring slots, exactly like the C++ engine's
        # in-flight events.
        lead_prev = jnp.max(jnp.where(state.is_leader & state.alive, ids, -1))
        lead_new = jnp.max(jnp.where(is_leader & state.alive, ids, -1))
        if axis is not None:
            lead_prev = jax.lax.pmax(lead_prev, axis)
            lead_new = jax.lax.pmax(lead_new, axis)
        link_busy = jnp.where(lead_new != lead_prev, 0, state.link_busy)
    else:
        link_busy = state.link_busy

    # ---- gossip: leader step-down on a newer election (see my_base) ---------
    if gossip:
        newest = seen_vreq // h_enc
        resign = is_leader & (newest > state.my_base) & state.alive
        is_leader = is_leader & ~resign
        next_hb = jnp.where(resign, DISARM, next_hb)
        proposal_tick = jnp.where(resign, DISARM, proposal_tick)
        # back to follower: re-arm the election timer (clean fidelity —
        # gossip requires it) so the node can detect the new leader failing
        k_rs = chan_key(tkey, Channel.ELECTION + 200)
        if axis is not None:
            k_rs = jax.random.fold_in(k_rs, jax.lax.axis_index(axis))
        rearm_rs = t + jax.random.randint(
            k_rs, (n_loc,), cfg.raft_election_lo_ms, cfg.raft_election_hi_ms,
            dtype=jnp.int32,
        )
        election_deadline = jnp.where(resign, rearm_rs, election_deadline)
    else:
        resign = jnp.zeros((n_loc,), bool)
    # a resigned leader abandons its open ack window: in-flight acks keep
    # arriving at the ex-leader (unicast), and without this a later
    # re-election could latch a phantom commit from pre-resignation acks
    hb_succ_in = jnp.where(resign, 0, state.hb_succ)
    hb_cnt_in = jnp.where(resign, 0, state.hb_cnt)
    hb_open_in = state.hb_open & ~resign

    # ---- proposal acks (leader side, raft-node.cc:234-251) ------------------
    hs = hb_succ_in + hbok_t
    hc = hb_cnt_in + hbtot_t
    if clean:
        commit = hb_open_in & (hs + 1 >= cfg.majority_need) & is_leader
        hb_open = hb_open_in & ~commit
        hb_succ, hb_cnt = hs, hc
    else:
        # reference: the check runs only at exactly N-1 responses in
        done = (hbtot_t > 0) & (hc == n - 1)
        commit = done & (hs + 1 >= cfg.majority_need)
        hb_succ = jnp.where(done, 0, hs)
        hb_cnt = jnp.where(done, 0, hc)
        hb_open = hb_open_in
    blk = jnp.clip(state.block_num, 0, cfg.raft_max_blocks - 1)
    block_tick = jnp.where(
        (jax.nn.one_hot(blk, cfg.raft_max_blocks, dtype=bool)
         & commit[:, None] & (state.block_num < cfg.raft_max_blocks)[:, None]),
        jnp.int32(t),
        state.block_tick,
    )
    block_num = state.block_num + commit
    # blockNum >= 50 cancels the heartbeat (raft-node.cc:248-251).  Gossip
    # divergence: completion must NOT silence the failure detector — with
    # term-style vote release, heartbeat silence triggers a fresh election
    # whose winner re-replicates from scratch (per-leader counters, no
    # shared log); the completed leader keeps the 4-byte control heartbeat
    # and simply stops proposing (add_change_value already cleared).
    if not gossip:
        next_hb = jnp.where(block_num >= cfg.raft_max_blocks, DISARM, next_hb)

    # ---- timer: sendVote (raft-node.cc:392-401) -----------------------------
    fire = (
        (jnp.int32(t) >= election_deadline)
        & (election_deadline != DISARM)
        & ~is_leader
        & state.alive
    )
    has_voted = has_voted | fire  # self-vote latch
    if gossip:
        # fresh election: restart the reply count (stale replies from the
        # previous election drained long ago — reply horizon << timeout)
        vote_success = jnp.where(fire, 0, vote_success)
        vote_failed = jnp.where(fire, 0, vote_failed)
    k_e2 = chan_key(tkey, Channel.ELECTION + 100)
    if axis is not None:
        k_e2 = jax.random.fold_in(k_e2, jax.lax.axis_index(axis))
    rearm2 = t + jax.random.randint(
        k_e2, (n_loc,), cfg.raft_election_lo_ms, cfg.raft_election_hi_ms,
        dtype=jnp.int32,
    )
    election_deadline = jnp.where(fire, rearm2, election_deadline)
    elections = state.elections + fire
    k_vq = chan_key(tkey, Channel.DELAY_BCAST)
    if gossip:
        # flood origin: full TTL, marked seen so the self-loop copy is inert
        base_v = ((jnp.int32(t) + 1) * n + ids + 1) * fire.astype(jnp.int32)
        origin_v = (base_v * h_enc + cfg.gossip_hops) * (base_v > 0)
        seen_vreq = jnp.maximum(seen_vreq, origin_v)
        # the candidate backs its own (newest) election
        my_base = jnp.maximum(my_base, base_v)
        out_v = jnp.maximum(origin_v, vreq_fwd)
        vq_contrib = gated(
            (out_v > 0).any(),
            lambda: dv.gossip_fwd(k_vq, out_v[:, None], nbrs_loc, n, lo, hi,
                                  drop, axis=axis, impl=eimpl)[:, :, 0],
            zeros_flat,
            axis,
        )
        vreq = ring_push_max(vreq, t, lo, vq_contrib)
    elif stat:
        vq_contrib = gated(
            fire.any(),
            lambda: (
                gd.bcast_value_max_stat_kreg(
                    k_vq, (ids + 1) * fire.astype(jnp.int32), nbr_in_loc,
                    ow_probs, drop, axis=axis, xg=exchange)
                if kreg else
                dv.bcast_value_max_stat(
                    k_vq, (ids + 1) * fire.astype(jnp.int32), ow_probs, drop,
                    axis=axis)
            ),
            zeros_flat,
            axis,
        )
        vreq = ring_push_max(vreq, t, lo, vq_contrib)
    elif kreg:
        vq_contrib = gated(
            fire.any(),
            lambda: gd.bcast_matrix_kreg(
                k_vq, fire, fire.astype(jnp.int32), nbr_in_loc, ids, lo, hi,
                drop, axis=axis, impl=eimpl, xg=exchange),
            jnp.zeros((hi - lo, n_loc, cfg.degree + 1), jnp.int32),
            axis,
        )
        vreq = ring_push_max(vreq, t, lo, vq_contrib)
    else:
        vq_contrib = gated(
            fire.any(),
            lambda: dv.bcast_matrix_dense(
                k_vq, fire, fire.astype(jnp.int32), lo, hi, drop, axis=axis,
                impl=eimpl),
            jnp.zeros((hi - lo, n_loc, n), jnp.int32),
            axis,
        )
        vreq = ring_push_max(vreq, t, lo, vq_contrib)

    # ---- timer: sendHeartBeat (raft-node.cc:405-433) ------------------------
    hb_fire = (
        is_leader & (jnp.int32(t) >= next_hb) & (next_hb != DISARM) & state.alive
    )
    # setProposal fires exactly once (raft-node.cc:216,431-433) — round==50
    # clears add_change_value for good, so the trigger must not re-fire
    set_prop = (jnp.int32(t) >= proposal_tick) & (proposal_tick != DISARM)
    add_change_value = (state.add_change_value | set_prop) & ~resign
    proposal_tick = jnp.where(set_prop, DISARM, proposal_tick)
    prop_send = hb_fire & add_change_value
    # Full mesh: either/or, like the reference (raft-node.cc:405-433).
    # Gossip: the leader ALWAYS floods the 4-byte plain heartbeat — a 20 KB
    # proposal store-and-forwards ~hops*(delay+ser) (~460 ms at defaults),
    # far beyond the 150-300 ms election window, so using the block channel
    # as the failure detector deposes a healthy leader every proposal phase;
    # separating the control heartbeat from block dissemination is the
    # documented gossip divergence.
    plain_send = hb_fire if gossip else (hb_fire & ~add_change_value)
    next_hb = jnp.where(hb_fire, next_hb + cfg.raft_heartbeat_ms, next_hb)
    # SendTX: round++; at round==50 stop adding proposals (raft-node.cc:361-365)
    round_ = state.round + prop_send
    add_change_value = add_change_value & ~(
        prop_send & (round_ >= cfg.raft_max_rounds)
    )
    # new proposal round opens the ack window
    hb_succ = jnp.where(prop_send, 0, hb_succ) if clean else hb_succ
    hb_cnt = jnp.where(prop_send, 0, hb_cnt) if clean else hb_cnt
    hb_open = (hb_open | prop_send) if clean else hb_open

    k_hb = chan_key(tkey, Channel.DELAY_BCAST2)
    if queued:
        # serial-pipe send (engine.cpp link_enqueue): the packet reaches the
        # (leader -> j) link after its scheduling delay d_j - prop, transmits
        # when the link frees (proposals occupy it for ser; 4-byte plain
        # heartbeats queue behind but occupy nothing), then propagates.
        # Deliveries land on the rings at dynamic per-destination offsets —
        # bounded by the (ser - hb) * rounds backlog that config.ring_depth
        # reserves — via scatter (fidelity-mode path; scatter cost is
        # irrelevant at the n=8-ish scales queued fidelity runs at).
        prop_ms = cfg.link_delay_ms
        prop_val = jnp.max(jnp.where(prop_send, ids + 1, 0))
        plain_on = jnp.max(plain_send.astype(jnp.int32))
        sender = jnp.max(jnp.where(prop_send | plain_send, ids, -1))
        if axis is not None:
            prop_val = jax.lax.pmax(prop_val, axis)
            plain_on = jax.lax.pmax(plain_on, axis)
            sender = jax.lax.pmax(sender, axis)
        any_send = (prop_val > 0) | (plain_on > 0)
        dest = any_send & (ids != sender)  # crashed peers still reserve the
        # pipe (C++ run_loop kind-2: reservation is sender-side)
        d_j = jax.random.randint(
            dv._shard_key(jax.random.fold_in(k_hb, 7), axis), (n_loc,), lo,
            hi, jnp.int32,
        )
        ser_s = jnp.where(prop_val > 0, ser, 0)
        start = jnp.maximum(t + d_j - prop_ms, link_busy)
        delivery = start + ser_s + prop_ms
        link_busy = jnp.where(dest, start + ser_s, link_busy)
        dd = hb_prop.shape[0]
        cols = jnp.arange(n_loc)
        didx = jnp.where(dest, delivery % dd, dd)  # dd = out-of-bounds drop
        hb_prop = hb_prop.at[didx, cols].max(
            jnp.where(dest, prop_val, 0), mode="drop")
        hb_plain = hb_plain.at[didx, cols].add(
            (dest & (plain_on > 0)).astype(jnp.int32), mode="drop")
    elif gossip:
        # plain heartbeats: tiny control messages, flooded with the tick as
        # the monotone base (concurrent leaders dedup to one — got_hb only
        # pacifies timers); proposals carry the 20 KB block, so every hop
        # re-serializes (store-and-forward), hence ser on each leg
        base_h = (jnp.int32(t) + 1) * plain_send.astype(jnp.int32)
        origin_h = (base_h * h_enc + cfg.gossip_hops) * (base_h > 0)
        seen_hb = jnp.maximum(seen_hb, origin_h)
        out_h = jnp.maximum(origin_h, hb_fwd)
        plain_contrib = gated(
            (out_h > 0).any(),
            lambda: dv.gossip_fwd(
                jax.random.fold_in(k_hb, 2), out_h[:, None], nbrs_loc, n, lo,
                hi, drop, axis=axis, impl=eimpl)[:, :, 0],
            zeros_flat,
            axis,
        )
        hb_plain = ring_push_max(hb_plain, t, lo, plain_contrib)
        base_p = (
            (jnp.int32(t) + 1) * (n + 1) + ids + 1
        ) * prop_send.astype(jnp.int32)
        origin_p = (base_p * h_enc + cfg.gossip_hops) * (base_p > 0)
        seen_prop = jnp.maximum(seen_prop, origin_p)
        out_p = jnp.maximum(origin_p, prop_fwd)
        prop_contrib = gated(
            (out_p > 0).any(),
            lambda: dv.gossip_fwd(
                jax.random.fold_in(k_hb, 3), out_p[:, None], nbrs_loc, n, lo,
                hi, drop, axis=axis, impl=eimpl)[:, :, 0],
            zeros_flat,
            axis,
        )
        hb_prop = ring_push_max(hb_prop, t, lo + ser, prop_contrib)
    elif kreg:
        if stat:
            plain_contrib = gated(
                plain_send.any(),
                # mode stays exact for the same O(1)-sender reason as the
                # full-mesh stat arm below
                lambda: gd.bcast_counts_stat_kreg(
                    k_hb, plain_send, nbr_in_loc, ids, ow_probs, drop,
                    axis=axis, mode="exact", xg=exchange),
                zeros_flat,
                axis,
            )
            prop_contrib = gated(
                prop_send.any(),
                lambda: gd.bcast_value_max_stat_kreg(
                    jax.random.fold_in(k_hb, 1),
                    (ids + 1) * prop_send.astype(jnp.int32), nbr_in_loc,
                    ow_probs, drop, axis=axis, xg=exchange),
                zeros_flat,
                axis,
            )
        else:
            plain_contrib = gated(
                plain_send.any(),
                lambda: gd.bcast_counts_kreg(
                    k_hb, plain_send, nbr_in_loc, ids, lo, hi, drop,
                    axis=axis, impl=eimpl, xg=exchange),
                zeros_flat,
                axis,
            )
            prop_contrib = gated(
                prop_send.any(),
                lambda: gd.bcast_value_max_kreg(
                    jax.random.fold_in(k_hb, 1), prop_send,
                    (ids + 1) * prop_send.astype(jnp.int32), nbr_in_loc,
                    ids, lo, hi, drop, axis=axis, impl=eimpl, xg=exchange),
                zeros_flat,
                axis,
            )
    elif stat:
        plain_contrib = gated(
            plain_send.any(),
            lambda: dv.bcast_counts_stat(
                k_hb,
                _psum_scalar(plain_send.astype(jnp.int32).sum(), axis),
                # mode stays exact here: this channel has O(1) senders (the
                # leader), and the Gaussian binomial approximation is biased
                # for count-1 draws (~9% on a p=1/3 bucket); the sampler-cost
                # argument for "normal" only applies to O(N)-count channels
                plain_send, ow_probs, drop, axis=axis, mode="exact"),
            zeros_flat,
            axis,
        )
        prop_contrib = gated(
            prop_send.any(),
            lambda: dv.bcast_value_max_stat(
                jax.random.fold_in(k_hb, 1),
                (ids + 1) * prop_send.astype(jnp.int32), ow_probs, drop,
                axis=axis),
            zeros_flat,
            axis,
        )
    else:
        plain_contrib = gated(
            plain_send.any(),
            lambda: dv.bcast_counts_dense(k_hb, plain_send, lo, hi, drop,
                                          axis=axis, impl=eimpl),
            zeros_flat,
            axis,
        )
        prop_contrib = gated(
            prop_send.any(),
            lambda: dv.bcast_value_max_dense(
                jax.random.fold_in(k_hb, 1), prop_send,
                (ids + 1) * prop_send.astype(jnp.int32), lo, hi, drop,
                axis=axis, impl=eimpl),
            zeros_flat,
            axis,
        )
    if not gossip and not queued:
        hb_plain = ring_push_add(hb_plain, t, lo, plain_contrib)
        hb_prop = ring_push_max(hb_prop, t, lo + ser, prop_contrib)

    # proposal acks: follower state never affects the SUCCESS reply
    # (raft-node.cc:170-193), so the round trip is short-circuited; Byzantine
    # followers flip to FAILED.  The SUCCESS (honest) and FAILED (Byzantine)
    # channels cover *disjoint* peer sets, so their independent delay draws
    # cover disjoint edges — each ack lands in exactly one channel at one tick,
    # and the leader's total count is their sum.  (Gossip acks are generated
    # at flood arrival instead — see the gossip block above.)
    k_rt = chan_key(tkey, Channel.DELAY_ROUNDTRIP)
    voters = state.alive & state.honest
    liars = state.alive & ~state.honest
    if gossip:
        pass
    elif queued:
        # the follower's ack is a 4-byte reply over the (follower -> leader)
        # link, which is never busy (followers send no blocks): it departs at
        # the proposal's queued DELIVERY tick and lands one one-way delay
        # later.  Ack ticks are per-destination, the receiver is the single
        # leader row: bucket them into a [D] histogram (psum'd across shards)
        # and add it into the leader's ring column on the owning shard.
        d2 = jax.random.randint(
            dv._shard_key(jax.random.fold_in(k_rt, 9), axis), (n_loc,), lo,
            hi, jnp.int32,
        )
        ack_arr = delivery + d2
        prop_on = prop_val > 0
        okd = dest & prop_on & voters
        badd = dest & prop_on & liars
        dd = hb_ok.shape[0]
        hist_ok = jnp.zeros((dd,), jnp.int32).at[
            jnp.where(okd, ack_arr % dd, dd)].add(1, mode="drop")
        hist_bad = jnp.zeros((dd,), jnp.int32).at[
            jnp.where(badd, ack_arr % dd, dd)].add(1, mode="drop")
        if axis is not None:
            hist_ok = jax.lax.psum(hist_ok, axis)
            hist_bad = jax.lax.psum(hist_bad, axis)
        col = sender - ids[0]
        owned = prop_on & (col >= 0) & (col < n_loc)
        col_c = jnp.clip(col, 0, n_loc - 1)
        hb_ok = hb_ok.at[:, col_c].add(jnp.where(owned, hist_ok, 0))
        hb_bad = hb_bad.at[:, col_c].add(jnp.where(owned, hist_bad, 0))
    elif stat:
        # fused chain-into-ring (ops/delivery.push_roundtrip_reply_counts_
        # stat) — bit-equal to the former sample → ring_push_add compose.
        # The kregular overlay swaps only the per-sender peer counts for
        # out-table gathers (equal at k = N-1, same keys/chain).
        if kreg:
            ok_peers = gd.out_counts(voters, nbr_out_loc, ids, axis, exchange)
            bad_peers = gd.out_counts(liars, nbr_out_loc, ids, axis, exchange)
        else:
            n_voters = _psum_scalar(voters.astype(jnp.int32).sum(), axis)
            n_liars = _psum_scalar(liars.astype(jnp.int32).sum(), axis)
            ok_peers = n_voters - voters.astype(jnp.int32)
            bad_peers = n_liars - liars.astype(jnp.int32)
        hb_ok, hb_bad = gated(
            prop_send.any(),
            lambda: (
                dv.push_roundtrip_reply_counts_stat(
                    hb_ok, t, rt_lo + ser, k_rt, prop_send,
                    ok_peers, rt_probs, drop,
                    axis=axis, mode=smode),
                dv.push_roundtrip_reply_counts_stat(
                    hb_bad, t, rt_lo + ser, jax.random.fold_in(k_rt, 1),
                    prop_send, bad_peers, rt_probs,
                    drop, axis=axis, mode=smode),
            ),
            (hb_ok, hb_bad),
            axis,
        )
    else:
        if kreg:
            def _rt(kk, peers):
                return gd.roundtrip_reply_counts_kreg(
                    kk, prop_send, nbr_out_loc, ids, lo, hi, drop,
                    peer_mask=peers, axis=axis, impl=eimpl, xg=exchange)
        else:
            def _rt(kk, peers):
                return dv.roundtrip_reply_counts_dense(
                    kk, prop_send, lo, hi, drop, peer_mask=peers, axis=axis,
                    impl=eimpl)
        ok_counts = gated(
            prop_send.any(), lambda: _rt(k_rt, voters), zeros_rt, axis,
        )
        bad_counts = gated(
            prop_send.any(),
            lambda: _rt(jax.random.fold_in(k_rt, 1), liars),
            zeros_rt,
            axis,
        )
        hb_ok = ring_push_add(hb_ok, t, rt_lo + ser, ok_counts)
        hb_bad = ring_push_add(hb_bad, t, rt_lo + ser, bad_counts)

    state = state.replace(
        is_leader=is_leader,
        has_voted=has_voted,
        election_deadline=election_deadline,
        vote_success=vote_success,
        vote_failed=vote_failed,
        next_hb=next_hb,
        proposal_tick=proposal_tick,
        add_change_value=add_change_value,
        m_value=m_value,
        block_num=block_num,
        round=round_,
        hb_succ=hb_succ,
        hb_cnt=hb_cnt,
        hb_open=hb_open,
        leader_tick=leader_tick,
        elections=elections,
        block_tick=block_tick,
        seen_vreq=seen_vreq,
        seen_hb=seen_hb,
        seen_prop=seen_prop,
        my_base=my_base,
        link_busy=link_busy,
    )
    bufs = RaftBufs(
        vreq=vreq, vres_ok=vres_ok, vres_no=vres_no, hb_plain=hb_plain,
        hb_prop=hb_prop, hb_ok=hb_ok, hb_bad=hb_bad,
    )
    return state, bufs


def _psum_scalar(x, axis):
    return x if axis is None else jax.lax.psum(x, axis)


def metrics(cfg, state: RaftState) -> dict:
    """The reference's measurement surface (SURVEY.md §5): leader-elected time
    (raft-node.cc:212), per-block processed time (:246), final Blocks/Rounds
    summary (:122-123), election starts (:399)."""
    alive = np.asarray(state.alive)
    is_leader = np.asarray(state.is_leader)
    leader_tick = np.asarray(state.leader_tick)
    block_num = np.asarray(state.block_num)
    block_tick = np.asarray(state.block_tick)
    m_value = np.asarray(state.m_value)
    leaders = np.flatnonzero(is_leader & alive)
    # under Byzantine double-voting a split brain is possible (no terms);
    # report the earliest-elected leader as "the" leader
    lead = int(leaders[np.argmin(leader_tick[leaders])]) if leaders.size else -1
    blocks = int(block_num[lead]) if lead >= 0 else 0
    bt = block_tick[lead][: blocks] if lead >= 0 else np.array([])
    # agreement: every alive follower that stored a value stored the leader's
    stored = m_value[alive]
    stored = stored[stored >= 0]
    return {
        "protocol": "raft",
        "n": cfg.n,
        "n_leaders": int(len(leaders)),
        "leader": lead,
        "leader_elected_ms": float(leader_tick[lead]) if lead >= 0 else -1.0,
        "blocks": blocks,
        "rounds": int(np.asarray(state.round).max()),
        "elections": int(np.asarray(state.elections).sum()),
        "last_block_ms": float(bt.max()) if bt.size else -1.0,
        "mean_block_interval_ms": (
            float(np.diff(bt).mean()) if bt.size > 1 else -1.0
        ),
        "agreement_ok": bool(
            lead < 0 or (stored.size == 0) or (stored == lead).all()
        ),
    }
