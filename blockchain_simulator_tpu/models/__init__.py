from blockchain_simulator_tpu.models.base import get_protocol  # noqa: F401
