"""PBFT consensus — tensorized state machine.

Re-design of the reference's ``PbftNode`` (pbft/pbft-node.h:19, pbft-node.cc):
a leader-driven 3-phase commit where the leader broadcasts PRE_PREPARE blocks
every 50 ms (SendBlock, pbft-node.cc:372-411), replicas broadcast PREPARE on
receipt (pbft-node.cc:193-211), every PREPARE is answered with a unicast
PREPARE_RES SUCCESS (pbft-node.cc:212-221), a node crossing the prepare
quorum broadcasts COMMIT (pbft-node.cc:223-239), and a node crossing the
commit quorum finalizes the block (pbft-node.cc:241-264 — the finality
measurement point, line 259).  A leader round has a 1/100 chance of a view
change rotating the leader (pbft-node.cc:294-303,401-403).

Tensorization (SURVEY.md §7): one tick = 1 ms for all N nodes at once.

- The per-``(v,n)`` vote table ``TX tx[1000]`` (pbft-node.h:50-56) becomes a
  **slot window**: live vote state is ``[N, W]`` keyed by ``slot % W``
  (``W = pbft_window``; default = ``pbft_max_slots`` = exact mode).  A slot's
  messages are all in flight within ``ring_depth`` ticks (≪ ``W`` block
  intervals), so by the time window ``w`` is re-tenanted by slot ``s + W``
  the old tenant's traffic has drained; the PRE_PREPARE channel carries the
  slot id, and a higher id evicts (zeroes) the window.  This caps the
  per-tick HBM footprint at O(N·W) instead of O(N·S) — the difference
  between ~20 and hundreds of simulated consensus rounds/sec at N = 100k.
- Per-slot outcomes (finality counts, commit/propose ticks) fold into tiny
  ``[S]`` accumulators via per-window scatter-reductions; sharded, these are
  per-shard partials combined once after the scan (``finalize``).
- PREPARE handling is *short-circuited*: a peer's reply never depends on its
  state, so a PREPARE broadcast by node i at tick t directly schedules N-1
  PREPARE_RES arrivals at i over the request+reply delay distribution.
- The reference's process-global ``v, n, val, n_round`` (pbft-node.cc:24-30,
  quirk #10 in SURVEY.md §2) become per-node state; a new leader infers the
  next sequence number from the highest PRE_PREPARE slot it has seen.
- Echo-back (quirk #1) is a deliberate divergence shared by the JAX backend
  and the C++ reference engine (engine.cpp:29-31): every echoed packet lands
  in the reference's "wrong msg" default branch, so dropping the echoes
  changes traffic volume but no protocol outcome; differential tests pin the
  echo-off behavior on both backends.

Fidelity modes: ``reference`` keeps N/2 thresholds and reset-on-threshold
counters (quirks #2, #4 — duplicate commits possible); ``clean`` latches each
(node, slot) so a slot commits exactly once.  ``quorum_rule="2f1"`` swaps in
Byzantine-safe 2f+1 thresholds (utils/config.py).

Windowed-mode preconditions (checked in init): the PRE_PREPARE for a slot
always lands before any of that slot's COMMIT votes (first commit arrival is
>= 4 one-way-lo after the proposal vs. <= one-way-hi for the PRE_PREPARE),
so counters are never attributed to a stale tenant; per-message drops can
break that ordering for an unlucky node, in which case its votes land in an
``unattributed`` counter instead of a slot (reported in metrics).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from blockchain_simulator_tpu.models.base import fault_masks, gated
from blockchain_simulator_tpu.ops import delay as delay_ops
from blockchain_simulator_tpu.ops import delivery as dv
from blockchain_simulator_tpu.ops import gatherdeliv as gd
from blockchain_simulator_tpu.ops import topology
from blockchain_simulator_tpu.ops.ring import ring_pop, ring_push_add, ring_push_max
from blockchain_simulator_tpu.utils.prng import Channel, chan_key

# propose-tick sentinel (min-reduced); np, not jnp: same int either way
# (iinfo is pure dtype metadata), and module scope stays trivially free of
# jax calls (jaxlint module-scope-backend-touch)
_NEVER = np.iinfo(np.int32).max

# state fields that are per-slot accumulators, NOT node-sharded: every shard
# holds a partial that ``finalize`` combines (parallel/shard.py keeps them
# replicated-spec and calls finalize after the scan)
GLOBAL_FIELDS = ("slot_commits", "slot_commit_tick", "slot_propose_tick")


@struct.dataclass
class PbftState:
    v: jax.Array            # [N] current view (init 1, pbft-node.cc:101)
    leader: jax.Array       # [N] believed leader (init 0)
    next_n: jax.Array       # [N] next sequence number a leader would use
    rounds_sent: jax.Array  # [N] blocks broadcast as leader (global n_round analog)
    slot_id: jax.Array      # [N, W] tenant slot of each window, -1 unknown
    prepare_vote: jax.Array  # [N, W]
    commit_vote: jax.Array   # [N, W]
    prep_sent: jax.Array     # [N, W] bool — COMMIT already broadcast (clean latch)
    committed_w: jax.Array   # [N, W] bool — tenant finalized at this node
    block_num: jax.Array     # [N] commits counted (duplicates possible in
    # reference fidelity, matching pbft-node.cc:260)
    unattributed: jax.Array  # [N] commits that crossed with an unknown tenant
    view_changes: jax.Array  # [N] view changes initiated
    alive: jax.Array         # [N] bool fault mask
    honest: jax.Array        # [N] bool fault mask
    # gossip (topology="gossip") dedup state; zeros on the full mesh
    seen_pp: jax.Array       # [N, W] highest TTL-encoded PRE_PREPARE seen
    seen_vc: jax.Array       # [N] highest TTL-encoded VIEW_CHANGE seen
    # queued-link transport registers (cfg.queued_links; [N,1] dummies off).
    # ns-3 models each directed link as a serial 3 Mbps pipe
    # (blockchain-simulator.cc:22-24): a block transmits when the link is
    # free, occupies it for its serialization time, then propagates — blocks
    # depart every 50 ms but serialize ~136 ms, so per-link queues grow
    # ~86 ms/round (engine.cpp:198-215 is the C++ twin).  Blocks only ever
    # flow from the current leader, so the busy state is per DESTINATION —
    # a [N] tensor, not [N,N]; the registers reset on a view change (a
    # first-time leader's links are vote-only, hence free, in both engines;
    # divergence only if a leader is RE-elected, which takes N rotations).
    # Delivery offsets grow without bound, so queued blocks bypass the ring
    # into a per-destination FIFO of (arrival tick, slot value) pairs.
    link_busy: jax.Array     # [N] tick until which (leader -> j) is busy
    ppq_tick: jax.Array      # [N, Q] queued-block arrival ticks (_NEVER free)
    ppq_val: jax.Array       # [N, Q] queued-block slot+1 values
    # --- per-slot accumulators (GLOBAL_FIELDS; per-shard partials) ----------
    slot_commits: jax.Array      # [S] nodes that finalized slot s (first time)
    slot_commit_tick: jax.Array  # [S] last finalization tick, -1 never
    slot_propose_tick: jax.Array  # [S] first proposal tick, _NEVER sentinel


@struct.dataclass
class PbftBufs:
    pp: jax.Array       # [D, N, W] PRE_PREPARE slot-id+1 values, max-combined
    prep_rt: jax.Array  # [D, N, W] PREPARE_RES (round-trip) reply counts
    commit: jax.Array   # [D, N, W] COMMIT arrival counts
    vc: jax.Array       # [D, N] VIEW_CHANGE, encoded v*N + leader + 1, max


def eff_window(cfg) -> int:
    w = getattr(cfg, "pbft_window", 0)
    if w <= 0 or w >= cfg.pbft_max_slots:
        return cfg.pbft_max_slots
    return w


def queue_len(cfg) -> int:
    """Static per-destination block-FIFO depth for queued-link mode: sized to
    r = min(pbft_max_rounds, pbft_max_slots) outright — cheap at the n=8-ish
    scales queued fidelity runs at, and together with the free-slot enqueue
    in ``step`` it makes silently clobbering an undelivered block impossible
    (the former steady-state backlog estimate undersized the FIFO under
    adversarial view-change timing, which both re-proposes stale slots and
    resets link_busy — ADVICE r5)."""
    ser = cfg.serialization_ticks(cfg.pbft_block_bytes)
    if not cfg.queued_links or ser == 0:
        return 1  # dummy registers; the ring path carries the blocks
    return min(cfg.pbft_max_rounds, cfg.pbft_max_slots)


def init(cfg, key=None):
    n, s = cfg.n, cfg.pbft_max_slots
    w = eff_window(cfg)
    d = cfg.ring_depth
    if cfg.topology == "gossip" and w < s:
        raise ValueError(
            "pbft gossip (topology='gossip') requires exact vote-table mode "
            "(pbft_window = 0 or >= pbft_max_slots): a multi-hop PRE_PREPARE "
            "can trail its slot's direct-unicast COMMIT votes, which exact "
            "mode attributes by window identity while a window would misfile"
        )
    if w < s:
        lo, hi = cfg.one_way_range()
        if 4 * lo <= hi:
            raise ValueError(
                f"pbft_window={w} < max_slots requires 4*delay_lo > delay_hi "
                f"(got lo={lo}, hi={hi}): a slot's PRE_PREPARE must land "
                "before its first COMMIT vote"
            )
        if w * cfg.pbft_block_interval_ms <= d + hi:
            raise ValueError(
                f"pbft_window={w} re-tenants a window every "
                f"{w * cfg.pbft_block_interval_ms} ms, inside the message "
                f"horizon (~{d + hi} ms); raise pbft_window"
            )
        if cfg.faults.byz_forge:
            raise ValueError(
                "byz_forge targets a concrete never-proposed slot; it "
                "requires exact mode (pbft_window = 0 or >= pbft_max_slots)"
            )
    alive, honest = fault_masks(cfg, n)
    zi = lambda *sh: jnp.zeros(sh, jnp.int32)
    zb = lambda *sh: jnp.zeros(sh, bool)
    state = PbftState(
        v=jnp.ones((n,), jnp.int32),
        leader=zi(n),
        next_n=zi(n),
        rounds_sent=zi(n),
        slot_id=jnp.full((n, w), -1, jnp.int32),
        prepare_vote=zi(n, w),
        commit_vote=zi(n, w),
        prep_sent=zb(n, w),
        committed_w=zb(n, w),
        block_num=zi(n),
        unattributed=zi(n),
        view_changes=zi(n),
        alive=alive,
        honest=honest,
        seen_pp=zi(n, w),
        seen_vc=zi(n),
        link_busy=zi(n),
        ppq_tick=jnp.full((n, queue_len(cfg)), _NEVER, jnp.int32),
        ppq_val=zi(n, queue_len(cfg)),
        slot_commits=zi(s),
        slot_commit_tick=jnp.full((s,), -1, jnp.int32),
        slot_propose_tick=jnp.full((s,), _NEVER, jnp.int32),
    )
    bufs = PbftBufs(pp=zi(d, n, w), prep_rt=zi(d, n, w), commit=zi(d, n, w), vc=zi(d, n))
    return state, bufs


def finalize(state: PbftState, axis) -> PbftState:
    """Combine per-shard slot accumulators (call once, after the scan)."""
    if axis is None:
        return state
    return state.replace(
        slot_commits=jax.lax.psum(state.slot_commits, axis),
        slot_commit_tick=jax.lax.pmax(state.slot_commit_tick, axis),
        slot_propose_tick=jax.lax.pmin(state.slot_propose_tick, axis),
    )


def _scatter_window_events(acc_add, acc_max, acc_min, events, eff_sid, t, s):
    """Fold [N, W] first-commit / propose events into [S] accumulators via a
    per-window reduction: all nodes crossing a window this tick share its
    tenant, so per-window (count, slot-id) pairs are exact and the scatter is
    W updates, not N·W.  Invalid slot ids route out of bounds and drop."""
    ev = events.astype(jnp.int32)
    cnt_w = ev.sum(axis=0)                                   # [W]
    sid_w = jnp.max(jnp.where(events, eff_sid, -1), axis=0)  # [W]
    idx = jnp.where((sid_w >= 0) & (cnt_w > 0), sid_w, s)    # s = out of bounds
    out = []
    if acc_add is not None:
        out.append(acc_add.at[idx].add(cnt_w, mode="drop"))
    if acc_max is not None:
        out.append(acc_max.at[idx].max(jnp.where(cnt_w > 0, jnp.int32(t), -1),
                                       mode="drop"))
    if acc_min is not None:
        out.append(acc_min.at[idx].min(jnp.where(cnt_w > 0, jnp.int32(t), _NEVER),
                                       mode="drop"))
    return out


def step(cfg, state: PbftState, bufs: PbftBufs, t, tkey, *, topo_tables=None,
         exchange=None):
    n, s = cfg.n, cfg.pbft_max_slots
    w = eff_window(cfg)
    exact = w == s
    axis = cfg.mesh_axis
    lo, hi = cfg.one_way_range()
    rt_lo, rt_hi = cfg.roundtrip_range()
    drop = cfg.faults.drop_prob
    clean = cfg.fidelity == "clean"
    stat = cfg.delivery == "stat"
    smode = cfg.eff_stat_sampler
    eimpl = cfg.eff_edge_sampler
    ow_probs = delay_ops.uniform_probs(lo, hi)
    rt_probs = delay_ops.roundtrip_probs(lo, hi)
    n_loc = state.v.shape[0]
    # global node ids of this shard's rows (== arange(N) unsharded)
    ids = dv._global_ids(n_loc, axis)
    windows = jnp.arange(w)

    ser = cfg.serialization_ticks(cfg.pbft_block_bytes)
    # queued-link transport (cfg.queued_links): blocks ride per-destination
    # serial-pipe FIFOs instead of the ring (see PbftState field comments);
    # with ser == 0 the pipe is never busy and queued == constant-latency
    # bit-exactly, so the plain ring path runs (engine.cpp behaves the same)
    queued = cfg.queued_links and ser > 0
    prop = cfg.link_delay_ms

    # ---- pop this tick's arrivals; crashed nodes process nothing ------------
    pp_t, pp = ring_pop(bufs.pp, t)
    prep_t, prep_rt = ring_pop(bufs.prep_rt, t)
    com_t, commit = ring_pop(bufs.commit, t)
    vc_t, vc = ring_pop(bufs.vc, t)
    am = state.alive.astype(jnp.int32)
    pp_t, prep_t, com_t = pp_t * am[:, None], prep_t * am[:, None], com_t * am[:, None]
    vc_t = vc_t * am

    # queued mode: this tick's serial-link block deliveries (exact mode is
    # enforced by runner._reject_cpp_only, so window == slot identity).  A
    # destination can receive TWO blocks in one tick — a view change frees
    # the new leader's links while an old-leader block is still backlogged —
    # so every hit scatters into its own window (same-window collisions are
    # impossible: exact mode keys windows by slot identity), matching the
    # C++ engine delivering both events.
    if queued:
        hits = state.ppq_tick == t  # [N, Q]
        vals = jnp.where(hits & state.alive[:, None], state.ppq_val, 0)
        ppq_tick = jnp.where(hits, _NEVER, state.ppq_tick)
        oh_arr = (
            ((vals - 1) % w)[:, :, None] == windows[None, None, :]
        ) & (vals > 0)[:, :, None]  # [N, Q, W]
        pp_t = jnp.maximum(
            pp_t, jnp.max(jnp.where(oh_arr, vals[:, :, None], 0), axis=1)
        )
    else:
        ppq_tick = state.ppq_tick

    # ---- gossip decode (topology="gossip"): the block-carrying channels
    # (PRE_PREPARE) and the control channel (VIEW_CHANGE) flood over the k-out
    # digraph with a hop TTL; votes stay direct unicast — they are 4-byte
    # packets, and flooding them would need per-sender dedup state (O(N^2)),
    # defeating the sparse path.  Channel values carry encoded*H + hops_left
    # (H = gossip_hops+1); a node processes each base value once (first
    # sighting) but forwards any strictly better TTL copy, so a nearly-expired
    # first arrival cannot truncate the flood (same scheme as models/paxos.py).
    gossip = cfg.topology == "gossip"
    # kregular gather overlay (topo/spec.py): every channel delivers DIRECT
    # to the circulant in/out neighbor tables through the O(N*K) gather
    # primitives (ops/gatherdeliv.py) — no relay, no dedup state, and at
    # degree k = N-1 bit-equal to the dense/full-mesh arms below (the sorted
    # full-overlay table is the identity, so the same keys draw the same
    # tensors).  With k below the commit quorum a node can never hear enough
    # votes — a stalling-but-valid scenario (KNOWN_ISSUES topo note).
    kreg = cfg.topology == "kregular"
    nbr_in_loc = nbr_out_loc = None
    if kreg:
        # topo_tables=None bakes the tables as trace constants (audit
        # scale); the sharded programs pass them as operands instead.  In
        # exchange mode the operands ARE this trace's rows already —
        # ids=None skips the take that GSPMD would turn into a full-table
        # all-gather (the retired table-regather debt)
        nbr_in_loc, nbr_out_loc = gd.local_tables(
            cfg, None if exchange is not None else ids, tables=topo_tables)
    seen_pp, seen_vc = state.seen_pp, state.seen_vc
    pp_fwd = vc_fwd = None
    nbrs_loc = None
    if gossip:
        h_enc = cfg.gossip_hops + 1
        nbrs_loc = jnp.take(
            jnp.asarray(topology.kregular_out_neighbors(n, cfg.degree, cfg.seed)),
            ids, axis=0,
        )
        pp_base, pp_hops = pp_t // h_enc, pp_t % h_enc
        better = (pp_t > seen_pp) & state.alive[:, None]
        new_base = (pp_base > seen_pp // h_enc) & state.alive[:, None]
        seen_pp = jnp.maximum(seen_pp, pp_t * better)
        pp_fwd = (pp_base * h_enc + jnp.maximum(pp_hops - 1, 0)) * (
            better & (pp_hops > 0)
        )
        pp_t = pp_base * new_base  # first sighting processes (value = slot+1)
        vc_base, vc_hops = vc_t // h_enc, vc_t % h_enc
        vbetter = (vc_t > seen_vc) & state.alive
        vnew = (vc_base > seen_vc // h_enc) & state.alive
        seen_vc = jnp.maximum(seen_vc, vc_t * vbetter)
        vc_fwd = (vc_base * h_enc + jnp.maximum(vc_hops - 1, 0)) * (
            vbetter & (vc_hops > 0)
        )
        vc_t = vc_base * vnew

    # ---- VIEW_CHANGE arrivals: adopt (v, leader) (pbft-node.cc:271-280) -----
    has_vc = vc_t > 0
    v = jnp.where(has_vc, (vc_t - 1) // n, state.v)
    leader = jnp.where(has_vc, (vc_t - 1) % n, state.leader)
    if queued:
        # leadership rotated: the NEW leader's links are vote-only, hence
        # free (votes never occupy the pipe — ser 0) in both engines; its
        # busy registers start fresh.  VC arrivals all land before the next
        # block tick (one-way hi <= interval, enforced by the runner), so
        # the reset settles strictly between block sends.
        any_vc = jnp.max(has_vc.astype(jnp.int32))
        if axis is not None:
            any_vc = jax.lax.pmax(any_vc, axis)
        link_busy = jnp.where(any_vc > 0, 0, state.link_busy)
    else:
        link_busy = state.link_busy

    # ---- PRE_PREPARE arrivals: evict stale tenant, store, broadcast PREPARE -
    got_pp = pp_t > 0  # [N, W]  (any arrival re-broadcasts PREPARE — the
    # reference PRE_PREPARE handler has no dedup, pbft-node.cc:193-211)
    arr_sid = pp_t - 1  # announced slot id
    new_tenant = got_pp & (arr_sid > state.slot_id)
    slot_id = jnp.where(new_tenant, arr_sid, state.slot_id)
    if exact:
        # windows ARE slot identities — nothing is ever re-tenanted, so a
        # learned tenant must not wipe the counters: votes can legitimately
        # precede the PRE_PREPARE (gossip: direct-unicast COMMITs outrun the
        # multi-hop block flood; drops: the pp may never come at all) and
        # were already attributed to this window by identity
        prepare_vote, commit_vote = state.prepare_vote, state.commit_vote
        prep_sent, committed_w = state.prep_sent, state.committed_w
    else:
        # windowed mode: a higher slot id evicts the stale tenant's state
        prepare_vote = jnp.where(new_tenant, 0, state.prepare_vote)
        commit_vote = jnp.where(new_tenant, 0, state.commit_vote)
        prep_sent = state.prep_sent & ~new_tenant
        committed_w = state.committed_w & ~new_tenant
    seen_hi = jnp.max(jnp.where(got_pp, arr_sid + 1, 0), axis=1)
    next_n = jnp.maximum(state.next_n, seen_hi)

    # PREPARE broadcast → short-circuited round-trip PREPARE_RES replies.
    # Only honest, alive peers contribute SUCCESS votes (Byzantine nodes flip
    # their votes to FAILED, which the counter ignores, pbft-node.cc:227).
    voters = state.alive & state.honest
    k_rt = chan_key(tkey, Channel.DELAY_ROUNDTRIP)
    prep_active = got_pp.any(axis=1)
    got_pp_i = got_pp.astype(jnp.int32)
    if stat:
        # fused sample-and-push (ops/delivery.push_roundtrip_reply_counts_
        # stat): each reply bucket's chain math lands straight in its ring
        # slice — bit-equal to the unfused sample → expand → ring_push_add
        # compose, without the [B2, N, W] stacked intermediate.  The gated
        # fallback returns the ring UNTOUCHED, which is what pushing an
        # all-zero contribution produced.  The kregular overlay swaps ONLY
        # the per-sender peer count — a gather over the out-table instead
        # of total-minus-self — and rides the same fused chain on the same
        # key (equal counts at k = N-1, hence bit-equal).
        if kreg:
            n_peers = gd.out_counts(voters, nbr_out_loc, ids, axis, exchange)
        else:
            n_voters = voters.astype(jnp.int32).sum()
            if axis is not None:
                n_voters = jax.lax.psum(n_voters, axis)
            n_peers = n_voters - voters.astype(jnp.int32)
        prep_rt = gated(
            prep_active.any(),
            lambda: dv.push_roundtrip_reply_counts_stat(
                prep_rt, t, rt_lo, k_rt, prep_active,
                n_peers, rt_probs, drop,
                axis=axis, mode=smode,
                # replies are per broadcast, i.e. per active (node, window)
                expand=lambda c: c[:, None] * got_pp_i,
            ),
            prep_rt,
            axis,
        )
    else:
        rt_counts = gated(
            prep_active.any(),
            lambda: (
                gd.roundtrip_reply_counts_kreg(
                    k_rt, prep_active, nbr_out_loc, ids, lo, hi, drop,
                    peer_mask=voters, axis=axis, impl=eimpl, xg=exchange,
                ) if kreg else dv.roundtrip_reply_counts_dense(
                    k_rt, prep_active, lo, hi, drop, peer_mask=voters,
                    axis=axis, impl=eimpl,
                )
            ),
            jnp.zeros((len(rt_probs), n_loc), jnp.int32),
            axis,
        )
        # replies are per broadcast, i.e. per active (node, window)
        prep_rt = ring_push_add(
            prep_rt, t, rt_lo, rt_counts[:, :, None] * got_pp_i[None, :, :]
        )

    # ---- PREPARE_RES arrivals → prepare_vote → COMMIT broadcast -------------
    pv = prepare_vote + prep_t
    crossed_p = (prep_t > 0) & (pv >= cfg.pbft_prepare_need)  # pbft-node.cc:231
    if clean:
        crossed_p = crossed_p & ~prep_sent
    prep_sent = prep_sent | crossed_p
    prepare_vote = jnp.where(crossed_p, 0, pv)  # reset on threshold (quirk #4)

    bt = cfg.pbft_block_interval_ms
    is_block_tick = (t % bt == 0) & (t > 0)
    commit_send = crossed_p & (state.alive & state.honest)[:, None]
    commit_mat = commit_send.astype(jnp.int32)
    if cfg.faults.byz_forge and cfg.faults.n_byzantine > 0:
        # Active attack: Byzantine nodes flood COMMIT votes for the
        # never-proposed last slot (exact mode: window == slot).  Under "n2"
        # there is no per-sender dedup (quirk #2): every copy of every
        # re-send lands in the accumulating counter, so f forgers cross any
        # threshold eventually.  A "2f1" receiver counts at most one vote per
        # sender *ever*, equivalent to the flood collapsing to a single send.
        if cfg.quorum_rule == "2f1":
            fire, copies = jnp.equal(t, bt), 1
        else:
            fire, copies = is_block_tick, cfg.faults.byz_copies
        forgers = (state.alive & ~state.honest).astype(jnp.int32) * jnp.int32(fire)
        commit_mat = commit_mat.at[:, w - 1].add(forgers * copies)
    k_cm = chan_key(tkey, Channel.DELAY_BCAST)
    zeros_w = jnp.zeros((hi - lo, n_loc, w), jnp.int32)
    if stat:
        # fused chain-into-ring (see the prep_rt channel above); the
        # kregular twin gathers the per-(receiver, slot) sender counts
        # over the in-table instead of totals-minus-own
        commit = gated(
            (commit_mat > 0).any(),
            lambda: (
                gd.push_bcast_slots_stat_kreg(
                    commit, t, lo, k_cm, commit_mat, nbr_in_loc, ids,
                    ow_probs, drop, axis=axis, mode=smode, xg=exchange,
                ) if kreg else dv.push_bcast_slots_stat(
                    commit, t, lo, k_cm, commit_mat, ow_probs, drop,
                    axis=axis, mode=smode,
                )
            ),
            commit,
            axis,
        )
    else:
        cm_contrib = gated(
            (commit_mat > 0).any(),
            lambda: (
                gd.bcast_slots_kreg(k_cm, commit_mat, nbr_in_loc, ids, lo,
                                    hi, drop, axis=axis, impl=eimpl,
                                    xg=exchange)
                if kreg else
                dv.bcast_slots_dense(k_cm, commit_mat, lo, hi, drop,
                                     axis=axis, impl=eimpl)
            ),
            zeros_w,
            axis,
        )
        commit = ring_push_add(commit, t, lo, cm_contrib)

    # ---- COMMIT arrivals → commit_vote → finality ---------------------------
    cv = commit_vote + com_t
    crossed_c = (com_t > 0) & (cv >= cfg.pbft_commit_need)  # pbft-node.cc:248
    if clean:
        crossed_c = crossed_c & ~committed_w
    commit_vote = jnp.where(crossed_c, 0, cv)
    first_commit = crossed_c & ~committed_w
    committed_w = committed_w | crossed_c
    block_num = state.block_num + crossed_c.sum(axis=1)
    # exact mode: an unknown tenant can only be window w itself (identity map)
    eff_sid = jnp.where(slot_id >= 0, slot_id, windows[None, :] if exact else -1)
    unattributed = state.unattributed + (first_commit & (eff_sid < 0)).sum(axis=1)
    slot_commits, slot_commit_tick = _scatter_window_events(
        state.slot_commits, state.slot_commit_tick, None,
        first_commit, eff_sid, t, s,
    )

    # ---- timers: leader block broadcast every 50 ms (SendBlock) -------------
    # stop at 40 rounds (pbft-node.cc:407). The reference's n_round is
    # process-global (quirk #10); the per-node analog of global round progress
    # is the sequence number next_n, so a post-view-change leader continues
    # the count instead of restarting it.
    send_block = (
        is_block_tick
        & (leader == ids)
        & (next_n < min(cfg.pbft_max_rounds, s))
        & state.alive
    )
    own_w = next_n % w
    own_onehot = (windows[None, :] == own_w[:, None]) & send_block[:, None]
    # the proposer learns its own window's tenant (it never hears its own
    # PRE_PREPARE); in exact mode the counters survive for the same reason
    # as at pp arrival above (identity windows — e.g. a post-view-change
    # leader re-proposing an in-flight slot must not discard its votes)
    slot_id = jnp.where(own_onehot, next_n[:, None], slot_id)
    if not exact:
        prepare_vote = jnp.where(own_onehot, 0, prepare_vote)
        commit_vote = jnp.where(own_onehot, 0, commit_vote)
        prep_sent = prep_sent & ~own_onehot
        committed_w = committed_w & ~own_onehot
    pp_val = own_onehot.astype(jnp.int32) * (next_n[:, None] + 1)
    k_pp = chan_key(tkey, Channel.DELAY_BCAST2)
    if queued:
        # serial-pipe send (engine.cpp link_enqueue): the packet reaches the
        # (leader -> j) link after its random scheduling delay d_j - prop,
        # transmission starts when the link frees, occupies it for ser, then
        # propagates.  A single block sender is guaranteed (no drops ->
        # consistent leader beliefs; enforced by runner._reject_cpp_only),
        # so sender-side scalars globalize with pmax.
        val_sent = jnp.max(jnp.where(send_block, next_n + 1, 0))
        sender = jnp.max(jnp.where(send_block, ids, -1))
        if axis is not None:
            val_sent = jax.lax.pmax(val_sent, axis)
            sender = jax.lax.pmax(sender, axis)
        dest = (val_sent > 0) & (ids != sender)  # crashed peers still get
        # the packet (C++ bcast sends to all); they ignore it at pop time
        d_j = jax.random.randint(
            dv._shard_key(k_pp, axis), (n_loc,), lo, hi, jnp.int32
        )
        link_at = t + d_j - prop
        start = jnp.maximum(link_at, link_busy)
        delivery = start + ser + prop
        link_busy = jnp.where(dest, start + ser, link_busy)
        # enqueue into the first FREE slot (post-pop), never an occupied one:
        # with the FIFO sized to min(max_rounds, max_slots) the occupancy —
        # bounded by the serial-pipe backlog divided by ser, plus in-flight
        # entries — can never fill it, so no undelivered block is ever
        # silently clobbered (delivery matches on ppq_tick == t, so slot
        # order is irrelevant)
        q = ppq_tick.shape[1]
        free = ppq_tick == _NEVER  # [N, Q]
        first_free = jnp.argmax(free, axis=1)
        oh_q = (
            (jnp.arange(q)[None, :] == first_free[:, None])
            & dest[:, None]
            & free
        )
        ppq_tick = jnp.where(oh_q, delivery[:, None], ppq_tick)
        ppq_val = jnp.where(oh_q, val_sent, state.ppq_val)
    else:
        ppq_val = state.ppq_val
    if queued:
        pass  # blocks already enqueued on the serial pipes; ring untouched
    elif gossip:
        # origin injection (TTL = gossip_hops) + this tick's relays, one
        # flood push over the out-edges; every hop re-serializes the block
        # (store-and-forward), hence the ser term on each leg
        h_enc = cfg.gossip_hops + 1
        origin_enc = (pp_val * h_enc + cfg.gossip_hops) * (pp_val > 0)
        # the proposer must never process its own announcement (the reference
        # leader never hears its own PRE_PREPARE); self-loop edges exist in
        # the random digraph, so mark the origin's copy as already seen
        seen_pp = jnp.maximum(seen_pp, origin_enc)
        pp_out = jnp.maximum(origin_enc, pp_fwd)
        pp_contrib = gated(
            (pp_out > 0).any(),
            lambda: dv.gossip_fwd(k_pp, pp_out, nbrs_loc, n, lo, hi, drop,
                                  axis=axis, impl=eimpl),
            zeros_w,
            axis,
        )
    elif kreg:
        pp_contrib = gated(
            send_block.any(),
            lambda: (
                gd.bcast_window_value_max_stat_kreg(
                    k_pp, pp_val, nbr_in_loc, ow_probs, drop, axis=axis,
                    xg=exchange)
                if stat else
                gd.bcast_window_value_max_kreg(
                    k_pp, pp_val, nbr_in_loc, ids, lo, hi, drop, axis=axis,
                    impl=eimpl, xg=exchange)
            ),
            zeros_w,
            axis,
        )
    elif stat:
        pp_contrib = gated(
            send_block.any(),
            lambda: dv.bcast_window_value_max_stat(k_pp, pp_val, ow_probs, drop,
                                                   axis=axis),
            zeros_w,
            axis,
        )
    else:
        pp_contrib = gated(
            send_block.any(),
            lambda: dv.bcast_window_value_max_dense(k_pp, pp_val, lo, hi, drop,
                                                    axis=axis, impl=eimpl),
            zeros_w,
            axis,
        )
    if not queued:
        pp = ring_push_max(pp, t, lo + ser, pp_contrib)
    rounds_sent = state.rounds_sent + send_block
    (slot_propose_tick,) = _scatter_window_events(
        None, None, state.slot_propose_tick,
        own_onehot, jnp.where(own_onehot, next_n[:, None], -1), t, s,
    )
    next_n = next_n + send_block

    # ---- random view change (P = 1/100 per leader round) --------------------
    k_u = chan_key(tkey, Channel.VIEW_CHANGE)
    if axis is not None:
        k_u = jax.random.fold_in(k_u, jax.lax.axis_index(axis))
    u = jax.random.randint(k_u, (n_loc,), 0, cfg.pbft_view_change_den)
    trigger = send_block & (u < cfg.pbft_view_change_num)
    new_leader = (leader + 1) % n  # rotation (pbft-node.cc:297)
    new_v = v + 1
    leader = jnp.where(trigger, new_leader, leader)
    v = jnp.where(trigger, new_v, v)
    view_changes = state.view_changes + trigger
    enc = jnp.where(trigger, new_v * n + new_leader + 1, 0)
    k_vc = chan_key(tkey, Channel.DELAY_REPLY)
    zeros_flat = jnp.zeros((hi - lo, n_loc), jnp.int32)
    if gossip:
        h_enc = cfg.gossip_hops + 1
        vc_origin = (enc * h_enc + cfg.gossip_hops) * (enc > 0)
        seen_vc = jnp.maximum(seen_vc, vc_origin)  # self-loop guard
        vc_out = jnp.maximum(vc_origin, vc_fwd)
        vc_contrib = gated(
            (vc_out > 0).any(),
            lambda: dv.gossip_fwd(k_vc, vc_out[:, None], nbrs_loc, n, lo, hi,
                                  drop, axis=axis, impl=eimpl)[:, :, 0],
            zeros_flat,
            axis,
        )
    elif kreg:
        vc_contrib = gated(
            trigger.any(),
            lambda: (
                gd.bcast_value_max_stat_kreg(k_vc, enc, nbr_in_loc, ow_probs,
                                             drop, axis=axis, xg=exchange)
                if stat else
                gd.bcast_value_max_kreg(k_vc, trigger, enc, nbr_in_loc, ids,
                                        lo, hi, drop, axis=axis, impl=eimpl,
                                        xg=exchange)
            ),
            zeros_flat,
            axis,
        )
    elif stat:
        vc_contrib = gated(
            trigger.any(),
            lambda: dv.bcast_value_max_stat(k_vc, enc, ow_probs, drop, axis=axis),
            zeros_flat,
            axis,
        )
    else:
        vc_contrib = gated(
            trigger.any(),
            lambda: dv.bcast_value_max_dense(k_vc, trigger, enc, lo, hi, drop,
                                             axis=axis, impl=eimpl),
            zeros_flat,
            axis,
        )
    vc = ring_push_max(vc, t, lo, vc_contrib)

    state = state.replace(
        seen_pp=seen_pp,
        seen_vc=seen_vc,
        link_busy=link_busy,
        ppq_tick=ppq_tick,
        ppq_val=ppq_val,
        v=v,
        leader=leader,
        next_n=next_n,
        rounds_sent=rounds_sent,
        slot_id=slot_id,
        prepare_vote=prepare_vote,
        commit_vote=commit_vote,
        prep_sent=prep_sent,
        committed_w=committed_w,
        block_num=block_num,
        unattributed=unattributed,
        view_changes=view_changes,
        slot_commits=slot_commits,
        slot_commit_tick=slot_commit_tick,
        slot_propose_tick=slot_propose_tick,
    )
    bufs = PbftBufs(pp=pp, prep_rt=prep_rt, commit=commit, vc=vc)
    return state, bufs


def metrics(cfg, state: PbftState) -> dict:
    """Reproduce the reference's measurement surface (SURVEY.md §5): per-block
    commit events with times (pbft-node.cc:259), rounds sent (:408), view
    changes (:278) — as structured host-side values, recomputed from the
    per-slot accumulators (identical to the per-(node,slot) bookkeeping in
    exact mode; windowed mode trades the full table for O(S) summaries)."""
    alive = np.asarray(state.alive)
    n_alive = int(alive.sum())
    commits = np.asarray(state.slot_commits)
    commit_tick = np.asarray(state.slot_commit_tick)
    propose_tick = np.asarray(state.slot_propose_tick)
    proposed = propose_tick < int(_NEVER)
    # a slot is final when every alive node finalized it (>= guards the
    # mixed sim's fluctuating membership: a node can finalize, then die)
    per_slot_done = (commits >= max(n_alive, 1)) & (n_alive > 0) & proposed
    n_final = int(per_slot_done.sum())
    last = commit_tick[per_slot_done].max() if n_final else -1
    # time-to-finality per block: last commit tick − the tick the block was
    # actually proposed (a view change stalls the pipeline, so
    # (slot+1)*interval would undercount after one)
    rounds = int(np.asarray(state.next_n).max())
    ttf = [
        float(commit_tick[s] - propose_tick[s])
        for s in range(min(rounds, len(commits)))
        if per_slot_done[s]
    ]
    # safety: a slot some alive node finalized although NO node ever proposed
    # it can only come from forged votes reaching quorum (quirk #2: the
    # reference's no-dedup counting lets f Byzantine nodes muster f*copies
    # votes; the 2f1 rule makes this impossible for f <= (n-1)//3)
    forged_commits = int(((commits > 0) & ~proposed).sum())
    unattributed = int(np.asarray(state.unattributed).sum())
    return {
        "protocol": "pbft",
        "n": cfg.n,
        "rounds_sent": rounds,
        "forged_commits": forged_commits,
        "unattributed_commits": unattributed,
        "leader_rounds_max": int(np.asarray(state.rounds_sent).max()),
        "blocks_final_all_nodes": n_final,
        "block_num_max": int(np.asarray(state.block_num).max()),
        "view_changes": int(np.asarray(state.view_changes).sum()),
        "last_commit_ms": float(last),
        "mean_time_to_finality_ms": float(np.mean(ttf)) if ttf else -1.0,
        # agreement is structural in this design: the PRE_PREPARE channel
        # carries the slot id (= the reference's val, generateTX
        # pbft-node.cc:92) and commits bind to it; the failure modes that
        # remain observable are forged/unattributed commits, reported above
        "agreement_ok": bool(forged_commits == 0 and unattributed == 0),
    }
