"""PBFT consensus — tensorized state machine.

Re-design of the reference's ``PbftNode`` (pbft/pbft-node.h:19, pbft-node.cc):
a leader-driven 3-phase commit where the leader broadcasts PRE_PREPARE blocks
every 50 ms (SendBlock, pbft-node.cc:372-411), replicas broadcast PREPARE on
receipt (pbft-node.cc:193-211), every PREPARE is answered with a unicast
PREPARE_RES SUCCESS (pbft-node.cc:212-221), a node crossing
``prepare_vote >= N/2`` broadcasts COMMIT (pbft-node.cc:223-239), and a node
crossing ``commit_vote > N/2`` commits the block (pbft-node.cc:241-264 — the
finality measurement point, line 259).  A leader round has a 1/100 chance of a
view change rotating the leader (pbft-node.cc:294-303,401-403).

Tensorization (SURVEY.md §7): one tick = 1 ms for all N nodes at once.

- The per-``(v,n)`` vote table ``TX tx[1000]`` (pbft-node.h:50-56) becomes
  ``[N, S]`` counter arrays.
- PREPARE handling is *short-circuited*: a peer's reply never depends on its
  state, so a PREPARE broadcast by node i at tick t directly schedules N-1
  PREPARE_RES arrivals at i over the request+reply delay distribution.
- COMMIT / PRE_PREPARE are slot-keyed aggregate broadcasts.
- The reference's process-global ``v, n, val, n_round`` (pbft-node.cc:24-30,
  quirk #10 in SURVEY.md §2) become per-node state; a new leader infers the
  next sequence number from the highest PRE_PREPARE slot it has seen.
- Echo-back (quirk #1) is a deliberate divergence shared by the JAX backend
  and the C++ reference engine (engine.cpp:29-31): every echoed packet lands
  in the reference's "wrong msg" default branch, so dropping the echoes
  changes traffic volume but no protocol outcome; differential tests pin the
  echo-off behavior on both backends.

Fidelity modes: ``reference`` keeps N/2 thresholds and reset-on-threshold
counters (quirks #2, #4 — duplicate commits possible); ``clean`` latches each
(node, slot) so a slot commits exactly once.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from blockchain_simulator_tpu.models.base import fault_masks, gated
from blockchain_simulator_tpu.ops import delay as delay_ops
from blockchain_simulator_tpu.ops import delivery as dv
from blockchain_simulator_tpu.ops.ring import ring_pop, ring_push_add, ring_push_max
from blockchain_simulator_tpu.utils.prng import Channel, chan_key


@struct.dataclass
class PbftState:
    v: jax.Array            # [N] current view (init 1, pbft-node.cc:101)
    leader: jax.Array       # [N] believed leader (init 0)
    next_n: jax.Array       # [N] next sequence number a leader would use
    rounds_sent: jax.Array  # [N] blocks broadcast as leader (global n_round analog)
    tx_val: jax.Array       # [N, S] stored block value per slot (tx[n].val)
    prepare_vote: jax.Array  # [N, S]
    commit_vote: jax.Array   # [N, S]
    prep_sent: jax.Array     # [N, S] bool — COMMIT already broadcast (clean latch)
    committed: jax.Array     # [N, S] bool — slot finalized
    commit_tick: jax.Array   # [N, S] first commit tick, -1 = never
    propose_tick: jax.Array  # [N, S] tick this node broadcast slot s as leader,
    # -1 = never (time-to-finality baseline; a view change can stall the
    # pipeline, so slot k is NOT necessarily proposed at (k+1)*interval)
    block_num: jax.Array     # [N] commits counted (duplicates possible in
    # reference fidelity, matching pbft-node.cc:260)
    view_changes: jax.Array  # [N] view changes initiated
    alive: jax.Array         # [N] bool fault mask
    honest: jax.Array        # [N] bool fault mask


@struct.dataclass
class PbftBufs:
    pp: jax.Array       # [D, N, S] PRE_PREPARE arrival counts
    prep_rt: jax.Array  # [D, N, S] PREPARE_RES (round-trip) reply counts
    commit: jax.Array   # [D, N, S] COMMIT arrival counts
    vc: jax.Array       # [D, N] VIEW_CHANGE, encoded v*N + leader + 1, max


def init(cfg, key=None):
    n, s = cfg.n, cfg.pbft_max_slots
    d = cfg.ring_depth
    alive, honest = fault_masks(cfg, n)
    zi = lambda *sh: jnp.zeros(sh, jnp.int32)
    zb = lambda *sh: jnp.zeros(sh, bool)
    state = PbftState(
        v=jnp.ones((n,), jnp.int32),
        leader=zi(n),
        next_n=zi(n),
        rounds_sent=zi(n),
        tx_val=jnp.full((n, s), -1, jnp.int32),
        prepare_vote=zi(n, s),
        commit_vote=zi(n, s),
        prep_sent=zb(n, s),
        committed=zb(n, s),
        commit_tick=jnp.full((n, s), -1, jnp.int32),
        propose_tick=jnp.full((n, s), -1, jnp.int32),
        block_num=zi(n),
        view_changes=zi(n),
        alive=alive,
        honest=honest,
    )
    bufs = PbftBufs(pp=zi(d, n, s), prep_rt=zi(d, n, s), commit=zi(d, n, s), vc=zi(d, n))
    return state, bufs




def step(cfg, state: PbftState, bufs: PbftBufs, t, tkey):
    n, s = cfg.n, cfg.pbft_max_slots
    axis = cfg.mesh_axis
    lo, hi = cfg.one_way_range()
    rt_lo, rt_hi = cfg.roundtrip_range()
    drop = cfg.faults.drop_prob
    clean = cfg.fidelity == "clean"
    stat = cfg.delivery == "stat"
    ow_probs = delay_ops.uniform_probs(lo, hi)
    rt_probs = delay_ops.roundtrip_probs(lo, hi)
    n_loc = state.v.shape[0]
    # global node ids of this shard's rows (== arange(N) unsharded)
    ids = dv._global_ids(n_loc, axis)
    slots = jnp.arange(s)

    # ---- pop this tick's arrivals; crashed nodes process nothing ------------
    pp_t, pp = ring_pop(bufs.pp, t)
    prep_t, prep_rt = ring_pop(bufs.prep_rt, t)
    com_t, commit = ring_pop(bufs.commit, t)
    vc_t, vc = ring_pop(bufs.vc, t)
    am = state.alive.astype(jnp.int32)
    pp_t, prep_t, com_t = pp_t * am[:, None], prep_t * am[:, None], com_t * am[:, None]
    vc_t = vc_t * am

    # ---- VIEW_CHANGE arrivals: adopt (v, leader) (pbft-node.cc:271-280) -----
    has_vc = vc_t > 0
    v = jnp.where(has_vc, (vc_t - 1) // n, state.v)
    leader = jnp.where(has_vc, (vc_t - 1) % n, state.leader)

    # ---- PRE_PREPARE arrivals: store value, then broadcast PREPARE ----------
    got_pp = pp_t > 0  # [N, S]
    # the reference block header carries val == n (generateTX, pbft-node.cc:92)
    tx_val = jnp.where(got_pp, slots[None, :], state.tx_val)
    seen_hi = jnp.max(jnp.where(got_pp, slots[None, :] + 1, 0), axis=1)
    next_n = jnp.maximum(state.next_n, seen_hi)

    # PREPARE broadcast → short-circuited round-trip PREPARE_RES replies.
    # Only honest, alive peers contribute SUCCESS votes (Byzantine nodes flip
    # their votes to FAILED, which the counter ignores, pbft-node.cc:227).
    voters = state.alive & state.honest
    k_rt = chan_key(tkey, Channel.DELAY_ROUNDTRIP)
    prep_active = got_pp.any(axis=1)
    if stat:
        n_voters = voters.astype(jnp.int32).sum()
        if axis is not None:
            n_voters = jax.lax.psum(n_voters, axis)
        rt_counts = gated(
            prep_active.any(),
            lambda: dv.roundtrip_reply_counts_stat(
                k_rt, prep_active, n_voters - voters.astype(jnp.int32), rt_probs,
                drop, axis=axis,
            ),
            jnp.zeros((len(rt_probs), n_loc), jnp.int32),
            axis,
        )
    else:
        rt_counts = gated(
            prep_active.any(),
            lambda: dv.roundtrip_reply_counts_dense(
                k_rt, prep_active, lo, hi, drop, peer_mask=voters, axis=axis
            ),
            jnp.zeros((len(rt_probs), n_loc), jnp.int32),
            axis,
        )
    # replies are per broadcast, i.e. per active (node, slot)
    prep_rt = ring_push_add(
        prep_rt, t, rt_lo, rt_counts[:, :, None] * got_pp.astype(jnp.int32)[None, :, :]
    )

    # ---- PREPARE_RES arrivals → prepare_vote → COMMIT broadcast -------------
    pv = state.prepare_vote + prep_t
    crossed_p = (prep_t > 0) & (pv >= cfg.pbft_prepare_need)  # pbft-node.cc:231
    if clean:
        crossed_p = crossed_p & ~state.prep_sent
    prep_sent = state.prep_sent | crossed_p
    prepare_vote = jnp.where(crossed_p, 0, pv)  # reset on threshold (quirk #4)

    bt = cfg.pbft_block_interval_ms
    is_block_tick = (t % bt == 0) & (t > 0)
    commit_send = crossed_p & (state.alive & state.honest)[:, None]
    commit_mat = commit_send.astype(jnp.int32)
    if cfg.faults.byz_forge and cfg.faults.n_byzantine > 0:
        # Active attack: Byzantine nodes flood COMMIT votes for the
        # never-proposed last slot.  Under "n2" there is no per-sender dedup
        # (quirk #2): every copy of every re-send lands in the accumulating
        # counter, so f forgers cross any threshold eventually.  A "2f1"
        # receiver counts at most one vote per sender *ever*, which is
        # equivalent to each forger's flood collapsing to a single send.
        if cfg.quorum_rule == "2f1":
            fire, copies = jnp.equal(t, bt), 1
        else:
            fire, copies = is_block_tick, cfg.faults.byz_copies
        forgers = (state.alive & ~state.honest).astype(jnp.int32) * jnp.int32(fire)
        commit_mat = commit_mat.at[:, s - 1].add(forgers * copies)
    k_cm = chan_key(tkey, Channel.DELAY_BCAST)
    zeros_slots = jnp.zeros((hi - lo, n_loc, s), jnp.int32)
    if stat:
        cm_contrib = gated(
            (commit_mat > 0).any(),
            lambda: dv.bcast_slots_stat(k_cm, commit_mat, ow_probs, drop, axis=axis),
            zeros_slots,
            axis,
        )
    else:
        cm_contrib = gated(
            (commit_mat > 0).any(),
            lambda: dv.bcast_slots_dense(k_cm, commit_mat, lo, hi, drop, axis=axis),
            zeros_slots,
            axis,
        )
    commit = ring_push_add(commit, t, lo, cm_contrib)

    # ---- COMMIT arrivals → commit_vote → finality ---------------------------
    cv = state.commit_vote + com_t
    crossed_c = (com_t > 0) & (cv >= cfg.pbft_commit_need)  # pbft-node.cc:248
    if clean:
        crossed_c = crossed_c & ~state.committed
    commit_vote = jnp.where(crossed_c, 0, cv)
    commit_tick = jnp.where(
        crossed_c & (state.commit_tick < 0), jnp.int32(t), state.commit_tick
    )
    committed = state.committed | crossed_c
    block_num = state.block_num + crossed_c.sum(axis=1)

    # ---- timers: leader block broadcast every 50 ms (SendBlock) -------------
    # stop at 40 rounds (pbft-node.cc:407). The reference's n_round is
    # process-global (quirk #10); the per-node analog of global round progress
    # is the sequence number next_n, so a post-view-change leader continues
    # the count instead of restarting it.
    send_block = (
        is_block_tick
        & (leader == ids)
        & (next_n < min(cfg.pbft_max_rounds, s))
        & state.alive
    )
    pp_slot_mat = jax.nn.one_hot(next_n, s, dtype=jnp.int32) * send_block[:, None]
    ser = cfg.serialization_ticks(cfg.pbft_block_bytes)
    k_pp = chan_key(tkey, Channel.DELAY_BCAST2)
    if stat:
        pp_contrib = gated(
            send_block.any(),
            lambda: dv.bcast_slots_stat(k_pp, pp_slot_mat, ow_probs, drop, axis=axis),
            zeros_slots,
            axis,
        )
    else:
        pp_contrib = gated(
            send_block.any(),
            lambda: dv.bcast_slots_dense(k_pp, pp_slot_mat, lo, hi, drop, axis=axis),
            zeros_slots,
            axis,
        )
    pp = ring_push_add(pp, t, lo + ser, pp_contrib)
    rounds_sent = state.rounds_sent + send_block
    propose_tick = jnp.where(
        (pp_slot_mat > 0) & (state.propose_tick < 0), jnp.int32(t), state.propose_tick
    )
    next_n = next_n + send_block

    # ---- random view change (P = 1/100 per leader round) --------------------
    k_u = chan_key(tkey, Channel.VIEW_CHANGE)
    if axis is not None:
        k_u = jax.random.fold_in(k_u, jax.lax.axis_index(axis))
    u = jax.random.randint(k_u, (n_loc,), 0, cfg.pbft_view_change_den)
    trigger = send_block & (u < cfg.pbft_view_change_num)
    new_leader = (leader + 1) % n  # rotation (pbft-node.cc:297)
    new_v = v + 1
    leader = jnp.where(trigger, new_leader, leader)
    v = jnp.where(trigger, new_v, v)
    view_changes = state.view_changes + trigger
    enc = jnp.where(trigger, new_v * n + new_leader + 1, 0)
    k_vc = chan_key(tkey, Channel.DELAY_REPLY)
    zeros_flat = jnp.zeros((hi - lo, n_loc), jnp.int32)
    if stat:
        vc_contrib = gated(
            trigger.any(),
            lambda: dv.bcast_value_max_stat(k_vc, enc, ow_probs, drop, axis=axis),
            zeros_flat,
            axis,
        )
    else:
        vc_contrib = gated(
            trigger.any(),
            lambda: dv.bcast_value_max_dense(k_vc, trigger, enc, lo, hi, drop, axis=axis),
            zeros_flat,
            axis,
        )
    vc = ring_push_max(vc, t, lo, vc_contrib)

    state = state.replace(
        v=v,
        leader=leader,
        next_n=next_n,
        rounds_sent=rounds_sent,
        tx_val=tx_val,
        prepare_vote=prepare_vote,
        commit_vote=commit_vote,
        prep_sent=prep_sent,
        committed=committed,
        commit_tick=commit_tick,
        propose_tick=propose_tick,
        block_num=block_num,
        view_changes=view_changes,
    )
    bufs = PbftBufs(pp=pp, prep_rt=prep_rt, commit=commit, vc=vc)
    return state, bufs


def metrics(cfg, state: PbftState) -> dict:
    """Reproduce the reference's measurement surface (SURVEY.md §5): per-block
    commit events with times (pbft-node.cc:259), rounds sent (:408), view
    changes (:278) — as structured host-side values."""
    committed = np.asarray(state.committed)
    ticks = np.asarray(state.commit_tick)
    alive = np.asarray(state.alive)
    proposed = np.asarray(state.propose_tick)  # [N, S], -1 = never
    never_proposed = (proposed < 0).all(axis=0)
    done = committed[alive]
    if done.shape[0] == 0:  # fully-crashed cluster: nothing can finalize
        per_slot_done = np.zeros(done.shape[1], bool)
    else:
        # forged slots (finalized but never proposed) are counted separately
        per_slot_done = done.all(axis=0) & ~never_proposed
    n_final = int(per_slot_done.sum())
    last = ticks[alive][:, per_slot_done].max() if n_final else -1
    # time-to-finality per block: last commit tick − the tick the block was
    # actually proposed (recorded at broadcast; a view change stalls the
    # pipeline, so (slot+1)*interval would undercount after one)
    rounds = int(np.asarray(state.next_n).max())
    ttf = []
    for slot in range(rounds):
        if per_slot_done[slot]:
            pt = proposed[:, slot]
            pt = pt[pt >= 0]
            if pt.size:
                ttf.append(float(ticks[alive, slot].max()) - float(pt.min()))
    # safety: a slot some alive node finalized although NO node ever proposed
    # it can only come from forged votes reaching quorum (quirk #2: the
    # reference's no-dedup counting lets f Byzantine nodes muster f*copies
    # votes; the 2f1 rule makes this impossible for f <= (n-1)//3)
    any_committed = committed[alive].any(axis=0) if alive.any() else np.zeros(
        committed.shape[1], bool
    )
    forged_commits = int((any_committed & never_proposed).sum())
    return {
        "protocol": "pbft",
        "n": cfg.n,
        "rounds_sent": rounds,
        "forged_commits": forged_commits,
        "leader_rounds_max": int(np.asarray(state.rounds_sent).max()),
        "blocks_final_all_nodes": n_final,
        "block_num_max": int(np.asarray(state.block_num).max()),
        "view_changes": int(np.asarray(state.view_changes).sum()),
        "last_commit_ms": float(last),
        "mean_time_to_finality_ms": float(np.mean(ttf)) if ttf else -1.0,
        # safety: one value per slot across nodes that stored one (the leader
        # never hears its own PRE_PREPARE, so its slot value stays unset — the
        # reference leader likewise commits an uninitialized tx[n].val)
        "agreement_ok": bool(
            all(
                len(np.unique(vals[vals >= 0])) <= 1
                for slot in range(rounds)
                if per_slot_done[slot]
                for vals in [np.asarray(state.tx_val)[alive, slot]]
            )
        ),
    }
