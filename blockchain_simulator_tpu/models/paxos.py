"""Single-decree Paxos — tensorized state machine.

Re-design of the reference's ``PaxosNode`` (paxos/paxos-node.h:19,
paxos-node.cc): a ticket (ballot) / propose / commit three-phase protocol where
every node is an acceptor and nodes 0..2 concurrently act as proposers from
t=0 (paxos-node.cc:136-138).  Reference call stack (SURVEY.md §3.4):

- ``requireTicket`` (paxos-node.cc:511-518): ``ticket += 1``, broadcast
  REQUEST_TICKET ``[0, ticket]`` with per-peer random delay U[0,50) ms
  (paxos-node.cc:397-400).
- acceptor REQUEST_TICKET: promise iff ``t > t_max`` (then ``t_max = t``),
  reply ``[RESPONSE_TICKET, SUCCESS, command]`` / ``[.., FAILED]``
  (paxos-node.cc:177-197).
- acceptor REQUEST_PROPOSE ``[1, t, c]``: accept iff ``t == t_max`` (then
  ``command = c; t_store = t``) (paxos-node.cc:199-221).
- acceptor REQUEST_COMMIT ``[2, t, c]``: execute iff ``t == t_store &&
  c == command`` (latch ``isCommit``; keeps replying SUCCESS)
  (paxos-node.cc:222-247).
- proposer RESPONSE_*: one *shared* ``vote_success``/``vote_failed`` counter
  pair counts replies of *all three* response types; the window closes when
  ``vote_success + vote_failed == N-2`` exactly and the action (send next
  phase's request / log CLIENT COMMIT SUCCESS / retry ``requireTicket``) is
  chosen by the *type of the reply that closed the window* with threshold
  ``vote_success >= N/2`` (paxos-node.cc:248-353).

Quirk fidelity (SURVEY.md §2 quirks #7/#8): the reference's broadcast loop
increments the peer iterator *before* use (paxos-node.cc:478-496), skipping the
first peer (node 0 for senders > 0, node 1 for sender 0) and dereferencing
``end()`` — so every broadcast reaches exactly N-2 valid peers, which is why
the ``N-2`` reply window closes at all.  ``fidelity="reference"`` models
exactly that: requests skip the sender's first peer, shared cross-phase
counters, ``>= N/2`` threshold, window closes on crossing ``N-2`` cumulative
replies (the strict ``==`` of the serial original is relaxed to a crossing
check because a tick can deliver several replies at once — documented
divergence).  ``fidelity="clean"`` fixes the protocol: full N-1 broadcast,
per-phase counters keyed to the proposer's phase register, the proposer
processes its own request as an acceptor (self-promise/self-accept — real
Paxos; the reference only gets this accidentally through its echo loop),
advance as soon as supporters reach ``N/2 + 1`` (a true majority of all N
acceptors including self, so any two quorums intersect), retry only on a
jittered per-window timeout (``paxos_retry_timeout_ms`` — without a timeout a
single dropped reply wedges a proposer forever; timeout-only retry also keeps
windows temporally disjoint so stale replies never pollute a fresh quorum
count), and promise replies carry ``t_store`` so the proposer
adopts the command with the *highest* store ticket (real Paxos adoption; the
reference adopts whatever command byte rides the window-closing reply,
paxos-node.cc:264-266, including FAILED replies whose command byte is
uninitialized stack memory — behavior we do not reproduce).

Echo-back (quirk #1, paxos-node.cc:158) is not modeled anywhere in this
framework — neither here nor in the C++ reference engine (engine.cpp:29-31
lists it as a deliberate, shared divergence): reflecting every packet to its
sender makes packets ping-pong forever (each reflection is itself reflected),
so the upstream event queue never drains, and nothing meaningful depends on
the echoes (they land in the "wrong msg" default branch).  Differential tests
therefore compare both backends with echo off (tests/test_differential.py).

Tensorization: proposer fan-in is O(P) with P = ``paxos_n_proposers`` (3), so
all channels are identity-preserving ``[.., N, P]`` tensors and delivery is
O(N·P) per tick in *both* delivery modes (``cfg.delivery`` is ignored — there
is no O(N²) structure to aggregate away).  Acceptor processing of concurrent
same-tick requests is serialized in proposer order 0..P-1 (statically
unrolled), a deterministic stand-in for the reference's arrival-order
processing.  Retries cap at ``paxos_max_ticket`` (the reference's single-char
codec would corrupt beyond '0'+9 anyway, quirk #11).

Gossip topology (``topology="gossip"``, BASELINE config 3): requests are not
broadcast — they *flood* over a random k-out digraph (ops/topology.py) with a
hop TTL.  Channel values carry ``encoded * H + hops_left`` (H = gossip_hops+1,
so a higher ticket always dominates in the max-combine regardless of TTL); a
node that sees a new request value (per-proposer monotone ``seen`` table —
request encodings strictly increase per proposer, which is what makes
value-dedup sound) processes it as an acceptor and replies *directly* to the
proposer (response overlay — replies are point-to-point in the protocol;
gossip is for dissemination).  Forwarding triggers on any strictly better
*TTL-encoded* copy (same value, more hops left), so a fast many-hop path
delivering a nearly-expired copy first cannot permanently truncate the flood
— the later fresher copy still propagates.  Per-tick cost is O(N·deg·P).
Clean-fidelity window timeouts must cover the full flood + reply horizon
``(gossip_hops+2) * delay_hi`` — up to gossip_hops+1 flood legs (arrival TTLs
gossip_hops..0) plus the reply leg — validated in ``init`` so the
temporal-separation argument still holds.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from blockchain_simulator_tpu.models.base import fault_masks, gated
from blockchain_simulator_tpu.ops import delay as delay_ops
from blockchain_simulator_tpu.ops import delivery as dv
from blockchain_simulator_tpu.ops import topology
from blockchain_simulator_tpu.ops import gatherdeliv as gd
from blockchain_simulator_tpu.ops.ring import ring_pop, ring_push_add, ring_push_max
from blockchain_simulator_tpu.utils.prng import Channel, chan_key

# proposer phase register
PH_TICKET, PH_PROPOSE, PH_COMMIT, PH_DONE = 0, 1, 2, 3
PH_IDLE = -1  # non-proposer rows


@struct.dataclass
class PaxosState:
    # acceptor state (paxos-node.h:40-43)
    t_max: jax.Array      # [N] highest ticket promised
    command: jax.Array    # [N] stored command; -1 = 'e' empty sentinel
    t_store: jax.Array    # [N] ticket of the stored command
    is_commit: jax.Array  # [N] bool — command executed (latch)
    exec_tick: jax.Array  # [N] first execute tick, -1 = never
    # proposer state (paxos-node.h:45-52); rows >= P are inert
    ticket: jax.Array        # [N] current ticket (0 until first requireTicket)
    phase: jax.Array         # [N] PH_*; informational in reference fidelity
    vote_success: jax.Array  # [N]
    vote_failed: jax.Array   # [N]
    proposal: jax.Array      # [N] command to propose (init own id, may adopt)
    adopt_val: jax.Array     # [N] max promise encoding seen this window
    commit_tick: jax.Array   # [N] CLIENT COMMIT SUCCESS tick (-1 = never)
    gave_up: jax.Array       # [N] bool — retry budget exhausted
    window_deadline: jax.Array  # [N] clean-fidelity retry timeout tick
    seen_req: jax.Array      # [N, 3, P] gossip dedup: highest TTL-encoded
    # request copy seen per (channel, proposer); zeros and unused on full mesh
    alive: jax.Array
    honest: jax.Array


@struct.dataclass
class PaxosBufs:
    # requests, value-encoded and max-combined (0 = empty):
    #   req_ticket[d, i, p] = ticket
    #   req_propose/req_commit[d, i, p] = ticket*(n+1) + command + 1
    req_ticket: jax.Array   # [D, N, P]
    req_propose: jax.Array  # [D, N, P]
    req_commit: jax.Array   # [D, N, P]
    # responses, landing at proposer rows; last axis = response type
    # (0 ticket, 1 propose, 2 commit)
    resp_ok: jax.Array      # [D, N, 3] SUCCESS counts (add)
    resp_no: jax.Array      # [D, N, 3] FAILED counts (add)
    # promise payloads: t_store*(n+1) + command + 1, max-combined (0 = empty /
    # empty-command 'e' promise)
    resp_cmd: jax.Array     # [D, N]


def init(cfg, key=None):
    n, d, p = cfg.n, cfg.ring_depth, cfg.paxos_n_proposers
    if cfg.fidelity == "clean":
        _, rt_hi = cfg.roundtrip_range()
        horizon = rt_hi
        if cfg.topology == "gossip":
            # an origin send with TTL=gossip_hops can traverse gossip_hops+1
            # flood legs (arrival TTLs gossip_hops..0 all processed + replied)
            # plus the direct reply leg, each up to hi-1 ms
            horizon = (cfg.gossip_hops + 2) * cfg.one_way_range()[1]
        if cfg.paxos_retry_timeout_ms < horizon:
            raise ValueError(
                f"paxos_retry_timeout_ms={cfg.paxos_retry_timeout_ms} must be "
                f">= the max reply horizon ({horizon} ms): clean-fidelity "
                "correctness relies on abandoned windows draining before retry"
            )
    alive, honest = fault_masks(cfg, n)
    ids = jnp.arange(n)
    zi = lambda *sh: jnp.zeros(sh, jnp.int32)
    zb = lambda *sh: jnp.zeros(sh, bool)
    state = PaxosState(
        t_max=zi(n),
        command=jnp.full((n,), -1, jnp.int32),  # 'e' (paxos-node.cc:63)
        t_store=zi(n),
        is_commit=zb(n),
        exec_tick=jnp.full((n,), -1, jnp.int32),
        ticket=zi(n),
        phase=jnp.where(ids < p, PH_TICKET, PH_IDLE).astype(jnp.int32),
        vote_success=zi(n),
        vote_failed=zi(n),
        proposal=ids.astype(jnp.int32),  # proposal = '0'+m_id (paxos-node.cc:66)
        adopt_val=zi(n),
        commit_tick=jnp.full((n,), -1, jnp.int32),
        gave_up=zb(n),
        window_deadline=jnp.full((n,), 1 << 30, jnp.int32),
        seen_req=zi(n, 3, p),
        alive=alive,
        honest=honest,
    )
    bufs = PaxosBufs(
        req_ticket=zi(d, n, p),
        req_propose=zi(d, n, p),
        req_commit=zi(d, n, p),
        resp_ok=zi(d, n, 3),
        resp_no=zi(d, n, 3),
        resp_cmd=zi(d, n),
    )
    return state, bufs


def _req_contrib(key, val_local, lo, hi, drop, axis, ids, p, ref_skip,
                 impl="threefry", inmask=None):
    """Broadcast contribution for one request channel: local per-node request
    values (nonzero only at proposer rows) → [B, N_loc, P] value tensor for
    ``ring_push_max``.  ``ref_skip`` drops the sender's first peer (the
    reference's iterator bug, paxos-node.cc:478-496).  ``inmask`` ([N_loc,
    P] bool) restricts delivery to receivers whose kregular in-table
    contains the proposer (topo/spec.py) — paxos delivery is already
    O(N*P), so the overlay is a static reachability mask on the SAME delay
    draws: all-true at degree k = N-1, hence bit-equal to the full mesh."""
    n_loc = val_local.shape[0]
    val_g = dv._gather(val_local, axis)[:p]  # [P] global proposer values
    k = dv._shard_key(key, axis)
    d = delay_ops.sample_edge_delays(k, (n_loc, p), lo, hi, impl)
    prop_ids = jnp.arange(p)
    mask = (val_g[None, :] > 0) & (ids[:, None] != prop_ids[None, :])
    if inmask is not None:
        mask = mask & inmask
    if ref_skip:
        first_peer = jnp.where(prop_ids == 0, 1, 0)
        mask = mask & (ids[:, None] != first_peer[None, :])
    if drop > 0.0:
        keep = jax.random.bernoulli(
            jax.random.fold_in(k, 0x0D20), 1.0 - drop, (n_loc, p)
        )
        mask = mask & keep
    m = mask.astype(jnp.int32)
    return (
        (d[None] == dv._bucket_iota(lo, hi, d.ndim)).astype(jnp.int32)
        * (m * val_g[None, :])[None]
    )


def _gossip_fwd_contrib(key, fwd_vals, nbrs_loc, n_glob, lo, hi, drop, axis,
                        impl="threefry"):
    """TTL-flood forwarding for the three request channels — shared op
    (ops/delivery.gossip_fwd), P = proposer lanes here."""
    return dv.gossip_fwd(key, fwd_vals, nbrs_loc, n_glob, lo, hi, drop, axis,
                         impl=impl)


def _reply_contribs(key, ok_wire, no_wire, cmd_wire, lo, hi, drop, axis, ids, p,
                    impl="threefry"):
    """Unicast acceptor→proposer replies: per-(acceptor, proposer, type) wires
    → (ok [B, N_loc, 3], no [B, N_loc, 3], cmd [B, N_loc]) contributions at
    the *local* proposer rows.  Each reply is its own packet with its own delay
    draw (paxos-node.cc:405-446); the promise payload rides the type-0 reply.
    Sharded, counts psum / payloads pmax across shards (the repliers)."""
    n_loc = ok_wire.shape[0]
    k = dv._shard_key(key, axis)
    d = delay_ops.sample_edge_delays(k, (n_loc, p, 3), lo, hi, impl)
    if drop > 0.0:
        keep = jax.random.bernoulli(
            jax.random.fold_in(k, 0x0D21), 1.0 - drop, (n_loc, p, 3)
        ).astype(jnp.int32)
        ok_wire = ok_wire * keep
        no_wire = no_wire * keep
        cmd_wire = cmd_wire * keep[:, :, 0]
    # one broadcast compare per channel instead of nb masked passes over the
    # [N_loc, P, 3] wire tensors (integer reductions — bit-equal either way)
    hits = (d[None] == dv._bucket_iota(lo, hi, d.ndim)).astype(jnp.int32)
    ok_b = (hits * ok_wire[None]).sum(1)  # [B, P, 3]
    no_b = (hits * no_wire[None]).sum(1)
    cmd_b = (hits[:, :, :, 0] * cmd_wire[None]).max(1)  # [B, P]
    if axis is not None:
        ok_b = jax.lax.psum(ok_b, axis)
        no_b = jax.lax.psum(no_b, axis)
        cmd_b = jax.lax.pmax(cmd_b, axis)
    take = jnp.clip(ids, 0, p - 1)
    is_prop = (ids < p).astype(jnp.int32)
    return (
        ok_b[:, take, :] * is_prop[None, :, None],
        no_b[:, take, :] * is_prop[None, :, None],
        cmd_b[:, take] * is_prop[None, :],
    )


def step(cfg, state: PaxosState, bufs: PaxosBufs, t, tkey, *,
         topo_tables=None, exchange=None):
    n, p = cfg.n, cfg.paxos_n_proposers
    axis = cfg.mesh_axis
    lo, hi = cfg.one_way_range()
    drop = cfg.faults.drop_prob
    clean = cfg.fidelity == "clean"
    eimpl = cfg.eff_edge_sampler
    c_enc = n + 1  # encoding base: val = ticket * c_enc + command + 1
    n_loc = state.t_max.shape[0]
    ids = dv._global_ids(n_loc, axis)
    nb = hi - lo

    # ---- pop arrivals; crashed nodes process nothing ------------------------
    rt_t, req_ticket = ring_pop(bufs.req_ticket, t)
    rp_t, req_propose = ring_pop(bufs.req_propose, t)
    rc_t, req_commit = ring_pop(bufs.req_commit, t)
    ok_t, resp_ok = ring_pop(bufs.resp_ok, t)
    no_t, resp_no = ring_pop(bufs.resp_no, t)
    cmd_t, resp_cmd = ring_pop(bufs.resp_cmd, t)
    am = state.alive.astype(jnp.int32)
    rt_t, rp_t, rc_t = rt_t * am[:, None], rp_t * am[:, None], rc_t * am[:, None]
    ok_t, no_t = ok_t * am[:, None], no_t * am[:, None]
    cmd_t = cmd_t * am

    # ---- gossip decode: TTL values → new-request dedup + forward set --------
    gossip = cfg.topology == "gossip"
    # kregular overlay: requests reach only receivers whose in-table holds
    # the proposer (static [N_loc, P] reachability mask over the SAME
    # O(N*P) delivery — paxos has no N x N structure to sparsify); replies
    # stay point-to-point on the reverse edge, the same response-overlay
    # rule the gossip arm documents.  Clean-fidelity windows that cannot
    # reach a majority simply time out and retry until gave_up.
    kreg = cfg.topology == "kregular"
    inmask = None
    if kreg:
        # paxos never reads cross-row state through the tables (the inmask
        # below is row-local), so exchange mode only switches the row
        # indexing to the ids=None operand pass-through
        nbr_in_loc, _ = gd.local_tables(
            cfg, None if exchange is not None else ids, tables=topo_tables)
        inmask = (
            nbr_in_loc[:, :, None] == jnp.arange(p)[None, None, :]
        ).any(axis=1)  # [N_loc, P]
    seen_req = state.seen_req
    fwd_vals = None
    if gossip:
        h_enc = cfg.gossip_hops + 1
        nbrs_loc = jnp.take(
            jnp.asarray(topology.kregular_out_neighbors(n, cfg.degree, cfg.seed)),
            ids, axis=0,
        )
        fwd_vals, proc = [], []
        for ci, arr in enumerate((rt_t, rp_t, rc_t)):
            base, hops = arr // h_enc, arr % h_enc
            seen = seen_req[:, ci, :]
            # acceptors process each base value once (first sighting) ...
            new_base = (base > seen // h_enc) & state.alive[:, None]
            # ... but forward any strictly better TTL-encoded copy, so a
            # nearly-expired first arrival can't truncate the flood
            better = (arr > seen) & state.alive[:, None]
            proc.append(base * new_base)
            seen_req = seen_req.at[:, ci, :].max(arr * better)
            fwd_vals.append(
                (base * h_enc + jnp.maximum(hops - 1, 0)) * (better & (hops > 0))
            )
        rt_t, rp_t, rc_t = proc

    # ---- acceptor FSM: concurrent requests serialized in proposer order -----
    t_max, command, t_store = state.t_max, state.command, state.t_store
    is_commit, exec_tick = state.is_commit, state.exec_tick
    tk_ok, tk_no, prom = [], [], []
    for q in range(p):  # REQUEST_TICKET (paxos-node.cc:177-197)
        tk = rt_t[:, q]
        ok = (tk > 0) & (tk > t_max)
        prom.append(jnp.where(ok & (command >= 0), t_store * c_enc + command + 1, 0))
        t_max = jnp.where(ok, tk, t_max)
        tk_ok.append(ok)
        tk_no.append((tk > 0) & ~ok)
    pr_ok, pr_no = [], []
    for q in range(p):  # REQUEST_PROPOSE (paxos-node.cc:199-221)
        v = rp_t[:, q]
        tkt, cmd = v // c_enc, v % c_enc - 1
        ok = (v > 0) & (tkt == t_max)
        command = jnp.where(ok, cmd, command)
        t_store = jnp.where(ok, tkt, t_store)
        pr_ok.append(ok)
        pr_no.append((v > 0) & ~ok)
    cm_ok, cm_no = [], []
    for q in range(p):  # REQUEST_COMMIT (paxos-node.cc:222-247)
        v = rc_t[:, q]
        tkt, cmd = v // c_enc, v % c_enc - 1
        ok = (v > 0) & (tkt == t_store) & (cmd == command)
        exec_tick = jnp.where(ok & (exec_tick < 0), jnp.int32(t), exec_tick)
        is_commit = is_commit | ok
        cm_ok.append(ok)
        cm_no.append((v > 0) & ~ok)
    ok_wire = jnp.stack(
        [jnp.stack(tk_ok, 1), jnp.stack(pr_ok, 1), jnp.stack(cm_ok, 1)], axis=2
    ).astype(jnp.int32)  # [N_loc, P, 3]
    no_wire = jnp.stack(
        [jnp.stack(tk_no, 1), jnp.stack(pr_no, 1), jnp.stack(cm_no, 1)], axis=2
    ).astype(jnp.int32)
    # Byzantine acceptors flip their votes; only honest promises carry payloads
    hn = state.honest[:, None, None]
    ok_w = jnp.where(hn, ok_wire, no_wire)
    no_w = jnp.where(hn, no_wire, ok_wire)
    cmd_wire = jnp.stack(prom, 1) * state.honest[:, None].astype(jnp.int32)

    any_req = (rt_t > 0).any() | (rp_t > 0).any() | (rc_t > 0).any()
    k_r = chan_key(tkey, Channel.DELAY_REPLY)
    zeros_ok = jnp.zeros((nb, n_loc, 3), jnp.int32)
    zeros_cmd = jnp.zeros((nb, n_loc), jnp.int32)
    ok_c, no_c, cmd_c = gated(
        any_req,
        lambda: _reply_contribs(k_r, ok_w, no_w, cmd_wire, lo, hi, drop, axis,
                                ids, p, impl=eimpl),
        (zeros_ok, zeros_ok, zeros_cmd),
        axis,
    )
    resp_ok = ring_push_add(resp_ok, t, lo, ok_c)
    resp_no = ring_push_add(resp_no, t, lo, no_c)
    resp_cmd = ring_push_max(resp_cmd, t, lo, cmd_c)

    # ---- proposer FSM: response counting ------------------------------------
    adopt_val = jnp.maximum(state.adopt_val, cmd_t)
    vs, vf = state.vote_success, state.vote_failed
    active = (ids < p) & state.alive & ~state.gave_up

    if clean:
        # per-phase counters: only replies of the current phase's type count;
        # vs/vf include the proposer's own acceptor vote (cast at send time)
        ph = state.phase
        waiting = active & (ph >= PH_TICKET) & (ph <= PH_COMMIT)
        sel = jnp.clip(ph, 0, 2)
        arr_ok = jnp.take_along_axis(ok_t, sel[:, None], 1)[:, 0] * waiting
        arr_no = jnp.take_along_axis(no_t, sel[:, None], 1)[:, 0] * waiting
        vs, vf = vs + arr_ok, vf + arr_no
        majority = cfg.quorum + 1  # true majority of all n acceptors (incl.
        # self): any two quorums intersect
        advance = waiting & (vs >= majority)
        # retry ONLY by window timeout, never early on failure counts: the
        # timeout exceeds the maximum reply round trip (asserted in init), so
        # an abandoned window's in-flight replies have fully drained before
        # the next same-type window opens — stale replies can never
        # double-count into a fresh window's quorum (exactness by temporal
        # separation; reply channels carry no ticket identity to filter by)
        want_retry = waiting & ~advance & (jnp.int32(t) >= state.window_deadline)
        adv0 = advance & (ph == PH_TICKET)
        adv1 = advance & (ph == PH_PROPOSE)
        adv2 = advance & (ph == PH_COMMIT)
    else:
        # shared counters, window closes crossing N-2 cumulative replies, the
        # closing reply's type picks the action (paxos-node.cc:248-353);
        # intra-tick reply order is fixed ticket → propose → commit
        win = n - 2
        before = vs + vf
        arr = ok_t + no_t  # [N_loc, 3]
        cum0 = before + arr[:, 0]
        cum1 = cum0 + arr[:, 1]
        cum2 = cum1 + arr[:, 2]
        crossed = active & (before < win) & (cum2 >= win)
        ctype = jnp.where(cum0 >= win, 0, jnp.where(cum1 >= win, 1, 2))
        vs_at = (
            vs
            + ok_t[:, 0]
            + jnp.where(ctype >= 1, ok_t[:, 1], 0)
            + jnp.where(ctype >= 2, ok_t[:, 2], 0)
        )
        success = vs_at >= cfg.quorum  # vote_success >= N/2 (paxos-node.cc:259)
        adv0 = crossed & success & (ctype == 0)
        adv1 = crossed & success & (ctype == 1)
        adv2 = crossed & success & (ctype == 2)
        want_retry = crossed & ~success
        # counters reset at the crossing; replies of later types keep counting
        left_ok = jnp.where(
            ctype == 0, ok_t[:, 1] + ok_t[:, 2], jnp.where(ctype == 1, ok_t[:, 2], 0)
        )
        left_no = jnp.where(
            ctype == 0, no_t[:, 1] + no_t[:, 2], jnp.where(ctype == 1, no_t[:, 2], 0)
        )
        vs = jnp.where(crossed, left_ok, vs + ok_t.sum(1))
        vf = jnp.where(crossed, left_no, vf + no_t.sum(1))

    # adoption at ticket→propose: highest-t_store promise wins (clean Paxos);
    # the reference's adopt-from-closing-reply (paxos-node.cc:264-266) is
    # order-dependent UB we determinize the same way
    adopted_cmd = adopt_val % c_enc - 1
    proposal = jnp.where(adv0 & (adopt_val > 0), adopted_cmd, state.proposal)

    # CLIENT COMMIT SUCCESS (paxos-node.cc:339) — the measurement point
    commit_tick = jnp.where(
        adv2 & (state.commit_tick < 0), jnp.int32(t), state.commit_tick
    )

    # retry: requireTicket (paxos-node.cc:281,511) — ticket += 1, bounded
    can_retry = state.ticket < cfg.paxos_max_ticket
    retry = want_retry & can_retry
    gave_up = state.gave_up | (want_retry & ~can_retry)

    # first firing: nodes 0..P-1 schedule requireTicket at t=0
    # (paxos-node.cc:136-138); a designated client lane instead fires when
    # the simulated external client sends CLIENT_PROPOSE
    # (paxos-node.cc:357-361, cfg.paxos_client_node/_ms)
    fire0 = (jnp.int32(t) == 0) & (ids < p) & state.alive
    cn = cfg.paxos_client_node
    if cn >= 0:
        is_client = ids == cn
        fire0 = (fire0 & ~is_client) | (
            (jnp.int32(t) == cfg.paxos_client_ms) & is_client & state.alive
        )
    send_tk = fire0 | retry
    ticket = jnp.where(send_tk, state.ticket + 1, state.ticket)

    new_window = send_tk | adv0 | adv1
    if clean:
        phase = jnp.where(
            adv0, PH_PROPOSE, jnp.where(adv1, PH_COMMIT, jnp.where(adv2, PH_DONE, state.phase))
        )
        phase = jnp.where(retry, PH_TICKET, phase)
        # the proposer is an acceptor too: process own request locally (real
        # Paxos self-promise/accept; the reference gets this only via echo).
        # The three windows are mutually exclusive per row this tick.
        self_tk_ok = send_tk & (ticket > t_max)
        self_enc = jnp.where(
            self_tk_ok & (command >= 0), t_store * c_enc + command + 1, 0
        )
        t_max = jnp.where(self_tk_ok, ticket, t_max)
        self_pp_ok = adv0 & (state.ticket == t_max)
        command = jnp.where(self_pp_ok, proposal, command)
        t_store = jnp.where(self_pp_ok, state.ticket, t_store)
        self_cm_ok = adv1 & (state.ticket == t_store) & (proposal == command)
        exec_tick = jnp.where(self_cm_ok & (exec_tick < 0), jnp.int32(t), exec_tick)
        is_commit = is_commit | self_cm_ok
        self_ok = self_tk_ok | self_pp_ok | self_cm_ok
        vs = jnp.where(new_window, self_ok.astype(jnp.int32), vs)
        vf = jnp.where(new_window, (~self_ok).astype(jnp.int32), vf)
        adopt_val = jnp.where(send_tk, self_enc, adopt_val)
        # jittered deadline: identical timeouts would make dueling proposers
        # retry in lockstep at the same tick forever (symmetric livelock);
        # the earliest retrier sweeps every acceptor's t_max and wins
        k_to = chan_key(tkey, Channel.ELECTION)
        if axis is not None:
            k_to = jax.random.fold_in(k_to, jax.lax.axis_index(axis))
        jitter = jax.random.randint(
            k_to, (n_loc,), 0, max(cfg.paxos_retry_timeout_ms // 2, 1),
            dtype=jnp.int32,
        )
        window_deadline = jnp.where(
            new_window, jnp.int32(t) + cfg.paxos_retry_timeout_ms + jitter,
            state.window_deadline,
        )
    else:
        # reference proposers have no phase register (actions are driven by
        # reply types alone) and no timeout; counters were already reset to
        # the post-crossing carryover (left_ok/left_no) in the counting block
        phase = jnp.where(adv2, PH_DONE, jnp.where(retry, PH_TICKET, state.phase))
        adopt_val = jnp.where(send_tk, 0, adopt_val)
        window_deadline = state.window_deadline

    # ---- push this tick's requests ------------------------------------------
    ref_skip = not clean
    tk_val = ticket * send_tk.astype(jnp.int32)
    pp_val = (state.ticket * c_enc + proposal + 1) * adv0.astype(jnp.int32)
    cm_val = (state.ticket * c_enc + state.proposal + 1) * adv1.astype(jnp.int32)
    zeros_req = jnp.zeros((nb, n_loc, p), jnp.int32)
    channels = (
        (tk_val, Channel.DELAY_BCAST),
        (pp_val, Channel.DELAY_BCAST2),
        (cm_val, Channel.DELAY_BCAST3),
    )
    contribs = []
    if gossip:
        # a proposer's own send is the flood origin: full TTL, own column,
        # marked seen so the loopback copy is not re-forwarded
        own = (ids[:, None] == jnp.arange(p)[None, :]).astype(jnp.int32)
        for ci, (val, chan) in enumerate(channels):
            init_mat = val[:, None] * own
            init_enc = (init_mat * h_enc + cfg.gossip_hops) * (init_mat > 0)
            # the origin marks its own full-TTL copy seen, so no loopback
            # copy (necessarily fewer hops) is ever re-forwarded
            seen_req = seen_req.at[:, ci, :].max(init_enc)
            enc = jnp.maximum(fwd_vals[ci], init_enc)
            contribs.append(gated(
                (enc > 0).any(),
                lambda e=enc, c=chan: _gossip_fwd_contrib(
                    chan_key(tkey, c), e, nbrs_loc, n, lo, hi, drop, axis,
                    impl=eimpl,
                ),
                zeros_req,
                axis,
            ))
    else:
        for val, chan in channels:
            contribs.append(gated(
                (val > 0).any(),
                lambda v=val, c=chan: _req_contrib(
                    chan_key(tkey, c), v, lo, hi, drop, axis, ids, p, ref_skip,
                    impl=eimpl, inmask=inmask,
                ),
                zeros_req,
                axis,
            ))
    req_ticket = ring_push_max(req_ticket, t, lo, contribs[0])
    req_propose = ring_push_max(req_propose, t, lo, contribs[1])
    req_commit = ring_push_max(req_commit, t, lo, contribs[2])

    state = state.replace(
        t_max=t_max,
        command=command,
        t_store=t_store,
        is_commit=is_commit,
        exec_tick=exec_tick,
        ticket=ticket,
        phase=phase,
        vote_success=vs,
        vote_failed=vf,
        proposal=proposal,
        adopt_val=adopt_val,
        commit_tick=commit_tick,
        gave_up=gave_up,
        window_deadline=window_deadline,
        seen_req=seen_req,
    )
    bufs = PaxosBufs(
        req_ticket=req_ticket,
        req_propose=req_propose,
        req_commit=req_commit,
        resp_ok=resp_ok,
        resp_no=resp_no,
        resp_cmd=resp_cmd,
    )
    return state, bufs


def metrics(cfg, state: PaxosState) -> dict:
    """The reference's measurement surface (SURVEY.md §5): CLIENT COMMIT
    SUCCESS with ticket/id/time (paxos-node.cc:339), ticket requests (:518),
    plus safety invariants the reference never checks."""
    p = cfg.paxos_n_proposers
    alive = np.asarray(state.alive)
    commit_tick = np.asarray(state.commit_tick)[:p]
    ticket = np.asarray(state.ticket)[:p]
    is_commit = np.asarray(state.is_commit)
    command = np.asarray(state.command)
    exec_tick = np.asarray(state.exec_tick)
    proposal = np.asarray(state.proposal)[:p]
    winners = np.flatnonzero(commit_tick >= 0)
    winner = int(winners[np.argmin(commit_tick[winners])]) if winners.size else -1
    executed = np.flatnonzero(is_commit & alive)
    exec_cmds = np.unique(command[executed]) if executed.size else np.array([])
    # safety: all executed acceptors executed the same command, and every
    # committed proposer's value is that command.  A committed proposer with
    # zero executed acceptors is itself an inconsistency (its commit quorum
    # claimed executions that nobody holds), not vacuous agreement.
    if winners.size and not exec_cmds.size:
        # a committed proposer whose commit quorum left zero executed alive
        # acceptors claimed executions nobody holds — an inconsistency
        agreement = False
    else:
        agreement = len(exec_cmds) <= 1 and all(
            proposal[w] == exec_cmds[0] for w in winners
        )
    return {
        "protocol": "paxos",
        "n": cfg.n,
        "n_committed_proposers": int(winners.size),
        "winner": winner,
        "winner_commit_ms": float(commit_tick[winner]) if winner >= 0 else -1.0,
        "winner_ticket": int(ticket[winner]) if winner >= 0 else -1,
        "max_ticket": int(ticket.max()) if p else 0,
        "retries": int((ticket - 1).clip(min=0).sum()),
        "acceptor_executes": int(executed.size),
        "first_execute_ms": float(exec_tick[executed].min()) if executed.size else -1.0,
        "decided_command": int(exec_cmds[0]) if exec_cmds.size else -1,
        "gave_up": int(np.asarray(state.gave_up).sum()),
        "agreement_ok": bool(agreement),
    }
