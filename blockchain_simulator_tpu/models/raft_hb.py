"""Raft heartbeat-blocked fast path: one scan step = one 50 ms heartbeat.

The raft tick engine (models/raft.py) carries [N] state and [D, N] rings
through every 1 ms tick.  But steady-state raft replication is LEADER-
CENTRIC: one proposal broadcast per heartbeat, N-1 acks back, a majority
count — the followers are homogeneous (clean fidelity: ack unconditionally,
store the value, re-arm the timer).  Aggregated, a whole heartbeat is O(1)
work — a handful of scalar bucket draws and a short crossing loop —
INDEPENDENT OF N: the same multi-rate-stepping-to-the-limit design as the
PBFT round path (models/pbft_round.py), taken further because raft's steady
state has a single actor.

Two phases under one jit, joined by a TRACED checked handoff:

1. **Election prefix** (tick engine, ``prefix_ticks(cfg)`` = election_hi +
   2*roundtrip_hi ticks): elections are genuinely event-driven (randomized
   timers, races, retries), so the faithful tick machine runs them.  At the
   handoff the program CHECKS it reached the quiet window between the
   election settling and the first proposal (exactly one leader, its vote
   wave drained, proposals scheduled but not yet started) and emits an
   ``ok`` flag.
2. **Heartbeat scan**: per step, the leader's proposal (once
   ``proposal_tick`` passes), its ack wave as multinomial bucket counts over
   the round-trip distribution offset by the 20 KB serialization time, and
   the clean-mode ack-window bookkeeping at BIN granularity with the tick
   engine's exact ordering: arrivals on the heartbeat boundary tick count
   into the OLD window, then the new proposal resets it, then later
   arrivals fill the new one.  With the reference's 54-tick proposal
   serialization the whole wave lands one heartbeat behind its proposal —
   reproducing the tick engine's characteristic "49 of 50 blocks at
   defaults" pipeline (see .claude/skills/verify/SKILL.md).

The handoff is a ``jax.lax.cond``: when ``ok`` is false (e.g. a split first
election that re-ran past the prefix, or setProposal already fired inside
the prefix) the false branch CONTINUES the tick engine from the prefix's
(state, bufs) carry through the rest of the window.  Because tick keys
derive from the absolute tick (utils/prng.py), the continuation is
bit-identical to one uninterrupted tick-engine run — the fast path is
checked, never silently wrong, and the whole program lowers inside ``jit``,
``vmap`` (the cond batches to a select: both branches run, so a batched
sweep costs ~one tick-engine pass) and ``shard_map`` (the handoff reductions
ride ``psum``/``pmax`` over ``cfg.mesh_axis``; phase 2 is replicated O(1)
scalar work).

Timer suppression is structural: heartbeats every 50 ms re-arm 150-300 ms
election timers, so in the fault classes this path accepts (crash/Byzantine
from t=0, no drops) no election can fire after the handoff.

Milestone contract vs the tick engine (same reasoning as pbft_round): ack
COUNTS are deterministic (no drops — every follower acks every proposal
exactly once), so per-block commit counts are bit-equal; commit TICKS carry
the +/-1 bucket-quantile jitter of the independent per-engine draws.

Documented divergence — post-completion election churn: when replication
finishes INSIDE the window (blockNum hits raft_max_blocks), the reference
cancels the heartbeat (raft-node.cc:248-251); in clean fidelity the silenced
heartbeat un-suppresses every follower's election timer and the tick engine
then churns elections for the rest of the window (a real consequence of
completion silencing the failure detector; the gossip overlay keeps a
control heartbeat for exactly this reason — models/raft.py).  This path ends
at completion instead: every consensus milestone (leader, blocks, block
ticks, rounds, agreement over the replicated log) is identical — the churn
starts only after the log is complete — but the ``elections`` metric counts
the consensus phase only, and post-completion re-leaders are not simulated.
Configurations whose window ends before completion (e.g. the reference
default, where serialized acks leave 49/50 blocks at the 10 s mark) have no
churn phase and match on every metric including ``elections``.

Reference anchors: sendHeartBeat/SendTX (raft-node.cc:405-433,340-365), ack
counting + blockNum (raft-node.cc:234-251), setProposal (+1 s, :216,433),
stop conditions (:248-251, :361-365).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from blockchain_simulator_tpu.models import raft as raft_tick
from blockchain_simulator_tpu.ops import delay as delay_ops
from blockchain_simulator_tpu.ops import delivery as dv
from blockchain_simulator_tpu.utils import prng
from blockchain_simulator_tpu.utils.prng import Channel, chan_key

DISARM = raft_tick.DISARM


def prefix_ticks(cfg) -> int:
    """Static election-phase length: the last possible first-attempt election
    fires by election_hi; its request+reply wave drains within 2 round trips."""
    _, rt_hi = cfg.roundtrip_range()
    return cfg.raft_election_hi_ms + 2 * rt_hi


def n_hb_steps(cfg) -> int:
    """Static heartbeat-step count of the steady scan (also the length of a
    traced run's per-heartbeat probe series, utils/trace.run_traced)."""
    return max((cfg.ticks - prefix_ticks(cfg)) // cfg.raft_heartbeat_ms + 2, 1)


def eligible(cfg) -> bool:
    return (
        cfg.protocol == "raft"
        and cfg.fidelity == "clean"  # reference mode never re-arms timers and
        # gates commits on exactly N-1 replies — tick-machine territory
        and cfg.topology == "full"
        and cfg.delivery == "stat"
        and cfg.faults.drop_prob == 0.0  # a dropped ack changes counts; a
        # dropped heartbeat un-suppresses a timer (re-election mid-stream)
        and not cfg.queued_links
        and cfg.raft_heartbeat_ms < cfg.raft_election_lo_ms  # timer suppression
        and cfg.sim_ms > prefix_ticks(cfg) + cfg.raft_heartbeat_ms
    )


def _ack_bins(cfg):
    """Static (bin -> step offset, tick-within-step, boundary flag) layout of
    the ack round-trip distribution shifted by the proposal serialization."""
    rt_lo, rt_hi = cfg.roundtrip_range()
    ser = cfg.serialization_ticks(cfg.raft_block_bytes)
    hb = cfg.raft_heartbeat_ms
    offs = [ser + rt_lo + b for b in range(rt_hi - rt_lo)]
    return [(o // hb, o % hb) for o in offs]


def _psum(x, axis):
    return x if axis is None else jax.lax.psum(x, axis)


def _pmax(x, axis):
    return x if axis is None else jax.lax.pmax(x, axis)


class Handoff(NamedTuple):
    """Leader-global scalars the heartbeat scan consumes (replicated across
    the mesh axis when sharded; garbage-but-finite when ``ok`` is false —
    the cond's false branch never reads them, and under vmap's both-branch
    select they only have to be safe to compute with)."""

    lead: jax.Array     # global leader id (-1 if none)
    hb0: jax.Array      # leader's next heartbeat tick
    p_start: jax.Array  # leader's setProposal tick
    bn0: jax.Array      # leader's block_num at handoff (0 in the quiet window)
    rnd0: jax.Array     # leader's round at handoff (0 in the quiet window)
    bt0: jax.Array      # [B] leader's block_tick row
    ok_cnt: jax.Array   # honest alive followers (SUCCESS acks), float32


def handoff(cfg, state, axis=None):
    """Checked-handoff evaluation on the post-prefix tick-engine state.

    Returns ``(ok, Handoff)``; every value is a replicated scalar (or [B]
    row) under ``shard_map`` — the reductions ride psum/pmax over ``axis``.
    """
    t_e = prefix_ticks(cfg)
    hb = cfg.raft_heartbeat_ms
    rt_hi = cfg.roundtrip_range()[1]
    n_loc = state.is_leader.shape[0]
    ids = dv._global_ids(n_loc, axis)
    lead_mask = state.is_leader & state.alive
    n_leaders = _psum(lead_mask.sum(), axis)
    lead = _pmax(jnp.max(jnp.where(lead_mask, ids, -1)), axis)

    def lval(x, fill):
        """Leader-row value (max over the — singleton when ok — leader set)."""
        return _pmax(jnp.max(jnp.where(lead_mask, x, fill)), axis)

    p_start = lval(state.proposal_tick, -1)
    ok = (
        (n_leaders == 1)
        # the election wave has fully drained: stale grants/denials land
        # within one round trip of the winning fire (leader_tick is the
        # win tick, itself at most rt_hi past the fire — prefix_ticks
        # budgets 2*rt_hi past election_hi for exactly this)
        & (lval(state.leader_tick, -1) + rt_hi <= t_e)
        & (p_start > t_e + hb)  # not yet proposing
        # DISARM (= setProposal already fired inside the prefix, possible
        # when raft_proposal_delay_ms is small) trivially satisfies the
        # not-yet-proposing comparison but means proposal waves may already
        # be in flight in the rings phase 2 discards — fall back to the
        # tick engine instead of silently never proposing (ADVICE r5)
        & (p_start != DISARM)
    )
    ok_cnt = (
        _psum((state.alive & state.honest).sum(), axis)
        - lval((state.alive & state.honest).astype(jnp.int32), 0)
    ).astype(jnp.float32)
    bt0 = _pmax(
        jnp.max(jnp.where(lead_mask[:, None], state.block_tick, -1), axis=0),
        axis,
    )
    return ok, Handoff(
        lead=lead,
        hb0=lval(state.next_hb, -1),
        p_start=p_start,
        bn0=lval(state.block_num, 0),
        rnd0=lval(state.round, 0),
        bt0=bt0,
        ok_cnt=ok_cnt,
    )


def steady_scan(cfg, key, h: Handoff, with_probe: bool = False):
    """Heartbeat-blocked steady-state scan from the handoff scalars.

    Pure O(1)-per-step scalar work — no [N] state, no collectives — so it
    vmaps over shards (models/mixed.py) and replicates cheaply under
    shard_map.  Returns ``(hs, open_, bn, rnd, add_on, stopped, bt)``.

    ``with_probe=True`` (utils/trace.run_traced) additionally emits one
    probe sample per HEARTBEAT step — ``{"blocks", "rounds",
    "acks_in_window", "stopped"}``, the leader-global values after the
    step — and returns ``(scan_out, ys)``.  The carry trajectory is
    bit-identical either way (the probe only reads the carry).
    """
    hb = cfg.raft_heartbeat_ms
    b_max = cfg.raft_max_blocks
    bins = _ack_bins(cfg)
    b2 = len(bins)
    span = max(s for s, _ in bins) + 1
    # bin processing order within a step: tick-within-step ascending; ties by
    # bin index (same tick => one counter update, order irrelevant)
    order = sorted(range(b2), key=lambda i: bins[i][1])
    k_steps = n_hb_steps(cfg)
    rt_probs = delay_ops.roundtrip_probs(*cfg.one_way_range())
    smode = cfg.eff_stat_sampler
    need = cfg.majority_need

    def hb_body(carry, k):
        pend, hs, open_, bn, rnd, add_on, stopped, bt = carry
        t_k = h.hb0 + k * hb

        def apply_bin(cnt, tick, hs, open_, bn, bt):
            """One ack bin through the window: count, threshold-cross,
            commit (clean latch) — the tick engine's per-tick rule."""
            hs = hs + cnt
            crossed = open_ & (cnt > 0) & (hs + 1 >= need)
            blk = jnp.clip(bn, 0, b_max - 1)
            bt = jnp.where(
                jax.nn.one_hot(blk, b_max, dtype=bool)
                & crossed & (bn < b_max),
                tick, bt,
            )
            return hs, open_ & ~crossed, bn + crossed, bt

        arrivals = pend[0]  # [B2] counts landing this step
        # boundary-tick arrivals (tick offset 0) hit the OLD window and
        # are fully folded — including into bn — BEFORE the proposal
        # gate below, matching the tick engine's within-tick order
        # (arrival processing, then the heartbeat timer section)
        for i in order:
            s_i, off_i = bins[i]
            if off_i != 0:
                continue
            # horizon mask: arrivals at or past the window end never land
            cnt = jnp.where(t_k + off_i < cfg.ticks, arrivals[i], 0)
            hs, open_, bn, bt = apply_bin(cnt, t_k + off_i,
                                          hs, open_, bn, bt)
        # heartbeat boundary: proposal + clean window reset
        # (raft-node.cc:405-433; raft.py step's timer section); a
        # boundary-tick commit that just hit b_max cancels it
        live = (t_k < cfg.ticks) & ~stopped
        p = live & (t_k >= h.p_start) & add_on & (bn < b_max)
        rnd = rnd + p
        add_on = add_on & ~(p & (rnd >= cfg.raft_max_rounds))
        hs = jnp.where(p, 0, hs)
        open_ = open_ | p
        # post-boundary arrivals fill the (possibly new) window
        for i in order:
            s_i, off_i = bins[i]
            if off_i == 0:
                continue
            cnt = jnp.where(t_k + off_i < cfg.ticks, arrivals[i], 0)
            hs, open_, bn, bt = apply_bin(cnt, t_k + off_i,
                                          hs, open_, bn, bt)
        # rotate the pending ring and enqueue this proposal's ack wave
        pend = jnp.concatenate(
            [pend[1:], jnp.zeros((1, b2), jnp.int32)], axis=0
        )
        cnts = delay_ops.sample_bucket_counts(
            jax.random.fold_in(chan_key(prng.tick_key(key, t_k),
                                        Channel.DELAY_ROUNDTRIP), 0x4B),
            jnp.where(p, h.ok_cnt, 0.0), rt_probs, smode,
        )  # [B2] scalar counts
        for i in range(b2):
            s_i, _ = bins[i]
            if s_i > 0:  # lands s_i steps later: row s_i-1 post-rotation
                pend = pend.at[s_i - 1, i].add(cnts[i])
        # s_i == 0 bins (ser + rt < heartbeat) land later THIS step,
        # which the rotated ring's row 0 has already passed — inject
        # them directly (offsets are > 0: acks always land strictly
        # after their proposal tick)
        if any(s == 0 for s, _ in bins):
            for i in order:
                s_i, off_i = bins[i]
                if s_i != 0:
                    continue
                cnt = jnp.where(t_k + off_i < cfg.ticks, cnts[i], 0)
                hs, open_, bn, bt = apply_bin(cnt, t_k + off_i,
                                              hs, open_, bn, bt)
        stopped = stopped | (bn >= b_max)  # blockNum>=50 cancels the
        # heartbeat (raft-node.cc:248-251)
        ys = (
            {"blocks": bn, "rounds": rnd, "acks_in_window": hs,
             "stopped": stopped.astype(jnp.int32)}
            if with_probe
            else ()
        )
        return (pend, hs, open_, bn, rnd, add_on, stopped, bt), ys

    carry0 = (
        jnp.zeros((span, b2), jnp.int32),
        jnp.int32(0),                       # hs (ack window count)
        jnp.bool_(False),                   # hb_open
        h.bn0,                              # 0 at handoff
        h.rnd0,                             # 0 at handoff
        jnp.bool_(True),                    # add_change_value (will set)
        jnp.bool_(False),                   # stopped
        h.bt0,                              # [B] commit ticks
    )
    (_, hs, open_, bn, rnd, add_on, stopped, bt), ys = jax.lax.scan(
        hb_body, carry0, jnp.arange(k_steps)
    )
    out = (hs, open_, bn, rnd, add_on, stopped, bt)
    return (out, ys) if with_probe else out


def materialize(cfg, state, h: Handoff, scan_out, axis=None):
    """Fold the steady-scan scalars back into the [N] state the metrics
    surface reads (each shard writes only its local leader/follower rows)."""
    hs, open_, bn, rnd, add_on, stopped, bt = scan_out
    n_loc = state.is_leader.shape[0]
    onehot = dv._global_ids(n_loc, axis) == h.lead
    return state.replace(
        block_num=jnp.where(onehot, bn, state.block_num),
        round=jnp.where(onehot, rnd, state.round),
        block_tick=jnp.where(onehot[:, None], bt[None, :],
                             state.block_tick),
        hb_succ=jnp.where(onehot, hs, state.hb_succ),
        hb_open=jnp.where(onehot, open_, state.hb_open),
        add_change_value=jnp.where(onehot, add_on, state.add_change_value),
        next_hb=jnp.where(onehot & stopped, DISARM, state.next_hb),
        # every alive follower stored the leader's proposal value once
        # replication ran (m_value = leader id, raft-node.cc:180-190)
        m_value=jnp.where(
            state.alive & ~onehot & (rnd > 0), h.lead, state.m_value
        ),
    )


def scan_from_init(cfg, state, bufs, key, probe=None):
    """Fully traced round-schedule raft simulation from an initial
    (state, bufs): tick-engine election prefix, traced checked handoff,
    ``lax.cond`` into either the heartbeat scan or a CONTINUATION of the
    tick engine from the prefix carry (bit-identical to one uninterrupted
    tick run — tick keys derive from the absolute tick).

    Shared by the single-chip runner (runner.make_sim_fn), vmapped sweeps
    (parallel/sweep.py) and the node-sharded path (parallel/shard.py, which
    calls it inside ``shard_map`` with ``cfg.mesh_axis`` set).

    ``probe`` (obsim/build.py) arms in-program taps without forking the
    engine: a ``(sample_fn, steady_map_fn, reduce_fn)`` triple —
    ``sample_fn(state) -> {field: scalar}`` per TICK, ``steady_map_fn(ys,
    handoff_state) -> {field: [K]}`` lifting the heartbeat scan's ys into
    the same fields, and ``reduce_fn(series) -> pytree`` collapsing a
    variable-length sample axis to a FIXED shape, so both ``lax.cond``
    branches (prefix+heartbeats vs prefix+ticks — different sample
    counts) merge on identical avals.  Returns ``(final, probes)``; the
    state trajectory is bit-identical to the unprobed call (taps only
    read; they consume zero PRNG)."""
    axis = cfg.mesh_axis
    t_e = prefix_ticks(cfg)
    sample_fn, steady_map_fn, reduce_fn = probe or (None, None, None)

    def tick_body(carry, t):
        st, bf = carry
        st, bf = raft_tick.step(cfg, st, bf, t, prng.tick_key(key, t))
        return (st, bf), sample_fn(st) if sample_fn is not None else ()

    def _cat(pre, post):
        return jax.tree.map(
            lambda a, b: jnp.concatenate([a, b], axis=0), pre, post
        )

    # ---- phase 1: election prefix on the tick engine -----------------------
    carry, pre_ys = jax.lax.scan(tick_body, (state, bufs), jnp.arange(t_e))
    ok, h = handoff(cfg, carry[0], axis)

    if probe is None:

        def fast_branch(carry):
            return materialize(cfg, carry[0], h, steady_scan(cfg, key, h),
                               axis)

        def tick_branch(carry):
            # the election prefix did not reach the quiet handoff window:
            # the faithful tick engine takes over from the prefix carry
            (st, _), _ = jax.lax.scan(
                tick_body, carry, t_e + jnp.arange(max(cfg.ticks - t_e, 0))
            )
            return st

        return jax.lax.cond(ok, fast_branch, tick_branch, carry)

    def fast_branch(carry):
        out, hb_ys = steady_scan(cfg, key, h, with_probe=True)
        st = materialize(cfg, carry[0], h, out, axis)
        series = _cat(pre_ys, steady_map_fn(hb_ys, carry[0]))
        return st, reduce_fn(series)

    def tick_branch(carry):
        (st, _), ys = jax.lax.scan(
            tick_body, carry, t_e + jnp.arange(max(cfg.ticks - t_e, 0))
        )
        return st, reduce_fn(_cat(pre_ys, ys))

    return jax.lax.cond(ok, fast_branch, tick_branch, carry)


def run(cfg, key):
    """``run(cfg, key) -> RaftState`` — init + scan_from_init (the
    single-device / vmap entry; jit-wrapped by runner.make_sim_fn)."""
    state, bufs = raft_tick.init(cfg, jax.random.fold_in(key, 0x1217))
    return scan_from_init(cfg, state, bufs, key)


def metrics(cfg, state) -> dict:
    return raft_tick.metrics(cfg, state)
