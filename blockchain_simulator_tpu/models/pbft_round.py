"""PBFT round-blocked fast path: one scan step = one 50 ms consensus round.

The general engine (models/pbft.py) advances 1 ms ticks, carrying [N, W] vote
state and [D, N, W] future-inbox rings.  That is the faithful, fully general
machine — but at N = 100k the compiled tick body rewrites each 57 MB ring
buffer several times per tick (round-3 HLO analysis: 13 full-buffer fusions,
~1.5 GB of HBM traffic per 1 ms tick), capping throughput near 8 simulated
rounds/s on a v5e chip.

This module exploits the protocol's structure instead (the TPU-first answer
to SURVEY.md §7 "hard parts" #2, multi-rate stepping, taken to its limit):
when no messages cross a round boundary, a whole PBFT round is a *closed*
static wave — propose at t0; PRE_PREPAREs land at t0+U{lo..hi-1}; each
receiver's PREPARE round-trip replies arrive as multinomial bucket counts
over the triangular two-leg distribution; vote counters cross thresholds by
a short cumulative loop over those buckets; COMMIT broadcasts group by send
tick and land as per-receiver multinomial counts again.  Everything is a
handful of ops on [N] vectors: no vote table, no rings, ~50 ticks of
simulation per scan step for less memory traffic than ONE tick of the
general engine.

Semantics match models/pbft.step for every configuration this path accepts
(`eligible` below): identical timer/threshold/fidelity logic, identical
view-change draw (same PRNG channel at the block tick), same metrics
surface; delivery randomness is drawn per round instead of per tick, so
results are distributionally — not bit — identical to the tick engine
(delivery="stat" is already an aggregate model).  Precisely, for DROP-FREE
configs: per-slot COUNTS (commits, proposals, view changes — every
milestone) are bit-equal, because both samplers deliver every message
exactly once; per-slot commit *ticks* carry +/-1-tick tail jitter (the
last threshold-crossing arrival falls in a different multinomial bucket
under different keys).  With drop_prob > 0 the thinning draws are
independent between engines, so counts agree only where thresholds make
the outcome deterministic (the drop tests pin such operating points, not
exact equality at intermediate rates).  Tests pin exactly these contracts
(tests/test_pbft_round.py).

Eligibility (checked statically from the config):
- protocol "pbft", topology "full", delivery "stat";
- per-message drops only with view changes disabled (each wave is then an
  independently thinned binomial, the tick engine's own stat-channel drop
  model; a dropped VIEW_CHANGE would diverge leader beliefs and rounds
  would stop being single-proposer);
- no byz_forge flood (targets the exact-window tick machine);
- the message horizon (including the constant block-serialization latency
  when modeled) must fit inside one block interval:
  ``ser + max_arrival_offset < pbft_block_interval_ms``, so rounds close.

Serialization (model_serialization=True) is a CONSTANT per-block offset in
the tick engine — only the PRE_PREPARE push carries it (pbft.py step:
``ring_push_max(pp, t, lo + ser, ...)``; votes/commits are 4-byte packets) —
so here it shifts the whole round wave rigidly by ``ser`` ticks: arrivals at
``t0 + ser + d_j``, commit sends at ``t0 + ser + d_j + rt``, commits landing
at most ``ser + max_arrival_offset`` after the block tick.  At the reference
default timing (50 KB blocks on 3 Mbps links -> ser = 134 ticks > the 50-tick
interval) rounds overlap and this path refuses.  Raising the interval alone
cannot fix that: the block size scales with the interval (num = tx_speed /
(1000/timeout), pbft-node.cc:377), and the reference's 1000 tx/s x 1 KB
offered load (8 Mbit/s) exceeds its own 3 Mbps link — the very overload that
makes its queues grow without bound (tests/test_fidelity.py).  A SUSTAINABLE
operating point (e.g. tx_speed=300 -> 2.4 Mbit/s, 80% utilization) with the
interval past ser + horizon (e.g. 200 ms -> ser = 160) is eligible, with
per-round cost identical to the serialization-free config (the offset is
arithmetic, not extra work).

Reference anchors: the round cadence being reproduced is SendBlock's 50 ms
self-rescheduling loop (pbft-node.cc:372-411); thresholds pbft-node.cc:231,
248; view change pbft-node.cc:294-303,401-403; finality log pbft-node.cc:259.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from flax import struct

from blockchain_simulator_tpu.models import pbft as pbft_tick
from blockchain_simulator_tpu.models.base import fault_masks
from blockchain_simulator_tpu.ops import delay as delay_ops
from blockchain_simulator_tpu.ops import delivery as dv
from blockchain_simulator_tpu.ops.delivery import _global_ids, _shard_key
from blockchain_simulator_tpu.utils.prng import Channel, chan_key

_NEVER = pbft_tick._NEVER

GLOBAL_FIELDS = pbft_tick.GLOBAL_FIELDS


@struct.dataclass
class PbftRoundState:
    """Cross-round state only — all in-round vote bookkeeping is transient.

    Field names/meanings mirror models/pbft.PbftState so pbft.metrics() reads
    either; the [N, W] table fields simply do not exist here.
    """

    v: jax.Array             # [N]
    leader: jax.Array        # [N]
    next_n: jax.Array        # [N]
    rounds_sent: jax.Array   # [N]
    block_num: jax.Array     # [N]
    unattributed: jax.Array  # [N] (always 0 on this path: no vote table
    # windows exist to misattribute into, even under drops)
    view_changes: jax.Array  # [N]
    alive: jax.Array         # [N]
    honest: jax.Array        # [N]
    slot_commits: jax.Array      # [S]
    slot_commit_tick: jax.Array  # [S]
    slot_propose_tick: jax.Array  # [S]


def max_arrival_offset(cfg) -> int:
    """Latest in-round event offset: commit sent at (hi-1)+rt_hi-1 arriving
    +hi-1 later."""
    lo, hi = cfg.one_way_range()
    rt_lo, rt_hi = cfg.roundtrip_range()
    return (hi - 1) + (rt_hi - 1) + (hi - 1)


def eligible(cfg) -> bool:
    ser = cfg.serialization_ticks(cfg.pbft_block_bytes)
    return (
        cfg.protocol == "pbft"
        and cfg.topology == "full"
        and cfg.delivery == "stat"
        # drops are fine while the leader never changes: every wave is
        # independently thinned (same binomial model as the tick engine's
        # stat channels).  With view changes enabled, a dropped VIEW_CHANGE
        # diverges leader beliefs and rounds stop being single-proposer —
        # that combination stays on the tick engine.  Windowed mode also
        # stays there: a pp-dropped receiver's commit crossing lands in the
        # tick engine's stale-tenant/unattributed bookkeeping, which this
        # path (no vote table) cannot reproduce; exact mode credits by
        # window identity in both engines.
        and (
            cfg.faults.drop_prob == 0.0
            or (
                cfg.pbft_view_change_num == 0
                and pbft_tick.eff_window(cfg) >= cfg.pbft_max_slots
            )
        )
        and not cfg.faults.byz_forge
        and not cfg.queued_links  # serial-pipe backlog is cross-round state
        and ser + max_arrival_offset(cfg) < cfg.pbft_block_interval_ms
    )


def init(cfg, key=None):
    n, s = cfg.n, cfg.pbft_max_slots
    alive, honest = fault_masks(cfg, n)
    zi = lambda *sh: jnp.zeros(sh, jnp.int32)
    state = PbftRoundState(
        v=jnp.ones((n,), jnp.int32),
        leader=zi(n),
        next_n=zi(n),
        rounds_sent=zi(n),
        block_num=zi(n),
        unattributed=zi(n),
        view_changes=zi(n),
        alive=alive,
        honest=honest,
        slot_commits=zi(s),
        slot_commit_tick=jnp.full((s,), -1, jnp.int32),
        slot_propose_tick=jnp.full((s,), _NEVER, jnp.int32),
    )
    return state, ()


finalize = pbft_tick.finalize  # same GLOBAL_FIELDS partial-combining


def _psum(x, axis):
    return x if axis is None else jax.lax.psum(x, axis)


def _pmax(x, axis):
    return x if axis is None else jax.lax.pmax(x, axis)


def _crossing_loop(buckets, need, clean: bool, start=None):
    """Threshold crossings of a vote counter fed bucket-by-bucket.

    ``buckets``: [B, N] arrival counts in tick order.  Replicates the tick
    engine's per-tick rule (pbft.step / pbft-node.cc:231,248): counter +=
    arrivals; crossed iff arrivals > 0 and counter >= need; on crossing the
    counter resets to 0 (reference fidelity; the whole batch is consumed) —
    ``clean`` latches so only the first crossing fires.

    Returns (crossed [B, N] bool, n_crossings [N], first_bucket [N] — index
    of first crossing, B if none).
    """
    b, n = buckets.shape
    if clean:
        # latched first crossing only: the counter never resets before it
        # fires, so the running cumulative sum IS the counter up to the
        # crossing, and the crossing is the FIRST bucket with arrivals at or
        # past the threshold (argmax of a bool picks the first True).  The
        # running sums are built by an unrolled add chain — NOT jnp.cumsum,
        # whose XLA:CPU lowering measured ~2.5 ms/round slower — and the
        # latch collapses to ~4 [B, N] ops instead of ~6 [N] ops per bucket.
        run = jnp.zeros((n,), jnp.int32) if start is None else start
        csums = []
        for k in range(b):
            run = run + buckets[k]
            csums.append(run)
        csum = jnp.stack(csums)  # [B, N]
        qual = (buckets > 0) & (csum >= need)
        any_q = qual.any(axis=0)
        first = jnp.argmax(qual, axis=0)  # first qualifying bucket
        crossed_mat = (jnp.arange(b)[:, None] == first[None, :]) & any_q[None, :]
        n_cross = any_q.astype(jnp.int32)
        return crossed_mat, n_cross, jnp.where(any_q, first, b)
    cnt = jnp.zeros((n,), jnp.int32) if start is None else start
    crossed_list = []
    for k in range(b):
        arr = buckets[k]
        cnt = cnt + arr
        crossed = (arr > 0) & (cnt >= need)
        cnt = jnp.where(crossed, 0, cnt)
        crossed_list.append(crossed)
    crossed_mat = jnp.stack(crossed_list)  # [B, N]
    n_cross = crossed_mat.astype(jnp.int32).sum(axis=0)
    first = jnp.argmax(crossed_mat, axis=0)
    first = jnp.where(crossed_mat.any(axis=0), first, b)
    return crossed_mat, n_cross, first


def step_round(cfg, state: PbftRoundState, r, key):
    """Advance one whole block interval starting at t0 = r * interval.

    Events are masked against the simulation window end (``cfg.ticks``): the
    tick engine truncates a final round's message wave mid-flight (sends
    happen at the block tick, but arrivals past the window never land), and
    the masks reproduce exactly that."""
    n, s = cfg.n, cfg.pbft_max_slots
    axis = cfg.mesh_axis
    bt = cfg.pbft_block_interval_ms
    lo, hi = cfg.one_way_range()
    rt_lo, rt_hi = cfg.roundtrip_range()
    b1 = hi - lo
    b2 = rt_hi - rt_lo
    clean = cfg.fidelity == "clean"
    smode = cfg.eff_stat_sampler
    ow_probs = delay_ops.uniform_probs(lo, hi)
    rt_probs = delay_ops.roundtrip_probs(lo, hi)
    # constant block-serialization offset: the tick engine pushes the
    # PRE_PREPARE at lo + ser (pbft.py), rigidly shifting the whole wave
    ser = cfg.serialization_ticks(cfg.pbft_block_bytes)
    t0 = r * bt
    n_loc = state.v.shape[0]
    ids = _global_ids(n_loc, axis)
    tkey = jax.random.fold_in(key, t0)

    # ---- A. block tick: SendBlock + view-change draw (pbft.step "timers") ---
    send = (
        (state.leader == ids)
        & (state.next_n < min(cfg.pbft_max_rounds, s))
        & state.alive
    )
    slot_p1 = _pmax(jnp.max(jnp.where(send, state.next_n + 1, 0)), axis)  # 0=none
    active = slot_p1 > 0
    slot = slot_p1 - 1
    rounds_sent = state.rounds_sent + send
    next_n = jnp.where(send, state.next_n + 1, state.next_n)
    # receivers learn the slot when the PRE_PREPARE lands (same round)
    next_n = jnp.maximum(next_n, slot_p1)
    slot_idx = jnp.where(active, slot, s)  # s = out-of-bounds drop
    slot_propose_tick = state.slot_propose_tick.at[slot_idx].min(
        jnp.where(active, jnp.int32(t0), _NEVER), mode="drop"
    )

    # view change: EXACTLY the tick engine's draw (same channel, same tick key)
    k_u = chan_key(tkey, Channel.VIEW_CHANGE)
    if axis is not None:
        k_u = jax.random.fold_in(k_u, jax.lax.axis_index(axis))
    u = jax.random.randint(k_u, (n_loc,), 0, cfg.pbft_view_change_den)
    trigger = send & (u < cfg.pbft_view_change_num)
    any_trigger = _pmax(jnp.max(trigger.astype(jnp.int32)), axis) > 0
    new_leader = _pmax(jnp.max(jnp.where(trigger, (state.leader + 1) % n, 0)), axis)
    view_changes = state.view_changes + trigger
    # no drops: every node (sender immediately, receivers within the round)
    # ends the round agreeing on (v+1, new_leader) — pbft-node.cc:271-280
    v = jnp.where(any_trigger, state.v + 1, state.v)
    leader = jnp.where(any_trigger, new_leader, state.leader)

    # ---- B. PRE_PREPARE arrivals + PREPARE round trips ----------------------
    # per-receiver arrival offset ser + d_j, d_j ~ U{lo..hi-1}; proposer excluded
    t_end = jnp.int32(cfg.ticks)  # arrivals at tick >= t_end never land
    k_pp = chan_key(tkey, Channel.DELAY_BCAST2)
    d_j = jax.random.randint(_shard_key(k_pp, axis), (n_loc,), lo, hi, jnp.int32)
    recv = active & state.alive & ~send & (t0 + ser + d_j < t_end)
    drop = cfg.faults.drop_prob
    if drop > 0.0:
        recv = recv & jax.random.bernoulli(
            _shard_key(jax.random.fold_in(k_pp, 0x0D0D), axis),
            1.0 - drop, (n_loc,),
        )
    # every receiver broadcasts PREPARE on arrival; honest alive peers reply
    # SUCCESS (short-circuited round trip, pbft-node.cc:212-221)
    voters = state.alive & state.honest
    n_voters = _psum(voters.astype(jnp.int32).sum(), axis)
    k_rt = chan_key(tkey, Channel.DELAY_ROUNDTRIP)
    # the tick engine's own stat round-trip helper: per-receiver reply
    # counts with (1-p)^2 two-leg thinning under drops
    rt_counts = dv.roundtrip_reply_counts_stat(
        k_rt, recv, n_voters - voters.astype(jnp.int32), rt_probs, drop,
        axis=axis, mode=smode,
    )  # [B2, N] reply counts, bucket k -> tick t0 + ser + d_j + rt_lo + k
    rt_land = (t0 + ser + d_j[None, :] + rt_lo + jnp.arange(b2)[:, None]) < t_end
    rt_counts = rt_counts * rt_land.astype(jnp.int32)
    crossed_p, _, _ = _crossing_loop(rt_counts, cfg.pbft_prepare_need, clean)
    commit_send = crossed_p & (state.alive & state.honest)[None, :]  # [B2, N]

    # ---- C. COMMIT waves -> finality ---------------------------------------
    # sender j's k-th crossing happens at offset o = ser + d_j + rt_lo + k;
    # group send counts by absolute offset o = (d_j - lo) + k: a length-b1
    # polynomial convolution along the tiny offset axis, materialized as b1
    # shifted pad-and-add terms instead of the former w_send x b2 nest of
    # masked [N] adds — dispatch count, not bytes, dominates the round step
    # on the CPU fallback path (VERDICT r5 weak-#4).  NOT a scatter-add:
    # XLA:CPU serializes scatter updates (measured 2.6x slower end-to-end).
    w_send = b1 + b2 - 1  # distinct send offsets
    off_base = ser + lo + rt_lo
    oh_d = d_j[None, :] == (lo + jnp.arange(b1))[:, None]  # [b1, N]
    cs = commit_send.astype(jnp.int32)
    send_at = sum(
        jnp.pad(cs * oh_d[e][None, :], ((e, b1 - 1 - e), (0, 0)))
        for e in range(b1)
    )  # [w_send, N]
    totals = _psum(send_at.sum(axis=1), axis)  # [w_send] global commit senders
    # receiver m hears, per send offset o, totals[o] - own sends at o,
    # spread multinomially over the one-way buckets.  One batched [W_send, N]
    # chain instead of W_send independent [N] chains: identical multinomial
    # statistics (sample_bucket_counts is elementwise over its leading
    # shape), ~W_send fewer PRNG/elementwise dispatches per round — the
    # dominant cost of a round step on the CPU fallback path.
    k_cm = chan_key(tkey, Channel.DELAY_BCAST)
    w_arr = w_send + b1 - 1
    m_all = jnp.where(state.alive[None, :], totals[:, None] - send_at, 0)
    if drop > 0.0:
        m_all = jnp.round(delay_ops.binom(
            _shard_key(jax.random.fold_in(k_cm, 0x0D12), axis),
            m_all, 1.0 - drop, smode,
        )).astype(jnp.int32)
    cnt_all = delay_ops.sample_bucket_counts(
        _shard_key(k_cm, axis), m_all, ow_probs, smode
    )  # [b1, w_send, N]
    # fold send offset + travel bucket into the arrival axis (i = o + e):
    # the same anti-diagonal pad-and-add convolution as send_at above,
    # replacing the b1 x w_send nest of [N] adds
    arrivals = sum(
        jnp.pad(cnt_all[e], ((e, b1 - 1 - e), (0, 0)))
        for e in range(b1)
    )  # [w_arr, N]
    arr_land = (t0 + off_base + lo + jnp.arange(w_arr)) < t_end  # [w_arr]
    arrivals = arrivals * arr_land.astype(jnp.int32)[:, None]
    crossed_c, n_cross_c, _ = _crossing_loop(
        arrivals, cfg.pbft_commit_need, clean
    )
    first_commit = crossed_c.any(axis=0) & active
    block_num = state.block_num + jnp.where(active, n_cross_c, 0)
    # last finalization tick of this slot (pbft.step scatters per-tick max;
    # arrival bucket tau -> tick t0 + off_base + lo + tau... offsets: bucket
    # index i of `arrivals` is send offset o + e, arrival tick = t0 + o_abs
    # + e_abs = t0 + (off_base + o) + (lo + e) -> t0 + off_base + lo + i
    bucket_idx = jnp.arange(w_arr, dtype=jnp.int32)[:, None]
    last_local = jnp.max(
        jnp.where(crossed_c, t0 + off_base + lo + bucket_idx, -1)
    )
    last_tick = _pmax(last_local, axis)
    n_first = _psum(first_commit.astype(jnp.int32).sum(), axis)
    slot_commits = state.slot_commits.at[slot_idx].add(
        jnp.where(active, first_commit.astype(jnp.int32).sum(), 0), mode="drop"
    )
    slot_commit_tick = state.slot_commit_tick.at[slot_idx].max(
        jnp.where(active & (n_first > 0), last_tick, -1), mode="drop"
    )

    return state.replace(
        v=v,
        leader=leader,
        next_n=next_n,
        rounds_sent=rounds_sent,
        block_num=block_num,
        view_changes=view_changes,
        slot_commits=slot_commits,
        slot_commit_tick=slot_commit_tick,
        slot_propose_tick=slot_propose_tick,
    )


def scan_rounds(cfg, state, key, with_probe: bool = False):
    """Scan every block interval inside the simulation window.

    Shared by the single-chip runner (runner.make_sim_fn) and the node-
    sharded path (parallel/shard.py), so the truncation semantics — round
    r runs iff its block tick r*interval < cfg.ticks, with the round body
    masking arrivals past the window — live in exactly one place.

    ``with_probe=True`` (utils/trace.run_traced) additionally emits the
    standard pbft probe (utils/trace.probe reads the shared field names)
    as scan ``ys`` — one sample per ROUND, the state after that round's
    whole wave — and returns ``(state, ys)``.  A CALLABLE ``with_probe``
    (obsim/build.py) is used as the probe function ``state -> pytree``
    instead of the trace one, same contract.  The state trajectory is
    bit-identical either way (the probe only reads)."""
    from blockchain_simulator_tpu.utils import trace as trace_mod

    if with_probe is True:
        probe_fn = functools.partial(trace_mod.probe, cfg)
    else:
        probe_fn = with_probe or None

    bt = cfg.pbft_block_interval_ms
    r_last = (cfg.ticks - 1) // bt
    if r_last < 1:
        if probe_fn is not None:
            empty = jax.tree.map(
                lambda x: jnp.zeros((0,), x.dtype), probe_fn(state)
            )
            return state, empty
        return state

    def body(st, r):
        st = step_round(cfg, st, r, key)
        return st, probe_fn(st) if probe_fn is not None else ()

    state, ys = jax.lax.scan(body, state, jnp.arange(1, r_last + 1))
    return (state, ys) if probe_fn is not None else state


def metrics(cfg, state) -> dict:
    """Same measurement surface as the tick engine (pbft.metrics)."""
    return pbft_tick.metrics(cfg, state)
