"""Protocol backend API.

The reference's plugin boundary is the per-protocol ``ns3::Application``
subclass, selected at *compile time* by editing network-helper.cc:17 and
blockchain-simulator.cc:72 (SURVEY.md §1).  Here a protocol backend is a
module-level triple of pure functions, selected at *runtime* by name:

- ``init(cfg, key) -> (state, bufs)``       — build the [N, ...] state pytree
  and the future-inbox ring buffers.
- ``step(cfg, state, bufs, t, tkey) -> (state, bufs)`` — one 1 ms tick for all
  N nodes at once (the tensorized equivalent of every event ns-3 would have
  dispatched in that interval: HandleRead FSM transitions + timer firings).
- ``metrics(cfg, state) -> dict``           — host-side structured metrics,
  reproducing the reference's NS_LOG measurement surface (SURVEY.md §5).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def get_protocol(name: str):
    """Runtime protocol selection (fixes the reference's compile-time switch)."""
    try:
        if name == "pbft":
            from blockchain_simulator_tpu.models import pbft as m
        elif name == "raft":
            from blockchain_simulator_tpu.models import raft as m
        elif name == "paxos":
            from blockchain_simulator_tpu.models import paxos as m
        elif name == "mixed":
            from blockchain_simulator_tpu.models import mixed as m
        else:
            raise ValueError(f"unknown protocol {name!r}")
    except ImportError as e:
        raise NotImplementedError(f"protocol backend {name!r} not available: {e}") from e
    return m


def gated(pred, fn, zeros, axis=None):
    """Skip a delivery computation when no sender is active this tick.
    Sharded, the predicate must be globally agreed (the branch contains
    collectives), so it is pmax-reduced over the mesh axis first."""
    if axis is not None:
        pred = jax.lax.pmax(pred.astype(jnp.int32), axis) > 0
    return jax.lax.cond(pred, fn, lambda: zeros)


def fault_masks(cfg, n: int):
    """(alive[N], honest[N]) bool masks from the fault config.

    Crashed nodes occupy the last ``n_crashed`` ids, Byzantine the last
    ``n_byzantine`` alive ids before them — so node 0 (PBFT initial leader,
    Paxos proposer) stays honest/alive under small fault counts."""
    f = cfg.faults
    nc = f.resolved_n_crashed(n)
    ids = np.arange(n)
    alive = ids < (n - nc)
    honest = ids < (n - nc - f.n_byzantine)
    return jnp.asarray(alive), jnp.asarray(honest)
