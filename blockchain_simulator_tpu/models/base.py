"""Protocol backend API.

The reference's plugin boundary is the per-protocol ``ns3::Application``
subclass, selected at *compile time* by editing network-helper.cc:17 and
blockchain-simulator.cc:72 (SURVEY.md §1).  Here a protocol backend is a
module-level triple of pure functions, selected at *runtime* by name:

- ``init(cfg, key) -> (state, bufs)``       — build the [N, ...] state pytree
  and the future-inbox ring buffers.
- ``step(cfg, state, bufs, t, tkey) -> (state, bufs)`` — one 1 ms tick for all
  N nodes at once (the tensorized equivalent of every event ns-3 would have
  dispatched in that interval: HandleRead FSM transitions + timer firings).
- ``metrics(cfg, state) -> dict``           — host-side structured metrics,
  reproducing the reference's NS_LOG measurement surface (SURVEY.md §5).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def get_protocol(name: str):
    """Runtime protocol selection (fixes the reference's compile-time switch)."""
    try:
        if name == "pbft":
            from blockchain_simulator_tpu.models import pbft as m
        elif name == "raft":
            from blockchain_simulator_tpu.models import raft as m
        elif name == "paxos":
            from blockchain_simulator_tpu.models import paxos as m
        elif name == "mixed":
            from blockchain_simulator_tpu.models import mixed as m
        else:
            raise ValueError(f"unknown protocol {name!r}")
    except ImportError as e:
        raise NotImplementedError(f"protocol backend {name!r} not available: {e}") from e
    return m


def sim_metrics(cfg, final) -> dict:
    """Host-side metrics for ONE final state, topology-aware: the committee
    path's final is a stacked [C, ...] pytree whose metrics are the
    two-level aggregate (topo/committee.py); every other topology is the
    flat protocol's own surface.  The one metrics door runner, sweeps and
    the scenario server share — call sites must not reach for
    ``get_protocol(cfg.protocol).metrics`` directly once a topology can
    reshape the final state."""
    if cfg.topology == "committee":
        from blockchain_simulator_tpu.topo import committee

        return committee.metrics(cfg, final)
    return get_protocol(cfg.protocol).metrics(cfg, final)


def gated(pred, fn, zeros, axis=None):
    """Skip a delivery computation when no sender is active this tick.
    Sharded, the predicate must be globally agreed (the branch contains
    collectives), so it is pmax-reduced over the mesh axis first."""
    if axis is not None:
        pred = jax.lax.pmax(pred.astype(jnp.int32), axis) > 0
    return jax.lax.cond(pred, fn, lambda: zeros)


def fault_masks(cfg, n: int):
    """(alive[N], honest[N]) bool masks from the fault config.

    Crashed nodes occupy the last ``n_crashed`` ids, Byzantine the last
    ``n_byzantine`` alive ids before them — so node 0 (PBFT initial leader,
    Paxos proposer) stays honest/alive under small fault counts."""
    f = cfg.faults
    nc = f.resolved_n_crashed(n)
    ids = np.arange(n)
    alive = ids < (n - nc)
    honest = ids < (n - nc - f.n_byzantine)
    return jnp.asarray(alive), jnp.asarray(honest)


def dyn_fault_masks(n: int, n_crashed, n_byzantine):
    """:func:`fault_masks` with the counts as TRACED operands.

    Same id layout (crashed = last ids, Byzantine = last alive ids before
    them), same int comparisons — bit-identical to the static masks at equal
    counts — but ``n_crashed`` / ``n_byzantine`` are scalar arrays, so one
    compiled program serves every fault level of a sweep
    (runner.make_dyn_sim_fn / parallel/sweep.py)."""
    ids = jnp.arange(n)
    nc = jnp.asarray(n_crashed, jnp.int32)
    nb = jnp.asarray(n_byzantine, jnp.int32)
    alive = ids < (n - nc)
    honest = ids < (n - nc - nb)
    return alive, honest


def canonical_fault_cfg(cfg):
    """The ONE static config whose dynamic-operand trace serves every
    (n_crashed, n_byzantine) point of a count sweep: counts zeroed to the
    FaultConfig defaults so every sweep over the same fault *structure*
    (drop_prob, byz_forge, byz_copies) shares one registry key.  ``seed``
    is normalized too — it never enters the trace (the PRNG key is a
    per-lane operand), so scenario requests and sweeps differing only in
    seed must share one executable (the serve/ batch-group contract).

    ``byz_forge`` keeps a static ``n_byzantine=1`` sentinel: pbft.step
    includes the forge wave in the trace only when the static count is
    positive, and the wave is driven by the traced ``alive & ~honest``
    forger mask — all-false at a dynamic f=0, where adding zero forged
    votes is bit-identical to the static f=0 program that omits the wave
    (the forge block consumes no PRNG keys)."""
    import dataclasses

    f = cfg.faults
    return cfg.with_(
        seed=0,
        faults=dataclasses.replace(
            f,
            crash_frac=0.0,
            n_crashed=-1,
            n_byzantine=1 if f.byz_forge else 0,
        )
    )


def apply_fault_masks(cfg, state, alive, honest):
    """Install traced fault masks into a state freshly init'd at the
    canonical (fault-free) config — bit-equal to ``init`` at the static
    config with those counts.

    Every protocol carries the masks as plain ``alive``/``honest`` state
    fields; raft additionally derives its initial election schedule from
    them (crashed nodes never start an election, models/raft.py init), so
    the disarm is re-applied here against the traced mask.  The mixed shard
    sim distributes faults per shard at init and is NOT supported
    (runner.make_dyn_sim_fn refuses it)."""
    state = state.replace(alive=alive, honest=honest)
    if cfg.protocol == "raft":
        from blockchain_simulator_tpu.models.raft import DISARM

        state = state.replace(
            election_deadline=jnp.where(alive, state.election_deadline, DISARM)
        )
    return state
