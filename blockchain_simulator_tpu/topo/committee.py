"""Two-level committee consensus (``topology="committee"``).

The hierarchy the scalable-BFT line runs in practice (PAPERS.md
2007.12637): N nodes split into ``cfg.committees`` equal committees of
m = N/C nodes; the FLAT protocol runs to quorum INSIDE each committee
(node c*m is that committee's node 0 — pbft initial leader / paxos
proposer lane 0), and an outer aggregate step over the committee
representatives declares the hierarchy's outcome once an outer quorum
(majority of committees) reports its inner milestone.

Execution shape: one ``lax.map`` over the stacked committee axis of the
UNVMAPPED inner tick engine — the same scatter-free batch body as the
multi-seed arm (parallel/partition.seq_map rationale, KNOWN_ISSUES #0i):
per-tick memory is O(C * f(m)) where f is the inner engine's footprint
(edge mode: O(N*m) total instead of O(N^2) — the committee-size memory
lever), and ring pushes stay plain dynamic-update-slices.

Fault layout: masks keep the repo's global last-ids rule
(models/base.dyn_fault_masks over the FULL id space, reshaped [C, m]) —
fault counts therefore concentrate in the tail committees, whose inner
consensus stalls first; counts stay traced operands, so ONE executable
serves every fault level per (protocol, committee structure).

One-committee contract (the pin in tests/test_zztopo.py): at C = 1 the
committee keys ARE the flat sim's key stream and the body IS the flat
dyn program, so the merged metrics dict contains the flat protocol's
metrics bit for bit, and the outer step adds zero latency (a single
representative has nobody to exchange with).

The outer aggregate is deterministic modeling, not a second simulated
consensus: representatives report their committee's inner milestone, and
the outer commit lands at the outer-quorum-th milestone plus one
worst-case representative round trip (``2*(one_way_hi - 1)``; 0 at
C = 1).  A simulated outer instance over the C representatives is the
natural extension (ROADMAP item 3 note).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from blockchain_simulator_tpu.models import base as base_model
from blockchain_simulator_tpu.utils import prng


def inner_cfg(cfg):
    """The flat per-committee config: n = committee size, full mesh inside
    the committee; everything else (protocol knobs, delivery, samplers,
    fault structure) inherits."""
    return cfg.with_(n=cfg.n // cfg.committees, topology="full")


def _committee_keys(key, c: int):
    """[C] stacked per-committee base keys.  C = 1 keeps the caller's key
    verbatim (the flat-protocol contract); C > 1 folds the committee index
    so committee streams decorrelate."""
    if c == 1:
        return key[None]
    return jax.vmap(lambda i: jax.random.fold_in(key, i))(jnp.arange(c))


def stacked_body(cfg, keys, alive_cm, honest_cm, probe=None):
    """The committee batch body: ``lax.map`` of the unvmapped inner tick
    engine over whatever leading committee axis the inputs carry —
    ``keys [c']``, ``alive_cm/honest_cm [c', m]`` -> stacked final state
    ``[c', ...]``.  Shared verbatim by :func:`run_stacked` (c' = C, one
    device) and the mesh arm (parallel/sweep.sharded_topo_sim_fn:
    shard_map hands each device its C/n_shards slice — the body never
    needs to know, there is no cross-committee communication before the
    host-side outer aggregate in :func:`metrics`).

    ``probe`` (obsim/build.py, utils/trace.py) arms per-committee taps:
    a ``(sample_fn, finalize_fn)`` pair — ``sample_fn(icfg, state) ->
    {field: scalar}`` per tick, ``finalize_fn(icfg, final, series) ->
    pytree`` over the committee's per-tick series ``{field: [T]}``
    (identity for full traces, windowed reduction + monitors for obsim).
    ``lax.map`` stacks the per-committee pytrees to leading-``[c', …]``
    leaves; returns ``(finals, probes)``.  The state trajectory is
    bit-identical to the unprobed call (taps only read)."""
    proto = base_model.get_protocol(cfg.protocol)
    icfg = inner_cfg(cfg)
    sample_fn, finalize_fn = probe or (None, None)

    def body(args):
        kc, alive_c, honest_c = args
        state, bufs = proto.init(icfg, jax.random.fold_in(kc, 0x1217))
        state = base_model.apply_fault_masks(icfg, state, alive_c, honest_c)

        def tick(carry, t):
            st, bf = carry
            st, bf = proto.step(icfg, st, bf, t, prng.tick_key(kc, t))
            return (st, bf), (
                sample_fn(icfg, st) if sample_fn is not None else ()
            )

        (state, bufs), ys = jax.lax.scan(
            tick, (state, bufs), jnp.arange(icfg.ticks)
        )
        if probe is None:
            return state
        return state, finalize_fn(icfg, state, ys)

    return jax.lax.map(body, (keys, alive_cm, honest_cm))


def run_stacked(cfg, key, n_crashed, n_byzantine, probe=None):
    """Traced committee sim: ``(key, n_crashed, n_byzantine) -> stacked
    final state [C, ...]`` — the dynamic-fault-operand program
    (runner.make_dyn_sim_fn committee arm; the static arm passes the
    config's own counts).  ``cfg`` must already be fault-canonical, like
    every dyn program (models/base.canonical_fault_cfg).  ``probe``
    threads through to :func:`stacked_body` (returns ``(finals,
    probes)`` when armed)."""
    c, m = cfg.committees, cfg.n // cfg.committees
    alive, honest = base_model.dyn_fault_masks(cfg.n, n_crashed, n_byzantine)
    keys = _committee_keys(key, c)
    return stacked_body(cfg, keys, alive.reshape(c, m), honest.reshape(c, m),
                        probe=probe)


def milestone_ms(protocol: str, inner_metrics: dict) -> float:
    """One committee's inner-consensus milestone: the tick its inner quorum
    completed the protocol's measured outcome, -1.0 if it never did."""
    m = inner_metrics
    if protocol == "pbft":
        return float(m["last_commit_ms"]) if m["blocks_final_all_nodes"] > 0 \
            else -1.0
    if protocol == "raft":
        return float(m["last_block_ms"]) if m["blocks"] > 0 else -1.0
    return float(m["winner_commit_ms"]) if m["n_committed_proposers"] > 0 \
        else -1.0


def metrics(cfg, finals) -> dict:
    """Host-side metrics of a stacked committee final state.

    C = 1: the flat protocol's full metrics dict (bit-equal to the flat
    run — the tests' contract) plus the ``outer_*`` keys.  C > 1: the
    outer aggregate plus the per-committee milestone list (hand-checkable
    against the formula: ``outer_commit_ms`` = outer-quorum-th smallest
    decided milestone + one representative round trip)."""
    proto = base_model.get_protocol(cfg.protocol)
    c = cfg.committees
    icfg = inner_cfg(cfg)
    inner = [
        proto.metrics(icfg, jax.tree.map(lambda x, i=i: x[i], finals))
        for i in range(c)
    ]
    miles = [milestone_ms(cfg.protocol, m) for m in inner]
    decided = sorted(t for t in miles if t >= 0)
    quorum = c // 2 + 1
    outer_round = 0.0 if c == 1 else float(2 * (cfg.one_way_range()[1] - 1))
    outer_commit = (
        decided[quorum - 1] + outer_round if len(decided) >= quorum else -1.0
    )
    outer = {
        "topology": "committee",
        "committees": c,
        "committee_size": icfg.n,
        "outer_quorum": quorum,
        "committees_decided": len(decided),
        "inner_milestones_ms": miles,
        "outer_round_ms": outer_round,
        "outer_commit_ms": float(outer_commit),
        "inner_agreement_ok": all(
            bool(m.get("agreement_ok", True)) for m in inner
        ),
    }
    if c == 1:
        return {**inner[0], **outer}
    return {
        "protocol": cfg.protocol,
        "n": cfg.n,
        "agreement_ok": outer["inner_agreement_ok"],
        **outer,
    }
