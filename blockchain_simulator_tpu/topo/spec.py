"""Topology representation: kinds, and the seeded k-regular overlay tables.

``TopoSpec`` is the typed view of ``SimConfig``'s topology axis — the
*structure* half of the runtime operand split: kind/degree/committees key
the executable registry (they change program shapes), while fault counts
and seeds stay traced operands riding ONE compiled program per topology.

The ``kregular`` member is a **circulant** k-regular digraph: a seeded
choice of k distinct offsets from 1..N-1 (offset 1 always included, so the
successor ring guarantees strong connectivity) plus offset 0 (the self
slot, masked at delivery).  Node j's in-neighbors are ``{(j + o) % N}``
and its out-neighbors ``{(j - o) % N}`` over the same offset set, so the
graph is k-in- AND k-out-regular with aligned slot tables — exactly what
the requester-side reply *gathers* in ops/gatherdeliv.py need to stay
scatter-free.

Rows are sorted ascending.  That is the bit-equality mechanism the repo
pins everything on: at degree k = N-1 the offset set is all of 0..N-1 and
every sorted row is ``[0, 1, .., N-1]`` — the identity table — so the
slot-major ``[K, N]`` delay draws of the gather path are the SAME arrays
the dense ``[N, N]`` path draws from the same threefry keys, and the
sparse program's metrics are bit-equal to the dense program's
(tests/test_zztopo.py, per protocol).

Pure numpy — importable with no jax/backend touch (jaxlint
``module-scope-backend-touch``); builders are memoized, so the per-tick
model code pays one table build per (n, degree, seed) per process.
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np

# Topology kinds (SimConfig.topology after the "dense" -> "full" alias
# normalization).
DENSE = "full"
GOSSIP = "gossip"
KREGULAR = "kregular"
COMMITTEE = "committee"


@dataclasses.dataclass(frozen=True)
class TopoSpec:
    """The structural identity of one topology: everything that changes
    compiled program SHAPES (and therefore belongs in the registry key —
    which it reaches automatically, being derived from SimConfig fields)."""

    kind: str
    n: int
    degree: int = 0       # kregular overlay degree k (0 for other kinds)
    committees: int = 0   # committee count (0 for other kinds)
    seed: int = 0         # overlay-builder seed (kregular only)

    @classmethod
    def from_config(cls, cfg) -> "TopoSpec":
        if cfg.topology == KREGULAR:
            return cls(KREGULAR, cfg.n, degree=cfg.degree, seed=cfg.topo_seed)
        if cfg.topology == COMMITTEE:
            return cls(COMMITTEE, cfg.n, committees=cfg.committees)
        return cls(cfg.topology, cfg.n)

    @property
    def slots(self) -> int:
        """Neighbor-table slot count K = degree + 1 (the self slot rides
        along, masked at delivery — at k = N-1, K = N and the table is the
        identity permutation)."""
        return self.degree + 1

    @property
    def committee_size(self) -> int:
        return self.n // self.committees if self.committees else self.n


@functools.lru_cache(maxsize=64)
def circulant_offsets(n: int, degree: int, seed: int) -> tuple:
    """The seeded offset set O of the circulant overlay: ``degree``
    distinct values from 1..n-1 (offset 1 always included — the successor
    ring makes the digraph strongly connected), plus offset 0 (self slot).
    Deterministic in (n, degree, seed)."""
    if not 1 <= degree <= n - 1:
        raise ValueError(f"degree={degree} must be in [1, {n - 1}]")
    if degree == n - 1:
        return tuple(range(n))  # the full mesh: every offset
    rng = np.random.default_rng(np.uint64(seed) ^ np.uint64(0x70B0_C14C))
    rest = rng.choice(np.arange(2, n), size=degree - 1, replace=False)
    return tuple(sorted({0, 1, *rest.tolist()}))


@functools.lru_cache(maxsize=32)
def _tables(n: int, degree: int, seed: int):
    """(nbr_in, nbr_out, inslot_of_out) int32 tables, rows sorted.

    - ``nbr_in[j]``  = sorted ``{(j + o) % n : o in O}``  — who j hears.
    - ``nbr_out[i]`` = sorted ``{(i - o) % n : o in O}``  — who hears i.
    - ``inslot_of_out[i, s]`` = the slot index of i inside
      ``nbr_in[nbr_out[i, s]]`` — the cross-index that lets a requester
      GATHER its per-slot replies back (ops/gatherdeliv.
      unicast_reply_counts_kreg) instead of the repliers scattering them.

    At degree n-1 all three are the identity-pattern tables (``nbr_in[j,s]
    = s``), which is the whole bit-equality contract."""
    offs = np.asarray(circulant_offsets(n, degree, seed), np.int64)
    ids = np.arange(n, dtype=np.int64)[:, None]
    nbr_in = np.sort((ids + offs[None, :]) % n, axis=1)
    nbr_out = np.sort((ids - offs[None, :]) % n, axis=1)
    # invert: i sits at exactly one slot of nbr_in[recv] for every receiver
    # recv = nbr_out[i, s] (i in in(recv) <=> recv in out(i)); rows are
    # sorted + distinct, so searchsorted is an exact index
    rows = nbr_in[nbr_out]                       # [n, K, K]
    inslot = np.argmax(rows == np.arange(n)[:, None, None], axis=2)
    assert (np.take_along_axis(rows, inslot[:, :, None], 2)[:, :, 0]
            == np.arange(n)[:, None]).all()
    return (nbr_in.astype(np.int32), nbr_out.astype(np.int32),
            inslot.astype(np.int32))


def in_table(n: int, degree: int, seed: int) -> np.ndarray:
    """[N, K] sorted in-neighbor table (K = degree + 1, self included)."""
    return _tables(n, degree, seed)[0]


def out_table(n: int, degree: int, seed: int) -> np.ndarray:
    """[N, K] sorted out-neighbor table."""
    return _tables(n, degree, seed)[1]


def inslot_table(n: int, degree: int, seed: int) -> np.ndarray:
    """[N, K]: ``inslot_table(..)[i, s]`` = slot of i in
    ``in_table(..)[out_table(..)[i, s]]`` (the reply-gather cross-index)."""
    return _tables(n, degree, seed)[2]


def overlay_diameter(n: int, degree: int, seed: int) -> int:
    """BFS diameter of the out-digraph from node 0 (validation aid; the
    circulant is vertex-transitive, so one source suffices)."""
    nbr = out_table(n, degree, seed)
    dist = np.full(n, -1)
    dist[0] = 0
    frontier = [0]
    hops = 0
    while frontier:
        hops += 1
        nxt = []
        for u in frontier:
            for v in nbr[u]:
                if dist[v] < 0:
                    dist[v] = hops
                    nxt.append(v)
        frontier = nxt
    if (dist < 0).any():
        raise ValueError("overlay not strongly connected (builder bug)")
    return int(dist.max())
