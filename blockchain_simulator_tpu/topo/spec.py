"""Topology representation: kinds, and the seeded k-regular overlay tables.

``TopoSpec`` is the typed view of ``SimConfig``'s topology axis — the
*structure* half of the runtime operand split: kind/degree/committees key
the executable registry (they change program shapes), while fault counts
and seeds stay traced operands riding ONE compiled program per topology.

The ``kregular`` member is a **circulant** k-regular digraph: a seeded
choice of k distinct offsets from 1..N-1 (offset 1 always included, so the
successor ring guarantees strong connectivity) plus offset 0 (the self
slot, masked at delivery).  Node j's in-neighbors are ``{(j + o) % N}``
and its out-neighbors ``{(j - o) % N}`` over the same offset set, so the
graph is k-in- AND k-out-regular with aligned slot tables — exactly what
the requester-side reply *gathers* in ops/gatherdeliv.py need to stay
scatter-free.

Rows are sorted ascending.  That is the bit-equality mechanism the repo
pins everything on: at degree k = N-1 the offset set is all of 0..N-1 and
every sorted row is ``[0, 1, .., N-1]`` — the identity table — so the
slot-major ``[K, N]`` delay draws of the gather path are the SAME arrays
the dense ``[N, N]`` path draws from the same threefry keys, and the
sparse program's metrics are bit-equal to the dense program's
(tests/test_zztopo.py, per protocol).

Pure numpy — importable with no jax/backend touch (jaxlint
``module-scope-backend-touch``); builders are memoized, so the per-tick
model code pays one table build per (n, degree, seed) per process.
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np

# Topology kinds (SimConfig.topology after the "dense" -> "full" alias
# normalization).
DENSE = "full"
GOSSIP = "gossip"
KREGULAR = "kregular"
COMMITTEE = "committee"


@dataclasses.dataclass(frozen=True)
class TopoSpec:
    """The structural identity of one topology: everything that changes
    compiled program SHAPES (and therefore belongs in the registry key —
    which it reaches automatically, being derived from SimConfig fields)."""

    kind: str
    n: int
    degree: int = 0       # kregular overlay degree k (0 for other kinds)
    committees: int = 0   # committee count (0 for other kinds)
    seed: int = 0         # overlay-builder seed (kregular only)

    @classmethod
    def from_config(cls, cfg) -> "TopoSpec":
        if cfg.topology == KREGULAR:
            return cls(KREGULAR, cfg.n, degree=cfg.degree, seed=cfg.topo_seed)
        if cfg.topology == COMMITTEE:
            return cls(COMMITTEE, cfg.n, committees=cfg.committees)
        return cls(cfg.topology, cfg.n)

    @property
    def slots(self) -> int:
        """Neighbor-table slot count K = degree + 1 (the self slot rides
        along, masked at delivery — at k = N-1, K = N and the table is the
        identity permutation)."""
        return self.degree + 1

    @property
    def committee_size(self) -> int:
        return self.n // self.committees if self.committees else self.n


@functools.lru_cache(maxsize=64)
def circulant_offsets(n: int, degree: int, seed: int) -> tuple:
    """The seeded offset set O of the circulant overlay: ``degree``
    distinct values from 1..n-1 (offset 1 always included — the successor
    ring makes the digraph strongly connected), plus offset 0 (self slot).
    Deterministic in (n, degree, seed)."""
    if not 1 <= degree <= n - 1:
        raise ValueError(f"degree={degree} must be in [1, {n - 1}]")
    if degree == n - 1:
        return tuple(range(n))  # the full mesh: every offset
    rng = np.random.default_rng(np.uint64(seed) ^ np.uint64(0x70B0_C14C))
    rest = rng.choice(np.arange(2, n), size=degree - 1, replace=False)
    return tuple(sorted({0, 1, *rest.tolist()}))


@functools.lru_cache(maxsize=32)
def _tables(n: int, degree: int, seed: int):
    """(nbr_in, nbr_out, inslot_of_out) int32 tables, rows sorted.

    - ``nbr_in[j]``  = sorted ``{(j + o) % n : o in O}``  — who j hears.
    - ``nbr_out[i]`` = sorted ``{(i - o) % n : o in O}``  — who hears i.
    - ``inslot_of_out[i, s]`` = the slot index of i inside
      ``nbr_in[nbr_out[i, s]]`` — the cross-index that lets a requester
      GATHER its per-slot replies back (ops/gatherdeliv.
      unicast_reply_counts_kreg) instead of the repliers scattering them.

    At degree n-1 all three are the identity-pattern tables (``nbr_in[j,s]
    = s``), which is the whole bit-equality contract."""
    offs = np.asarray(circulant_offsets(n, degree, seed), np.int64)
    ids = np.arange(n, dtype=np.int64)[:, None]
    nbr_in = np.sort((ids + offs[None, :]) % n, axis=1)
    nbr_out = np.sort((ids - offs[None, :]) % n, axis=1)
    # invert: i sits at exactly one slot of nbr_in[recv] for every receiver
    # recv = nbr_out[i, s] (i in in(recv) <=> recv in out(i)); rows are
    # sorted + distinct, so searchsorted is an exact index
    rows = nbr_in[nbr_out]                       # [n, K, K]
    inslot = np.argmax(rows == np.arange(n)[:, None, None], axis=2)
    assert (np.take_along_axis(rows, inslot[:, :, None], 2)[:, :, 0]
            == np.arange(n)[:, None]).all()
    return (nbr_in.astype(np.int32), nbr_out.astype(np.int32),
            inslot.astype(np.int32))


def in_table(n: int, degree: int, seed: int) -> np.ndarray:
    """[N, K] sorted in-neighbor table (K = degree + 1, self included)."""
    return _tables(n, degree, seed)[0]


def out_table(n: int, degree: int, seed: int) -> np.ndarray:
    """[N, K] sorted out-neighbor table."""
    return _tables(n, degree, seed)[1]


def inslot_table(n: int, degree: int, seed: int) -> np.ndarray:
    """[N, K]: ``inslot_table(..)[i, s]`` = slot of i in
    ``in_table(..)[out_table(..)[i, s]]`` (the reply-gather cross-index)."""
    return _tables(n, degree, seed)[2]


def owner_bucket_plan(table, n_shards: int, capacity: int | None = None):
    """Build-time owner-bucketed exchange plan for a ``[N_pad, K]`` neighbor
    table over ``n_shards`` node shards (N_pad divisible by n_shards;
    row g lives on shard ``g // n_loc`` with ``n_loc = N_pad // n_shards``).

    Returns ``(pos, send)``:

    - ``send[o, d, :]`` — the **shard-local** row indices shard ``o`` must
      ship to shard ``d``: the sorted distinct global rows referenced by
      receiver d's table slice that are owned by o, minus ``o * n_loc``
      (zero-padded to the bucket capacity C).  Shaped ``[D, D, C]`` so a
      ``P(nodes)`` sharding hands each owner shard its own send row.
    - ``pos[i, j]`` — where table entry ``table[i, j]`` lands in the
      receiver's concatenated exchange buffer: after
      ``all_to_all(take(x_loc, send[o] rows))`` flattens to ``[D * C, ...]``
      on shard d, the row for global id g sits at
      ``o * C + rank_of(g in bucket(d, o))``.  Shaped like ``table``.

    The per-round capacity C is static: the max bucket size over every
    (receiver, owner) pair, provably <= min(n_loc, K * n_loc) for a
    k-regular overlay.  Passing an explicit ``capacity`` smaller than the
    required C raises ``ValueError`` — overflow is a checked invariant,
    never a silent truncation (undersized buffers would drop neighbor rows
    and corrupt delivery counts silently otherwise).
    """
    table = np.asarray(table)
    n_pad, _k = table.shape
    if n_shards < 1 or n_pad % n_shards:
        raise ValueError(
            f"owner_bucket_plan: N_pad={n_pad} not divisible by "
            f"n_shards={n_shards}"
        )
    n_loc = n_pad // n_shards
    if table.size and (table.min() < 0 or table.max() >= n_pad):
        raise ValueError("owner_bucket_plan: table entries outside [0, N_pad)")
    # pass 1: per receiver shard, the sorted distinct referenced rows.
    # owner(g) = g // n_loc is monotone in g, so each owner's bucket is a
    # contiguous run of the sorted uniques — searchsorted finds the cuts.
    per_recv = []
    required = 0
    shard_ids = np.arange(n_shards)
    for d in range(n_shards):
        uniq, inv = np.unique(table[d * n_loc:(d + 1) * n_loc],
                              return_inverse=True)
        starts = np.searchsorted(uniq // n_loc, shard_ids)
        counts = np.diff(np.append(starts, len(uniq)))
        required = max(required, int(counts.max()) if len(uniq) else 0)
        per_recv.append((uniq, inv, starts))
    if capacity is None:
        capacity = required
    elif capacity < required:
        raise ValueError(
            f"owner_bucket_plan: bucket capacity {capacity} < required "
            f"{required} (n_shards={n_shards}, n_loc={n_loc}) — refusing to "
            "truncate the exchange"
        )
    # pass 2: fill pos (receiver-buffer positions) and send (owner rows)
    capacity = max(capacity, 1)
    pos = np.empty_like(table, dtype=np.int32)
    send = np.zeros((n_shards, n_shards, capacity), np.int32)
    for d in range(n_shards):
        uniq, inv, starts = per_recv[d]
        own = uniq // n_loc
        rank = np.arange(len(uniq)) - starts[own]
        pos[d * n_loc:(d + 1) * n_loc] = (
            own[inv] * capacity + rank[inv]
        ).reshape(n_loc, -1).astype(np.int32)
        send[own, d, rank] = (uniq - own * n_loc).astype(np.int32)
    return pos, send


def overlay_diameter(n: int, degree: int, seed: int) -> int:
    """BFS diameter of the out-digraph from node 0 (validation aid; the
    circulant is vertex-transitive, so one source suffices)."""
    nbr = out_table(n, degree, seed)
    dist = np.full(n, -1)
    dist[0] = 0
    frontier = [0]
    hops = 0
    while frontier:
        hops += 1
        nxt = []
        for u in frontier:
            for v in nbr[u]:
                if dist[v] < 0:
                    dist[v] = hops
                    nxt.append(v)
        frontier = nxt
    if (dist < 0).any():
        raise ValueError("overlay not strongly connected (builder bug)")
    return int(dist.max())
