"""Sparse & hierarchical topologies — the runtime topology axis.

The reference builds exactly one topology (a full N x (N-1)/2 mesh,
blockchain-simulator.cc:34-51) and every tensorized model historically
materialized it as dense N x N edge tensors — quadratic memory, ~100k
nodes practical ceiling (ROADMAP item 3).  This package makes topology a
runtime axis orthogonal to the protocol, the way fault structure already
is:

- :mod:`~blockchain_simulator_tpu.topo.spec` — the representation type
  (``TopoSpec``) and the seeded, deterministic circulant overlay builders
  behind ``topology="kregular"`` (fixed-degree neighbor-index tables the
  models consume through the gather-based delivery primitives in
  ``ops/gatherdeliv.py``: O(N*k) per tick instead of O(N^2), bit-equal to
  the dense program at degree k = N-1);
- :mod:`~blockchain_simulator_tpu.topo.committee` — two-level committee
  consensus behind ``topology="committee"``: inner-quorum consensus per
  committee (a scatter-free ``lax.map`` over the stacked committee axis)
  plus an outer aggregate step over committee representatives; with one
  committee it IS the flat protocol.

Import-clean by the jaxlint ``module-scope-backend-touch`` contract: no
module in this package touches a backend (or jax at all, for spec.py) at
import time.
"""

from blockchain_simulator_tpu.topo.spec import TopoSpec  # noqa: F401
