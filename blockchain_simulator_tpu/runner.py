"""Simulation runner: the tensorized replacement for ``Simulator::Run``.

The reference drives everything through ns-3's serial event dispatch
(blockchain-simulator.cc:57; SURVEY.md §3.1 "THE hot loop").  Here the whole
simulation is one ``jax.lax.scan`` over ticks, compiled once by XLA: per tick,
every node's FSM transition and every in-flight message delivery happen as
batched tensor ops.  Protocol selection is a runtime config field.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp

from blockchain_simulator_tpu.models.base import get_protocol
from blockchain_simulator_tpu.utils import prng
from blockchain_simulator_tpu.utils.config import SimConfig


@functools.lru_cache(maxsize=64)
def make_sim_fn(cfg: SimConfig):
    """Build (and cache) the jitted end-to-end simulation function for a config.

    Returns ``sim(key) -> final_state`` running ``cfg.ticks`` ticks.
    """
    proto = get_protocol(cfg.protocol)

    @jax.jit
    def sim(key):
        state, bufs = proto.init(cfg, jax.random.fold_in(key, 0x1217))

        def body(carry, t):
            st, bf = carry
            st, bf = proto.step(cfg, st, bf, t, prng.tick_key(key, t))
            return (st, bf), ()

        (state, bufs), _ = jax.lax.scan(body, (state, bufs), jnp.arange(cfg.ticks))
        return state

    return sim


def run_simulation(cfg: SimConfig, seed: int | None = None, with_timing: bool = False):
    """Run one simulation; returns the protocol's structured metrics dict
    (the reference's NS_LOG lines, SURVEY.md §5, as data)."""
    proto = get_protocol(cfg.protocol)
    sim = make_sim_fn(cfg)
    key = jax.random.key(cfg.seed if seed is None else seed)
    t0 = time.perf_counter()
    final = jax.block_until_ready(sim(key))
    wall = time.perf_counter() - t0
    m = proto.metrics(cfg, final)
    if with_timing:
        m["wallclock_s"] = wall
        m["ticks"] = cfg.ticks
    return m


def final_state(cfg: SimConfig, seed: int | None = None):
    """Run and return the raw final state pytree (for tests/checkpointing)."""
    sim = make_sim_fn(cfg)
    key = jax.random.key(cfg.seed if seed is None else seed)
    return jax.block_until_ready(sim(key))
