"""Simulation runner: the tensorized replacement for ``Simulator::Run``.

The reference drives everything through ns-3's serial event dispatch
(blockchain-simulator.cc:57; SURVEY.md §3.1 "THE hot loop").  Here the whole
simulation is one ``jax.lax.scan`` over ticks, compiled once by XLA: per tick,
every node's FSM transition and every in-flight message delivery happen as
batched tensor ops.  Protocol selection is a runtime config field.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from blockchain_simulator_tpu.models import base as base_model
from blockchain_simulator_tpu.models.base import get_protocol
from blockchain_simulator_tpu.utils import aotcache, prng
from blockchain_simulator_tpu.utils.config import SimConfig
from blockchain_simulator_tpu.utils.sync import force_sync


class UnbatchableConfigError(NotImplementedError):
    """A config whose faults cannot become traced per-run operands — it has
    no dynamic-fault-operand program (``make_dyn_sim_fn``), so it can join
    neither a compile-once sweep group (parallel/sweep.py) nor a micro-batched
    serving dispatch (serve/).

    Typed so the sweep layer and the scenario server classify the refusal
    without string-matching; subclasses ``NotImplementedError`` so historical
    ``except NotImplementedError`` call sites keep working."""


def check_batchable(cfg: SimConfig) -> None:
    """Raise :class:`UnbatchableConfigError` when ``cfg`` has no
    dynamic-fault-operand program.  Currently that is exactly the mixed
    shard sim: its faults are per-shard *init structure*, not maskable
    state (models/base.apply_fault_masks)."""
    if cfg.protocol == "mixed":
        raise UnbatchableConfigError(
            "dynamic fault operands are not implemented for the mixed shard "
            "sim (faults live at the raft-shard level, models/mixed.py); "
            "sweep it with one static compile per fault config"
        )


def use_round_schedule(cfg: SimConfig) -> bool:
    """Resolve cfg.schedule: does this config run a phase-blocked fast path
    (PBFT: one scan step per block interval; raft: per heartbeat; mixed: the
    heartbeat scan inside every raft shard)?"""
    if cfg.schedule == "tick":
        return False
    if cfg.topology in ("kregular", "committee"):
        # the phase-blocked fast paths are full-mesh aggregates
        # (pbft_round/raft_hb eligibility already pins topology == "full");
        # the sparse/hierarchical axes run the general tick engine — inside
        # each committee too (topo/committee.py runs proto.step per tick)
        if cfg.schedule == "round":
            raise ValueError(
                f"schedule='round' is a full-mesh fast path; topology="
                f"{cfg.topology!r} runs the tick engine (use schedule="
                "'tick' or 'auto')"
            )
        return False
    if cfg.protocol == "raft":
        from blockchain_simulator_tpu.models import raft_hb

        ok = raft_hb.eligible(cfg)
        if cfg.schedule == "round":
            if not ok:
                raise ValueError(
                    "schedule='round' for raft requires clean fidelity + "
                    "full mesh + stat delivery with no drops/queued links, "
                    "heartbeat < election_lo, and a window longer than the "
                    "election prefix (models/raft_hb.eligible)"
                )
            return True
        return ok and cfg.n >= 4096  # "auto"
    if cfg.protocol == "mixed":
        from blockchain_simulator_tpu.models import mixed

        ok = mixed.fast_eligible(cfg)
        if cfg.schedule == "round":
            if not ok:
                raise ValueError(
                    "schedule='round' for the mixed sim requires its raft "
                    "shards to be heartbeat-schedulable: clean fidelity + "
                    "stat delivery with no drops/queued links and a window "
                    "longer than the election prefix (models/raft_hb.eligible "
                    "on the shard sub-config)"
                )
            return True
        # "auto": no n-threshold — the handoff is checked per shard and the
        # fallback CONTINUES the tick scan from the prefix carry, so the
        # fast path is never slower than the tick engine it replaces
        return ok
    if cfg.protocol != "pbft":
        return False
    from blockchain_simulator_tpu.models import pbft_round

    ok = pbft_round.eligible(cfg)
    if cfg.schedule == "round":
        if not ok:
            raise ValueError(
                "schedule='round' requires pbft + full mesh + stat delivery "
                "with no byz_forge, no queued links, drops only when view "
                "changes are disabled AND the vote table is exact "
                "(pbft_window = 0 or >= pbft_max_slots), and a message "
                "horizon — including the constant block-serialization "
                "latency when modeled — inside one block interval "
                "(models/pbft_round.eligible)"
            )
        return True
    return ok and cfg.n >= 4096  # "auto"


def _reject_cpp_only(cfg: SimConfig) -> None:
    """Validate fidelity modes on the tensorized backends: refuse what only
    the C++ engine models, rather than silently returning constant-latency /
    echo-free numbers for it."""
    if cfg.echo_back:
        raise NotImplementedError(
            "echo_back (quirk #1) is modeled by the C++ engine only "
            "(engine.run_cpp): the tensorized backends design the echo away "
            "(models/pbft.py docstring).  Deliberate scope decision, "
            "re-evaluated round 5: a reflected packet is processed through "
            "the full FSM, so echoed PREPAREs spawn fresh replies that are "
            "themselves reflected — exact fidelity needs up-to-6-leg "
            "reflection-cascade delay convolutions per vote channel, at odds "
            "with the aggregate count-based channel design that makes these "
            "engines fast; the C++ engine covers the quirk and "
            "tests/test_fidelity.py pins the traffic delta"
        )
    if cfg.queued_links:
        # pbft: per-destination serial-pipe registers (models/pbft.py).
        # paxos: every message is 3-4 bytes (ser = 0), the pipe is never
        # busy, and queued-link transport IS the constant-latency model —
        # accepted as-is (the C++ engine reduces identically,
        # tests/test_fidelity.py::test_queued_links_zero_serialization...).
        # pbft/raft: per-destination serial-pipe registers (models/pbft.py
        # FIFOs, models/raft.py widened rings).  paxos messages are all 3-4
        # bytes (ser = 0), the pipe is never busy, and queued-link transport
        # IS the constant-latency model — accepted as-is (the C++ engine
        # reduces identically, tests/test_fidelity.py).
        if cfg.protocol == "mixed":
            raise NotImplementedError(
                "queued_links is not modeled by the mixed shard sim (its "
                "raft shards are small full meshes whose timing the cross-"
                "shard PBFT layer aggregates); use pbft/raft/paxos directly"
            )
        if cfg.protocol in ("pbft", "raft"):
            if cfg.topology != "full":
                raise ValueError(
                    "queued_links (tensorized) requires topology='full': the "
                    "serial-pipe registers model the leader's direct links"
                )
            if cfg.faults.drop_prob != 0.0:
                raise ValueError(
                    "queued_links (tensorized) requires drop_prob = 0: with "
                    "drops, leader beliefs can diverge and the per-destination "
                    "busy registers assume a single block sender; use the C++ "
                    "engine (engine.run_cpp) for queued links with drops"
                )
        if cfg.protocol == "pbft":
            from blockchain_simulator_tpu.models import pbft

            _, hi = cfg.one_way_range()
            if pbft.eff_window(cfg) < cfg.pbft_max_slots:
                raise ValueError(
                    "queued_links (tensorized) requires the exact vote table "
                    "(pbft_window = 0 or >= pbft_max_slots): a backlogged "
                    "block can trail its slot's votes past a window re-tenancy"
                )
            if hi - 1 >= cfg.pbft_block_interval_ms:
                raise ValueError(
                    "queued_links (tensorized) requires the one-way delay to "
                    "fit inside one block interval so leadership rotations "
                    "settle between block sends"
                )


@aotcache.cached_factory("sim")
def make_sim_fn(cfg: SimConfig):
    """Build (and cache) the jitted end-to-end simulation function for a config.

    Returns ``sim(key) -> final_state`` running ``cfg.ticks`` ticks — the
    general per-tick engine or, when the config resolves to it, a phase-
    blocked fast path: round-blocked PBFT (one scan step per 50 ms block
    interval, models/pbft_round.py), heartbeat-blocked raft behind a traced
    checked handoff (models/raft_hb.py), or the heartbeat-scheduled mixed
    sim (models/mixed.scan_fast).  Every returned function is fully traced
    (no host branches), so it composes with vmap and shard_map.

    Caching lives in the unified executable registry (utils/aotcache.py,
    hit/miss stats on every run manifest) rather than a per-module
    ``lru_cache``; the callable per config is still built exactly once per
    process.  Every engine arm this factory can dispatch to is traced and
    budget-pinned by the graph audit (lint/graph/programs.py ``sim.*``
    specs; ``python -m blockchain_simulator_tpu.lint.graph``).
    """
    _reject_cpp_only(cfg)
    if cfg.topology == "committee":
        from blockchain_simulator_tpu.topo import committee

        use_round_schedule(cfg)  # validates schedule='round' (always tick)
        # static arm of the committee hierarchy: the config's own fault
        # counts ride the (traced) operand slots of the shared dyn body,
        # mirroring the static==dyn equality every protocol pins
        # (tests/test_zsweep_cache.py), so ONE body serves both doors
        canon = base_model.canonical_fault_cfg(cfg)
        nc = cfg.faults.resolved_n_crashed(cfg.n)
        nb = cfg.faults.n_byzantine

        @jax.jit
        def sim_committee(key):
            return committee.run_stacked(
                canon, key, jnp.int32(nc), jnp.int32(nb)
            )

        return sim_committee
    if use_round_schedule(cfg):
        if cfg.protocol == "raft":
            from blockchain_simulator_tpu.models import raft_hb

            # the checked handoff is a lax.cond inside the trace
            # (models/raft_hb.scan_from_init): the whole program lowers
            # under jit, vmap (sweeps) and shard_map — no host branch
            return jax.jit(functools.partial(raft_hb.run, cfg))
        if cfg.protocol == "mixed":
            from blockchain_simulator_tpu.models import mixed

            @jax.jit
            def sim_mixed(key):
                state, bufs = mixed.init(cfg, jax.random.fold_in(key, 0x1217))
                return mixed.scan_fast(cfg, state, bufs, key)

            return sim_mixed
        from blockchain_simulator_tpu.models import pbft_round

        @jax.jit
        def sim_round(key):
            state, _ = pbft_round.init(cfg, jax.random.fold_in(key, 0x1217))
            return pbft_round.scan_rounds(cfg, state, key)

        return sim_round

    proto = get_protocol(cfg.protocol)

    @jax.jit
    def sim(key):
        state, bufs = proto.init(cfg, jax.random.fold_in(key, 0x1217))

        def body(carry, t):
            st, bf = carry
            st, bf = proto.step(cfg, st, bf, t, prng.tick_key(key, t))
            return (st, bf), ()

        (state, bufs), _ = jax.lax.scan(body, (state, bufs), jnp.arange(cfg.ticks))
        return state

    return sim


def make_dyn_sim_fn(cfg: SimConfig):
    """Build the dynamic-fault-operand simulation function for a config:
    ``sim(key, n_crashed, n_byzantine) -> final_state`` with the fault
    COUNTS as traced scalars (fault masks computed inside the trace,
    models/base.dyn_fault_masks), so one compiled program serves every
    fault level of a sweep — the compile-once substrate of
    parallel/sweep.run_fault_sweep / run_byzantine_sweep.

    ``cfg`` is canonicalized (models/base.canonical_fault_cfg) so every
    sweep over the same fault *structure* shares one trace; at equal
    counts the result is bit-equal to ``make_sim_fn`` at the static config
    (pinned in tests/test_zsweep_cache.py).  Returns the UNJITTED function:
    the sweep layer owns the single ``jit(vmap(...))`` wrapper, so an
    f-sweep costs exactly one executable.  The mixed shard sim distributes
    faults per shard at init and is refused with a typed
    :class:`UnbatchableConfigError` (:func:`check_batchable`)."""
    cfg = base_model.canonical_fault_cfg(cfg)
    check_batchable(cfg)
    _reject_cpp_only(cfg)
    n = cfg.n

    if cfg.topology == "committee":
        from blockchain_simulator_tpu.topo import committee

        use_round_schedule(cfg)  # validates schedule='round' (always tick)
        return functools.partial(committee.run_stacked, cfg)

    if use_round_schedule(cfg):
        if cfg.protocol == "raft":
            from blockchain_simulator_tpu.models import raft as raft_tick
            from blockchain_simulator_tpu.models import raft_hb

            def sim_hb(key, n_crashed, n_byzantine):
                state, bufs = raft_tick.init(cfg, jax.random.fold_in(key, 0x1217))
                state = base_model.apply_fault_masks(
                    cfg, state, *base_model.dyn_fault_masks(n, n_crashed, n_byzantine)
                )
                return raft_hb.scan_from_init(cfg, state, bufs, key)

            return sim_hb
        from blockchain_simulator_tpu.models import pbft_round

        def sim_round(key, n_crashed, n_byzantine):
            state, _ = pbft_round.init(cfg, jax.random.fold_in(key, 0x1217))
            state = base_model.apply_fault_masks(
                cfg, state, *base_model.dyn_fault_masks(n, n_crashed, n_byzantine)
            )
            return pbft_round.scan_rounds(cfg, state, key)

        return sim_round

    proto = get_protocol(cfg.protocol)

    def sim(key, n_crashed, n_byzantine):
        state, bufs = proto.init(cfg, jax.random.fold_in(key, 0x1217))
        state = base_model.apply_fault_masks(
            cfg, state, *base_model.dyn_fault_masks(n, n_crashed, n_byzantine)
        )

        def body(carry, t):
            st, bf = carry
            st, bf = proto.step(cfg, st, bf, t, prng.tick_key(key, t))
            return (st, bf), ()

        (state, bufs), _ = jax.lax.scan(body, (state, bufs), jnp.arange(cfg.ticks))
        return state

    return sim


def topo_tables_inslot(cfg: SimConfig) -> bool:
    """Does this protocol's kregular arm consume the ``inslot`` cross-index
    (three tables) or just the in/out pair (two)?  The one place the
    operand-feeding callers (parallel/sweep.sharded_topo_sim_fn, the graph
    audit specs) learn the table arity."""
    return cfg.protocol == "raft"


def make_topo_dyn_sim_fn(cfg: SimConfig, exchange_spec=None):
    """The tables-as-operands twin of :func:`make_dyn_sim_fn` for the
    kregular overlay: ``sim(key, n_crashed, n_byzantine, *tables) ->
    final_state`` where ``tables`` are the full ``[N, K]`` int32 overlay
    tables (ops/gatherdeliv.table_operands — ``(in, out)``, plus
    ``inslot`` for raft; :func:`topo_tables_inslot`).  Feeding them as
    arguments instead of letting the trace bake them keeps multi-MB
    overlays out of the jaxpr (KNOWN_ISSUES #0n's escape hatch, the
    large-jaxpr-constant graph rule) and lets parallel/sweep.py's
    ``sharded_topo_sim_fn`` shard them over the mesh's node axis.

    With ``exchange_spec`` (a ``parallel.partition.ExchangeSpec``) the
    operand list grows by the owner-bucketed exchange plans —
    ``spec.n_operands`` extra arrays after the tables (pos+send per table
    kind, topo/spec.owner_bucket_plan) — and every cross-row neighbor
    read inside the tick body routes through the resulting
    ``NeighborExchange`` instead of a global gather (the shard-local
    layout of parallel/sweep.sharded_topo_sim_fn).  Values are bit-equal
    either way; only the data movement differs.

    Same trace contract as ``make_dyn_sim_fn``: ``cfg`` is canonicalized,
    the function is returned UNJITTED (the caller owns the jit/pjit
    wrapper), and at equal table values the computation is identical —
    ``jnp.take(tables[i], ids)`` sees the same numbers whether the table
    is an operand or a constant, so results are bit-equal under the exact
    sampler (pinned in tests/test_zzshardtopo.py)."""
    cfg = base_model.canonical_fault_cfg(cfg)
    check_batchable(cfg)
    _reject_cpp_only(cfg)
    if cfg.topology != "kregular":
        raise ValueError(
            f"make_topo_dyn_sim_fn is the kregular tables-as-operands "
            f"program; topology={cfg.topology!r} has no overlay tables "
            "(committee shards its stacked axis instead — parallel/sweep."
            "sharded_topo_sim_fn routes it)"
        )
    use_round_schedule(cfg)  # validates schedule='round' (kregular: tick)
    n = cfg.n
    n_tables = 3 if topo_tables_inslot(cfg) else 2
    proto = get_protocol(cfg.protocol)

    n_plans = exchange_spec.n_operands if exchange_spec is not None else 0

    def sim(key, n_crashed, n_byzantine, *operands):
        if len(operands) != n_tables + n_plans:
            raise ValueError(
                f"{cfg.protocol} kregular sim takes {n_tables} overlay "
                f"tables{f' + {n_plans} exchange plans' if n_plans else ''}"
                f", got {len(operands)}"
            )
        tables = operands[:n_tables]
        xg = (exchange_spec.build(*operands[n_tables:])
              if exchange_spec is not None else None)
        state, bufs = proto.init(cfg, jax.random.fold_in(key, 0x1217))
        state = base_model.apply_fault_masks(
            cfg, state, *base_model.dyn_fault_masks(n, n_crashed, n_byzantine)
        )

        def body(carry, t):
            st, bf = carry
            st, bf = proto.step(cfg, st, bf, t, prng.tick_key(key, t),
                                topo_tables=tables, exchange=xg)
            return (st, bf), ()

        (state, bufs), _ = jax.lax.scan(
            body, (state, bufs), jnp.arange(cfg.ticks)
        )
        return state

    return sim


def run_simulation(cfg: SimConfig, seed: int | None = None, with_timing: bool = False):
    """Run one simulation; returns the protocol's structured metrics dict
    (the reference's NS_LOG lines, SURVEY.md §5, as data).

    ``with_timing`` stages through ``utils/obs.timed_run`` — the one
    compile-vs-execution split every timing surface shares — and reports
    both ``compile_plus_first_run_s`` and the execution-only
    ``wallclock_s``."""
    sim = make_sim_fn(cfg)
    key = jax.random.key(cfg.seed if seed is None else seed)
    if with_timing:
        from blockchain_simulator_tpu.utils import obs

        final, compile_s, wall = obs.timed_run(sim, key)
        m = base_model.sim_metrics(cfg, final)
        m["wallclock_s"] = wall
        m["compile_plus_first_run_s"] = round(compile_s, 3)
        m["ticks"] = cfg.ticks
        return m
    # force_sync, not block_until_ready: the latter returns before execution
    # completes on this env's axon backend (KNOWN_ISSUES.md #1)
    final = force_sync(sim(key))
    return base_model.sim_metrics(cfg, final)


def final_state(cfg: SimConfig, seed: int | None = None):
    """Run and return the raw final state pytree (for tests/checkpointing)."""
    sim = make_sim_fn(cfg)
    key = jax.random.key(cfg.seed if seed is None else seed)
    return jax.block_until_ready(sim(key))


def run_multi_seed(cfg: SimConfig, seeds, record: bool = True):
    """Multi-seed Monte Carlo: run ``len(seeds)`` seeds of one config as ONE
    dispatch of the scatter-free ``lax.map`` executable
    (parallel/sweep.multi_seed_fn — the tick-path throughput arm of
    ISSUE 13 / ROADMAP item 4).  Returns one metrics dict per seed, in
    order, each bit-equal (exact sampler; parallel/sweep.py caveat for the
    "normal" CLT float path) to ``run_simulation(cfg, seed=s)``.

    Compared to looping :func:`run_simulation`: one executable per
    (fault structure, seed count) — seed values ride the key operand, so a
    fresh seed set never recompiles — and one Python dispatch + sync for
    the whole batch.  Compared to the vmapped ``run_seed_sweep``: the
    unvmapped ``lax.map`` body keeps the tick engine's ring pushes plain
    dynamic-update-slices instead of vmap's DUS→scatter lowering, which
    XLA:CPU serializes (KNOWN_ISSUES #0i; measured on the tick path in
    ARTIFACT_tick_bench.json).  Mixed (the one un-batchable protocol)
    raises the typed :class:`UnbatchableConfigError`."""
    from blockchain_simulator_tpu.parallel import sweep

    canon = base_model.canonical_fault_cfg(cfg)
    points = [(cfg, int(s)) for s in seeds]
    return sweep.run_dyn_points(canon, points, record=record,
                                multi_seed=True)


@aotcache.cached_factory("segment")
def make_segment_fn(cfg: SimConfig, n_ticks: int):
    """Jitted ``seg(key, state, bufs, t0) -> (state, bufs)`` advancing the
    simulation ``n_ticks`` ticks from traced start tick ``t0``.  Because tick
    keys derive from the absolute tick (utils/prng.py), segmented execution is
    bit-identical to one uninterrupted scan — the checkpoint/resume substrate
    (the reference has none, SURVEY.md §5)."""
    _reject_cpp_only(cfg)
    if cfg.topology == "committee":
        raise ValueError(
            "segmented/checkpointed execution steps the flat (state, bufs) "
            "pair; the committee path's stacked state has no segment form "
            "(topo/committee.py) — run it un-checkpointed"
        )
    proto = get_protocol(cfg.protocol)

    @jax.jit
    def seg(key, state, bufs, t0):
        def body(carry, t):
            st, bf = carry
            st, bf = proto.step(cfg, st, bf, t, prng.tick_key(key, t))
            return (st, bf), ()

        return jax.lax.scan(body, (state, bufs), t0 + jnp.arange(n_ticks))[0]

    return seg


def run_checkpointed(
    cfg: SimConfig,
    every_ms: int,
    ckpt_dir,
    seed: int | None = None,
    keep_all: bool = False,
):
    """Run to completion, writing a checkpoint every ``every_ms`` virtual ms.

    Returns ``(metrics, last_checkpoint_path)``.  ``keep_all`` retains every
    snapshot (``ckpt_<tick>.npz``); otherwise only the latest survives.
    """
    import pathlib

    from blockchain_simulator_tpu.utils.checkpoint import save_checkpoint

    if every_ms < 1:
        raise ValueError(f"every_ms must be >= 1, got {every_ms}")
    # Checkpointing segments the general per-tick engine (its carry is the
    # full (state, bufs) pytree); the round fast path has no tick-granular
    # segmentation, so pin the schedule rather than silently running a
    # different simulator than run_simulation would.
    if use_round_schedule(cfg):
        if cfg.schedule == "round":
            raise ValueError(
                "schedule='round' does not support checkpointing (the round "
                "fast path is not tick-segmentable); use schedule='tick'"
            )
        cfg = cfg.with_(schedule="tick")
    ckpt_dir = pathlib.Path(ckpt_dir)
    ckpt_dir.mkdir(parents=True, exist_ok=True)
    # bake the effective seed into the stored config so resume_simulation
    # continues the exact PRNG stream without needing the override repeated
    if seed is not None:
        cfg = cfg.with_(seed=seed)
    proto = get_protocol(cfg.protocol)
    key = jax.random.key(cfg.seed)
    state, bufs = proto.init(cfg, jax.random.fold_in(key, 0x1217))
    t, last_path = 0, None
    while t < cfg.ticks:
        n = min(every_ms, cfg.ticks - t)
        state, bufs = make_segment_fn(cfg, n)(key, state, bufs, jnp.int32(t))
        t += n
        jax.block_until_ready(state)
        path = ckpt_dir / f"ckpt_{t:08d}.npz"
        save_checkpoint(path, cfg, state, bufs, t)
        if last_path is not None and not keep_all:
            last_path.unlink()
        last_path = path
    return proto.metrics(cfg, state), last_path


def _dyn_checkpoint_cfg(cfg: SimConfig, seed: int | None) -> SimConfig:
    """Validate + normalize a config for dynamic-fault checkpointed
    execution: tick schedule pinned (the fast paths are not
    tick-segmentable — same rule as :func:`run_checkpointed`), effective
    seed baked in, batchability and cpp-only modes checked up front."""
    check_batchable(cfg)
    _reject_cpp_only(cfg)
    if use_round_schedule(cfg):
        if cfg.schedule == "round":
            raise ValueError(
                "schedule='round' does not support checkpointing (the round "
                "fast path is not tick-segmentable); use schedule='tick'"
            )
        cfg = cfg.with_(schedule="tick")
    if seed is not None:
        cfg = cfg.with_(seed=seed)
    return cfg


def run_dyn_checkpointed(
    cfg: SimConfig,
    every_ms: int,
    ckpt_dir,
    seed: int | None = None,
    keep_all: bool = False,
    resume: bool = True,
):
    """The dynamic-fault-operand analog of :func:`run_checkpointed` — and
    the sweep supervisor's tick-level degrade arm for very long
    single-sim chunks (parallel/journal.py): init at the CANONICAL fault
    structure, install the traced fault masks from ``cfg.faults``' counts
    (models/base.dyn_fault_masks — the masks then ride ``state`` as
    ordinary leaves, so the shared ``segment`` executable advances them),
    and checkpoint every ``every_ms`` virtual ms with the ``(n_crashed,
    n_byzantine)`` operands stored alongside state/bufs.

    ``resume=True`` (default): when ``ckpt_dir`` already holds a
    ``ckpt_*.npz`` from a crashed run of the SAME config, execution
    continues from the latest one instead of restarting — a re-killed
    chunk loses at most one segment.  A checkpoint for a different
    config (or a static-path archive with no ``__dyn__`` entry) raises
    rather than silently blending two runs.

    Rows are bit-equal to the un-checkpointed dyn program
    (``jit(make_dyn_sim_fn(cfg))``) — the tick keys derive from absolute
    ticks (utils/prng.py), pinned in tests/test_checkpoint.py.
    Returns ``(metrics, last_checkpoint_path)``."""
    import pathlib

    from blockchain_simulator_tpu.utils.checkpoint import (
        load_checkpoint,
        load_dyn_counts,
        save_checkpoint,
    )

    if every_ms < 1:
        raise ValueError(f"every_ms must be >= 1, got {every_ms}")
    cfg = _dyn_checkpoint_cfg(cfg, seed)
    canon = base_model.canonical_fault_cfg(cfg)
    nc = cfg.faults.resolved_n_crashed(cfg.n)
    nb = cfg.faults.n_byzantine
    proto = get_protocol(cfg.protocol)
    key = jax.random.key(cfg.seed)
    ckpt_dir = pathlib.Path(ckpt_dir)
    ckpt_dir.mkdir(parents=True, exist_ok=True)
    existing = sorted(ckpt_dir.glob("ckpt_*.npz")) if resume else []
    if existing:
        stored_cfg, state, bufs, t = load_checkpoint(existing[-1])
        if stored_cfg != cfg:
            raise ValueError(
                f"checkpoint {existing[-1]} belongs to a different config "
                f"(stored hash != requested); refusing to blend runs"
            )
        stored_dyn = load_dyn_counts(existing[-1])
        if stored_dyn != (nc, nb):
            raise ValueError(
                f"checkpoint {existing[-1]} stores dyn operands "
                f"{stored_dyn}, requested ({nc}, {nb})"
            )
        last_path = existing[-1]
    else:
        state, bufs = proto.init(canon, jax.random.fold_in(key, 0x1217))
        state = base_model.apply_fault_masks(
            cfg, state, *base_model.dyn_fault_masks(cfg.n, nc, nb)
        )
        t, last_path = 0, None
    while t < cfg.ticks:
        n = min(every_ms, cfg.ticks - t)
        state, bufs = make_segment_fn(canon, n)(key, state, bufs, jnp.int32(t))
        t += n
        jax.block_until_ready(state)
        path = ckpt_dir / f"ckpt_{t:08d}.npz"
        save_checkpoint(path, cfg, state, bufs, t, dyn_counts=(nc, nb))
        if last_path is not None and not keep_all:
            last_path.unlink()
        last_path = path
    return proto.metrics(cfg, state), last_path


def resume_dyn_simulation(ckpt_path):
    """Load a dynamic-fault checkpoint and run the remaining ticks through
    the canonical-structure ``segment`` executable; returns metrics
    bit-equal to the uninterrupted dyn run.  Raises on a static-path
    archive (no stored operands) — use :func:`resume_simulation`."""
    from blockchain_simulator_tpu.utils.checkpoint import (
        load_checkpoint,
        load_dyn_counts,
    )

    dyn = load_dyn_counts(ckpt_path)
    if dyn is None:
        raise ValueError(
            f"{ckpt_path} is a static-path checkpoint (no __dyn__ operands);"
            " use resume_simulation"
        )
    cfg, state, bufs, t = load_checkpoint(ckpt_path)
    canon = base_model.canonical_fault_cfg(cfg)
    proto = get_protocol(cfg.protocol)
    key = jax.random.key(cfg.seed)
    if t < cfg.ticks:
        state, bufs = make_segment_fn(canon, cfg.ticks - t)(
            key, state, bufs, jnp.int32(t)
        )
        jax.block_until_ready(state)
    return proto.metrics(cfg, state)


def resume_simulation(ckpt_path, seed: int | None = None):
    """Load a checkpoint and run the remaining ticks; returns metrics.

    ``seed`` must match the original run's (it defaults to the config's seed
    stored in the checkpoint); the tick stream continues bit-exactly.
    """
    from blockchain_simulator_tpu.utils.checkpoint import load_checkpoint

    cfg, state, bufs, t = load_checkpoint(ckpt_path)
    proto = get_protocol(cfg.protocol)
    key = jax.random.key(cfg.seed if seed is None else seed)
    if t < cfg.ticks:
        state, bufs = make_segment_fn(cfg, cfg.ticks - t)(
            key, state, bufs, jnp.int32(t)
        )
        jax.block_until_ready(state)
    return proto.metrics(cfg, state)
