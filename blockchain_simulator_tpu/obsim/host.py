"""The host boundary of obsim: run probed programs, summarize lanes,
trip the flight recorder on monitor violations.

This is the ONLY obsim module allowed to import ``utils/telemetry`` —
everything it does happens strictly AFTER ``block_until_ready``, on
host-side numpy, so the host-side-only telemetry rule (KNOWN_ISSUES
#0m) holds by layering: taps/build/schema/diverge stay telemetry-free
(source-pinned in tests/test_zzobsim.py) and no callback can reach a
trace through here.
"""

from __future__ import annotations

import jax
import numpy as np

from blockchain_simulator_tpu.models import base as base_model
from blockchain_simulator_tpu.models.base import sim_metrics
from blockchain_simulator_tpu.obsim import build
from blockchain_simulator_tpu.obsim import schema
from blockchain_simulator_tpu.utils import telemetry


def summarize_lane(cfg, pcfg: schema.ProbeConfig, probes, lane: int) -> dict:
    """Summarize ONE lane of a batched probe pytree (leading batch axis
    from vmap/lax.map/mesh dispatch) — slice, then schema.summarize."""
    return schema.summarize(
        cfg, pcfg, jax.tree.map(lambda x: np.asarray(x)[lane], probes)
    )


def note_violations(summary: dict, cfg, seed: int) -> str | None:
    """The violation → post-mortem hook: when a probe summary carries
    nonzero safety-monitor counters, record the event on the flight ring
    and dump a ``consensus-violation`` post-mortem (armed by
    ``$BLOCKSIM_FLIGHT_DIR``; utils/telemetry.FlightRecorder).  Returns
    the dump path (None when clean or disarmed).  Liveness lag is a
    gauge, not a violation — it never trips this hook
    (chaos/invariants.check_consensus_probes gates it separately)."""
    if not summary.get("violations"):
        return None
    from blockchain_simulator_tpu.utils import obs

    telemetry.flight.note(
        "consensus-violation",
        protocol=summary.get("protocol"),
        topology=summary.get("topology"),
        seed=int(seed),
        config=obs.config_hash(cfg),
        monitors=summary.get("monitors"),
    )
    telemetry.metrics.counter("obsim_violations_total").inc(
        summary["violations"]
    )
    return telemetry.flight.dump("consensus-violation")


def run_probed(cfg, seed: int = 0, pcfg: schema.ProbeConfig | None = None,
               n_crashed: int | None = None,
               n_byzantine: int | None = None) -> tuple[dict, dict]:
    """Solo probed run: ``(metrics, probe_summary)`` for one (cfg, seed).

    The host-facing entry for drills, the report tool and notebooks: the
    armed executable comes from the ``consobs-solo`` registry entry (one
    per (fault structure, probe config)); fault counts default to the
    config's own (the static-arm convention).  Primary metrics are
    bit-equal to the disarmed run under the exact sampler — the probe
    summary is pure addition."""
    pcfg = pcfg or schema.ProbeConfig()
    canon = base_model.canonical_fault_cfg(cfg)
    fc = cfg.faults
    ops = (fc.resolved_n_crashed(cfg.n) if n_crashed is None else n_crashed,
           fc.n_byzantine if n_byzantine is None else n_byzantine)
    sim = build.probed_solo_fn(canon, pcfg)
    final, probes = jax.block_until_ready(
        sim(jax.random.PRNGKey(seed), *map(int, ops))
    )
    m = sim_metrics(cfg, final)
    summary = schema.summarize(canon, pcfg, jax.tree.map(np.asarray, probes))
    note_violations(summary, cfg, seed)
    return m, summary
