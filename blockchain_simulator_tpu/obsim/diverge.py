"""First-divergence forensics over two probe series.

Turns an opaque "bit-equality pin failed" into a located report: given
two probe pytrees (or plain ``{field: array}`` series dicts) from runs
that SHOULD agree — sharded vs single-device, resumed vs uninterrupted,
meshed vs plain, armed replay vs original — find the first sample index
where any field differs, and which field/lane it is.  Pure numpy; never
traced (forensics run on host-side results).

Series axis conventions (obsim/schema.py): the LAST axis is the sample
(window) axis; a leading axis, when present, is the committee/lane axis.
The report therefore names ``(sample, field, lane)``; the caller maps
sample -> tick via the run's sample unit (schema.sample_axis) — e.g. a
window boundary index maps through schema.window_bounds.
"""

from __future__ import annotations

import numpy as np


def _series_of(probes) -> dict:
    """Accept a probe pytree (``{"series": ..., "monitors": ...}``), a
    bare series dict, or a trace-style series dict (utils/trace.py runs
    carry a host-attached ``"t"`` axis — compared too: differing sample
    axes ARE a divergence)."""
    if isinstance(probes, dict) and "series" in probes \
            and isinstance(probes["series"], dict):
        return {k: np.asarray(v) for k, v in probes["series"].items()}
    return {k: np.asarray(v) for k, v in probes.items()}


def first_divergence(a, b) -> dict | None:
    """First divergent (sample, field[, lane]) between two probe series.

    Returns None when identical; otherwise a dict with the minimal
    divergent ``sample`` index (across all fields), the sorted ``fields``
    that diverge AT that sample, per-field ``lanes`` (leading-axis
    indices; empty for 1-D series), and per-field ``got``/``want`` values
    at the divergence point.  Raises on structural mismatch (different
    fields or shapes) — that is not a divergence, it is comparing
    different probe configs."""
    sa, sb = _series_of(a), _series_of(b)
    if sorted(sa) != sorted(sb):
        raise ValueError(
            f"probe structure mismatch: {sorted(sa)} vs {sorted(sb)}"
        )
    first: int | None = None
    detail: dict = {}
    for k in sorted(sa):
        va, vb = sa[k], sb[k]
        if va.shape != vb.shape:
            raise ValueError(
                f"probe shape mismatch on {k!r}: {va.shape} vs {vb.shape}"
            )
        neq = va != vb
        if not neq.any():
            continue
        # last axis = sample axis; collapse any leading lane axes
        per_sample = neq.reshape(-1, neq.shape[-1]).any(axis=0)
        s = int(np.flatnonzero(per_sample)[0])
        if first is None or s < first:
            first = s
        detail[k] = s
    if first is None:
        return None
    fields = sorted(k for k, s in detail.items() if s == first)
    out = {"sample": first, "fields": fields, "lanes": {}, "got": {},
           "want": {}}
    for k in fields:
        va, vb = sa[k], sb[k]
        col_a, col_b = va[..., first], vb[..., first]
        lanes = np.argwhere(np.atleast_1d(col_a != col_b))
        out["lanes"][k] = [tuple(int(i) for i in ix) for ix in lanes] \
            if va.ndim > 1 else []
        out["got"][k] = col_a.tolist() if va.ndim > 1 else int(col_a)
        out["want"][k] = col_b.tolist() if vb.ndim > 1 else int(col_b)
    return out


def render(div: dict | None, t_axis=None, unit: str = "sample") -> str:
    """Human-readable one-paragraph report of a :func:`first_divergence`
    result.  ``t_axis`` (e.g. schema.window_bounds output, or a trace
    series' ``"t"`` array) maps the sample index to the run's time axis
    when provided."""
    if div is None:
        return "no divergence: series identical"
    s = div["sample"]
    where = f"{unit} {s}"
    if t_axis is not None:
        where += f" (t={int(np.asarray(t_axis)[s])})"
    lines = [f"first divergence at {where}: "
             f"field(s) {', '.join(div['fields'])}"]
    for k in div["fields"]:
        lanes = div["lanes"][k]
        lane_s = f" lanes {lanes}" if lanes else ""
        lines.append(f"  {k}{lane_s}: got {div['got'][k]} "
                     f"want {div['want'][k]}")
    return "\n".join(lines)
