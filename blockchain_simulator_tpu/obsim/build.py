"""Probed twins of the dynamic-fault program factories.

``make_probed_dyn_sim_fn(cfg, pcfg)`` mirrors ``runner.make_dyn_sim_fn``
arm for arm — committee ``lax.map`` stack, round-schedule raft heartbeat
fast path (taps thread through the ``lax.cond`` phase split), round-
blocked PBFT, general tick engine — returning ``sim(key, n_crashed,
n_byzantine) -> (final_state, probes)`` with the probe pytree described
in :mod:`obsim.schema`.

Registry discipline (utils/aotcache.py): the probed programs live under
their OWN ``consobs-*`` factory names keyed ``(cfg, pcfg, …)`` — one
executable per (fault structure, probe config) — and the disarmed
factories are not touched at all, so today's programs stay byte-identical
(fingerprint pin in tests/test_zzobsim.py).  The batched/mesh twins
mirror parallel/sweep.py's ``dyn_batched_fn`` / ``multi_seed_fn`` /
``mesh_dyn_batched_fn`` shapes: ``vmap`` for the sweep batch, the
scatter-free ``lax.map`` body (partition.seq_map, KNOWN_ISSUES #0i) for
the multi-seed arm, and shard_map/pjit over the mesh's sweep axis.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from blockchain_simulator_tpu.models import base as base_model
from blockchain_simulator_tpu.models.base import get_protocol
from blockchain_simulator_tpu.obsim import schema
from blockchain_simulator_tpu.obsim import taps
from blockchain_simulator_tpu.runner import (
    _reject_cpp_only,
    check_batchable,
    use_round_schedule,
)
from blockchain_simulator_tpu.utils import aotcache
from blockchain_simulator_tpu.utils import prng


def make_probed_dyn_sim_fn(cfg, pcfg: schema.ProbeConfig):
    """``sim(key, n_crashed, n_byzantine) -> (final_state, probes)`` —
    runner.make_dyn_sim_fn with the taps armed.  UNJITTED, like its twin:
    the factories below own the jit/vmap/mesh wrappers.  The state
    trajectory is bit-identical to the disarmed program (taps read state,
    consume zero PRNG), so primary metrics are bit-equal under the exact
    sampler — the tests' contract."""
    cfg = base_model.canonical_fault_cfg(cfg)
    check_batchable(cfg)
    _reject_cpp_only(cfg)
    schema.series_fields(cfg.protocol)  # typed refusal before tracing
    n = cfg.n

    if cfg.topology == "committee":
        from blockchain_simulator_tpu.topo import committee

        use_round_schedule(cfg)  # validates schedule='round' (always tick)

        def finalize_fn(icfg, final, ys):
            return taps.finalize(icfg, pcfg, final, ys, icfg.ticks)

        def sim_comm(key, n_crashed, n_byzantine):
            return committee.run_stacked(
                cfg, key, n_crashed, n_byzantine,
                probe=(taps.sample, finalize_fn),
            )

        return sim_comm

    if use_round_schedule(cfg):
        if cfg.protocol == "raft":
            from blockchain_simulator_tpu.models import raft as raft_tick
            from blockchain_simulator_tpu.models import raft_hb

            # both lax.cond branches must reduce to one aval: clamp the
            # window count to the SHORTER branch's sample count (prefix
            # ticks + heartbeats vs full ticks)
            m_fast = raft_hb.prefix_ticks(cfg) + raft_hb.n_hb_steps(cfg)
            w_eff = max(1, min(pcfg.windows, m_fast, cfg.ticks))

            def reduce_fn(series):
                m = jax.tree.leaves(series)[0].shape[0]
                red = {"series": taps.window(series, m, w_eff)}
                if pcfg.monitors:
                    red["liveness_lag"] = taps.liveness_lag(
                        series[schema.PROGRESS_FIELD["raft"]]
                    )
                return red

            probe = (
                functools.partial(taps.sample, cfg),
                taps.raft_steady_sample,
                reduce_fn,
            )

            def sim_hb(key, n_crashed, n_byzantine):
                state, bufs = raft_tick.init(
                    cfg, jax.random.fold_in(key, 0x1217)
                )
                state = base_model.apply_fault_masks(
                    cfg, state,
                    *base_model.dyn_fault_masks(n, n_crashed, n_byzantine),
                )
                final, red = raft_hb.scan_from_init(
                    cfg, state, bufs, key, probe=probe
                )
                probes = {"series": red["series"]}
                if pcfg.monitors:
                    mon = taps.monitors(cfg, final)
                    mon["liveness_lag"] = red["liveness_lag"]
                    probes["monitors"] = mon
                return final, probes

            return sim_hb

        from blockchain_simulator_tpu.models import pbft_round

        bt = cfg.pbft_block_interval_ms
        r_last = (cfg.ticks - 1) // bt
        if r_last < 1:
            raise ValueError(
                "cannot arm probes on a round-schedule run with zero "
                f"block rounds (ticks={cfg.ticks} <= interval={bt})"
            )

        def sim_round(key, n_crashed, n_byzantine):
            state, _ = pbft_round.init(cfg, jax.random.fold_in(key, 0x1217))
            state = base_model.apply_fault_masks(
                cfg, state,
                *base_model.dyn_fault_masks(n, n_crashed, n_byzantine),
            )
            final, ys = pbft_round.scan_rounds(
                cfg, state, key,
                with_probe=functools.partial(taps.sample, cfg),
            )
            return final, taps.finalize(cfg, pcfg, final, ys, r_last)

        return sim_round

    proto = get_protocol(cfg.protocol)

    def sim(key, n_crashed, n_byzantine):
        state, bufs = proto.init(cfg, jax.random.fold_in(key, 0x1217))
        state = base_model.apply_fault_masks(
            cfg, state,
            *base_model.dyn_fault_masks(n, n_crashed, n_byzantine),
        )

        def body(carry, t):
            st, bf = carry
            st, bf = proto.step(cfg, st, bf, t, prng.tick_key(key, t))
            return (st, bf), taps.sample(cfg, st)

        (state, bufs), ys = jax.lax.scan(
            body, (state, bufs), jnp.arange(cfg.ticks)
        )
        return state, taps.finalize(cfg, pcfg, state, ys, cfg.ticks)

    return sim


# --------------------------------------------------- cached executables ---


@aotcache.cached_factory("consobs-solo")
def probed_solo_fn(cfg, pcfg: schema.ProbeConfig):
    """One probed solo executable per (fault structure, probe config) —
    the armed twin of serve/dispatch._solo_fn / a jitted
    runner.make_dyn_sim_fn."""
    return jax.jit(make_probed_dyn_sim_fn(cfg, pcfg))


@aotcache.cached_factory("consobs-batched")
def probed_batched_fn(cfg, pcfg: schema.ProbeConfig, multi_seed: bool = False):
    """The armed twin of sweep.dyn_batched_fn (``jit(vmap(...))``) and —
    with ``multi_seed=True``, which only disambiguates the registry key
    the way sweep.multi_seed_fn's ``n_seeds`` does — of the sequential
    ``lax.map`` multi-seed arm (partition.seq_map, scatter-free batch
    body, KNOWN_ISSUES #0i).  Probe leaves gain the leading batch axis."""
    from blockchain_simulator_tpu.parallel import partition

    fn = make_probed_dyn_sim_fn(cfg, pcfg)
    if multi_seed:
        return jax.jit(partition.seq_map(fn))
    return jax.jit(jax.vmap(fn))


@aotcache.cached_factory("consobs-mesh")
def probed_mesh_fn(cfg, pcfg: schema.ProbeConfig, mesh):
    """The armed twin of sweep.mesh_dyn_batched_fn, arm for arm: size-1
    mesh degenerates to :func:`probed_batched_fn`; a >1 nodes axis takes
    the explicit-sharding pjit arm (partition.batched_out_shardings is
    pytree-generic, so the probe leaves ride it — ``[B, C, …]`` committee
    probes shard their committee dim like the finals, flat ``[B, W]``
    series shard the batch axis); a sweep-only mesh shard_maps the
    scatter-free ``lax.map`` body with every out leaf — finals and probes
    alike carry the leading batch axis — on the sweep axis."""
    from jax.sharding import PartitionSpec as P

    from blockchain_simulator_tpu.parallel import partition
    from blockchain_simulator_tpu.parallel.mesh import NODES_AXIS, SWEEP_AXIS

    fn = make_probed_dyn_sim_fn(cfg, pcfg)
    if partition.mesh_size(mesh) == 1:
        return probed_batched_fn(cfg, pcfg)
    if int(dict(mesh.shape).get(NODES_AXIS, 1)) > 1:
        batched = jax.vmap(fn)
        b = max(partition.sweep_axis_size(mesh), 1)
        keys_sds = jax.eval_shape(
            lambda: jax.vmap(jax.random.key)(jnp.arange(b, dtype=jnp.uint32))
        )
        cnt_sds = jax.ShapeDtypeStruct((b,), jnp.int32)
        outs = jax.eval_shape(batched, keys_sds, cnt_sds, cnt_sds)
        lane = P(SWEEP_AXIS) if partition.sweep_axis_size(mesh) > 1 else P()
        return partition.partition(
            batched, mesh,
            in_shardings=(lane, lane, lane),
            out_shardings=partition.batched_out_shardings(cfg, mesh, outs),
        )
    lane = P(SWEEP_AXIS)
    return partition.partition(
        partition.seq_map(fn), mesh,
        in_specs=(lane, lane, lane), out_specs=lane,
    )
