"""obsim — in-program consensus observability.

Traced probe taps, on-device invariant monitors, and first-divergence
forensics that ride INSIDE the compiled simulation programs (ISSUE 17).
The host-side-only telemetry rule (KNOWN_ISSUES #0m) is untouched: every
value this package produces on-device is ordinary traced data returned
alongside the final state — never a host callback — and only
:mod:`obsim.host` (which never enters a trace) may touch
``utils/telemetry``.

Layout:

- :mod:`obsim.schema` — the probe schema: :class:`~obsim.schema.ProbeConfig`
  (frozen, hashable — rides executable-registry keys), per-protocol field
  registry, window-boundary math, host-side summaries.
- :mod:`obsim.taps` — the traced taps: per-tick samples, windowed
  reductions, liveness lag, final-state invariant monitors.  Imported from
  inside jitted programs; telemetry-free by construction (pinned).
- :mod:`obsim.build` — probed twins of the runner/sweep program factories,
  cached in the unified executable registry under ``consobs-*`` names.
- :mod:`obsim.diverge` — first-divergence forensics over two probe series.
- :mod:`obsim.host` — the host boundary: run probed programs, summarize,
  trip the flight recorder on monitor violations.
"""
