"""The probe schema: what a probed program returns and what it means.

A probed program returns ``(final_state, probes)`` where ``probes`` is a
plain dict pytree:

- ``probes["series"]`` — per-protocol windowed counter series, one
  ``[W]`` int32 array per field (``[C, W]`` stacked on the committee
  path, a leading batch axis on the sweep paths).  Window ``j`` holds the
  counter's value at the last sample of window ``j`` — cumulative
  counters sampled at ``W`` evenly spaced boundaries over the run, so
  adjacent-window differences are per-window event volumes.
- ``probes["monitors"]`` — on-device invariant monitors evaluated on the
  FINAL state (int32 scalars; ``[C]`` per committee): ``viol_agreement``
  (safety: conflicting/forged/unattributed commits among correct nodes),
  ``viol_quorum`` (quorum-certificate consistency), and ``liveness_lag``
  (samples since the protocol's progress counter last advanced; the
  sample axis is ticks on the tick engines, rounds/heartbeats on the
  fast paths — ``summarize`` records the unit).

The probe structure is a function of ``(cfg, ProbeConfig)`` only — both
are frozen/hashable and ride the executable-registry key, so there is
exactly ONE executable per (fault structure, probe config) and the
disarmed programs (no ProbeConfig anywhere) stay byte-identical to
today's (pinned in tests/test_zzobsim.py).
"""

from __future__ import annotations

import dataclasses

import numpy as np

# Monitor fields every protocol emits (schema.py is importable without jax).
MONITOR_FIELDS = ("viol_agreement", "viol_quorum", "liveness_lag")

# Per-protocol windowed-series fields (obsim/taps.sample emits exactly
# these, in this order).  "msgs_*" are message-volume counters, "phase_*"
# / "slots_*" phase-occupancy and quorum-progress counts, the rest event
# counters; the protocol's PROGRESS field feeds the liveness monitor.
SERIES_FIELDS = {
    "pbft": (
        "msgs_rounds",      # blocks broadcast as leader (send volume)
        "commits",          # slot finalization events, summed over slots
        "blocks",           # max chain height across nodes
        "views",            # max view number across nodes
        "view_changes",     # view changes initiated, summed
        "slots_any",        # slots with >= 1 finalizer
        "slots_quorum",     # slots with >= 2n/3+1 finalizers
    ),
    "raft": (
        "msgs_rounds",      # proposal rounds broadcast (leader send volume)
        "blocks",           # max blocks committed across nodes
        "elections",        # sendVote firings, summed
        "leaders",          # alive leaders right now (occupancy)
    ),
    "paxos": (
        "msgs_tickets",     # tickets requested, summed (retry volume)
        "executes",         # acceptors that executed (latched)
        "committed",        # proposers with CLIENT COMMIT SUCCESS
        "phase_ticket",     # proposers in the ticket phase
        "phase_propose",    # proposers in the propose phase
        "phase_commit",     # proposers in the commit phase
    ),
}

# The monotone progress counter driving liveness_lag, per protocol.
PROGRESS_FIELD = {"pbft": "commits", "raft": "blocks", "paxos": "executes"}


@dataclasses.dataclass(frozen=True)
class ProbeConfig:
    """The probe configuration — frozen and hashable so it can ride an
    executable-registry key next to SimConfig (utils/aotcache.py).

    ``windows``: number of evenly spaced sample boundaries the series are
    reduced to (clipped to the run's sample count).  ``monitors``: emit
    the invariant monitors alongside the series.
    """

    windows: int = 16
    monitors: bool = True

    def __post_init__(self):
        if self.windows < 1:
            raise ValueError(f"ProbeConfig.windows must be >= 1: {self.windows}")


def series_fields(protocol: str):
    """The windowed-series field names for a protocol (KeyError = the
    protocol has no probe schema; mixed is refused by the dyn path
    already, runner.check_batchable)."""
    if protocol not in SERIES_FIELDS:
        raise KeyError(
            f"no probe schema for protocol {protocol!r} "
            f"(have {sorted(SERIES_FIELDS)})"
        )
    return SERIES_FIELDS[protocol]


def window_bounds(n_samples: int, windows: int) -> np.ndarray:
    """Static sample indices of the window boundaries: ``W`` evenly spaced
    last-sample-of-window positions over ``n_samples`` samples, the last
    always ``n_samples - 1``.  Pure numpy at trace time — the gather these
    feed is static-index (scatter-free, KNOWN_ISSUES #0n) and vmap-safe."""
    n_samples = int(n_samples)
    if n_samples < 1:
        raise ValueError(f"window_bounds needs >= 1 sample: {n_samples}")
    w = max(1, min(int(windows), n_samples))
    return (np.arange(1, w + 1) * n_samples) // w - 1


def sample_axis(cfg) -> tuple:
    """``(unit, n_samples)`` of the probe sample axis for a config: what
    one sample index means, before windowing — ticks on the tick engines,
    block rounds / election-prefix-ticks-then-heartbeats on the fast
    paths.  Import-light (no jax); mirrors runner.make_dyn_sim_fn's arm
    dispatch."""
    from blockchain_simulator_tpu.runner import use_round_schedule

    if cfg.topology == "committee":
        from blockchain_simulator_tpu.topo import committee

        return ("tick", committee.inner_cfg(cfg).ticks)
    if use_round_schedule(cfg):
        if cfg.protocol == "raft":
            return ("mixed-tick-heartbeat", -1)  # phase split: length varies
        bt = cfg.pbft_block_interval_ms
        return ("round", max((cfg.ticks - 1) // bt, 0))
    return ("tick", cfg.ticks)


def summarize(cfg, pcfg: ProbeConfig, probes) -> dict:
    """Host-side JSON-able summary of one probed run's probe pytree
    (device arrays in, plain ints/lists out).  Committee probes ([C, W]
    series, [C] monitors) summarize per committee and aggregate the
    monitors; 3-D (batched-committee) leaves are summarized per leading
    lane by the sweep layer before reaching here."""
    unit, _ = sample_axis(cfg)
    series = {k: np.asarray(v) for k, v in probes["series"].items()}
    any_leaf = next(iter(series.values()))
    out = {
        "protocol": cfg.protocol,
        "topology": cfg.topology,
        "windows": int(any_leaf.shape[-1]),
        "sample_unit": unit,
        "fields": sorted(series),
        "final": {
            k: v[..., -1].tolist() if v.ndim > 1 else int(v[-1])
            for k, v in series.items()
        },
    }
    mon = probes.get("monitors")
    if mon is not None:
        mon = {k: np.asarray(v) for k, v in mon.items()}
        out["monitors"] = {
            k: v.tolist() if v.ndim else int(v) for k, v in mon.items()
        }
        out["violations"] = int(
            sum(int(np.sum(mon[k])) for k in ("viol_agreement", "viol_quorum"))
        )
    return out
