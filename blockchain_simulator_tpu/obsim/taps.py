"""Traced probe taps: per-sample counters, windowed reductions, and
final-state invariant monitors.

Everything in this module runs INSIDE a jit trace.  Two hard rules, both
pinned by tests/test_zzobsim.py:

- **No host calls**: this module never imports ``utils/telemetry`` (the
  host-side-only rule, KNOWN_ISSUES #0m) — the graph audit's
  ``host-callback-in-program`` rule proves no callback reaches the HLO.
- **Zero PRNG**: taps only READ state; they never consume a key.  Armed
  programs therefore step through bit-identical state trajectories, which
  is what makes the armed-vs-disarmed primary-metrics bit-equality pins
  (exact sampler) possible at all.

Reductions are scatter-free by construction: sums/maxes of state fields
per sample, and a static-index gather (KNOWN_ISSUES #0n) to pick the
window boundaries — safe under ``vmap``/``lax.map``/``shard_map``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from blockchain_simulator_tpu.obsim import schema

_I32_NEVER = np.iinfo(np.int32).max  # models/pbft._NEVER sentinel


def _i32(x):
    return jnp.asarray(x, jnp.int32)


# ------------------------------------------------------------- samples ---


def sample(cfg, state) -> dict:
    """One probe sample: the protocol's schema.SERIES_FIELDS counters read
    off ``state`` (device-side, a handful of sums/maxes).  ``cfg`` must be
    the config the state belongs to (the INNER config on the committee
    path, so ``cfg.n`` is the committee size)."""
    p = cfg.protocol
    if p == "pbft":
        q = (2 * cfg.n) // 3 + 1
        return {
            "msgs_rounds": _i32(state.rounds_sent.sum()),
            "commits": _i32(state.slot_commits.sum()),
            "blocks": _i32(state.block_num.max()),
            "views": _i32(state.v.max()),
            "view_changes": _i32(state.view_changes.sum()),
            "slots_any": _i32((state.slot_commits > 0).sum()),
            "slots_quorum": _i32((state.slot_commits >= q).sum()),
        }
    if p == "raft":
        return {
            "msgs_rounds": _i32(state.round.sum()),
            "blocks": _i32(state.block_num.max()),
            "elections": _i32(state.elections.sum()),
            "leaders": _i32((state.is_leader & state.alive).sum()),
        }
    if p == "paxos":
        from blockchain_simulator_tpu.models import paxos as paxos_model

        ph = state.phase
        return {
            "msgs_tickets": _i32(state.ticket.sum()),
            "executes": _i32(state.is_commit.sum()),
            "committed": _i32((state.commit_tick >= 0).sum()),
            "phase_ticket": _i32((ph == paxos_model.PH_TICKET).sum()),
            "phase_propose": _i32((ph == paxos_model.PH_PROPOSE).sum()),
            "phase_commit": _i32((ph == paxos_model.PH_COMMIT).sum()),
        }
    raise NotImplementedError(p)


def raft_steady_sample(ys: dict, h_state) -> dict:
    """Map the raft heartbeat fast path's per-heartbeat scan ys
    (models/raft_hb.steady_scan ``with_probe=True``: blocks/rounds/...)
    into the raft probe schema.  Elections and leadership are frozen by
    the handoff's steady-state precondition, so those fields broadcast
    the handoff state's values across the heartbeat axis."""
    blocks = _i32(ys["blocks"])
    return {
        "msgs_rounds": _i32(ys["rounds"]),
        "blocks": blocks,
        "elections": jnp.full_like(blocks, _i32(h_state.elections.sum())),
        "leaders": jnp.full_like(
            blocks, _i32((h_state.is_leader & h_state.alive).sum())
        ),
    }


# ------------------------------------------------- windowed reductions ---


def window(series: dict, n_samples: int, windows: int) -> dict:
    """Reduce per-sample series ``{field: [..., m]}`` to window-boundary
    series ``{field: [..., W]}`` via a static-index gather on the last
    axis (schema.window_bounds; scatter-free, KNOWN_ISSUES #0n)."""
    idx = schema.window_bounds(n_samples, windows)
    return {k: v[..., idx] for k, v in series.items()}


def liveness_lag(progress) -> jax.Array:
    """Samples since the cumulative progress counter last advanced
    (``m`` = never advanced).  ``progress`` is the protocol's
    schema.PROGRESS_FIELD per-sample series ``[m]``; a max-reduce over a
    comparison against the shifted series — no scatter, no PRNG."""
    prog = _i32(progress)
    m = prog.shape[-1]
    prev = jnp.concatenate([jnp.zeros_like(prog[..., :1]), prog[..., :-1]],
                           axis=-1)
    inc = prog > prev
    idx = jnp.arange(m, dtype=jnp.int32)
    last = jnp.max(jnp.where(inc, idx, -1), axis=-1)
    return _i32(jnp.where(last < 0, m, m - 1 - last))


# ------------------------------------------------------------ monitors ---


def monitors(cfg, state) -> dict:
    """On-device invariant monitors over the FINAL state: traced twins of
    each protocol's host-side ``metrics()`` agreement logic (so a monitor
    firing and ``agreement_ok=False`` are the same event), plus a
    quorum-certificate consistency check.  Returns int32 violation
    counters; zero = clean.  A byzantine node tripping these is SIGNAL,
    not a bug (KNOWN_ISSUES #0o).  ``liveness_lag`` is attached by the
    callers that hold the per-sample progress series."""
    p = cfg.protocol
    if p == "pbft":
        commits = state.slot_commits
        proposed = state.slot_propose_tick < _I32_NEVER
        # forged (quorum without any proposal) + misattributed commits —
        # models/pbft.metrics forged_commits/unattributed_commits, traced
        viol_agree = _i32(((commits > 0) & ~proposed).sum()
                          + state.unattributed.sum())
        # a finalization stamped BEFORE its slot's first proposal is an
        # inconsistent quorum certificate (commit_tick is a last-event
        # pmax, propose_tick a first-event pmin — clean runs order them)
        viol_quorum = _i32(
            ((commits > 0) & proposed
             & (state.slot_commit_tick >= 0)
             & (state.slot_commit_tick < state.slot_propose_tick)).sum()
        )
        return {"viol_agreement": viol_agree, "viol_quorum": viol_quorum}
    if p == "raft":
        cand = state.is_leader & state.alive
        lt = jnp.where(cand, state.leader_tick, _I32_NEVER)
        lead = _i32(jnp.argmin(lt))  # earliest-elected alive leader
        stored = state.alive & (state.m_value >= 0)
        # raft.metrics agreement: every alive stored value names the leader
        viol_agree = _i32(jnp.where(
            cand.any(), (stored & (state.m_value != lead)).sum(), 0
        ))
        # split brain among CORRECT nodes (byzantine double-voting can
        # split honestly-elected leaders; >1 honest alive leader = signal)
        viol_quorum = _i32(jnp.maximum(
            (state.is_leader & state.alive & state.honest).sum() - 1, 0
        ))
        return {"viol_agreement": viol_agree, "viol_quorum": viol_quorum}
    if p == "paxos":
        np_prop = cfg.paxos_n_proposers
        executed = state.is_commit & state.alive
        n_exec = executed.sum()
        cmd_min = jnp.min(jnp.where(executed, state.command, _I32_NEVER))
        cmd_max = jnp.max(jnp.where(executed, state.command, -1))
        distinct = (n_exec > 0) & (cmd_min != cmd_max)
        winners = state.commit_tick[:np_prop] >= 0
        # paxos.metrics agreement: one executed command, and every
        # committed proposer proposed exactly it
        wrong = winners & (state.proposal[:np_prop] != cmd_min)
        viol_agree = _i32(distinct) + _i32(
            jnp.where(n_exec > 0, wrong.sum(), 0)
        )
        # a committed proposer whose quorum left zero executed acceptors
        # claimed executions nobody holds (paxos.metrics, same branch)
        viol_quorum = _i32((winners.sum() > 0) & (n_exec == 0))
        return {"viol_agreement": viol_agree, "viol_quorum": viol_quorum}
    raise NotImplementedError(p)


# ------------------------------------------------------------ assembly ---


def finalize(cfg, pcfg, final_state, series, n_samples: int) -> dict:
    """Assemble the probe pytree from a run's per-sample series dict
    ``{field: [m]}`` and its final state: windowed series always, the
    monitor block when ``pcfg.monitors`` (schema docstring).  Pure traced
    data — callers return it as a second jit output."""
    out = {"series": window(series, n_samples, pcfg.windows)}
    if pcfg.monitors:
        mon = monitors(cfg, final_state)
        mon["liveness_lag"] = liveness_lag(
            series[schema.PROGRESS_FIELD[cfg.protocol]]
        )
        out["monitors"] = mon
    return out
