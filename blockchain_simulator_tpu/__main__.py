"""``python -m blockchain_simulator_tpu`` — see cli.py."""

import sys

from blockchain_simulator_tpu.cli import main

sys.exit(main())
