"""Topology builders.

The reference builds exactly one topology: a full mesh of N·(N-1)/2
point-to-point links (blockchain-simulator.cc:34-51), O(N²) in links and
per-wave messages — the scaling wall (SURVEY.md §5 "long-context" analog).
The framework's delivery ops treat the full mesh implicitly (broadcast = all
peers); this module adds the sparse alternative for 10k+ nodes (BASELINE
config 3): a random k-out gossip digraph over which requests *flood* with a
hop TTL instead of being broadcast edge-by-edge.

``kregular_out_neighbors`` returns a ``[N, deg]`` table of global receiver
ids: column 0 is the successor ring edge (guarantees strong connectivity),
the remaining columns are independent random permutations (one out-edge per
node each, giving the O(log N) diameter of a random regular digraph).
Self-loops and duplicate edges can occur in the random columns and are
harmless — gossip delivery deduplicates by value at the receiver.
"""

from __future__ import annotations

import numpy as np


def kregular_out_neighbors(n: int, deg: int, seed: int) -> np.ndarray:
    """[N, deg] int32 global out-neighbor table (ring + deg-1 random
    permutation columns), deterministic in ``seed``."""
    if deg < 2:
        raise ValueError(f"gossip degree must be >= 2, got {deg}")
    rng = np.random.default_rng(seed ^ 0x70B0)
    cols = [(np.arange(n) + 1) % n]
    for _ in range(deg - 1):
        cols.append(rng.permutation(n))
    return np.stack(cols, axis=1).astype(np.int32)


def flood_reach_hops(n: int, deg: int, nbrs: np.ndarray, src: int) -> int:
    """BFS hop count to reach every node from ``src`` (test/validation aid)."""
    dist = np.full(n, -1)
    dist[src] = 0
    frontier = [src]
    hops = 0
    while frontier:
        hops += 1
        nxt = []
        for u in frontier:
            for v in nbrs[u]:
                if dist[v] < 0:
                    dist[v] = hops
                    nxt.append(v)
        frontier = nxt
    if (dist < 0).any():
        raise ValueError("gossip graph not strongly connected from src")
    return int(dist.max())
