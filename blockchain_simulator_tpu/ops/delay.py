"""Delay models.

The reference defers every send by ``Simulator::Schedule(getRandomDelay(), ...)``
with per-protocol uniform distributions (pbft-node.cc:66-69 U{3..5} ms,
raft-node.cc:63-66 U{0..2} ms, paxos-node.cc:397-400 U[0,50) ms) on top of the
3 ms point-to-point channel delay (blockchain-simulator.cc:24).  Here a delay is
an integer number of ticks; two families of samplers:

- *edge* samplers draw one delay per (sender, receiver) edge — exact.
- *stat* samplers draw per-receiver bucket **counts** directly from the induced
  binomial/multinomial distribution — statistically exact for full-mesh
  channels whose receivers only consume counts, and O(N·B) instead of O(N²).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


def uniform_probs(lo: int, hi: int) -> np.ndarray:
    """Bucket probabilities of U{lo..hi-1}, indexed 0..hi-lo-1 (offset lo)."""
    b = hi - lo
    return np.full((b,), 1.0 / b)


def roundtrip_probs(lo: int, hi: int) -> np.ndarray:
    """Distribution of the sum of two independent U{lo..hi-1} draws
    (request delay + reply delay), indexed 0..2*(hi-lo)-2 (offset 2*lo)."""
    p = uniform_probs(lo, hi)
    return np.convolve(p, p)


def _rbg_key(key: jax.Array) -> jax.Array:
    """Derive an ``rbg``-impl key (XLA's RngBitGenerator — far cheaper bit
    generation than threefry on XLA:CPU) from WHATEVER impl the caller's key
    uses: threefry keys hold 2 words, rbg/unsafe_rbg 4; tile-then-slice
    reduces to the identity for 4-word keys and to ``tile(kd, 2)`` for
    threefry.  Shared by :func:`_fast_normal` and the ``"rbg"`` edge
    sampler; the source key is already per-channel/per-tick folded, so
    streams stay decorrelated."""
    kd = jnp.ravel(jax.random.key_data(key))
    return jax.random.wrap_key_data(jnp.tile(kd, 4)[:4], impl="rbg")


def sample_edge_delays(key: jax.Array, shape, lo: int, hi: int,
                       impl: str = "threefry") -> jax.Array:
    """One delay per edge, in [lo, hi).

    ``impl`` selects the bit source (``SimConfig.edge_sampler``):

    - ``"threefry"`` (default): ``jax.random.randint`` on the caller's key —
      the historical stream every seed-pinned edge-path test rides.
    - ``"rbg"``: the same *exact-uniform integer* map fed by cheap
      RngBitGenerator words (the ``_fast_normal`` trick, minus the CLT):
      when the span ``hi - lo`` is a power of two <= 2^16, each 32-bit word
      bit-slices into TWO independent 16-bit fields and a mask — exactly
      uniform at half the generated bits; otherwise full 32-bit words map
      through the same shift-and-remainder construction
      ``jax.random.randint`` uses (bias <= span * 2^-32, identical class).
      Either way the map is pure integer arithmetic, so the repo's bit
      contract holds across differently-compiled UNBATCHED programs: the
      SAME key gives the SAME delays under jit, eager, ``lax.map`` lanes
      and mesh per-device bodies (the multi-seed/mesh sweep arms) — unlike
      the float ``"normal"`` stat mode's reassociation latitude
      (parallel/sweep.py).  One scoped caveat, shared with
      :func:`_fast_normal`: XLA's RngBitGenerator is NOT batch-invariant
      under ``vmap`` — a vmapped lane (other than lane 0) draws different
      bits than the same key unbatched, so vmap-vs-solo bit-equality pins
      must keep ``edge_sampler="threefry"`` exactly as they must keep
      ``stat_sampler="exact"``.  The stream DIFFERS from ``"threefry"``
      (same distribution), so the toggle is a config field, never an
      implicit swap.
    """
    if impl == "threefry":
        return jax.random.randint(key, shape, lo, hi, dtype=jnp.int32)
    if impl != "rbg":
        raise ValueError(f"unknown edge sampler impl {impl!r}")
    span = hi - lo
    rbg = _rbg_key(key)
    if span & (span - 1) == 0 and span <= (1 << 16):
        # power-of-two span: mask 16-bit fields — exactly uniform, and each
        # generated word yields two independent draws (disjoint bit fields)
        if not shape:
            return sample_edge_delays(key, (1,), lo, hi, impl)[0]
        r = shape[0]
        words = jax.random.bits(
            rbg, ((r + 1) // 2,) + tuple(shape[1:]), jnp.uint32
        )
        fields = jnp.concatenate(
            [words & jnp.uint32(0xFFFF), words >> 16], axis=0
        )[:r]
        return (lo + (fields & jnp.uint32(span - 1))).astype(jnp.int32)
    # general span: full 32-bit words through randint's own construction
    # (remainder over the word range) — bias <= span * 2^-32, the same
    # class jax.random.randint documents for non-power-of-two spans
    words = jax.random.bits(rbg, tuple(shape), jnp.uint32)
    return (lo + (words % jnp.uint32(span))).astype(jnp.int32)


def _fast_normal(key: jax.Array, shape) -> jax.Array:
    """Cheap standard-normal draws for the "normal"-mode sampler: one
    Philox word (``rbg`` impl — XLA's RngBitGenerator, far cheaper than
    threefry on XLA:CPU) yields TWO z values via 16-bit popcounts —
    ``(popcount(u16) - 8) / 2`` is a centered Binomial(16, 1/2), the CLT
    normal with mean 0 / variance exactly 1 — skipping the uniform->erfinv
    pipeline of ``jax.random.normal`` entirely (integer ops until the
    final scale) and halving the generated bits.

    Quality is deliberately CLT-level: the Gaussian binomial approximation
    this feeds is itself O(1/sqrt(n)) off, and the z lattice (step 0.5,
    first two moments exact) disappears into the round-to-integer-counts
    that follows.  Everything bit-contract-sensitive (per-edge delays,
    elections, view changes) stays on exact threefry draws.  The rbg key
    derives from the caller's (already per-channel/per-tick folded)
    threefry key, so streams stay decorrelated; the two halves of a word
    are disjoint bit fields, hence independent.  Profiled on the CPU
    fallback bench (VERDICT r5 weak-#4): the threefry
    ``jax.random.normal`` variant put the 10k-node round step at ~70%
    PRNG time (155 rounds/s); this form more than doubles end-to-end
    throughput (424 rounds/s single-core)."""
    if not shape:
        return _fast_normal(key, (1,))[0]
    rbg = _rbg_key(key)
    r = shape[0]
    words = jax.random.bits(rbg, ((r + 1) // 2,) + tuple(shape[1:]), jnp.uint32)
    lo = jax.lax.population_count(words & jnp.uint32(0xFFFF))
    hi = jax.lax.population_count(words >> 16)
    z = jnp.concatenate([lo, hi], axis=0)[:r]
    return (z.astype(jnp.float32) - 8.0) * 0.5


def binom(key: jax.Array, n: jax.Array, p: float, mode: str = "exact") -> jax.Array:
    """Binomial(n, p) draw (float32 out, same shape as ``n``).

    ``mode="normal"``: Gaussian approximation, ~6 elementwise passes instead
    of the ~40 of BTRS rejection sampling — see sample_bucket_counts."""
    n = jnp.asarray(n, jnp.float32)
    if mode == "normal":
        z = _fast_normal(key, n.shape)
        mu = n * p
        sigma = jnp.sqrt(jnp.maximum(mu * (1.0 - p), 0.0))
        return jnp.clip(jnp.round(mu + sigma * z), 0.0, n)
    return jax.random.binomial(key, n, p)


def sample_bucket_counts(key: jax.Array, n: jax.Array, probs: np.ndarray,
                         mode: str = "exact") -> jax.Array:
    """Split ``n`` (int array, any shape) into bucket counts ~ Multinomial(n, probs).

    Implemented as a chain of conditional binomials over the (small, static)
    bucket axis.  Returns int32 of shape ``(len(probs),) + n.shape``.

    ``mode`` selects the per-bucket binomial sampler:

    - ``"exact"``: ``jax.random.binomial`` (BTRS rejection sampling) — exact,
      but ~40 elementwise passes per bucket; the round-2 tick loop spent much
      of its time here.
    - ``"normal"``: Gaussian approximation ``round(mu + sigma*z)`` clipped to
      ``[0, remaining]``.  Counts still sum exactly to ``n`` (the chain
      construction guarantees it), so every message is delivered exactly
      once; only the spread across delay buckets is approximate, with
      relative error O(1/sqrt(n·p)) — negligible at the 10k-100k-node scales
      this mode is selected for (SimConfig.stat_sampler = "auto" picks it
      only at large n).  All buckets' z-draws come from ONE
      ``jax.random.normal`` call over a leading bucket axis: a single fused
      threefry pass instead of a fold_in + draw per bucket — the chain's
      per-bucket work is then ~5 cheap elementwise ops, which is what makes
      the sampler-bound round fast path viable on the XLA:CPU fallback
      (the per-bucket variant measured ~3x slower end-to-end there).

    The ``"exact"`` chain mirrors the single-derivation trick at the key
    level: per-bucket keys come from ONE batched ``vmap(fold_in)`` pass
    over the bucket axis instead of a scalar ``fold_in(key, b)`` inside the
    loop — one fused threefry dispatch for the whole chain.  ``vmap`` of
    ``fold_in`` is bit-identical to the per-bucket scalar calls (fold_in is
    an elementwise threefry of the folded constant), so the exact stream —
    and every seed-pinned bit-equality test riding it — is unchanged; a
    ``jax.random.split``-based hoist would have been equally fused but
    minted a brand-new stream, moving every pinned trajectory for zero
    additional win (moments are identical either way — the per-bucket keys
    are independent uniforms in both constructions).  Only the BTRS
    rejection passes themselves remain per-bucket; they are inherently
    sequential (each bucket's ``n`` is the previous bucket's remainder).
    """
    return jnp.stack(list(bucket_count_chain(key, n, probs, mode))).astype(
        jnp.int32
    )


def bucket_count_chain(key: jax.Array, n: jax.Array, probs: np.ndarray,
                       mode: str = "exact"):
    """The conditional-binomial chain behind :func:`sample_bucket_counts`,
    yielded one bucket at a time (float32, shape ``n.shape``) so callers can
    fuse each bucket's sampler math into its consumer without materializing
    the stacked ``[B, ...]`` tensor — ops/delivery.py's fused ring pushes
    combine bucket ``b`` into its ring slice as it is produced.  Yields the
    EXACT values :func:`sample_bucket_counts` stacks (same keys, same
    arithmetic, same order), so fused and unfused consumers are bit-equal."""
    n = jnp.asarray(n, jnp.float32)
    nb = len(probs)
    # the last bucket is always the remainder — it never consumes a draw
    z_all = (
        _fast_normal(key, (max(nb - 1, 1),) + n.shape)
        if mode == "normal" else None
    )
    keys = (
        jax.vmap(lambda b: jax.random.fold_in(key, b))(jnp.arange(max(nb - 1, 1)))
        if mode != "normal" and nb > 1 else None
    )
    remaining = n
    p_left = 1.0
    for b, pb in enumerate(probs):
        frac = float(min(max(pb / max(p_left, 1e-9), 0.0), 1.0))
        if b == nb - 1 or frac >= 1.0:
            c = remaining
        elif mode == "normal":
            mu = remaining * frac
            sigma = jnp.sqrt(jnp.maximum(mu * (1.0 - frac), 0.0))
            c = jnp.clip(jnp.round(mu + sigma * z_all[b]), 0.0, remaining)
        else:
            c = binom(keys[b], remaining, frac, mode)
        yield c
        remaining = remaining - c
        p_left -= pb
