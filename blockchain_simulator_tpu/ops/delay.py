"""Delay models.

The reference defers every send by ``Simulator::Schedule(getRandomDelay(), ...)``
with per-protocol uniform distributions (pbft-node.cc:66-69 U{3..5} ms,
raft-node.cc:63-66 U{0..2} ms, paxos-node.cc:397-400 U[0,50) ms) on top of the
3 ms point-to-point channel delay (blockchain-simulator.cc:24).  Here a delay is
an integer number of ticks; two families of samplers:

- *edge* samplers draw one delay per (sender, receiver) edge — exact.
- *stat* samplers draw per-receiver bucket **counts** directly from the induced
  binomial/multinomial distribution — statistically exact for full-mesh
  channels whose receivers only consume counts, and O(N·B) instead of O(N²).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


def uniform_probs(lo: int, hi: int) -> np.ndarray:
    """Bucket probabilities of U{lo..hi-1}, indexed 0..hi-lo-1 (offset lo)."""
    b = hi - lo
    return np.full((b,), 1.0 / b)


def roundtrip_probs(lo: int, hi: int) -> np.ndarray:
    """Distribution of the sum of two independent U{lo..hi-1} draws
    (request delay + reply delay), indexed 0..2*(hi-lo)-2 (offset 2*lo)."""
    p = uniform_probs(lo, hi)
    return np.convolve(p, p)


def sample_edge_delays(key: jax.Array, shape, lo: int, hi: int) -> jax.Array:
    """One delay per edge, in [lo, hi)."""
    return jax.random.randint(key, shape, lo, hi, dtype=jnp.int32)


def binom(key: jax.Array, n: jax.Array, p: float, mode: str = "exact") -> jax.Array:
    """Binomial(n, p) draw (float32 out, same shape as ``n``).

    ``mode="normal"``: Gaussian approximation, ~6 elementwise passes instead
    of the ~40 of BTRS rejection sampling — see sample_bucket_counts."""
    n = jnp.asarray(n, jnp.float32)
    if mode == "normal":
        z = jax.random.normal(key, n.shape, jnp.float32)
        mu = n * p
        sigma = jnp.sqrt(jnp.maximum(mu * (1.0 - p), 0.0))
        return jnp.clip(jnp.round(mu + sigma * z), 0.0, n)
    return jax.random.binomial(key, n, p)


def sample_bucket_counts(key: jax.Array, n: jax.Array, probs: np.ndarray,
                         mode: str = "exact") -> jax.Array:
    """Split ``n`` (int array, any shape) into bucket counts ~ Multinomial(n, probs).

    Implemented as a chain of conditional binomials over the (small, static)
    bucket axis.  Returns int32 of shape ``(len(probs),) + n.shape``.

    ``mode`` selects the per-bucket binomial sampler:

    - ``"exact"``: ``jax.random.binomial`` (BTRS rejection sampling) — exact,
      but ~40 elementwise passes per bucket; the round-2 tick loop spent much
      of its time here.
    - ``"normal"``: Gaussian approximation ``round(mu + sigma*z)`` clipped to
      ``[0, remaining]`` — ~6 passes per bucket.  Counts still sum exactly to
      ``n`` (the chain construction guarantees it), so every message is
      delivered exactly once; only the spread across delay buckets is
      approximate, with relative error O(1/sqrt(n·p)) — negligible at the
      10k-100k-node scales this mode is selected for (SimConfig.stat_sampler
      = "auto" picks it only at large n).
    """
    n = jnp.asarray(n, jnp.float32)
    counts = []
    remaining = n
    p_left = 1.0
    for b, pb in enumerate(probs):
        kb = jax.random.fold_in(key, b)
        frac = float(min(max(pb / max(p_left, 1e-9), 0.0), 1.0))
        if b == len(probs) - 1 or frac >= 1.0:
            c = remaining
        else:
            c = binom(kb, remaining, frac, mode)
        counts.append(c)
        remaining = remaining - c
        p_left -= pb
    return jnp.stack(counts).astype(jnp.int32)
