"""Pallas TPU kernel for the ring-buffer push (SURVEY.md §7 L7).

The tick engines' dominant cost at N = 100k is pushing delivery contributions
into the future-inbox rings (round-3 ablation, tools/ablate.py: ~2.0 of
2.24 ms/tick).  The XLA forms both lose bandwidth:

- ``buf.at[idx_vec].add`` lowers to generic scatter — catastrophic on TPU
  (~30x slower than the DUS chain, per the round-3 rewrite);
- the DUS chain (ops/ring._push) is a dynamic-slice + dynamic-update-slice
  pair per delay bucket; inside a ``lax.scan`` body XLA cannot always prove
  the carried buffer dead, so each pair costs a slice-sized (or worse,
  buffer-sized) copy, B times per channel per tick.

This kernel fuses the whole push into ONE in-place pass: the ring flattens to
``[D, L]``, the grid runs over ``(bucket, L-tile)``, and a scalar-prefetched
tick index lets the BlockSpec index_map address exactly the ``B`` ring slices
the push touches — nothing else is read or written (``input_output_aliases``
pins in-place semantics; untouched slices keep their values).  Traffic is the
information-theoretic floor: read+write of B slices plus read of the
contribution.

Availability: compiled path on TPU only (``jax.default_backend() == "tpu"``);
``interpret=True`` runs anywhere and is used by the CPU correctness tests
(tests/test_ops.py).  ``ring._push`` falls back to the DUS chain when the
kernel is unavailable or the shape does not tile (L has no usable 128-multiple
divisor).  Selection: env ``BLOCKSIM_RING_KERNEL`` in {"auto" (default),
"pallas", "dus"}.

Round-4 measurement verdict (ARTIFACT_ring_kernel.json, KNOWN_ISSUES.md #5):
the DUS chain measured IN ISOLATION is already ~75% of HBM peak for the op's
intrinsic traffic (128 us/tick for the three PBFT channels at N=100k, vs
~86 us theoretical) — the round-3 ablation's "2.0 of 2.24 ms/tick is pushes"
was a subtraction artifact (patching pushes out lets XLA dead-code-eliminate
the dependent consumers too).  A pallas kernel moves the same bytes, so it
cannot materially beat the DUS chain; on this environment's axon backend its
Mosaic compile additionally ran >15 min without completing.  ``"auto"``
therefore resolves to the DUS chain everywhere; the kernel stays as an
explicitly-selectable (``"pallas"``), interpret-tested alternative.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

try:  # pallas is part of jax, but keep the import soft for exotic builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False

# VMEM budget per block: 3 live blocks (buf in, contrib in, out) with double
# buffering; 512 KB each stays well inside ~16 MB/core.
_MAX_TILE_BYTES = 512 * 1024
_MIN_TILE = 128


def mode() -> str:
    return os.environ.get("BLOCKSIM_RING_KERNEL", "auto")


def enabled() -> bool:
    m = mode()
    if m == "dus" or not _HAVE_PALLAS:
        return False
    # "auto" resolves to the DUS chain: measured near-bandwidth-optimal in
    # isolation, and this env's axon backend did not finish compiling the
    # pallas kernel (>15 min; see module docstring / KNOWN_ISSUES.md #5).
    # Even explicit "pallas" needs the TPU backend — Mosaic does not lower
    # to CPU/GPU; tests use fused_push(..., interpret=True) directly.
    return m == "pallas" and jax.default_backend() == "tpu"


@functools.lru_cache(maxsize=None)
def _pick_tile(l: int, itemsize: int) -> int | None:
    """Largest divisor of ``l`` of the form 128*k fitting the VMEM budget."""
    best = None
    limit = _MAX_TILE_BYTES // itemsize
    k = 1
    # divisors of l/128 (l is a few hundred thousand at most — trial division
    # over k up to l/128 is trace-time only and cached)
    if l % _MIN_TILE != 0:
        return None
    m = l // _MIN_TILE
    for k in range(1, m + 1):
        if m % k == 0:
            tl = _MIN_TILE * k
            if tl <= limit:
                best = tl
            else:
                break
    return best


def _kernel(combine):
    def body(t_ref, buf_blk, c_blk, out_blk):
        del t_ref  # consumed by the index_maps
        out_blk[...] = combine(buf_blk[...], c_blk[...])

    return body


def fused_push(buf, t, lo: int, contrib, op: str, interpret: bool = False):
    """In-place ``buf[(t+lo+b) % D] op= contrib[b]`` for all buckets b.

    ``buf``: [D, ...rest]; ``contrib``: [B, ...rest] (same rest), B <= D.
    ``op``: "add" | "max".  Returns the updated buffer (donated input).
    """
    d = buf.shape[0]
    b = contrib.shape[0]
    rest = buf.shape[1:]
    l = int(np.prod(rest)) if rest else 1
    tl = _pick_tile(l, buf.dtype.itemsize)
    assert tl is not None and b <= d  # callers check pushable() first
    # [D, 1, L] so block (1, 1, TL) satisfies the TPU tiling rule: the
    # sublane (second-to-last) block dim equals the full array dim (1) and
    # the lane dim TL is a 128-multiple
    buf2 = buf.reshape(d, 1, l)
    c2 = contrib.reshape(b, 1, l)
    combine = jnp.add if op == "add" else jnp.maximum
    t_arr = jnp.asarray(t, jnp.int32).reshape(1)

    def idx_ring(bi, i, t_ref):
        return ((t_ref[0] + lo + bi) % d, 0, i)

    def idx_contrib(bi, i, t_ref):
        del t_ref
        return (bi, 0, i)

    out = pl.pallas_call(
        _kernel(combine),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, l // tl),
            in_specs=[
                pl.BlockSpec((1, 1, tl), idx_ring),
                pl.BlockSpec((1, 1, tl), idx_contrib),
            ],
            out_specs=pl.BlockSpec((1, 1, tl), idx_ring),
        ),
        out_shape=jax.ShapeDtypeStruct((d, 1, l), buf.dtype),
        # out aliases the ring input: the kernel is a true in-place update and
        # the D-B untouched slices keep their values
        input_output_aliases={1: 0},
        interpret=interpret,
    )(t_arr, buf2, c2)
    return out.reshape(buf.shape)


def pushable(buf, contrib) -> bool:
    """Static eligibility of the fused kernel for this push."""
    if not _HAVE_PALLAS:
        return False
    if contrib.shape[0] > buf.shape[0]:
        return False
    rest = buf.shape[1:]
    l = int(np.prod(rest)) if rest else 1
    return _pick_tile(l, buf.dtype.itemsize) is not None
