"""Message delivery: senders → per-bucket ring contributions.

Each function turns "who is sending what this tick" into a contribution tensor
``[B, ...receiver dims]`` to be ``ring_push``-ed, where ``B`` spans the delay
distribution's support (offset ``lo``).  The reference's per-message
``Simulator::Schedule(getRandomDelay(), ...)`` (SURVEY.md C8) becomes either an
exact per-edge sample (*dense*) or a statistically exact per-receiver bucket
count (*stat*, for full-mesh count-consumed channels at large N).

Conventions: senders never deliver to themselves (the reference's peer lists
exclude self, network-helper.cc / blockchain-simulator.cc:44-45); ``send`` masks
are already fault-masked by the caller; ``drop_prob`` models lossy edges (a
capability absent in the reference — its simulated links never drop).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from blockchain_simulator_tpu.ops.delay import sample_bucket_counts, sample_edge_delays


def _edge_hits(key, send, lo: int, hi: int, drop_prob: float = 0.0):
    """[B, N_send, N_recv] 0/1 delivery indicators, self-edges removed."""
    n = send.shape[0]
    d = sample_edge_delays(key, (n, n), lo, hi)
    mask = send.astype(jnp.int32)[:, None] * (1 - jnp.eye(n, dtype=jnp.int32))
    if drop_prob > 0.0:
        keep = jax.random.bernoulli(
            jax.random.fold_in(key, 0x0D0D), 1.0 - drop_prob, (n, n)
        )
        mask = mask * keep.astype(jnp.int32)
    return jnp.stack([(d == lo + b).astype(jnp.int32) * mask for b in range(hi - lo)])


# --------------------------------------------------------------------------- #
# dense (exact per-edge) delivery                                             #
# --------------------------------------------------------------------------- #


def bcast_counts_dense(key, send, lo, hi, drop_prob=0.0):
    """Broadcast → per-receiver arrival counts.  Returns [B, N]."""
    return _edge_hits(key, send, lo, hi, drop_prob).sum(1)


def bcast_value_max_dense(key, send, value, lo, hi, drop_prob=0.0):
    """Broadcast of a per-sender value (>0; 0 = empty), max-combined at the
    receiver.  Returns [B, N]."""
    hits = _edge_hits(key, send, lo, hi, drop_prob)
    return (hits * value.astype(jnp.int32)[None, :, None]).max(1)


def bcast_slots_dense(key, slot_mat, lo, hi, drop_prob=0.0):
    """Slot-keyed broadcast (e.g. PBFT messages carrying seq no n): sender i
    broadcasts one message per active slot in ``slot_mat[i, s]`` (0/1).
    Returns arrival counts per (receiver, slot): [B, N, S].

    Note: when a sender is active in several slots in the same tick, those
    broadcasts share one delay draw per edge (a documented simplification; the
    reference draws per message, pbft-node.cc:364)."""
    send = slot_mat.max(axis=1)
    hits = _edge_hits(key, send, lo, hi, drop_prob)  # [B, N, N]
    return jnp.einsum("bij,is->bjs", hits, slot_mat.astype(jnp.int32))


def roundtrip_reply_counts_dense(key, send, lo, hi, drop_prob=0.0, peer_mask=None):
    """Short-circuited request/reply round trip: sender i broadcasts, every
    peer replies unconditionally and instantly, the reply travels back with an
    independent delay.  Used where the peer's state does not affect the reply
    (PBFT PREPARE → PREPARE_RES SUCCESS, pbft-node.cc:212-221; Raft HEARTBEAT →
    HEARTBEAT_RES SUCCESS, raft-node.cc:170-193).  ``peer_mask`` restricts which
    peers reply (crashed/Byzantine exclusion).  Returns reply counts at the
    original sender: [B2, N], offset 2*lo, B2 = 2*(hi-lo)-1."""
    n = send.shape[0]
    d1 = sample_edge_delays(jax.random.fold_in(key, 1), (n, n), lo, hi)
    d2 = sample_edge_delays(jax.random.fold_in(key, 2), (n, n), lo, hi)
    total = d1 + d2  # delay until the reply reaches the sender
    mask = send.astype(jnp.int32)[:, None] * (1 - jnp.eye(n, dtype=jnp.int32))
    if peer_mask is not None:
        mask = mask * peer_mask.astype(jnp.int32)[None, :]
    if drop_prob > 0.0:
        # either leg can drop
        keep = jax.random.bernoulli(
            jax.random.fold_in(key, 0x0D0E), (1.0 - drop_prob) ** 2, (n, n)
        )
        mask = mask * keep.astype(jnp.int32)
    lo2 = 2 * lo
    nb = 2 * (hi - lo) - 1
    return jnp.stack(
        [((total == lo2 + b).astype(jnp.int32) * mask).sum(1) for b in range(nb)]
    )


def unicast_reply_counts_dense(key, reply, lo, hi, drop_prob=0.0):
    """Route per-(replier, requester) reply counts back to each requester.
    ``reply[r, c]`` = number of (identical, count-consumed) replies node r
    sends node c this tick.  Returns [B, N] indexed by requester c."""
    n = reply.shape[0]
    d = sample_edge_delays(key, (n, n), lo, hi)
    mask = 1 - jnp.eye(n, dtype=jnp.int32)
    if drop_prob > 0.0:
        keep = jax.random.bernoulli(
            jax.random.fold_in(key, 0x0D0F), 1.0 - drop_prob, (n, n)
        )
        mask = mask * keep.astype(jnp.int32)
    r = reply.astype(jnp.int32) * mask
    return jnp.stack([(r * (d == lo + b)).sum(0) for b in range(hi - lo)])


def bcast_matrix_dense(key, send, value, lo, hi, drop_prob=0.0):
    """Identity-preserving broadcast for request channels whose handling
    depends on receiver state at arrival (Raft VOTE_REQ, Paxos REQUEST_*).
    ``value`` (>0 per sender; 0 = empty) lands at ``[b, receiver, sender]``.
    Returns [B, N, N] (max-combined into a matrix ring)."""
    hits = _edge_hits(key, send, lo, hi, drop_prob)  # [B, send, recv]
    return jnp.swapaxes(hits * value.astype(jnp.int32)[None, :, None], 1, 2)


# --------------------------------------------------------------------------- #
# stat (aggregated, statistically exact) delivery                             #
# --------------------------------------------------------------------------- #


def bcast_counts_stat(key, n_senders, is_sender, probs: np.ndarray, drop_prob=0.0):
    """Full-mesh broadcast arrival counts without materializing edges.

    Each receiver j hears from ``n_senders - is_sender[j]`` peers; its arrival
    buckets are Multinomial over the delay distribution, independent across
    receivers (distinct edges ⇒ independent delays).  Returns [B, N]."""
    m = jnp.asarray(n_senders, jnp.int32) - is_sender.astype(jnp.int32)
    if drop_prob > 0.0:
        m = jnp.round(
            jax.random.binomial(
                jax.random.fold_in(key, 0x0D10), m.astype(jnp.float32), 1.0 - drop_prob
            )
        ).astype(jnp.int32)
    return sample_bucket_counts(key, m, probs)


def bcast_slots_stat(key, slot_mat, probs: np.ndarray, drop_prob=0.0):
    """Stat version of bcast_slots_dense: receiver j hears, per slot s,
    from ``(Σ_i slot_mat[i,s]) - slot_mat[j,s]`` senders; arrival buckets are
    multinomial per (receiver, slot).  Returns [B, N, S]."""
    sm = slot_mat.astype(jnp.int32)
    m = sm.sum(axis=0)[None, :] - sm  # [N, S]
    if drop_prob > 0.0:
        m = jnp.round(
            jax.random.binomial(
                jax.random.fold_in(key, 0x0D12), m.astype(jnp.float32), 1.0 - drop_prob
            )
        ).astype(jnp.int32)
    return sample_bucket_counts(key, m, probs)


def bcast_value_max_stat(key, value, probs: np.ndarray, drop_prob=0.0):
    """Stat version of bcast_value_max_dense for ≤-a-few senders (e.g. PBFT
    VIEW_CHANGE from the leader): deliver the max announced value to every
    receiver with one per-receiver delay draw.  Returns [B, N]."""
    n = value.shape[0]
    vmax = value.astype(jnp.int32).max()
    nb = len(probs)
    d = jax.random.categorical(key, jnp.log(jnp.asarray(probs) + 1e-30), shape=(n,))
    sent = (vmax > 0).astype(jnp.int32)
    if drop_prob > 0.0:
        keep = jax.random.bernoulli(
            jax.random.fold_in(key, 0x0D13), 1.0 - drop_prob, (n,)
        )
        sent = sent * keep.astype(jnp.int32)
    # a node that announced the (same, max) value already applied it locally;
    # re-delivery to it is a harmless no-op, matching max-combine semantics
    return jnp.stack([(d == b).astype(jnp.int32) * sent * vmax for b in range(nb)])


def roundtrip_reply_counts_stat(key, send, n_peers, rt_probs: np.ndarray, drop_prob=0.0):
    """Stat version of roundtrip_reply_counts_dense: each active sender gets
    ``n_peers`` replies multinomially spread over the round-trip distribution.
    Returns [B2, N]."""
    m = send.astype(jnp.int32) * jnp.asarray(n_peers, jnp.int32)
    if drop_prob > 0.0:
        p_keep = (1.0 - drop_prob) ** 2
        m = jnp.round(
            jax.random.binomial(
                jax.random.fold_in(key, 0x0D11), m.astype(jnp.float32), p_keep
            )
        ).astype(jnp.int32)
    return sample_bucket_counts(key, m, rt_probs)
