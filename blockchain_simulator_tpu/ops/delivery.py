"""Message delivery: senders → per-bucket ring contributions.

Each function turns "who is sending what this tick" into a contribution tensor
``[B, ...receiver dims]`` to be ``ring_push``-ed, where ``B`` spans the delay
distribution's support (offset ``lo``).  The reference's per-message
``Simulator::Schedule(getRandomDelay(), ...)`` (SURVEY.md C8) becomes either an
exact per-edge sample (*dense*) or a statistically exact per-receiver bucket
count (*stat*, for full-mesh count-consumed channels at large N).

SPMD: every function takes ``axis`` — the name of a mesh axis over which the
node dimension is sharded (None = unsharded).  Inside ``shard_map`` the
receiver axis stays local while sender-side quantities are globalized with XLA
collectives (``all_gather`` for masks/values, ``psum`` for totals); this is the
TPU-native replacement for the reference's simulated UDP fan-out
(pbft-node.cc:350-368) — message exchange rides ICI, not a socket model.

Conventions: senders never deliver to themselves (the reference's peer lists
exclude self, blockchain-simulator.cc:44-45); ``send`` masks are already
fault-masked by the caller; ``drop_prob`` models lossy edges (a capability
absent in the reference — its simulated links never drop).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from blockchain_simulator_tpu.ops.delay import (
    binom,
    bucket_count_chain,
    sample_bucket_counts,
    sample_edge_delays,
)


def _shard_key(key, axis):
    """Decorrelate per-shard sampling (each edge must be drawn exactly once,
    by the shard that consumes it)."""
    if axis is None:
        return key
    return jax.random.fold_in(key, lax.axis_index(axis))


def _gather(x, axis):
    """Local [n_loc, ...] -> global [N, ...] along the node axis."""
    if axis is None:
        return x
    return lax.all_gather(x, axis, tiled=True)


def _global_ids(n_loc: int, axis):
    """Global node ids of this shard's rows."""
    base = 0 if axis is None else lax.axis_index(axis) * n_loc
    return base + jnp.arange(n_loc)


def _bucket_iota(lo: int, hi: int, ndim: int):
    """``[B, 1, ...]`` bucket values ``lo..hi-1`` broadcastable against a
    rank-``ndim`` delay tensor — the vectorized replacement for the
    per-bucket ``d == lo + b`` python loops, which XLA:CPU compiled as B
    separate compare+select passes over the edge tensor; one broadcast
    compare fuses into a single traversal."""
    return jnp.arange(lo, hi, dtype=jnp.int32).reshape((-1,) + (1,) * ndim)


def _edge_hits(key, send, lo: int, hi: int, drop_prob: float = 0.0, axis=None,
               send_global=None, impl: str = "threefry"):
    """[B, N_send_global, N_recv_local] 0/1 delivery indicators, self-edges
    removed.  Delays are sampled receiver-side (each edge's delay is consumed
    by exactly one shard, so per-shard independent draws are exact).
    ``send_global`` lets callers reuse an already-gathered sender mask;
    ``impl`` selects the per-edge bit source (SimConfig.edge_sampler)."""
    n_loc = send.shape[0]
    send_g = _gather(send, axis) if send_global is None else send_global
    n_glob = send_g.shape[0]
    k = _shard_key(key, axis)
    d = sample_edge_delays(k, (n_glob, n_loc), lo, hi, impl)
    notself = (jnp.arange(n_glob)[:, None] != _global_ids(n_loc, axis)[None, :])
    mask = send_g.astype(jnp.int32)[:, None] * notself.astype(jnp.int32)
    if drop_prob > 0.0:
        keep = jax.random.bernoulli(
            jax.random.fold_in(k, 0x0D0D), 1.0 - drop_prob, (n_glob, n_loc)
        )
        mask = mask * keep.astype(jnp.int32)
    return (d[None] == _bucket_iota(lo, hi, d.ndim)).astype(jnp.int32) * mask[None]


# --------------------------------------------------------------------------- #
# dense (exact per-edge) delivery                                             #
# --------------------------------------------------------------------------- #


def bcast_counts_dense(key, send, lo, hi, drop_prob=0.0, axis=None,
                       impl="threefry"):
    """Broadcast → per-receiver arrival counts.  Returns [B, N_loc]."""
    return _edge_hits(key, send, lo, hi, drop_prob, axis, impl=impl).sum(1)


def bcast_value_max_dense(key, send, value, lo, hi, drop_prob=0.0, axis=None,
                          impl="threefry"):
    """Broadcast of a per-sender value (>0; 0 = empty), max-combined at the
    receiver.  Returns [B, N_loc]."""
    hits = _edge_hits(key, send, lo, hi, drop_prob, axis, impl=impl)
    value_g = _gather(value, axis)
    return (hits * value_g.astype(jnp.int32)[None, :, None]).max(1)


def bcast_slots_dense(key, slot_mat, lo, hi, drop_prob=0.0, axis=None,
                      impl="threefry"):
    """Slot-keyed broadcast (e.g. PBFT messages carrying seq no n): sender i
    broadcasts ``slot_mat[i, s]`` copies per slot (int counts; >1 only for
    Byzantine vote flooding).  Returns arrival counts per (receiver, slot):
    [B, N_loc, S].

    Note: when a sender is active in several slots (or copies) in the same
    tick, those broadcasts share one delay draw per edge (a documented
    simplification; the reference draws per message, pbft-node.cc:364)."""
    slot_g = _gather(slot_mat.astype(jnp.int32), axis)
    send = slot_mat.max(axis=1) > 0
    hits = _edge_hits(
        key, send, lo, hi, drop_prob, axis, send_global=slot_g.max(axis=1) > 0,
        impl=impl,
    )  # [B, N_glob, N_loc] 0/1
    return jnp.einsum("bij,is->bjs", hits, slot_g)


def bcast_window_value_max_dense(key, value_mat, lo, hi, drop_prob=0.0, axis=None,
                                 impl="threefry"):
    """Per-window value broadcast (PBFT PRE_PREPARE carrying the slot id):
    sender i announces ``value_mat[i, w]`` (>0; 0 = empty) for window w; the
    receiver max-combines per window.  Returns [B, N_loc, W].

    Windows of one sender share one delay draw per edge (same simplification
    as bcast_slots_dense)."""
    value_g = _gather(value_mat.astype(jnp.int32), axis)  # [N_glob, W]
    send = value_mat.max(axis=1) > 0
    hits = _edge_hits(
        key, send, lo, hi, drop_prob, axis, send_global=value_g.max(axis=1) > 0,
        impl=impl,
    )  # [B, N_glob, N_loc] 0/1
    return (hits[:, :, :, None] * value_g[None, :, None, :]).max(axis=1)


def bcast_window_value_max_stat(key, value_mat, probs: np.ndarray, drop_prob=0.0,
                                axis=None):
    """Stat version of bcast_window_value_max_dense for few senders per
    window (the PBFT leader): deliver each window's max announced value with
    one independent per-(receiver, window) delay draw.  A receiver whose own
    announcement equals the max is the sender — it gets nothing (the
    reference leader never hears its own PRE_PREPARE).  Returns [B, N_loc, W]."""
    k = _shard_key(key, axis)
    vm = value_mat.astype(jnp.int32)
    n, w = vm.shape
    vmax = vm.max(axis=0)  # [W]
    if axis is not None:
        vmax = lax.pmax(vmax, axis)
    nb = len(probs)
    d = jax.random.categorical(k, jnp.log(jnp.asarray(probs) + 1e-30), shape=(n, w))
    recv = (vmax[None, :] > 0) & (vm < vmax[None, :])
    if drop_prob > 0.0:
        keep = jax.random.bernoulli(
            jax.random.fold_in(k, 0x0D14), 1.0 - drop_prob, (n, w)
        )
        recv = recv & keep
    val = recv.astype(jnp.int32) * vmax[None, :]
    return (d[None] == _bucket_iota(0, nb, d.ndim)).astype(jnp.int32) * val[None]


def roundtrip_reply_counts_dense(
    key, send, lo, hi, drop_prob=0.0, peer_mask=None, axis=None,
    impl="threefry",
):
    """Short-circuited request/reply round trip: sender i broadcasts, every
    peer replies unconditionally and instantly, the reply travels back with an
    independent delay.  Used where the peer's state does not affect the reply
    (PBFT PREPARE → PREPARE_RES SUCCESS, pbft-node.cc:212-221; Raft HEARTBEAT →
    HEARTBEAT_RES SUCCESS, raft-node.cc:170-193).  ``peer_mask`` (local
    [n_loc]) restricts which peers reply (crashed/Byzantine exclusion).
    Returns reply counts at the original (local) sender: [B2, N_loc],
    offset 2*lo, B2 = 2*(hi-lo)-1.

    Sharded: the *sender* consumes both legs' delays, so delays are sampled
    sender-side over the gathered peer axis."""
    n_loc = send.shape[0]
    peers = jnp.ones((n_loc,), bool) if peer_mask is None else peer_mask
    peers_g = _gather(peers, axis)
    n_glob = peers_g.shape[0]
    k = _shard_key(key, axis)
    d1 = sample_edge_delays(jax.random.fold_in(k, 1), (n_loc, n_glob), lo, hi, impl)
    d2 = sample_edge_delays(jax.random.fold_in(k, 2), (n_loc, n_glob), lo, hi, impl)
    total = d1 + d2  # delay until the reply reaches the sender
    notself = (_global_ids(n_loc, axis)[:, None] != jnp.arange(n_glob)[None, :])
    mask = (
        send.astype(jnp.int32)[:, None]
        * notself.astype(jnp.int32)
        * peers_g.astype(jnp.int32)[None, :]
    )
    if drop_prob > 0.0:
        # either leg can drop
        keep = jax.random.bernoulli(
            jax.random.fold_in(k, 0x0D0E), (1.0 - drop_prob) ** 2, (n_loc, n_glob)
        )
        mask = mask * keep.astype(jnp.int32)
    lo2 = 2 * lo
    nb = 2 * (hi - lo) - 1
    # one broadcast compare + reduction instead of nb masked passes over the
    # [N_loc, N_glob] edge tensor (integer sums — bit-equal either way)
    return (
        (total[None] == _bucket_iota(lo2, lo2 + nb, total.ndim)).astype(jnp.int32)
        * mask[None]
    ).sum(2)


def unicast_reply_counts_dense(key, reply, lo, hi, drop_prob=0.0, axis=None,
                               impl="threefry"):
    """Route per-(replier, requester) reply counts back to each requester.
    ``reply[r, c]`` = number of (identical, count-consumed) replies local
    node r sends global node c this tick.  Returns [B, N_loc] indexed by
    *local* requester — sharded, the contribution must be summed across
    shards (the repliers), which is a ``psum`` over the axis."""
    n_loc, n_glob = reply.shape
    k = _shard_key(key, axis)
    d = sample_edge_delays(k, (n_loc, n_glob), lo, hi, impl)
    notself = (_global_ids(n_loc, axis)[:, None] != jnp.arange(n_glob)[None, :])
    mask = notself.astype(jnp.int32)
    if drop_prob > 0.0:
        keep = jax.random.bernoulli(
            jax.random.fold_in(k, 0x0D0F), 1.0 - drop_prob, (n_loc, n_glob)
        )
        mask = mask * keep.astype(jnp.int32)
    r = reply.astype(jnp.int32) * mask
    out_g = (
        r[None] * (d[None] == _bucket_iota(lo, hi, d.ndim)).astype(jnp.int32)
    ).sum(1)  # [B, N_glob]
    if axis is None:
        return out_g
    out_g = lax.psum(out_g, axis)
    # slice this shard's requesters
    start = lax.axis_index(axis) * n_loc
    return lax.dynamic_slice_in_dim(out_g, start, n_loc, axis=1)


def bcast_matrix_dense(key, send, value, lo, hi, drop_prob=0.0, axis=None,
                       impl="threefry"):
    """Identity-preserving broadcast for request channels whose handling
    depends on receiver state at arrival (Raft VOTE_REQ, Paxos REQUEST_*).
    ``value`` (>0 per sender; 0 = empty) lands at ``[b, receiver_local,
    sender_global]``.  Returns [B, N_loc, N_glob] (max-combined into a matrix
    ring)."""
    hits = _edge_hits(key, send, lo, hi, drop_prob, axis, impl=impl)  # [B, glob, loc]
    value_g = _gather(value, axis)
    return jnp.swapaxes(hits * value_g.astype(jnp.int32)[None, :, None], 1, 2)


# --------------------------------------------------------------------------- #
# stat (aggregated, statistically exact) delivery                             #
# --------------------------------------------------------------------------- #


def bcast_counts_stat(key, n_senders, is_sender, probs: np.ndarray, drop_prob=0.0, axis=None,
                      mode="exact"):
    """Full-mesh broadcast arrival counts without materializing edges.

    Each receiver j hears from ``n_senders - is_sender[j]`` peers; its arrival
    buckets are Multinomial over the delay distribution, independent across
    receivers (distinct edges ⇒ independent delays).  ``n_senders`` must be
    the *global* sender count (psum'ed by the caller when sharded).
    Returns [B, N_loc]."""
    k = _shard_key(key, axis)
    m = jnp.asarray(n_senders, jnp.int32) - is_sender.astype(jnp.int32)
    if drop_prob > 0.0:
        m = jnp.round(
            binom(jax.random.fold_in(k, 0x0D10), m, 1.0 - drop_prob, mode)
        ).astype(jnp.int32)
    return sample_bucket_counts(k, m, probs, mode)


def _slots_stat_m(key, slot_mat, drop_prob, axis, mode):
    """(shard key, per-(receiver, slot) sender counts) of the stat slot
    broadcast — the shared front half of :func:`bcast_slots_stat` and the
    fused :func:`push_bcast_slots_stat` (identical keys and arithmetic, so
    the two are bit-equal)."""
    k = _shard_key(key, axis)
    sm = slot_mat.astype(jnp.int32)
    totals = sm.sum(axis=0)
    if axis is not None:
        totals = lax.psum(totals, axis)
    m = totals[None, :] - sm  # [N_loc, S]
    if drop_prob > 0.0:
        m = jnp.round(
            binom(jax.random.fold_in(k, 0x0D12), m, 1.0 - drop_prob, mode)
        ).astype(jnp.int32)
    return k, m


def bcast_slots_stat(key, slot_mat, probs: np.ndarray, drop_prob=0.0, axis=None,
                     mode="exact"):
    """Stat version of bcast_slots_dense: receiver j hears, per slot s,
    from ``(Σ_i slot_mat[i,s]) - slot_mat[j,s]`` senders; arrival buckets are
    multinomial per (receiver, slot).  Returns [B, N_loc, S]."""
    k, m = _slots_stat_m(key, slot_mat, drop_prob, axis, mode)
    return sample_bucket_counts(k, m, probs, mode)


def bcast_value_max_stat(key, value, probs: np.ndarray, drop_prob=0.0, axis=None):
    """Stat version of bcast_value_max_dense for ≤-a-few senders (e.g. PBFT
    VIEW_CHANGE from the leader): deliver the max announced value to every
    receiver with one per-receiver delay draw.  Returns [B, N_loc]."""
    k = _shard_key(key, axis)
    n = value.shape[0]
    vmax = value.astype(jnp.int32).max()
    if axis is not None:
        vmax = lax.pmax(vmax, axis)
    nb = len(probs)
    d = jax.random.categorical(k, jnp.log(jnp.asarray(probs) + 1e-30), shape=(n,))
    sent = (vmax > 0).astype(jnp.int32)
    if drop_prob > 0.0:
        keep = jax.random.bernoulli(
            jax.random.fold_in(k, 0x0D13), 1.0 - drop_prob, (n,)
        )
        sent = sent * keep.astype(jnp.int32)
    # a node that announced the (same, max) value already applied it locally;
    # re-delivery to it is a harmless no-op, matching max-combine semantics
    return (
        (d[None] == _bucket_iota(0, nb, d.ndim)).astype(jnp.int32)
        * (sent * vmax)[None]
    )


def _roundtrip_stat_m(key, send, n_peers, drop_prob, axis, mode):
    """(shard key, per-sender reply counts) of the stat round trip — the
    shared front half of :func:`roundtrip_reply_counts_stat` and the fused
    :func:`push_roundtrip_reply_counts_stat`."""
    k = _shard_key(key, axis)
    m = send.astype(jnp.int32) * jnp.asarray(n_peers, jnp.int32)
    if drop_prob > 0.0:
        p_keep = (1.0 - drop_prob) ** 2
        m = jnp.round(
            binom(jax.random.fold_in(k, 0x0D11), m, p_keep, mode)
        ).astype(jnp.int32)
    return k, m


def roundtrip_reply_counts_stat(
    key, send, n_peers, rt_probs: np.ndarray, drop_prob=0.0, axis=None, mode="exact"
):
    """Stat version of roundtrip_reply_counts_dense: each active sender gets
    ``n_peers`` (global count, per local sender) replies multinomially spread
    over the round-trip distribution.  Returns [B2, N_loc]."""
    k, m = _roundtrip_stat_m(key, send, n_peers, drop_prob, axis, mode)
    return sample_bucket_counts(k, m, rt_probs, mode)


# --------------------------------------------------------------------------- #
# fused sample-and-push (stat chains combined straight into the rings)        #
# --------------------------------------------------------------------------- #


def push_bucket_counts(buf, t, push_lo: int, key, m, probs: np.ndarray,
                       mode: str = "exact", expand=None):
    """Sample ``Multinomial(m, probs)`` bucket counts and combine each bucket
    into its ring slice AS IT IS PRODUCED — the cost-analysis-driven fusion
    of the tick engine's delivery math (ISSUE 13 / KNOWN_ISSUES #5: the tick
    wall is sampler/delivery compute).  Equivalent unfused form::

        ring_push_add(buf, t, push_lo, expand*(sample_bucket_counts(...)))

    materializes the stacked ``[B, ...]`` tensor between two unfusable op
    islands (the chain's stack and the push's unstack); here bucket ``b``'s
    ~5 elementwise chain ops fuse directly into its dynamic-update-slice,
    so XLA never round-trips the intermediate through memory.  Bit-equal to
    the unfused form: same keys (delay.bucket_count_chain yields exactly
    what sample_bucket_counts stacks), same integer adds, same bucket
    order.  ``expand`` (optional) maps a bucket's int32 counts to its ring
    contribution (e.g. broadcasting per-window activity masks).

    When the pallas ring kernel is armed (``BLOCKSIM_RING_KERNEL``,
    ops/ring_kernel.py) the unfused compose runs instead, so the kernel
    keeps seeing whole stacked contributions."""
    from blockchain_simulator_tpu.ops import ring_kernel
    from blockchain_simulator_tpu.ops.ring import ring_push_add

    if ring_kernel.enabled():
        cnt = sample_bucket_counts(key, m, probs, mode)
        contrib = (
            cnt if expand is None
            else jnp.stack([expand(cnt[b]) for b in range(cnt.shape[0])])
        )
        return ring_push_add(buf, t, push_lo, contrib)
    d = buf.shape[0]
    for b, c in enumerate(bucket_count_chain(key, m, probs, mode)):
        cb = c.astype(jnp.int32)
        contrib = cb if expand is None else expand(cb)
        idx = jnp.mod(t + push_lo + b, d)
        cur = lax.dynamic_index_in_dim(buf, idx, 0, keepdims=False)
        buf = lax.dynamic_update_index_in_dim(buf, cur + contrib, idx, 0)
    return buf


def push_bcast_slots_stat(buf, t, push_lo: int, key, slot_mat,
                          probs: np.ndarray, drop_prob=0.0, axis=None,
                          mode="exact"):
    """Fused ``ring_push_add(buf, t, push_lo, bcast_slots_stat(...))`` —
    bit-equal to the compose (shared key/count helper), without the stacked
    [B, N_loc, S] intermediate."""
    k, m = _slots_stat_m(key, slot_mat, drop_prob, axis, mode)
    return push_bucket_counts(buf, t, push_lo, k, m, probs, mode)


def push_roundtrip_reply_counts_stat(buf, t, push_lo: int, key, send, n_peers,
                                     rt_probs: np.ndarray, drop_prob=0.0,
                                     axis=None, mode="exact", expand=None):
    """Fused ``ring_push_add(buf, t, push_lo, expand*(roundtrip_reply_counts_
    stat(...)))`` — bit-equal to the compose, without the stacked [B2, N_loc]
    (or expanded [B2, N_loc, W]) intermediate."""
    k, m = _roundtrip_stat_m(key, send, n_peers, drop_prob, axis, mode)
    return push_bucket_counts(buf, t, push_lo, k, m, rt_probs, mode, expand)


# --------------------------------------------------------------------------- #
# gossip flood forwarding (gossip topology)                                 #
# --------------------------------------------------------------------------- #


def gossip_fwd(key, fwd_vals, nbrs_loc, n_glob, lo, hi, drop_prob=0.0, axis=None,
               fold=0x0D22, impl="threefry"):
    """TTL-flood forwarding: ``fwd_vals [N_loc, P]`` (>0 TTL-encoded values
    held by local rows; P = any per-value lane — Paxos proposers, PBFT
    windows) → ``[B, N_loc, P]`` scatter-max contributions at each sender's
    out-neighbors (``nbrs_loc [N_loc, deg]`` global ids), one fresh delay draw
    per (sender, edge, lane).  Sharded: scatter into the global row space,
    pmax across shards (each shard contributes its senders' forwards), slice
    the local rows back out."""
    n_loc, p = fwd_vals.shape
    deg = nbrs_loc.shape[1]
    k = _shard_key(key, axis)
    d = sample_edge_delays(k, (n_loc, deg, p), lo, hi, impl)
    vals = jnp.broadcast_to(fwd_vals[:, None, :], (n_loc, deg, p))
    if drop_prob > 0.0:
        keep = jax.random.bernoulli(
            jax.random.fold_in(k, fold), 1.0 - drop_prob, (n_loc, deg, p)
        )
        vals = vals * keep
    # one scatter-max over a flattened (bucket, receiver) index — XLA handles
    # a single big scatter far better than hi-lo separate ones
    flat_idx = (d - lo) * n_glob + nbrs_loc[:, :, None]  # [n_loc, deg, p]
    flat = jnp.zeros(((hi - lo) * n_glob, p), jnp.int32)
    flat = flat.at[flat_idx, jnp.arange(p)[None, None, :]].max(vals)
    out = flat.reshape(hi - lo, n_glob, p)
    if axis is not None:
        out = lax.pmax(out, axis)
        start = lax.axis_index(axis) * n_loc
        out = lax.dynamic_slice_in_dim(out, start, n_loc, axis=1)
    return out
