from blockchain_simulator_tpu.ops import delay, delivery, ring  # noqa: F401
