"""Gather-based sparse delivery: neighbor-index tables instead of N x N.

The ``topology="kregular"`` twin of ops/delivery.py.  Every primitive here
consumes the circulant overlay tables of topo/spec.py — local ``[n_loc,
K]`` slices whose values are GLOBAL node ids, K = degree + 1 (the self
slot rides along and is masked) — and costs O(N*K) per tick where the
dense primitive costs O(N^2): delays are drawn slot-major ``[K, N]``, and
sender-side values reach receivers through ``jnp.take`` gathers (the MoE
routing / sparse-attention dispatch shape).  No primitive here scatters —
even the reply channels, which route *requester-side* through the
``inslot`` cross-index table, so the whole kregular tick body lowers
scatter-free (KNOWN_ISSUES #0i; pinned in tests/test_zztopo.py).

Bit-equality contract (the repo's correctness pin, tests/test_zztopo.py):
at degree k = N-1 the sorted overlay tables are the identity permutation
(topo/spec.py), every delay/drop tensor here has the SAME shape and is
drawn from the SAME key as its dense twin, and every mask/reduction runs
over the same index set — so the sparse program's integer channel values
(hence its metrics) equal the dense program's bit for bit under
``stat_sampler="exact"`` + ``edge_sampler="threefry"``.

SPMD: same convention as ops/delivery.py — receiver rows stay local,
sender-side quantities globalize with ``all_gather`` (``axis`` is the mesh
axis name; None = unsharded).  The tables reach the primitives in one of
two ways: as static trace constants sliced to local rows by the caller
(models pass ``nbr[ids]``, exactly like the gossip arm's ``nbrs_loc`` —
fine at audit scale), or as real program OPERANDS
(:func:`table_operands` + the ``tables=`` argument of
:func:`local_tables`) so multi-MB overlays never bake into the jaxpr and
the mesh-sharded programs can shard them over the node dimension
(KNOWN_ISSUES #0n's escape hatch, implemented by parallel/sweep.py's
``sharded_topo_sim_fn``).

Shard-local exchange mode: every kregular primitive also takes ``xg=``, a
``parallel.partition.NeighborExchange``.  With it, the cross-row neighbor
reads that :func:`_nbr_rows` would realize as all_gather + ``jnp.take``
become owner-bucketed ``all_to_all`` exchanges — same values (a pure
permutation + local gather, bit-equal by construction), but no tensor at
global shape ever exists on a device.  ``xg`` rides the GSPMD-partitioned
(global-view) trace: ``axis`` stays None there, the RNG draw shapes are
untouched, and the exchange islands are shard_map regions inside the same
jit program (parallel/sweep.py builds them per executable from the plans
in topo/spec.owner_bucket_plan).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from blockchain_simulator_tpu.ops import delivery as dv
from blockchain_simulator_tpu.ops.delay import (
    binom,
    sample_bucket_counts,
    sample_edge_delays,
)


# ------------------------------------------------------------- tables -------


def table_operands(cfg, inslot: bool = False):
    """The full overlay tables of ``cfg`` as host numpy arrays — ``(in,
    out)`` or ``(in, out, inslot)``, each int32 ``[N, K]`` — for feeding a
    program as real OPERANDS instead of letting :func:`local_tables` bake
    them into the jaxpr (multi-MB constants at large n, the
    large-jaxpr-constant graph rule).  Deterministic in ``(n, degree,
    topo_seed)`` so one device_put per registry entry suffices."""
    from blockchain_simulator_tpu.topo import spec as topo_spec

    args = (cfg.n, cfg.degree, cfg.topo_seed)
    tabs = [topo_spec.in_table(*args), topo_spec.out_table(*args)]
    if inslot:
        tabs.append(topo_spec.inslot_table(*args))
    return tuple(tabs)


def local_tables(cfg, ids, inslot: bool = False, tables=None, base: int = 0):
    """The overlay tables of ``cfg``, sliced to this shard's rows: ``(in,
    out)`` or ``(in, out, inslot)`` — the one localization call site the
    three models share.

    Layout contract: row indexing varies, row VALUES never do — every
    returned table row holds GLOBAL node ids (sorted ascending;
    ``inslot`` values are slot indices, not ids).  Three row-indexing
    modes:

    - ``ids`` global (the default): the shard's global row ids — unsharded
      that is the whole table, and the take is a row slice.
    - ``ids`` shard-offset + ``base``: ``ids`` counts 0..n_loc-1 within
      this shard and ``base`` is the shard's first global row, so the
      selected rows are ``ids + base`` (lets shard_map bodies pass their
      local iota without materializing global ids).
    - ``ids=None``: pass-through — the ``tables`` operands are ALREADY
      this trace's rows (the shard-local exchange mode of
      parallel/sweep.py, where re-gathering rows of a ``P(nodes)``-sharded
      operand would make GSPMD all-gather the whole table: the retired
      ``table-regather`` debt).  No take is emitted at all.

    With ``tables=None`` the tables are trace constants (the audit-scale
    default); passing the :func:`table_operands` arrays (possibly tracers)
    keeps them program operands — same values, same gather, no baked
    constant."""
    if tables is None:
        tables = table_operands(cfg, inslot=inslot)
    elif len(tables) != (3 if inslot else 2):
        raise ValueError(
            f"local_tables: expected {3 if inslot else 2} tables for "
            f"inslot={inslot}, got {len(tables)}"
        )
    if ids is None:
        return tuple(jnp.asarray(t) for t in tables)
    rows = ids if base == 0 else ids + base
    return tuple(jnp.take(jnp.asarray(t), rows, axis=0) for t in tables)


def _nbr_rows(x, idx_loc, axis=None, xg=None, kind="in", col=None):
    """Every cross-row neighbor read goes through this one door: the
    values of ``x`` at the global row ids in ``idx_loc`` (``[N_loc, K]``),
    i.e. ``take(x_global, idx_loc, axis=0)`` — or, with ``col`` (``[N_loc,
    K]`` column picks into 2-D ``x``), the elementwise
    ``take(x_global.reshape(-1), idx_loc * x.shape[1] + col)``.

    Fallback (``xg=None``): globalize ``x`` with all_gather (identity when
    ``axis`` is None — the single-device and GSPMD global-view traces) and
    gather.  Exchange mode: a :class:`~blockchain_simulator_tpu.parallel.
    partition.NeighborExchange` ships only the owner-bucketed rows via
    ``all_to_all`` — bit-equal values, O(N*K/D) communication, no global
    tensor.  ``kind`` names which table's plan the ids follow ("in" =
    ``nbr_in`` rows, "out" = ``nbr_out`` rows)."""
    if xg is not None:
        return xg(x, kind=kind, col=col)
    x_g = dv._gather(x, axis)
    if col is None:
        return jnp.take(x_g, idx_loc, axis=0)
    return jnp.take(x_g.reshape(-1), idx_loc * x.shape[1] + col)


# ------------------------------------------------------------ gather sums ---


def in_counts(x, nbr_in_loc, ids, axis=None, xg=None):
    """Per-receiver sum of a local int/bool ``[N_loc]`` vector over TRUE
    in-neighbors (self slot excluded): the kregular replacement for the
    dense stat chains' ``total - own`` sender counts.  Returns [N_loc]."""
    vals = _nbr_rows(x.astype(jnp.int32), nbr_in_loc, axis, xg)  # [N_loc, K]
    notself = (nbr_in_loc != ids[:, None]).astype(jnp.int32)
    return (vals * notself).sum(1)


def out_counts(x, nbr_out_loc, ids, axis=None, xg=None):
    """Per-sender count of its out-neighbors inside a local mask ``x``
    (self excluded) — the gathered ``n_peers`` of the round-trip stat
    chains.  Returns [N_loc]."""
    vals = _nbr_rows(x.astype(jnp.int32), nbr_out_loc, axis, xg, "out")
    notself = (nbr_out_loc != ids[:, None]).astype(jnp.int32)
    return (vals * notself).sum(1)


# ------------------------------------------------- edge-exact (slot-major) ---


def _slot_hits(key, src_act, nbr_in_loc, ids, lo, hi, drop, axis, impl):
    """[B, K, N_loc] 0/1 delivery indicators — the slot-major twin of
    dv._edge_hits' [B, N_glob, N_loc]: delay/drop tensors are [K, N_loc]
    on the SAME key, so at K = N (identity tables) the arrays are equal.
    ``src_act`` is the [N_loc, K] int32 send activity of each slot's
    SOURCE node (a :func:`_nbr_rows` read of the sender flags — self slot
    not yet masked; the mask lands here)."""
    n_loc, k1 = nbr_in_loc.shape
    k = dv._shard_key(key, axis)
    d = sample_edge_delays(k, (k1, n_loc), lo, hi, impl)
    notself = nbr_in_loc.T != ids[None, :]                # [K, N_loc]
    mask = src_act.T * notself.astype(jnp.int32)
    if drop > 0.0:
        keep = jax.random.bernoulli(
            jax.random.fold_in(k, 0x0D0D), 1.0 - drop, (k1, n_loc)
        )
        mask = mask * keep.astype(jnp.int32)
    return (d[None] == dv._bucket_iota(lo, hi, d.ndim)).astype(jnp.int32) * mask[None]


def bcast_counts_kreg(key, send, nbr_in_loc, ids, lo, hi, drop=0.0, axis=None,
                      impl="threefry", xg=None):
    """Overlay broadcast -> per-receiver arrival counts.  [B, N_loc]."""
    src_act = _nbr_rows(send.astype(jnp.int32), nbr_in_loc, axis, xg)
    return _slot_hits(key, src_act, nbr_in_loc, ids, lo, hi, drop, axis,
                      impl).sum(1)


def bcast_value_max_kreg(key, send, value, nbr_in_loc, ids, lo, hi, drop=0.0,
                         axis=None, impl="threefry", xg=None):
    """Overlay value broadcast (>0; 0 = empty), max-combined.  [B, N_loc]."""
    src_act = _nbr_rows(send.astype(jnp.int32), nbr_in_loc, axis, xg)
    hits = _slot_hits(key, src_act, nbr_in_loc, ids, lo, hi, drop, axis, impl)
    val_t = _nbr_rows(value.astype(jnp.int32), nbr_in_loc, axis, xg).T
    return (hits * val_t[None]).max(1)


def bcast_slots_kreg(key, slot_mat, nbr_in_loc, ids, lo, hi, drop=0.0,
                     axis=None, impl="threefry", xg=None):
    """Overlay slot-keyed broadcast (pbft COMMIT waves): arrival counts per
    (receiver, slot) gathered over in-neighbors.  [B, N_loc, S].

    The sender flag is derived AFTER the neighbor-row read (``max`` over
    the slot dim commutes with a row gather), so exchange mode ships the
    [.., S] slot rows once and pays no second collective for the flags."""
    slot_rows = _nbr_rows(slot_mat.astype(jnp.int32), nbr_in_loc, axis, xg)
    src_act = (slot_rows.max(2) > 0).astype(jnp.int32)          # [N_loc, K]
    hits = _slot_hits(key, src_act, nbr_in_loc, ids, lo, hi, drop, axis, impl)
    return jnp.einsum("bkj,jks->bjs", hits, slot_rows)


def bcast_window_value_max_kreg(key, value_mat, nbr_in_loc, ids, lo, hi,
                                drop=0.0, axis=None, impl="threefry", xg=None):
    """Overlay per-window value broadcast (pbft PRE_PREPARE), receiver
    max-combines per window.  [B, N_loc, W]."""
    val_rows = _nbr_rows(value_mat.astype(jnp.int32), nbr_in_loc, axis, xg)
    src_act = (val_rows.max(2) > 0).astype(jnp.int32)           # [N_loc, K]
    hits = _slot_hits(key, src_act, nbr_in_loc, ids, lo, hi, drop, axis, impl)
    return (hits[:, :, :, None] * jnp.swapaxes(val_rows, 0, 1)[None]).max(1)


def bcast_matrix_kreg(key, send, value, nbr_in_loc, ids, lo, hi, drop=0.0,
                      axis=None, impl="threefry", xg=None):
    """Identity-preserving overlay broadcast (raft VOTE_REQ): ``value``
    lands at ``[b, receiver_local, in_slot]`` — the K-slot twin of the
    dense [B, N_loc, N_glob] matrix channel.  Slot s of receiver j is
    sender ``nbr_in_loc[j, s]`` (rows sorted, so argmax-over-slots keeps
    the dense path's lowest-candidate-id tie-break).  [B, N_loc, K]."""
    src_act = _nbr_rows(send.astype(jnp.int32), nbr_in_loc, axis, xg)
    hits = _slot_hits(key, src_act, nbr_in_loc, ids, lo, hi, drop, axis, impl)
    val_t = _nbr_rows(value.astype(jnp.int32), nbr_in_loc, axis, xg).T
    return jnp.swapaxes(hits * val_t[None], 1, 2)


def roundtrip_reply_counts_kreg(key, send, nbr_out_loc, ids, lo, hi, drop=0.0,
                                peer_mask=None, axis=None, impl="threefry",
                                xg=None):
    """Short-circuited overlay round trip: sender i reaches its
    out-neighbors, every eligible peer replies instantly with an
    independent return delay.  [B2, N_loc], offset 2*lo."""
    n_loc, k1 = nbr_out_loc.shape
    peers = jnp.ones((n_loc,), bool) if peer_mask is None else peer_mask
    k = dv._shard_key(key, axis)
    d1 = sample_edge_delays(jax.random.fold_in(k, 1), (n_loc, k1), lo, hi, impl)
    d2 = sample_edge_delays(jax.random.fold_in(k, 2), (n_loc, k1), lo, hi, impl)
    total = d1 + d2
    notself = nbr_out_loc != ids[:, None]
    mask = (
        send.astype(jnp.int32)[:, None]
        * notself.astype(jnp.int32)
        * _nbr_rows(peers.astype(jnp.int32), nbr_out_loc, axis, xg, "out")
    )
    if drop > 0.0:
        keep = jax.random.bernoulli(
            jax.random.fold_in(k, 0x0D0E), (1.0 - drop) ** 2, (n_loc, k1)
        )
        mask = mask * keep.astype(jnp.int32)
    lo2 = 2 * lo
    nb = 2 * (hi - lo) - 1
    return (
        (total[None] == dv._bucket_iota(lo2, lo2 + nb, total.ndim)).astype(jnp.int32)
        * mask[None]
    ).sum(2)


def unicast_reply_counts_kreg(key, reply_slots, nbr_in_loc, nbr_out_loc,
                              inslot_loc, ids, lo, hi, drop=0.0, axis=None,
                              impl="threefry", xg=None):
    """Route per-(replier, in-slot) reply counts back to each requester —
    WITHOUT a scatter: requester c gathers slot s of replier ``nbr_out_loc
    [c, s]`` through the precomputed ``inslot`` cross-index (topo/spec.py:
    the slot c occupies in that replier's in-table).  Delay/drop tensors
    are replier-major [N_loc, K] on the dense function's key/folds, so at
    K = N they equal the dense [N_loc, N_glob] draws.  [B, N_loc]."""
    n_loc, k1 = reply_slots.shape
    k = dv._shard_key(key, axis)
    d = sample_edge_delays(k, (n_loc, k1), lo, hi, impl)
    mask = (nbr_in_loc != ids[:, None]).astype(jnp.int32)
    if drop > 0.0:
        keep = jax.random.bernoulli(
            jax.random.fold_in(k, 0x0D0F), 1.0 - drop, (n_loc, k1)
        )
        mask = mask * keep.astype(jnp.int32)
    r = reply_slots.astype(jnp.int32) * mask
    # requester-side flat col-select: slot inslot_loc[c, s] of replier row
    # nbr_out_loc[c, s] — replier-major [N, K] globalized (or exchanged)
    rv = _nbr_rows(r, nbr_out_loc, axis, xg, "out", col=inslot_loc)
    dd = _nbr_rows(d, nbr_out_loc, axis, xg, "out", col=inslot_loc)
    return (
        (dd[None] == dv._bucket_iota(lo, hi, dd.ndim)).astype(jnp.int32)
        * rv[None]
    ).sum(2)


def reply_counts_by_target_kreg(wire, target, nbr_out_loc, ids, axis=None,
                                xg=None):
    """Per-target reply totals WITHOUT the dense path's global scatter-add:
    target c gathers ``wire`` over its out-neighbors and keeps repliers
    whose decoded ``target`` id is c (a replier's target is always one of
    its in-neighbors, so the out-gather covers every reply).  The raft
    stat vote/ack router.  Returns [N_loc] int32."""
    w = _nbr_rows(wire.astype(jnp.int32), nbr_out_loc, axis, xg, "out")
    tg = _nbr_rows(target, nbr_out_loc, axis, xg, "out")
    return (w * (tg == ids[:, None])).sum(1)


# ------------------------------------------------ stat (gathered counts) ----


def bcast_counts_stat_kreg(key, send, nbr_in_loc, ids, probs: np.ndarray,
                           drop=0.0, axis=None, mode="exact", xg=None):
    """Stat twin of dv.bcast_counts_stat over the overlay: receiver j hears
    from its ACTIVE in-neighbors (gathered count), buckets multinomial.
    At k = N-1 the gathered count equals ``n_senders - is_sender`` and the
    chain is bit-equal to the dense stat path.  [B, N_loc]."""
    k = dv._shard_key(key, axis)
    m = in_counts(send, nbr_in_loc, ids, axis, xg)
    if drop > 0.0:
        m = jnp.round(
            binom(jax.random.fold_in(k, 0x0D10), m, 1.0 - drop, mode)
        ).astype(jnp.int32)
    return sample_bucket_counts(k, m, probs, mode)


def push_bcast_slots_stat_kreg(buf, t, push_lo: int, key, slot_mat,
                               nbr_in_loc, ids, probs: np.ndarray, drop=0.0,
                               axis=None, mode="exact", xg=None):
    """Fused stat slot broadcast over the overlay (the kregular twin of
    dv.push_bcast_slots_stat): per-(receiver, slot) sender counts come
    from an in-neighbor gather-sum, then ride the same fused
    chain-into-ring push on the same key."""
    k = dv._shard_key(key, axis)
    vals = _nbr_rows(slot_mat.astype(jnp.int32), nbr_in_loc, axis, xg)
    notself = (nbr_in_loc != ids[:, None]).astype(jnp.int32)
    m = (vals * notself[:, :, None]).sum(1)              # [N_loc, S]
    if drop > 0.0:
        m = jnp.round(
            binom(jax.random.fold_in(k, 0x0D12), m, 1.0 - drop, mode)
        ).astype(jnp.int32)
    return dv.push_bucket_counts(buf, t, push_lo, k, m, probs, mode)


def bcast_value_max_stat_kreg(key, value, nbr_in_loc, probs: np.ndarray,
                              drop=0.0, axis=None, xg=None):
    """Stat twin of dv.bcast_value_max_stat over the overlay: each receiver
    gets the max value announced in its IN-neighborhood (self included —
    matching the dense global max, where re-delivery to the announcer is a
    harmless max-combine no-op) with one per-receiver delay draw.
    [B, N_loc]."""
    k = dv._shard_key(key, axis)
    n_loc = value.shape[0]
    vmax = _nbr_rows(value.astype(jnp.int32), nbr_in_loc, axis, xg).max(1)
    nb = len(probs)
    d = jax.random.categorical(k, jnp.log(jnp.asarray(probs) + 1e-30),
                               shape=(n_loc,))
    sent = (vmax > 0).astype(jnp.int32)
    if drop > 0.0:
        keep = jax.random.bernoulli(
            jax.random.fold_in(k, 0x0D13), 1.0 - drop, (n_loc,)
        )
        sent = sent * keep.astype(jnp.int32)
    return (
        (d[None] == dv._bucket_iota(0, nb, d.ndim)).astype(jnp.int32)
        * (sent * vmax)[None]
    )


def bcast_window_value_max_stat_kreg(key, value_mat, nbr_in_loc,
                                     probs: np.ndarray, drop=0.0, axis=None,
                                     xg=None):
    """Stat twin of dv.bcast_window_value_max_stat over the overlay:
    per-(receiver, window) in-neighborhood max, one delay draw each; a
    receiver whose own announcement IS the neighborhood max is the sender
    and gets nothing.  [B, N_loc, W]."""
    k = dv._shard_key(key, axis)
    vm = value_mat.astype(jnp.int32)
    n_loc, w = vm.shape
    vmax = _nbr_rows(vm, nbr_in_loc, axis, xg).max(1)    # [N_loc, W]
    nb = len(probs)
    d = jax.random.categorical(k, jnp.log(jnp.asarray(probs) + 1e-30),
                               shape=(n_loc, w))
    recv = (vmax > 0) & (vm < vmax)
    if drop > 0.0:
        keep = jax.random.bernoulli(
            jax.random.fold_in(k, 0x0D14), 1.0 - drop, (n_loc, w)
        )
        recv = recv & keep
    val = recv.astype(jnp.int32) * vmax
    return (d[None] == dv._bucket_iota(0, nb, d.ndim)).astype(jnp.int32) * val[None]
