"""Future-inbox ring buffers.

The reference's entire concurrency model is the ns-3 event queue: every send is
``Simulator::Schedule(delay, SendPacket, ...)`` (pbft-node.cc:345,365; SURVEY.md
§3.5).  The tensorized equivalent is a ring buffer over future ticks: a channel
buffer has shape ``[D, N, ...]``; a message scheduled at tick ``t`` with delay
``d`` lands in slice ``(t + d) % D``; at tick ``t`` the simulator *pops* slice
``t % D`` (read + zero).  ``D`` need only exceed the maximum scheduling horizon
(config.ring_depth), so memory is O(D·N·channel-width) — never O(events).

Channels come in two flavors (SURVEY.md §7 "variable-size inboxes"):
- **aggregate** channels combine concurrent deliveries with a commutative op
  (add for vote counts, max for value announcements) — exploiting that the
  protocols consume most messages as counts;
- **matrix** channels keep sender identity ``[D, N_recv, N_send]`` for the few
  request types whose replies must be routed back to the requester.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def ring_pop(buf, t):
    """Read and clear the current tick's slice. Returns (slice, buf')."""
    idx = jnp.mod(t, buf.shape[0])
    cur = jax.lax.dynamic_index_in_dim(buf, idx, 0, keepdims=False)
    return cur, jax.lax.dynamic_update_index_in_dim(
        buf, jnp.zeros_like(cur), idx, 0
    )


def _push(buf, t, lo: int, contrib, op: str):
    """Combine ``contrib[b, ...]`` into slices ``t+lo+b``, b in [0, B).

    Two lowerings:

    - **pallas** (TPU): one fused in-place kernel touching exactly the B
      addressed ring slices (ops/ring_kernel.py) — the bandwidth floor.
    - **DUS chain** (fallback): unrolled dynamic-slice / dynamic-update-slice
      pairs over the (small, static) bucket axis.  A ``buf.at[idx_vec].add``
      would lower to XLA generic scatter, which TPUs execute catastrophically
      slowly — the round-3 ablation (tools/ablate.py) measured the scatter
      form ~30x slower than this chain; the pallas kernel removes the chain's
      remaining per-pair copy cost (round-4 measurement in
      ARTIFACT_ring_kernel.json).

    Lowering selection is PROCESS-SCOPED: ``ring_kernel.enabled()`` reads
    ``BLOCKSIM_RING_KERNEL`` at trace time, and traced sim fns are cached by
    config (runner.make_sim_fn / parallel.shard lru_caches), so flipping the
    env var mid-process keeps previously built fns on their old lowering.
    Set the variable before building sim fns (or clear the caches via
    ``make_sim_fn.cache_clear()``) — tools/ring_kernel_bench.py runs each
    mode in a fresh child process for exactly this reason.
    """
    from blockchain_simulator_tpu.ops import ring_kernel

    if ring_kernel.enabled() and ring_kernel.pushable(buf, contrib):
        return ring_kernel.fused_push(buf, t, lo, contrib, op)
    combine = jnp.add if op == "add" else jnp.maximum
    d = buf.shape[0]
    for b in range(contrib.shape[0]):
        idx = jnp.mod(t + lo + b, d)
        cur = jax.lax.dynamic_index_in_dim(buf, idx, 0, keepdims=False)
        buf = jax.lax.dynamic_update_index_in_dim(buf, combine(cur, contrib[b]), idx, 0)
    return buf


def ring_push_add(buf, t, lo: int, contrib):
    """Add ``contrib[b, ...]`` into slices ``t+lo+b``, b in [0, B)."""
    return _push(buf, t, lo, contrib, "add")


def ring_push_max(buf, t, lo: int, contrib):
    """Max-combine (for value channels where 0 == empty)."""
    return _push(buf, t, lo, contrib, "max")
