"""Future-inbox ring buffers.

The reference's entire concurrency model is the ns-3 event queue: every send is
``Simulator::Schedule(delay, SendPacket, ...)`` (pbft-node.cc:345,365; SURVEY.md
§3.5).  The tensorized equivalent is a ring buffer over future ticks: a channel
buffer has shape ``[D, N, ...]``; a message scheduled at tick ``t`` with delay
``d`` lands in slice ``(t + d) % D``; at tick ``t`` the simulator *pops* slice
``t % D`` (read + zero).  ``D`` need only exceed the maximum scheduling horizon
(config.ring_depth), so memory is O(D·N·channel-width) — never O(events).

Channels come in two flavors (SURVEY.md §7 "variable-size inboxes"):
- **aggregate** channels combine concurrent deliveries with a commutative op
  (add for vote counts, max for value announcements) — exploiting that the
  protocols consume most messages as counts;
- **matrix** channels keep sender identity ``[D, N_recv, N_send]`` for the few
  request types whose replies must be routed back to the requester.
"""

from __future__ import annotations

import jax.numpy as jnp


def ring_pop(buf, t):
    """Read and clear the current tick's slice. Returns (slice, buf')."""
    idx = jnp.mod(t, buf.shape[0])
    cur = buf[idx]
    return cur, buf.at[idx].set(0)


def _idx(buf, t, lo, nb):
    return jnp.mod(t + lo + jnp.arange(nb), buf.shape[0])


def ring_push_add(buf, t, lo: int, contrib):
    """Scatter-add ``contrib[b, ...]`` into slices ``t+lo+b``, b in [0, B)."""
    return buf.at[_idx(buf, t, lo, contrib.shape[0])].add(contrib)


def ring_push_max(buf, t, lo: int, contrib):
    """Scatter-max (for value channels where 0 == empty)."""
    return buf.at[_idx(buf, t, lo, contrib.shape[0])].max(contrib)
