"""Future-inbox ring buffers.

The reference's entire concurrency model is the ns-3 event queue: every send is
``Simulator::Schedule(delay, SendPacket, ...)`` (pbft-node.cc:345,365; SURVEY.md
§3.5).  The tensorized equivalent is a ring buffer over future ticks: a channel
buffer has shape ``[D, N, ...]``; a message scheduled at tick ``t`` with delay
``d`` lands in slice ``(t + d) % D``; at tick ``t`` the simulator *pops* slice
``t % D`` (read + zero).  ``D`` need only exceed the maximum scheduling horizon
(config.ring_depth), so memory is O(D·N·channel-width) — never O(events).

Channels come in two flavors (SURVEY.md §7 "variable-size inboxes"):
- **aggregate** channels combine concurrent deliveries with a commutative op
  (add for vote counts, max for value announcements) — exploiting that the
  protocols consume most messages as counts;
- **matrix** channels keep sender identity ``[D, N_recv, N_send]`` for the few
  request types whose replies must be routed back to the requester.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def ring_pop(buf, t):
    """Read and clear the current tick's slice. Returns (slice, buf')."""
    idx = jnp.mod(t, buf.shape[0])
    cur = jax.lax.dynamic_index_in_dim(buf, idx, 0, keepdims=False)
    return cur, jax.lax.dynamic_update_index_in_dim(
        buf, jnp.zeros_like(cur), idx, 0
    )


def _push(buf, t, lo: int, contrib, combine):
    """Combine ``contrib[b, ...]`` into slices ``t+lo+b``, b in [0, B).

    Unrolled over the (small, static) bucket axis as dynamic-slice /
    dynamic-update-slice pairs: a ``buf.at[idx_vector].add`` lowers to XLA
    generic scatter, which TPUs execute catastrophically slowly — the round-3
    ablation (tools/ablate.py) measured the scatter form at ~2.0 ms/tick of a
    2.24 ms/tick total at N=100k; the DUS form is ~30x faster.  In-place
    update is preserved (each step is a DUS on the scan-carried buffer).
    """
    d = buf.shape[0]
    for b in range(contrib.shape[0]):
        idx = jnp.mod(t + lo + b, d)
        cur = jax.lax.dynamic_index_in_dim(buf, idx, 0, keepdims=False)
        buf = jax.lax.dynamic_update_index_in_dim(buf, combine(cur, contrib[b]), idx, 0)
    return buf


def ring_push_add(buf, t, lo: int, contrib):
    """Add ``contrib[b, ...]`` into slices ``t+lo+b``, b in [0, B)."""
    return _push(buf, t, lo, contrib, lambda cur, c: cur + c)


def ring_push_max(buf, t, lo: int, contrib):
    """Max-combine (for value channels where 0 == empty)."""
    return _push(buf, t, lo, contrib, jnp.maximum)
