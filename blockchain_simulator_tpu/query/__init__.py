"""Adaptive consensus-design queries (ROADMAP item 5): typed threshold
searches (query/spec.py) answered by a deterministic bisection/
refinement engine (query/engine.py) over the compile-once sweep stack —
journaled for kill -9 resume, served as durable long-running requests
(serve/schema.py ``"query"``)."""

from blockchain_simulator_tpu.query.engine import run_query
from blockchain_simulator_tpu.query.spec import QuerySpec, parse_query

__all__ = ["QuerySpec", "parse_query", "run_query"]
