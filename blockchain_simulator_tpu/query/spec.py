"""Typed consensus-design queries: what to search, over what, until when.

The sweep stack answers fixed grids; the questions practitioners ask are
thresholds — "what is the largest f this config survives?", "where is
the crash/loss cliff?", "what is the cheapest overlay degree k that
still reaches finality?" (ROADMAP item 5).  A :class:`QuerySpec` names
one such question as data: a query *kind*, the integer parameter domain
it searches, and an explicit per-point *predicate* (commit target +
optional tick budget, aggregated across seeds) the engine
(query/engine.py) bisects against.

Query kinds
-----------
``max_f_surviving``
    Largest ``n_crashed`` (or ``n_byzantine``, via ``param``) at which
    the predicate still holds.  Fault counts are traced operands
    (models/base.canonical_fault_cfg), so every probe hits one cached
    executable — the search costs dispatches, never recompiles.
``cliff_locate``
    The bracketing form of the same search: answers BOTH sides of the
    boundary (``last_true`` / ``first_false``) and accepts a
    ``probe_width`` > 1 to narrow the bracket faster (more points per
    generation, still ONE dispatch per generation).
``min_k_finality``
    Smallest kregular overlay degree ``k`` at which the predicate
    holds (increasing predicate).  Degree is program STRUCTURE, so each
    distinct probed k compiles once — inherent, and the reason this
    kind dispatches one chunk per probed value instead of one per
    generation (KNOWN_ISSUES.md).

Predicate semantics
-------------------
A point passes when, per seed, the protocol's commit metric reaches
``commit_target`` AND (``tick_budget`` > 0) the protocol's
commit-latency metric is within ``tick_budget`` ms AND the host
agreement check passed; seed verdicts aggregate under ``agg``:
``all_commit`` (every seed) or ``majority_commit`` (strict majority).
The engine assumes the predicate is monotone along the searched
parameter — see KNOWN_ISSUES.md for what happens near a noisy cliff.
"""

from __future__ import annotations

import dataclasses

KINDS = ("max_f_surviving", "cliff_locate", "min_k_finality")
FAULT_PARAMS = ("n_crashed", "n_byzantine")
AGGS = ("all_commit", "majority_commit")

# Per-protocol metric doors the predicate reads (models/{pbft,raft,
# paxos}.py metrics()): the commit-count metric and its latency twin.
COMMIT_KEYS = {
    "pbft": "blocks_final_all_nodes",
    "raft": "blocks",
    "paxos": "n_committed_proposers",
}
TIME_KEYS = {
    "pbft": "last_commit_ms",
    "raft": "last_block_ms",
    "paxos": "winner_commit_ms",
}


@dataclasses.dataclass(frozen=True)
class QuerySpec:
    """One adaptive query, fully determined by its fields (the engine is
    deterministic, so spec + base config + seeds IS the answer)."""

    kind: str
    param: str = "n_crashed"   # searched axis (fault count, or "degree")
    lo: int = 0                # inclusive domain floor
    hi: int = -1               # inclusive ceiling; -1 = kind default
    seeds: tuple = (0,)
    commit_target: int = 1     # commit-count metric must reach this
    tick_budget: int = 0       # ms bound on the latency metric; 0 = none
    agg: str = "all_commit"
    probe_width: int = 1       # interior probes per refinement generation

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["seeds"] = list(self.seeds)
        return d


def parse_query(obj) -> QuerySpec:
    """Validate a wire-shaped ``{"kind": ..., ...}`` dict into a
    :class:`QuerySpec`; raises ``ValueError`` with a one-line reason
    (serve/schema.py wraps it in the typed 400)."""
    if not isinstance(obj, dict):
        raise ValueError(f"query must be an object, got {type(obj).__name__}")
    obj = dict(obj)
    kind = obj.pop("kind", None)
    if kind not in KINDS:
        raise ValueError(f"query kind {kind!r} not in {KINDS}")
    fields = {f.name: f for f in dataclasses.fields(QuerySpec)}
    kw = {"kind": kind}
    for k, v in obj.items():
        if k == "kind" or k not in fields:
            raise ValueError(f"unknown query field {k!r}")
        if k == "seeds":
            if not isinstance(v, (list, tuple)) or not v \
                    or not all(isinstance(s, int)
                               and not isinstance(s, bool) for s in v):
                raise ValueError("query seeds must be a non-empty int list")
            kw[k] = tuple(int(s) for s in v)
        elif k in ("param", "agg"):
            if not isinstance(v, str):
                raise ValueError(f"query field {k!r} must be a string")
            kw[k] = v
        else:
            if isinstance(v, bool) or not isinstance(v, int):
                raise ValueError(f"query field {k!r} must be an int")
            kw[k] = int(v)
    spec = QuerySpec(**kw)
    if spec.kind == "min_k_finality":
        if "param" in kw and spec.param != "degree":
            raise ValueError("min_k_finality searches param 'degree'")
        spec = dataclasses.replace(spec, param="degree")
    elif spec.param not in FAULT_PARAMS:
        raise ValueError(
            f"query param {spec.param!r} not in {FAULT_PARAMS} "
            f"(degree is min_k_finality only)")
    if spec.agg not in AGGS:
        raise ValueError(f"query agg {spec.agg!r} not in {AGGS}")
    if spec.commit_target < 1:
        raise ValueError("query commit_target must be >= 1")
    if spec.tick_budget < 0:
        raise ValueError("query tick_budget must be >= 0")
    if not 1 <= spec.probe_width <= 64:
        raise ValueError("query probe_width must be in [1, 64]")
    if spec.lo < 0:
        raise ValueError("query lo must be >= 0")
    if spec.hi != -1 and spec.hi < spec.lo:
        raise ValueError(f"query domain empty: lo={spec.lo} > hi={spec.hi}")
    return spec


def resolve_domain(spec: QuerySpec, cfg) -> tuple[int, int]:
    """The concrete inclusive ``[lo, hi]`` integer domain for this base
    config: ``hi=-1`` defaults to the parameter's natural ceiling
    (``n - 1`` for fault counts — node 0 stays alive by the fault-mask
    layout — and ``n - 1`` for degree, which IS the full mesh)."""
    lo = spec.lo
    hi = spec.hi if spec.hi != -1 else cfg.n - 1
    if spec.param == "degree":
        lo = max(lo, 1)
    if hi >= cfg.n:
        raise ValueError(
            f"query hi={hi} exceeds the {spec.param} ceiling n-1={cfg.n - 1}")
    if hi < lo:
        raise ValueError(f"query domain empty: [{lo}, {hi}]")
    return lo, hi


def point_cfg(cfg, spec: QuerySpec, value: int):
    """The concrete SimConfig at one searched parameter value."""
    if spec.param == "degree":
        return cfg.with_(topology="kregular", degree=int(value))
    # an explicit count overrides crash_frac (FaultConfig.resolved_n_crashed),
    # so only the searched field moves — the rest of the fault load stays
    return cfg.with_(faults=dataclasses.replace(
        cfg.faults, **{spec.param: int(value)}))


def row_ok(protocol: str, row: dict, spec: QuerySpec) -> bool:
    """The per-seed predicate on one metrics row."""
    commits = row.get(COMMIT_KEYS.get(protocol, ""), 0)
    if commits is None or int(commits) < spec.commit_target:
        return False
    if not row.get("agreement_ok", False):
        return False
    if spec.tick_budget > 0:
        t = row.get(TIME_KEYS.get(protocol, ""))
        if t is None or not 0 <= float(t) <= float(spec.tick_budget):
            return False
    return True


def verdict(protocol: str, rows, spec: QuerySpec) -> bool:
    """Aggregate one point's per-seed rows into the point verdict."""
    oks = [row_ok(protocol, r, spec) for r in rows]
    if spec.agg == "majority_commit":
        return sum(oks) * 2 > len(oks)
    return all(oks)
