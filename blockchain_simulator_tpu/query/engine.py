"""The deterministic adaptive-search driver behind every query kind.

One algorithm answers all three specs (query/spec.py): a monotone
*boundary search* over an inclusive integer domain ``[lo, hi]``.  The
domain splits into a low side (where the predicate looks like it does at
``lo``) and a high side (like at ``hi``); the search narrows the
bracket between them until it is exactly one step wide.  For the fault
kinds the predicate is monotone *decreasing* (more crashed/Byzantine
nodes never helps), for ``min_k_finality`` it is *increasing* (more
overlay edges never hurt) — the same loop runs both by mapping each
point verdict onto low/high.

Every refinement **generation** batches into ONE
``parallel/sweep.run_dyn_points`` dispatch: the probe values of a step
share the canonical fault structure (fault counts and seeds are traced
operands), and the probe list is padded by repeating its last value so
every generation dispatches the SAME lane count — the warmup generation
pays the one compile, every later generation is a pure cache hit
(``tests/test_zzquery.py`` pins this against the aotcache registry).
``min_k_finality`` is the documented exception: overlay degree is
program structure, so each distinct probed k compiles once and the
generation dispatches one chunk per value (KNOWN_ISSUES.md).

**Durability** (``journal=``, a parallel/journal.SweepJournal): each
generation journals as one content-keyed chunk under the ``+q<step>``
namespace (journal.query_key_suffix) — disjoint from grid and probe
chunks over the same canon — durable before the next generation
dispatches.  The search trajectory is deterministic, so a killed search
re-derives the same steps, serves every completed generation from the
journal (0 recomputed steps — the ``query-kill9`` drill pins this), and
a pure journal replay re-answers the query bit-equal.

Each generation emits a ``query.step`` telemetry span (child of the
ambient context — the serve path parents it under the request's root
span) and fires the ``query.step`` chaos point before dispatching.
"""

from __future__ import annotations

from blockchain_simulator_tpu.chaos import inject
from blockchain_simulator_tpu.models.base import canonical_fault_cfg
from blockchain_simulator_tpu.parallel import journal as journal_mod
from blockchain_simulator_tpu.parallel import sweep
from blockchain_simulator_tpu.query import spec as spec_mod
from blockchain_simulator_tpu.utils import telemetry
from blockchain_simulator_tpu.utils.config import SimConfig

# Kinds whose predicate is monotone increasing along the parameter.
_INCREASING = {"min_k_finality"}


def _probe_values(lt: int, ff: int, width: int) -> list[int]:
    """Up to ``width`` evenly spaced unique ints strictly inside
    ``(lt, ff)`` — never empty while ``ff - lt > 1``."""
    span = ff - lt
    k = min(width, span - 1)
    vals = {
        min(max(lt + round(span * j / (k + 1)), lt + 1), ff - 1)
        for j in range(1, k + 1)
    }
    return sorted(vals)


class _Search:
    """One query run's mutable state: memoized verdicts, the evaluation
    trail, and the dispatch accounting."""

    def __init__(self, cfg: SimConfig, spec: spec_mod.QuerySpec,
                 journal=None, mesh=None, multi_seed: bool = False):
        self.cfg = cfg
        self.spec = spec
        self.journal = journal
        self.mesh = mesh
        self.multi_seed = multi_seed
        self.seeds = list(spec.seeds)
        # constant lanes per generation: the warmup step evaluates BOTH
        # endpoints, so every step dispatches max(probe_width, 2) values
        self.width = max(spec.probe_width, 2)
        self.verdicts: dict[int, bool] = {}
        self.trail: list[dict] = []
        self.points: list[dict] = []
        self.step = 0
        self.dispatches = 0
        self.lanes = 0
        self.pad = 0
        self.cached_steps = 0
        self.mono_violations = 0

    # -------------------------------------------------------- evaluation ---
    def _dispatch(self, values: list[int]):
        """ONE generation's dispatch: fault kinds batch every (value,
        seed) lane into one chunk; the degree kind dispatches one chunk
        per value (per-k structure)."""
        sfx = journal_mod.query_key_suffix(self.step)
        rows_by_value: dict[int, list[dict]] = {}
        metas = []
        if self.spec.param == "degree":
            for v in values:
                cfg_v = spec_mod.point_cfg(self.cfg, self.spec, v)
                canon_v = canonical_fault_cfg(cfg_v)
                pts = [(cfg_v, s) for s in self.seeds]
                rows, meta = sweep.run_dyn_points(
                    canon_v, pts, record=False, journal=self.journal,
                    multi_seed=self.multi_seed, key_suffix=sfx,
                    with_index=True)
                rows_by_value[v] = rows
                metas.append(meta)
        else:
            padded = list(values) + [values[-1]] * (self.width - len(values))
            pts = [(spec_mod.point_cfg(self.cfg, self.spec, v), s)
                   for v in padded for s in self.seeds]
            canon = canonical_fault_cfg(pts[0][0])
            rows, meta = sweep.run_dyn_points(
                canon, pts, record=False,
                n_out=len(values) * len(self.seeds),
                mesh=self.mesh, journal=self.journal,
                multi_seed=self.multi_seed, key_suffix=sfx,
                with_index=True)
            for i, v in enumerate(values):
                s0 = i * len(self.seeds)
                rows_by_value[v] = rows[s0:s0 + len(self.seeds)]
            metas.append(meta)
        return rows_by_value, metas

    def evaluate(self, values: list[int], bracket) -> None:
        """Evaluate one generation of unique, never-before-seen values;
        memoize verdicts, extend the trail."""
        inject.chaos_point("query.step", step=self.step, n=len(values),
                           values=list(values))
        with telemetry.span("query.step", step=self.step, n=len(values)):
            rows_by_value, metas = self._dispatch(values)
        fired = sum(m["dispatches"] for m in metas)
        self.dispatches += fired
        self.lanes += sum(m["lanes"] for m in metas)
        self.pad += sum(m["pad"] for m in metas)
        if fired == 0:
            self.cached_steps += 1
        gen_verdicts = []
        for v in values:
            ok = spec_mod.verdict(self.cfg.protocol, rows_by_value[v],
                                  self.spec)
            self.verdicts[v] = ok
            gen_verdicts.append([int(v), bool(ok)])
            for s, row in zip(self.seeds, rows_by_value[v]):
                self.points.append(
                    {"value": int(v), "seed": int(s), "metrics": row})
        self.trail.append({
            "step": self.step,
            "values": [int(v) for v in values],
            "verdicts": gen_verdicts,
            "bracket": list(bracket) if bracket else None,
            "keys": [c["key"] for m in metas for c in m["chunks"]],
        })
        self.step += 1

    def is_high(self, v: int) -> bool:
        ok = self.verdicts[v]
        return ok if self.spec.kind in _INCREASING else not ok


def run_query(cfg: SimConfig, spec: spec_mod.QuerySpec, journal=None,
              mesh=None, multi_seed: bool = False) -> dict:
    """Answer one query: deterministic adaptive search over the cached
    executable.  Returns ``{"query", "answer", "trail", "points",
    "run"}`` — everything except ``"run"`` (this run's dispatch
    accounting: ``dispatches``, ``cached_steps``, ``steps``, ``lanes``,
    ``pad``, ``monotonicity_violations``) is bit-equal across a fresh
    run, a kill-resume, and a pure journal replay of the same query."""
    lo, hi = spec_mod.resolve_domain(spec, cfg)
    st = _Search(cfg, spec, journal=journal, mesh=mesh,
                 multi_seed=multi_seed)
    # warmup generation: both endpoints (the one compile for fault kinds)
    st.evaluate([lo] if lo == hi else [lo, hi], None)
    if st.is_high(lo):
        low_max, high_min = None, lo       # boundary below the domain
    elif not st.is_high(hi):
        low_max, high_min = hi, None       # boundary above the domain
    else:
        lt, ff = lo, hi
        while ff - lt > 1:
            probes = _probe_values(lt, ff, st.width)
            st.evaluate(probes, (lt, ff))
            highs = [v for v in probes if st.is_high(v)]
            new_ff = min(highs) if highs else ff
            lows_ok = [v for v in probes if not st.is_high(v) and v < new_ff]
            # a low-side verdict ABOVE the new high boundary breaks the
            # monotone assumption — counted, resolved conservatively
            # toward the lower boundary (KNOWN_ISSUES.md)
            st.mono_violations += sum(
                1 for v in probes if not st.is_high(v) and v >= new_ff)
            lt = max(lows_ok) if lows_ok else lt
            ff = new_ff
        low_max, high_min = lt, ff
    if spec.kind == "min_k_finality":
        answer = {"k_min": high_min, "last_failing": low_max}
    elif spec.kind == "max_f_surviving":
        answer = {"f_max": low_max, "first_failing": high_min}
    else:
        answer = {"last_true": low_max, "first_false": high_min}
    answer["param"] = spec.param
    answer["domain"] = [lo, hi]
    return {
        "query": spec.to_dict(),
        "answer": answer,
        "trail": st.trail,
        "points": st.points,
        "run": {
            "steps": st.step,
            "dispatches": st.dispatches,
            "cached_steps": st.cached_steps,
            "lanes": st.lanes,
            "pad": st.pad,
            "values_evaluated": len(st.verdicts),
            "monotonicity_violations": st.mono_violations,
        },
    }
