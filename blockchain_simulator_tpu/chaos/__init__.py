"""Deterministic fault injection for the serving stack itself.

The repo simulates Byzantine and crash faults *inside* the consensus
models; this package applies the same discipline to the framework around
them — the scenario server, the batched dispatch primitive, the
persistent compile cache, the health gate.  Production code carries
named chaos points (:func:`~blockchain_simulator_tpu.chaos.inject.
chaos_point`) that are free when disarmed; a seeded
:class:`~blockchain_simulator_tpu.chaos.inject.ChaosController` arms
them with counted, reproducible faults (raise, hang, slow, poison), and
:mod:`~blockchain_simulator_tpu.chaos.invariants` checks that the stack
kept its accounting promises while the faults flew:

- **no request unaccounted** — every admission ends in exactly one of
  {response, typed rejection, replayed};
- **no lost manifest lines** — every terminal outcome has its access-log
  line in runs.jsonl;
- **registry stats monotone** — cache counters never run backwards.

``tools/chaos_drill.py`` scripts the scenarios (dispatch-fail/hang,
cache-corrupt, health-flap, batcher-kill, queue-storm, poison-request,
crash-restart) and pins that each runs identically twice under one chaos
seed; README "Chaos drills" is the operator doc.

The FLEET scenarios (:mod:`~blockchain_simulator_tpu.chaos.
fleet_scenarios`, ``tools/fleet_bench.py``) extend the same discipline to
the replicated serving tier: replica death mid-traffic with WAL handoff,
slow-replica hedged failover, router retry storms, and double-claim
races — checked by :func:`~blockchain_simulator_tpu.chaos.invariants.
check_fleet` (exactly one terminal outcome per admission fleet-wide, each
handed-off id replayed exactly once, WAL leases exclusive).
"""

from blockchain_simulator_tpu.chaos.inject import (  # noqa: F401
    ChaosController,
    ChaosFault,
    ChaosKill,
    chaos_point,
    controller,
)
from blockchain_simulator_tpu.chaos.invariants import (  # noqa: F401
    Ledger,
    check_fleet,
    check_server,
    registry_monotone,
)
