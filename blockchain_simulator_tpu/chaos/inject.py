"""Chaos points: named fault-injection hooks the real stack calls through.

A chaos point is one line in production code::

    from blockchain_simulator_tpu.chaos import inject
    ...
    inject.chaos_point("sweep.dyn_dispatch", canon=canon, n=len(points))

Disarmed (the default, and the only state tests/serving ever see unless a
drill arms one) it costs a global read and a predicted branch.  Armed, the
installed :class:`ChaosController` consults the actions registered for
that site and may sleep (slow/hang) or raise (:class:`ChaosFault`,
:class:`ChaosKill`) — *through the same exception paths a real
infrastructure fault would take*, which is the point: the degrade
machinery (degrade-to-solo, circuit breakers, batcher supervision,
quarantine) is exercised by the exact control flow it defends.

Determinism: actions trigger on **counted** firings, never wall-clock or
probability — ``fail_next(site, n=3)`` fails exactly the next three
firings of that site.  The controller's seeded ``rng`` exists for the
*scenario scripts* (tools/chaos_drill.py) to draw request mixes and
corruption offsets reproducibly; the hook layer itself is count-exact so
one chaos seed replays one fault schedule bit-for-bit.

Registered sites (grep ``chaos_point(`` for ground truth):

- ``sweep.dyn_dispatch`` — parallel/sweep.run_dyn_points, before the
  vmapped dispatch (the sweeps' and the server's shared batched path);
- ``serve.solo_dispatch`` — serve/dispatch._solo_metrics, before the solo
  executable runs (ctx carries ``req_id`` so poison can target one
  request);
- ``serve.batcher`` — the ScenarioServer batcher loop, once per
  iteration after the arrivals drain (where :class:`ChaosKill` simulates
  a dead batcher thread for the supervision drill);
- ``fleet.send`` — serve/router.py, before each POST to a replica (ctx
  carries ``replica`` and ``req_id``: a drill can slow or fail the path
  to ONE replica — the hedged-failover scenario);
- ``fleet.handoff`` — serve/router.py, at the start of a dead replica's
  WAL handoff (ctx carries ``replica``);
- ``sweep.chunk`` — parallel/sweep._run_chunk, once per chunk dispatch
  ATTEMPT of a journaled sweep (ctx carries ``key``, ``index``, ``n``,
  ``arm`` — ``primary``/``degrade``/``degrade-checkpoint`` — and
  ``mesh``), so a drill can kill a sweep between durable chunk appends
  (the resume drill) or wedge exactly the primary arm and watch the
  supervisor degrade (parallel/journal.py);
- ``query.step`` — query/engine.py, once per refinement generation
  BEFORE its dispatch (ctx carries ``step``, ``n``, ``values``), so a
  drill can kill an adaptive search between durable step appends and
  pin the resume-with-0-recomputed-steps contract (the ``query-kill9``
  scenario).
"""

from __future__ import annotations

import threading
import time
from random import Random

__all__ = [
    "ChaosController",
    "ChaosFault",
    "ChaosKill",
    "chaos_point",
    "controller",
]


class ChaosFault(RuntimeError):
    """An injected dispatch/infrastructure failure (the generic raise)."""


class ChaosKill(ChaosFault):
    """An injected batcher-thread death: raised at the ``serve.batcher``
    site it escapes the per-group flush guard on purpose, so only the
    batcher *supervisor* (serve/server.py) can save the daemon."""


class _Action:
    """One armed behavior at one site: fires for ``count`` triggerings
    (None = forever), optionally only when ``match(ctx)`` holds."""

    __slots__ = ("kind", "count", "fired", "exc", "sleep_s", "match")

    def __init__(self, kind, count=1, exc=None, sleep_s=0.0, match=None):
        self.kind = kind
        self.count = count
        self.fired = 0
        self.exc = exc
        self.sleep_s = sleep_s
        self.match = match

    def live(self) -> bool:
        return self.count is None or self.fired < self.count


class ChaosController:
    """Seeded, armable fault schedule over the registered chaos points.

    Install with :func:`controller` (context manager) or
    :meth:`install`/:meth:`uninstall`; only ONE controller is active per
    process (the drill runs scenarios sequentially).  All mutation is
    lock-guarded: chaos points fire from the batcher thread and HTTP
    worker threads concurrently.
    """

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self.rng = Random(self.seed)
        self._actions: dict[str, list[_Action]] = {}
        self._lock = threading.Lock()
        # every fired injection, in firing order: the drill's determinism
        # check compares this schedule across the two same-seed runs
        self.events: list[tuple[str, str]] = []

    # ------------------------------------------------------------ arming ---
    def _arm(self, site: str, action: _Action) -> None:
        with self._lock:
            self._actions.setdefault(site, []).append(action)

    def fail_next(self, site: str, n: int = 1, exc=ChaosFault,
                  match=None) -> None:
        """Raise ``exc`` on the next ``n`` firings of ``site``."""
        self._arm(site, _Action("fail", count=n, exc=exc, match=match))

    def kill_next(self, site: str, n: int = 1) -> None:
        """Raise :class:`ChaosKill` on the next ``n`` firings — the
        thread-death injection (only meaningful at ``serve.batcher``)."""
        self._arm(site, _Action("fail", count=n, exc=ChaosKill))

    def hang_next(self, site: str, seconds: float, n: int = 1,
                  match=None) -> None:
        """Sleep ``seconds`` on the next ``n`` firings (a bounded stand-in
        for a wedged dispatch: long relative to request timeouts).
        ``match(ctx)`` narrows the firings (e.g. one fleet replica)."""
        self._arm(site, _Action("hang", count=n, sleep_s=float(seconds),
                                match=match))

    def slow_next(self, site: str, seconds: float, n: int = 1,
                  match=None) -> None:
        """Same mechanics as hang, logged distinctly: latency, not loss."""
        self._arm(site, _Action("slow", count=n, sleep_s=float(seconds),
                                match=match))

    def poison(self, site: str, req_id: str, exc=ChaosFault) -> None:
        """Raise forever at ``site`` whenever ``ctx['req_id'] == req_id`` —
        a request that fails every dispatch, batched or solo (the
        quarantine drill)."""
        self._arm(site, _Action(
            "poison", count=None, exc=exc,
            match=lambda ctx, rid=req_id: ctx.get("req_id") == rid,
        ))

    # ------------------------------------------------------------- firing ---
    def fire(self, site: str, ctx: dict) -> None:
        sleep_s = 0.0
        raise_exc = None
        with self._lock:
            for action in self._actions.get(site, ()):
                if not action.live():
                    continue
                if action.match is not None and not action.match(ctx):
                    continue
                action.fired += 1
                self.events.append((site, action.kind))
                if action.kind in ("hang", "slow"):
                    sleep_s = action.sleep_s
                else:
                    raise_exc = action.exc
                break  # one action per firing: schedules stay count-exact
        if sleep_s:
            time.sleep(sleep_s)
        if raise_exc is not None:
            raise raise_exc(f"chaos[{site}] injected {raise_exc.__name__} "
                            f"(seed={self.seed})")

    def schedule(self) -> list[str]:
        """The fired-injection log as stable strings (the determinism
        artifact field: two same-seed runs must produce equal schedules)."""
        with self._lock:
            return [f"{site}:{kind}" for site, kind in self.events]

    # ------------------------------------------------------- installation ---
    def install(self) -> "ChaosController":
        global _controller
        _controller = self
        return self

    def uninstall(self) -> None:
        global _controller
        if _controller is self:
            _controller = None

    def __enter__(self) -> "ChaosController":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


_controller: ChaosController | None = None


def controller(seed: int = 0) -> ChaosController:
    """``with chaos.controller(seed) as ctl: ctl.fail_next(...)`` — the
    drill idiom.  Installation is process-global; the context manager
    guarantees the points disarm even when a scenario dies."""
    return ChaosController(seed)


def chaos_point(site: str, **ctx) -> None:
    """The production-side hook: a no-op unless a controller is installed.

    Keyword context (``req_id``, ``canon``...) is matched by targeted
    actions (poison); plain counted actions ignore it."""
    c = _controller
    if c is not None:
        c.fire(site, ctx)
