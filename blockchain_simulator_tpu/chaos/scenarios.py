"""The scripted chaos scenarios: one fault class each, invariants after.

Every scenario drives a REAL :class:`~blockchain_simulator_tpu.serve.
server.ScenarioServer` (or the real persistent cache) through one fault
class with the chaos points armed, then runs the invariant checker
(chaos/invariants.py) over the client ledger, the server's quiescent
stats, the scenario's own runs.jsonl access log and the executable-
registry counters.  :func:`run_scenario` wraps a scenario with its
seeded controller, a private access log, and the registry bracketing —
and returns a **normalized summary**: only deterministic fields (outcome
kinds per request id, terminal counters, the fired chaos schedule,
violations), no latencies or timestamps, so the drill's same-seed
double-run can require ``summary1 == summary2`` byte-for-byte.

Scenario catalog (tools/chaos_drill.py runs all; tests pick):

- ``dispatch-fail``   batched dispatch raises → degrade-to-solo, breaker
  opens after the threshold, solo-only mode, half-open probe re-closes;
- ``dispatch-hang``   batched dispatch hangs/slows → queued requests
  behind the hang expire into typed 504s, slow traffic still answers;
- ``cache-corrupt``   a persistent-cache entry is bit-flipped on disk →
  checksum detects, self-heal (delete/recompile/rewrite) counts
  ``corrupt_healed``, the next load is a clean disk hit;
- ``health-flap``     a seed-driven sick/healthy verdict pattern →
  admission 503s exactly while sick, serves exactly while healthy;
- ``batcher-kill``    the batcher thread dies mid-loop → the supervisor
  restarts it (backoff), the grouped requests still answer;
- ``queue-storm``     a burst beyond ``max_queue`` → typed 429s with
  manifests for the overflow, the admitted backlog drains served;
- ``poison-request``  one request fails batched AND solo → typed
  ``dispatch-failed``, quarantined, resubmission never joins a batch;
- ``crash-restart``   admitted requests outlive a dead server via the
  WAL: replayed exactly once per pending id, answers bit-equal (exact
  sampler) to the uninterrupted reference, second restart replays zero;
- ``sweep-kill9``     a journaled fault sweep dies mid-grid → rerunning
  it resumes from the sweep journal (parallel/journal.py): completed
  chunks never recompute, rows bit-equal to the uninterrupted sweep
  (the subprocess SIGKILL variant is tools/sweep_resume_drill.py);
- ``sweep-wedge``     a chunk's dispatch wedges → the supervisor's
  deadline fires, bounded retries, then the recorded degrade arm
  answers — the journal carries the whole transition trail;
- ``query-kill9``     a replica dies mid-adaptive-search (query/) with
  the admission WAL-durable and two generations journaled → a restart
  replays the query, serves every completed generation from the journal
  (0 recomputed steps) and re-answers bit-equal to an uninterrupted
  reference.

All scenarios run at toy scale (pbft n=8, exact sampler — the shared
tests/test_zserve.py template) so the whole drill is compile-cheap and
the warm registry serves every scenario after the first.
"""

from __future__ import annotations

import os
import tempfile
import time

from blockchain_simulator_tpu.chaos import inject, invariants
from blockchain_simulator_tpu.utils import aotcache, obs

# the shared warm template (tests/test_zserve.py TPL): every scenario
# batches on this canonical structure so the drill compiles it ONCE
TPL = {"protocol": "pbft", "n": 8, "sim_ms": 200, "stat_sampler": "exact"}

# terminal counters that are deterministic under a scripted scenario
# (batches/occupancy are timing-shaped and deliberately excluded)
_COUNT_KEYS = ("received", "served", "errors", "timeouts", "replayed",
               "quarantined", "batcher_restarts")


def _norm(metrics: dict) -> dict:
    return {k: str(v) for k, v in metrics.items()}


def _counts(stats: dict) -> dict:
    rec = {k: stats.get(k, 0) for k in _COUNT_KEYS}
    rec["rejected"] = dict(sorted((stats.get("rejected") or {}).items()))
    return rec


def _submit(srv, ledger, obj, wait_s=300.0):
    """Submit one request, record its terminal outcome in the ledger,
    return the response body (typed rejections included)."""
    req_id = obj.get("id")
    ledger.submitted(req_id)
    resp = srv.request(obj, wait_s=wait_s)
    ledger.record(req_id, resp)
    return resp


# ------------------------------------------------------------- scenarios ---


def scenario_dispatch_fail(ctl, workdir, quick):
    """Batched dispatch raises N times: every request still answers (the
    degrade path), the group's breaker opens at the threshold, solo-only
    mode serves, and the half-open probe re-closes the breaker."""
    from blockchain_simulator_tpu.serve import ScenarioServer

    ctl.fail_next("sweep.dyn_dispatch", n=2)
    ledger = invariants.Ledger()
    modes = []
    # the cooldown is generous vs the warm inter-pair gap (~ms) so pair 3
    # deterministically lands while the breaker is still open, and the
    # explicit sleep before pair 4 deterministically lands after it
    with ScenarioServer(max_batch=2, max_wait_ms=2000.0,
                        breaker_threshold=2, breaker_cooldown_s=2.0) as srv:
        for i in range(4):
            if i == 3:
                time.sleep(2.5)  # past the cooldown: the half-open probe
            a = srv.submit(dict(TPL, seed=10 + i, id=f"a{i}"))
            b = srv.submit(dict(TPL, seed=20 + i, id=f"b{i}",
                                faults={"n_byzantine": 1}))
            ledger.submitted(f"a{i}")
            ledger.submitted(f"b{i}")
            ra, rb = a.result(300), b.result(300)
            ledger.record(f"a{i}", ra)
            ledger.record(f"b{i}", rb)
            modes.append(ra.get("batch", {}).get("mode"))
        breaker_states = [br["state"]
                          for br in srv.stats()["breakers"].values()]
        stats = srv.stats()
    violations = []
    if modes != ["degraded-solo", "degraded-solo", "breaker-solo",
                 "batched"]:
        violations.append(f"breaker mode trajectory wrong: {modes}")
    if breaker_states != ["closed"]:
        violations.append(f"breaker did not re-close: {breaker_states}")
    return {"ledger": ledger, "stats": stats, "violations": violations,
            "extra": {"modes": modes, "breaker_states": breaker_states}}


def scenario_dispatch_hang(ctl, workdir, quick):
    """Batched dispatch hangs longer than the victims' timeouts: the pair
    in the hung flush still answers, the requests stuck behind it expire
    into typed 504s, and a merely-slow dispatch afterwards answers ok."""
    from blockchain_simulator_tpu.serve import ScenarioServer

    hang_s = 1.2
    ctl.hang_next("sweep.dyn_dispatch", hang_s)
    ctl.slow_next("sweep.dyn_dispatch", 0.05)
    ledger = invariants.Ledger()
    with ScenarioServer(max_batch=2, max_wait_ms=2000.0) as srv:
        a = srv.submit(dict(TPL, seed=1, id="hung-a"))
        b = srv.submit(dict(TPL, seed=2, id="hung-b"))
        ledger.submitted("hung-a")
        ledger.submitted("hung-b")
        time.sleep(0.4)  # the pair is now inside the hanging dispatch
        c = srv.submit(dict(TPL, seed=3, id="stuck-c", timeout_s=0.2))
        d = srv.submit(dict(TPL, seed=4, id="stuck-d", timeout_s=0.2))
        ledger.submitted("stuck-c")
        ledger.submitted("stuck-d")
        for rid, fut in (("hung-a", a), ("hung-b", b),
                         ("stuck-c", c), ("stuck-d", d)):
            ledger.record(rid, fut.result(300))
        # a merely-SLOW batched dispatch (the second armed action) still
        # answers: submit as a pair so the batched path actually runs
        e = srv.submit(dict(TPL, seed=5, id="slow-e"))
        f = srv.submit(dict(TPL, seed=6, id="slow-f"))
        ledger.submitted("slow-e")
        ledger.submitted("slow-f")
        ledger.record("slow-e", e.result(300))
        ledger.record("slow-f", f.result(300))
        stats = srv.stats()
    violations = []
    want = {"hung-a": ["ok"], "hung-b": ["ok"],
            "stuck-c": ["timeout"], "stuck-d": ["timeout"],
            "slow-e": ["ok"], "slow-f": ["ok"]}
    if ledger.kinds() != want:
        violations.append(f"hang outcomes wrong: {ledger.kinds()}")
    return {"ledger": ledger, "stats": stats, "violations": violations,
            "extra": {"hang_s": hang_s}}


def scenario_cache_corrupt(ctl, workdir, quick):
    """A persistent-cache entry is bit-flipped on disk: the checksum
    catches it BEFORE deserialization, the entry self-heals (delete →
    recompile → rewrite, ``corrupt_healed`` counted) and the next load is
    a clean disk hit with a bit-equal result."""
    import jax
    import jax.numpy as jnp

    cache_dir = os.path.join(workdir, "compile_cache")
    prev = os.environ.get(aotcache.PERSIST_ENV)
    os.environ[aotcache.PERSIST_ENV] = cache_dir
    violations = []
    try:
        args = (jnp.arange(16, dtype=jnp.int32),)

        def build():
            return jax.jit(lambda x: (x * 2 + 1).sum())

        s0 = aotcache.registry.stats()
        c1, i1 = aotcache.aot_compile("chaos-probe", build(), args)
        v1 = int(c1(*args))
        entries = sorted(os.listdir(cache_dir))
        if len(entries) != 1:
            # the save itself failed (disk full?): report, don't crash —
            # a drill must always end in an invariant verdict
            violations.append(f"expected 1 cache entry, found {entries}")
            return {"ledger": None, "stats": None,
                    "violations": violations,
                    "extra": {"sources": [i1["source"]], "value": v1,
                              "healed": 0}}
        path = os.path.join(cache_dir, entries[0])
        size = os.path.getsize(path)
        # flip one bit in the body (the checksummed blob dominates the
        # file; the offset is seed-driven, the detection is not)
        offset = ctl.rng.randrange(size // 5, size - 1)
        with open(path, "r+b") as f:
            f.seek(offset)
            byte = f.read(1)
            f.seek(offset)
            f.write(bytes([byte[0] ^ 0x40]))
        c2, i2 = aotcache.aot_compile("chaos-probe", build(), args)
        v2 = int(c2(*args))
        c3, i3 = aotcache.aot_compile("chaos-probe", build(), args)
        v3 = int(c3(*args))
        s1 = aotcache.registry.stats()
        healed = s1["corrupt_healed"] - s0["corrupt_healed"]
        if healed != 1:
            violations.append(f"corrupt_healed moved by {healed}, not 1")
        if i2["source"] != "compile":
            violations.append("corrupt entry was served from disk")
        if i3["source"] != "disk":
            violations.append("healed entry did not reload from disk")
        if not (v1 == v2 == v3):
            violations.append(f"values diverged: {v1} {v2} {v3}")
        extra = {"sources": [i1["source"], i2["source"], i3["source"]],
                 "value": v1, "healed": healed}
    finally:
        if prev is None:
            os.environ.pop(aotcache.PERSIST_ENV, None)
        else:
            os.environ[aotcache.PERSIST_ENV] = prev
    return {"ledger": None, "stats": None, "violations": violations,
            "extra": extra}


def scenario_health_flap(ctl, workdir, quick):
    """A seed-driven sick/healthy flap pattern: submissions 503 exactly
    while the verdict is bad and serve exactly while it is good — the
    gate never loses a request either way."""
    from blockchain_simulator_tpu.serve import ScenarioServer

    pattern = [ctl.rng.random() < 0.5 for _ in range(8)]
    ledger = invariants.Ledger()
    got = []
    with ScenarioServer(max_batch=2, max_wait_ms=5.0) as srv:
        for i, sick in enumerate(pattern):
            srv.set_health("sick" if sick else "healthy")
            resp = _submit(srv, ledger, dict(TPL, seed=30 + i, id=f"h{i}"))
            got.append(resp.get("kind") if resp.get("status") == "error"
                       else "ok")
        srv.set_health("healthy")
        stats = srv.stats()
    want = ["admission-paused" if sick else "ok" for sick in pattern]
    violations = []
    if got != want:
        violations.append(f"flap outcomes {got} != verdict pattern {want}")
    return {"ledger": ledger, "stats": stats, "violations": violations,
            "extra": {"pattern": ["sick" if s else "healthy"
                                  for s in pattern]}}


def scenario_batcher_kill(ctl, workdir, quick):
    """The batcher thread dies mid-loop (ChaosKill escapes the flush
    guard): the supervisor restarts it with backoff and the requests the
    dead thread had already grouped still answer."""
    from blockchain_simulator_tpu.serve import ScenarioServer

    ctl.kill_next("serve.batcher", n=1)
    ledger = invariants.Ledger()
    with ScenarioServer(max_batch=2, max_wait_ms=2000.0) as srv:
        a = srv.submit(dict(TPL, seed=1, id="k0"))
        b = srv.submit(dict(TPL, seed=2, id="k1"))
        ledger.submitted("k0")
        ledger.submitted("k1")
        ledger.record("k0", a.result(300))
        ledger.record("k1", b.result(300))
        _submit(srv, ledger, dict(TPL, seed=3, id="k2"))
        stats = srv.stats()
    violations = []
    if stats["batcher_restarts"] != 1:
        violations.append(
            f"batcher_restarts {stats['batcher_restarts']} != 1")
    if any(k != ["ok"] for k in ledger.kinds().values()):
        violations.append(f"kill outcomes wrong: {ledger.kinds()}")
    return {"ledger": ledger, "stats": stats, "violations": violations,
            "extra": {}}


def scenario_queue_storm(ctl, workdir, quick):
    """A submission burst beyond ``max_queue`` with the batcher held:
    exactly ``max_queue`` admit, the overflow 429s (each with its
    manifest line), and starting the batcher drains the backlog served."""
    from blockchain_simulator_tpu.serve import ScenarioServer

    max_queue = 3 if quick else 6
    burst = max_queue + (3 if quick else 5)
    ledger = invariants.Ledger()
    pendings = {}
    srv = ScenarioServer(max_batch=2, max_wait_ms=5.0,
                         max_queue=max_queue, start=False)
    try:
        from blockchain_simulator_tpu.serve import schema as serve_schema

        for i in range(burst):
            rid = f"s{i}"
            ledger.submitted(rid)
            try:
                pendings[rid] = srv.submit(dict(TPL, seed=40 + i, id=rid))
            except serve_schema.ServeError as e:
                ledger.record_error(rid, e)
        srv.start()  # the storm passed: drain the admitted backlog
        for rid, fut in pendings.items():
            ledger.record(rid, fut.result(300))
        stats = srv.stats()
    finally:
        srv.close()
    kinds = ledger.kinds()
    n_ok = sum(k == ["ok"] for k in kinds.values())
    n_429 = sum(k == ["queue-full"] for k in kinds.values())
    violations = []
    if n_ok != max_queue or n_429 != burst - max_queue:
        violations.append(
            f"storm split wrong: {n_ok} served / {n_429} rejected "
            f"(queue {max_queue}, burst {burst})")
    return {"ledger": ledger, "stats": stats, "violations": violations,
            "extra": {"max_queue": max_queue, "burst": burst}}


def scenario_poison_request(ctl, workdir, quick):
    """One request fails batched AND solo (poison): its peer still
    answers, the poison id lands in quarantine with a typed
    ``dispatch-failed``, and a resubmission of the same id never joins a
    batch again (singleton quarantined flush) while fresh peers batch."""
    from blockchain_simulator_tpu.serve import ScenarioServer

    ctl.fail_next("sweep.dyn_dispatch", n=1)
    ctl.poison("serve.solo_dispatch", "poison-1")
    ledger = invariants.Ledger()
    with ScenarioServer(max_batch=2, max_wait_ms=2000.0) as srv:
        p = srv.submit(dict(TPL, seed=1, id="poison-1"))
        q = srv.submit(dict(TPL, seed=2, id="peer-1"))
        ledger.submitted("poison-1")
        ledger.submitted("peer-1")
        rp, rq = p.result(300), q.result(300)
        ledger.record("poison-1", rp)
        ledger.record("peer-1", rq)
        # resubmit the poison with healthy peers in flight: the peers
        # must batch with each other, never with the quarantined id
        p2 = srv.submit(dict(TPL, seed=3, id="poison-1"))
        a = srv.submit(dict(TPL, seed=4, id="peer-2"))
        b = srv.submit(dict(TPL, seed=5, id="peer-3",
                            faults={"n_byzantine": 1}))
        for rid in ("poison-1", "peer-2", "peer-3"):
            ledger.submitted(rid)
        rp2, ra, rb = p2.result(300), a.result(300), b.result(300)
        ledger.record("poison-1", rp2)
        ledger.record("peer-2", ra)
        ledger.record("peer-3", rb)
        stats = srv.stats()
    violations = []
    if rp.get("kind") != "dispatch-failed" \
            or rp2.get("kind") != "dispatch-failed":
        violations.append("poison did not fail with dispatch-failed")
    if rq.get("batch", {}).get("mode") != "degraded-solo":
        violations.append(f"peer not degraded-solo: {rq.get('batch')}")
    if ra.get("batch", {}).get("mode") != "batched" \
            or rb.get("batch", {}).get("mode") != "batched":
        violations.append("fresh peers failed to batch around quarantine")
    if stats["quarantined"] != 1 or stats["quarantine_size"] != 1:
        violations.append(
            f"quarantine counters wrong: {stats['quarantined']}, "
            f"{stats['quarantine_size']}")
    return {"ledger": ledger, "stats": stats, "violations": violations,
            "extra": {"peer_modes": [rq["batch"]["mode"],
                                     ra["batch"]["mode"],
                                     rb["batch"]["mode"]]}}


def scenario_crash_restart(ctl, workdir, quick):
    """The WAL drill, in-process: a server answers some requests and dies
    (abandoned, never closed) with more admitted; a restarted server on
    the same WAL replays exactly the pending ids, each answer bit-equal
    (exact sampler) to a solo reference run; a THIRD restart replays
    nothing.  The subprocess kill -9 variant lives in
    tools/chaos_drill.py ``--full`` (and the slow-marked test)."""
    from blockchain_simulator_tpu import runner
    from blockchain_simulator_tpu.serve import ScenarioServer
    from blockchain_simulator_tpu.utils.config import FaultConfig, SimConfig

    wal = os.path.join(workdir, "serve_wal.jsonl")
    ledger = invariants.Ledger()
    # phase 1: live traffic, answered and journaled done
    with ScenarioServer(max_batch=2, max_wait_ms=5.0, wal_path=wal) as srv:
        _submit(srv, ledger, dict(TPL, seed=50, id="live-0"))
        _submit(srv, ledger, dict(TPL, seed=51, id="live-1"))
    # phase 2: admitted but never answered — the batcher never runs and
    # the server is abandoned without close(): a process death stand-in
    crashed = ScenarioServer(max_batch=2, max_wait_ms=5.0, wal_path=wal,
                             start=False)
    crash_points = [
        ("crash-0", dict(TPL, seed=60, id="crash-0")),
        ("crash-1", dict(TPL, seed=61, id="crash-1",
                         faults={"n_byzantine": 1})),
        ("crash-2", dict(TPL, seed=62, id="crash-2",
                         faults={"n_crashed": 1})),
    ]
    for _, obj in crash_points:
        crashed.submit(obj)
    crashed._wal.close()  # the admits are fsynced; drop the handle
    del crashed
    # phase 3: restart replays exactly the pending ids
    srv2 = ScenarioServer(max_batch=2, max_wait_ms=5.0, wal_path=wal)
    t0 = time.monotonic()
    while srv2.stats()["queue_depth"] and time.monotonic() - t0 < 120:
        time.sleep(0.02)
    stats = srv2.stats()
    srv2.close()
    violations = []
    if stats["replayed"] != len(crash_points):
        violations.append(
            f"replayed {stats['replayed']} != {len(crash_points)} pending")
    # bit-equality: each replayed access-log answer vs a solo static run
    log = os.environ.get(obs.RUNS_ENV)
    recs = obs.read_jsonl(log) if log else []
    replay_recs = {r.get("id"): r for r in recs if r.get("replayed") is True}
    divergence = 0
    for rid, obj in crash_points:
        rec = replay_recs.get(rid)
        if rec is None or rec.get("status") != "ok":
            violations.append(f"replay of {rid!r} missing or failed: "
                              f"{None if rec is None else rec.get('kind')}")
            divergence += 1
            continue
        kw = {k: v for k, v in obj.items()
              if k not in ("id", "seed", "faults")}
        cfg = SimConfig(**kw, faults=FaultConfig(**obj.get("faults", {})))
        ref = runner.run_simulation(cfg, seed=obj["seed"])
        if _norm(rec["metrics"]) != _norm(ref):
            violations.append(f"replay of {rid!r} diverged from the "
                              f"uninterrupted reference")
            divergence += 1
    # phase 4: idempotence — a second restart has nothing to replay
    srv3 = ScenarioServer(max_batch=2, max_wait_ms=5.0, wal_path=wal)
    replay_again = srv3.stats()["replayed"]
    srv3.close()
    if replay_again != 0:
        violations.append(
            f"second restart replayed {replay_again} ids (want 0)")
    return {"ledger": ledger, "stats": stats, "violations": violations,
            "replayed_ids": [rid for rid, _ in crash_points],
            # the crashed server died holding these admissions: the
            # telemetry conservation check (invariants.check_telemetry)
            # must see the balance off by exactly this many
            "lost_admissions": len(crash_points),
            "extra": {"replay_divergence": divergence,
                      "replayed": stats["replayed"],
                      "replay_again": replay_again}}


def _canon_rows(res) -> dict:
    """``run_fault_sweep`` result -> {fault level: [canonical-JSON rows]}:
    the bit-equality comparison for journaled sweeps.  Canonical JSON on
    BOTH sides because resumed rows ride a JSON round trip (ints/floats
    are repr-exact; container types normalize) — the honest equality for
    rows that crossed a file."""
    return {
        fc.n_byzantine: [obs.canonical_json(m) for m in rows]
        for fc, rows in res.items()
    }


def scenario_sweep_kill9(ctl, workdir, quick):
    """The durable-sweep crash drill, in-process: a journaled fault sweep
    dies (ChaosKill at the ``sweep.chunk`` point) with 2 of 4 level
    chunks journaled; rerunning the SAME sweep on the same journal
    resumes — completed chunks are never recomputed (their keys stay
    unique in the journal, registry misses move 0), only the missing
    levels dispatch, and every row is bit-equal to an un-journaled
    reference sweep.  The subprocess SIGKILL variant (a REAL kill -9,
    ARTIFACT_resume_sweep.json) lives in tools/sweep_resume_drill.py."""
    from blockchain_simulator_tpu.parallel import journal as journal_mod
    from blockchain_simulator_tpu.parallel.sweep import (
        dyn_chunk_keys,
        run_fault_sweep,
    )
    from blockchain_simulator_tpu.utils.config import FaultConfig, SimConfig

    cfg = SimConfig(**TPL)
    fcs = [FaultConfig(n_byzantine=f) for f in range(4)]
    seeds = (0, 1)
    jp = os.path.join(workdir, "sweep.journal")
    kill_index = 2
    ctl.fail_next("sweep.chunk", n=1, exc=inject.ChaosKill,
                  match=lambda c: c.get("index") == kill_index)
    violations = []
    killed = False
    try:
        run_fault_sweep(cfg, fcs, seeds,
                        journal=journal_mod.SweepJournal(jp))
    except inject.ChaosKill:
        killed = True
    if not killed:
        violations.append("chaos kill at chunk 2 never fired")
    pre_keys = set(journal_mod.SweepJournal(jp).completed())
    if len(pre_keys) != kill_index:
        violations.append(
            f"{len(pre_keys)} chunks survived the kill, want {kill_index}")
    # resume: the same sweep call on the same journal path
    misses_before = aotcache.registry.stats()["misses"]
    resumed = run_fault_sweep(cfg, fcs, seeds,
                              journal=journal_mod.SweepJournal(jp))
    resume_misses = aotcache.registry.stats()["misses"] - misses_before
    if resume_misses != 0:
        violations.append(
            f"resume compiled {resume_misses} executables (want 0: the "
            f"sweep executable was warm)")
    post = journal_mod.SweepJournal(jp)
    post_keys = set(post.completed())
    recomputed = [k for k in pre_keys if k not in post_keys]
    if recomputed:
        violations.append(f"completed chunks vanished on resume: "
                          f"{sorted(recomputed)}")
    appended = len(post_keys) - len(pre_keys)
    if appended != len(fcs) - kill_index:
        violations.append(
            f"resume appended {appended} chunks, want "
            f"{len(fcs) - kill_index} (recompute-at-most-one broken)")
    reference = run_fault_sweep(cfg, fcs, seeds)
    rows_equal = _canon_rows(resumed) == _canon_rows(reference)
    if not rows_equal:
        violations.append("resumed rows diverge from the uninterrupted "
                          "reference sweep")
    # coverage from the GRID, not the journal's own content: a journal
    # that silently dropped a chunk must fail here
    violations += invariants.check_sweep_journal(
        post, expected_keys=dyn_chunk_keys(cfg, fcs, seeds),
        expected_rows=len(fcs) * len(seeds),
    )
    return {"ledger": None, "stats": None, "violations": violations,
            "extra": {"killed": killed,
                      "chunks_before_kill": len(pre_keys),
                      "chunks_resumed": appended,
                      "resume_misses": resume_misses,
                      "rows_bit_equal": rows_equal}}


def scenario_sweep_wedge(ctl, workdir, quick):
    """A chunk's primary dispatch wedges (chaos hang far beyond the
    supervisor deadline, both attempts): the supervisor records
    deadline → retry → deadline → degrade in the journal, the degrade
    arm answers, later chunks dispatch normally, and the whole grid's
    rows are bit-equal to an unsupervised reference — a hung chunk costs
    bounded wall, never the sweep."""
    from blockchain_simulator_tpu.parallel import journal as journal_mod
    from blockchain_simulator_tpu.parallel.sweep import (
        dyn_chunk_keys,
        run_fault_sweep,
    )
    from blockchain_simulator_tpu.utils.config import FaultConfig, SimConfig

    cfg = SimConfig(**TPL)
    fcs = [FaultConfig(n_byzantine=f) for f in range(2)]
    seeds = (0, 1)
    jp = os.path.join(workdir, "sweep.journal")
    # wedge chunk 0's primary arm only: the degrade arm (and chunk 1)
    # must sail through — counted firings keep the schedule exact.  The
    # hang must dwarf the deadline, the deadline must dwarf a warm n=8
    # dispatch on the 1-core box (~0.2 s).
    ctl.hang_next("sweep.chunk", seconds=2.0, n=2,
                  match=lambda c: c.get("arm") == "primary"
                  and c.get("index") == 0)
    sup = journal_mod.ChunkSupervisor(deadline_s=1.0, retries=1,
                                      backoff_s=0.02, rng=ctl.rng.random)
    j = journal_mod.SweepJournal(jp)
    result = run_fault_sweep(cfg, fcs, seeds, journal=j, supervise=sup)
    # the two abandoned primary attempts are still sleeping/dispatching:
    # drain them so neither this scenario's determinism twin nor process
    # exit races a zombie mid-XLA
    journal_mod.drain_abandoned()
    events = [e["event"] for e in j.events()]
    violations = []
    want = ["deadline", "retry", "deadline", "degrade"]
    if events != want:
        violations.append(f"supervisor trail {events} != {want}")
    reference = run_fault_sweep(cfg, fcs, seeds)
    rows_equal = _canon_rows(result) == _canon_rows(reference)
    if not rows_equal:
        violations.append("degraded rows diverge from the reference sweep")
    post = journal_mod.SweepJournal(jp)
    violations += invariants.check_sweep_journal(
        post, expected_keys=dyn_chunk_keys(cfg, fcs, seeds),
        expected_rows=len(fcs) * len(seeds),
    )
    return {"ledger": None, "stats": None, "violations": violations,
            "extra": {"events": events, "rows_bit_equal": rows_equal}}


def scenario_query_kill9(ctl, workdir, quick):
    """The durable-query crash drill, in-process: a replica dies
    (ChaosKill at the ``query.step`` point, the worker's stand-in for
    process death) two refinement generations into an adaptive search,
    with the admission WAL-durable and both generations journaled.  A
    restarted replica on the same WAL + journal replays the query: every
    completed generation is served from the journal (0 recomputed steps —
    their chunk keys stay unique), 0 new executables compile (the search
    executable was warm), and the final answer is bit-equal to an
    uninterrupted reference run of the same query.  A third restart
    replays nothing."""
    from blockchain_simulator_tpu.parallel import journal as journal_mod
    from blockchain_simulator_tpu.query import engine as qengine
    from blockchain_simulator_tpu.query import spec as qspec
    from blockchain_simulator_tpu.serve import ScenarioServer
    from blockchain_simulator_tpu.utils.config import SimConfig

    wal = os.path.join(workdir, "query_wal.jsonl")
    jp = os.path.join(workdir, "query.journal")
    # sim_ms=400: long enough that pbft n=8 commits below the cliff, so
    # the search takes 3 generations (endpoints, midpoints, final) — the
    # kill lands on generation 2 with 0 and 1 already durable
    qspec_obj = {"kind": "max_f_surviving", "seeds": [0, 1]}
    qobj = dict(TPL, sim_ms=400, id="q-kill", timeout_s=300.0,
                query=qspec_obj)
    kill_step = 2
    ctl.fail_next("query.step", n=1, exc=inject.ChaosKill,
                  match=lambda c: c.get("step") == kill_step)
    violations = []
    # phase 1: the worker dies mid-search; the server is abandoned
    # (never closed) — the in-process process-death stand-in
    crashed = ScenarioServer(wal_path=wal, journal_path=jp)
    crashed.submit(qobj)
    t0 = time.monotonic()
    while time.monotonic() - t0 < 120:
        with crashed._lock:
            workers = [t for _, _, t in crashed._queries]
        if workers and not any(t.is_alive() for t in workers):
            break
        time.sleep(0.02)
    else:
        violations.append("query worker never died under the chaos kill")
    crashed._wal.close()  # the admit is fsynced; drop the handles
    crashed._journal.close()
    del crashed
    pre_keys = set(journal_mod.SweepJournal(jp).completed())
    if len(pre_keys) != kill_step:
        violations.append(
            f"{len(pre_keys)} generations survived the kill, want "
            f"{kill_step}")
    # phase 2: restart on the same WAL + journal — the replay re-runs
    # the query, resuming from the journal
    misses_before = aotcache.registry.stats()["misses"]
    srv2 = ScenarioServer(wal_path=wal, journal_path=jp)
    t0 = time.monotonic()
    # the query leaves the arrivals queue the moment its worker spawns,
    # so quiescence is "the replayed request answered", not queue depth
    while not srv2.stats()["served"] and time.monotonic() - t0 < 120:
        time.sleep(0.02)
    stats = srv2.stats()
    srv2.close()
    resume_misses = aotcache.registry.stats()["misses"] - misses_before
    if resume_misses != 0:
        violations.append(
            f"resume compiled {resume_misses} executables (want 0: the "
            f"search executable was warm)")
    if stats["replayed"] != 1:
        violations.append(f"replayed {stats['replayed']} != 1 pending")
    log = os.environ.get(obs.RUNS_ENV)
    recs = obs.read_jsonl(log) if log else []
    rec = next((r for r in recs if r.get("id") == "q-kill"
                and r.get("replayed") is True), None)
    cached_steps = None
    answer_equal = False
    if rec is None or rec.get("status") != "ok":
        violations.append(
            f"replayed query missing or failed: "
            f"{None if rec is None else rec.get('kind')}")
    else:
        run = rec.get("run") or {}
        cached_steps = run.get("cached_steps")
        # 0 completed steps recomputed: every pre-kill generation served
        # from the journal, only the missing ones dispatched
        if cached_steps != kill_step:
            violations.append(
                f"resume served {cached_steps} generations from the "
                f"journal, want {kill_step}")
        if run.get("dispatches") != run.get("steps", 0) - kill_step:
            violations.append(
                f"resume dispatched {run.get('dispatches')} generations, "
                f"want {run.get('steps', 0) - kill_step} "
                f"(recompute-at-most-zero broken)")
        # bit-equality vs an uninterrupted reference run of the query —
        # journaled (to a fresh journal) so the trail's chunk keys are
        # populated on both sides; the keys are content-derived, so they
        # match across journal files by construction
        cfg = SimConfig(**dict(TPL, sim_ms=400))
        ref = qengine.run_query(
            cfg, qspec.parse_query(qspec_obj),
            journal=journal_mod.SweepJournal(
                os.path.join(workdir, "query_ref.journal")))
        answer_equal = (
            obs.canonical_json(rec.get("answer"))
            == obs.canonical_json(ref["answer"])
            and obs.canonical_json(rec.get("trail"))
            == obs.canonical_json(ref["trail"])
        )
        if not answer_equal:
            violations.append(
                "replayed answer/trail diverge from the uninterrupted "
                "reference query")
        post = journal_mod.SweepJournal(jp)
        violations += invariants.check_query_trail(rec, journal=post)
        violations += invariants.check_sweep_journal(post)
    # phase 3: idempotence — nothing left to replay
    srv3 = ScenarioServer(wal_path=wal, journal_path=jp)
    replay_again = srv3.stats()["replayed"]
    srv3.close()
    if replay_again != 0:
        violations.append(
            f"third restart replayed {replay_again} ids (want 0)")
    return {"ledger": None, "stats": stats, "violations": violations,
            "replayed_ids": ["q-kill"],
            # the crashed server died holding this admission: the
            # telemetry conservation balance must be off by exactly one
            "lost_admissions": 1,
            "extra": {"generations_before_kill": len(pre_keys),
                      "cached_steps_on_resume": cached_steps,
                      "resume_misses": resume_misses,
                      "answer_bit_equal": answer_equal,
                      "replay_again": replay_again}}


SCENARIOS = {
    "dispatch-fail": scenario_dispatch_fail,
    "dispatch-hang": scenario_dispatch_hang,
    "cache-corrupt": scenario_cache_corrupt,
    "health-flap": scenario_health_flap,
    "batcher-kill": scenario_batcher_kill,
    "queue-storm": scenario_queue_storm,
    "poison-request": scenario_poison_request,
    "crash-restart": scenario_crash_restart,
    "sweep-kill9": scenario_sweep_kill9,
    "sweep-wedge": scenario_sweep_wedge,
    "query-kill9": scenario_query_kill9,
}


def run_scenario(name: str, seed: int, workdir: str | None = None,
                 quick: bool = False) -> dict:
    """Run ONE scenario under a fresh seeded controller with a private
    access log; returns its normalized (deterministic) summary.

    The summary carries the outcome kinds per request id, the terminal
    counters, the fired chaos schedule and every invariant violation —
    and nothing timing-shaped, so two same-seed runs must compare equal
    (the drill's determinism gate)."""
    from blockchain_simulator_tpu.utils import telemetry

    fn = SCENARIOS[name]
    workdir = workdir or tempfile.mkdtemp(prefix=f"chaos_{name}_")
    log = os.path.join(workdir, "access.jsonl")
    prev = os.environ.get(obs.RUNS_ENV)
    os.environ[obs.RUNS_ENV] = log
    reg_before = aotcache.registry.stats()
    tel_before = telemetry.metrics.snapshot()
    try:
        with inject.controller(seed) as ctl, telemetry.capture() as spans:
            rep = fn(ctl, workdir, quick)
            schedule = ctl.schedule()
    finally:
        if prev is None:
            os.environ.pop(obs.RUNS_ENV, None)
        else:
            os.environ[obs.RUNS_ENV] = prev
    reg_after = aotcache.registry.stats()
    tel_after = telemetry.metrics.snapshot()
    violations = list(rep.get("violations") or [])
    ledger, stats = rep.get("ledger"), rep.get("stats")
    if stats is not None:
        violations += invariants.check_server(
            ledger, stats, log_path=log,
            registry_before=reg_before, registry_after=reg_after,
            replayed_ids=rep.get("replayed_ids", ()),
        )
    else:
        violations += invariants.registry_monotone(reg_before, reg_after)
    # the telemetry cross-checks (ISSUE 14): counter deltas must conserve
    # like the Ledger, and the scenario's serving span trees — normalized
    # timing-free — ride the summary, so the drill's byte-equal
    # determinism gate now covers telemetry too
    violations += invariants.check_telemetry(
        tel_before, tel_after,
        lost_admissions=int(rep.get("lost_admissions", 0)))
    if violations:
        telemetry.flight.note("chaos.invariant_violation", scenario=name,
                              n=len(violations))
        telemetry.flight.dump("invariant-violation")
    return {
        "scenario": name,
        "seed": seed,
        "outcomes": ledger.kinds() if ledger is not None else None,
        "counts": _counts(stats) if stats is not None else None,
        "chaos_schedule": schedule,
        "span_tree": invariants.normalize_spans(spans),
        "violations": violations,
        **{k: v for k, v in (rep.get("extra") or {}).items()},
    }
