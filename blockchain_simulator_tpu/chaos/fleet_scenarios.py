"""Fleet chaos scenarios: the replicated serving tier under fire.

Each scenario drives a REAL :class:`~blockchain_simulator_tpu.serve.
router.FleetRouter` over live HTTP endpoints — a mix of real in-process
replicas (:class:`LocalReplica`: a ScenarioServer behind the daemon's own
handler) and scripted :class:`StubReplica` fault actors (real sockets, no
dispatch: a stub can admit-to-WAL-then-die, reject with 429, or answer
instantly, which keeps the drills deterministic and compile-cheap) — then
checks the fleet invariants (chaos/invariants.check_fleet).  Summaries
are normalized exactly like the single-daemon scenarios (outcome kinds,
terminal counters, the fired chaos schedule — nothing timing-shaped), so
``tools/fleet_bench.py`` can demand byte-equal same-seed double runs.

Scenario catalog:

- ``fleet-replica-death``  the acceptance drill in-process: the replica
  holding admitted-but-unanswered requests (WAL-journaled, connections
  broken mid-flight) dies; the router's probes declare it dead, its WAL
  is lease-claimed and every pending id — including one whose request no
  longer validates — replays on the live peer exactly once, marked
  ``"replayed": true``, answers bit-equal (exact sampler) to
  uninterrupted references;
- ``fleet-slow-replica``   the path to one replica is chaos-slowed past
  ``hedge_ms``; the hedge answers from the peer, the slow answer arrives
  late and is dropped (counted, never delivered — no double answer);
- ``fleet-retry-storm``    every replica answers 429 queue-full; the
  router retries with backoff exactly ``retries`` times per request then
  answers the typed 429 — bounded, no amplification loop, and traffic
  serves again the moment the replicas recover;
- ``fleet-double-claim``   two routers race one dead WAL (fresh claim and
  torn-claim legs): the lease wins exactly once, the loser replays
  nothing, every pending id replays exactly once fleet-wide.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time

from blockchain_simulator_tpu.chaos import inject, invariants
from blockchain_simulator_tpu.chaos.scenarios import TPL, _norm
from blockchain_simulator_tpu.serve import fleet as fleet_mod
from blockchain_simulator_tpu.serve.wal import WriteAheadLog
from blockchain_simulator_tpu.utils import aotcache, obs


# ------------------------------------------------------------ endpoints ---


class StubReplica:
    """A scripted replica endpoint: real HTTP on an ephemeral port, no
    simulation dispatch.  ``mode`` (mutable mid-scenario) scripts the
    fault behavior per POST /scenario:

    - ``"ok"``        answer 200 with a stub body immediately;
    - ``"slow"``      sleep ``slow_s`` then answer 200 (the hedged-
      failover victim);
    - ``"reject-429"``answer the typed queue-full body (retry-storm);
    - ``"admit-die"`` journal the admit into ``wal_path`` (fsynced, the
      real serve/wal.py writer) and break the connection without a
      response — a kill -9 landing between admission and answer, as the
      router sees it.

    ``/healthz`` answers 200 while the stub lives; :meth:`die` closes the
    listener so probes see connection-refused, like a dead process."""

    def __init__(self, replica_id: str, mode: str = "ok",
                 wal_path: str | None = None, slow_s: float = 0.0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.id = str(replica_id)
        self.mode = mode
        self.wal_path = wal_path
        self.slow_s = float(slow_s)
        self.wal = WriteAheadLog(wal_path, sync=True) if wal_path else None
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _send(self, code, body):
                blob = (json.dumps(body) + "\n").encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

            def do_GET(self):
                self._send(200, {"ready": True, "stub": stub.id})

            def do_POST(self):
                length = int(self.headers.get("Content-Length") or 0)
                try:
                    obj = json.loads(self.rfile.read(length) or b"{}")
                except json.JSONDecodeError:
                    obj = {}
                rid = str(obj.get("id"))
                mode = stub.mode
                if mode == "admit-die":
                    if stub.wal is not None:
                        stub.wal.append_admit(rid, obj)
                    return  # no response: the connection breaks mid-flight
                if mode == "reject-429":
                    self._send(429, {
                        "id": rid, "status": "error", "code": 429,
                        "kind": "queue-full",
                        "error": f"stub {stub.id} is full",
                    })
                    return
                if mode == "slow":
                    time.sleep(stub.slow_s)
                self._send(200, {
                    "id": rid, "status": "ok", "code": 200,
                    "metrics": {"served_by": stub.id},
                    "batch": {"size": 1, "mode": "stub"},
                    "latency_ms": 0.0,
                })

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.base_url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def die(self) -> None:
        """Close the listener: probes and sends now see refused — the
        router-side signature of a dead process."""
        self.httpd.shutdown()
        self.httpd.server_close()
        if self.wal is not None:
            self.wal.close()

    def close(self) -> None:
        try:
            self.die()
        except Exception:
            pass


class LocalReplica:
    """A REAL replica in-process: a ScenarioServer behind the daemon's
    own HTTP handler (serve/__main__.make_httpd) on an ephemeral port —
    the peer that answers WAL replays with real, reference-comparable
    metrics in the drills, and the per-replica unit of the in-process
    micro-bench (tools/fleet_bench.py --quick)."""

    def __init__(self, replica_id: str, wal_path: str | None = None,
                 **server_kw):
        from blockchain_simulator_tpu.serve.__main__ import make_httpd
        from blockchain_simulator_tpu.serve.server import ScenarioServer

        self.id = str(replica_id)
        self.wal_path = wal_path
        self.server = ScenarioServer(wal_path=wal_path, replica=self.id,
                                     **server_kw)
        self.httpd = make_httpd(self.server, "127.0.0.1", 0)
        self.base_url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        try:
            self.httpd.shutdown()
            self.httpd.server_close()
        finally:
            self.server.close()


def _affinity_order(obj: dict, victim, peer):
    """Order two endpoints so the request template's batch-group affinity
    lands on ``victim`` — the drills aim their traffic without touching
    router internals (serve/router.py hashes group[:8] over the replica
    list)."""
    from blockchain_simulator_tpu.serve import schema

    req = schema.parse_request(dict(obj), "probe")
    idx = int(obs.config_hash(req.canon)[:8], 16) % 2
    return [victim, peer] if idx == 0 else [peer, victim]


# ------------------------------------------------------------ scenarios ---


def scenario_replica_death(ctl, workdir, quick):
    """Replica kill mid-traffic: admitted-but-unanswered ids (plus one
    pre-crash admit that no longer validates) replay on the live peer
    exactly once, marked, bit-equal to uninterrupted references."""
    from blockchain_simulator_tpu import runner
    from blockchain_simulator_tpu.serve.router import FleetRouter
    from blockchain_simulator_tpu.utils.config import FaultConfig, SimConfig

    wal = os.path.join(workdir, "victim.wal")
    crash_points = [
        ("fcrash-0", dict(TPL, seed=300, id="fcrash-0")),
        ("fcrash-1", dict(TPL, seed=301, id="fcrash-1",
                          faults={"n_byzantine": 1})),
        ("fcrash-2", dict(TPL, seed=302, id="fcrash-2",
                          faults={"n_crashed": 1})),
    ]
    # a pre-crash admission whose request no longer parses: the replay
    # must answer its typed 400, never crash the handoff
    stale = WriteAheadLog(wal, sync=True)
    stale.append_admit("fstale-0", {"protocol": "pbft", "n": 8,
                                    "no_such_field": 1, "id": "fstale-0"})
    stale.close()

    victim = StubReplica("fvictim", mode="admit-die", wal_path=wal)
    peer = LocalReplica("fpeer", max_batch=2, max_wait_ms=5.0)
    ledger = invariants.Ledger()
    violations: list[str] = []
    router = FleetRouter(
        _affinity_order(crash_points[0][1], victim, peer),
        probe_interval_s=0.1, dead_after=2, owner="drill-router",
        request_timeout_s=60.0,
    )
    try:
        pendings = []
        for i, (rid, obj) in enumerate(crash_points):
            ledger.submitted(rid)
            pendings.append((rid, router.submit(obj)))
            # serialize admissions: each submit must park (WAL-admitted,
            # connection broken) before the next, so the replay order —
            # pinned to WAL admission order below — is deterministic
            deadline = time.monotonic() + 30
            while router.stats()["parked_total"] < i + 1 \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
        victim.die()
        if not router.join_handoffs(1, timeout_s=60.0):
            violations.append("handoff never completed")
        for rid, pending in pendings:
            ledger.record(rid, pending.result(60.0))
        stats = router.stats()
    finally:
        router.close()
        peer.close()
        victim.close()
    # bit-equality: each replayed answer vs an uninterrupted reference
    log = os.environ.get(obs.RUNS_ENV)
    recs = obs.read_jsonl(log) if log else []
    replay_recs = {r.get("id"): r for r in recs
                   if r.get("replayed") is True}
    divergence = 0
    for rid, obj in crash_points:
        rec = replay_recs.get(rid)
        if rec is None or rec.get("status") != "ok":
            violations.append(f"fleet replay of {rid!r} missing/failed")
            divergence += 1
            continue
        kw = {k: v for k, v in obj.items()
              if k not in ("id", "seed", "faults")}
        cfg = SimConfig(**kw, faults=FaultConfig(**obj.get("faults", {})))
        ref = runner.run_simulation(cfg, seed=obj["seed"])
        if _norm(rec["metrics"]) != _norm(ref):
            violations.append(f"fleet replay of {rid!r} diverged from "
                              f"the uninterrupted reference")
            divergence += 1
    stale_rec = replay_recs.get("fstale-0")
    if stale_rec is None or stale_rec.get("kind") != "invalid-request":
        violations.append(
            f"stale admit did not replay as a typed rejection: "
            f"{None if stale_rec is None else stale_rec.get('kind')}")
    handoff_ids = [rid for rid, _ in crash_points] + ["fstale-0"]
    violations += invariants.check_fleet(
        ledger, stats, log_path=log, handoff_ids=handoff_ids)
    want_order = ["fstale-0"] + [rid for rid, _ in crash_points]
    got_order = stats["handoffs"][0].get("replayed") \
        if stats.get("handoffs") else []
    if got_order != want_order:
        violations.append(
            f"replay order {got_order} != WAL admission order "
            f"{want_order}")
    if any(k != ["ok"] for k in ledger.kinds().values()):
        violations.append(f"death outcomes wrong: {ledger.kinds()}")
    return {"ledger": ledger, "stats": stats, "violations": violations,
            "handoff_ids": handoff_ids,
            "extra": {"replay_divergence": divergence}}


def scenario_slow_replica(ctl, workdir, quick):
    """The path to one replica is chaos-slowed past ``hedge_ms``: the
    hedge answers from the peer, the slow answer lands late and is
    dropped — one answer per admission, counted duplicates only."""
    from blockchain_simulator_tpu.serve.router import FleetRouter

    slow = StubReplica("fslow", mode="ok")
    fast = StubReplica("ffast", mode="ok")
    ctl.slow_next("fleet.send", 0.8,
                  match=lambda ctx: ctx.get("replica") == "fslow")
    ledger = invariants.Ledger()
    violations: list[str] = []
    router = FleetRouter(
        _affinity_order(dict(TPL, seed=1), slow, fast),
        hedge_ms=60.0, probe=False, owner="drill-router",
        request_timeout_s=30.0, validate=True,
    )
    try:
        ledger.submitted("fhedge-0")
        resp = router.request(dict(TPL, seed=1, id="fhedge-0"), wait_s=30.0)
        ledger.record("fhedge-0", resp)
        # the slow primary answers ~0.8 s in: wait for the counted drop
        deadline = time.monotonic() + 30
        while router.stats()["late_answers"] < 1 \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        stats = router.stats()
    finally:
        router.close()
        slow.close()
        fast.close()
    if resp.get("status") != "ok" or not resp.get("hedged"):
        violations.append(f"hedge did not answer: {resp}")
    if stats["hedges"] != 1:
        violations.append(f"hedges {stats['hedges']} != 1")
    if stats["late_answers"] != 1:
        violations.append(
            f"late_answers {stats['late_answers']} != 1 (the slow "
            f"primary's answer must be dropped, not delivered)")
    violations += invariants.check_fleet(ledger, stats)
    return {"ledger": ledger, "stats": stats, "violations": violations,
            "handoff_ids": [], "extra": {}}


def scenario_retry_storm(ctl, workdir, quick):
    """Every replica 429s: the retry budget is spent exactly (bounded,
    backoff between attempts), the terminal answer is the typed 429, and
    recovery serves immediately — no storm amplification."""
    from blockchain_simulator_tpu.serve.router import FleetRouter

    a = StubReplica("fra", mode="reject-429")
    b = StubReplica("frb", mode="reject-429")
    ledger = invariants.Ledger()
    violations: list[str] = []
    n_storm = 3
    router = FleetRouter(
        [a, b], retries=2, retry_backoff_s=0.01, probe=False,
        owner="drill-router", request_timeout_s=30.0,
    )
    try:
        for i in range(n_storm):
            rid = f"fstorm-{i}"
            ledger.submitted(rid)
            ledger.record(rid, router.request(
                dict(TPL, seed=400 + i, id=rid), wait_s=30.0))
        mid_stats = router.stats()
        a.mode = b.mode = "ok"  # the storm passes
        ledger.submitted("fstorm-after")
        after = router.request(dict(TPL, seed=500, id="fstorm-after"),
                               wait_s=30.0)
        ledger.record("fstorm-after", after)
        stats = router.stats()
    finally:
        router.close()
        a.close()
        b.close()
    kinds = ledger.kinds()
    want = {f"fstorm-{i}": ["queue-full"] for i in range(n_storm)}
    want["fstorm-after"] = ["ok"]
    if kinds != want:
        violations.append(f"storm outcomes wrong: {kinds}")
    if mid_stats["retries"] != 2 * n_storm:
        violations.append(
            f"retry budget not exactly spent: {mid_stats['retries']} "
            f"retries for {n_storm} requests at retries=2")
    violations += invariants.check_fleet(ledger, stats)
    return {"ledger": ledger, "stats": stats, "violations": violations,
            "handoff_ids": [], "extra": {"storm": n_storm}}


def scenario_double_claim(ctl, workdir, quick):
    """Two routers race one dead WAL, twice: once over a fresh claim,
    once over a TORN claim file (a claimant that died mid-claim).  Each
    time exactly one lease wins, pendings replay exactly once fleet-wide,
    and the loser replays nothing."""
    peer = StubReplica("fclaim-peer", mode="ok")
    violations: list[str] = []
    extra: dict = {}

    def post(obj):
        import urllib.request

        data = json.dumps(obj).encode()
        req = urllib.request.Request(
            f"{peer.base_url}/scenario", data=data,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read())

    try:
        for leg, torn in (("fresh", False), ("torn", True)):
            wal = os.path.join(workdir, f"dead-{leg}.wal")
            w = WriteAheadLog(wal, sync=True)
            ids = [f"fdc-{leg}-{i}" for i in range(2)]
            for rid in ids:
                w.append_admit(rid, dict(TPL, seed=600, id=rid))
            w.close()
            if torn:
                # a claimant that died between create and write: the
                # claim file exists with no parseable owner record
                with open(fleet_mod.claim_path(wal), "w"):
                    pass
            results = [None, None]

            def race(i, owner):
                results[i] = fleet_mod.handoff_wal(
                    wal, owner, post, release=False)

            threads = [threading.Thread(target=race, args=(i, f"router-{i}"))
                       for i in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            claims = [r for r in results if r and r["claimed"]]
            if len(claims) != 1:
                violations.append(
                    f"{leg}: {len(claims)} routers claimed the WAL "
                    f"(lease must win exactly once)")
                continue
            winner = claims[0]
            if winner["replayed"] != ids:
                violations.append(
                    f"{leg}: replayed {winner['replayed']} != {ids} "
                    f"(every pending id exactly once, in order)")
            loser = next(r for r in results if r and not r["claimed"])
            if loser["replayed"]:
                violations.append(f"{leg}: loser replayed "
                                  f"{loser['replayed']}")
            # the claim is still held: a second handoff (a replica
            # restarting, a third router) must find nothing claimable
            again = fleet_mod.handoff_wal(wal, "router-3", post)
            if again["claimed"]:
                violations.append(f"{leg}: held lease was re-claimed")
            fleet_mod.release_claim(wal)
            # post-release: the replay retired every id, nothing pends
            empty = fleet_mod.handoff_wal(wal, "router-4", post)
            if not empty["claimed"] or empty["pending"] != 0:
                violations.append(
                    f"{leg}: post-release handoff saw {empty['pending']} "
                    f"pending (want 0 — done records must retire ids)")
            fleet_mod.release_claim(wal)
            extra[leg] = {"winner_replayed": winner["replayed"]}
    finally:
        peer.close()
    return {"ledger": None, "stats": None, "violations": violations,
            "handoff_ids": [], "extra": extra}


FLEET_SCENARIOS = {
    "fleet-replica-death": scenario_replica_death,
    "fleet-slow-replica": scenario_slow_replica,
    "fleet-retry-storm": scenario_retry_storm,
    "fleet-double-claim": scenario_double_claim,
}


def _router_counts(stats: dict | None) -> dict | None:
    """The deterministic slice of router stats (timing-shaped fields —
    per-replica forwarded splits under rr, breaker cooldowns — excluded)."""
    if stats is None:
        return None
    return {
        "received": stats.get("received"),
        "answered": dict(sorted((stats.get("answered") or {}).items())),
        "retries": stats.get("retries"),
        "hedges": stats.get("hedges"),
        "late_answers": stats.get("late_answers"),
        "parked_total": stats.get("parked_total"),
        "handoff_lost": stats.get("handoff_lost"),
        "handoffs": [
            {"replica": h.get("replica"),
             "claimed": h.get("claimed"),
             "replayed": h.get("replayed"),
             "redispatched": h.get("redispatched")}
            for h in (stats.get("handoffs") or [])
        ],
    }


def run_fleet_scenario(name: str, seed: int, workdir: str | None = None,
                       quick: bool = False) -> dict:
    """Run ONE fleet scenario under a fresh seeded controller with a
    private access log; returns its normalized (deterministic) summary —
    the same contract as chaos/scenarios.run_scenario, so the drill's
    same-seed double run can demand byte equality."""
    fn = FLEET_SCENARIOS[name]
    workdir = workdir or tempfile.mkdtemp(prefix=f"chaos_{name}_")
    log = os.path.join(workdir, "access.jsonl")
    prev = os.environ.get(obs.RUNS_ENV)
    os.environ[obs.RUNS_ENV] = log
    reg_before = aotcache.registry.stats()
    try:
        with inject.controller(seed) as ctl:
            rep = fn(ctl, workdir, quick)
            schedule = ctl.schedule()
    finally:
        if prev is None:
            os.environ.pop(obs.RUNS_ENV, None)
        else:
            os.environ[obs.RUNS_ENV] = prev
    reg_after = aotcache.registry.stats()
    violations = list(rep.get("violations") or [])
    violations += invariants.registry_monotone(reg_before, reg_after)
    ledger = rep.get("ledger")
    return {
        "scenario": name,
        "seed": seed,
        "outcomes": ledger.kinds() if ledger is not None else None,
        "counts": _router_counts(rep.get("stats")),
        "handoff_ids": list(rep.get("handoff_ids") or []),
        "chaos_schedule": schedule,
        "violations": violations,
        **{k: v for k, v in (rep.get("extra") or {}).items()},
    }
