"""The accounting contracts a chaos scenario must not break.

The serving stack's promise (serve/server.py) is *no silent drop*: every
request that enters ``submit`` leaves through exactly one typed door —
a success response, a typed rejection, or (after a crash) a WAL replay —
and every door writes an access-log line.  Faults are allowed to change
WHICH door; they are never allowed to lose a request or a line.  This
module turns that promise into a checkable function the drill
(tools/chaos_drill.py) and the tests run after every scenario:

1. **No request unaccounted** — each submitted request id observed
   exactly one terminal outcome client-side, and the server's own
   counters balance: ``received + replayed == served + errors + timeouts
   + Σ rejected`` with the queue drained (``queue_depth == 0``).
2. **No lost manifest lines** — every terminal outcome (including each
   replayed request) has at least one runs.jsonl record carrying its id.
3. **Registry stats monotone** — executable-registry counters
   (hits/misses/evictions/disk_*/corrupt_healed) never decrease across
   the scenario: a fault may add misses or heals, it may not rewind
   history.

Violations are returned as human-readable strings (empty list = clean);
the drill sums them into the ``chaos_invariant_violations`` metric.
"""

from __future__ import annotations

from blockchain_simulator_tpu.utils import obs

# Counters that must never decrease across a scenario (invariant 3).
MONOTONE_KEYS = (
    "hits", "misses", "evictions", "disk_hits", "disk_misses",
    "disk_saves", "disk_errors", "corrupt_healed",
)

# Span attrs that are deterministic under a scripted scenario and so may
# ride the normalized span tree (everything else — durations, batch
# occupancy/bucket/mode, retry attempt counts — is timing-shaped and
# excluded, the same rule the scenario summaries apply to stats).
_SPAN_NORM_ATTRS = ("id", "outcome", "replayed", "replay", "hedge")


def normalize_spans(spans) -> list[str]:
    """Timing-free span-tree summary (utils/telemetry.py records): one
    sorted string per *serving* span — its root-to-leaf name path plus
    the deterministic attrs — so two same-seed scenario runs must
    produce byte-equal lists (the drill's determinism gate now covers
    span trees, ISSUE 14 satellite).

    ``sweep.*`` spans are excluded: a deadline-abandoned chunk attempt
    closes its span whenever the abandoned thread finishes, which can
    land inside one run's capture window and outside the other's — the
    journal's ``event`` lines are that trail's deterministic record."""
    by_id: dict[tuple, dict] = {}
    recs = []
    for rec in spans:
        if rec.get("kind") != "span":
            continue
        name = str(rec.get("name"))
        if name.startswith("sweep."):
            continue
        by_id[(rec.get("trace"), rec.get("id"))] = rec
        recs.append(rec)
    out = []
    for rec in recs:
        path = [str(rec.get("name"))]
        seen = {rec.get("id")}
        parent = by_id.get((rec.get("trace"), rec.get("parent")))
        while parent is not None and parent.get("id") not in seen:
            path.append(str(parent.get("name")))
            seen.add(parent.get("id"))
            parent = by_id.get((parent.get("trace"), parent.get("parent")))
        attrs = rec.get("attrs") or {}
        kept = ";".join(
            f"{k}={attrs[k]}" for k in _SPAN_NORM_ATTRS if k in attrs
        )
        out.append("/".join(reversed(path))
                   + f"[{kept}]" + f"~{rec.get('status')}")
    return sorted(out)


def _counter_sum(snapshot: dict, name: str) -> float:
    """Sum a counter family (bare name + every label set) out of a
    telemetry.metrics.snapshot()."""
    total = 0.0
    for key, v in (snapshot.get("counters") or {}).items():
        if key == name or key.startswith(name + "{"):
            total += v
    return total


def check_telemetry(before: dict, after: dict,
                    lost_admissions: int = 0) -> list[str]:
    """The metrics-registry accounting contract (utils/telemetry.py),
    on two ``telemetry.metrics.snapshot()`` brackets of a scenario:

    1. **Conservation** — the serve counter deltas balance exactly like
       the Ledger: ``received + replayed == answered + rejected`` (every
       admission the scenario's servers saw left through a counted
       door).  ``lost_admissions`` is the crash allowance: a scenario
       that kills a server with admitted-but-unanswered requests
       declares exactly how many admissions died with it (their WAL
       replays re-enter through the ``replayed`` counter) — the balance
       must then be off by exactly that many, no more, no fewer.
    2. **Monotone** — no counter delta is negative (telemetry counters
       never rewind, the registry-stats rule applied to telemetry).
    """
    violations: list[str] = []
    deltas = {}
    for name in ("blocksim_serve_received_total",
                 "blocksim_serve_replayed_total",
                 "blocksim_serve_answered_total",
                 "blocksim_serve_rejected_total"):
        deltas[name] = _counter_sum(after, name) - _counter_sum(before, name)
        if deltas[name] < 0:
            violations.append(
                f"telemetry counter {name!r} ran backwards "
                f"(delta {deltas[name]})")
    entered = (deltas["blocksim_serve_received_total"]
               + deltas["blocksim_serve_replayed_total"])
    left = (deltas["blocksim_serve_answered_total"]
            + deltas["blocksim_serve_rejected_total"])
    if entered != left + lost_admissions:
        violations.append(
            f"telemetry counters do not reconcile: received+replayed="
            f"{entered} but answered+rejected={left} with "
            f"{lost_admissions} declared crash-lost admissions "
            f"(deltas: {deltas})")
    return violations


class Ledger:
    """Client-side record of every submission a scenario made: one
    *attempt* per ``submitted()`` call (the same id may legitimately be
    submitted twice — a client retry, or a poison resubmission), one
    terminal outcome filled per attempt as responses land.  The checker
    demands exactly one outcome per attempt — zero means a lost request,
    two means a double answer."""

    def __init__(self):
        # id -> one outcome list per submission attempt, oldest first
        self.attempts: dict[str, list[list[str]]] = {}

    def submitted(self, req_id: str) -> None:
        self.attempts.setdefault(str(req_id), []).append([])

    def record(self, req_id: str, response: dict) -> None:
        """Record the uniform response body (ok or typed error) against
        the oldest still-unanswered attempt of this id; a surplus answer
        piles onto the newest attempt, which the checker flags."""
        kind = "ok" if response.get("status") == "ok" \
            else str(response.get("kind"))
        slots = self.attempts.setdefault(str(req_id), [[]])
        for slot in slots:
            if not slot:
                slot.append(kind)
                return
        slots[-1].append(kind)

    def record_error(self, req_id: str, err: Exception) -> None:
        """Record a typed ServeError raised by ``submit``."""
        self.record(str(req_id), {
            "status": "error", "kind": getattr(err, "kind", "internal-error"),
        })

    def kinds(self) -> dict[str, list[str]]:
        """id -> outcome kinds across attempts in submission order, for
        the drill's determinism comparison."""
        return {
            k: [kind for slot in v for kind in slot]
            for k, v in sorted(self.attempts.items())
        }


def registry_monotone(before: dict, after: dict) -> list[str]:
    """Invariant 3 on two aotcache stats snapshots."""
    violations = []
    for key in MONOTONE_KEYS:
        b, a = before.get(key, 0) or 0, after.get(key, 0) or 0
        if a < b:
            violations.append(
                f"registry counter {key!r} ran backwards: {b} -> {a}"
            )
    return violations


def _stats_balance(stats: dict) -> list[str]:
    """Invariant 1, server side: the terminal counters cover every
    admission (fresh and replayed) with nothing left in the queue."""
    violations = []
    depth = stats.get("queue_depth", 0)
    if depth != 0:
        violations.append(f"queue_depth {depth} != 0 after quiescence")
    entered = stats.get("received", 0) + stats.get("replayed", 0)
    left = (
        stats.get("served", 0) + stats.get("errors", 0)
        + stats.get("timeouts", 0)
        + sum((stats.get("rejected") or {}).values())
    )
    if entered != left:
        violations.append(
            f"request accounting broken: received+replayed={entered} but "
            f"served+errors+timeouts+rejected={left} "
            f"(stats: { {k: stats.get(k) for k in ('received', 'replayed', 'served', 'errors', 'timeouts', 'rejected')} })"
        )
    return violations


def ledger_complete(ledger: Ledger) -> list[str]:
    """Invariant 1, client side: exactly one terminal outcome per
    submission attempt — zero is a lost request, two is a double answer
    (shared by :func:`check_server` and :func:`check_fleet`)."""
    violations = []
    for req_id, attempts in ledger.attempts.items():
        for i, slot in enumerate(attempts):
            if len(slot) != 1:
                violations.append(
                    f"request {req_id!r} attempt {i} has {len(slot)} "
                    f"terminal outcomes {slot} (exactly one required)"
                )
    return violations


def check_fleet(
    ledger: Ledger | None,
    router_stats: dict,
    log_path=None,
    handoff_ids=(),
) -> list[str]:
    """The fleet-wide accounting contracts (serve/router.py + fleet.py):

    1. **Exactly one terminal outcome per admission** — client-side
       (ledger) and router-side: every received request is answered
       exactly once (``received == Σ answered``; late duplicate answers
       are *dropped*, counted in ``late_answers``, never delivered).
    2. **Handoff exactly once fleet-wide** — each dead-WAL id appears in
       exactly ONE completed handoff's replay set, and (``log_path``)
       has exactly ONE access-log line marked ``"replayed": true`` — no
       id is replayed twice, none is lost.
    3. **Claims are exclusive** — no WAL reports more than one claiming
       handoff (the lease rule serve/fleet.py enforces on disk).
    """
    violations: list[str] = []
    if ledger is not None:
        violations += ledger_complete(ledger)
    received = router_stats.get("received", 0)
    answered = sum((router_stats.get("answered") or {}).values())
    if received != answered:
        violations.append(
            f"router accounting broken: received={received} but "
            f"answered={answered} ({router_stats.get('answered')})"
        )
    handoffs = router_stats.get("handoffs") or []
    replay_counts: dict[str, int] = {}
    claims_by_wal: dict[str, int] = {}
    for h in handoffs:
        if h.get("claimed"):
            wal = str(h.get("wal"))
            claims_by_wal[wal] = claims_by_wal.get(wal, 0) + 1
        for rid in list(h.get("replayed") or []) \
                + list(h.get("redispatched") or []):
            replay_counts[str(rid)] = replay_counts.get(str(rid), 0) + 1
    for wal, n in claims_by_wal.items():
        if n > 1:
            violations.append(
                f"WAL {wal!r} claimed by {n} handoffs (lease must win "
                f"exactly once)")
    for rid, n in replay_counts.items():
        if n > 1:
            violations.append(
                f"id {rid!r} replayed {n} times across handoffs")
    for rid in handoff_ids:
        if replay_counts.get(str(rid), 0) != 1:
            violations.append(
                f"handoff id {rid!r} replayed "
                f"{replay_counts.get(str(rid), 0)} times (want exactly 1)")
    if log_path is not None:
        recs = obs.read_jsonl(log_path)
        for rid in handoff_ids:
            marked = sum(1 for r in recs
                         if str(r.get("id")) == str(rid)
                         and r.get("replayed") is True)
            if marked != 1:
                violations.append(
                    f"handoff id {rid!r} has {marked} replayed-marked "
                    f"access-log lines (want exactly 1)")
    return violations


def check_sweep_journal(
    journal,
    expected_keys=(),
    expected_rows: int | None = None,
) -> list[str]:
    """The durable-sweep accounting contracts (parallel/journal.py):

    1. **Each chunk key journaled at most once** — completed chunks are
       skipped on resume, so a second valid line for one key means a
       completed chunk was recomputed (the recompute-at-most-one rule
       broken) or double-appended.
    2. **Every journaled row checksums clean** — a chunk line whose rows
       fail their checksums is bit rot or a torn write that PARSED; the
       reader already demotes it to recompute, the checker reports it.
    3. **Coverage** — every ``expected_keys`` chunk is present and valid,
       and (``expected_rows``) the valid chunks carry that many rows
       total.
    4. **Events well-formed** — every supervisor event line names a known
       transition, so the degrade trail is machine-readable.
    """
    from blockchain_simulator_tpu.parallel import journal as journal_mod

    violations: list[str] = []
    lines = journal.chunk_lines()
    seen: dict[str, int] = {}
    for rec in lines:
        key = str(rec.get("key"))
        seen[key] = seen.get(key, 0) + 1
        rows, sums = rec.get("rows"), rec.get("sums")
        if not isinstance(rows, list) or not isinstance(sums, list) \
                or len(rows) != len(sums):
            violations.append(f"chunk {key!r} line is malformed")
            continue
        bad = sum(1 for r, s in zip(rows, sums)
                  if journal_mod.row_checksum(r) != s)
        if bad:
            violations.append(
                f"chunk {key!r} has {bad}/{len(rows)} rows failing their "
                f"checksum")
    for key, n in seen.items():
        if n > 1:
            violations.append(
                f"chunk {key!r} journaled {n} times (completed chunks "
                f"must never recompute)")
    done = journal.completed()
    for key in expected_keys:
        if str(key) not in done:
            violations.append(f"expected chunk {key!r} missing/invalid")
    if expected_rows is not None:
        total = sum(len(rows) for rows in done.values())
        if total != expected_rows:
            violations.append(
                f"journal carries {total} valid rows, expected "
                f"{expected_rows}")
    known = {"deadline", "probe", "retry", "degrade", "failed", "error"}
    for ev in journal.events():
        if ev.get("event") not in known:
            violations.append(f"unknown supervisor event {ev.get('event')!r}")
    return violations


def check_query_trail(result: dict, journal=None,
                      expect_monotone: bool = True) -> list[str]:
    """The adaptive-query accounting contracts (query/engine.py):

    1. **Trail well-formed** — steps numbered consecutively from 0, one
       verdict per probed value, and no value ever evaluated twice (the
       memoization rule: a refinement loop that re-probes a value is
       wasting dispatches or disagreeing with itself).
    2. **Points complete** — the evaluation trail carries exactly one
       metrics row per (probed value, seed).
    3. **Answer consistent** — the reported boundary agrees with the
       recorded verdicts: the surviving side really passed, the failing
       side really failed, and a fully-narrowed bracket is exactly one
       step wide.
    4. **Key hygiene** — every chunk key ends in its step's ``+q<step>``
       suffix (parallel/journal.query_key_suffix), so query chunks can
       never collide with grid (pure hex) or probe (``+p``) chunks; with
       ``journal`` given, every trail key is present and valid there
       (run :func:`check_sweep_journal` separately for the journal-side
       duplicate/checksum rules).
    5. **Monotone** (``expect_monotone``) — the search observed no
       verdict ordered against the monotone-predicate assumption
       (KNOWN_ISSUES.md documents when to relax this).
    """
    violations: list[str] = []
    trail = result.get("trail")
    if not isinstance(trail, list) or not trail:
        return [f"query trail missing/empty: {type(trail).__name__}"]
    query = result.get("query") or {}
    answer = result.get("answer") or {}
    seeds = list(query.get("seeds") or [])
    verdicts: dict[int, bool] = {}
    for i, step in enumerate(trail):
        if step.get("step") != i:
            violations.append(
                f"trail step {i} numbered {step.get('step')!r}")
        values = step.get("values") or []
        sv = step.get("verdicts") or []
        if sorted(v for v, _ in sv) != sorted(values):
            violations.append(
                f"step {i} verdicts {sv} do not cover values {values}")
        for v, ok in sv:
            if v in verdicts:
                violations.append(
                    f"value {v} evaluated twice (step {i} re-probed it)")
            verdicts[int(v)] = bool(ok)
        sfx = f"+q{i}"
        for key in step.get("keys") or []:
            if not str(key).endswith(sfx):
                violations.append(
                    f"step {i} chunk key {key!r} lacks the {sfx!r} suffix")
            elif journal is not None \
                    and str(key) not in journal.completed():
                violations.append(
                    f"step {i} chunk {key!r} missing/invalid in journal")
    points = result.get("points")
    if points is not None:
        want = {(v, s) for v in verdicts for s in seeds}
        got = [(p.get("value"), p.get("seed")) for p in points]
        if len(got) != len(set(got)) or set(got) != want:
            violations.append(
                f"points cover {len(set(got))}/{len(got)} unique "
                f"(value, seed) pairs, expected exactly {len(want)}")
    low_keys = {"max_f_surviving": ("f_max", "first_failing"),
                "cliff_locate": ("last_true", "first_false"),
                "min_k_finality": ("last_failing", "k_min")}
    kind = query.get("kind")
    lo_k, hi_k = low_keys.get(kind, (None, None))
    if lo_k is not None:
        low, high = answer.get(lo_k), answer.get(hi_k)
        ok_low = kind != "min_k_finality"  # low side passes except min_k
        if low is not None and verdicts.get(low) is not ok_low:
            violations.append(
                f"answer {lo_k}={low} contradicts its verdict "
                f"{verdicts.get(low)}")
        if high is not None and verdicts.get(high) is ok_low:
            violations.append(
                f"answer {hi_k}={high} contradicts its verdict "
                f"{verdicts.get(high)}")
        if low is not None and high is not None and high != low + 1:
            violations.append(
                f"bracket not fully narrowed: {lo_k}={low}, {hi_k}={high}")
    run = result.get("run") or {}
    if expect_monotone and run.get("monotonicity_violations", 0):
        violations.append(
            f"{run['monotonicity_violations']} monotonicity violation(s) "
            f"observed during the search")
    return violations


def check_server(
    ledger: Ledger | None,
    stats: dict,
    log_path=None,
    registry_before: dict | None = None,
    registry_after: dict | None = None,
    replayed_ids=(),
) -> list[str]:
    """Run every invariant a scenario can supply evidence for; returns the
    violation list (empty = clean).

    ``ledger`` — the scenario's client-side submissions (None skips 1a);
    ``stats`` — ``ScenarioServer.stats()`` at quiescence;
    ``log_path`` — the scenario's runs.jsonl access log (None skips 2);
    ``registry_before/after`` — aotcache snapshots bracketing the run;
    ``replayed_ids`` — ids the scenario expects WAL replay to answer.
    """
    violations: list[str] = []
    if ledger is not None:
        violations += ledger_complete(ledger)
    violations += _stats_balance(stats)
    if log_path is not None:
        recs = obs.read_jsonl(log_path)
        logged = {str(r.get("id")) for r in recs if r.get("id") is not None}
        replay_logged = {
            str(r.get("id")) for r in recs if r.get("replayed") is True
        }
        if ledger is not None:
            for req_id in ledger.attempts:
                if req_id not in logged:
                    violations.append(
                        f"request {req_id!r} has no access-log line "
                        f"(manifest lost)"
                    )
        for req_id in replayed_ids:
            if str(req_id) not in replay_logged:
                violations.append(
                    f"replayed request {req_id!r} has no replayed "
                    f"access-log line"
                )
    if registry_before is not None and registry_after is not None:
        violations += registry_monotone(registry_before, registry_after)
    return violations


def check_consensus_probes(summaries, max_lag: int | None = None) -> list[str]:
    """The ISSUE 17 consensus-safety invariant: no in-program monitor
    fired across a scenario's probed runs.

    ``summaries`` is an iterable of probe summaries (obsim/schema.
    summarize output — a row's ``m["probe"]``, a serve response's
    ``metrics["probe"]``, or a bare summary dict).  Each summary's
    safety counters (``viol_agreement``, ``viol_quorum`` — already
    host-aggregated into its ``"violations"`` total) must be zero:
    these are the on-device twins of the host agreement checks, so a
    nonzero count under a crash/delay drill means the fault injection
    broke consensus SAFETY, not just liveness — always a violation.

    ``liveness_lag`` (progress-free trailing window, in samples) is a
    gauge, not a safety counter: it is gated only when the caller sets
    ``max_lag`` (scenario-specific — a crash drill legitimately stalls
    progress; a fault-free soak should not).

    Returns human-readable strings, empty when clean — the drill sums
    them into ``chaos_invariant_violations`` like every other check."""
    violations: list[str] = []
    for i, summary in enumerate(summaries):
        if not isinstance(summary, dict):
            violations.append(f"probe summary {i} is not a dict: {summary!r}")
            continue
        s = summary["probe"] if "probe" in summary else summary
        who = (f"run {i} ({s.get('protocol', '?')}/"
               f"{s.get('topology', '?')})")
        mon = s.get("monitors")
        if mon is None:
            violations.append(f"{who}: no monitors in probe summary "
                              f"(probes disarmed or monitors=False)")
            continue
        n_viol = s.get("violations", 0)
        if n_viol:
            detail = {k: mon.get(k) for k in ("viol_agreement", "viol_quorum")}
            violations.append(
                f"{who}: {n_viol} consensus safety violation(s) {detail}"
            )
        if max_lag is not None:
            lag = mon.get("liveness_lag")
            lag_max = max(_flat_ints(lag)) if lag is not None else None
            if lag_max is not None and lag_max > max_lag:
                violations.append(
                    f"{who}: liveness lag {lag_max} samples exceeds "
                    f"max_lag={max_lag}"
                )
    return violations


def _flat_ints(v):
    """Flatten a summary leaf (int, or nested lists from committee /
    multi-lane summaries) to a flat int list."""
    if isinstance(v, (list, tuple)):
        out = []
        for x in v:
            out.extend(_flat_ints(x))
        return out or [0]
    return [int(v)]
