"""Config/fault-sweep parallelism: batch whole simulations.

The outer-axis analog of BASELINE config 4 ("Byzantine-fault sweep f=0..n/3,
pmap over fault configs"): many seeds of one config run as a single vmapped
program; over a mesh, the batch axis shards over ``sweep`` (``spmd_axis_name``)
while the node axis shards over ``nodes``.

Fault *counts* (crash counts, Byzantine counts) are traced per-run OPERANDS
(runner.make_dyn_sim_fn): an f-sweep over any number of fault levels is ONE
vmapped executable over the (fault level, seed) cross product — where it used
to compile one program per f value (~20 s of XLA per point on this box for
seconds of simulation).  Fault *structure* (drop_prob, byz_forge, byz_copies)
stays static: :func:`run_fault_sweep` groups its fault configs by canonical
structure (models/base.canonical_fault_cfg) and compiles once per group.
Results are bit-equal to the per-point static path (pinned in
tests/test_zsweep_cache.py); the mixed shard sim keeps the static path.

Bit-equality caveat: under ``stat_sampler="exact"`` (and the whole edge
path) equality is exact — integer draws whose arithmetic is identical in
both programs.  The ``"normal"`` CLT sampler (auto at n >= 4096) has a
float path that XLA may arrange differently in the two compiled programs:
with the SAME keys, one message can land one delay bucket over, moving a
commit tail by ±1 tick (measured once across a 22-point 10k sweep,
``tools/sweep_cache_bench.py`` notes) — the same jitter class
models/pbft_round.py documents vs the tick engine; counts and milestones
are unaffected.

Durability: every dynamic-operand sweep accepts ``journal=`` (a
parallel/journal.SweepJournal) — execution then chunks one fault level
(seed tile) per fsynced journal append, a restarted identical sweep
skips completed chunks (recompute <= the one in-flight chunk, rows
bit-equal under the exact sampler), and ``supervise=`` adds per-chunk
deadlines with bounded retry and a recorded degrade arm.  See
parallel/journal.py for the journal-vs-WAL semantics.

Compiled programs live in the unified executable registry
(utils/aotcache.py) — hit/miss stats land on every run manifest.  The
same-structure grouping below is pinned at the IR level by the graph
audit's divergence twins (lint/graph/programs.py ``sweep_dynf.*``): fault
configs differing only in counts must trace to ONE jaxpr fingerprint, or
``lint.graph`` fails ``registry-key-divergence`` in CI.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from blockchain_simulator_tpu.chaos import inject
from blockchain_simulator_tpu.models.base import canonical_fault_cfg, sim_metrics
from blockchain_simulator_tpu.parallel import journal as journal_mod
from blockchain_simulator_tpu.parallel import partition
from blockchain_simulator_tpu.parallel.mesh import NODES_AXIS, SWEEP_AXIS
from blockchain_simulator_tpu.runner import (
    UnbatchableConfigError,
    check_batchable,
    make_dyn_sim_fn,
    make_sim_fn,
    make_topo_dyn_sim_fn,
    topo_tables_inslot,
)
from blockchain_simulator_tpu.utils import aotcache, obs, telemetry
from blockchain_simulator_tpu.utils.config import SimConfig


@aotcache.cached_factory("sweep-batched")
def _batched_fn(cfg: SimConfig, mesh=None):
    """Jitted ``batched(keys) -> finals`` for one (cfg, mesh): registry-
    cached so repeated sweeps of one config reuse the compiled program
    instead of building a fresh jit wrapper per call (jaxlint
    static-arg-recompile-hazard; runner.make_sim_fn convention)."""
    if mesh is None:
        return jax.jit(jax.vmap(make_sim_fn(cfg)))
    from blockchain_simulator_tpu.parallel.shard import make_sharded_sim_fn

    return jax.jit(
        jax.vmap(make_sharded_sim_fn(cfg, mesh), spmd_axis_name=SWEEP_AXIS)
    )


@aotcache.cached_factory("sweep-batched-dynf")
def dyn_batched_fn(cfg: SimConfig):
    """Jitted ``batched(keys, n_crashed[B], n_byzantine[B]) -> finals`` —
    THE one executable of a whole fault-count sweep (``cfg`` must already be
    canonical; one registry entry per fault structure).  Public: the
    scenario server's micro-batched dispatch (serve/dispatch.py) rides the
    same registry entry as the sweeps, so a sweep warms the server and
    vice versa."""
    return jax.jit(jax.vmap(make_dyn_sim_fn(cfg)))


# back-compat alias (pre-serve name; lint/graph/programs.py and external
# callers were updated, but keep the old spelling importable)
_dyn_batched_fn = dyn_batched_fn


@aotcache.cached_factory("partition-dyn-sweep")
def mesh_dyn_batched_fn(cfg: SimConfig, mesh):
    """Mesh-partitioned ``batched(keys[B], n_crashed[B], n_byzantine[B]) ->
    finals``: the (fault level, seed) batch axis sharded over the mesh's
    ``sweep`` axis, through the partition layer (parallel/partition.py).

    Three arms, all one registry entry per (fault structure, mesh) — the
    mesh rides the key, so the one-executable-per-fault-structure contract
    holds per mesh:

    - **mesh of size 1**: degenerates to :func:`dyn_batched_fn` — the
      PR 4 single-device program itself, so results are trivially
      bit-identical to the plain vmapped sweep (the registry serves the
      ``sweep-batched-dynf`` entry; sweeps and serving stay warm).
    - **sweep-only mesh** (nodes axis 1): shard_map over the batch axis
      with a per-device body of ``lax.map`` over the UNVMAPPED dyn sim.
      The unvmapped body keeps its dynamic-update-slice pushes as plain
      DUS instead of vmap's scatter lowering (KNOWN_ISSUES #0b: XLA:CPU
      serializes scatter) — measured ~2.3x per lane over the vmapped
      program at 10k nodes on the CPU mesh, before any device parallelism.
    - **nodes axis > 1**: the explicit-sharding pjit arm — batch over
      ``sweep``, each lane's node dim over ``nodes``
      (partition.batched_out_shardings), XLA GSPMD partitioning the scan:
      the "node axis optionally sharded for large n" option.

    Callers must pad the batch to a multiple of the sweep axis size
    (partition.pad_points; run_dyn_points does).  Bit-equality to the
    single-device path is pinned under the exact sampler in
    tests/test_zzpartition.py — the normal CLT sampler keeps the module
    caveat's ±1-tick float latitude."""
    fn = make_dyn_sim_fn(cfg)
    if partition.mesh_size(mesh) == 1:
        return dyn_batched_fn(cfg)
    if int(dict(mesh.shape).get(NODES_AXIS, 1)) > 1:
        batched = jax.vmap(fn)
        b = max(partition.sweep_axis_size(mesh), 1)
        keys_sds = jax.eval_shape(
            lambda: jax.vmap(jax.random.key)(jnp.arange(b, dtype=jnp.uint32))
        )
        cnt_sds = jax.ShapeDtypeStruct((b,), jnp.int32)
        outs = jax.eval_shape(batched, keys_sds, cnt_sds, cnt_sds)
        from jax.sharding import PartitionSpec as P

        lane = P(SWEEP_AXIS) if partition.sweep_axis_size(mesh) > 1 else P()
        return partition.partition(
            batched, mesh,
            in_shardings=(lane, lane, lane),
            out_shardings=partition.batched_out_shardings(cfg, mesh, outs),
        )

    # per-device: local lanes run SEQUENTIALLY through the unvmapped
    # program (lax.map = scan of the solo body, constant program size)
    body = partition.seq_map(fn)

    from jax.sharding import PartitionSpec as P

    lane = P(SWEEP_AXIS)
    return partition.partition(
        body, mesh, in_specs=(lane, lane, lane), out_specs=lane
    )


@aotcache.cached_factory("shard-topo-sim")
def sharded_topo_sim_fn(cfg: SimConfig, mesh, layout: str = "exchange"):
    """Node-dim mesh-sharded topology program: ``sim(key, n_crashed,
    n_byzantine) -> final_state`` for a kregular or committee config with
    the overlay partitioned over the mesh's ``nodes`` axis — the 10M-node
    arm of ROADMAP item 3 (the [N, K] tables and per-edge tensors stop
    living on one device).  ``cfg`` must already be fault-canonical
    (models/base.canonical_fault_cfg — the :func:`run_sharded_topo` /
    bench callers canonicalize): ONE registry entry per (protocol,
    topology, fault structure, mesh), fault counts ride the operands.

    Three arms:

    - **mesh of size 1**: ``jax.jit(make_dyn_sim_fn(cfg))`` — literally
      the single-device program (tables as trace constants, the PR 15
      path), so the degenerate case is bit-identical by construction.
    - **kregular, nodes > 1**: the explicit-sharding pjit arm.  The body
      is ``runner.make_topo_dyn_sim_fn`` — the tick engine with the
      ``[N, K]`` overlay tables as real OPERANDS (ops/gatherdeliv.
      table_operands; KNOWN_ISSUES #0n's escape hatch) — compiled through
      ``partition.partition`` with the tables and every node-dim final
      sharded ``P(NODES_AXIS)`` (partition.node_dim_rules; the protocol's
      ``GLOBAL_FIELDS`` replicate).  The model traces in global view
      (``cfg.mesh_axis`` stays None), so the traced computation — RNG
      draw shapes included — is identical to the single-device program,
      hence bit-equal results under the exact sampler
      (tests/test_zzshardtopo).  Two data-movement layouts:

      * ``layout="exchange"`` (the default): cross-shard neighbor reads
        route through a ``partition.NeighborExchange`` — owner-bucketed
        ``all_to_all`` islands (plans from topo/spec.owner_bucket_plan
        ride as extra ``P(NODES_AXIS)`` operands) — and the table rows
        pass through ``local_tables(ids=None)`` untaken, so no tensor is
        ever materialized at global shape: prologue and per-tick comms
        are O(N*K/D) per device instead of the full-table all-gather.
        The exchange is a pure permutation + local gather, bit-equal to
        the global gather by construction.
      * ``layout="regather"``: the pre-exchange behavior — neighbor
        reads stay plain ``jnp.take`` gathers for XLA GSPMD to
        partition, which re-gathers the ``P(nodes)`` tables/state on
        every device (the retired ``table-regather`` debt).  Kept so
        tools/gather_locality_bench.py can measure old-vs-new inside one
        artifact, and as the fallback if an exchange regression ever
        needs bisecting.

      The sharded tables (and exchange plans) are device_put once per
      factory call and closed over; ``sim.partitioned`` /
      ``sim.table_avals`` expose the inner pjit callable and its sharded
      operand avals so the graph/comms audits trace the
      operands-as-arguments jaxpr (zero large-jaxpr-constant findings).
      Uneven ``n % shards`` is fine: explicit NamedShardings must divide
      evenly in this jax, so the factory zero-pads the table rows to the
      next multiple (the wrapper slices them back before the engine sees
      them — padding rows are never read; exchange plans are built on the
      padded tables and stay padded) and any final whose node dim stays
      uneven replicates instead of sharding.
    - **committee, nodes > 1**: shard_map over the STACKED committee axis
      (``committees % shards == 0`` required): each device runs
      ``topo/committee.stacked_body`` — the same ``lax.map`` of the
      unvmapped inner engine — on its slice of the ``[C]`` key stack and
      ``[C, m]`` fault masks.  Committee bodies never communicate before
      the host-side outer aggregate, and the per-committee keys are
      computed from the GLOBAL committee index before the shard_map, so
      every lane's stream matches the single-device program bit for bit.
      ``cfg.mesh_axis`` stays None (utils/config.py pins committee configs
      unsharded at the NODE level — this arm shards the committee STACK,
      which is the hierarchy's node-dim analog)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from blockchain_simulator_tpu.models import base as base_model
    from blockchain_simulator_tpu.ops import gatherdeliv as gd

    if cfg.topology not in ("kregular", "committee"):
        raise ValueError(
            f"sharded_topo_sim_fn shards the sparse/hierarchical overlays; "
            f"topology={cfg.topology!r} has no node-dim topo structure "
            "(dense configs ride parallel/shard.py, gossip is unsharded)"
        )
    if partition.mesh_size(mesh) == 1:
        return jax.jit(make_dyn_sim_fn(cfg))
    n_shards = int(dict(mesh.shape).get(NODES_AXIS, 1))
    if n_shards <= 1:
        raise ValueError(
            "sharded_topo_sim_fn partitions the node dimension: the mesh "
            f"needs nodes > 1 (got shape {dict(mesh.shape)}); sweep-axis "
            "meshes belong to mesh_dyn_batched_fn"
        )

    if cfg.topology == "committee":
        from blockchain_simulator_tpu.topo import committee

        c, m = cfg.committees, cfg.n // cfg.committees
        if c % n_shards != 0:
            raise ValueError(
                f"committees={c} not divisible by {n_shards} node shards "
                "(the committee stack shards whole committees)"
            )

        def body(keys, alive_cm, honest_cm):
            return committee.stacked_body(cfg, keys, alive_cm, honest_cm)

        keys_sds = jax.eval_shape(
            lambda: committee._committee_keys(jax.random.key(0), c)
        )
        mask_sds = jax.eval_shape(
            lambda: jax.tree.map(
                lambda x: x.reshape(c, m),
                base_model.dyn_fault_masks(cfg.n, jnp.int32(0), jnp.int32(0)),
            )
        )
        outs = jax.eval_shape(body, keys_sds, *mask_sds)
        out_specs = partition.match_partition_rules(
            partition.node_dim_rules(), outs
        )
        lane = P(NODES_AXIS)
        shmapped = partition.partition(
            body, mesh, in_specs=(lane, lane, lane), out_specs=out_specs,
            wrap_jit=False,
        )

        @jax.jit
        def sim(key, n_crashed, n_byzantine):
            alive, honest = base_model.dyn_fault_masks(
                cfg.n, n_crashed, n_byzantine
            )
            keys = committee._committee_keys(key, c)
            return shmapped(keys, alive.reshape(c, m), honest.reshape(c, m))

        return sim

    if layout not in ("exchange", "regather"):
        raise ValueError(
            f"sharded_topo_sim_fn layout must be 'exchange' or 'regather', "
            f"got {layout!r}"
        )
    proto = base_model.get_protocol(cfg.protocol)
    tables = gd.table_operands(cfg, inslot=topo_tables_inslot(cfg))
    # explicit NamedShardings must divide evenly (jax 0.4 pjit aval
    # check) — zero-pad the table rows to the next multiple of the shard
    # count and slice back inside the program (pad rows are never read:
    # every gather indexes ids < n)
    pad = (-cfg.n) % n_shards
    if pad:
        tables = tuple(
            np.pad(t, ((0, pad),) + ((0, 0),) * (t.ndim - 1))
            for t in tables
        )
    n_tables = len(tables)
    if layout == "exchange":
        from blockchain_simulator_tpu.topo import spec as topo_spec

        # plans over the PADDED tables: pad rows only reference row 0 (an
        # extra shipped row at worst), and the exchange output is sliced
        # back to cfg.n rows inside NeighborExchange
        xspec = partition.ExchangeSpec(mesh, cfg.n)
        plans = ()
        for tab in (tables[0], tables[1]):  # "in", "out" — xspec.kinds
            plans += topo_spec.owner_bucket_plan(tab, n_shards)
        inner_fn = make_topo_dyn_sim_fn(cfg, exchange_spec=xspec)
    else:
        plans = ()
        inner_fn = make_topo_dyn_sim_fn(cfg)
    if pad:
        def fn(key, n_crashed, n_byzantine, *ops):
            return inner_fn(
                key, n_crashed, n_byzantine,
                *(t[: cfg.n] for t in ops[:n_tables]), *ops[n_tables:]
            )
    else:
        fn = inner_fn
    operands = tables + plans
    tab_sds = tuple(
        jax.ShapeDtypeStruct(t.shape, jnp.int32) for t in operands
    )
    key_sds = jax.eval_shape(lambda: jax.random.key(0))
    cnt_sds = jax.ShapeDtypeStruct((), jnp.int32)
    outs = jax.eval_shape(fn, key_sds, cnt_sds, cnt_sds, *tab_sds)
    out_shardings = partition.match_partition_rules(
        partition.node_dim_rules(getattr(proto, "GLOBAL_FIELDS", ())), outs
    )
    # finals whose node dim stays uneven can't carry an explicit sharded
    # spec either — replicate those leaves (uneven n only)
    out_shardings = jax.tree.map(
        lambda spec, aval: (
            P()
            if spec
            and spec[0] == NODES_AXIS
            and aval.shape[0] % n_shards != 0
            else spec
        ),
        out_shardings,
        outs,
        is_leaf=lambda x: x is None or isinstance(x, P),
    )
    table_spec = P(NODES_AXIS)
    p = partition.partition(
        fn, mesh,
        in_shardings=(P(), P(), P()) + (table_spec,) * len(operands),
        out_shardings=out_shardings,
    )
    ns = NamedSharding(mesh, table_spec)
    operands_dev = tuple(jax.device_put(t, ns) for t in operands)

    def sim(key, n_crashed, n_byzantine):
        return p(key, n_crashed, n_byzantine, *operands_dev)

    # audit hooks: the graph specs trace `partitioned` with `table_avals`
    # as arguments, so the audited jaxpr carries the tables (and, in
    # exchange layout, the pos/send plans) as operands — the runtime
    # closure above never re-bakes them either (device arrays)
    sim.partitioned = p
    sim.table_avals = tab_sds
    sim.exchange_layout = layout
    return sim


def run_sharded_topo(cfg: SimConfig, mesh, seed: int | None = None):
    """Run one kregular/committee simulation node-dim-sharded over
    ``mesh`` (:func:`sharded_topo_sim_fn`); returns the same metrics dict
    as ``runner.run_simulation`` — bit-equal to it under the exact sampler
    at any mesh size (the tables-as-operands computation is identical and
    the committee stack shards whole committees)."""
    canon = canonical_fault_cfg(cfg)
    sim = sharded_topo_sim_fn(canon, mesh)
    nc = cfg.faults.resolved_n_crashed(cfg.n)
    nb = cfg.faults.n_byzantine
    key = jax.random.key(cfg.seed if seed is None else seed)
    final = jax.block_until_ready(
        sim(key, jnp.int32(nc), jnp.int32(nb))
    )
    return sim_metrics(cfg, final)


@aotcache.cached_factory("multi-seed-tick")
def multi_seed_fn(cfg: SimConfig, n_seeds: int):
    """THE single-device multi-seed Monte Carlo executable:
    ``batched(keys[B], n_crashed[B], n_byzantine[B]) -> finals`` running B
    seeds of one fault structure as ONE dispatch of a ``lax.map`` over the
    UNVMAPPED dyn program (partition.seq_map — the per-device body of the
    mesh sweep arm, without the mesh).

    Why this beats the vmapped ``dyn_batched_fn`` on the tick path
    (ISSUE 13 / ROADMAP item 4): every tick-engine channel push is a
    dynamic-update-slice on a scan-carried ring, and vmap over the batch
    axis lowers each one to XLA generic scatter, which XLA:CPU serializes
    (KNOWN_ISSUES #0b/#0i — the mesh bench measured the scatter-free body
    ~2.3x per lane at 10k nodes on the round path; the tick engine pushes
    3-4 rings per tick, so its gap is wider, see ARTIFACT_tick_bench.json).
    The ``lax.map`` body keeps every push a plain DUS, each lane is the
    batch-1-shaped program (the only shape ever observed to survive the
    TPU batch>=2 hazard, issue #2), and the whole batch costs one Python
    dispatch + one executable.

    ``cfg`` must already be canonical (models/base.canonical_fault_cfg):
    one registry entry per (fault structure, B) — seeds and fault counts
    ride the mapped operands, never the trace (divergence twins pin this,
    lint/graph/programs.py ``multi_seed.*``).  Rows are bit-equal per seed
    to sequential solo runs of ``jit(make_dyn_sim_fn(cfg))`` under the
    exact sampler (tests/test_ztick.py); the "normal" CLT float caveat in
    the module docstring applies unchanged."""
    # n_seeds only keys the registry entry (jit specializes on the operand
    # batch shape either way; keying it keeps hit/miss stats per-(cfg, B)
    # truthful — the one-executable pins count misses around dispatches)
    del n_seeds
    return jax.jit(partition.seq_map(make_dyn_sim_fn(cfg)))


def run_seed_sweep(cfg: SimConfig, seeds, mesh=None):
    """Run ``len(seeds)`` simulations of one config in a single vmapped
    program; returns a list of per-seed metrics dicts."""
    # Every schedule is fully traceable — including round-schedule raft,
    # whose checked handoff is a lax.cond (models/raft_hb.scan_from_init)
    # that vmap lowers to a select: both branches run for the whole batch,
    # so a batched round-schedule raft sweep costs about one tick-engine
    # pass (the fallback branch continues the prefix carry, it does not
    # restart), never more.
    if mesh is not None:
        n_sweep = mesh.shape[SWEEP_AXIS]
        if len(seeds) % n_sweep != 0:
            raise ValueError(
                f"{len(seeds)} seeds not divisible by sweep axis size {n_sweep}"
            )
    keys = jax.vmap(jax.random.key)(jnp.asarray(seeds, jnp.uint32))
    finals = jax.block_until_ready(_batched_fn(cfg, mesh)(keys))
    out = []
    for i, seed in enumerate(seeds):
        final_i = jax.tree.map(lambda x: x[i], finals)
        m = sim_metrics(cfg, final_i)
        # observability routing: a finalized COPY of every sweep row goes to
        # the optional runs.jsonl ($BLOCKSIM_RUNS_JSONL, utils/obs.py); the
        # returned dicts stay pure metrics — tests compare them bit-for-bit
        # against single runs
        obs.record_run({"seed": int(seed), **m}, cfg)
        out.append(m)
    return out


def _dyn_operands(cfg: SimConfig, fc) -> tuple[int, int]:
    """The traced (n_crashed, n_byzantine) operand point of a fault config."""
    return fc.resolved_n_crashed(cfg.n), fc.n_byzantine


def _dispatch_dyn_points(canon: SimConfig, points, record: bool = True,
                         n_out: int | None = None, mesh=None,
                         multi_seed: bool = False, probe=None):
    """ONE un-journaled batched dispatch of a same-structure point list —
    the body :func:`run_dyn_points` either calls directly (no journal) or
    wraps in chunked, supervised, durable execution.  ``multi_seed``
    selects the scatter-free ``lax.map`` program (:func:`multi_seed_fn`)
    over the vmapped one on the single-device path; a mesh dispatch
    already maps sequentially per device, so the flag is a no-op there.
    ``probe`` (an obsim/schema.ProbeConfig) swaps in the armed twin of
    the same arm (obsim/build.py ``consobs-*`` registry entries) and
    attaches a per-row ``"probe"`` summary; monitor violations trip the
    flight recorder host-side (obsim/host.note_violations)."""
    points = list(points)
    # the batched-dispatch chaos point: the drills inject raise/hang/slow
    # here — the exact exception path a real backend fault takes through
    # the sweeps AND the serving degrade machinery (chaos/inject.py)
    inject.chaos_point("sweep.dyn_dispatch", canon=canon, n=len(points))
    if probe is not None:
        from blockchain_simulator_tpu.obsim import build as obsim_build
    if mesh is not None and partition.mesh_size(mesh) > 1 \
            and len(points) == 1:
        # a 1-point list on the mesh path would pad to a full sweep-axis
        # width of duplicate lanes; the single-device program answers it
        # with zero pad waste and rows bit-equal under the exact sampler
        # (the same equivalence the supervised degrade arm relies on)
        mesh = None
    dispatch_points = points
    if mesh is not None and partition.mesh_size(mesh) > 1:
        lanes = max(partition.sweep_axis_size(mesh), 1)
        dispatch_points, _ = partition.pad_points(points, lanes)
        batched = (obsim_build.probed_mesh_fn(canon, probe, mesh)
                   if probe is not None else mesh_dyn_batched_fn(canon, mesh))
    elif multi_seed:
        batched = (obsim_build.probed_batched_fn(canon, probe,
                                                 multi_seed=True)
                   if probe is not None
                   else multi_seed_fn(canon, len(points)))
    else:
        batched = (obsim_build.probed_batched_fn(canon, probe)
                   if probe is not None else dyn_batched_fn(canon))
    keys = jax.vmap(jax.random.key)(
        jnp.asarray([s for _, s in dispatch_points], jnp.uint32)
    )
    ops = [_dyn_operands(cfg, cfg.faults) for cfg, _ in dispatch_points]
    nc = jnp.asarray([o[0] for o in ops], jnp.int32)
    nb = jnp.asarray([o[1] for o in ops], jnp.int32)
    # BLOCKSIM_PROFILE arms a jax.profiler capture around the executable
    # run (utils/telemetry.py; free when disarmed).  A serve flush that
    # routed here is already inside its own profile_region — the nested
    # guard skips this one.
    with telemetry.profile_region("sweep_dispatch"):
        outs = jax.block_until_ready(batched(keys, nc, nb))
    finals, probes = outs if probe is not None else (outs, None)
    out = []
    if n_out is not None:
        points = points[:n_out]
    for i, (cfg_i, seed) in enumerate(points):
        final_i = jax.tree.map(lambda x: x[i], finals)
        m = sim_metrics(cfg_i, final_i)
        if probe is not None:
            from blockchain_simulator_tpu.obsim import host as obsim_host

            m["probe"] = obsim_host.summarize_lane(cfg_i, probe, probes, i)
            obsim_host.note_violations(m["probe"], cfg_i, int(seed))
        if record:
            obs.record_run({"seed": int(seed), **m}, cfg_i)
        out.append(m)
    return out


def _run_chunk(canon, tile, record, n_out, mesh, supervise, journal, key,
               index, multi_seed=False, probe=None):
    """Compute ONE chunk, optionally under the supervisor's deadline →
    retry → degrade state machine (parallel/journal.py).  The
    ``sweep.chunk`` chaos point fires once per ATTEMPT with the arm in
    its ctx, so a drill can wedge exactly the primary arm and watch the
    degrade arm answer."""

    def primary():
        inject.chaos_point("sweep.chunk", key=key, index=index,
                           n=len(tile), arm="primary",
                           mesh=mesh is not None)
        # every chunk ATTEMPT is one span on a chunk-scoped trace
        # (utils/telemetry.py; the ISSUE 14 sweep-side mint point): the
        # post-mortem story "which chunk, which arm, how long" as data
        with telemetry.span("sweep.chunk", key=key, index=index,
                            n=len(tile), arm="primary"):
            return _dispatch_dyn_points(canon, tile, record, n_out, mesh,
                                        multi_seed, probe)

    if supervise is None:
        return primary()

    from blockchain_simulator_tpu.runner import use_round_schedule

    if supervise.checkpoint_dir and len(tile) == 1 \
            and not use_round_schedule(tile[0][0]):
        # the very-long-single-sim arm: tick-level mid-chunk checkpoints
        # (utils/checkpoint.py) — a re-kill resumes MID-chunk from the
        # last segment instead of restarting the whole sim
        cfg_pt, seed_pt = tile[0]

        def degrade():
            inject.chaos_point("sweep.chunk", key=key, index=index,
                               n=len(tile), arm="degrade-checkpoint",
                               mesh=False)
            import os as _os

            from blockchain_simulator_tpu import runner as runner_mod

            with telemetry.span("sweep.chunk", key=key, index=index,
                                n=len(tile), arm="degrade-checkpoint"):
                m, _ = runner_mod.run_dyn_checkpointed(
                    cfg_pt, supervise.checkpoint_every_ms,
                    _os.path.join(supervise.checkpoint_dir, key),
                    seed=seed_pt,
                )
            return [m]
    else:
        # the mesh-shrink arm (partition.py's size-1/no-mesh path): the
        # single-device program is bit-equal under the exact sampler, so
        # a degraded chunk's rows are indistinguishable from healthy ones
        def degrade():
            inject.chaos_point("sweep.chunk", key=key, index=index,
                               n=len(tile), arm="degrade", mesh=False)
            with telemetry.span("sweep.chunk", key=key, index=index,
                                n=len(tile), arm="degrade"):
                return _dispatch_dyn_points(canon, tile, record, n_out,
                                            mesh=None, probe=probe)

    rows, _events = journal_mod.run_supervised(
        primary, degrade, supervise, journal=journal, key=key,
    )
    return rows


def run_dyn_points(canon: SimConfig, points, record: bool = True,
                   n_out: int | None = None, mesh=None, journal=None,
                   chunk_size: int | None = None, supervise=None,
                   multi_seed: bool = False, probe=None,
                   key_suffix: str = "", with_index: bool = False):
    """THE group-dispatch primitive: one vmapped executable over an
    arbitrary list of same-structure ``(cfg, seed)`` points.

    ``points`` is a sequence of ``(cfg, seed)`` pairs whose configs all
    canonicalize to ``canon`` (``canonical_fault_cfg``) — they may differ
    only in fault COUNTS, which become the traced per-lane operands.
    Returns one metrics dict per point, in order, each bit-equal (exact
    sampler; see the module caveat for the normal CLT path) to a solo run
    of the same ``(cfg, seed)``.

    Both the fault sweeps (:func:`run_fault_sweep`, a cross product of
    points) and the scenario server's micro-batched dispatch
    (serve/dispatch.py, whatever compatible requests are queued) route
    through here.  ``record=False`` skips the per-row runs.jsonl hook for
    callers that write their own access-log records (the server does);
    ``n_out`` computes host-side metrics for only the first ``n_out``
    points (the server's bucket-padded lanes are duplicates whose metrics
    would be discarded).

    With ``mesh`` set the batch axis shards over the mesh's sweep axis
    through :func:`mesh_dyn_batched_fn` (parallel/partition.py): the point
    list is padded to a multiple of the sweep axis size by repeating the
    last point (padding lanes ride at the tail, so real-point indices are
    unchanged and pad metrics are never computed).  A mesh of size 1 takes
    the single-device path verbatim.

    **Durable execution** (``journal=``, a parallel/journal.SweepJournal):
    the point list splits into ``chunk_size``-point chunks (default: one
    chunk; the fault sweeps pass one chunk per fault level, aligned up to
    the mesh lanes), each chunk's rows are appended to the journal —
    fsynced, with per-row checksums and the registry ``cache`` block —
    BEFORE the next chunk dispatches, and chunks whose content-addressed
    key (parallel/journal.chunk_key) is already journaled are served from
    the journal without dispatching: a restarted sweep recomputes at most
    the one chunk that was in flight.  Resumed rows ride a JSON round
    trip (ints/floats exact) and are NOT re-recorded to runs.jsonl.
    ``supervise=`` (a journal.ChunkSupervisor) additionally wraps every
    computed chunk in the deadline → retry/backoff → degrade machine,
    with the transitions journaled as ``event`` lines — and works
    without a journal too (chunked + supervised, just not durable).

    The wedged-health fail-fast gate lives on the SWEEP entrypoints
    (:func:`run_fault_sweep` / :func:`run_byzantine_sweep`), not here:
    the scenario server's batched flushes route through this function
    and its admission is already health-gated — raising per flush would
    only be swallowed into an un-gated degrade-to-solo
    (serve/dispatch.run_batch's typed-error wrapper).

    ``multi_seed=True`` dispatches single-device batches through the
    scatter-free ``lax.map`` executable (:func:`multi_seed_fn`) instead of
    the vmapped one — the tick-path throughput arm (ISSUE 13; measured in
    ARTIFACT_tick_bench.json), rows bit-equal under the exact sampler.
    The default stays the vmapped program so existing registry
    trajectories and pins are untouched; ``runner.run_multi_seed`` and
    the sweeps' ``multi_seed=`` kwarg are the opt-ins.

    ``probe=`` (an obsim/schema.ProbeConfig) arms the in-program
    consensus taps: every row gains a ``"probe"`` summary
    (obsim/schema.summarize) and monitor violations trip the flight
    recorder (obsim/host.note_violations).  Primary metrics stay
    bit-equal to the disarmed dispatch — taps consume zero PRNG.  Armed
    flushes journal under a probe-suffixed chunk key, so a journal
    written disarmed never answers an armed flush (and vice versa);
    journal-cached armed rows serve their stored summaries as-written
    without re-firing the violation hook.

    ``key_suffix`` is appended verbatim to every chunk's journal key
    (after the probe suffix) — the namespace hook the query engine
    (query/engine.py) uses to keep refinement chunks (``+q<step>``)
    disjoint from grid chunks over the same canonical structure.

    ``with_index=True`` returns ``(rows, meta)`` instead of bare rows:
    ``meta["rows"][i]`` maps output row ``i`` back to its point —
    ``{"point": index into ``points``, "seed", "key" (journal chunk key
    or None un-journaled), "cached" (served from the journal without
    dispatching)}`` — and ``meta`` carries the dispatch accounting a
    refinement loop needs (``dispatches`` actually fired, ``lanes``
    dispatched including mesh padding, ``pad`` wasted lanes,
    ``chunks`` per-chunk trail).  A 1-point list never pads: it takes
    the single-device path even under a mesh (bit-equal, exact
    sampler)."""
    points = list(points)
    meta = {"rows": [], "chunks": [], "lanes": 0, "dispatches": 0, "pad": 0}

    def _lanes(n: int) -> int:
        if n > 1 and mesh is not None and partition.mesh_size(mesh) > 1:
            axis = max(partition.sweep_axis_size(mesh), 1)
            return -(-n // axis) * axis
        return n

    def _done(rows):
        return (rows, meta) if with_index else rows

    if journal is None and supervise is None:
        rows = _dispatch_dyn_points(canon, points, record, n_out, mesh,
                                    multi_seed, probe)
        if points:
            meta["dispatches"] = 1
            meta["lanes"] = _lanes(len(points))
            meta["pad"] = meta["lanes"] - len(points)
        pts_out = points if n_out is None else points[:n_out]
        meta["rows"] = [
            {"point": i, "seed": int(s), "key": None, "cached": False}
            for i, (_, s) in enumerate(pts_out)
        ]
        return _done(rows)
    if not points:
        return _done([])
    if chunk_size is None or n_out is not None:
        # n_out callers (serve's bucket-padded flushes) journal the whole
        # batch as ONE chunk: pad lanes never split across chunk keys
        chunk_size = len(points)
    if mesh is not None and partition.mesh_size(mesh) > 1:
        chunk_size = partition.align_chunk(
            chunk_size, max(partition.sweep_axis_size(mesh), 1)
        )
    done = journal.completed() if journal is not None else {}
    out = []
    for index, start in enumerate(range(0, len(points), chunk_size)):
        tile = points[start:start + chunk_size]
        want = len(tile) if n_out is None else max(0, min(len(tile), n_out))
        t_out = None if n_out is None else want
        key = journal_mod.chunk_key(canon, index, tile, mesh, n_out=t_out)
        if probe is not None:
            # armed and disarmed flushes must never share a journal key:
            # a cached disarmed chunk has no "probe" summaries to serve
            key += f"+p{probe.windows}{'m' if probe.monitors else ''}"
        key += key_suffix
        cached = done.get(key)
        if cached is not None and len(cached) == want:
            meta["chunks"].append({"key": key, "index": index,
                                   "cached": True, "n": want})
            meta["rows"] += [
                {"point": start + j, "seed": int(tile[j][1]), "key": key,
                 "cached": True}
                for j in range(want)
            ]
            out.extend(cached)
            continue
        # every dispatch ATTEMPT runs record=False: only the winning
        # arm's rows (journaled below) reach runs.jsonl — an abandoned
        # slow attempt finishing late must not double-record its points
        rows = _run_chunk(canon, tile, False, t_out, mesh, supervise,
                          journal, key, index, multi_seed, probe)
        # durable BEFORE the next chunk dispatches — the recompute-at-
        # most-one contract the kill -9 drill pins
        if journal is not None:
            journal.append_chunk(key, index, rows,
                                 cache=aotcache.registry.manifest())
        if record:
            pts_out = tile if t_out is None else tile[:t_out]
            for (cfg_i, seed_i), m in zip(pts_out, rows):
                obs.record_run({"seed": int(seed_i), **m}, cfg_i)
        meta["dispatches"] += 1
        meta["lanes"] += _lanes(len(tile))
        meta["pad"] += _lanes(len(tile)) - len(tile)
        meta["chunks"].append({"key": key, "index": index,
                               "cached": False, "n": len(rows)})
        meta["rows"] += [
            {"point": start + j, "seed": int(tile[j][1]), "key": key,
             "cached": False}
            for j in range(len(rows))
        ]
        out.extend(rows)
    return _done(out)


def dyn_chunk_keys(cfg: SimConfig, fault_configs, seeds, mesh=None):
    """The chunk keys a journaled ``run_fault_sweep(cfg, fault_configs,
    seeds, mesh=..., journal=...)`` will use for ONE same-structure group
    — derived from the grid alone, never from a journal's content, so a
    drill's coverage check is independent evidence (a journal that
    silently lost a chunk fails it).  All ``fault_configs`` must share
    one canonical structure (the helper asserts it)."""
    fcs = list(fault_configs)
    canons = {canonical_fault_cfg(cfg.with_(faults=fc)) for fc in fcs}
    if len(canons) != 1:
        raise ValueError(
            f"dyn_chunk_keys covers one structure group, got {len(canons)}"
        )
    canon = next(iter(canons))
    chunk = len(seeds)
    if mesh is not None and partition.mesh_size(mesh) > 1:
        chunk = partition.align_chunk(
            chunk, max(partition.sweep_axis_size(mesh), 1)
        )
    points = [(cfg.with_(faults=fc), s) for fc in fcs for s in seeds]
    return [
        journal_mod.chunk_key(canon, i, points[st:st + chunk], mesh)
        for i, st in enumerate(range(0, len(points), chunk))
    ]


def _run_dyn_group(cfg: SimConfig, canon: SimConfig, fcs, seeds, mesh=None,
                   journal=None, supervise=None, multi_seed=False):
    """One compiled program for every (fault config, seed) point of a
    same-structure group; returns {fc: [metrics per seed]} with rows
    bit-equal to ``run_seed_sweep(cfg.with_(faults=fc), seeds)``.

    With a journal, the group chunks one-fault-level-per-chunk (the
    seed tile) — the ISSUE's canonical-structure-group × level tile —
    so a crash mid-grid loses at most one level's seed batch."""
    points = [(cfg.with_(faults=fc), seed) for fc in fcs for seed in seeds]
    tiled = journal is not None or supervise is not None
    rows = run_dyn_points(canon, points, mesh=mesh, journal=journal,
                          chunk_size=len(seeds) if tiled else None,
                          supervise=supervise, multi_seed=multi_seed)
    n_s = len(seeds)
    return {
        fc: rows[i * n_s:(i + 1) * n_s] for i, fc in enumerate(fcs)
    }


def run_fault_sweep(cfg: SimConfig, fault_configs, seeds, mesh=None,
                    journal=None, supervise=None, multi_seed=False):
    """BASELINE config 4: sweep fault configs with seeds vmapped inside.
    Returns {fault_config: [metrics per seed]}.

    Fault configs that differ only in their COUNTS (crash/Byzantine) batch
    into one dynamic-operand executable per structure group — the whole
    default sweep is ONE compile.  Structurally distinct configs (different
    drop_prob / byz_forge / byz_copies) land in separate groups, each with
    its own dynamic-operand compile — same compile count as the old
    per-config loop, and future same-structure sweeps reuse the entry.
    Un-batchable configs (today: the mixed shard sim — the typed
    ``runner.UnbatchableConfigError``, classified here without
    string-matching) take the static ``run_seed_sweep`` path
    (one static compile per fault config).

    ``mesh`` shards every dynamic-operand group's (fault config, seed)
    batch over the mesh's sweep axis (see :func:`run_dyn_points`); the
    static fallback stays single-device — its mesh story is
    ``run_seed_sweep(mesh=...)``'s node-sharded one, with different
    divisibility requirements.

    ``journal=`` (parallel/journal.SweepJournal) makes the sweep durable:
    each structure group chunks one fault level (seed tile) per journaled
    chunk, and a restarted identical sweep skips completed chunks —
    recompute is at most the one in-flight chunk, rows bit-equal under
    the exact sampler.  The static (un-batchable) fallback is NOT
    journaled — it has no dynamic-operand chunk identity.  ``supervise=``
    (journal.ChunkSupervisor) adds per-chunk deadlines with bounded
    retry and a recorded degrade arm.  Before any dispatch, a fresh
    ``wedged`` verdict in the rolling health log
    ($BLOCKSIM_HEALTH_JSONL) fails fast with the typed
    ``utils.health.BackendWedgedError`` instead of hanging on backend
    init — the bench.py ladder rule, now on the sweep tier.

    ``multi_seed=True`` routes every single-device dynamic-operand group
    through the scatter-free ``lax.map`` executable
    (:func:`multi_seed_fn`) — seed-replicated sweep tiles collapse into
    one dispatch of the tick-path throughput arm (ISSUE 13), rows
    bit-equal to the default vmapped dispatch under the exact sampler."""
    from blockchain_simulator_tpu.utils import health

    health.require_not_wedged()
    fault_configs = list(fault_configs)
    groups: dict[SimConfig, list] = {}
    order = {}
    for fc in fault_configs:
        try:
            check_batchable(cfg.with_(faults=fc))
        except UnbatchableConfigError:
            order[fc] = None
            continue
        canon = canonical_fault_cfg(cfg.with_(faults=fc))
        if fc not in groups.setdefault(canon, []):
            groups[canon].append(fc)
        order[fc] = canon
    done: dict = {}
    for canon, fcs in groups.items():
        done.update(_run_dyn_group(cfg, canon, fcs, seeds, mesh=mesh,
                                   journal=journal, supervise=supervise,
                                   multi_seed=multi_seed))
    results = {}
    for fc in fault_configs:
        if order[fc] is None:
            results[fc] = run_seed_sweep(cfg.with_(faults=fc), seeds)
        else:
            results[fc] = done[fc]
    return results


def run_byzantine_sweep(cfg: SimConfig, f_values=None, seeds=(0,), forge=True,
                        mesh=None, journal=None, supervise=None,
                        multi_seed=False):
    """BASELINE config 4 end-to-end: sweep the Byzantine count f over
    ``f_values`` (default 0..(n-1)//3), seeds batched per f — the whole
    sweep is ONE vmapped executable over (f, seed) (dynamic fault operands;
    the per-f recompile this loop used to pay is gone).  ``mesh`` shards
    the (f, seed) cross product over the mesh's sweep axis
    (:func:`run_dyn_points`; tools/mesh_sweep_bench.py is the artifact).

    Each entry reports the two safety-relevant outcomes next to the fault
    level: ``forged_commits`` (a slot finalized although no honest leader ever
    proposed it — possible under the reference's no-dedup "n2" counting, see
    utils/config.py quorum_rule) and ``agreement_ok``.  Returns a list of
    {"f": f, "seed": s, **metrics} dicts.
    """
    if forge and cfg.protocol != "pbft":
        raise ValueError(
            "the forging attack is implemented for pbft only; pass "
            "forge=False to sweep passive vote-flipping Byzantine nodes "
            f"for {cfg.protocol!r}"
        )
    if f_values is None:
        f_values = range(cfg.byz_f + 1)
    f_values = list(f_values)
    fcs = [
        dataclasses.replace(cfg.faults, n_byzantine=f, byz_forge=forge)
        for f in f_values
    ]
    # dedup: repeated f values share one fault config (and one batch row set)
    res = run_fault_sweep(cfg, list(dict.fromkeys(fcs)), seeds, mesh=mesh,
                          journal=journal, supervise=supervise,
                          multi_seed=multi_seed)
    out = []
    for f, fc in zip(f_values, fcs):
        for seed, m in zip(seeds, res[fc]):
            out.append({"f": int(f), "seed": int(seed), **m})
    return out
