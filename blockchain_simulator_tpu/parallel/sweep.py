"""Config/fault-sweep parallelism: batch whole simulations.

The outer-axis analog of BASELINE config 4 ("Byzantine-fault sweep f=0..n/3,
pmap over fault configs"): many seeds of one config run as a single vmapped
program; over a mesh, the batch axis shards over ``sweep`` (``spmd_axis_name``)
while the node axis shards over ``nodes``.

Fault *counts* (crash counts, Byzantine counts) are traced per-run OPERANDS
(runner.make_dyn_sim_fn): an f-sweep over any number of fault levels is ONE
vmapped executable over the (fault level, seed) cross product — where it used
to compile one program per f value (~20 s of XLA per point on this box for
seconds of simulation).  Fault *structure* (drop_prob, byz_forge, byz_copies)
stays static: :func:`run_fault_sweep` groups its fault configs by canonical
structure (models/base.canonical_fault_cfg) and compiles once per group.
Results are bit-equal to the per-point static path (pinned in
tests/test_zsweep_cache.py); the mixed shard sim keeps the static path.

Bit-equality caveat: under ``stat_sampler="exact"`` (and the whole edge
path) equality is exact — integer draws whose arithmetic is identical in
both programs.  The ``"normal"`` CLT sampler (auto at n >= 4096) has a
float path that XLA may arrange differently in the two compiled programs:
with the SAME keys, one message can land one delay bucket over, moving a
commit tail by ±1 tick (measured once across a 22-point 10k sweep,
``tools/sweep_cache_bench.py`` notes) — the same jitter class
models/pbft_round.py documents vs the tick engine; counts and milestones
are unaffected.

Compiled programs live in the unified executable registry
(utils/aotcache.py) — hit/miss stats land on every run manifest.  The
same-structure grouping below is pinned at the IR level by the graph
audit's divergence twins (lint/graph/programs.py ``sweep_dynf.*``): fault
configs differing only in counts must trace to ONE jaxpr fingerprint, or
``lint.graph`` fails ``registry-key-divergence`` in CI.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from blockchain_simulator_tpu.chaos import inject
from blockchain_simulator_tpu.models.base import canonical_fault_cfg, get_protocol
from blockchain_simulator_tpu.parallel import partition
from blockchain_simulator_tpu.parallel.mesh import NODES_AXIS, SWEEP_AXIS
from blockchain_simulator_tpu.runner import (
    UnbatchableConfigError,
    check_batchable,
    make_dyn_sim_fn,
    make_sim_fn,
)
from blockchain_simulator_tpu.utils import aotcache, obs
from blockchain_simulator_tpu.utils.config import SimConfig


@aotcache.cached_factory("sweep-batched")
def _batched_fn(cfg: SimConfig, mesh=None):
    """Jitted ``batched(keys) -> finals`` for one (cfg, mesh): registry-
    cached so repeated sweeps of one config reuse the compiled program
    instead of building a fresh jit wrapper per call (jaxlint
    static-arg-recompile-hazard; runner.make_sim_fn convention)."""
    if mesh is None:
        return jax.jit(jax.vmap(make_sim_fn(cfg)))
    from blockchain_simulator_tpu.parallel.shard import make_sharded_sim_fn

    return jax.jit(
        jax.vmap(make_sharded_sim_fn(cfg, mesh), spmd_axis_name=SWEEP_AXIS)
    )


@aotcache.cached_factory("sweep-batched-dynf")
def dyn_batched_fn(cfg: SimConfig):
    """Jitted ``batched(keys, n_crashed[B], n_byzantine[B]) -> finals`` —
    THE one executable of a whole fault-count sweep (``cfg`` must already be
    canonical; one registry entry per fault structure).  Public: the
    scenario server's micro-batched dispatch (serve/dispatch.py) rides the
    same registry entry as the sweeps, so a sweep warms the server and
    vice versa."""
    return jax.jit(jax.vmap(make_dyn_sim_fn(cfg)))


# back-compat alias (pre-serve name; lint/graph/programs.py and external
# callers were updated, but keep the old spelling importable)
_dyn_batched_fn = dyn_batched_fn


@aotcache.cached_factory("partition-dyn-sweep")
def mesh_dyn_batched_fn(cfg: SimConfig, mesh):
    """Mesh-partitioned ``batched(keys[B], n_crashed[B], n_byzantine[B]) ->
    finals``: the (fault level, seed) batch axis sharded over the mesh's
    ``sweep`` axis, through the partition layer (parallel/partition.py).

    Three arms, all one registry entry per (fault structure, mesh) — the
    mesh rides the key, so the one-executable-per-fault-structure contract
    holds per mesh:

    - **mesh of size 1**: degenerates to :func:`dyn_batched_fn` — the
      PR 4 single-device program itself, so results are trivially
      bit-identical to the plain vmapped sweep (the registry serves the
      ``sweep-batched-dynf`` entry; sweeps and serving stay warm).
    - **sweep-only mesh** (nodes axis 1): shard_map over the batch axis
      with a per-device body of ``lax.map`` over the UNVMAPPED dyn sim.
      The unvmapped body keeps its dynamic-update-slice pushes as plain
      DUS instead of vmap's scatter lowering (KNOWN_ISSUES #0b: XLA:CPU
      serializes scatter) — measured ~2.3x per lane over the vmapped
      program at 10k nodes on the CPU mesh, before any device parallelism.
    - **nodes axis > 1**: the explicit-sharding pjit arm — batch over
      ``sweep``, each lane's node dim over ``nodes``
      (partition.batched_out_shardings), XLA GSPMD partitioning the scan:
      the "node axis optionally sharded for large n" option.

    Callers must pad the batch to a multiple of the sweep axis size
    (partition.pad_points; run_dyn_points does).  Bit-equality to the
    single-device path is pinned under the exact sampler in
    tests/test_zzpartition.py — the normal CLT sampler keeps the module
    caveat's ±1-tick float latitude."""
    fn = make_dyn_sim_fn(cfg)
    if partition.mesh_size(mesh) == 1:
        return dyn_batched_fn(cfg)
    if int(dict(mesh.shape).get(NODES_AXIS, 1)) > 1:
        batched = jax.vmap(fn)
        b = max(partition.sweep_axis_size(mesh), 1)
        keys_sds = jax.eval_shape(
            lambda: jax.vmap(jax.random.key)(jnp.arange(b, dtype=jnp.uint32))
        )
        cnt_sds = jax.ShapeDtypeStruct((b,), jnp.int32)
        outs = jax.eval_shape(batched, keys_sds, cnt_sds, cnt_sds)
        from jax.sharding import PartitionSpec as P

        lane = P(SWEEP_AXIS) if partition.sweep_axis_size(mesh) > 1 else P()
        return partition.partition(
            batched, mesh,
            in_shardings=(lane, lane, lane),
            out_shardings=partition.batched_out_shardings(cfg, mesh, outs),
        )

    def body(keys, nc, nb):
        # per-device: local lanes run SEQUENTIALLY through the unvmapped
        # program (lax.map = scan of the solo body, constant program size)
        return jax.lax.map(lambda args: fn(*args), (keys, nc, nb))

    from jax.sharding import PartitionSpec as P

    lane = P(SWEEP_AXIS)
    return partition.partition(
        body, mesh, in_specs=(lane, lane, lane), out_specs=lane
    )


def run_seed_sweep(cfg: SimConfig, seeds, mesh=None):
    """Run ``len(seeds)`` simulations of one config in a single vmapped
    program; returns a list of per-seed metrics dicts."""
    proto = get_protocol(cfg.protocol)
    # Every schedule is fully traceable — including round-schedule raft,
    # whose checked handoff is a lax.cond (models/raft_hb.scan_from_init)
    # that vmap lowers to a select: both branches run for the whole batch,
    # so a batched round-schedule raft sweep costs about one tick-engine
    # pass (the fallback branch continues the prefix carry, it does not
    # restart), never more.
    if mesh is not None:
        n_sweep = mesh.shape[SWEEP_AXIS]
        if len(seeds) % n_sweep != 0:
            raise ValueError(
                f"{len(seeds)} seeds not divisible by sweep axis size {n_sweep}"
            )
    keys = jax.vmap(jax.random.key)(jnp.asarray(seeds, jnp.uint32))
    finals = jax.block_until_ready(_batched_fn(cfg, mesh)(keys))
    out = []
    for i, seed in enumerate(seeds):
        final_i = jax.tree.map(lambda x: x[i], finals)
        m = proto.metrics(cfg, final_i)
        # observability routing: a finalized COPY of every sweep row goes to
        # the optional runs.jsonl ($BLOCKSIM_RUNS_JSONL, utils/obs.py); the
        # returned dicts stay pure metrics — tests compare them bit-for-bit
        # against single runs
        obs.record_run({"seed": int(seed), **m}, cfg)
        out.append(m)
    return out


def _dyn_operands(cfg: SimConfig, fc) -> tuple[int, int]:
    """The traced (n_crashed, n_byzantine) operand point of a fault config."""
    return fc.resolved_n_crashed(cfg.n), fc.n_byzantine


def run_dyn_points(canon: SimConfig, points, record: bool = True,
                   n_out: int | None = None, mesh=None):
    """THE group-dispatch primitive: one vmapped executable over an
    arbitrary list of same-structure ``(cfg, seed)`` points.

    ``points`` is a sequence of ``(cfg, seed)`` pairs whose configs all
    canonicalize to ``canon`` (``canonical_fault_cfg``) — they may differ
    only in fault COUNTS, which become the traced per-lane operands.
    Returns one metrics dict per point, in order, each bit-equal (exact
    sampler; see the module caveat for the normal CLT path) to a solo run
    of the same ``(cfg, seed)``.

    Both the fault sweeps (:func:`run_fault_sweep`, a cross product of
    points) and the scenario server's micro-batched dispatch
    (serve/dispatch.py, whatever compatible requests are queued) route
    through here.  ``record=False`` skips the per-row runs.jsonl hook for
    callers that write their own access-log records (the server does);
    ``n_out`` computes host-side metrics for only the first ``n_out``
    points (the server's bucket-padded lanes are duplicates whose metrics
    would be discarded).

    With ``mesh`` set the batch axis shards over the mesh's sweep axis
    through :func:`mesh_dyn_batched_fn` (parallel/partition.py): the point
    list is padded to a multiple of the sweep axis size by repeating the
    last point (padding lanes ride at the tail, so real-point indices are
    unchanged and pad metrics are never computed).  A mesh of size 1 takes
    the single-device path verbatim."""
    points = list(points)
    # the batched-dispatch chaos point: the drills inject raise/hang/slow
    # here — the exact exception path a real backend fault takes through
    # the sweeps AND the serving degrade machinery (chaos/inject.py)
    inject.chaos_point("sweep.dyn_dispatch", canon=canon, n=len(points))
    dispatch_points = points
    if mesh is not None and partition.mesh_size(mesh) > 1:
        lanes = max(partition.sweep_axis_size(mesh), 1)
        dispatch_points, _ = partition.pad_points(points, lanes)
        batched = mesh_dyn_batched_fn(canon, mesh)
    else:
        batched = dyn_batched_fn(canon)
    keys = jax.vmap(jax.random.key)(
        jnp.asarray([s for _, s in dispatch_points], jnp.uint32)
    )
    ops = [_dyn_operands(cfg, cfg.faults) for cfg, _ in dispatch_points]
    nc = jnp.asarray([o[0] for o in ops], jnp.int32)
    nb = jnp.asarray([o[1] for o in ops], jnp.int32)
    finals = jax.block_until_ready(batched(keys, nc, nb))
    out = []
    if n_out is not None:
        points = points[:n_out]
    for i, (cfg_i, seed) in enumerate(points):
        proto = get_protocol(cfg_i.protocol)
        final_i = jax.tree.map(lambda x: x[i], finals)
        m = proto.metrics(cfg_i, final_i)
        if record:
            obs.record_run({"seed": int(seed), **m}, cfg_i)
        out.append(m)
    return out


def _run_dyn_group(cfg: SimConfig, canon: SimConfig, fcs, seeds, mesh=None):
    """One compiled program for every (fault config, seed) point of a
    same-structure group; returns {fc: [metrics per seed]} with rows
    bit-equal to ``run_seed_sweep(cfg.with_(faults=fc), seeds)``."""
    points = [(cfg.with_(faults=fc), seed) for fc in fcs for seed in seeds]
    rows = run_dyn_points(canon, points, mesh=mesh)
    n_s = len(seeds)
    return {
        fc: rows[i * n_s:(i + 1) * n_s] for i, fc in enumerate(fcs)
    }


def run_fault_sweep(cfg: SimConfig, fault_configs, seeds, mesh=None):
    """BASELINE config 4: sweep fault configs with seeds vmapped inside.
    Returns {fault_config: [metrics per seed]}.

    Fault configs that differ only in their COUNTS (crash/Byzantine) batch
    into one dynamic-operand executable per structure group — the whole
    default sweep is ONE compile.  Structurally distinct configs (different
    drop_prob / byz_forge / byz_copies) land in separate groups, each with
    its own dynamic-operand compile — same compile count as the old
    per-config loop, and future same-structure sweeps reuse the entry.
    Un-batchable configs (today: the mixed shard sim — the typed
    ``runner.UnbatchableConfigError``, classified here without
    string-matching) take the static ``run_seed_sweep`` path
    (one static compile per fault config).

    ``mesh`` shards every dynamic-operand group's (fault config, seed)
    batch over the mesh's sweep axis (see :func:`run_dyn_points`); the
    static fallback stays single-device — its mesh story is
    ``run_seed_sweep(mesh=...)``'s node-sharded one, with different
    divisibility requirements."""
    fault_configs = list(fault_configs)
    groups: dict[SimConfig, list] = {}
    order = {}
    for fc in fault_configs:
        try:
            check_batchable(cfg.with_(faults=fc))
        except UnbatchableConfigError:
            order[fc] = None
            continue
        canon = canonical_fault_cfg(cfg.with_(faults=fc))
        if fc not in groups.setdefault(canon, []):
            groups[canon].append(fc)
        order[fc] = canon
    done: dict = {}
    for canon, fcs in groups.items():
        done.update(_run_dyn_group(cfg, canon, fcs, seeds, mesh=mesh))
    results = {}
    for fc in fault_configs:
        if order[fc] is None:
            results[fc] = run_seed_sweep(cfg.with_(faults=fc), seeds)
        else:
            results[fc] = done[fc]
    return results


def run_byzantine_sweep(cfg: SimConfig, f_values=None, seeds=(0,), forge=True,
                        mesh=None):
    """BASELINE config 4 end-to-end: sweep the Byzantine count f over
    ``f_values`` (default 0..(n-1)//3), seeds batched per f — the whole
    sweep is ONE vmapped executable over (f, seed) (dynamic fault operands;
    the per-f recompile this loop used to pay is gone).  ``mesh`` shards
    the (f, seed) cross product over the mesh's sweep axis
    (:func:`run_dyn_points`; tools/mesh_sweep_bench.py is the artifact).

    Each entry reports the two safety-relevant outcomes next to the fault
    level: ``forged_commits`` (a slot finalized although no honest leader ever
    proposed it — possible under the reference's no-dedup "n2" counting, see
    utils/config.py quorum_rule) and ``agreement_ok``.  Returns a list of
    {"f": f, "seed": s, **metrics} dicts.
    """
    if forge and cfg.protocol != "pbft":
        raise ValueError(
            "the forging attack is implemented for pbft only; pass "
            "forge=False to sweep passive vote-flipping Byzantine nodes "
            f"for {cfg.protocol!r}"
        )
    if f_values is None:
        f_values = range(cfg.byz_f + 1)
    f_values = list(f_values)
    fcs = [
        dataclasses.replace(cfg.faults, n_byzantine=f, byz_forge=forge)
        for f in f_values
    ]
    # dedup: repeated f values share one fault config (and one batch row set)
    res = run_fault_sweep(cfg, list(dict.fromkeys(fcs)), seeds, mesh=mesh)
    out = []
    for f, fc in zip(f_values, fcs):
        for seed, m in zip(seeds, res[fc]):
            out.append({"f": int(f), "seed": int(seed), **m})
    return out
