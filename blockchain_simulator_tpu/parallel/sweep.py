"""Config/fault-sweep parallelism: batch whole simulations.

The outer-axis analog of BASELINE config 4 ("Byzantine-fault sweep f=0..n/3,
pmap over fault configs"): many seeds of one config run as a single vmapped
program; over a mesh, the batch axis shards over ``sweep`` (``spmd_axis_name``)
while the node axis shards over ``nodes``.  Fault *structure* (crash counts,
Byzantine counts) is static per config, so an f-sweep compiles one program per
f value but batches all seeds of that f.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from blockchain_simulator_tpu.models.base import get_protocol
from blockchain_simulator_tpu.parallel.mesh import SWEEP_AXIS
from blockchain_simulator_tpu.runner import make_sim_fn
from blockchain_simulator_tpu.utils import obs
from blockchain_simulator_tpu.utils.config import SimConfig


@functools.lru_cache(maxsize=32)
def _batched_fn(cfg: SimConfig, mesh=None):
    """Jitted ``batched(keys) -> finals`` for one (cfg, mesh): cached so
    repeated sweeps of one config reuse the compiled program instead of
    building a fresh jit wrapper per call (jaxlint
    static-arg-recompile-hazard; runner.make_sim_fn convention)."""
    if mesh is None:
        return jax.jit(jax.vmap(make_sim_fn(cfg)))
    from blockchain_simulator_tpu.parallel.shard import make_sharded_sim_fn

    return jax.jit(
        jax.vmap(make_sharded_sim_fn(cfg, mesh), spmd_axis_name=SWEEP_AXIS)
    )


def run_seed_sweep(cfg: SimConfig, seeds, mesh=None):
    """Run ``len(seeds)`` simulations of one config in a single vmapped
    program; returns a list of per-seed metrics dicts."""
    proto = get_protocol(cfg.protocol)
    # Every schedule is fully traceable — including round-schedule raft,
    # whose checked handoff is a lax.cond (models/raft_hb.scan_from_init)
    # that vmap lowers to a select: both branches run for the whole batch,
    # so a batched round-schedule raft sweep costs about one tick-engine
    # pass (the fallback branch continues the prefix carry, it does not
    # restart), never more.
    if mesh is not None:
        n_sweep = mesh.shape[SWEEP_AXIS]
        if len(seeds) % n_sweep != 0:
            raise ValueError(
                f"{len(seeds)} seeds not divisible by sweep axis size {n_sweep}"
            )
    keys = jax.vmap(jax.random.key)(jnp.asarray(seeds, jnp.uint32))
    finals = jax.block_until_ready(_batched_fn(cfg, mesh)(keys))
    out = []
    for i, seed in enumerate(seeds):
        final_i = jax.tree.map(lambda x: x[i], finals)
        m = proto.metrics(cfg, final_i)
        # observability routing: a finalized COPY of every sweep row goes to
        # the optional runs.jsonl ($BLOCKSIM_RUNS_JSONL, utils/obs.py); the
        # returned dicts stay pure metrics — tests compare them bit-for-bit
        # against single runs
        obs.record_run({"seed": int(seed), **m}, cfg)
        out.append(m)
    return out


def run_fault_sweep(cfg: SimConfig, fault_configs, seeds):
    """BASELINE config 4: one batched run per fault config (static structure),
    seeds vmapped inside.  Returns {fault_config: [metrics per seed]}."""
    results = {}
    for fc in fault_configs:
        results[fc] = run_seed_sweep(cfg.with_(faults=fc), seeds)
    return results


def run_byzantine_sweep(cfg: SimConfig, f_values=None, seeds=(0,), forge=True):
    """BASELINE config 4 end-to-end: sweep the Byzantine count f over
    ``f_values`` (default 0..(n-1)//3), seeds batched per f.

    Each entry reports the two safety-relevant outcomes next to the fault
    level: ``forged_commits`` (a slot finalized although no honest leader ever
    proposed it — possible under the reference's no-dedup "n2" counting, see
    utils/config.py quorum_rule) and ``agreement_ok``.  Returns a list of
    {"f": f, "seed": s, **metrics} dicts.
    """
    import dataclasses

    if forge and cfg.protocol != "pbft":
        raise ValueError(
            "the forging attack is implemented for pbft only; pass "
            "forge=False to sweep passive vote-flipping Byzantine nodes "
            f"for {cfg.protocol!r}"
        )
    if f_values is None:
        f_values = range(cfg.byz_f + 1)
    out = []
    for f in f_values:
        faults = dataclasses.replace(cfg.faults, n_byzantine=f, byz_forge=forge)
        for seed, m in zip(seeds, run_seed_sweep(cfg.with_(faults=faults), seeds)):
            out.append({"f": int(f), "seed": int(seed), **m})
    return out
