"""Durable sweeps: a fsynced, torn-tail-tolerant journal of chunk results.

The sweep-tier analog of the serving WAL (serve/wal.py).  The serving
tier survives kill -9 because every admission is durable before work
starts; the sweep tier — the path ROADMAP items 3 and 5 point at
million-node grids and multi-hour TPU sessions — ran every grid to
completion in one process, so a crash, an OOM, or a wedged tunnel
(KNOWN_ISSUES.md #3) threw away the whole run.  With a journal attached
(``run_fault_sweep(..., journal=)``, ``run_byzantine_sweep(...,
journal=)``, ``run_dyn_points(..., journal=)``) a sweep decomposes into
deterministic **chunks** — one per canonical-fault-structure group ×
seed/level tile — and each completed chunk appends its rows durably
*before* the next chunk dispatches.  A restarted sweep skips completed
chunks and recomputes at most the one chunk that was in flight when the
process died, bit-equal under the exact sampler (the parallel/sweep.py
``"normal"``-CLT caveat applies as everywhere).

Journal-vs-WAL semantics (the two are deliberately different):

- the WAL journals **intent** (admits before work, at-least-once replay,
  idempotent by request id); the sweep journal journals **results** —
  a chunk line exists only when its rows are complete, so replaying it
  is a read, never a re-execution;
- WAL replay re-runs the work; journal resume *skips* it — the registry
  miss count is unchanged by resumed chunks (pinned in tests);
- both share the torn-tail rule: a crash mid-append leaves an
  unparseable tail line that readers skip (utils/obs.read_jsonl), and
  the chunk that owned it is simply recomputed.

Chunk identity is content-addressed: :func:`chunk_key` hashes the
canonical structure's config hash, the chunk index, the mesh descriptor
and the chunk's ``(config hash, seed)`` point list — stable across
processes (tests pin it through a subprocess), so resume never trusts
file order, only keys.  Row integrity is per-row checksums
(:func:`row_checksum` over the canonical JSON): a corrupted row fails
its checksum and demotes the whole chunk to "recompute", never to
silently-wrong rows.

Supervision (:class:`ChunkSupervisor` + :func:`run_supervised`): chunk
dispatch can be wrapped in a per-chunk deadline.  On expiry the
dispatch thread is ABANDONED (never killed — killing a client hung in
backend init is what wedges the tunnel, KNOWN_ISSUES.md #3), the
backend is optionally probed through ``utils/health.
probe_backend_supervised``, and the chunk is retried with jittered
exponential backoff a bounded number of times before taking the
recorded **degrade** arm — re-dispatching on the size-1/no-mesh path
(parallel/partition.py's degenerate arm) or, for a single very long
sim, tick-level mid-chunk checkpoints through utils/checkpoint.py
(``runner.run_dyn_checkpointed``).  Every transition lands as an
``event`` line in the journal, so a post-mortem reads as data which
chunks wedged, how many retries they cost, and which arm finally
answered.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time

JOURNAL_SCHEMA = 1


def _canonical_json(rec) -> str:
    from blockchain_simulator_tpu.utils import obs

    return obs.canonical_json(rec)


def row_checksum(row: dict) -> str:
    """sha256 (16 hex chars) of a row's canonical JSON — verified by the
    reader before a journaled chunk is trusted.  JSON-round-trip stable:
    a row read back from the journal checksums identically."""
    return hashlib.sha256(_canonical_json(row).encode()).hexdigest()[:16]


def chunk_key(canon, index: int, points, mesh=None,
              n_out: int | None = None) -> str:
    """The content-addressed identity of one sweep chunk, stable across
    processes: canonical-structure config hash + chunk index + mesh
    descriptor + the chunk's ``(config hash, seed)`` point list + the
    row-count trim (``n_out`` — the serve path journals only the real
    lanes of a padded batch, so two batches sharing a padded point list
    but trimming differently must not share a key).  Resume matches on
    this key only — file order and wall-clock never matter."""
    from blockchain_simulator_tpu.utils import obs

    mesh_desc = None
    if mesh is not None:
        from blockchain_simulator_tpu.parallel import partition

        mesh_desc = partition.mesh_shape_dict(mesh)
    ident = {
        "canon": obs.config_hash(canon),
        "index": int(index),
        "mesh": mesh_desc,
        "n_out": None if n_out is None else int(n_out),
        "points": [[obs.config_hash(cfg), int(seed)] for cfg, seed in points],
    }
    return hashlib.sha256(_canonical_json(ident).encode()).hexdigest()[:16]


def query_key_suffix(step: int) -> str:
    """The query-engine chunk-key namespace (query/engine.py): every
    refinement step's chunk journals under ``chunk_key(...) + "+q<step>"``
    — mirroring the obsim probe suffix (``+p<W>``, sweep.run_dyn_points)
    so an adaptive search and a grid sweep over the SAME canonical
    structure can share one journal file without ever sharing a key.
    Grid keys are pure 16-hex; probe keys end ``+p...``; query keys end
    ``+q<step>`` — three disjoint namespaces by construction."""
    return f"+q{int(step)}"


def query_chunk_key(canon, step: int, points, mesh=None,
                    n_out: int | None = None) -> str:
    """Content key of ONE query refinement chunk: the ordinary
    :func:`chunk_key` at index 0 (each refinement generation dispatches
    as one chunk) plus the ``+q<step>`` namespace suffix.  Derived from
    the search trajectory alone — a drill's coverage check recomputes
    these without reading the journal (the dyn_chunk_keys idiom)."""
    return chunk_key(canon, 0, points, mesh, n_out=n_out) \
        + query_key_suffix(step)


class SweepJournal:
    """Append-only chunk-result journal; one JSON object per line.

    ``chunk`` lines carry the rows (with per-row checksums and the
    manifest ``cache`` block for provenance), ``event`` lines carry the
    supervisor's state machine.  Appends are fsynced by default
    (``sync=True``) — the kill -9 resume drill depends on a completed
    chunk surviving the very next instruction being SIGKILL.  Thread-safe
    (the supervisor's dispatch thread and the sweep loop both append)."""

    def __init__(self, path: str, sync: bool = True):
        self.path = str(path)
        self.sync = bool(sync)
        self._lock = threading.Lock()
        self._f = None
        # completed-chunk cache: loaded from disk on the first
        # :meth:`completed` call, then folded forward by this instance's
        # own appends — a long-lived server's per-flush journal check is
        # O(1), not O(journal).  A FRESH instance re-reads the file (the
        # resume path's source of truth stays the disk).
        self._completed: dict[str, list[dict]] | None = None

    # ------------------------------------------------------------ append ---
    def _append(self, rec: dict, fsync: bool) -> None:
        rec = {"sj": JOURNAL_SCHEMA, "ts": round(time.time(), 3), **rec}
        with self._lock:
            if self._f is None:
                d = os.path.dirname(self.path)
                if d:
                    os.makedirs(d, exist_ok=True)
                # torn-tail repair BEFORE the first append: a crash
                # mid-write leaves a partial line with no newline, and
                # appending straight after it would merge the new record
                # into the garbage — losing BOTH to the tolerant reader.
                # Terminate the torn line first so it parses (and is
                # skipped) alone.
                try:
                    with open(self.path, "rb") as rf:
                        rf.seek(-1, os.SEEK_END)
                        torn = rf.read(1) != b"\n"
                except (OSError, ValueError):  # missing or empty file
                    torn = False
                self._f = open(self.path, "a")
                if torn:
                    self._f.write("\n")
            self._f.write(_canonical_json(rec) + "\n")
            self._f.flush()
            if fsync:
                os.fsync(self._f.fileno())

    def append_chunk(self, key: str, index: int, rows, cache=None) -> None:
        """Durable BEFORE the next chunk dispatches: rows + per-row
        checksums + the registry ``cache`` block (compile provenance —
        which process paid the misses these rows rode on)."""
        rows = list(rows)
        self._append({
            "op": "chunk", "key": str(key), "index": int(index),
            "n": len(rows), "rows": rows,
            "sums": [row_checksum(r) for r in rows],
            "cache": cache,
        }, fsync=self.sync)
        if self._completed is not None:
            self._completed.setdefault(str(key), rows)

    def append_event(self, key: str, event: str, **fields) -> None:
        """Supervisor trail (``deadline``/``probe``/``retry``/``degrade``/
        ``failed``): flushed, not fsynced — losing one on a crash widens
        the post-mortem, never correctness."""
        self._append({"op": "event", "key": str(key), "event": str(event),
                      **fields}, fsync=False)

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    # -------------------------------------------------------------- read ---
    def records(self) -> list[dict]:
        """Every parseable journal record in file order (torn tail lines
        skipped — utils/obs.read_jsonl is the shared tolerant reader)."""
        from blockchain_simulator_tpu.utils import obs

        return [
            rec for rec in obs.read_jsonl(self.path)
            if rec.get("sj") == JOURNAL_SCHEMA and rec.get("op")
        ]

    def completed(self) -> dict[str, list[dict]]:
        """``{chunk key: rows}`` for every chunk line whose row checksums
        all verify.  A chunk with any bad checksum (bit rot, a hand-edited
        file) is EXCLUDED — demoted to recompute, never to wrong rows.
        First valid line per key wins (a key can legitimately appear once;
        duplicates are an invariant violation the chaos checker flags).

        Cached per instance (disk read + checksum pass once, then folded
        forward by this instance's appends); treat the returned mapping
        as read-only."""
        if self._completed is None:
            self._completed = self._read_completed()
        return self._completed

    def _read_completed(self) -> dict[str, list[dict]]:
        out: dict[str, list[dict]] = {}
        for rec in self.records():
            if rec["op"] != "chunk":
                continue
            key = str(rec.get("key"))
            if key in out:
                continue
            rows = rec.get("rows")
            sums = rec.get("sums")
            if not isinstance(rows, list) or not isinstance(sums, list) \
                    or len(rows) != len(sums):
                continue
            if all(row_checksum(r) == s for r, s in zip(rows, sums)):
                out[key] = rows
        return out

    def compact(self, keep_keys=None) -> tuple[int, int]:
        """WAL-style compaction (the KNOWN_ISSUES #0k follow-on): rewrite
        the journal to ONLY the checksum-valid chunk lines whose key is in
        ``keep_keys`` (None/empty = drop every chunk), dropping event
        lines and corrupt/duplicate chunks outright.  Atomic replace; the
        open handle and the completed-chunk cache reset so later appends
        and lookups see the compacted file.

        The serving daemon calls this at its startup compaction point
        (serve/server.py, next to ``WriteAheadLog.compact``) keyed on its
        PENDING ADMISSIONS: with a replay backlog every still-answerable
        chunk is kept — a compacted journal replays those batches with
        zero dispatches, same as before (pinned in tests) — and with no
        backlog the file empties, so a live-traffic daemon's journal stays
        proportional to its crash backlog instead of its history.

        Returns ``(kept, dropped)`` chunk-line counts."""
        keep = set() if keep_keys is None else {str(k) for k in keep_keys}
        tmp = f"{self.path}.tmp.{os.getpid()}"
        # the WHOLE read-filter-replace runs under the append lock: a
        # concurrent append_chunk between the snapshot and os.replace
        # would otherwise be silently deleted despite its fsync (the
        # reads below take no lock of their own, so no reentrancy)
        with self._lock:
            lines = self.chunk_lines()
            kept_recs = []
            seen: set[str] = set()
            for rec in lines:
                key = str(rec.get("key"))
                if key not in keep or key in seen:
                    continue
                # verify THIS line's own checksums — a corrupt line that
                # precedes a valid duplicate must not be the one kept
                rows, sums = rec.get("rows"), rec.get("sums")
                if not isinstance(rows, list) or not isinstance(sums, list) \
                        or len(rows) != len(sums) \
                        or any(row_checksum(r) != s
                               for r, s in zip(rows, sums)):
                    continue
                kept_recs.append(rec)
                seen.add(key)
            if self._f is not None:
                self._f.close()
                self._f = None
            with open(tmp, "w") as f:
                for rec in kept_recs:
                    f.write(_canonical_json(rec) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            self._completed = None
        return len(kept_recs), len(lines) - len(kept_recs)

    def events(self) -> list[dict]:
        """Every supervisor event line, in order."""
        return [r for r in self.records() if r["op"] == "event"]

    def chunk_lines(self) -> list[dict]:
        """Every parseable chunk line (checksum-verified or not) — the
        invariant checker counts duplicates and checksum failures here."""
        return [r for r in self.records() if r["op"] == "chunk"]


# ----------------------------------------------------------- supervision ---


class ChunkDeadlineError(TimeoutError):
    """A chunk dispatch missed its deadline; the dispatch thread was
    abandoned (never killed — KNOWN_ISSUES.md #3)."""


class ChunkFailedError(RuntimeError):
    """A chunk exhausted its retries AND its degrade arm — the typed
    terminal failure of the supervised state machine (the sweep caller
    sees this, never a hung process)."""


class ChunkSupervisor:
    """Policy knobs for supervised chunk dispatch.

    ``deadline_s``       per-attempt wall deadline on the PRIMARY arm
                         (None = no deadline: failures still retry,
                         hangs hang);
    ``degrade_deadline_s``  deadline on the degrade arm — default None:
                         the degrade arm is the last resort (abandoning
                         it too leaves nothing), and the checkpoint arm
                         legitimately runs long sims whose loss its own
                         per-segment checkpoints already bound;
    ``retries``          primary-arm attempts beyond the first;
    ``backoff_s``        base of the jittered exponential retry backoff;
    ``probe``            probe the backend via utils/health.
                         probe_backend_supervised after a deadline expiry
                         (``probe_patience_s`` per attempt) and record
                         the verdict as a journal event;
    ``checkpoint_dir``   enables the tick-level checkpoint degrade arm
                         for single-point chunks of tick-schedule configs
                         (runner.run_dyn_checkpointed: a re-kill resumes
                         MID-chunk from the last segment checkpoint);
    ``checkpoint_every_ms``  segment length of that arm;
    ``rng``              ``random.random``-like jitter source, injectable
                         so drills replay one backoff schedule.
    """

    def __init__(self, deadline_s: float | None = 30.0, retries: int = 2,
                 backoff_s: float = 0.5, probe: bool = False,
                 probe_patience_s: float = 60.0,
                 checkpoint_dir: str | None = None,
                 checkpoint_every_ms: int = 200, rng=None,
                 degrade_deadline_s: float | None = None):
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        self.degrade_deadline_s = (None if degrade_deadline_s is None
                                   else float(degrade_deadline_s))
        self.retries = max(0, int(retries))
        self.backoff_s = float(backoff_s)
        self.probe = bool(probe)
        self.probe_patience_s = float(probe_patience_s)
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every_ms = int(checkpoint_every_ms)
        import random as _random

        self.rng = rng if rng is not None else _random.random


# dispatch threads abandoned by an expired deadline: still running real
# compute, never signaled.  Tracked so drills/tests can drain them before
# process exit — interpreter teardown mid-XLA-dispatch aborts the process.
_abandoned: list[threading.Thread] = []


def drain_abandoned(timeout_s: float = 60.0) -> int:
    """Join every abandoned dispatch thread (bounded by ``timeout_s``
    total); returns how many actually finished.  A thread still alive
    when the budget runs out stays TRACKED (and uncounted) — callers can
    see the shortfall and wait again; silently dropping a live thread
    would recreate the interpreter-teardown abort this helper exists to
    prevent.  Drills and tests call this before exiting — a long-lived
    sweep process never needs to."""
    n = 0
    deadline = time.monotonic() + timeout_s
    still_alive: list[threading.Thread] = []
    while _abandoned:
        t = _abandoned.pop()
        t.join(max(0.0, deadline - time.monotonic()))
        if t.is_alive():
            still_alive.append(t)
        else:
            n += 1
    _abandoned.extend(still_alive)
    return n


def _with_deadline(fn, deadline_s):
    """Run ``fn()`` under a wall deadline in a worker thread.  On expiry
    the thread is ABANDONED — left running, never signaled (the health
    module's rule, KNOWN_ISSUES.md #3: killing a client hung in backend
    init is what wedges the tunnel) — and :class:`ChunkDeadlineError`
    raises in the caller.  ``deadline_s=None`` calls ``fn`` inline."""
    if deadline_s is None:
        return fn()
    box: list = []

    def worker():
        try:
            box.append(("ok", fn()))
        except BaseException as e:  # delivered to the supervisor, not lost
            box.append(("err", e))

    t = threading.Thread(target=worker, daemon=True,
                         name="sweep-chunk-dispatch")
    t.start()
    t.join(deadline_s)
    if not box:
        _abandoned.append(t)
        raise ChunkDeadlineError(
            f"chunk dispatch exceeded {deadline_s:.3f}s deadline; "
            "dispatch thread abandoned (KNOWN_ISSUES.md #3)"
        )
    kind, val = box[0]
    if kind == "err":
        raise val
    return val


def run_supervised(primary, degrade, sup: ChunkSupervisor,
                   journal: SweepJournal | None = None,
                   key: str = "?") -> tuple[list, list[str]]:
    """The deadline → retry/backoff → degrade state machine around one
    chunk.  ``primary``/``degrade`` are zero-arg callables returning the
    chunk's rows (``degrade=None`` disables the arm).  Returns
    ``(rows, events)`` where events is the ordered transition trail —
    also appended to ``journal`` as ``event`` lines as they happen.

    Terminal behavior: rows from the primary arm (possibly after
    retries), rows from the degrade arm (recorded), or a typed
    :class:`ChunkFailedError` carrying the last underlying error —
    never a silently hung sweep."""
    events: list[str] = []

    def note(event: str, **fields):
        events.append(event)
        if journal is not None:
            journal.append_event(key, event, **fields)
        # the flight recorder (utils/telemetry.py) mirrors the trail and
        # turns a degrade/terminal-failure into an atomic post-mortem
        # dump when $BLOCKSIM_FLIGHT_DIR is armed (ring-only otherwise)
        from blockchain_simulator_tpu.utils import telemetry

        telemetry.flight.note(f"sweep.{event}", key=key, **fields)
        if event in ("degrade", "failed"):
            telemetry.flight.dump(f"supervisor-{event}")

    last_err: BaseException | None = None
    for attempt in range(1, sup.retries + 2):
        try:
            return _with_deadline(primary, sup.deadline_s), events
        except ChunkDeadlineError as e:
            last_err = e
            note("deadline", attempt=attempt,
                 deadline_s=sup.deadline_s)
            if sup.probe:
                from blockchain_simulator_tpu.utils import health

                verdict = health.probe_backend_supervised(
                    patience_s=sup.probe_patience_s, rng=sup.rng,
                )
                note("probe", verdict=verdict.get("verdict"),
                     attempts=verdict.get("attempts"))
        except Exception as e:  # a raising dispatch: retryable fault
            last_err = e
            note("error", attempt=attempt,
                 error=f"{type(e).__name__}: {e}"[:200])
        if attempt <= sup.retries:
            note("retry", attempt=attempt)
            time.sleep(sup.backoff_s * (2.0 ** (attempt - 1))
                       * (0.5 + sup.rng()))
    if degrade is not None:
        note("degrade")
        try:
            return _with_deadline(degrade, sup.degrade_deadline_s), events
        except Exception as e:
            last_err = e
            note("failed", error=f"{type(e).__name__}: {e}"[:200])
    else:
        note("failed", error=f"{type(last_err).__name__}: {last_err}"[:200]
             if last_err else "no degrade arm")
    raise ChunkFailedError(
        f"chunk {key} failed after {sup.retries + 1} attempt(s)"
        f"{' and the degrade arm' if degrade is not None else ''}: "
        f"{type(last_err).__name__ if last_err else '?'}: {last_err}"
    ) from last_err
