"""Multi-host (DCN) execution: the same SPMD program over processes.

The reference's "distributed backend" is simulated UDP in one thread
(SURVEY.md §5); the TPU-native equivalent scales out in two tiers:

- intra-host: ICI collectives inside ``shard_map`` (parallel/shard.py);
- multi-host: ``jax.distributed.initialize`` + a global mesh built from all
  processes' devices — the SAME PartitionSpecs then span DCN, with XLA
  routing ``all_gather``/``psum`` across hosts.  Nothing in the simulation
  code changes; this module only adds process bootstrap, the global-mesh
  runner, and result gathering.

Testable without a TPU pod: two localhost CPU processes, each with
``--xla_force_host_platform_device_count=K`` virtual devices, form a
2-process DCN group (tests/test_multihost.py); the milestone metrics are
bit-identical to a single-process run over the same mesh shape, because
every random draw is keyed by (seed, tick, channel, shard index) — the
process boundary is invisible to the program.

CLI: ``python -m blockchain_simulator_tpu.parallel.multihost --coordinator
HOST:PORT --num-processes N --process-id I [sim flags]`` — or pass
``--multihost`` flags to the main CLI.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def init_multihost(coordinator: str, num_processes: int, process_id: int) -> None:
    """Join (or start, for process 0) the distributed coordination service."""
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


def run_sharded_multihost(cfg, n_node_shards: int | None = None, seed=None) -> dict:
    """Run one node-sharded simulation over ALL processes' devices.

    Must be called in every process of the group (it is one SPMD program);
    every process returns the full metrics dict (final state is allgathered
    host-side, so no process holds only its shard).
    """
    import jax
    from jax.experimental import multihost_utils

    from blockchain_simulator_tpu.models.base import get_protocol
    from blockchain_simulator_tpu.parallel.mesh import make_mesh
    from blockchain_simulator_tpu.parallel.shard import make_sharded_sim_fn

    proto = get_protocol(cfg.protocol)
    mesh = make_mesh(n_node_shards=n_node_shards)  # all global devices
    sim = make_sharded_sim_fn(cfg, mesh)
    final = sim(jax.random.key(cfg.seed if seed is None else seed))
    # shards live on different hosts; gather to replicated numpy everywhere
    # (tiled=True: reassemble the GLOBAL shape, no extra process axis — the
    # only mode supported for non-fully-addressable global arrays)
    final = multihost_utils.process_allgather(final, tiled=True)
    return proto.metrics(cfg, final)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="blockchain_simulator_tpu.parallel.multihost")
    p.add_argument("--coordinator", required=True, help="HOST:PORT of process 0")
    p.add_argument("--num-processes", type=int, required=True)
    p.add_argument("--process-id", type=int, required=True)
    p.add_argument("--force-cpu-devices", type=int, default=0,
                   help="force the CPU backend with this many virtual devices "
                        "per process (testing without accelerators)")
    p.add_argument("--protocol", default="pbft")
    p.add_argument("--n", type=int, default=64)
    p.add_argument("--sim-ms", type=int, default=2500)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--delivery", default="edge")
    p.add_argument("--serialization", choices=["on", "off"], default="on")
    p.add_argument("--schedule", choices=["tick", "round", "auto"],
                   default="auto", help="stepping granularity; 'round' pins "
                   "the PBFT round-blocked fast path (models/pbft_round.py)")
    args = p.parse_args(argv)

    if args.force_cpu_devices:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={args.force_cpu_devices}"
            ).strip()
    import jax

    if args.force_cpu_devices:
        jax.config.update("jax_platforms", "cpu")

    from blockchain_simulator_tpu.utils.config import SimConfig

    init_multihost(args.coordinator, args.num_processes, args.process_id)
    cfg = SimConfig(
        protocol=args.protocol,
        n=args.n,
        sim_ms=args.sim_ms,
        seed=args.seed,
        delivery=args.delivery,
        model_serialization=args.serialization == "on",
        schedule=args.schedule,
    )
    m = run_sharded_multihost(cfg)
    if jax.process_index() == 0:
        print(json.dumps({"process_count": jax.process_count(),
                          "device_count": jax.device_count(), **m}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
