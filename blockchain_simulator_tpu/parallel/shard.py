"""Node-shard SPMD execution: thin spec declarations over partition rules.

The entire simulation — state init, the full ``lax.scan`` over ticks, every
delivery collective — runs as one SPMD program over the mesh's ``nodes``
axis: node state ``[N, ...]`` and ring buffers ``[D, N, ...]`` are
row-sharded, and the delivery ops in ``ops/delivery.py`` globalize
sender-side quantities with ``all_gather``/``psum``/``pmax`` over ICI
(SURVEY.md §2: the TPU-native equivalent of the reference's simulated
point-to-point channels).

Since the partition layer landed, each wrapper here is just its *rule
declaration* (regex path patterns → PartitionSpecs, ``parallel/
partition.py``) plus the engine call: specs come from
``partition.match_partition_rules`` and the mesh meets the executable
through ``partition.partition`` — there is no direct ``shard_map`` call
site in this module (tests/test_zzpartition.py pins that).

All four factories here are traced over a 2-device mesh and budget-pinned
by the graph audit (lint/graph/programs.py ``shard.*`` specs).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from blockchain_simulator_tpu.models.base import get_protocol
from blockchain_simulator_tpu.parallel import partition
from blockchain_simulator_tpu.parallel.mesh import NODES_AXIS
from blockchain_simulator_tpu.utils import aotcache
from blockchain_simulator_tpu.utils import prng
from blockchain_simulator_tpu.utils.config import SimConfig

# ----------------------------------------------------- rule declarations ---

# Node state [N, ...]: row-shard dim 0 — except the protocol's
# ``GLOBAL_FIELDS`` (per-slot accumulators): replicated, each shard carries
# a partial that the protocol's ``finalize`` combines.  The rule set itself
# lives in the partition layer (partition.node_dim_rules) — the sharded
# topo programs (parallel/sweep.sharded_topo_sim_fn) declare theirs from
# the same helper.
def state_rules(global_fields=()):
    return partition.node_dim_rules(global_fields)


# Ring/delivery buffers [D, N, ...]: the node axis is dim 1.
BUF_RULES = ((r".*", P(None, NODES_AXIS)),)

# Mixed shard-sim (models/mixed.py): raft leaves [S, ...] row-shard over
# the shard axis; the S-representative PBFT layer is replicated (every
# device steps an identical copy — see mixed.step).
MIXED_RULES = (
    (r"^raft(/|$)", P(NODES_AXIS)),
    (r"^pbft(/|$)", partition.REPLICATED),
)


def state_specs(state, global_fields=()):
    """PartitionSpecs for a state pytree (rule-matched; see state_rules)."""
    return partition.match_partition_rules(state_rules(global_fields), state)


def node_specs(state, bufs, global_fields=()):
    """(state specs, buffer specs) for a (state, bufs) pair."""
    return (
        state_specs(state, global_fields),
        partition.match_partition_rules(BUF_RULES, bufs),
    )


def mixed_specs(state, bufs):
    """PartitionSpecs for the mixed shard-sim's (state, bufs) pair."""
    return (
        partition.match_partition_rules(MIXED_RULES, state),
        partition.match_partition_rules(MIXED_RULES, bufs),
    )


def _partitioned(run, mesh, in_specs, out_specs):
    """The wrappers' one door to the mesh: per-shard specs → shard_map
    (partition.py's fallback path), unjitted — each wrapper embeds the
    result in its own ``@jax.jit`` sim exactly as before the layer
    existed, so the traced IR (and its pinned budget) is unchanged."""
    return partition.partition(
        run, mesh, in_specs=in_specs, out_specs=out_specs, wrap_jit=False
    )


# ------------------------------------------------------------- factories ---


@aotcache.cached_factory("shard-round")
def _make_sharded_round_fn(cfg: SimConfig, mesh: Mesh):
    """Node-sharded round-blocked PBFT fast path (models/pbft_round.py):
    one scan step per 50 ms block interval, node state row-sharded, the
    per-round reductions (slot max, commit-sender totals, trigger/lands)
    riding ``psum``/``pmax`` over ICI.  step_round is written against
    ``cfg.mesh_axis`` exactly like the tick engine's step."""
    from blockchain_simulator_tpu.models import pbft_round

    n_shards = mesh.shape[NODES_AXIS]
    if cfg.n % n_shards != 0:
        raise ValueError(f"n={cfg.n} not divisible by {n_shards} node shards")
    cfg_local = cfg.with_(mesh_axis=NODES_AXIS)

    state0, _ = jax.eval_shape(lambda: pbft_round.init(cfg, jax.random.key(0)))
    state_spec = state_specs(state0, pbft_round.GLOBAL_FIELDS)

    def run(key, state):
        state = pbft_round.scan_rounds(cfg_local, state, key)
        return pbft_round.finalize(state, NODES_AXIS)

    shmapped = _partitioned(
        run, mesh, in_specs=(P(), state_spec), out_specs=state_spec
    )

    @jax.jit
    def sim(key):
        state, _ = pbft_round.init(cfg, jax.random.fold_in(key, 0x1217))
        return shmapped(key, state)

    return sim


@aotcache.cached_factory("shard-raft-hb")
def _make_sharded_raft_hb_fn(cfg: SimConfig, mesh: Mesh):
    """Node-sharded heartbeat-blocked raft fast path (models/raft_hb.py):
    the tick-engine election prefix runs sharded exactly like the general
    engine; the checked handoff is a traced ``lax.cond`` whose predicate and
    leader scalars are psum/pmax-agreed across the mesh, so every device
    takes the same branch — either the replicated O(1) heartbeat scan (each
    shard materializes only its local rows) or a continuation of the sharded
    tick scan from the prefix carry."""
    from blockchain_simulator_tpu.models import raft as raft_tick
    from blockchain_simulator_tpu.models import raft_hb

    n_shards = mesh.shape[NODES_AXIS]
    if cfg.n % n_shards != 0:
        raise ValueError(f"n={cfg.n} not divisible by {n_shards} node shards")
    cfg_local = cfg.with_(mesh_axis=NODES_AXIS)

    state0, bufs0 = jax.eval_shape(lambda: raft_tick.init(cfg, jax.random.key(0)))
    state_spec, bufs_spec = node_specs(state0, bufs0)

    def run(key, state, bufs):
        return raft_hb.scan_from_init(cfg_local, state, bufs, key)

    shmapped = _partitioned(
        run, mesh, in_specs=(P(), state_spec, bufs_spec), out_specs=state_spec
    )

    @jax.jit
    def sim(key):
        state, bufs = raft_tick.init(cfg, jax.random.fold_in(key, 0x1217))
        return shmapped(key, state, bufs)

    return sim


@aotcache.cached_factory("shard-mixed")
def _make_sharded_mixed_fast_fn(cfg: SimConfig, mesh: Mesh):
    """Shard-sharded heartbeat-scheduled mixed sim (models/mixed.scan_fast):
    raft shard rows over the mesh axis, the S-representative PBFT layer
    replicated, the per-shard handoff verdict psum-agreed."""
    from blockchain_simulator_tpu.models import mixed

    n_shards = mesh.shape[NODES_AXIS]
    if cfg.mixed_shards % n_shards != 0:
        raise ValueError(
            f"mixed_shards={cfg.mixed_shards} not divisible by "
            f"{n_shards} mesh shards"
        )
    cfg_local = cfg.with_(mesh_axis=NODES_AXIS)

    state0, bufs0 = jax.eval_shape(lambda: mixed.init(cfg, jax.random.key(0)))
    state_spec, bufs_spec = mixed_specs(state0, bufs0)

    def run(key, state, bufs):
        return mixed.scan_fast(cfg_local, state, bufs, key)

    shmapped = _partitioned(
        run, mesh, in_specs=(P(), state_spec, bufs_spec), out_specs=state_spec
    )

    @jax.jit
    def sim(key):
        state, bufs = mixed.init(cfg, jax.random.fold_in(key, 0x1217))
        return shmapped(key, state, bufs)

    return sim


@aotcache.cached_factory("shard-sim")
def make_sharded_sim_fn(cfg: SimConfig, mesh: Mesh):
    """Jitted ``sim(key) -> final_state`` with node state sharded over the
    mesh's ``nodes`` axis.  ``cfg.n`` must divide by the axis size.

    Schedule resolution matches runner.make_sim_fn: the PBFT round-blocked
    fast path when eligible ('round' explicit, or 'auto' at n >= 4096), the
    raft heartbeat fast path (traced checked handoff — the prefix runs on
    the sharded tick engine, the steady scan is replicated O(1) work), the
    heartbeat-scheduled mixed sim, else the general per-tick engine."""
    from blockchain_simulator_tpu.runner import _reject_cpp_only, use_round_schedule

    _reject_cpp_only(cfg)
    if use_round_schedule(cfg):
        if cfg.protocol == "raft":
            return _make_sharded_raft_hb_fn(cfg, mesh)
        if cfg.protocol == "mixed":
            return _make_sharded_mixed_fast_fn(cfg, mesh)
        return _make_sharded_round_fn(cfg, mesh)
    n_shards = mesh.shape[NODES_AXIS]
    proto = get_protocol(cfg.protocol)
    cfg_local = cfg.with_(mesh_axis=NODES_AXIS)

    state0, bufs0 = jax.eval_shape(lambda: proto.init(cfg, jax.random.key(0)))
    if cfg.protocol == "mixed":
        # the sharded unit is the raft SHARD row, not the node
        if cfg.mixed_shards % n_shards != 0:
            raise ValueError(
                f"mixed_shards={cfg.mixed_shards} not divisible by "
                f"{n_shards} mesh shards"
            )
        state_spec, bufs_spec = mixed_specs(state0, bufs0)
    else:
        if cfg.n % n_shards != 0:
            raise ValueError(f"n={cfg.n} not divisible by {n_shards} node shards")
        state_spec, bufs_spec = node_specs(
            state0, bufs0, getattr(proto, "GLOBAL_FIELDS", ())
        )

    def run(key, state, bufs):
        def body(carry, t):
            st, bf = carry
            st, bf = proto.step(cfg_local, st, bf, t, prng.tick_key(key, t))
            return (st, bf), ()

        (state, bufs), _ = jax.lax.scan(body, (state, bufs), jnp.arange(cfg.ticks))
        if hasattr(proto, "finalize"):
            state = proto.finalize(state, NODES_AXIS)
        return state

    shmapped = _partitioned(
        run, mesh, in_specs=(P(), state_spec, bufs_spec), out_specs=state_spec
    )

    @jax.jit
    def sim(key):
        state, bufs = proto.init(cfg, jax.random.fold_in(key, 0x1217))
        return shmapped(key, state, bufs)

    return sim


def run_sharded(cfg: SimConfig, mesh: Mesh, seed: int | None = None):
    """Run one node-sharded simulation, return the protocol metrics dict."""
    proto = get_protocol(cfg.protocol)
    sim = make_sharded_sim_fn(cfg, mesh)
    key = jax.random.key(cfg.seed if seed is None else seed)
    final = jax.block_until_ready(sim(key))
    return proto.metrics(cfg, final)
