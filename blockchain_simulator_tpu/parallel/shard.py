"""Node-shard SPMD execution via ``shard_map``.

The entire simulation — state init, the full ``lax.scan`` over ticks, every
delivery collective — runs as one SPMD program over the mesh's ``nodes`` axis:
node state ``[N, ...]`` and ring buffers ``[D, N, ...]`` are row-sharded, and
the delivery ops in ``ops/delivery.py`` globalize sender-side quantities with
``all_gather``/``psum``/``pmax`` over ICI (SURVEY.md §2: the TPU-native
equivalent of the reference's simulated point-to-point channels).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from blockchain_simulator_tpu.models.base import get_protocol
from blockchain_simulator_tpu.parallel.mesh import NODES_AXIS
from blockchain_simulator_tpu.utils import prng
from blockchain_simulator_tpu.utils.config import SimConfig


def mixed_specs(state, bufs):
    """PartitionSpecs for the mixed shard-sim (models/mixed.py): raft leaves
    ``[S, ...]`` row-shard over the shard axis; the S-representative PBFT
    layer is replicated (every device steps an identical copy — see
    mixed.step)."""
    shard0 = lambda x: P(NODES_AXIS, *([None] * (x.ndim - 1)))
    repl = lambda x: P(*([None] * x.ndim))
    return (
        type(state)(
            raft=jax.tree.map(shard0, state.raft),
            pbft=jax.tree.map(repl, state.pbft),
        ),
        type(bufs)(
            raft=jax.tree.map(shard0, bufs.raft),
            pbft=jax.tree.map(repl, bufs.pbft),
        ),
    )


def state_specs(state, global_fields=()):
    """PartitionSpecs for a state pytree: leaves are [N, ...] (shard dim 0)
    except the protocol's ``GLOBAL_FIELDS`` (per-slot accumulators,
    replicated spec — each shard carries a partial that the protocol's
    ``finalize`` combines)."""

    def state_leaf_spec(path, x):
        name = path[-1].name if hasattr(path[-1], "name") else None
        if name in global_fields:
            return P(*([None] * x.ndim))
        return P(NODES_AXIS, *([None] * (x.ndim - 1)))

    return jax.tree_util.tree_map_with_path(state_leaf_spec, state)


def node_specs(state, bufs, global_fields=()):
    """PartitionSpecs: state leaves per ``state_specs``; buffer leaves are
    [D, N, ...] (shard dim 1)."""
    bufs_spec = jax.tree.map(
        lambda x: P(None, NODES_AXIS, *([None] * (x.ndim - 2))), bufs
    )
    return state_specs(state, global_fields), bufs_spec


@functools.lru_cache(maxsize=64)
def _make_sharded_round_fn(cfg: SimConfig, mesh: Mesh):
    """Node-sharded round-blocked PBFT fast path (models/pbft_round.py):
    one scan step per 50 ms block interval, node state row-sharded, the
    per-round reductions (slot max, commit-sender totals, trigger/lands)
    riding ``psum``/``pmax`` over ICI.  step_round is written against
    ``cfg.mesh_axis`` exactly like the tick engine's step."""
    from blockchain_simulator_tpu.models import pbft_round

    n_shards = mesh.shape[NODES_AXIS]
    if cfg.n % n_shards != 0:
        raise ValueError(f"n={cfg.n} not divisible by {n_shards} node shards")
    cfg_local = cfg.with_(mesh_axis=NODES_AXIS)

    state0, _ = jax.eval_shape(lambda: pbft_round.init(cfg, jax.random.key(0)))
    state_spec = state_specs(state0, pbft_round.GLOBAL_FIELDS)

    def run(key, state):
        state = pbft_round.scan_rounds(cfg_local, state, key)
        return pbft_round.finalize(state, NODES_AXIS)

    shmapped = jax.shard_map(
        run,
        mesh=mesh,
        in_specs=(P(), state_spec),
        out_specs=state_spec,
        check_vma=False,  # same waiver as the tick path below
    )

    @jax.jit
    def sim(key):
        state, _ = pbft_round.init(cfg, jax.random.fold_in(key, 0x1217))
        return shmapped(key, state)

    return sim


@functools.lru_cache(maxsize=64)
def make_sharded_sim_fn(cfg: SimConfig, mesh: Mesh):
    """Jitted ``sim(key) -> final_state`` with node state sharded over the
    mesh's ``nodes`` axis.  ``cfg.n`` must divide by the axis size.

    Schedule resolution: the PBFT round-blocked fast path when eligible
    ('round' explicit, or 'auto' at n >= 4096), else the general per-tick
    engine.  Raft differs from runner.make_sim_fn here: its heartbeat fast
    path (models/raft_hb.py) is O(1) per step and single-chip by design, so
    sharded raft always runs the tick engine ('round' explicit raises)."""
    from blockchain_simulator_tpu.runner import _reject_cpp_only, use_round_schedule

    _reject_cpp_only(cfg)
    if use_round_schedule(cfg):
        if cfg.protocol == "raft":
            # the raft heartbeat fast path is O(1) per step (leader-centric
            # aggregation, models/raft_hb.py) — sharding it is meaningless;
            # sharded raft always runs the tick engine
            if cfg.schedule == "round":
                raise ValueError(
                    "schedule='round' (heartbeat fast path) is single-chip "
                    "for raft — its steady state is O(1) per step; use "
                    "schedule='tick'/'auto' for sharded raft"
                )
            cfg = cfg.with_(schedule="tick")
        else:
            return _make_sharded_round_fn(cfg, mesh)
    n_shards = mesh.shape[NODES_AXIS]
    proto = get_protocol(cfg.protocol)
    cfg_local = cfg.with_(mesh_axis=NODES_AXIS)

    state0, bufs0 = jax.eval_shape(lambda: proto.init(cfg, jax.random.key(0)))
    if cfg.protocol == "mixed":
        # the sharded unit is the raft SHARD row, not the node
        if cfg.mixed_shards % n_shards != 0:
            raise ValueError(
                f"mixed_shards={cfg.mixed_shards} not divisible by "
                f"{n_shards} mesh shards"
            )
        state_spec, bufs_spec = mixed_specs(state0, bufs0)
    else:
        if cfg.n % n_shards != 0:
            raise ValueError(f"n={cfg.n} not divisible by {n_shards} node shards")
        state_spec, bufs_spec = node_specs(
            state0, bufs0, getattr(proto, "GLOBAL_FIELDS", ())
        )

    def run(key, state, bufs):
        def body(carry, t):
            st, bf = carry
            st, bf = proto.step(cfg_local, st, bf, t, prng.tick_key(key, t))
            return (st, bf), ()

        (state, bufs), _ = jax.lax.scan(body, (state, bufs), jnp.arange(cfg.ticks))
        if hasattr(proto, "finalize"):
            state = proto.finalize(state, NODES_AXIS)
        return state

    shmapped = jax.shard_map(
        run,
        mesh=mesh,
        in_specs=(P(), state_spec, bufs_spec),
        out_specs=state_spec,
        check_vma=False,  # delivery ops mix gathered (unreplicated) and
        # replicated values; correctness is covered by the
        # sharded-vs-unsharded equivalence test
    )

    @jax.jit
    def sim(key):
        state, bufs = proto.init(cfg, jax.random.fold_in(key, 0x1217))
        return shmapped(key, state, bufs)

    return sim


def run_sharded(cfg: SimConfig, mesh: Mesh, seed: int | None = None):
    """Run one node-sharded simulation, return the protocol metrics dict."""
    proto = get_protocol(cfg.protocol)
    sim = make_sharded_sim_fn(cfg, mesh)
    key = jax.random.key(cfg.seed if seed is None else seed)
    final = jax.block_until_ready(sim(key))
    return proto.metrics(cfg, final)
