"""Device mesh construction.

TPU-native replacement for the reference's "distributed backend": where the
reference fans out over simulated UDP sockets on one CPU thread
(SURVEY.md §2 parallelism checklist — it has no real parallelism at all),
this framework shards the node axis of every state/buffer tensor over a
``jax.sharding.Mesh`` and lets XLA insert ICI collectives.  Axes:

- ``"nodes"``  — node-shard parallelism (SPMD over the simulated cluster).
- ``"sweep"``  — batch whole simulations (seeds / fault configs).

Multi-host: build the mesh from ``jax.devices()`` after
``jax.distributed.initialize()`` — the same specs then span DCN.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

NODES_AXIS = "nodes"
SWEEP_AXIS = "sweep"


def make_mesh(n_node_shards: int | None = None, n_sweep: int = 1, devices=None) -> Mesh:
    """A (sweep, nodes) mesh. Defaults to all available devices on nodes."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    if n_node_shards is None:
        if devices.size % n_sweep != 0:
            raise ValueError(
                f"{devices.size} devices not divisible by n_sweep={n_sweep}"
            )
        n_node_shards = devices.size // n_sweep
    if n_sweep * n_node_shards > devices.size:
        raise ValueError(
            f"mesh {n_sweep}x{n_node_shards} needs {n_sweep * n_node_shards} "
            f"devices, only {devices.size} available"
        )
    devices = devices[: n_sweep * n_node_shards].reshape(n_sweep, n_node_shards)
    return Mesh(devices, (SWEEP_AXIS, NODES_AXIS))
