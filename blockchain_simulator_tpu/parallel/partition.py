"""Partition rules: the single place meshes meet executables.

Every mesh-partitioned program in the repo — the node-sharded sim wrappers
(parallel/shard.py), the mesh-partitioned fault sweeps (parallel/sweep.py)
and the scenario server's mesh-sharded batched dispatch (serve/dispatch.py)
— goes through one of two doors here:

- :func:`match_partition_rules` turns a declaration of ``(regex path
  pattern, PartitionSpec)`` rules into a full PartitionSpec pytree for any
  state/buffer tree (the SNIPPETS.md [2] pattern): first ``re.search``
  match on the ``/``-joined key path wins, scalar leaves are never
  partitioned, specs are rank-padded so ``shard_map`` sees full-rank specs.
- :func:`partition` compiles a function against a mesh (the SNIPPETS.md
  [3] pattern): explicit global-view shardings prefer **pjit** (``jax.jit``
  with ``NamedSharding``s — XLA GSPMD partitions the internals), per-shard
  specs fall back to a **shard_map-wrapped jit** (map-style named-axis
  collectives, the sim wrappers' delivery ops), and a **mesh of size 1
  degenerates** to a plain ``jax.jit`` under the mesh context so the
  compiled program is bit-identical to the unpartitioned one.

Why the sweep path partitions the BATCH axis with shard_map rather than
vmap sharding: a vmapped sim lowers its dynamic-update-slice pushes to
scatter (lint/graph found them; XLA:CPU serializes scatter —
KNOWN_ISSUES.md #0b), so the per-device body here is a ``lax.map`` of the
UNVMAPPED program — plain DUS, no batch lockstep.  Measured on the
8-virtual-device CPU mesh (tools/mesh_sweep_bench.py): ~2.3x per lane over
the single-device vmapped sweep program at 10k nodes, rows bit-equal under
the exact sampler.  On a real TPU mesh the devices additionally run in
parallel; on the 1-core CPU box the win is purely the scatter-free body.

Registry contract: partitioned executables live in the unified registry
(utils/aotcache.py) keyed on ``(factory, cfg, mesh)`` — the mesh IS part of
the key, so a mesh-sharded entry never collides with the single-device one
and the one-executable-per-fault-structure sweep contract survives per
mesh (tests/test_zzpartition.py pins it).
"""

from __future__ import annotations

import functools
import re

import numpy as np

REPLICATED = None  # sentinel alias: a rule spec of None means "replicate"

# The three compilation arms of :func:`partition`, as stable strings: every
# partitioned callable is tagged with ``partition_arm`` / ``partition_mesh``
# attributes (see _tag_arm) so the comms auditor (lint/comms) can report
# WHICH door a program went through without re-deriving the dispatch.
PJIT_ARM = "pjit"              # explicit shardings; XLA GSPMD partitions
SHARD_MAP_ARM = "shard_map"    # per-shard specs; map-style collectives
SINGLE_DEVICE_ARM = "single"   # size-1 mesh degenerate: plain jit


def _spec_cls():
    from jax.sharding import PartitionSpec

    return PartitionSpec


def mesh_size(mesh) -> int:
    """Total device count of a mesh."""
    return int(np.asarray(mesh.devices).size)


def path_name(path) -> str:
    """``/``-joined name of a pytree key path (dict keys, dataclass/struct
    field names, sequence indices)."""
    parts = []
    for p in path:
        if hasattr(p, "name"):
            parts.append(str(p.name))
        elif hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def match_partition_rules(rules, tree):
    """PartitionSpec pytree for ``tree`` from ``((regex, spec), ...)`` rules.

    The first rule whose pattern ``re.search``-matches the leaf's
    ``/``-joined path wins; its spec (a ``PartitionSpec`` or
    :data:`REPLICATED`) is padded with ``None`` to the leaf's rank, so
    ``P(NODES_AXIS)`` declares "shard dim 0, replicate the rest" for any
    rank.  Scalar (0-d or size-1) leaves are never partitioned.  A
    non-scalar leaf matching no rule raises — silent replication is how
    sharding bugs hide (SNIPPETS.md [2] raises the same way).
    """
    import jax

    P = _spec_cls()
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def spec_for(path, leaf):
        shape = getattr(leaf, "shape", ())
        ndim = len(shape)
        if ndim == 0 or int(np.prod(shape)) == 1:
            return P()
        name = path_name(path)
        for pat, spec in compiled:
            if pat.search(name) is None:
                continue
            entries = tuple(spec) if spec is not None else ()
            if len(entries) > ndim:
                raise ValueError(
                    f"partition rule {pat.pattern!r} spec {spec} has "
                    f"{len(entries)} entries for rank-{ndim} leaf {name!r}"
                )
            return P(*entries, *([None] * (ndim - len(entries))))
        raise ValueError(f"no partition rule matched leaf {name!r} "
                         f"(shape {tuple(shape)})")

    return jax.tree_util.tree_map_with_path(spec_for, tree)


def mesh_tag(mesh) -> str:
    """Stable mesh descriptor for program/budget keys: axis names and
    sizes with size-1 axes elided (``"sweep2_nodes4"``), ``"single"`` for
    a 1-device mesh.  The comms baseline (COMMS_BASELINE.json) keys every
    budget on ``program@tag`` so a 2-device audit pin never collides with
    a 4-device one."""
    parts = [
        f"{name}{size}"
        for name, size in mesh_shape_dict(mesh).items() if int(size) > 1
    ]
    return "_".join(parts) if parts else "single"


def _tag_arm(fn, mesh, arm):
    """Best-effort arm/mesh metadata on a partitioned callable (jit
    wrappers accept attributes on this jax; a C-level wrapper that refuses
    just stays untagged — the metadata is advisory, never load-bearing)."""
    try:
        fn.partition_arm = arm
        fn.partition_mesh = mesh_shape_dict(mesh)
    except (AttributeError, TypeError):
        pass
    return fn


def _shard_map(f, mesh, in_specs, out_specs):
    """``shard_map`` across jax versions: ``jax.shard_map`` + ``check_vma``
    on current releases, ``jax.experimental.shard_map`` + ``check_rep`` on
    0.4.x.  Replication checking is waived either way: delivery ops mix
    gathered (unreplicated) and replicated values; correctness is covered
    by the sharded-vs-unsharded equivalence tests."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as sm

    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              check_rep=False)


def _named_shardings(mesh, specs):
    """PartitionSpec pytree -> NamedSharding pytree over ``mesh`` (specs
    are the pytree leaves: a bare spec broadcasts as a jit prefix)."""
    import jax
    from jax.sharding import NamedSharding

    P = _spec_cls()
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda s: isinstance(s, P),
    )


def partition(fn, mesh, *, in_shardings=None, out_shardings=None,
              in_specs=None, out_specs=None, wrap_jit=True):
    """Compile ``fn`` against ``mesh`` — the one mesh↔executable door.

    Exactly one style may be given (the SNIPPETS.md [3] selection rule):

    - ``in_shardings``/``out_shardings`` (PartitionSpec pytrees, *global*
      view): explicit shardings are honoured via pjit — ``jax.jit`` with
      ``NamedSharding``s; XLA GSPMD partitions the function body.  Both
      must be given, or neither makes sense to honour.  A mesh of size 1
      degenerates to plain ``jax.jit(fn)`` run under the mesh context —
      bit-identical to the unpartitioned program (pinned in
      tests/test_zzpartition.py).
    - ``in_specs``/``out_specs`` (PartitionSpec pytrees, *per-shard*
      view): the shard_map-wrapped-jit fallback — map-style collectives
      over the mesh axis names (``psum``/``all_gather`` in
      ops/delivery.py).  Size-1 meshes keep the shard_map wrapper: the
      body's axis names must stay bound (a 1-device ``psum`` is identity).
      ``wrap_jit=False`` returns the bare shard_map-wrapped function for
      callers that embed it in a larger jitted program (the shard.py sim
      wrappers init state under their own jit) — the traced IR is then
      identical to a hand-rolled shard_map call.
    """
    explicit = in_shardings is not None or out_shardings is not None
    mapped = in_specs is not None or out_specs is not None
    if explicit and mapped:
        raise ValueError(
            "partition() takes either explicit shardings (pjit) or "
            "per-shard specs (shard_map), not both"
        )
    if explicit:
        if in_shardings is None or out_shardings is None:
            raise ValueError(
                "partition() requires both in_shardings and out_shardings "
                "when using explicit-sharding pjit; pass in_specs/out_specs "
                "for the shard_map fallback instead"
            )
        if not wrap_jit:
            raise ValueError(
                "wrap_jit=False is a shard_map-path option: jit IS the "
                "pjit mechanism for explicit shardings"
            )
        import jax

        # per-call jit is this layer's JOB: every caller is itself a
        # @cached_factory factory (shard.py wrappers, sweep.mesh_dyn_
        # batched_fn), so the registry memoizes the wrapper one level up —
        # the same sanctioning the rule grants those factories directly
        if mesh_size(mesh) == 1:
            @functools.wraps(fn)
            def single_device_fn(*args):
                with mesh:
                    return fn(*args)

            return _tag_arm(jax.jit(single_device_fn), mesh,  # jaxlint: disable=static-arg-recompile-hazard
                            SINGLE_DEVICE_ARM)
        return _tag_arm(
            jax.jit(  # jaxlint: disable=static-arg-recompile-hazard
                fn,
                in_shardings=_named_shardings(mesh, in_shardings),
                out_shardings=_named_shardings(mesh, out_shardings),
            ),
            mesh, PJIT_ARM,
        )
    if not mapped:
        raise ValueError(
            "partition() needs in_shardings/out_shardings (pjit) or "
            "in_specs/out_specs (shard_map)"
        )
    shmapped = _shard_map(fn, mesh, in_specs, out_specs)
    if not wrap_jit:
        return _tag_arm(shmapped, mesh, SHARD_MAP_ARM)
    import jax

    # cached one level up, same as the explicit-sharding arm above
    return _tag_arm(jax.jit(shmapped), mesh, SHARD_MAP_ARM)  # jaxlint: disable=static-arg-recompile-hazard


# ----------------------------------------------------- node-dim rule sets ---


def node_dim_rules(replicated_names=()):
    """``((regex, spec), ...)`` declaring: the named leaves replicate,
    every other non-scalar leaf shards dim 0 over the nodes axis.

    The one rule shape every node-dim consumer shares
    (:func:`match_partition_rules` turns it into full specs per tree):
    the sharded sim wrappers' per-node state (parallel/shard.state_rules
    passes the protocol's ``GLOBAL_FIELDS``), the kregular ``[N, K]``
    overlay-table operands and unbatched ``[N, ...]`` finals, and the
    committee path's ``[C, ...]`` stacked finals (dim 0 is the committee
    axis — the hierarchy's node-dim analog) in
    parallel/sweep.sharded_topo_sim_fn."""
    from blockchain_simulator_tpu.parallel.mesh import NODES_AXIS

    P = _spec_cls()
    rules = tuple(
        (rf"(^|/){re.escape(name)}$", REPLICATED)
        for name in replicated_names
    )
    return rules + ((r".*", P(NODES_AXIS)),)


# ------------------------------------------- shard-local neighbor exchange ---


class NeighborExchange:
    """Owner-bucketed cross-shard neighbor reads — the runtime half of
    ``topo.spec.owner_bucket_plan``.

    ``xg(x, kind="in")`` computes exactly ``jnp.take(x, table, axis=0)``
    for the kind's ``[N, K]`` overlay table, and ``xg(x, kind=..., col=c)``
    exactly ``jnp.take(x.reshape(-1), table * x.shape[1] + c)`` — but the
    only communication is ONE ``all_to_all`` of the static ``[D, C, ...]``
    owner buckets per call under ``shard_map``: no operand, intermediate,
    or gather result is ever materialized at global shape on any device.
    The result is a pure permutation + local gather of ``x``'s rows, so it
    is bit-equal to the global gather by construction (pinned in
    tests/test_zzexchange.py at mesh sizes 1/2/4/8).

    The plan arrays (``pos``/``send`` per table kind) are PROGRAM OPERANDS
    (traced, ``P(nodes)``-sharded), not constants: they ride the compiled
    program next to the table operands (sweep.sharded_topo_sim_fn), so the
    executable stays one-per-fault-structure and carries no O(N) consts
    (the <64KB jaxpr-consts pin in tests/test_zzshardtopo.py).
    """

    def __init__(self, mesh, n: int, plans: dict):
        if not plans:
            raise ValueError("NeighborExchange needs at least one plan")
        self.mesh = mesh
        self.n = int(n)
        self.plans = dict(plans)
        pos, send = next(iter(self.plans.values()))
        self.n_shards = int(send.shape[0])
        self.n_pad = int(pos.shape[0])

    def _pad(self, a):
        import jax.numpy as jnp

        pad = self.n_pad - int(a.shape[0])
        if pad:
            a = jnp.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1))
        return a

    def __call__(self, x, kind: str = "in", col=None):
        import jax.numpy as jnp
        from jax import lax

        from blockchain_simulator_tpu.parallel.mesh import NODES_AXIS

        pos, send = self.plans[kind]
        d, c = int(send.shape[0]), int(send.shape[2])
        P = _spec_cls()
        sliced = self.n_pad - int(x.shape[0])
        x = self._pad(x)

        if col is None:
            def body(x_loc, pos_loc, send_loc):
                sb = jnp.take(x_loc, send_loc[0], axis=0)     # [D, C, ...]
                rb = lax.all_to_all(sb, NODES_AXIS,
                                    split_axis=0, concat_axis=0)
                flat = rb.reshape((d * c,) + rb.shape[2:])
                return jnp.take(flat, pos_loc, axis=0)        # [n_loc, K, .]
            out = _shard_map(
                body, self.mesh,
                (P(NODES_AXIS), P(NODES_AXIS), P(NODES_AXIS)),
                P(NODES_AXIS),
            )(x, pos, send)
        else:
            w = int(x.shape[1])
            col = self._pad(col)

            def body(x_loc, pos_loc, send_loc, col_loc):
                sb = jnp.take(x_loc, send_loc[0], axis=0)     # [D, C, w]
                rb = lax.all_to_all(sb, NODES_AXIS,
                                    split_axis=0, concat_axis=0)
                flat = rb.reshape((d * c * w,))
                return jnp.take(flat, pos_loc * w + col_loc, axis=0)
            out = _shard_map(
                body, self.mesh,
                (P(NODES_AXIS), P(NODES_AXIS), P(NODES_AXIS), P(NODES_AXIS)),
                P(NODES_AXIS),
            )(x, pos, send, col)
        return out[: self.n] if sliced else out


class ExchangeSpec:
    """Static description of the exchange-plan operand block a sharded
    kregular program appends after its table operands: ``(pos, send)`` per
    table kind, in ``kinds`` order.  The factory (sweep.sharded_topo_sim_fn)
    builds the plan arrays once per executable from the PADDED tables
    (topo.spec.owner_bucket_plan) and threads this spec through
    runner.make_topo_dyn_sim_fn so the traced sim can rebind them into a
    :class:`NeighborExchange` at trace time."""

    def __init__(self, mesh, n: int, kinds=("in", "out")):
        self.mesh = mesh
        self.n = int(n)
        self.kinds = tuple(kinds)

    @property
    def n_operands(self) -> int:
        return 2 * len(self.kinds)

    def build(self, *plan_operands) -> NeighborExchange:
        if len(plan_operands) != self.n_operands:
            raise ValueError(
                f"ExchangeSpec.build: expected {self.n_operands} plan "
                f"operands ({'/'.join(self.kinds)} pos+send), got "
                f"{len(plan_operands)}"
            )
        plans = {
            k: (plan_operands[2 * i], plan_operands[2 * i + 1])
            for i, k in enumerate(self.kinds)
        }
        return NeighborExchange(self.mesh, self.n, plans)


# ----------------------------------------------------- mesh-sweep helpers ---


def sweep_axis_size(mesh) -> int:
    """Size of the mesh's sweep axis (0 when the mesh has none)."""
    from blockchain_simulator_tpu.parallel.mesh import SWEEP_AXIS

    return int(dict(mesh.shape).get(SWEEP_AXIS, 0))


def seq_map(fn):
    """``batched(*operands) -> finals`` running the batch SEQUENTIALLY
    through the UNVMAPPED ``fn`` via ``lax.map`` — the scatter-free batch
    body (KNOWN_ISSUES #0i): per-lane dynamic-update-slice pushes stay
    plain DUS instead of vmap's DUS→scatter lowering, which XLA:CPU
    serializes, and each lane is a batch-1-shaped program (the only shape
    ever observed to work on the TPU, issue #2).  Shared by the
    mesh-partitioned sweep's per-device body (sweep.mesh_dyn_batched_fn)
    and the single-device multi-seed tick executable
    (sweep.multi_seed_fn) so the two arms stay one mechanism."""
    import jax

    def batched(*operands):
        return jax.lax.map(lambda args: fn(*args), operands)

    return batched


def pad_points(points, lanes: int):
    """Pad ``points`` (any list) to a multiple of ``lanes`` by repeating the
    last element — the uneven-grid lanes of a mesh dispatch (a padded lane
    costs one discarded per-device map step, same trade as serve's bucket
    padding).  Returns ``(padded, n_real)``."""
    points = list(points)
    if not points:
        raise ValueError("pad_points needs at least one point")
    n_real = len(points)
    rem = n_real % lanes
    if rem:
        points = points + [points[-1]] * (lanes - rem)
    return points, n_real


def align_chunk(chunk_size: int, lanes: int) -> int:
    """Round a journal chunk size up to a multiple of the mesh's sweep
    lanes (parallel/journal.py chunking): every chunk then pads at most
    one partial tile through :func:`pad_points`, instead of every chunk
    paying ``lanes - (size % lanes)`` discarded lanes."""
    chunk_size = max(1, int(chunk_size))
    lanes = max(1, int(lanes))
    rem = chunk_size % lanes
    return chunk_size + (lanes - rem if rem else 0)


def mesh_shape_dict(mesh) -> dict:
    """``{axis name: size}`` of a mesh as plain JSON-able types — the one
    serialization every mesh-reporting surface shares (serve batch blocks,
    /stats, the daemon READY line)."""
    return {str(k): int(v) for k, v in dict(mesh.shape).items()}


def batched_out_shardings(cfg, mesh, out_avals):
    """Global-view out specs for a vmapped ``[B, ...]`` final-state pytree:
    batch dim over the sweep axis; when the mesh also has >1 node shards,
    a leaf whose dim 1 is ``cfg.n``-sized rides the nodes axis there (the
    "node axis optionally sharded for large n" option — GSPMD propagates
    the constraint into the scan).  Only dim 1 is considered: node state
    is ``[N, ...]`` by repo convention (shard.py), and shape-matching
    deeper dims could tag a same-sized non-node dim (e.g. a slot table at
    ``pbft_max_slots == n``) — such leaves just stay replicated.

    Topology rule (topo/): the committee path's finals are stacked
    ``[B, C, m, ...]`` (topo/committee.py) — there dim 1 is the COMMITTEE
    axis, the node-dim analog of the hierarchy, and it rides the nodes
    axis when it divides evenly; kregular finals keep the flat ``[B, N,
    ...]`` shape and the same dim-1 rule applies.  The UNBATCHED topo
    programs (sweep.sharded_topo_sim_fn) don't come through here: their
    node dim is dim 0 and their overlay tables are real operands —
    :func:`node_dim_rules` declares those."""
    import jax

    from blockchain_simulator_tpu.parallel.mesh import NODES_AXIS, SWEEP_AXIS

    P = _spec_cls()
    n_nodes = int(dict(mesh.shape).get(NODES_AXIS, 1))
    sweep = SWEEP_AXIS if sweep_axis_size(mesh) > 1 else None
    node_dim = (cfg.committees if cfg.topology == "committee" else cfg.n)

    def leaf_spec(leaf):
        shape = tuple(getattr(leaf, "shape", ()))
        entries = [sweep]
        for i, d in enumerate(shape[1:]):
            if (i == 0 and n_nodes > 1 and d == node_dim
                    and node_dim % n_nodes == 0):
                entries.append(NODES_AXIS)
            else:
                entries.append(None)
        return P(*entries)

    return jax.tree.map(leaf_spec, out_avals)
