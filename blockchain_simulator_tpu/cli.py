"""Command-line driver.

The reference's ``main`` (blockchain-simulator.cc:63) instantiates
``ns3::CommandLine`` but registers zero flags (SURVEY.md §5): N is hard-coded
to 8, the protocol is chosen by *editing two source files*
(network-helper.cc:17, blockchain-simulator.cc:72), and every operating
constant is a literal.  Here every one of those constants is a runtime flag
over the typed ``SimConfig`` (utils/config.py), the protocol is selected by
name, and the execution engine is switchable between the JAX/TPU backend and
the C++ CPU reference engine.

    python -m blockchain_simulator_tpu --protocol pbft --n 8 --sim-ms 2500
    python -m blockchain_simulator_tpu --protocol paxos --engine cpp --seeds 0 1 2
    python -m blockchain_simulator_tpu --protocol raft --n 64 --shards 8

Output: one JSON metrics line per run (the reference's NS_LOG measurement
surface as structured data, SURVEY.md §5).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys

from blockchain_simulator_tpu.utils import obs
from blockchain_simulator_tpu.utils.config import FaultConfig, SimConfig


def build_parser() -> argparse.ArgumentParser:
    d = SimConfig()
    p = argparse.ArgumentParser(
        prog="blockchain_simulator_tpu",
        description="TPU-native blockchain-consensus simulation framework",
    )
    p.add_argument("--protocol", choices=["pbft", "raft", "paxos", "mixed"],
                   default=d.protocol)
    p.add_argument("--n", type=int, default=d.n, help="cluster size")
    p.add_argument("--sim-ms", type=int, default=d.sim_ms,
                   help="virtual-time window in ms")
    p.add_argument("--seed", type=int, default=d.seed)
    p.add_argument("--seeds", type=int, nargs="+", default=None,
                   help="seed sweep (batched on the JAX engine)")
    p.add_argument("--fidelity", choices=["reference", "clean"],
                   default=d.fidelity)
    p.add_argument("--delivery", choices=["edge", "stat"], default=d.delivery)
    p.add_argument("--schedule", choices=["tick", "round", "auto"],
                   default=d.schedule,
                   help="tick = general 1ms-tick engine; round = phase-"
                        "blocked fast path (PBFT: one step per block "
                        "interval; raft: per heartbeat behind a traced "
                        "checked election handoff; mixed: the heartbeat "
                        "scan inside every raft shard); auto = round when "
                        "eligible and n >= 4096 (mixed: whenever eligible)")
    p.add_argument("--stat-sampler", choices=["exact", "normal", "auto"],
                   default=d.stat_sampler,
                   help="binomial sampler for stat-delivery bucket counts: "
                        "exact = BTRS rejection; normal = Gaussian "
                        "approximation (fast at large n); auto = by n")
    p.add_argument("--engine", choices=["jax", "cpp"], default="jax",
                   help="jax = tensorized TPU backend; cpp = serial "
                        "per-message C++ reference engine")
    p.add_argument("--shards", type=int, default=0,
                   help="shard node state over this many devices (jax engine)")
    p.add_argument("--link-delay-ms", type=int, default=d.link_delay_ms)
    p.add_argument("--serialization", choices=["on", "off"],
                   default="on" if d.model_serialization else "off",
                   help="model per-message block serialization time "
                        "(bytes*8/link_rate; the reference's dominant "
                        "timing term) in addition to propagation delay")
    # topology axis (topo/): full mesh, gossip flood (BASELINE config 3),
    # kregular gather overlay, or two-level committee hierarchy
    p.add_argument("--topology",
                   choices=["full", "dense", "gossip", "kregular", "committee"],
                   default=d.topology,
                   help="full/dense = reference full mesh; gossip = TTL "
                        "flood over a random k-out digraph; kregular = "
                        "fixed-degree circulant overlay with gather-based "
                        "direct delivery (O(N*k) memory; bit-equal to the "
                        "mesh at --degree n-1); committee = the flat "
                        "protocol inside each of --committees committees "
                        "plus an outer representative aggregate")
    p.add_argument("--degree", type=int, default=d.degree,
                   help="out-degree k (gossip flood fan-out / kregular "
                        "overlay degree)")
    p.add_argument("--gossip-hops", type=int, default=d.gossip_hops,
                   help="flood TTL (gossip)")
    p.add_argument("--committees", type=int, default=d.committees,
                   help="committee count (topology=committee; must divide n)")
    p.add_argument("--topo-seed", type=int, default=d.topo_seed,
                   help="kregular overlay-builder seed (separate from the "
                        "run seed so sweeps share one overlay/executable)")
    p.add_argument("--paxos-timeout-ms", type=int, default=d.paxos_retry_timeout_ms,
                   help="clean-fidelity retry window timeout")
    p.add_argument("--paxos-client", nargs=2, type=int, default=None,
                   metavar=("NODE", "MS"),
                   help="CLIENT_PROPOSE hook (paxos-node.cc:357-361): proposer "
                        "lane NODE fires requireTicket at MS instead of t=0")
    # C++-engine-only transport/fidelity extras
    p.add_argument("--echo-back", action="store_true",
                   help="reflect every received packet to its sender once "
                        "(bounded quirk #1; --engine cpp only)")
    p.add_argument("--queued-links", action="store_true",
                   help="ns-3-exact serial-link transport: packets queue per "
                        "directed 3 Mbps link (--engine cpp only)")
    p.add_argument("--quorum-rule", choices=["n2", "2f1"], default=d.quorum_rule,
                   help="n2 = reference majority thresholds (no vote dedup); "
                        "2f1 = Byzantine-safe 2f+1 quorum with per-sender dedup")
    # faults
    p.add_argument("--crash", type=int, default=-1,
                   help="number of crashed nodes")
    p.add_argument("--byzantine", type=int, default=0,
                   help="number of vote-flipping nodes")
    p.add_argument("--byz-forge", action="store_true",
                   help="Byzantine nodes flood forged COMMIT votes for a "
                        "never-proposed slot (pbft)")
    p.add_argument("--byz-copies", type=int, default=3,
                   help="forged vote copies per sender under n2 counting")
    p.add_argument("--drop", type=float, default=0.0,
                   help="per-message drop probability")
    p.add_argument("--byz-sweep", action="store_true",
                   help="BASELINE config 4: sweep Byzantine f = 0..(n-1)//3 "
                        "with vote forging; one JSON line per (f, seed)")
    # per-protocol knobs (reference values as defaults)
    p.add_argument("--pbft-interval-ms", type=int, default=d.pbft_block_interval_ms)
    p.add_argument("--pbft-rounds", type=int, default=d.pbft_max_rounds)
    p.add_argument("--pbft-max-slots", type=int, default=d.pbft_max_slots,
                   help="vote-table slots; rounds are capped at "
                        "min(pbft_rounds, pbft_max_slots)")
    p.add_argument("--pbft-window", type=int, default=d.pbft_window,
                   help="live vote-state window W (0 = exact full table); "
                        "the O(N*W) memory lever at 100k nodes")
    p.add_argument("--pbft-tx-speed", type=int, default=d.pbft_tx_speed,
                   help="offered tx/s; with --pbft-tx-size sets the block "
                        "size (pbft-node.cc:104-105; 300 is the sustainable "
                        "rate on the 3 Mbps link the serialization-aware "
                        "round path needs, models/pbft_round.py)")
    p.add_argument("--pbft-tx-size", type=int, default=d.pbft_tx_size)
    p.add_argument("--raft-heartbeat-ms", type=int, default=d.raft_heartbeat_ms)
    p.add_argument("--raft-blocks", type=int, default=d.raft_max_blocks)
    p.add_argument("--raft-tx-speed", type=int, default=d.raft_tx_speed)
    p.add_argument("--raft-tx-size", type=int, default=d.raft_tx_size)
    p.add_argument("--paxos-proposers", type=int, default=d.paxos_n_proposers)
    p.add_argument("--mixed-shards", type=int, default=d.mixed_shards,
                   help="raft shard count for --protocol mixed")
    p.add_argument("--timing", action="store_true",
                   help="include wallclock timing in the output")
    # observability (utils/trace.py; the reference's NS_LOG surface as data)
    p.add_argument("--trace", metavar="FILE.npz",
                   help="record the probe series (committed blocks, views, "
                        "elections, ...) to an .npz next to the metrics "
                        "line — per tick on the general engine, per round/"
                        "heartbeat on the fast paths (utils/trace.py); "
                        "with --seeds, one FILE.<seed>.npz per seed")
    p.add_argument("--profile", metavar="LOGDIR",
                   help="capture a jax.profiler trace of the (pre-compiled) "
                        "run into LOGDIR (view with TensorBoard/perfetto)")
    return p


def config_from_args(args) -> SimConfig:
    return SimConfig(
        protocol=args.protocol,
        n=args.n,
        sim_ms=args.sim_ms,
        seed=args.seed,
        fidelity=args.fidelity,
        delivery=args.delivery,
        stat_sampler=args.stat_sampler,
        schedule=args.schedule,
        quorum_rule=args.quorum_rule,
        link_delay_ms=args.link_delay_ms,
        model_serialization=args.serialization == "on",
        topology=args.topology,
        degree=args.degree,
        gossip_hops=args.gossip_hops,
        committees=args.committees,
        topo_seed=args.topo_seed,
        paxos_retry_timeout_ms=args.paxos_timeout_ms,
        paxos_client_node=args.paxos_client[0] if args.paxos_client else -1,
        paxos_client_ms=args.paxos_client[1] if args.paxos_client else 0,
        echo_back=args.echo_back,
        queued_links=args.queued_links,
        pbft_block_interval_ms=args.pbft_interval_ms,
        pbft_max_rounds=args.pbft_rounds,
        pbft_max_slots=args.pbft_max_slots,
        pbft_window=args.pbft_window,
        pbft_tx_speed=args.pbft_tx_speed,
        pbft_tx_size=args.pbft_tx_size,
        raft_heartbeat_ms=args.raft_heartbeat_ms,
        raft_max_blocks=args.raft_blocks,
        raft_tx_speed=args.raft_tx_speed,
        raft_tx_size=args.raft_tx_size,
        paxos_n_proposers=args.paxos_proposers,
        mixed_shards=args.mixed_shards,
        faults=FaultConfig(
            n_crashed=args.crash,
            n_byzantine=args.byzantine,
            drop_prob=args.drop,
            byz_forge=args.byz_forge,
            byz_copies=args.byz_copies,
        ),
    )


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    def emit(record, cfg=None, **kw):
        """Every result line leaves through here: one JSON line with the
        obs manifest attached (and, when $BLOCKSIM_RUNS_JSONL is set, the
        same record appended there — utils/obs.py)."""
        print(json.dumps(obs.finalize(record, cfg, **kw)))

    try:
        cfg = config_from_args(args)
    except ValueError as e:
        # SimConfig validation (e.g. --paxos-client lane/ms range) — same
        # clean-UX contract as the flag checks below: message + exit code 2
        print(f"error: {e}", file=sys.stderr)
        return 2
    seeds = args.seeds if args.seeds is not None else [args.seed]

    if args.engine != "cpp" and args.echo_back:
        print("error: --echo-back requires --engine cpp (the tensorized "
              "backends design the echo away; see SimConfig docs)",
              file=sys.stderr)
        return 2
    if args.engine != "cpp" and args.queued_links:
        # pbft (serial-pipe registers) and paxos (ser = 0) run on the
        # tensorized backends; anything else gets the runner's message
        from blockchain_simulator_tpu.runner import _reject_cpp_only

        try:
            _reject_cpp_only(cfg)
        except (ValueError, NotImplementedError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    if args.engine == "cpp":
        if args.shards > 1:
            print("error: --shards requires the jax engine", file=sys.stderr)
            return 2
        if args.protocol == "mixed":
            print("error: --protocol mixed requires the jax engine "
                  "(the C++ engine implements pbft/raft/paxos only)",
                  file=sys.stderr)
            return 2
        if args.topology != "full":
            print(f"error: --topology {args.topology} requires the jax engine "
                  "(the C++ engine simulates the full mesh only)",
                  file=sys.stderr)
            return 2
        if args.byz_sweep:
            print("error: --byz-sweep requires the jax engine",
                  file=sys.stderr)
            return 2
        if args.trace or args.profile:
            print("error: --trace/--profile require the jax engine",
                  file=sys.stderr)
            return 2
        import time

        from blockchain_simulator_tpu.engine import run_cpp

        for s in seeds:
            t0 = time.perf_counter()
            try:
                m = run_cpp(cfg, seed=s)
            except ValueError as e:
                print(f"error: {e}", file=sys.stderr)
                return 2
            if args.timing:
                m["wallclock_s"] = time.perf_counter() - t0
            emit(m, cfg)
        return 0

    if args.byz_sweep:
        from blockchain_simulator_tpu.parallel.sweep import run_byzantine_sweep

        for row in run_byzantine_sweep(cfg, seeds=seeds):
            # the row ran cfg with its OWN FaultConfig (sweep.py builds
            # n_byzantine=f, byz_forge=True per point): hash that config so
            # the manifest's join key matches what was simulated; the sweep
            # already appended the row to runs.jsonl (obs.record_run), so
            # the printed line must not append again
            row_cfg = cfg.with_(faults=dataclasses.replace(
                cfg.faults, n_byzantine=row["f"], byz_forge=True))
            emit(row, row_cfg, append=False)
        return 0

    if args.trace or args.profile:
        if args.shards > 1:
            print("error: --trace/--profile apply to unsharded jax runs",
                  file=sys.stderr)
            return 2
        if args.profile and len(seeds) > 1:
            print("error: --profile applies to single-seed jax runs "
                  "(--trace accepts --seeds: one FILE.<seed>.npz per seed)",
                  file=sys.stderr)
            return 2
        from blockchain_simulator_tpu.runner import _reject_cpp_only
        from blockchain_simulator_tpu.utils import trace as trace_mod

        try:
            # validate BEFORE any compile: cpp-only fidelity flags and
            # ineligible explicit schedule='round' fail here with the same
            # message + exit code 2 as every other path (run_traced
            # re-validates, but a multi-seed loop must not discover the
            # error on seed 0 after minutes of compile)
            _reject_cpp_only(cfg)
            if args.trace:
                import os as _os

                import numpy as _np

                for s in seeds:
                    m, series = trace_mod.run_traced(cfg, seed=s)
                    if len(seeds) == 1:
                        path = args.trace
                    else:
                        root, ext = _os.path.splitext(args.trace)
                        path = f"{root}.{s}{ext or '.npz'}"
                    _np.savez(path, **series)
                    m["trace_file"] = path
                    m["trace_series"] = sorted(series)
                    m["seed"] = s
                    emit(m, cfg)
            else:
                m = trace_mod.profile_run(cfg, args.profile, seed=seeds[0])
                m["profile_dir"] = args.profile
                emit(m, cfg)
        except (ValueError, NotImplementedError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        return 0

    if args.timing and (args.shards > 1 or len(seeds) > 1):
        print("note: --timing applies to single-seed unsharded jax runs; "
              "ignoring", file=sys.stderr)

    if args.shards > 1:
        from blockchain_simulator_tpu.parallel.mesh import make_mesh
        from blockchain_simulator_tpu.parallel.shard import run_sharded
        from blockchain_simulator_tpu.parallel.sweep import run_seed_sweep

        mesh = make_mesh(n_node_shards=args.shards)
        if len(seeds) > 1:
            # append=False: run_seed_sweep already logged each row with
            # obs.record_run — one runs.jsonl record per run, not two
            for m in run_seed_sweep(cfg, seeds=seeds, mesh=mesh):
                emit(m, cfg, append=False)
        else:
            emit(run_sharded(cfg, mesh, seed=seeds[0]), cfg)
        return 0

    if len(seeds) > 1:
        from blockchain_simulator_tpu.parallel.sweep import run_seed_sweep

        for m in run_seed_sweep(cfg, seeds=seeds):
            emit(m, cfg, append=False)
        return 0

    from blockchain_simulator_tpu.runner import run_simulation

    m = run_simulation(cfg, seed=seeds[0], with_timing=args.timing)
    emit(
        m, cfg,
        compile_s=m.get("compile_plus_first_run_s"),
        run_s=m.get("wallclock_s"),
        rounds=m.get("blocks_final_all_nodes", m.get("blocks")),
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
