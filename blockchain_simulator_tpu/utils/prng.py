"""Threaded PRNG discipline.

The reference calls libc ``rand()`` with no ``srand`` anywhere (SURVEY.md §5:
every run uses the same default seed, so runs are accidentally reproducible).
Here reproducibility is by design: one base key per simulation, folded with the
tick index once per step, and with a small static channel id per use site.
Every random draw is therefore a pure function of (seed, tick, channel, shape).
"""

from __future__ import annotations

import jax


# Static channel ids — one per independent randomness consumer per tick.
class Channel:
    DELAY_BCAST = 0      # broadcast one-way delays
    DELAY_ROUNDTRIP = 1  # request+reply round-trip delays
    DELAY_REPLY = 2      # unicast reply delays
    VIEW_CHANGE = 3      # PBFT rand()%100 view-change draw
    ELECTION = 4         # Raft election timeout draws
    DROP = 5             # fault injection: per-edge message drops
    DELAY_BCAST2 = 6     # second broadcast channel in the same tick
    DELAY_REPLY2 = 7
    STAT = 8             # statistical-delivery binomial chains
    DELAY_BCAST3 = 9     # third broadcast channel (Paxos commit requests)


def tick_key(base: jax.Array, tick) -> jax.Array:
    """Key for one simulation tick."""
    return jax.random.fold_in(base, tick)


def chan_key(tkey: jax.Array, channel: int) -> jax.Array:
    """Key for one use site within a tick."""
    return jax.random.fold_in(tkey, channel)
