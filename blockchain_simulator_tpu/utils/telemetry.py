"""Fleet-wide host-side telemetry: tracing, metrics, flight recorder, profiling.

PR 2's observability layer records what a *simulation* did (probe series,
manifests, health verdicts).  This module records what the *system around
the simulations* did — the serving fleet, the batcher, the sweep tier —
as four host-side primitives every tier shares:

- **Request-scoped tracing.**  A :class:`TraceContext` (``trace_id`` +
  ``span_id``) is minted at router admission (serve/router.py) and at
  sweep-chunk dispatch (parallel/sweep.py), propagated across processes
  via the ``X-Blocksim-Trace`` HTTP header, and every closed span becomes
  one JSON record: into the in-process flight recorder always, and into
  the span log (``$BLOCKSIM_SPANS_JSONL``, the shared rotating
  utils/obs.py writer) when armed.  The serving span model (README
  "Telemetry"): ``router.request`` → ``router.send`` → ``serve.request``
  → {``serve.admit``, ``serve.queue_wait``, ``serve.batch_wait``,
  ``serve.dispatch`` (pad-bucket attrs), ``serve.answer``} — segments
  tile the request's wall clock, so a span tree accounts for the whole
  p50 by construction.  :func:`spans_to_chrome_trace` exports spans (and,
  via utils/trace.chrome_events, a sim probe series) onto ONE
  Perfetto/Chrome-trace timeline.
- **Metrics registry.**  Cheap thread-safe counters / gauges /
  fixed-bucket histograms (:data:`metrics`), exposed as Prometheus text
  (``GET /metrics`` on the serve daemon and the fleet router) and as a
  compact snapshot on the run manifest (utils/obs.py).  Histogram
  percentiles power the ``/stats`` ``latency_ms`` blocks
  (serve/server.py, serve/router.py).
- **Flight recorder.**  A bounded in-memory ring of recent spans/events
  (:data:`flight`), dumped atomically to an ``ARTIFACT``-style JSON on
  shutdown, crash, supervisor degrade, or chaos invariant violation —
  when ``$BLOCKSIM_FLIGHT_DIR`` names a directory (unset = ring only,
  no file I/O).
- **Profiling hooks.**  ``BLOCKSIM_PROFILE=<dir>`` arms
  :func:`profile_region` — a ``jax.profiler.trace`` capture around
  dispatch flushes (serve/dispatch.py) and sweep chunks
  (parallel/sweep.py).  Disarmed it is one dict read and a predicted
  branch, mirroring chaos/inject.py's pattern.

HARD RULE (the host-sync-in-traced rule's telemetry corollary, enforced
by tests/test_ztelemetry.py): every call into this module is host-side
only.  Spans, counters and profile regions must never appear inside
jitted/vmapped/scanned code — a span's ``time`` calls are host syncs, and
traced code already has its own observability (utils/trace.py probe
series).  Models and ops never import this module.

Telemetry must never take down the thing it observes: every file write
is swallowed on failure, and :func:`FlightRecorder.dump` with no armed
directory is a no-op returning ``None``.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
import uuid

from blockchain_simulator_tpu.utils import obs

# HTTP propagation header: "<trace_id>:<span_id>" (the sender's span
# becomes the receiver's parent).
TRACE_HEADER = "X-Blocksim-Trace"

# Span log path (JSONL via the rotating obs.append_jsonl writer); unset =
# spans stay in the flight-recorder ring only.
SPANS_ENV = "BLOCKSIM_SPANS_JSONL"

# Flight-recorder dump directory; unset = dumps are no-ops.
FLIGHT_ENV = "BLOCKSIM_FLIGHT_DIR"
# retention: newest K ARTIFACT_flight_*.json kept per dump directory
# (default 32; 0 disables pruning) — the flight-dir analog of the
# obs.append_jsonl size-capped rotation: post-mortems are rolling
# observability artifacts, and a long chaos drill or a violation storm
# must not fill the disk with them
FLIGHT_KEEP_ENV = "BLOCKSIM_FLIGHT_KEEP"
FLIGHT_KEEP_DEFAULT = 32

# jax.profiler capture directory; unset = profile_region is free.
PROFILE_ENV = "BLOCKSIM_PROFILE"

TELEMETRY_SCHEMA = 1

# monotonic -> wall mapping, fixed at import: code paths stamp
# time.monotonic() (the clock the serving stack already uses) and spans
# publish wall-clock starts so cross-process timelines align.
_EPOCH = time.time() - time.monotonic()


def new_trace_id() -> str:
    """16 hex chars, unique per admission/chunk (uuid4-derived)."""
    return uuid.uuid4().hex[:16]


def new_span_id() -> str:
    """8 hex chars, unique within a trace."""
    return uuid.uuid4().hex[:8]


class TraceContext:
    """One (trace_id, span_id) point in a trace: the identity a child
    span parents to, and the value the ``X-Blocksim-Trace`` header
    carries across processes."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = str(trace_id)
        self.span_id = str(span_id)

    def header(self) -> str:
        return f"{self.trace_id}:{self.span_id}"

    def __eq__(self, other):
        return (isinstance(other, TraceContext)
                and other.trace_id == self.trace_id
                and other.span_id == self.span_id)

    def __repr__(self):
        return f"TraceContext({self.trace_id}:{self.span_id})"


def parse_header(value) -> TraceContext | None:
    """Parse a ``X-Blocksim-Trace`` header value; garbage (missing,
    malformed, empty ids) reads as None — a bad header must never reject
    a request."""
    if not isinstance(value, str) or ":" not in value:
        return None
    tid, _, sid = value.partition(":")
    tid, sid = tid.strip(), sid.strip()
    if not tid or not sid or not all(
            c in "0123456789abcdef" for c in (tid + sid).lower()):
        return None
    return TraceContext(tid, sid)


# ------------------------------------------------------------ span sinks ---

_tls = threading.local()
# extra span sinks (callables taking one span record): tests and the
# report tool install capture buffers here; the flight recorder is NOT a
# sink — it is unconditional.
_sinks: list = []
_sinks_lock = threading.Lock()


def current() -> TraceContext | None:
    """The calling thread's active trace context (set by :func:`span` /
    :func:`context`), or None."""
    return getattr(_tls, "ctx", None)


@contextlib.contextmanager
def context(ctx: TraceContext | None):
    """Install ``ctx`` as the thread's current trace context without
    opening a span — the HTTP handlers' header-extraction shim."""
    prev = current()
    _tls.ctx = ctx
    try:
        yield ctx
    finally:
        _tls.ctx = prev


@contextlib.contextmanager
def capture():
    """Collect every span emitted (process-wide) during the block —
    drills and tests read the list after."""
    buf: list[dict] = []
    with _sinks_lock:
        _sinks.append(buf.append)
    try:
        yield buf
    finally:
        with _sinks_lock:
            try:
                _sinks.remove(buf.append)
            except ValueError:
                pass


def emit(name: str, t0: float, t1: float | None = None,
         trace: str | None = None, parent: str | None = None,
         span_id: str | None = None, status: str = "ok", **attrs) -> str:
    """Record one closed span from explicit ``time.monotonic()`` stamps —
    the request-lifecycle synthesizer (serve/server.py builds a request's
    whole segment tree at answer time from stamps, because the segments
    straddle threads).  Returns the span id so callers can parent
    children to it.  Emission goes to the flight-recorder ring, any
    installed capture sinks, and the span log when armed."""
    t1 = time.monotonic() if t1 is None else t1
    sid = span_id or new_span_id()
    rec = {
        "kind": "span",
        "name": str(name),
        "trace": trace or new_trace_id(),
        "id": sid,
        "parent": parent,
        "ts": round(t0 + _EPOCH, 6),
        "dur_ms": round(max(t1 - t0, 0.0) * 1000.0, 3),
        "pid": os.getpid(),
        "status": str(status),
    }
    if attrs:
        rec["attrs"] = {k: v for k, v in attrs.items() if v is not None}
    flight.record(rec)
    with _sinks_lock:
        sinks = list(_sinks)
    for sink in sinks:
        try:
            sink(rec)
        except Exception:
            pass  # a broken sink must never break the emitting code path
    path = os.environ.get(SPANS_ENV)
    if path:
        obs.append_jsonl(rec, path)
    return sid


@contextlib.contextmanager
def span(name: str, ctx: TraceContext | None = None, **attrs):
    """Open/close one span around a block: child of ``ctx`` (or the
    thread's current context; a fresh trace when neither exists), set as
    the thread's current context inside the block — so nested spans and
    outbound HTTP headers (serve/router.py ``_http``) pick it up.  An
    escaping exception marks ``status="error"`` and re-raises.  Yields
    the span's own :class:`TraceContext`."""
    parent = ctx if ctx is not None else current()
    tid = parent.trace_id if parent is not None else new_trace_id()
    sid = new_span_id()
    mine = TraceContext(tid, sid)
    prev = current()
    _tls.ctx = mine
    t0 = time.monotonic()
    status = "ok"
    try:
        yield mine
    except BaseException:
        status = "error"
        raise
    finally:
        _tls.ctx = prev
        emit(name, t0, time.monotonic(), trace=tid,
             parent=parent.span_id if parent is not None else None,
             span_id=sid, status=status, **attrs)


# --------------------------------------------------------------- metrics ---

# Fixed latency buckets (ms): wide enough for a sub-ms solo dispatch and
# a multi-second cold compile; fixed so two processes' histograms merge.
DEFAULT_MS_BUCKETS = (
    1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 30000.0,
)


class Counter:
    """Monotone counter.  All mutation under the registry lock the
    instrument was created with (instrument methods are the hot path:
    one lock, one add)."""

    __slots__ = ("name", "labels", "_lock", "value")

    def __init__(self, name: str, labels: dict, lock: threading.Lock):
        self.name = name
        self.labels = labels
        self._lock = lock
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Gauge:
    """Last-write-wins gauge."""

    __slots__ = ("name", "labels", "_lock", "value")

    def __init__(self, name: str, labels: dict, lock: threading.Lock):
        self.name = name
        self.labels = labels
        self._lock = lock
        self.value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)


class Histogram:
    """Fixed-bucket histogram (cumulative exposition, Prometheus-style).

    ``bounds`` are upper bucket edges; an implicit +Inf bucket catches
    the tail.  :meth:`percentile` answers at bucket resolution — the
    upper edge of the bucket the nearest-rank observation fell in,
    capped at the maximum observed value (so the +Inf bucket reports a
    real number).  Good enough for the ``/stats`` p50/p95/p99 blocks;
    exact percentiles stay obs.percentile over raw samples where callers
    keep them (tools/serve_bench.py)."""

    __slots__ = ("name", "labels", "bounds", "_lock", "counts", "sum",
                 "count", "_max")

    def __init__(self, name: str, labels: dict, lock: threading.Lock,
                 bounds=DEFAULT_MS_BUCKETS):
        self.name = name
        self.labels = labels
        self.bounds = tuple(float(b) for b in bounds)
        if list(self.bounds) != sorted(self.bounds):
            raise ValueError("histogram bounds must be sorted ascending")
        self._lock = lock
        self.counts = [0] * (len(self.bounds) + 1)  # [+Inf] last
        self.sum = 0.0
        self.count = 0
        self._max = 0.0

    def observe(self, v: float) -> None:
        v = float(v)
        i = 0
        for b in self.bounds:
            if v <= b:
                break
            i += 1
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1
            if v > self._max:
                self._max = v

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile at bucket resolution (0.0 when
        empty)."""
        with self._lock:
            total = self.count
            counts = list(self.counts)
            vmax = self._max
        if total == 0:
            return 0.0
        rank = max(1, int(round(q / 100.0 * total)))
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank:
                edge = self.bounds[i] if i < len(self.bounds) else vmax
                return round(min(edge, vmax), 3)
        return round(vmax, 3)

    def percentiles(self, qs=(50.0, 95.0, 99.0)) -> dict:
        return {f"p{int(q)}": self.percentile(q) for q in qs}


def _label_str(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return "{" + inner + "}"


class MetricsRegistry:
    """Get-or-create registry of instruments keyed on (name, labels).

    One process-global instance (:data:`metrics`) backs ``/metrics`` on
    every HTTP surface; tests and per-server ``/stats`` percentiles use
    private :class:`Histogram` instances instead, so N servers in one
    process do not blur each other's latency."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: dict = {}

    def _get(self, cls, name: str, labels: dict, **kw):
        key = (cls.__name__, name, tuple(sorted(labels.items())))
        with self._lock:
            inst = self._instruments.get(key)
            if inst is None:
                inst = cls(name, dict(labels), self._lock, **kw)
                self._instruments[key] = inst
            return inst

    def counter(self, name: str, **labels) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, bounds=DEFAULT_MS_BUCKETS,
                  **labels) -> Histogram:
        return self._get(Histogram, name, labels, bounds=bounds)

    def reset(self) -> None:
        """Drop every instrument — scenario/test isolation (the drills
        bracket runs with snapshots instead; see chaos/invariants.py
        check_telemetry)."""
        with self._lock:
            self._instruments = {}

    # ------------------------------------------------------- exposition ---
    def exposition(self) -> str:
        """Prometheus text format v0.0.4 — the ``GET /metrics`` body."""
        lines: list[str] = []
        with self._lock:
            instruments = list(self._instruments.values())
        typed: set[str] = set()
        for inst in sorted(instruments, key=lambda i: i.name):
            kind = type(inst).__name__.lower()
            if inst.name not in typed:
                lines.append(f"# TYPE {inst.name} {kind}")
                typed.add(inst.name)
            ls = _label_str(inst.labels)
            if isinstance(inst, Histogram):
                cum = 0
                for b, c in zip(inst.bounds, inst.counts):
                    cum += c
                    lb = dict(inst.labels, le=f"{b:g}")
                    lines.append(f"{inst.name}_bucket{_label_str(lb)} {cum}")
                cum += inst.counts[-1]
                lb = dict(inst.labels, le="+Inf")
                lines.append(f"{inst.name}_bucket{_label_str(lb)} {cum}")
                lines.append(f"{inst.name}_sum{ls} {inst.sum:g}")
                lines.append(f"{inst.name}_count{ls} {inst.count}")
            else:
                lines.append(f"{inst.name}{ls} {inst.value:g}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """Compact JSON-able view: counters/gauges by ``name{labels}``,
        histograms as {count, sum, p50, p95, p99} — the flight-recorder
        dump and ARTIFACT_telemetry.json payload, and the delta source
        for chaos/invariants.check_telemetry."""
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        with self._lock:
            instruments = list(self._instruments.values())
        for inst in instruments:
            key = inst.name + _label_str(inst.labels)
            if isinstance(inst, Counter):
                out["counters"][key] = inst.value
            elif isinstance(inst, Gauge):
                out["gauges"][key] = inst.value
            else:
                out["histograms"][key] = {
                    "count": inst.count, "sum": round(inst.sum, 3),
                    **inst.percentiles(),
                }
        return out

    def manifest(self) -> dict:
        """The tiny provenance block obs.manifest attaches to runs.jsonl
        lines when telemetry has instruments: counter totals only (the
        full snapshot would bloat every access-log line)."""
        with self._lock:
            instruments = list(self._instruments.values())
        counters = {
            inst.name + _label_str(inst.labels): inst.value
            for inst in instruments if isinstance(inst, Counter)
        }
        return {"counters": counters, "spans": flight.spans_recorded}


metrics = MetricsRegistry()


def write_exposition(handler) -> None:
    """Serve the ``GET /metrics`` body on a BaseHTTPRequestHandler — the
    one Prometheus endpoint implementation both HTTP surfaces share
    (serve/__main__.py daemon, serve/router.py fleet front)."""
    blob = metrics.exposition().encode()
    handler.send_response(200)
    handler.send_header("Content-Type", "text/plain; version=0.0.4")
    handler.send_header("Content-Length", str(len(blob)))
    handler.end_headers()
    handler.wfile.write(blob)


# -------------------------------------------------------- flight recorder ---


class FlightRecorder:
    """Bounded ring of the most recent spans/events in this process.

    Always on (a ring append is two list ops under a lock); the *file*
    side is armed by ``$BLOCKSIM_FLIGHT_DIR`` — :meth:`dump` writes one
    atomic ``ARTIFACT``-style JSON (tmp + ``os.replace``) named after its
    trigger, so a crash, a chaos invariant violation, a supervisor
    degrade, or a shutdown each leave a self-describing post-mortem.
    Dump failures are swallowed: the recorder must never take down the
    process it is recording."""

    def __init__(self, capacity: int = 4096):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._ring: list[dict] = []
        self._next = 0
        self.spans_recorded = 0
        self.dumps = 0
        self._dump_seq = itertools.count(1)

    def record(self, rec: dict) -> None:
        with self._lock:
            if len(self._ring) < self.capacity:
                self._ring.append(rec)
            else:
                self._ring[self._next % self.capacity] = rec
            self._next += 1
            if rec.get("kind") == "span":
                self.spans_recorded += 1

    def note(self, event: str, **fields) -> None:
        """Record one non-span event (supervisor transitions, chaos
        verdicts, lifecycle marks)."""
        self.record({"kind": "event", "event": str(event),
                     "ts": round(time.time(), 6), "pid": os.getpid(),
                     **fields})

    def snapshot(self) -> list[dict]:
        """Ring contents, oldest first."""
        with self._lock:
            if len(self._ring) < self.capacity:
                return list(self._ring)
            i = self._next % self.capacity
            return self._ring[i:] + self._ring[:i]

    def reset(self) -> None:
        with self._lock:
            self._ring = []
            self._next = 0
            self.spans_recorded = 0

    def dump(self, reason: str, path: str | None = None) -> str | None:
        """Write the post-mortem; returns the path, or None when neither
        ``path`` nor ``$BLOCKSIM_FLIGHT_DIR`` is set (disarmed) or the
        write failed (swallowed)."""
        if path is None:
            d = os.environ.get(FLIGHT_ENV)
            if not d:
                return None
            # sequence number: repeated same-reason triggers in one
            # process (a drill's scenarios, a long sweep's degrades)
            # each keep their own post-mortem instead of overwriting
            path = os.path.join(
                d, f"ARTIFACT_flight_{reason}_{os.getpid()}"
                   f"_{next(self._dump_seq)}.json")
        doc = {
            "telemetry_schema": TELEMETRY_SCHEMA,
            "reason": str(reason),
            "ts": round(time.time(), 3),
            "pid": os.getpid(),
            "records": self.snapshot(),
            "metrics": metrics.snapshot(),
        }
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(doc, f, default=str)
                f.write("\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        self._prune(os.path.dirname(path) or ".")
        with self._lock:
            self.dumps += 1
        return path

    @staticmethod
    def _prune(d: str) -> None:
        """Keep only the newest ``$BLOCKSIM_FLIGHT_KEEP`` (default 32)
        ``ARTIFACT_flight_*.json`` post-mortems in ``d``; 0 disables.
        Runs after every successful dump; failures are swallowed like the
        dump's own (the recorder never takes down its process)."""
        try:
            keep = int(os.environ.get(FLIGHT_KEEP_ENV, FLIGHT_KEEP_DEFAULT))
        except ValueError:
            keep = FLIGHT_KEEP_DEFAULT
        if keep <= 0:
            return
        try:
            names = [n for n in os.listdir(d)
                     if n.startswith("ARTIFACT_flight_")
                     and n.endswith(".json")]
            if len(names) <= keep:
                return
            paths = [os.path.join(d, n) for n in names]
            # (mtime, name): stable order for same-second bursts
            paths.sort(key=lambda p: (os.path.getmtime(p), p))
            for p in paths[:-keep]:
                os.unlink(p)
        except OSError:
            pass


flight = FlightRecorder()


def install_crash_dump() -> None:
    """Chain a flight-recorder dump onto ``sys.excepthook`` AND
    ``threading.excepthook`` — the daemon entrypoints call this once so
    an unhandled exception leaves a post-mortem before the traceback.
    The threading hook matters more: the daemons' crash surface is
    worker threads (HTTP handlers, router dispatch/hedge/handoff), not
    the main thread blocking in serve_forever.  (kill -9 has no hook;
    the WAL and sweep journal carry that case.)"""
    import sys
    import threading as _threading

    prev = sys.excepthook

    def hook(exc_type, exc, tb):
        try:
            flight.note("crash", error=f"{exc_type.__name__}: {exc}"[:500])
            flight.dump("crash")
        finally:
            prev(exc_type, exc, tb)

    sys.excepthook = hook
    prev_t = _threading.excepthook

    def thread_hook(args):
        try:
            flight.note(
                "crash",
                thread=getattr(args.thread, "name", None),
                error=f"{args.exc_type.__name__}: {args.exc_value}"[:500],
            )
            flight.dump("crash")
        finally:
            prev_t(args)

    _threading.excepthook = thread_hook


def reset() -> None:
    """Fresh metrics + flight ring (test/drill isolation).  Does not
    touch installed sinks or thread-local contexts."""
    metrics.reset()
    flight.reset()


# -------------------------------------------------------------- profiling ---

_profile_seq = itertools.count()
_profile_active = threading.local()


@contextlib.contextmanager
def profile_region(name: str):
    """``jax.profiler`` capture around one host-side region (a dispatch
    flush, a sweep chunk) into ``$BLOCKSIM_PROFILE/<name>-<k>``.

    Disarmed (env unset — the only state tests and serving see unless an
    operator arms it): one dict read, zero jax imports.  Armed: one
    capture directory per region instance, viewable in TensorBoard's
    profile plugin or ui.perfetto.dev.  Nested regions (a serve flush
    inside a profiled sweep chunk) skip the inner capture —
    ``jax.profiler.trace`` does not nest.  Profiler failures are
    swallowed: profiling must never take down the dispatch it measures.
    """
    d = os.environ.get(PROFILE_ENV)
    if not d or getattr(_profile_active, "on", False):
        yield
        return
    logdir = os.path.join(d, f"{name}-{next(_profile_seq)}")
    try:
        import jax

        cm = jax.profiler.trace(logdir)
        cm.__enter__()
    except Exception:
        yield
        return
    _profile_active.on = True
    try:
        yield
    finally:
        _profile_active.on = False
        try:
            cm.__exit__(None, None, None)
        except Exception:
            pass  # a failing profiler must never take down the dispatch


# ----------------------------------------------------------- trace export ---


def spans_to_chrome_trace(spans, path, series: dict | None = None,
                          name: str = "telemetry") -> dict:
    """Export span records (+ optionally one sim probe series) as a
    single Chrome-trace/Perfetto JSON timeline.

    Spans become complete events ("ph": "X") grouped one thread row per
    trace (so a request's segment tree reads left-to-right on its own
    row), timestamped on the shared wall clock.  ``series`` (a
    utils/trace.py probe series dict) is overlaid through
    ``trace.chrome_events`` as counter tracks in a second process — the
    "serving spans and sim probe series on ONE timeline" recipe (README
    "Telemetry").  Returns ``{"events", "path"}``."""
    events: list[dict] = [
        {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
         "args": {"name": name}},
    ]
    tids: dict[str, int] = {}
    for rec in spans:
        if rec.get("kind") != "span":
            continue
        trace_id = str(rec.get("trace"))
        tid = tids.get(trace_id)
        if tid is None:
            tid = tids[trace_id] = len(tids) + 1
            events.append({
                "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
                "args": {"name": f"trace {trace_id}"},
            })
        args = dict(rec.get("attrs") or {})
        args["span_id"] = rec.get("id")
        if rec.get("parent"):
            args["parent"] = rec.get("parent")
        if rec.get("status") != "ok":
            args["status"] = rec.get("status")
        events.append({
            "name": rec.get("name"), "ph": "X", "pid": 1, "tid": tid,
            "ts": int(float(rec.get("ts", 0.0)) * 1e6),
            "dur": max(int(float(rec.get("dur_ms", 0.0)) * 1000.0), 1),
            "args": args,
        })
    if series is not None:
        from blockchain_simulator_tpu.utils import trace as trace_mod

        events.extend(trace_mod.chrome_events(series, name="sim", pid=0))
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    with open(path, "w") as f:
        json.dump(doc, f)
    return {"events": len(events), "path": str(path)}
