"""Tracing / observability.

The reference's only observability is timestamped ``NS_LOG_INFO`` lines
(SURVEY.md §5) read by eye.  Here observability is data, at three levels:

- **End-of-run metrics**: each backend's ``metrics()`` (already structured).
- **Per-tick time series** (this module): ``run_traced`` scans the simulation
  with a per-tick probe emitted as ``ys``, returning ``{name: np.ndarray[T]}``
  — the tensorized equivalent of grepping the reference's log for
  commit/election/finality lines with timestamps, at zero host-callback cost
  (the series is device-side until the end).
- **Profiler capture**: ``profile_run`` wraps a run in ``jax.profiler.trace``
  for TensorBoard/perfetto (compile + device timeline), the replacement for
  the pcap/ascii tracing ns-3 offers but the reference never enables.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from blockchain_simulator_tpu.models.base import get_protocol
from blockchain_simulator_tpu.utils import prng
from blockchain_simulator_tpu.utils.config import SimConfig
from blockchain_simulator_tpu.utils.sync import force_sync


def probe(cfg: SimConfig, state) -> dict:
    """Per-tick scalar probes for a protocol state (device-side, cheap)."""
    p = cfg.protocol
    if p == "pbft":
        return {
            "blocks_committed_max": state.block_num.max(),
            "commit_events_total": state.slot_commits.sum(),
            "view_max": state.v.max(),
            "rounds_sent": state.next_n.max(),
        }
    if p == "raft":
        return {
            "n_leaders": (state.is_leader & state.alive).sum(),
            "blocks": state.block_num.max(),
            "elections": state.elections.sum(),
        }
    if p == "paxos":
        return {
            "executes": state.is_commit.sum(),
            "max_ticket": state.ticket.max(),
            "committed_proposers": (state.commit_tick >= 0).sum(),
        }
    if p == "mixed":
        return {
            "shards_with_leader": (state.raft.is_leader & state.raft.alive)
            .any(axis=1)
            .sum(),
            "raft_blocks_total": state.raft.block_num.max(axis=1).sum(),
            "global_blocks": state.pbft.block_num.max(),
        }
    raise NotImplementedError(p)


def run_traced(cfg: SimConfig, seed: int | None = None):
    """Run one simulation recording the probe every tick.

    Returns ``(metrics, series)`` where ``series`` maps probe names to
    ``np.ndarray`` of length ``cfg.ticks`` (value *after* each tick).

    Always runs the general per-tick engine (a per-tick series is the whole
    point); for configs that resolve to the round-blocked fast path the
    milestone metrics are distribution-identical, not bit-identical, to
    ``run_simulation`` (see models/pbft_round.py).
    """
    proto = get_protocol(cfg.protocol)

    @jax.jit
    def sim(key):
        state, bufs = proto.init(cfg, jax.random.fold_in(key, 0x1217))

        def body(carry, t):
            st, bf = carry
            st, bf = proto.step(cfg, st, bf, t, prng.tick_key(key, t))
            return (st, bf), probe(cfg, st)

        (state, _), ys = jax.lax.scan(body, (state, bufs), jnp.arange(cfg.ticks))
        return state, ys

    key = jax.random.key(cfg.seed if seed is None else seed)
    state, ys = jax.block_until_ready(sim(key))
    series = {k: np.asarray(v) for k, v in ys.items()}
    return proto.metrics(cfg, state), series


def events_from_series(series: dict, name: str) -> np.ndarray:
    """Ticks at which a monotone counter series increments — the reconstruction
    of the reference's per-event log timestamps (e.g. pbft-node.cc:259 commit
    lines) from the recorded time series."""
    s = np.asarray(series[name])
    prev = np.concatenate([[0], s[:-1]])
    return np.flatnonzero(s > prev)


def profile_run(cfg: SimConfig, logdir: str, seed: int | None = None) -> dict:
    """Capture a profiler trace of one (pre-compiled) run into ``logdir``.

    Returns the run metrics augmented with wallclock timings.  View with
    TensorBoard's profile plugin or ui.perfetto.dev.
    """
    from blockchain_simulator_tpu.runner import make_sim_fn

    proto = get_protocol(cfg.protocol)
    sim = make_sim_fn(cfg)
    key = jax.random.key(cfg.seed if seed is None else seed)
    t0 = time.perf_counter()
    # force_sync throughout: block_until_ready alone measures dispatch, not
    # execution, on this env's axon backend (KNOWN_ISSUES.md #1)
    force_sync(sim(key))  # compile + warm outside the capture
    compile_s = time.perf_counter() - t0
    with jax.profiler.trace(logdir):
        t0 = time.perf_counter()
        final = force_sync(sim(key))
        run_s = time.perf_counter() - t0
    m = proto.metrics(cfg, final)
    m["compile_plus_first_run_s"] = compile_s
    m["profiled_run_s"] = run_s
    return m
