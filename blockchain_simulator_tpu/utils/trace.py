"""Tracing / observability.

The reference's only observability is timestamped ``NS_LOG_INFO`` lines
(SURVEY.md §5) read by eye.  Here observability is data, at three levels:

- **End-of-run metrics**: each backend's ``metrics()`` (already structured).
- **Probe time series** (this module): ``run_traced`` runs the SAME simulator
  ``run_simulation`` would — it dispatches through
  ``runner.use_round_schedule``, validating an ineligible explicit
  ``schedule='round'`` with the same ``ValueError`` — with a per-step probe
  emitted as scan ``ys``:

  - general tick engine: one sample per 1 ms tick (the tensorized equivalent
    of grepping the reference's log for commit/election/finality lines);
  - round-blocked PBFT (models/pbft_round): one sample per BLOCK ROUND;
  - heartbeat raft (models/raft_hb): one sample per HEARTBEAT after the
    election prefix (per-tick samples when the checked handoff fell back to
    the tick engine);
  - heartbeat-scheduled mixed (models/mixed.scan_fast): per-heartbeat shard
    aggregates + the global PBFT layer sampled at the same ticks.

  Fast-path series carry a ``"t"`` array mapping sample index -> virtual
  tick; pass ``cfg.with_(schedule="tick")`` for bit-exact per-tick series on
  the general engine (the documented override).
- **Event export**: ``events_from_series`` reconstructs per-event ticks from
  monotone counters; ``to_chrome_trace`` converts a whole series dict into a
  Chrome-trace/Perfetto JSON timeline (counter tracks + instant events).
- **Profiler capture**: ``profile_run`` wraps a run in ``jax.profiler.trace``
  for TensorBoard/perfetto (compile + device timeline).
"""

from __future__ import annotations

import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from blockchain_simulator_tpu.models.base import get_protocol
from blockchain_simulator_tpu.utils import aotcache
from blockchain_simulator_tpu.utils import prng
from blockchain_simulator_tpu.utils.config import SimConfig
from blockchain_simulator_tpu.utils.sync import force_sync


def probe(cfg: SimConfig, state) -> dict:
    """Per-step scalar probes for a protocol state (device-side, cheap).

    Reads only the field names shared between each protocol's tick state and
    its fast-path state (e.g. PbftState and PbftRoundState), so the same
    probe serves both engines."""
    p = cfg.protocol
    if p == "pbft":
        return {
            "blocks_committed_max": state.block_num.max(),
            "commit_events_total": state.slot_commits.sum(),
            "view_max": state.v.max(),
            "rounds_sent": state.next_n.max(),
        }
    if p == "raft":
        return {
            "n_leaders": (state.is_leader & state.alive).sum(),
            "blocks": state.block_num.max(),
            "elections": state.elections.sum(),
        }
    if p == "paxos":
        return {
            "executes": state.is_commit.sum(),
            "max_ticket": state.ticket.max(),
            "committed_proposers": (state.commit_tick >= 0).sum(),
        }
    if p == "mixed":
        return {
            "shards_with_leader": (state.raft.is_leader & state.raft.alive)
            .any(axis=1)
            .sum(),
            "raft_blocks_total": state.raft.block_num.max(axis=1).sum(),
            "global_blocks": state.pbft.block_num.max(),
        }
    raise NotImplementedError(p)


def _np_series(ys) -> dict:
    return {k: np.asarray(v) for k, v in ys.items()}


# The jitted programs are cached per config in the unified executable
# registry (utils/aotcache.py; SimConfig is frozen/hashable, the same
# convention as runner.make_sim_fn) so a multi-seed --trace sweep compiles
# once and reruns with fresh keys — and the hit/miss trail lands on the run
# manifest's `cache` block.  Every program below — including the
# multi-program raft_hb/mixed factories' prefix/steady/cont pieces — is
# traced and budget-pinned by the graph audit (lint/graph/programs.py
# `trace.*` specs).

@aotcache.cached_factory("trace-tick")
def _tick_traced_fn(cfg: SimConfig):
    proto = get_protocol(cfg.protocol)

    @jax.jit
    def sim(key):
        state, bufs = proto.init(cfg, jax.random.fold_in(key, 0x1217))

        def body(carry, t):
            st, bf = carry
            st, bf = proto.step(cfg, st, bf, t, prng.tick_key(key, t))
            return (st, bf), probe(cfg, st)

        (state, _), ys = jax.lax.scan(body, (state, bufs), jnp.arange(cfg.ticks))
        return state, ys

    return sim


def _traced_tick(cfg: SimConfig, seed):
    """General per-tick engine with the probe as scan ``ys`` (the seed
    behavior of run_traced, now the schedule='tick' arm)."""
    proto = get_protocol(cfg.protocol)
    key = jax.random.key(cfg.seed if seed is None else seed)
    state, ys = jax.block_until_ready(_tick_traced_fn(cfg)(key))
    return proto.metrics(cfg, state), _np_series(ys)


@aotcache.cached_factory("trace-pbft-round")
def _pbft_round_traced_fn(cfg: SimConfig):
    from blockchain_simulator_tpu.models import pbft_round

    @jax.jit
    def sim(key):
        state, _ = pbft_round.init(cfg, jax.random.fold_in(key, 0x1217))
        return pbft_round.scan_rounds(cfg, state, key, with_probe=True)

    return sim


def _traced_pbft_round(cfg: SimConfig, seed):
    """Round-blocked PBFT fast path with one probe sample per round.

    The scan is exactly runner.make_sim_fn's (same init, same keys, probes
    only read), so the returned metrics are bit-identical to
    ``run_simulation``'s on this config."""
    from blockchain_simulator_tpu.models import pbft_round

    key = jax.random.key(cfg.seed if seed is None else seed)
    state, ys = jax.block_until_ready(_pbft_round_traced_fn(cfg)(key))
    series = _np_series(ys)
    bt = cfg.pbft_block_interval_ms
    # sample i is the state after round r = i + 1 (block tick r * interval)
    series["t"] = (1 + np.arange(len(next(iter(series.values()))))) * bt
    return pbft_round.metrics(cfg, state), series


@aotcache.cached_factory("trace-raft-hb")
def _raft_hb_traced_fns(cfg: SimConfig):
    """(prefix, steady, cont) jitted programs for the traced raft fast path;
    the key is a runtime argument so seeds share one compile."""
    from blockchain_simulator_tpu.models import raft as raft_tick
    from blockchain_simulator_tpu.models import raft_hb

    t_e = raft_hb.prefix_ticks(cfg)

    def body(key, carry, t):
        st, bf = carry
        st, bf = raft_tick.step(cfg, st, bf, t, prng.tick_key(key, t))
        return (st, bf), probe(cfg, st)

    @jax.jit
    def prefix(key):
        state, bufs = raft_tick.init(cfg, jax.random.fold_in(key, 0x1217))
        carry, ys = jax.lax.scan(
            functools.partial(body, key), (state, bufs), jnp.arange(t_e)
        )
        ok, h = raft_hb.handoff(cfg, carry[0])
        return carry, ys, ok, h

    @jax.jit
    def steady(state, h, key):
        out, ys = raft_hb.steady_scan(cfg, key, h, with_probe=True)
        return raft_hb.materialize(cfg, state, h, out), ys

    @jax.jit
    def cont(carry, key):
        (st, _), ys = jax.lax.scan(
            functools.partial(body, key), carry,
            t_e + jnp.arange(max(cfg.ticks - t_e, 0)),
        )
        return st, ys

    return prefix, steady, cont


def _traced_raft_hb(cfg: SimConfig, seed):
    """Heartbeat-blocked raft fast path, probed.

    The phase split runs on the host (run_traced is a single-seed host
    driver; the CLI forbids --trace under vmap/shard_map): the tick-engine
    election prefix runs first, the checked handoff verdict is read back,
    and EITHER the per-heartbeat steady scan (per-heartbeat series) OR the
    tick-engine continuation from the prefix carry (per-tick series over the
    full window) runs — the same two branches as raft_hb.scan_from_init's
    traced ``lax.cond``, with the same keys, so milestones match
    ``run_simulation``."""
    from blockchain_simulator_tpu.models import raft_hb

    prefix, steady, cont = _raft_hb_traced_fns(cfg)
    key = jax.random.key(cfg.seed if seed is None else seed)
    carry, pre_ys, ok, h = jax.block_until_ready(prefix(key))

    if bool(ok):
        state, ys = jax.block_until_ready(steady(carry[0], h, key))
        series = _np_series(ys)
        hb = cfg.raft_heartbeat_ms
        series["t"] = int(h.hb0) + np.arange(raft_hb.n_hb_steps(cfg)) * hb
        return raft_hb.metrics(cfg, state), series

    state, post_ys = jax.block_until_ready(cont(carry, key))
    series = {
        k: np.concatenate([np.asarray(pre_ys[k]), np.asarray(post_ys[k])])
        for k in pre_ys
    }
    return raft_hb.metrics(cfg, state), series


@aotcache.cached_factory("trace-mixed")
def _mixed_traced_fns(cfg: SimConfig):
    """(prefix, finish, prefix_probed, cont) jitted programs for the traced
    mixed fast path; the key is a runtime argument so seeds share one
    compile."""
    from blockchain_simulator_tpu.models import mixed, raft_hb

    rcfg, _ = mixed.sub_configs(cfg)
    t_e = raft_hb.prefix_ticks(rcfg)

    @jax.jit
    def prefix(key):
        state, bufs = mixed.init(cfg, jax.random.fold_in(key, 0x1217))
        return mixed.prefix_handoff(cfg, state, bufs, key)

    @jax.jit
    def finish(carry, h_s, key):
        return mixed.fast_finish(cfg, carry, h_s, key, with_probe=True)

    def body(key, c, t):
        st, bf = c
        st, bf = mixed.step(cfg, st, bf, t, prng.tick_key(key, t))
        return (st, bf), probe(cfg, st)

    # fallback arm only: re-probe the prefix per tick for a contiguous
    # series (prefix() records no ys; the rerun is one extra compile of the
    # same engine, paid only when a shard's handoff failed)
    @jax.jit
    def prefix_probed(key):
        state, bufs = mixed.init(cfg, jax.random.fold_in(key, 0x1217))
        return jax.lax.scan(
            functools.partial(body, key), (state, bufs), jnp.arange(t_e)
        )

    @jax.jit
    def cont(carry, key):
        (st, _), ys = jax.lax.scan(
            functools.partial(body, key), carry,
            t_e + jnp.arange(max(cfg.ticks - t_e, 0)),
        )
        return st, ys

    return prefix, finish, prefix_probed, cont


def _traced_mixed_fast(cfg: SimConfig, seed):
    """Heartbeat-scheduled mixed sim, probed: per-heartbeat SHARD AGGREGATES
    (total/min raft blocks over shards, shards stopped) plus the global PBFT
    layer sampled at the same ticks; per-tick mixed series over the full
    window when any shard's handoff fell back to the tick engine."""
    from blockchain_simulator_tpu.models import mixed, raft_hb

    rcfg, _ = mixed.sub_configs(cfg)
    t_e = raft_hb.prefix_ticks(rcfg)
    prefix, finish, prefix_probed, cont = _mixed_traced_fns(cfg)
    key = jax.random.key(cfg.seed if seed is None else seed)
    carry, ok_all, h_s = jax.block_until_ready(prefix(key))

    if bool(ok_all):
        state, (raft_ys, pbft_ys) = jax.block_until_ready(
            finish(carry, h_s, key)
        )
        hb = rcfg.raft_heartbeat_ms
        k_steps = raft_hb.n_hb_steps(rcfg)
        # shards' heartbeat clocks differ by their election offsets; the
        # aggregate series is indexed by STEP, timestamped at the latest
        # shard's k-th heartbeat (documented approximation)
        t_hb = int(np.asarray(h_s.hb0).max()) + np.arange(k_steps) * hb
        blocks = np.asarray(raft_ys["blocks"])          # [S, K]
        stopped = np.asarray(raft_ys["stopped"])        # [S, K]
        g_blocks = np.asarray(pbft_ys["global_blocks"])         # [ticks - t_e]
        g_commits = np.asarray(pbft_ys["global_commit_events"])
        # sample the per-tick global layer at the heartbeat ticks
        gi = np.clip(t_hb - t_e, 0, max(len(g_blocks) - 1, 0))
        series = {
            "t": t_hb,
            "raft_blocks_total": blocks.sum(axis=0),
            "raft_blocks_min": blocks.min(axis=0),
            "shards_stopped": stopped.sum(axis=0),
            "global_blocks": g_blocks[gi] if len(g_blocks) else np.zeros(
                (k_steps,), np.int32),
            "global_commit_events": g_commits[gi] if len(g_commits)
            else np.zeros((k_steps,), np.int32),
        }
        return mixed.metrics(cfg, state), series

    carry2, pre_ys = jax.block_until_ready(prefix_probed(key))
    state, post_ys = jax.block_until_ready(cont(carry2, key))
    series = {
        k: np.concatenate([np.asarray(pre_ys[k]), np.asarray(post_ys[k])])
        for k in pre_ys
    }
    return mixed.metrics(cfg, state), series


@aotcache.cached_factory("trace-committee")
def _committee_traced_fn(cfg: SimConfig):
    """Jitted ``sim(key) -> (stacked_finals, series)`` for the committee
    hierarchy: the static-arm run_stacked body (runner.make_sim_fn
    committee arm — config's own fault counts on the dyn operand slots)
    with the standard probe sampled per tick INSIDE each committee's
    ``lax.map`` body (topo/committee.stacked_body probe hook), so the
    series leaves stack to ``[C, ticks]``."""
    from blockchain_simulator_tpu.models import base as base_model
    from blockchain_simulator_tpu.topo import committee

    canon = base_model.canonical_fault_cfg(cfg)
    nc = cfg.faults.resolved_n_crashed(cfg.n)
    nb = cfg.faults.n_byzantine

    def finalize_fn(icfg, final, ys):
        del icfg, final  # full per-tick series — no reduction on this path
        return ys

    @jax.jit
    def sim(key):
        return committee.run_stacked(
            canon, key, jnp.int32(nc), jnp.int32(nb),
            probe=(probe, finalize_fn),
        )

    return sim


def _traced_committee(cfg: SimConfig, seed):
    """Committee hierarchy with stacked per-committee probe series.

    ``series`` leaves are ``[C, ticks]`` (lane 0 of the leading axis is
    committee 0); ``series["t"]`` is the inner tick axis.  Metrics are
    the committee outer aggregate (topo/committee.metrics), bit-identical
    to ``run_simulation``'s on this config (probes only read)."""
    from blockchain_simulator_tpu.topo import committee

    key = jax.random.key(cfg.seed if seed is None else seed)
    finals, ys = jax.block_until_ready(_committee_traced_fn(cfg)(key))
    series = _np_series(ys)
    series["t"] = np.arange(committee.inner_cfg(cfg).ticks)
    return committee.metrics(cfg, finals), series


def _reject_stacked(cfg: SimConfig) -> None:
    # profile_run only: the profiler capture wraps the flat static
    # program; probe tracing handles committee via _traced_committee
    if cfg.topology == "committee":
        raise NotImplementedError(
            "profile_run wraps the flat (state, bufs) engine; profile the "
            "inner committee config instead (probe tracing — run_traced — "
            "does support committee, with stacked [C, ticks] series)"
        )


def run_traced(cfg: SimConfig, seed: int | None = None):
    """Run one simulation recording a probe series.

    Returns ``(metrics, series)`` where ``series`` maps probe names to
    ``np.ndarray``.  Dispatches through ``runner.use_round_schedule``
    exactly like ``run_simulation`` — an ineligible explicit
    ``schedule='round'`` raises the same ``ValueError``, and cpp-only
    fidelity flags are rejected the same way (``runner._reject_cpp_only``)
    — so the traced simulator is ALWAYS the one the untraced run would use:

    - tick engine: per-tick samples, length ``cfg.ticks`` (no ``"t"`` key;
      the sample index IS the tick).  ``cfg.with_(schedule="tick")`` forces
      this arm for bit-exact tick series on any config.  The kregular
      overlay rides this arm too (its tables are trace constants).
    - fast paths: per-round / per-heartbeat samples with a ``"t"`` array of
      virtual ticks (see the module docstring for each protocol's keys).
    - committee hierarchy: stacked ``[C, ticks]`` series, one lane per
      committee, plus the inner ``"t"`` tick axis (per-committee counter
      tracks and instant events in the chrome-trace export).
    """
    from blockchain_simulator_tpu.runner import (
        _reject_cpp_only,
        use_round_schedule,
    )

    _reject_cpp_only(cfg)
    if cfg.topology == "committee":
        use_round_schedule(cfg)  # validates schedule='round' (always tick)
        return _traced_committee(cfg, seed)
    if use_round_schedule(cfg):  # raises on ineligible explicit 'round'
        if cfg.protocol == "pbft":
            return _traced_pbft_round(cfg, seed)
        if cfg.protocol == "raft":
            return _traced_raft_hb(cfg, seed)
        return _traced_mixed_fast(cfg, seed)
    return _traced_tick(cfg, seed)


def events_from_series(series: dict, name: str) -> np.ndarray:
    """Sample indices at which a monotone counter series increments — the
    reconstruction of the reference's per-event log timestamps (e.g.
    pbft-node.cc:259 commit lines) from the recorded time series.  For
    per-tick series the index is the tick; fast-path series map indices to
    ticks via ``series["t"]``."""
    s = np.asarray(series[name])
    prev = np.concatenate([[0], s[:-1]])
    return np.flatnonzero(s > prev)


# to_chrome_trace caps each counter track's sample count so multi-hour
# windows stay loadable in the Perfetto UI; instant events are never dropped.
MAX_COUNTER_SAMPLES = 2000


def chrome_events(series: dict, name: str = "sim", pid: int = 0,
                  ) -> list[dict]:
    """The Chrome-trace event list of one probe series dict — the body of
    :func:`to_chrome_trace`, exposed so utils/telemetry.py can overlay a
    sim series and serving spans on ONE timeline
    (``telemetry.spans_to_chrome_trace(series=...)``).  ``pid`` namespaces
    the process row so the overlay's span process stays separate."""
    ts_map = np.asarray(series["t"]) if "t" in series else None
    events: list[dict] = [
        {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
         "args": {"name": name}},
    ]
    tid = 0

    def emit(label: str, v: np.ndarray) -> None:
        nonlocal tid
        t_axis = (
            ts_map
            if ts_map is not None and len(ts_map) == len(v)
            else np.arange(len(v))
        )
        tid += 1
        events.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": label},
        })
        stride = max(1, len(v) // MAX_COUNTER_SAMPLES)
        for i in range(0, len(v), stride):
            events.append({
                "name": label, "ph": "C", "pid": pid, "tid": 0,
                "ts": int(t_axis[i]) * 1000,
                "args": {label: float(v[i])},
            })
        d = np.diff(v.astype(np.int64), prepend=0)
        if np.all(d >= 0):  # monotone counter: increments are events
            for i in np.flatnonzero(d > 0):
                events.append({
                    "name": label, "ph": "i", "s": "t", "pid": pid,
                    "tid": tid, "ts": int(t_axis[i]) * 1000,
                    "args": {"value": int(v[i]), "delta": int(d[i])},
                })

    for k in sorted(series):
        if k == "t":
            continue
        v = np.asarray(series[k])
        if v.size == 0 or v.ndim not in (1, 2):
            continue
        if v.ndim == 1:
            emit(k, v)
        else:
            # stacked committee series [C, m] (run_traced committee arm):
            # one counter track + per-committee instant events per lane
            for ci in range(v.shape[0]):
                emit(f"{k}/c{ci}", v[ci])
    return events


def to_chrome_trace(series: dict, path, name: str = "sim") -> dict:
    """Convert a probe series dict to a Chrome-trace JSON timeline.

    Written for ui.perfetto.dev / chrome://tracing: one process named
    ``name``; every 1-D series becomes a counter track ("ph": "C",
    downsampled to <= MAX_COUNTER_SAMPLES points), and every monotone
    non-decreasing series additionally emits one INSTANT event ("ph": "i")
    per increment — commits, elections, view changes as discrete marks on
    their own named tracks.  Virtual time maps 1 tick (= 1 simulated ms) to
    1000 trace-µs, so the UI's ms ruler reads in simulated milliseconds.

    ``series["t"]`` (fast-path series) supplies sample->tick mapping for
    every same-length series; series without a matching ``t`` use their
    sample index as the tick.  Returns ``{"events", "instants", "path"}``
    (counts, for callers that report them).
    """
    events = chrome_events(series, name=name, pid=0)
    n_instant = sum(1 for e in events if e.get("ph") == "i")
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    with open(path, "w") as f:
        json.dump(doc, f)
    return {"events": len(events), "instants": n_instant, "path": str(path)}


def profile_run(cfg: SimConfig, logdir: str, seed: int | None = None) -> dict:
    """Capture a profiler trace of one (pre-compiled) run into ``logdir``.

    Returns the run metrics augmented with wallclock timings.  View with
    TensorBoard's profile plugin or ui.perfetto.dev.
    """
    from blockchain_simulator_tpu.runner import make_sim_fn

    _reject_stacked(cfg)
    proto = get_protocol(cfg.protocol)
    sim = make_sim_fn(cfg)
    key = jax.random.key(cfg.seed if seed is None else seed)
    t0 = time.perf_counter()
    # force_sync throughout: block_until_ready alone measures dispatch, not
    # execution, on this env's axon backend (KNOWN_ISSUES.md #1)
    force_sync(sim(key))  # compile + warm outside the capture
    compile_s = time.perf_counter() - t0
    with jax.profiler.trace(logdir):
        t0 = time.perf_counter()
        final = force_sync(sim(key))
        run_s = time.perf_counter() - t0
    m = proto.metrics(cfg, final)
    m["compile_plus_first_run_s"] = compile_s
    m["profiled_run_s"] = run_s
    return m
