"""Unified executable registry + persistent AOT compile caching.

Compilation dominates end-to-end wall on every sweep-shaped workload this
repo cares about: the committed ``BENCH_*.json`` history shows ``compile_s``
at 20-23 s against ~18 s of run wall, and a 0..33 Byzantine f-sweep used to
pay one full XLA compile PER FAULT LEVEL for seconds of actual simulation.
This module is the one place compiled programs live:

- **In-process registry** (:class:`ExecutableRegistry`, module singleton
  :data:`registry`): a single keyed LRU store that subsumes the scattered
  ``functools.lru_cache`` factories (``runner.make_sim_fn``,
  ``utils/trace.py``'s traced fns, ``parallel/sweep._batched_fn``).  Factory
  functions opt in with :func:`cached_factory` — the jaxlint
  ``static-arg-recompile-hazard`` rule recognizes it as a sanctioned cache
  decorator, same as ``functools.lru_cache``.  Hit/miss/eviction stats are
  exported into every run manifest (``utils/obs.py`` ``cache`` block).
- **AOT staging** (:func:`aot_compile`): explicit
  ``jit(f).lower(*args).compile()`` with the executable's own cost analysis
  attached — the compile-vs-run split every timing surface wants, without a
  throwaway first execution.
- **Persistent on-disk layer**: with ``$BLOCKSIM_COMPILE_CACHE`` set,
  :func:`aot_compile` round-trips executables through
  ``jax.experimental.serialize_executable`` (measured WORKING on this
  container's jax 0.4.37 / XLA:CPU — bit-equal metrics across processes,
  ~1 s deserialize vs ~8-20 s trace+lower+compile; KNOWN_ISSUES.md #0e,
  repro: ``tools/repro_exe_serialize.py``).  Independently,
  :func:`enable_xla_cache` points jax's own compilation cache
  (``jax_compilation_cache_dir``) at ``$BLOCKSIM_XLA_CACHE`` so even
  non-AOT ``jit`` calls skip XLA re-optimization across processes.

Design constraints:

- **Never touch a backend at import** (jaxlint module-scope-backend-touch;
  KNOWN_ISSUES.md #3: backend init can hang ~25 min on a wedged tunnel).
  This module does not even import jax at module scope — ``utils/obs.py``
  imports it from the bench PARENT process, which deliberately never
  initializes jax.
- **Corrupt or stale disk entries must never take down a run**: every
  persistent-layer failure falls back to a fresh compile and is counted in
  the stats instead of raised.  Entries carry a content checksum verified
  BEFORE deserialization; a failed check self-heals (detect -> delete ->
  recompile -> rewrite) and counts ``corrupt_healed`` in the stats and in
  every manifest ``cache`` block — the chaos cache-corrupt drill
  (tools/chaos_drill.py) flips real bits to prove it.
"""

from __future__ import annotations

import collections
import functools
import hashlib
import os
import pickle
import sys
import threading
import time

# Persistent serialized-executable directory (unset = in-process only).
PERSIST_ENV = "BLOCKSIM_COMPILE_CACHE"
# jax's own compilation-cache directory (unset = disabled).
XLA_CACHE_ENV = "BLOCKSIM_XLA_CACHE"

# Bump when the on-disk entry layout changes: stale-format entries are
# treated as misses, never parsed.  v2 added the content checksum: the
# serialized body is hashed at write time and verified BEFORE deserialize,
# so a bit-flipped entry (KNOWN_ISSUES.md #0e's corruption folklore) is
# detected, deleted, recompiled and rewritten — counted as
# ``corrupt_healed`` — instead of feeding garbage to the deserializer or
# silently degrading to a compile with no trace of why.
_DISK_FORMAT = 2


class _CorruptEntry(Exception):
    """A persistent-cache entry that failed the content checksum (or could
    not even be parsed): bit rot, a torn write, or outside interference —
    the self-heal path's trigger, never surfaced to callers."""


def _dist_version(name: str) -> str | None:
    """Installed package version without importing the package (the
    utils/obs.py convention)."""
    try:
        import importlib.metadata

        return importlib.metadata.version(name)
    except Exception:
        return None


def _backend_if_initialized() -> str | None:
    """The active backend name, ONLY if one is already initialized — this
    function never triggers a backend init of its own (utils/obs.manifest
    has the incident history)."""
    if "jax" not in sys.modules:
        return None
    try:
        from jax._src import xla_bridge

        if getattr(xla_bridge, "_backends", None):
            # guarded: a backend already exists, so this cannot init one
            # (the module-scope-backend-touch rule does not police this
            # module, so no suppression is needed — jaxlint's stale-
            # suppression check flagged the one that used to sit here)
            return sys.modules["jax"].default_backend()
    except Exception:
        pass
    return None


def _mesh_desc(args: tuple, kwargs: tuple) -> str | None:
    """Compact mesh descriptor (``"sweep=8,nodes=1"``) of the first
    ``jax.sharding.Mesh`` among a registry key's arguments, or None for a
    single-device entry.  Duck-typed (``axis_names`` + ``devices`` +
    mapping ``shape``) — this module never imports jax (module
    docstring: a stats read must not be able to init a backend)."""
    for a in args + tuple(v for _, v in kwargs):
        if hasattr(a, "axis_names") and hasattr(a, "devices"):
            try:
                return ",".join(
                    f"{k}={int(v)}" for k, v in dict(a.shape).items()
                )
            except Exception:
                return None
    return None


def _display_key(name: str, args: tuple, kwargs: tuple) -> str:
    """Short human-readable key for stats/manifests: the factory name plus
    the config hash of the first dataclass argument (the join key used
    everywhere else in the observability layer)."""
    import dataclasses

    from blockchain_simulator_tpu.utils import obs

    for a in args + tuple(v for _, v in kwargs):
        if dataclasses.is_dataclass(a):
            return f"{name}:{obs.config_hash(a)}"
    return name


class ExecutableRegistry:
    """Keyed LRU store for built callables/executables with hit/miss stats.

    Keys are ``(factory name, args, kwargs)`` — every factory argument in
    this repo is hashable (frozen ``SimConfig``, ``jax.sharding.Mesh``,
    ints), the same property the old per-module ``lru_cache``s relied on.
    """

    def __init__(self, maxsize: int = 128):
        self.maxsize = maxsize
        self._entries: collections.OrderedDict = collections.OrderedDict()
        # key -> mesh descriptor string (None for single-device entries);
        # kept in lockstep with _entries so stats can expose the mesh spec
        # of every live entry without re-parsing keys
        self._mesh: dict = {}
        self._lock = threading.RLock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.disk_hits = 0
        self.disk_misses = 0
        self.disk_saves = 0
        self.disk_errors = 0
        self.corrupt_healed = 0
        self.last_key: str | None = None
        self.last_mesh: str | None = None

    # ---------------------------------------------------------- memoize ---
    def get(self, name: str, args: tuple, kwargs: dict, build):
        """Return the cached build for ``(name, args, kwargs)``, building
        (and recording a miss) when absent.  LRU beyond ``maxsize``."""
        key = (name, args, tuple(sorted(kwargs.items())))
        with self._lock:
            if key in self._entries:
                self.hits += 1
                self._entries.move_to_end(key)
                self.last_key = _display_key(name, args, key[2])
                self.last_mesh = self._mesh.get(key)
                return self._entries[key]
        # build OUTSIDE the lock: builds trace/compile for minutes and must
        # not serialize unrelated factories behind a single mutex
        value = build(*args, **kwargs)
        with self._lock:
            self.misses += 1
            self.last_key = _display_key(name, args, key[2])
            self.last_mesh = _mesh_desc(args, key[2])
            self._entries[key] = value
            self._entries.move_to_end(key)
            self._mesh[key] = self.last_mesh
            while len(self._entries) > self.maxsize:
                evicted, _ = self._entries.popitem(last=False)
                self._mesh.pop(evicted, None)
                self.evictions += 1
        return value

    def clear(self, name: str | None = None) -> None:
        """Drop every entry (``name=None``) or just one factory's entries —
        the ``lru_cache.cache_clear`` analog ``cached_factory`` wrappers
        expose (tools/ablate.py patches ops and rebuilds through a cleared
        ``make_sim_fn``; a shared-store clear must not evict every other
        factory with it)."""
        with self._lock:
            if name is None:
                self._entries.clear()
                self._mesh.clear()
                return
            for key in [k for k in self._entries if k[0] == name]:
                del self._entries[key]
                self._mesh.pop(key, None)

    def __len__(self) -> int:
        return len(self._entries)

    # ------------------------------------------------------------ stats ---
    def stats(self) -> dict:
        """Full stats snapshot (tests, artifacts)."""
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "entries": len(self._entries),
                "disk_hits": self.disk_hits,
                "disk_misses": self.disk_misses,
                "disk_saves": self.disk_saves,
                "disk_errors": self.disk_errors,
                "corrupt_healed": self.corrupt_healed,
                "last_key": self.last_key,
                "persistent_dir": persistent_dir(),
            }

    def stats_snapshot(self) -> dict:
        """Thread-safe point-in-time snapshot for stats endpoints: the full
        :meth:`stats` record plus a per-factory entry breakdown.  The
        scenario server (serve/) attaches this to its ``/stats`` endpoint
        and its bench/self-test manifests so a running daemon's cache state
        is inspectable without touching jax (pure counter reads).

        Schema note (v. mesh bump): ``mesh`` maps each factory to a
        ``{mesh descriptor: entry count}`` breakdown — the mesh spec of
        every live registry entry (``"sweep=8,nodes=1"``; single-device
        entries count under ``"none"``).  Readers must tolerate absent or
        grown keys (the serve/ contract)."""
        with self._lock:
            by_factory: dict[str, int] = {}
            by_mesh: dict[str, dict[str, int]] = {}
            for key in self._entries:
                by_factory[key[0]] = by_factory.get(key[0], 0) + 1
                desc = self._mesh.get(key) or "none"
                fac = by_mesh.setdefault(key[0], {})
                fac[desc] = fac.get(desc, 0) + 1
            snap = self.stats()  # RLock: safe to re-enter
            snap["by_factory"] = dict(sorted(by_factory.items()))
            snap["mesh"] = {
                k: dict(sorted(v.items())) for k, v in sorted(by_mesh.items())
            }
            return snap

    def manifest(self) -> dict:
        """The compact ``cache`` block utils/obs.py attaches to every
        runs.jsonl line.  Pure counter reads — never touches jax."""
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "key": self.last_key,
                "mesh": self.last_mesh,
                "corrupt_healed": self.corrupt_healed,
                "persistent_dir": persistent_dir(),
            }


registry = ExecutableRegistry()


def cached_factory(name: str):
    """Decorator: memoize a ``factory(*hashable_args) -> callable`` in the
    process-wide :data:`registry` (the ``functools.lru_cache`` replacement;
    jaxlint's static-arg-recompile-hazard sanctions it the same way).

    ``wrapper.__wrapped__`` is the raw factory, as with ``lru_cache``.

    Registering a name here puts the factory under the graph audit's
    contract: ``lint/graph/programs.py`` must carry at least one
    ``ProgramSpec`` covering it (discovery is by AST over this decorator),
    or ``python -m blockchain_simulator_tpu.lint.graph`` fails the
    ``unaudited-factory`` rule in CI.
    """

    def deco(build):
        @functools.wraps(build)
        def wrapper(*args, **kwargs):
            return registry.get(name, args, kwargs, build)

        # lru_cache API parity: per-factory invalidation without touching
        # the other factories sharing the registry (tools/ablate.py relies
        # on make_sim_fn.cache_clear() between patched-op variants)
        wrapper.cache_clear = lambda: registry.clear(name)
        return wrapper

    return deco


# ------------------------------------------------------- persistent layer ---


def persistent_dir() -> str | None:
    """Serialized-executable directory ($BLOCKSIM_COMPILE_CACHE), or None."""
    return os.environ.get(PERSIST_ENV) or None


def enable_xla_cache() -> str | None:
    """Point jax's own compilation cache at ``$BLOCKSIM_XLA_CACHE`` (no-op
    when unset).  Thresholds are zeroed because on XLA:CPU the default
    min-compile-time filter would skip exactly the entries a 2-core box
    needs.  Returns the directory when enabled."""
    path = os.environ.get(XLA_CACHE_ENV)
    if not path:
        return None
    import jax

    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    return path


def _disk_key(name: str, cfg, example_args, extra) -> str:
    """Content hash of everything that must match for a serialized
    executable to be valid: factory name, canonical config, input avals,
    jax/jaxlib versions, backend, device count."""
    import dataclasses
    import json

    import jax

    from blockchain_simulator_tpu.utils import obs

    avals = [
        f"{getattr(a, 'shape', None)}:{getattr(a, 'dtype', None)}"
        for a in jax.tree.leaves(example_args)
    ]
    blob = json.dumps(
        {
            "format": _DISK_FORMAT,
            "name": name,
            "cfg": obs.config_hash(cfg) if dataclasses.is_dataclass(cfg) else str(cfg),
            "avals": avals,
            "extra": repr(extra),
            "jax": _dist_version("jax"),
            "jaxlib": _dist_version("jaxlib"),
            "backend": jax.default_backend(),
            "n_devices": len(jax.devices()),
        },
        sort_keys=True,
    )
    return hashlib.sha256(blob.encode()).hexdigest()[:24]


def _model_modules(cfg) -> None:
    """Import the model modules whose flax-struct pytree types appear in a
    serialized executable's in/out treedefs — unpickling a treedef resolves
    them by type, so they must be importable first."""
    from blockchain_simulator_tpu.models.base import get_protocol

    proto = getattr(cfg, "protocol", None)
    if proto is None:
        return
    get_protocol(proto)
    if proto == "pbft":
        from blockchain_simulator_tpu.models import pbft_round  # noqa: F401
    elif proto in ("raft", "mixed"):
        from blockchain_simulator_tpu.models import raft_hb  # noqa: F401


def _load_entry(path: str):
    """Parse + checksum-verify one on-disk entry; returns ``(payload,
    in_tree, out_tree)`` ready for ``deserialize_and_load``.  Raises
    :class:`_CorruptEntry` on bit rot (unparseable container, checksum
    mismatch, or a body that fails to parse despite its checksum) and
    ``ValueError`` on a clean-but-stale format version — the two are
    counted differently (``corrupt_healed`` vs ``disk_errors``) because
    only the first means the bytes changed under us."""
    try:
        with open(path, "rb") as f:
            rec = pickle.load(f)
        fmt = rec[0]
    except Exception as e:
        raise _CorruptEntry(f"unparseable entry: {e}") from e
    if fmt != _DISK_FORMAT:
        raise ValueError(f"stale cache format {fmt}")
    try:
        _, digest, blob = rec
    except Exception as e:
        raise _CorruptEntry(f"malformed v{_DISK_FORMAT} entry: {e}") from e
    if hashlib.sha256(blob).hexdigest() != digest:
        raise _CorruptEntry("content checksum mismatch")
    try:
        return pickle.loads(blob)
    except Exception as e:
        # the checksum matched, so the WRITER produced a bad body — still
        # a heal (delete + recompile + rewrite), never a crash
        raise _CorruptEntry(f"checksummed body failed to parse: {e}") from e


def aot_compile(name: str, jitted, example_args: tuple, cfg=None, extra=None):
    """AOT-stage ``jitted`` for ``example_args``: returns ``(compiled,
    info)`` where ``info`` = ``{"source": "disk"|"compile",
    "compile_s": float, "cost": {"flops", "bytes"} | None}``.

    With ``$BLOCKSIM_COMPILE_CACHE`` set, tries
    ``jax.experimental.serialize_executable`` round-trips first (load) and
    last (save); any disk-layer failure degrades to a fresh compile and a
    counter bump, never an exception.  The in-process :data:`registry` is
    the first-level cache — wrap call sites in :func:`cached_factory` (or
    call :func:`aot_cached`) so repeat invocations skip this entirely.
    """
    import jax

    info: dict = {"source": "compile", "compile_s": None, "cost": None}
    pdir = persistent_dir()
    path = None
    if pdir:
        try:
            os.makedirs(pdir, exist_ok=True)
            path = os.path.join(
                pdir, f"{name}-{_disk_key(name, cfg, example_args, extra)}.jaxexe"
            )
        except Exception:
            registry.disk_errors += 1
            path = None
    t0 = time.perf_counter()
    if path and os.path.exists(path):
        try:
            from jax.experimental.serialize_executable import (
                deserialize_and_load,
            )

            if cfg is not None:
                _model_modules(cfg)
            payload, in_tree, out_tree = _load_entry(path)
            compiled = deserialize_and_load(payload, in_tree, out_tree)
            registry.disk_hits += 1
            info["source"] = "disk"
            info["compile_s"] = time.perf_counter() - t0
            info["cost"] = _cost(compiled)
            return compiled, info
        except _CorruptEntry:
            # the self-heal cycle: detect -> delete -> recompile (below)
            # -> rewrite (the save path overwrites).  Counted so a flaky
            # disk is visible in every manifest instead of masquerading
            # as an unexplained slow compile.
            registry.corrupt_healed += 1
            try:
                os.unlink(path)
            except OSError:
                pass
        except Exception:
            # stale-format/foreign/undeserializable entry: recompile (and
            # overwrite below) — the bytes were intact, the entry was not
            # usable here
            registry.disk_errors += 1
    elif path:
        registry.disk_misses += 1
    compiled = jitted.lower(*example_args).compile()
    info["compile_s"] = time.perf_counter() - t0
    info["cost"] = _cost(compiled)
    if path:
        try:
            from jax.experimental.serialize_executable import serialize

            payload, in_tree, out_tree = serialize(compiled)
            blob = pickle.dumps((payload, in_tree, out_tree))
            digest = hashlib.sha256(blob).hexdigest()
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                pickle.dump((_DISK_FORMAT, digest, blob), f)
            os.replace(tmp, path)  # atomic: readers never see a torn entry
            registry.disk_saves += 1
        except Exception:
            registry.disk_errors += 1
    return compiled, info


def cost_of(staged) -> dict | None:
    """XLA's own {flops, bytes accessed} normalized to ``{"flops",
    "bytes"}``, or None.  ``staged`` is anything exposing
    ``cost_analysis()`` — a compiled executable (the roofline fields
    bench.py puts on its artifact) or a ``jax.stages.Lowered`` (the
    analytical model the graph auditor's budget gate pins,
    lint/graph/ir.py) — so every cost surface in the repo reads the same
    record."""
    try:
        ca = staged.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        return {
            "flops": float(ca.get("flops", 0.0)),
            "bytes": float(ca.get("bytes accessed", 0.0)),
        }
    except Exception:
        return None


_cost = cost_of  # internal alias kept for the aot_compile call sites below


def aot_cached(name: str, jitted_factory, example_args: tuple, cfg=None, extra=None):
    """Registry-memoized :func:`aot_compile`: one entry per (name, cfg,
    extra, input avals).  ``jitted_factory()`` is only called on a miss.
    Returns ``(compiled, info)`` — ``info`` is the build-time record (a
    registry hit returns the original record with ``source`` unchanged and
    ``compile_s`` as paid at build time)."""
    import jax

    shapes = tuple(
        (str(getattr(a, "shape", None)), str(getattr(a, "dtype", None)))
        for a in jax.tree.leaves(example_args)
    )
    return registry.get(
        f"aot:{name}",
        (cfg, extra, shapes),
        {},
        lambda *_a, **_k: aot_compile(
            name, jitted_factory(), example_args, cfg=cfg, extra=extra
        ),
    )
