from blockchain_simulator_tpu.utils.config import FaultConfig, SimConfig  # noqa: F401
from blockchain_simulator_tpu.utils import prng  # noqa: F401
