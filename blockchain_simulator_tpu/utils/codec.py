"""Wire codec — the reference's ASCII message format (SURVEY.md C7).

The reference encodes every protocol message as 3-4 ASCII bytes, one char per
field, via ``intToChar``/``charToInt`` (``'0' + value``): pbft-node.cc:57-63,
raft-node.cc:54-60, paxos-node.cc:49-55.  Consequence (quirk #11): every field
— view numbers, seq numbers, tickets, node ids — is capped at 0-9; anything
larger silently corrupts into ``':'``, ``';'``, ... (the reference never
checks).  Block-carrying messages append a ``'1'``-filled tx payload whose
first bytes the header overwrites (``generateTX``, pbft-node.cc:79-95,
raft-node.cc:323-336).

The tensorized backends deliberately design this away (channels carry int
fields directly — there is nothing to parse), so the codec exists as the
boundary component: encoding a simulated message stream to the reference's
exact wire format (e.g. for trace export) and decoding such bytes back.
``strict=True`` raises on out-of-range fields; ``strict=False`` reproduces
the reference's silent corruption byte-for-byte.

Message schemas below are the complete wire protocol from SURVEY.md §2
("Protocol message formats"), with the declared-but-unused types included.
"""

from __future__ import annotations

# --- the three per-protocol Message enums ----------------------------------
# pbft-node.h:80-91
PBFT_TYPES = {
    "REQUEST": 0, "PRE_PREPARE": 1, "PREPARE": 2, "COMMIT": 3,
    "PRE_PREPARE_RES": 4, "PREPARE_RES": 5, "COMMIT_RES": 6, "REPLY": 7,
    "VIEW_CHANGE": 8,
}
# raft-node.h:81-89
RAFT_TYPES = {
    "CLIENT_REQ": 0, "CLIENT_RES": 1, "VOTE_REQ": 2, "VOTE_RES": 3,
    "HEARTBEAT": 4, "HEARTBEAT_RES": 5,
}
# paxos-node.h:72-81
PAXOS_TYPES = {
    "REQUEST_TICKET": 0, "REQUEST_PROPOSE": 1, "REQUEST_COMMIT": 2,
    "RESPONSE_TICKET": 3, "RESPONSE_PROPOSE": 4, "RESPONSE_COMMIT": 5,
    "CLIENT_PROPOSE": 6,
}

# field layout per (protocol, type): header byte 0 is always the type char.
# (SURVEY.md §2 message-format table; field names follow the reference code.)
SCHEMAS = {
    "pbft": {
        "PRE_PREPARE": ("v", "n", "val"),      # pbft-node.cc:89-93
        "PREPARE": ("v", "n", "val"),          # pbft-node.cc:196-209
        "PREPARE_RES": ("v", "n", "state"),    # pbft-node.cc:215-220
        "COMMIT": ("v", "n"),                  # pbft-node.cc:231-238
        "COMMIT_RES": ("v", "n"),              # built, never sent (:249-253)
        "VIEW_CHANGE": ("v", "leader"),        # pbft-node.cc:294-303
    },
    "raft": {
        "VOTE_REQ": ("id",),                   # raft-node.cc:392-401
        "VOTE_RES": ("state",),                # raft-node.cc:154-167
        "HEARTBEAT": ("hb_type", "val"),       # raft-node.cc:405-429
        "HEARTBEAT_RES": ("hb_type", "state"),  # raft-node.cc:170-193
    },
    "paxos": {
        "REQUEST_TICKET": ("ticket",),           # paxos-node.cc:511-518
        # state-conditional: the SUCCESS promise carries the stored command,
        # the FAILED reply is ['type','fail'] only — its byte 3 is
        # uninitialized stack garbage upstream (paxos-node.cc:177-197), so
        # decode() returns 'command' only when state == SUCCESS (0)
        "RESPONSE_TICKET": ("state", "command"),
        "REQUEST_PROPOSE": ("ticket", "command"),  # paxos-node.cc:258-274
        "RESPONSE_PROPOSE": ("state",),          # paxos-node.cc:199-221
        "REQUEST_COMMIT": ("ticket", "command"),  # paxos-node.cc:295-305
        "RESPONSE_COMMIT": ("state",),           # paxos-node.cc:222-247
        "CLIENT_PROPOSE": (),                    # paxos-node.cc:357-361
    },
}

_TYPE_ENUMS = {"pbft": PBFT_TYPES, "raft": RAFT_TYPES, "paxos": PAXOS_TYPES}


def int_to_char(v: int, strict: bool = True) -> int:
    """``intToChar``: ``'0' + v`` (pbft-node.cc:57-59).  One byte out.

    quirk #11: the reference accepts any int and silently produces a
    non-digit byte for v outside 0-9 (``10 -> ':'``); ``strict=True`` raises
    instead, ``strict=False`` reproduces the corruption."""
    if strict and not 0 <= v <= 9:
        raise ValueError(
            f"field value {v} does not fit the reference's single-char "
            "encoding (0-9, SURVEY.md quirk #11); pass strict=False to "
            "reproduce the silent corruption"
        )
    return ord("0") + v


def char_to_int(b: int) -> int:
    """``charToInt``: ``c - '0'`` (pbft-node.cc:61-63).  No validation —
    exactly like the reference (a corrupted byte round-trips to its
    out-of-range int)."""
    return b - ord("0")


def encode(protocol: str, msg_type: str, *fields: int, strict: bool = True,
           payload_txs: int = 0, tx_size: int = 0) -> bytes:
    """Encode one message to the reference's wire bytes.

    ``payload_txs``/``tx_size`` append a ``generateTX`` block: ``num * size``
    bytes of ``'1'`` fill whose first ``len(header)`` bytes the header
    overwrites (pbft-node.cc:79-95: the header is written INTO the block
    buffer, so the wire length is the block size, not header + block)."""
    schema = _schema(protocol, msg_type)
    # state-conditional layout: the paxos RESPONSE_TICKET FAILED reply is
    # ['type','fail'] only (paxos-node.cc:190-193) — encode it without a
    # command byte, mirroring decode()
    if (
        (protocol, msg_type) == ("paxos", "RESPONSE_TICKET")
        and fields
        and fields[0] != 0  # SUCCESS == 0 (paxos-node.h:85)
    ):
        schema = schema[:1]
    if len(fields) != len(schema):
        raise ValueError(
            f"{protocol}/{msg_type} takes fields {schema}, got {len(fields)}"
        )
    header = bytes(
        [int_to_char(_TYPE_ENUMS[protocol][msg_type], strict)]
        + [int_to_char(v, strict) for v in fields]
    )
    if payload_txs:
        block = bytearray(b"1" * max(payload_txs * tx_size, len(header)))
        block[: len(header)] = header
        return bytes(block)
    return header


def decode(protocol: str, data: bytes) -> tuple[str, dict[str, int]]:
    """Decode wire bytes to ``(msg_type, {field: value})``.

    Like ``getPacketContent`` + the ``HandleRead`` switch, only the header
    chars are read; any block payload beyond the schema is ignored."""
    if not data:
        raise ValueError("empty packet")
    enum = _TYPE_ENUMS[_check_protocol(protocol)]
    t = char_to_int(data[0])
    by_val = {v: k for k, v in enum.items()}
    if t not in by_val or by_val[t] not in SCHEMAS[protocol]:
        raise ValueError(f"unknown/unused {protocol} message type byte {data[0]!r}")
    name = by_val[t]
    schema = SCHEMAS[protocol][name]
    # state-conditional layout: a paxos RESPONSE_TICKET FAILED reply carries
    # no command (upstream leaves byte 3 uninitialized, paxos-node.cc:190-193)
    # — surface only the fields the sender actually wrote
    if (
        protocol == "paxos"
        and name == "RESPONSE_TICKET"
        and len(data) >= 2
        and char_to_int(data[1]) != 0  # SUCCESS == 0 (paxos-node.h:85)
    ):
        schema = schema[:1]
    if len(data) < 1 + len(schema):
        raise ValueError(
            f"{protocol}/{name} needs {1 + len(schema)} bytes, got {len(data)}"
        )
    return name, {f: char_to_int(data[1 + i]) for i, f in enumerate(schema)}


def _check_protocol(protocol: str) -> str:
    if protocol not in SCHEMAS:
        raise ValueError(f"unknown protocol {protocol!r}")
    return protocol


def _schema(protocol: str, msg_type: str) -> tuple[str, ...]:
    _check_protocol(protocol)
    if msg_type not in SCHEMAS[protocol]:
        raise ValueError(
            f"{protocol} has no wire schema for {msg_type!r} "
            f"(declared-but-unused types are not encodable)"
        )
    return SCHEMAS[protocol][msg_type]
