"""Backend health telemetry: structured tunnel verdicts instead of folklore.

This environment's single-client TPU tunnel is the repo's most fragile
dependency (KNOWN_ISSUES.md #1, #3): a client hard-killed mid-compile wedges
it for hours, and a wedged tunnel turns every naive backend init into a
~25-minute stall.  bench.py has carried an inline defense since round 5 — a
tiny-matmul probe stage whose absence within a patience window declares the
tunnel sick.  This module lifts that logic into a reusable, recorded form:

- :func:`probe_backend` — the in-process probe: backend id, device count,
  tiny-matmul compile+run latency, forced scalar readback.  Returns a
  structured verdict dict (``healthy`` or ``sick``); never raises.
- :func:`probe_backend_supervised` — the parent-side classifier: runs the
  probe in a detached child and, when no verdict lands within ``patience_s``,
  retries with jittered exponential backoff (one slow probe must not flip
  the serve admission gate) before returning ``wedged`` — ABANDONING each
  silent child without killing it (killing a client hung in backend init is
  what wedges the tunnel, KNOWN_ISSUES.md #3).  The verdict records the
  attempt count.
- ``python -m blockchain_simulator_tpu.utils.health`` — prints exactly one
  JSON verdict line and appends it to a rolling ``HEALTH.jsonl``, so tunnel
  state across rounds becomes data (`--log ''` disables the file).

bench.py consumes :func:`probe_backend` for its child's stage-0 probe; its
parent keeps its own patience/abandon loop because it also ladders
measurements behind the probe.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import subprocess
import sys
import tempfile
import time

VERDICTS = ("healthy", "sick", "wedged")

HEALTH_ENV = "BLOCKSIM_HEALTH_JSONL"


class BackendWedgedError(RuntimeError):
    """The rolling health log's latest verdict says the backend is wedged
    (KNOWN_ISSUES.md #3): dispatching would hang on backend init, so the
    caller fails fast instead.  Typed so the sweep tier
    (parallel/sweep.py ``journal=`` paths and the sweep entrypoints) and
    drills classify the refusal without string-matching.  Carries the
    offending verdict record as ``.verdict``."""

    def __init__(self, verdict: dict):
        self.verdict = dict(verdict)
        super().__init__(
            f"backend wedged per health log (probe_s="
            f"{verdict.get('probe_s')}, ts={verdict.get('ts')}): refusing "
            "to dispatch — a wedged tunnel turns backend init into a "
            "~25-minute hang (KNOWN_ISSUES.md #3); re-probe with "
            "`python -m blockchain_simulator_tpu.utils.health`"
        )


def require_not_wedged(path: str | None = None, max_age_s: float = 3600.0,
                       replica: str | None = None) -> dict | None:
    """Fail fast on a fresh ``wedged`` verdict — the sweep tier's
    admission gate (the way bench.py ladders its measurements behind the
    probe): consulted before dispatch so a multi-hour grid never hangs on
    backend init a probe already classified.

    Reads :func:`latest_verdict` (explicit path, else
    ``$BLOCKSIM_HEALTH_JSONL``; no log = no gate) and raises the typed
    :class:`BackendWedgedError` only when the latest verdict is
    ``wedged`` AND younger than ``max_age_s`` (a stale verdict from hours
    ago says nothing about the tunnel now — bench.py re-probes, sweeps
    fail open).  Returns the verdict record consulted (or None), so
    callers can journal the provenance."""
    rec = latest_verdict(path, replica=replica)
    if rec is None:
        return None
    if rec.get("verdict") == "wedged":
        ts = rec.get("ts")
        fresh = not (isinstance(ts, (int, float))
                     and time.time() - ts > max_age_s)
        if fresh:
            raise BackendWedgedError(rec)
    return rec


def probe_backend(platform: str | None = None,
                  replica: str | None = None) -> dict:
    """Probe whatever backend jax resolves (or ``platform``) in-process.

    The probe is bench.py's historical stage 0: ``jax.default_backend()``
    (the init that hangs on a wedged tunnel), then a jitted 128x128 bf16
    matmul with a forced float readback — the only sync this env honors
    (KNOWN_ISSUES.md #1).  Healthy cold via the tunnel: ~45 s (~10 s init +
    ~32 s compile); CPU: well under a second.

    Never raises: any failure returns a ``sick`` verdict with the error
    string.  A *hang* cannot be classified in-process — callers that need
    the ``wedged`` verdict use :func:`probe_backend_supervised`.
    """
    t0 = time.monotonic()
    rec: dict = {"verdict": "sick", "probe_s": None, "backend": None}
    if replica:
        # fleet identity: verdicts are per-PROCESS, so N replicas sharing
        # one rolling HEALTH.jsonl must label their lines or they gate
        # each other's admission (latest_verdict filters on this)
        rec["replica"] = str(replica)
    try:
        import jax

        # the env's sitecustomize forces jax_platforms="axon,cpu" at the
        # config level, so the env var alone does not stick (conftest.py);
        # re-assert a caller-requested platform before any backend init
        platform = platform or os.environ.get("JAX_PLATFORMS") or None
        if platform:
            jax.config.update("jax_platforms", platform)
        import jax.numpy as jnp

        # the probe's JOB is the backend init (the one call that hangs on a
        # wedged tunnel); callers run it in a supervised, abandonable child
        rec["backend"] = jax.default_backend()  # jaxlint: disable=module-scope-backend-touch
        rec["device_count"] = len(jax.devices())  # jaxlint: disable=module-scope-backend-touch
        rec["init_s"] = round(time.monotonic() - t0, 2)
        t1 = time.monotonic()
        val = float(
            jax.jit(lambda a: (a @ a).sum())(jnp.ones((128, 128), jnp.bfloat16))  # jaxlint: disable=module-scope-backend-touch
        )
        rec["compile_run_s"] = round(time.monotonic() - t1, 2)
        rec["probe_value"] = val
        rec["verdict"] = "healthy"
    except Exception as e:  # a broken backend is the datum, not a crash
        rec["error"] = f"{type(e).__name__}: {e}"[:500]
    rec["probe_s"] = round(time.monotonic() - t0, 2)
    return rec


def probe_backend_supervised(
    patience_s: float = 120.0,
    env=None,
    attempts: int = 2,
    backoff_s: float = 2.0,
    rng=None,
    replica: str | None = None,
) -> dict:
    """Run the probe in a detached child; classify a silent child as
    ``wedged`` — but only after ``attempts`` probes, separated by a
    jittered exponential backoff.

    One slow probe (a cold tunnel paying its ~45 s init+compile under
    load, a paging blip) must not flip the serving admission gate to
    paused: a would-be ``wedged`` verdict is retried ``attempts - 1``
    times, sleeping ``backoff_s * 2**k * uniform(0.5, 1.5)`` between
    probes, and only the final miss is declared.  ``healthy``/``sick``
    verdicts return immediately.  The returned record carries
    ``attempts`` (probes actually run) so HEALTH.jsonl shows how hard the
    verdict was earned.  ``rng`` (a ``random.random``-like callable)
    makes the jitter injectable for deterministic drills.
    """
    rng = rng if rng is not None else random.random
    rec: dict = {}
    for attempt in range(1, max(1, int(attempts)) + 1):
        rec = _probe_attempt(patience_s, env)
        rec["attempts"] = attempt
        if rec["verdict"] != "wedged" or attempt >= attempts:
            break
        time.sleep(backoff_s * (2.0 ** (attempt - 1)) * (0.5 + rng()))
    rec["supervised"] = True
    if replica:
        rec["replica"] = str(replica)
    return rec


def _probe_attempt(patience_s: float, env=None) -> dict:
    """ONE supervised probe attempt.

    The child is ``python -m blockchain_simulator_tpu.utils.health --child``
    (one JSON line on stdout).  If no line lands within ``patience_s`` the
    tunnel is presumed wedged and the child is ABANDONED — left running,
    never signaled (KNOWN_ISSUES.md #3) — with its pid reported so an
    operator can watch it free itself.
    """
    child_env = dict(os.environ)
    if env:
        child_env.update(env)
    # the child must resolve this package even when the caller runs elsewhere
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    child_env["PYTHONPATH"] = os.pathsep.join(
        p for p in (pkg_root, child_env.get("PYTHONPATH")) if p
    )
    fd, out_path = tempfile.mkstemp(prefix="health_", suffix=".jsonl")
    out_f = os.fdopen(fd, "w")
    t0 = time.monotonic()
    proc = subprocess.Popen(
        [sys.executable, "-m", "blockchain_simulator_tpu.utils.health",
         "--child"],
        stdout=out_f,
        stderr=subprocess.DEVNULL,
        env=child_env,
        start_new_session=True,
    )
    out_f.close()

    def read_verdict():
        try:
            with open(out_path) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(rec, dict) and "verdict" in rec:
                        return rec
        except OSError:
            pass
        return None

    deadline = t0 + patience_s
    while True:
        if proc.poll() is not None:
            rec = read_verdict()
            if rec is None:
                rec = {
                    "verdict": "sick",
                    "probe_s": round(time.monotonic() - t0, 2),
                    "backend": None,
                    "error": f"probe child exited rc={proc.returncode} "
                             "with no verdict line",
                }
            break
        if time.monotonic() > deadline:
            rec = {
                "verdict": "wedged",
                "probe_s": round(time.monotonic() - t0, 2),
                "backend": None,
                "error": f"no probe verdict within {patience_s:.0f}s; child "
                         "abandoned WITHOUT kill (KNOWN_ISSUES.md #3)",
                "abandoned_pid": proc.pid,
            }
            break
        time.sleep(0.5)
    if proc.poll() is not None:
        try:
            os.unlink(out_path)
        except OSError:
            pass
    # an abandoned child keeps its output file: it is still writing to it
    return rec


def latest_verdict(path: str | None = None,
                   replica: str | None = None) -> dict | None:
    """Most recent verdict record from a rolling health log (explicit path,
    else ``$BLOCKSIM_HEALTH_JSONL``), or None when no log / no parseable
    verdict line exists.  Read-only and never raises: the scenario server
    (serve/) consults this at startup to decide whether admission opens
    paused — a stale or missing log must default to serving, not crash.

    ``replica`` (a fleet replica id) restricts the read to that replica's
    own lines plus UNLABELED lines (a global probe gates everyone): N
    replicas sharing one HEALTH.jsonl no longer clobber each other's
    admission gating.  Without it, every verdict line counts — the
    single-daemon behavior, unchanged."""
    from blockchain_simulator_tpu.utils import obs

    path = path or os.environ.get(HEALTH_ENV)
    if not path:
        return None
    last = None
    for rec in obs.read_jsonl(path):
        if rec.get("verdict") not in VERDICTS:
            continue
        if replica is not None and rec.get("replica") is not None \
                and str(rec.get("replica")) != str(replica):
            continue
        last = rec
    return last


def append_health(rec: dict, path: str | None = None) -> None:
    """Append one verdict line to the rolling health log.  Path precedence:
    explicit arg, $BLOCKSIM_HEALTH_JSONL, nothing (no-op — resolved here so
    obs.append_jsonl's own $BLOCKSIM_RUNS_JSONL fallback never captures
    health verdicts).  Failures are swallowed — telemetry never takes down
    the caller."""
    from blockchain_simulator_tpu.utils import obs

    path = path or os.environ.get(HEALTH_ENV)
    if path:
        obs.append_jsonl(rec, path)


def main(argv=None) -> int:
    """CLI: print exactly ONE JSON verdict line; exit 0 healthy, 1 sick,
    2 wedged.  Default mode is supervised (the only mode that can report
    ``wedged`` instead of hanging with the tunnel)."""
    p = argparse.ArgumentParser(prog="blockchain_simulator_tpu.utils.health")
    p.add_argument("--child", action="store_true",
                   help="internal: run the in-process probe and print it")
    p.add_argument("--in-process", action="store_true",
                   help="probe this process's backend directly (can hang "
                        "for ~25 min on a wedged tunnel; default is a "
                        "supervised child with --patience)")
    p.add_argument("--patience", type=float, default=120.0,
                   help="supervised mode: seconds to wait for the child's "
                        "verdict before declaring the tunnel wedged")
    p.add_argument("--attempts", type=int, default=2,
                   help="supervised mode: probes (jittered exponential "
                        "backoff between them) before a silent tunnel is "
                        "declared wedged — one slow probe must not flip "
                        "the serve admission gate")
    p.add_argument("--platform", default=None,
                   help="force a jax platform (e.g. cpu) for the probe")
    p.add_argument("--replica", default=None,
                   help="fleet replica id to label the verdict with — "
                        "replicas sharing one HEALTH.jsonl gate admission "
                        "on their own lines only (serve/fleet.py)")
    p.add_argument("--log", default="HEALTH.jsonl",
                   help="rolling verdict log to append to ('' disables)")
    args = p.parse_args(argv)

    if args.child:
        rec = probe_backend(platform=args.platform, replica=args.replica)
        print(json.dumps(rec), flush=True)
        return 0 if rec["verdict"] == "healthy" else 1

    if args.in_process:
        rec = probe_backend(platform=args.platform, replica=args.replica)
    else:
        env = {"JAX_PLATFORMS": args.platform} if args.platform else None
        rec = probe_backend_supervised(patience_s=args.patience, env=env,
                                       attempts=args.attempts,
                                       replica=args.replica)
    rec["ts"] = round(time.time(), 3)
    print(json.dumps(rec), flush=True)
    append_health(rec, args.log or None)
    return {"healthy": 0, "sick": 1}.get(rec["verdict"], 2)


if __name__ == "__main__":
    sys.exit(main())
