"""Checkpoint / resume.

The reference has no checkpointing of any kind — simulation state dies with
the process (SURVEY.md §5).  Here the entire simulation is one pytree
(protocol state + future-inbox ring buffers) plus the tick counter, so a
checkpoint is a flat ``np.savez`` archive of the leaves with the config
embedded as JSON.  Because every random draw is a pure function of
``(seed, tick, channel)`` (utils/prng.py), resuming from a checkpoint
reproduces the uninterrupted run *bit-exactly* — tested in
tests/test_checkpoint.py.
"""

from __future__ import annotations

import dataclasses
import json
import os
import pathlib

import jax
import numpy as np

from blockchain_simulator_tpu.utils.config import FaultConfig, SimConfig


def config_to_json(cfg: SimConfig) -> str:
    return json.dumps(dataclasses.asdict(cfg))


def config_from_json(s: str) -> SimConfig:
    d = json.loads(s)
    d["faults"] = FaultConfig(**d["faults"])
    return SimConfig(**d)


def save_checkpoint(path, cfg: SimConfig, state, bufs, tick: int,
                    dyn_counts=None) -> None:
    """Write one checkpoint: config + tick + all state/buffer leaves.

    ``dyn_counts`` — the traced ``(n_crashed, n_byzantine)`` fault
    operands of a dynamic-fault-operand run (runner.run_dyn_checkpointed):
    stored alongside state/bufs so a resumed run re-derives the exact
    masks (models/base.dyn_fault_masks) the crashed run was tracing.
    ``None`` (the static path) writes no ``__dyn__`` entry — archives
    stay readable both ways."""
    arrays = {}
    for prefix, tree in (("s", state), ("b", bufs)):
        for i, leaf in enumerate(jax.tree.leaves(tree)):
            arrays[f"{prefix}{i}"] = np.asarray(leaf)
    if dyn_counts is not None:
        nc, nb = dyn_counts
        arrays["__dyn__"] = np.asarray([int(nc), int(nb)], dtype=np.int32)
    # content-first atomicity (the WAL/journal rule): write the archive to
    # a sibling tmp, fsync, then os.replace — a kill mid-save can never
    # leave a torn ckpt_*.npz for the resume glob to trip over (the tmp
    # name does not match the glob).  This is load-bearing for the sweep
    # supervisor's re-kill story (runner.run_dyn_checkpointed resume=True
    # trusts the newest archive).
    path = str(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            np.savez(
                f,
                __cfg__=np.frombuffer(config_to_json(cfg).encode(),
                                      dtype=np.uint8),
                __tick__=np.int64(tick),
                **arrays,
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load_checkpoint(path):
    """Read a checkpoint back: ``(cfg, state, bufs, tick)``.

    The pytree structure is rebuilt from the protocol's ``init`` (via
    ``eval_shape`` — no device work), then filled with the stored leaves.
    """
    from blockchain_simulator_tpu.models.base import get_protocol

    path = pathlib.Path(path)
    z = np.load(path)
    cfg = config_from_json(bytes(z["__cfg__"]).decode())
    tick = int(z["__tick__"])
    proto = get_protocol(cfg.protocol)
    s0, b0 = jax.eval_shape(
        lambda: proto.init(cfg, jax.random.key(0))
    )
    state = jax.tree.unflatten(
        jax.tree.structure(s0),
        [jax.numpy.asarray(z[f"s{i}"]) for i in range(len(jax.tree.leaves(s0)))],
    )
    bufs = jax.tree.unflatten(
        jax.tree.structure(b0),
        [jax.numpy.asarray(z[f"b{i}"]) for i in range(len(jax.tree.leaves(b0)))],
    )
    return cfg, state, bufs, tick


def load_dyn_counts(path):
    """The stored ``(n_crashed, n_byzantine)`` dynamic-fault operands of a
    checkpoint, or ``None`` for a static-path archive (pre-dyn
    checkpoints have no ``__dyn__`` entry — tolerated, not an error)."""
    z = np.load(pathlib.Path(path))
    if "__dyn__" not in z:
        return None
    d = np.asarray(z["__dyn__"])
    return int(d[0]), int(d[1])
