"""Forced device synchronization for trustworthy wall-clock timing.

On this environment's tunneled TPU backend (the "axon" PJRT plugin),
``jax.block_until_ready`` can return before the device has actually finished
executing: round-2 measurements showed a 100k-node simulation "completing" in
~4 ms of wall time while quadrupling the tick count barely moved the clock
(sub-microsecond per tick — physically impossible), and forcing a scalar
readback of the result put the true time at ~4.8 s.  Every timing path in
this package therefore goes through :func:`force_sync`, which transfers one
scalar derived from (every leaf of) the result to the host — a data
dependency no conforming runtime can satisfy before execution is complete.

This is strictly stronger than ``block_until_ready`` and costs one tiny
device-to-host transfer, which is noise at the timescales being measured.
See KNOWN_ISSUES.md for the full evidence trail.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def force_sync(tree):
    """Block until ``tree`` (any pytree of arrays) is fully materialized.

    Returns ``tree`` unchanged, so timing code can write
    ``result = force_sync(fn(args))``.

    One readback suffices even for a many-leaf result: all outputs of a jitted
    call come from one XLA execution, so any output buffer being transferable
    implies the whole execution retired.  (Round-3 measurement: each readback
    costs ~70 ms over the tunnel, so per-leaf sync would add ~1.2 s of
    constant overhead to every timing.)
    """
    jax.block_until_ready(tree)  # cheap first pass; correct on conforming backends
    for leaf in jax.tree_util.tree_leaves(tree):
        x = jnp.asarray(leaf)
        if x.size:
            float(jnp.ravel(x)[0].astype(jnp.float32))
            break
    return tree
