"""Typed simulation configuration.

The reference hard-codes every operating constant (see SURVEY.md §5 "Config"):
N=8 (blockchain-simulator.cc:67), 3 Mbps / 3 ms links (blockchain-simulator.cc:22-24),
port 7071, PBFT tx_size/tx_speed/timeout (pbft-node.cc:102-107), Raft election
window / heartbeat (raft-node.cc:69-72,80), Paxos proposer set {0,1,2}
(paxos-node.cc:136), per-protocol random send delays, stop thresholds 40/50
blocks.  Every one of those numbers is a field here, with the reference value
as the default.

Time is discretized into 1 ms ticks (fine enough to resolve the 0-6 ms /
0-50 ms delay distributions and the 50 ms timers of the reference).
All delay fields are expressed in ticks (= ms).
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class FaultConfig:
    """Fault-injection configuration (a capability the reference lacks entirely;
    its only fault-like mechanisms are PBFT's random view change, pbft-node.cc:401-403,
    and Raft's election timeout, raft-node.cc:114).

    All masks are derived deterministically from the seed at init time.
    """

    # Fraction of nodes that are crashed from t=0 (never send, never process).
    crash_frac: float = 0.0
    # Number of crashed nodes (overrides crash_frac when >= 0). Crashed nodes
    # are chosen as the *last* ids so proposers/leader-0 stay alive by default.
    n_crashed: int = -1
    # Per-message drop probability on every edge.
    drop_prob: float = 0.0
    # Number of Byzantine nodes (vote-flippers): their SUCCESS votes are
    # delivered as FAILED and vice versa. Chosen as the last ids.
    n_byzantine: int = 0
    # Active Byzantine attack (PBFT): forgers broadcast COMMIT votes for a
    # slot no honest leader ever proposed (the last slot index).  With the
    # reference's counting — no per-sender vote dedup, SURVEY.md quirk #2 —
    # each forger's vote counts ``byz_copies`` times, so f forgers muster
    # f*byz_copies forged votes; a ``quorum_rule="2f1"`` node deduplicates by
    # sender id, capping each forger at one counted vote.
    byz_forge: bool = False
    byz_copies: int = 3

    def resolved_n_crashed(self, n: int) -> int:
        if self.n_crashed >= 0:
            return min(self.n_crashed, n)
        return int(self.crash_frac * n)


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Full, hashable (static under jit) simulation configuration."""

    # --- core ---------------------------------------------------------------
    protocol: str = "pbft"  # runtime-selectable (the reference's compile-time
    # switch at network-helper.cc:17 becomes a flag; SURVEY.md §1)
    n: int = 8  # cluster size (blockchain-simulator.cc:67)
    sim_ms: int = 10_000  # app window 0-10 s (blockchain-simulator.cc:54-55)
    seed: int = 0

    # --- network model ------------------------------------------------------
    link_delay_ms: int = 3  # p2p channel Delay (blockchain-simulator.cc:24)
    link_rate_mbps: float = 3.0  # p2p channel DataRate (blockchain-simulator.cc:23)
    # If True (default — faithful to the reference's timing), add
    # ceil(bytes*8/rate) serialization time to block-carrying messages: the
    # reference's 50 KB PBFT blocks take ~136 ms on its 3 Mbps links
    # (blockchain-simulator.cc:22-24, pbft-node.cc:377-380) and its 20 KB
    # Raft proposals ~54 ms (raft-node.cc:409) — the dominant timing term of
    # the system being reproduced.  Simplification (documented divergence):
    # links are NOT queued — serialization is a constant per-message latency,
    # whereas ns-3 queues back-to-back packets per link.  At the reference
    # PBFT defaults this is a REAL divergence: a 50 KB block serializes
    # ~136 ms but blocks depart every 50 ms, so the upstream's per-link
    # queues grow ~86 ms per round and its time-to-finality drifts linearly
    # (quantified in tests/test_fidelity.py via queued_links below).  Set
    # False to model propagation + the explicit random scheduling delay only
    # (the round-blocked PBFT fast path requires this).
    model_serialization: bool = True
    # ns-3-exact queued transport: each directed link is a serial 3 Mbps
    # pipe — a packet transmits when the link is free, occupies it for its
    # serialization time, then propagates; small votes queue behind blocks
    # on the same link.  Modeled per-edge by the C++ engine
    # (engine.cpp:198-215, all protocols) and by the tensorized engines via
    # per-destination busy registers for the leader's block channel: pbft
    # routes queued blocks through per-destination FIFOs (models/pbft.py —
    # its backlog is unbounded), raft keeps them on rings widened by the
    # bounded (ser - hb) * rounds backlog and queues plain heartbeats behind
    # in-flight proposals (models/raft.py).  4-byte vote/control unicast
    # traffic keeps constant latency — a documented divergence: a sender's
    # own votes never queue behind its in-flight blocks, which moves no
    # milestone since thresholds never hinge on the one leader vote;
    # tests/test_fidelity.py pins both engines against each other.  Paxos
    # messages are all 3-4 bytes, so queued == constant-latency there
    # (accepted as a bit-exact no-op).  The mixed shard sim refuses the flag.
    queued_links: bool = False

    # --- topology -----------------------------------------------------------
    # The runtime topology axis (topo/): how the N nodes are wired.
    # "full"      — the reference's full mesh (blockchain-simulator.cc:34-51);
    #               "dense" is an accepted alias, normalized to "full" so the
    #               registry key / config hash is one spelling.
    # "gossip"    — random k-out digraph over which block/control messages
    #               FLOOD with a hop TTL (BASELINE config 3; the pre-topo/
    #               spelling "kregular" meant this relay mode).
    # "kregular"  — seeded circulant k-regular overlay with DIRECT
    #               neighbor-index delivery: per-tick messages are gathered
    #               through [N, k+1] in/out tables (topo/spec.py,
    #               ops/gatherdeliv.py) instead of dense N x N edge tensors —
    #               O(N*k) memory, and at degree k = N-1 bit-equal to the
    #               full mesh (the sorted full-overlay table is the identity
    #               permutation, so the same threefry draws are consumed).
    # "committee" — two-level hierarchy: the protocol runs INSIDE each of
    #               ``committees`` equal committees (lax.map over the stacked
    #               committee axis, O(N * n/committees) memory), then an
    #               outer aggregate step over committee representatives
    #               (topo/committee.py).
    topology: str = "full"
    degree: int = 16  # out-degree: gossip flood fan-out / kregular overlay k
    gossip_hops: int = 8  # flood TTL; must cover the graph diameter (~log_deg N)
    committees: int = 4  # committee count when topology == "committee"
    topo_seed: int = 0  # kregular overlay-builder seed — deliberately separate
    # from the run seed, so fault/seed sweeps over one overlay share ONE
    # compiled program (the overlay is topology *structure*, not randomness)

    # --- execution backend --------------------------------------------------
    # "edge": exact per-edge delay sampling (O(N^2) work per active tick).
    # "stat": statistically-exact aggregated delivery — per-receiver bucket
    #         counts drawn from binomial/multinomial chains (O(N·B)); valid for
    #         full-mesh count-consumed channels; the 100k-node path.
    delivery: str = "edge"
    # Binomial sampler for "stat" delivery bucket counts (ops/delay.py):
    # "exact"  — BTRS rejection sampling (jax.random.binomial).
    # "normal" — Gaussian approximation: ~6x fewer elementwise passes; counts
    #            still sum exactly (every message delivered exactly once),
    #            only the spread across delay buckets is approximate with
    #            relative error O(1/sqrt(count)).
    # "auto"   — "normal" when n >= 4096 (where the error is negligible and
    #            the tick loop is sampler-bound), else "exact".
    stat_sampler: str = "auto"
    # Per-edge integer delay sampler for the *edge* paths (ops/delay.py
    # sample_edge_delays — dense delivery, gossip forwarding):
    # "threefry" — jax.random.randint on the caller's threefry key: the
    #              historical stream every bit-pinned edge-path test rides.
    # "rbg"      — the same exact-uniform integer map fed by XLA's
    #              RngBitGenerator (the ops/delay._fast_normal trick): far
    #              cheaper bit generation on XLA:CPU, pure integer ops —
    #              bit-stable across unbatched compilations (jit, lax.map
    #              lanes, mesh bodies), though NOT under vmap batching
    #              (RngBitGenerator is not batch-invariant; same caveat
    #              class as the "normal" stat mode — see ops/delay.py).
    #              Power-of-two spans bit-slice each word into two
    #              exactly-uniform 16-bit draws.  A DIFFERENT stream than
    #              "threefry" (same distribution), so flipping the toggle
    #              moves seed-pinned trajectories.
    # "auto"     — "rbg" when n >= 4096 (edge tensors are O(N^2): the
    #              sampler dominates the tick), else "threefry".
    edge_sampler: str = "threefry"
    # Stepping granularity of the simulation loop:
    # "tick"  — the general engine: one scan step per 1 ms tick (always valid).
    # "round" — PBFT fast path: one scan step per block interval
    #           (models/pbft_round.py); requires full-mesh stat delivery, no
    #           byz_forge/queued links, drops only with view changes off (and
    #           the exact vote table), and the message wave — including the
    #           constant serialization offset when modeled — closing inside
    #           one block interval (pbft_round.eligible).
    # "auto"  — "round" when eligible and n >= 4096 (where the tick engine's
    #           per-tick ring traffic dominates), else "tick".
    schedule: str = "auto"
    # "reference": replicate the reference's observable quirks (N/2 thresholds,
    #              reset-on-threshold vote counters, never-re-armed Raft
    #              election timer, N-2 Paxos reply counting).
    # "clean":     documented fixes (latched commits, re-armed timers, N-1
    #              counting, highest-command adoption).
    fidelity: str = "clean"
    # Quorum rule for PBFT/Raft vote thresholds (SURVEY.md quirk #2; BASELINE
    # config 4 sweeps f up to n/3, where the reference's simple-majority rule
    # is not Byzantine-safe):
    # "n2":  the reference's thresholds — PBFT prepare >= N/2, commit > N/2
    #        (pbft-node.cc:231,248), Raft votes+self > N/2 (raft-node.cc:209)
    #        — and no per-sender vote deduplication.
    # "2f1": Byzantine-safe 2f+1 quorum with f = (n-1)//3, votes deduplicated
    #        per sender: any two quorums intersect in >= f+1 nodes, hence in
    #        an honest node, so no two honest nodes finalize different blocks
    #        and forged vote floods cannot reach quorum.
    quorum_rule: str = "n2"

    # --- PBFT (pbft-node.cc) -------------------------------------------------
    pbft_block_interval_ms: int = 50  # timeout=0.05 (pbft-node.cc:106)
    pbft_max_rounds: int = 40  # stop at n_round==40 (pbft-node.cc:407)
    pbft_tx_size: int = 1000  # 1 KB per tx (pbft-node.cc:104)
    pbft_tx_speed: int = 1000  # 1000 tx/s (pbft-node.cc:105)
    pbft_delay_lo: int = 3  # random send delay U{3,4,5} ms
    pbft_delay_hi: int = 6  # (pbft-node.cc:66-69), exclusive
    pbft_view_change_num: int = 1  # P(view change) = num/den per leader round
    pbft_view_change_den: int = 100  # (rand()%100==5, pbft-node.cc:401)
    pbft_max_slots: int = 64  # vote-table slots (tx[1000], pbft-node.h:50; 40
    # rounds only ever touch slots 0..39)
    pbft_window: int = 0  # live vote-state window W: per-node vote counters
    # live in [N, W] keyed by slot % W and are evicted on re-tenancy, capping
    # per-tick memory traffic at O(N·W) instead of O(N·S) (the 100k-node
    # scaling lever).  0 (default) = W = pbft_max_slots = exact full-table
    # mode.  A window is safe when W * block_interval far exceeds the message
    # horizon (validated in pbft.init); per-slot metrics are exact in both
    # modes (they fold into [S] accumulators either way).

    # --- Raft (raft-node.cc) -------------------------------------------------
    raft_heartbeat_ms: int = 50  # heartbeat_timeout=0.05 (raft-node.cc:80)
    raft_election_lo_ms: int = 150  # election timeout U[150,300) ms
    raft_election_hi_ms: int = 300  # (raft-node.cc:69-72)
    raft_delay_lo: int = 0  # random send delay U{0,1,2} ms
    raft_delay_hi: int = 3  # (raft-node.cc:63-66), exclusive
    raft_proposal_delay_ms: int = 1000  # proposals start 1 s after election
    # (raft-node.cc:216)
    raft_max_blocks: int = 50  # stop at blockNum>=50 (raft-node.cc:248)
    raft_max_rounds: int = 50  # stop proposals at round==50 (raft-node.cc:361)
    raft_tx_size: int = 200  # 200 B per tx (raft-node.cc:23)
    raft_tx_speed: int = 2000  # 2000 tx/s (raft-node.cc:24)

    # --- Paxos (paxos-node.cc) -----------------------------------------------
    paxos_delay_lo: int = 0  # random send delay U[0,50) ms
    paxos_delay_hi: int = 50  # (paxos-node.cc:397-400), exclusive
    paxos_n_proposers: int = 3  # nodes 0,1,2 propose at t=0 (paxos-node.cc:136)
    paxos_max_ticket: int = 120  # ticket values are single bytes in the
    # reference codec ('0'+t, paxos-node.cc:49-51); cap retries
    paxos_retry_timeout_ms: int = 250  # clean-fidelity failure detection: a
    # reply window unresolved after this long is abandoned and retried with a
    # higher ticket (~2x the 106 ms max round trip).  The reference has no
    # timeout — a lost reply wedges its proposer forever; reference fidelity
    # reproduces that stall.
    # CLIENT_PROPOSE external-client hook (paxos-node.cc:357-361): proposer
    # lane `paxos_client_node` (must be < paxos_n_proposers; -1 = none) does
    # not fire requireTicket at t=0 — a simulated client triggers it at
    # `paxos_client_ms` instead (mid-run injection; both engines).
    paxos_client_node: int = -1
    paxos_client_ms: int = 0

    # --- echo-back fidelity (quirk #1) ---------------------------------------
    # Reflect every received packet to its sender once (never re-reflect):
    # the bounded variant of the reference's unconditional echo
    # (pbft-node.cc:175, raft-node.cc:136, paxos-node.cc:158), which would
    # ping-pong forever.  Modeled by the C++ engine only — the tensorized
    # backends design echo away (models/pbft.py docstring) and refuse it.
    echo_back: bool = False

    # --- mixed-protocol shard sim (BASELINE config 5) ------------------------
    mixed_shards: int = 16  # number of raft shards; shard size = n // shards;
    # cross-shard PBFT runs over the shard representatives

    # --- faults --------------------------------------------------------------
    faults: FaultConfig = dataclasses.field(default_factory=FaultConfig)

    # --- sharding ------------------------------------------------------------
    # Name of the mesh axis over which node state is sharded (None = unsharded).
    mesh_axis: Optional[str] = None

    # ------------------------------------------------------------------------
    def __post_init__(self):
        if self.topology == "dense":  # alias: one spelling in the registry key
            object.__setattr__(self, "topology", "full")
        if self.protocol not in ("pbft", "raft", "paxos", "mixed"):
            raise ValueError(f"unknown protocol {self.protocol!r}")
        if self.delivery not in ("edge", "stat"):
            raise ValueError(f"unknown delivery mode {self.delivery!r}")
        if self.fidelity not in ("reference", "clean"):
            raise ValueError(f"unknown fidelity {self.fidelity!r}")
        if self.stat_sampler not in ("exact", "normal", "auto"):
            raise ValueError(f"unknown stat_sampler {self.stat_sampler!r}")
        if self.edge_sampler not in ("threefry", "rbg", "auto"):
            raise ValueError(f"unknown edge_sampler {self.edge_sampler!r}")
        if self.schedule not in ("tick", "round", "auto"):
            raise ValueError(f"unknown schedule {self.schedule!r}")
        if self.quorum_rule not in ("n2", "2f1"):
            raise ValueError(f"unknown quorum_rule {self.quorum_rule!r}")
        if self.quorum_rule == "2f1" and self.fidelity != "clean":
            raise ValueError(
                "quorum_rule='2f1' requires fidelity='clean': vote dedup "
                "relies on the clean latches (each node votes once per slot); "
                "the reference's reset-on-threshold counters re-count"
            )
        if self.faults.byz_forge:
            if self.protocol != "pbft":
                raise ValueError(
                    "byz_forge (forged COMMIT-vote flooding) is a PBFT attack; "
                    f"protocol {self.protocol!r} does not implement it"
                )
            if self.pbft_max_rounds >= self.pbft_max_slots:
                raise ValueError(
                    "byz_forge targets the last vote-table slot; "
                    "pbft_max_rounds must be < pbft_max_slots so no honest "
                    "leader ever proposes it"
                )
        if self.topology not in ("full", "gossip", "kregular", "committee"):
            raise ValueError(
                f"unknown topology {self.topology!r} (valid: full/dense, "
                "gossip, kregular, committee)"
            )
        if self.protocol == "paxos" and not 1 <= self.paxos_n_proposers <= self.n:
            raise ValueError(
                f"paxos_n_proposers={self.paxos_n_proposers} must be in [1, n={self.n}]"
            )
        if self.paxos_client_node >= 0:
            if self.protocol != "paxos":
                raise ValueError("paxos_client_node requires protocol='paxos'")
            if self.paxos_client_node >= self.paxos_n_proposers:
                raise ValueError(
                    f"paxos_client_node={self.paxos_client_node} must be a "
                    f"proposer lane (< paxos_n_proposers="
                    f"{self.paxos_n_proposers}): lanes are the static "
                    "proposer channel layout in both engines"
                )
            if not 0 <= self.paxos_client_ms < self.sim_ms:
                raise ValueError(
                    f"paxos_client_ms={self.paxos_client_ms} outside the "
                    f"simulation window [0, {self.sim_ms})"
                )
        if self.topology == "gossip":
            if self.protocol not in ("paxos", "pbft", "raft"):
                raise NotImplementedError(
                    "gossip topology is implemented for paxos (BASELINE "
                    "config 3: request floods), pbft (block-dissemination "
                    "floods) and raft (vote/heartbeat floods with direct "
                    "unicast replies); the mixed shard sim keeps full-mesh "
                    "raft inside its (small) shards by design"
                )
            if self.fidelity != "clean":
                raise ValueError(
                    "reference fidelity is defined on the full mesh only "
                    "(the reference has no gossip relay)"
                )
            if self.protocol == "raft":
                if self.delivery != "stat":
                    raise ValueError(
                        "raft gossip rides the stat-mode value channels; "
                        "use delivery='stat' with topology='gossip'"
                    )
                # flood values encode (tick+1)*(n+1) + id, TTL-scaled by
                # gossip_hops+1 — must fit int32
                if (self.sim_ms + 1) * (self.n + 1) * (self.gossip_hops + 1) >= 2**31:
                    raise ValueError(
                        "raft gossip encoding (sim_ms+1)*(n+1)*(gossip_hops+1) "
                        "overflows int32 at this size; reduce sim_ms, n, or "
                        "gossip_hops"
                    )
        if self.topology == "kregular":
            if self.protocol not in ("paxos", "pbft", "raft"):
                raise NotImplementedError(
                    "the kregular gather overlay is implemented for pbft, "
                    "raft and paxos; the mixed shard sim keeps full-mesh "
                    "raft inside its (small) shards by design"
                )
            if self.fidelity != "clean":
                raise ValueError(
                    "reference fidelity is defined on the full mesh only; "
                    "the kregular overlay requires fidelity='clean' (e.g. "
                    "the reference's N-2 paxos reply window never closes "
                    "when a proposer reaches only k neighbors)"
                )
            if not 1 <= self.degree <= self.n - 1:
                raise ValueError(
                    f"kregular degree={self.degree} must be in [1, n-1="
                    f"{self.n - 1}] (degree n-1 IS the full mesh)"
                )
        if self.topology == "committee":
            if self.protocol not in ("paxos", "pbft", "raft"):
                raise NotImplementedError(
                    "committee topology runs the flat protocol per "
                    "committee; the mixed shard sim is already a two-level "
                    "hierarchy of its own"
                )
            if self.committees < 1:
                raise ValueError(f"committees={self.committees} must be >= 1")
            if self.n % self.committees != 0:
                raise ValueError(
                    f"n={self.n} must divide evenly into "
                    f"committees={self.committees} equal committees"
                )
            m = self.n // self.committees
            if m < 2:
                raise ValueError(
                    f"committee size n/committees = {m} must be >= 2 "
                    "(a 1-node committee has no quorum to run)"
                )
            if self.protocol == "paxos" and self.paxos_n_proposers > m:
                raise ValueError(
                    f"paxos_n_proposers={self.paxos_n_proposers} exceeds "
                    f"the committee size {m}: proposers are per-committee "
                    "lanes (nodes 0..P-1 of each committee)"
                )
            if self.mesh_axis is not None:
                raise ValueError(
                    "committee topology is unsharded in this version: the "
                    "committee axis is a lax.map, not a mesh axis "
                    "(shard the SWEEP axis instead, parallel/partition.py)"
                )

    # --- derived quantities (plain python; all static under jit) ------------
    @property
    def eff_stat_sampler(self) -> str:
        """Resolved stat_sampler ('auto' -> by cluster size)."""
        if self.stat_sampler == "auto":
            return "normal" if self.n >= 4096 else "exact"
        return self.stat_sampler

    @property
    def eff_edge_sampler(self) -> str:
        """Resolved edge_sampler ('auto' -> by cluster size)."""
        if self.edge_sampler == "auto":
            return "rbg" if self.n >= 4096 else "threefry"
        return self.edge_sampler

    @property
    def ticks(self) -> int:
        """Total simulation ticks (1 tick = 1 ms)."""
        return self.sim_ms

    def one_way_range(self) -> tuple[int, int]:
        """[lo, hi) one-way message delay in ticks: link propagation + the
        protocol's explicit random scheduling delay (SURVEY.md §3.5 notes the
        double delay: Simulator::Schedule(getRandomDelay) + channel Delay)."""
        if self.protocol == "pbft":
            lo, hi = self.pbft_delay_lo, self.pbft_delay_hi
        elif self.protocol == "raft":
            lo, hi = self.raft_delay_lo, self.raft_delay_hi
        else:
            lo, hi = self.paxos_delay_lo, self.paxos_delay_hi
        d = self.link_delay_ms
        lo, hi = lo + d, hi + d
        if lo < 1:  # a message can never arrive in the tick it was sent
            lo, hi = 1, max(hi, 2)
        if hi <= lo:  # degenerate range (e.g. delay_lo == delay_hi): one bucket
            hi = lo + 1
        return lo, hi

    def roundtrip_range(self) -> tuple[int, int]:
        """[lo, hi) request+reply delay (reply is processed instantly at the
        peer and travels back with an independent random delay)."""
        lo, hi = self.one_way_range()
        return 2 * lo, 2 * hi - 1

    @property
    def ring_depth(self) -> int:
        """Ring-buffer depth: must exceed the maximum scheduling horizon.
        With serialization modeled, the worst case is a round trip whose
        request leg carries a block-sized message (Raft proposal acks land at
        rt_hi - 1 + ser; 20 KB at 3 Mbps ≈ 54 ticks)."""
        _, rt_hi = self.roundtrip_range()
        if self.protocol == "pbft":
            # queued-link mode routes blocks through per-destination serial-
            # pipe FIFOs (models/pbft.py PbftState registers) — their delivery
            # offsets are unbounded and never touch the ring, which then only
            # carries 4-byte vote/control traffic
            biggest = 0 if self.queued_links else self.pbft_block_bytes
        elif self.protocol == "raft":
            biggest = self.raft_block_bytes
            if self.queued_links:
                # queued raft deliveries stay on the rings: the serial-pipe
                # backlog is bounded — a proposal serializes ser ticks but
                # departs every heartbeat, so after R proposal rounds the
                # per-link queue holds at most (ser - hb) * R extra ticks
                # (models/raft.py link_busy; the backlog resets with the
                # leader's links on a leadership change)
                ser = self.serialization_ticks(biggest)
                extra = max(0, ser - self.raft_heartbeat_ms) * self.raft_max_rounds
                return rt_hi + ser + 1 + extra
        else:
            biggest = 4
        return rt_hi + self.serialization_ticks(biggest) + 1

    @property
    def quorum(self) -> int:
        """The reference's majority threshold N/2 (pbft-node.cc:231,248;
        raft-node.cc:209; paxos-node.cc:259) — integer division, *not* 2f+1."""
        return self.n // 2

    @property
    def byz_f(self) -> int:
        """Max tolerable Byzantine count under the 2f+1 rule: f = (n-1)//3."""
        return (self.n - 1) // 3

    @property
    def pbft_prepare_need(self) -> int:
        """Votes needed to cross the prepare phase (>= semantics).
        n2: prepare_vote >= N/2 (pbft-node.cc:231)."""
        if self.quorum_rule == "2f1":
            return 2 * self.byz_f + 1
        return self.quorum

    @property
    def pbft_commit_need(self) -> int:
        """Votes needed to finalize (>= semantics).
        n2: commit_vote > N/2 (pbft-node.cc:248) ⇔ >= N/2 + 1."""
        if self.quorum_rule == "2f1":
            return 2 * self.byz_f + 1
        return self.quorum + 1

    @property
    def majority_need(self) -> int:
        """Raft votes (including self) needed to win / commit (>= semantics).
        n2: votes + self > N/2 (raft-node.cc:209)."""
        if self.quorum_rule == "2f1":
            return 2 * self.byz_f + 1
        return self.quorum + 1

    @property
    def raft_lose_need(self) -> int:
        """FAILED votes at which a candidate abandons the election
        (>= semantics).  n2: vote_failed >= N/2 (raft-node.cc:225); 2f1: the
        election is unwinnable once n - vote_failed < majority_need."""
        if self.quorum_rule == "2f1":
            return self.n - self.majority_need + 1
        return self.quorum

    @property
    def pbft_block_txs(self) -> int:
        # num = tx_speed / (1000/(timeout*1000))  (pbft-node.cc:377)
        return self.pbft_tx_speed * self.pbft_block_interval_ms // 1000

    @property
    def pbft_block_bytes(self) -> int:
        return self.pbft_block_txs * self.pbft_tx_size  # 50 KB

    @property
    def raft_block_txs(self) -> int:
        # num = tx_speed / (1000/(heartbeat_timeout*1000)) (raft-node.cc:409)
        return self.raft_tx_speed * self.raft_heartbeat_ms // 1000

    @property
    def raft_block_bytes(self) -> int:
        return self.raft_block_txs * self.raft_tx_size  # 20 KB

    def serialization_ticks(self, nbytes: int) -> int:
        if not self.model_serialization:
            return 0
        return int(nbytes * 8 / (self.link_rate_mbps * 1e6) * 1000 + 0.999)

    def with_(self, **kw) -> "SimConfig":
        return dataclasses.replace(self, **kw)
