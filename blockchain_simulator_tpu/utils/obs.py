"""Run manifests: one schema-versioned provenance record per JSON output line.

Every entrypoint that prints a result line (cli, bench.py, parallel/sweep.py,
tools/run_config*.py) routes it through :func:`finalize`, which attaches a
``manifest`` sub-record — config hash, jax/jaxlib versions, backend + device
count, the compile-vs-execution wall split, and rounds/s computed uniformly —
and appends the finalized record to an optional ``runs.jsonl``
(``BLOCKSIM_RUNS_JSONL``).  ``tools/bench_compare.py`` reads that file (plus
the committed ``BENCH_*.json``) into a machine-readable perf trajectory.

Design constraints this module must respect:

- **Never initialize a backend.**  The bench parent process deliberately
  avoids importing jax (a sick TPU tunnel turns backend init into a
  multi-minute hang, KNOWN_ISSUES.md #3), and the cli's C++-engine path never
  needs it.  Backend/device fields are therefore filled only when ``jax`` is
  *already imported* (in which case the caller has initialized the backend
  itself) or when passed explicitly; package versions come from
  ``importlib.metadata``, which imports nothing.
- **Never mutate a caller's metrics dict into inequality.**  Library code
  (sweeps, runner) returns metrics dicts that tests compare bit-for-bit
  against other runs; only the *printing* layer attaches manifests.
  :func:`record_run` exists for libraries: it appends a finalized COPY to
  ``runs.jsonl`` (when enabled) and leaves the caller's dict untouched.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import sys
import time

OBS_SCHEMA = 1

# Environment switch: when set, every finalized record is appended (one JSON
# line each) to this path.  Unset = no file I/O (the default for tests).
RUNS_ENV = "BLOCKSIM_RUNS_JSONL"

# Size cap for every rolling JSONL this writer appends to (runs.jsonl,
# HEALTH.jsonl via utils/health.py, telemetry span logs): when the file
# exceeds the cap it rotates to ``<path>.1`` (one generation kept) before
# the append, so multi-drill processes never grow a log without bound.
# The default is far above any single drill's output; set the env to a
# small value to exercise rotation (tests do).  0 disables rotation.
LOG_MAX_ENV = "BLOCKSIM_LOG_MAX_BYTES"
LOG_MAX_BYTES_DEFAULT = 64 * 1024 * 1024


def _dist_version(name: str) -> str | None:
    """Installed package version without importing the package."""
    try:
        import importlib.metadata

        return importlib.metadata.version(name)
    except Exception:
        return None


def config_hash(cfg) -> str:
    """Stable 16-hex-digit digest of a SimConfig (or any dataclass): the
    join key between a result line, a trace file, and a runs.jsonl record."""
    if dataclasses.is_dataclass(cfg):
        d = dataclasses.asdict(cfg)
    else:
        d = dict(cfg)
    blob = json.dumps(d, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def manifest(cfg=None, backend=None, device_count=None) -> dict:
    """The schema-versioned provenance record.

    ``backend``/``device_count`` are taken from the arguments when given
    (e.g. bench.py's parent passes the child's probed backend through);
    otherwise they are read from jax ONLY if jax is already imported — this
    function never triggers a backend init of its own.
    """
    rec: dict = {
        "obs_schema": OBS_SCHEMA,
        "ts": round(time.time(), 3),
        "jax": _dist_version("jax"),
        "jaxlib": _dist_version("jaxlib"),
    }
    if cfg is not None:
        rec["config_hash"] = config_hash(cfg)
        rec["protocol"] = getattr(cfg, "protocol", None)
        rec["n"] = getattr(cfg, "n", None)
    if backend is None and "jax" in sys.modules:
        jax = sys.modules["jax"]
        try:
            # only read the backend if one is ALREADY initialized: merely
            # importing the package pulls jax in (e.g. the cli's C++-engine
            # path), and default_backend() would then trigger a backend init
            # that can hang for ~25 min on a wedged tunnel (KNOWN_ISSUES #3)
            from jax._src import xla_bridge

            if getattr(xla_bridge, "_backends", None):
                # guarded: only reached when a backend ALREADY exists, so
                # neither call below can trigger an init of its own
                backend = jax.default_backend()  # jaxlint: disable=module-scope-backend-touch
                device_count = len(jax.devices())  # jaxlint: disable=module-scope-backend-touch
        except Exception:  # backend broken: provenance, never a failure mode
            pass
    if backend is not None:
        rec["backend"] = backend
    if device_count is not None:
        rec["device_count"] = device_count
    try:
        # executable-registry provenance (utils/aotcache.py): hit/miss
        # counters, the last registry key touched, and the persistent cache
        # dir (null when disabled).  aotcache never imports jax at module
        # scope and .manifest() only reads counters, so this is safe from
        # the bench parent's no-jax path too.
        from blockchain_simulator_tpu.utils import aotcache

        rec["cache"] = aotcache.registry.manifest()
    except Exception:  # provenance, never a failure mode
        pass
    try:
        # telemetry provenance (utils/telemetry.py): compact counter
        # totals + spans recorded, attached only once the process has
        # actually counted something — a bare sim run's manifest stays
        # the size it always was.  telemetry is pure-stdlib host code
        # (no jax), so this is safe from the bench parent's no-jax path.
        from blockchain_simulator_tpu.utils import telemetry

        tel = telemetry.metrics.manifest()
        if tel.get("counters"):
            rec["telemetry"] = tel
    except Exception:  # provenance, never a failure mode
        pass
    return rec


def canonical_json(rec) -> str:
    """THE canonical JSON encoding shared by every content-addressed
    surface (sweep-journal chunk keys and row checksums,
    parallel/journal.py): sorted keys, compact separators, no default
    coercion — a value json can't encode should fail loudly here, not
    checksum differently on the read side after a round trip."""
    return json.dumps(rec, sort_keys=True, separators=(",", ":"))


def percentile(xs, q: float) -> float:
    """Nearest-rank percentile on a sorted copy — THE percentile every
    latency surface shares (serve self-test, tools/serve_bench.py), so the
    gated ``*_p99_ms`` trajectories are computed one way.  No numpy: the
    callers include daemon control paths that must not touch a backend."""
    xs = sorted(xs)
    if not xs:
        return 0.0
    idx = min(len(xs) - 1, max(0, int(round(q / 100.0 * (len(xs) - 1)))))
    return xs[idx]


def rounds_per_s(rounds, run_s) -> float | None:
    """THE uniform throughput computation: completed consensus rounds over
    the measured execution-only wall (never the compile-inclusive first
    run)."""
    if rounds is None or not run_s or run_s <= 0:
        return None
    return round(rounds / run_s, 2)


def timed_run(sim, key, measure_key=None):
    """Compile-vs-execution wall split via force_sync staging.

    Runs ``sim`` twice through ``utils/sync.force_sync`` (the only sync this
    env's tunnel honors, KNOWN_ISSUES.md #1): ``sim(key)`` pays compile +
    warmup, then ``sim(measure_key or key)`` measures execution only (the
    artifact scripts warm on one seed and report another).  Returns
    ``(final, compile_plus_first_run_s, run_s)``.
    """
    from blockchain_simulator_tpu.utils.sync import force_sync

    t0 = time.perf_counter()
    force_sync(sim(key))
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    final = force_sync(sim(key if measure_key is None else measure_key))
    run_s = time.perf_counter() - t0
    return final, compile_s, run_s


def _read_jsonl_one(path: str) -> list[dict]:
    out: list[dict] = []
    try:
        f = open(path)
    except OSError:
        return out
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


def read_jsonl(path: str) -> list[dict]:
    """Every parseable dict record of a rolling JSONL log, in order — the
    one tolerant reader the rolling logs share (runs.jsonl access-log
    checks in chaos/invariants.py, health verdicts, bench_compare's
    trajectory load).  Torn lines (a crash or a concurrent append
    mid-write) and non-dict records are skipped; a missing file reads as
    empty — log readers never raise.

    The retained rotation generation (``<path>.1``, the writer's
    :func:`rotate_if_over`) is read FIRST so a log that rotated mid-drill
    still reads as one continuous history — without this, a rotation
    would silently sever bench_compare's regression baselines and the
    invariant checkers' access-log coverage."""
    return _read_jsonl_one(path + ".1") + _read_jsonl_one(path)


# rotate_if_over's per-path stat is amortized: the size check runs on the
# first append to a path and then every _ROTATE_EVERY appends — at 64 MiB
# default cap, a between-checks overshoot of a few records is noise, and
# the serving hot path (several span lines per answered request) stops
# paying a stat syscall per line.
_ROTATE_EVERY = 16
_rotate_counts: dict[str, int] = {}


def rotate_if_over(path: str, max_bytes: int | None = None) -> bool:
    """Rotate ``path`` to ``path + ".1"`` when it exceeds the size cap
    (``$BLOCKSIM_LOG_MAX_BYTES``, default 64 MiB; 0 disables).  One
    rotated generation is kept — these are rolling observability logs,
    and every reader (:func:`read_jsonl`, health.latest_verdict, the
    invariant checkers) is already tolerant of a log that begins
    mid-history.  Returns True when a rotation happened; failures are
    swallowed like every other write in this module."""
    if max_bytes is None:
        try:
            max_bytes = int(os.environ.get(LOG_MAX_ENV,
                                           LOG_MAX_BYTES_DEFAULT))
        except ValueError:
            max_bytes = LOG_MAX_BYTES_DEFAULT
    if max_bytes <= 0:
        return False
    try:
        if os.path.getsize(path) <= max_bytes:
            return False
        os.replace(path, path + ".1")
        return True
    except OSError:
        return False


def append_jsonl(record: dict, path: str | None = None) -> None:
    """Append one JSON line; path defaults to $BLOCKSIM_RUNS_JSONL (no-op
    when neither is set).  The shared rolling-log writer — runs.jsonl,
    HEALTH.jsonl and the telemetry span log all come through here — so
    the size-capped rotation (:func:`rotate_if_over`) bounds all of them
    in one place.  Append failures are swallowed: observability must
    never take down the run it observes."""
    path = path or os.environ.get(RUNS_ENV)
    if not path:
        return
    n = _rotate_counts.get(path, 0)
    if n % _ROTATE_EVERY == 0:
        rotate_if_over(path)
    _rotate_counts[path] = n + 1
    try:
        with open(path, "a") as f:
            f.write(json.dumps(record) + "\n")
    except OSError:
        pass


def finalize(
    record: dict,
    cfg=None,
    compile_s=None,
    run_s=None,
    rounds=None,
    runs_path: str | None = None,
    append: bool = True,
) -> dict:
    """Attach the manifest to ``record`` and (``append=True``) append it to
    the optional runs.jsonl.  Idempotent: a record that already carries a
    manifest is returned untouched and NOT re-appended.  Pass
    ``append=False`` when a library layer (sweep's ``record_run``) already
    logged the run — the printed line still gets its manifest without the
    rolling log double-counting it.  Returns ``record`` so call sites stay
    one-line: ``print(json.dumps(obs.finalize(m, cfg)))``."""
    if "manifest" in record:
        return record
    record["manifest"] = manifest(
        cfg,
        backend=record.get("backend"),
        device_count=record.get("devices"),
    )
    if compile_s is not None:
        record["manifest"]["compile_plus_first_run_s"] = round(compile_s, 3)
    if run_s is not None:
        record["manifest"]["run_s"] = round(run_s, 3)
        rps = rounds_per_s(rounds, run_s)
        if rps is not None:
            record["manifest"]["rounds_per_s"] = rps
    if append:
        append_jsonl(record, runs_path)
    return record


def record_run(metrics: dict, cfg=None, **kw) -> None:
    """Library-side hook: append a finalized COPY of ``metrics`` to the
    optional runs.jsonl without touching the caller's dict (sweep rows are
    compared bit-for-bit against single runs in tests)."""
    if not (kw.get("runs_path") or os.environ.get(RUNS_ENV)):
        return
    finalize(dict(metrics), cfg, **kw)
