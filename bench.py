"""Headline benchmark: PBFT consensus rounds per second at scale.

North star (BASELINE.json): simulate 100k-node PBFT to finality at >= 1000
consensus rounds/sec.  The reference (ns-3, one CPU thread, 8 nodes) pushes
every one of the ~3N^2 per-round messages through a serial event queue
(SURVEY.md §3.2); here a round is a handful of O(N) tensor ops under one
jitted lax.scan, with count-consumed channels delivered via statistically
exact multinomial aggregation (O(N·B) instead of O(N^2)).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is value / 1000 rounds/sec (the BASELINE.json target at N=100k).
"""

from __future__ import annotations

import json
import time

import jax

from blockchain_simulator_tpu.models.base import get_protocol
from blockchain_simulator_tpu.runner import make_sim_fn
from blockchain_simulator_tpu.utils.config import SimConfig

N_NODES = 100_000
ROUNDS = 40
BASELINE_ROUNDS_PER_SEC = 1000.0


def main():
    cfg = SimConfig(
        protocol="pbft",
        n=N_NODES,
        # 40 rounds at 50 ms plus the commit tail — no idle coda
        sim_ms=ROUNDS * 50 + 100,
        pbft_max_rounds=ROUNDS,
        pbft_max_slots=48,
        delivery="stat",
    )
    sim = make_sim_fn(cfg)
    key = jax.random.key(0)
    final = jax.block_until_ready(sim(key))  # compile + warm
    t0 = time.perf_counter()
    final = jax.block_until_ready(sim(jax.random.key(1)))
    wall = time.perf_counter() - t0
    m = get_protocol("pbft").metrics(cfg, final)
    rounds_done = m["blocks_final_all_nodes"]
    value = rounds_done / wall
    print(
        json.dumps(
            {
                "metric": f"pbft_{N_NODES // 1000}k_consensus_rounds_per_sec",
                "value": round(value, 2),
                "unit": "rounds/s",
                "vs_baseline": round(value / BASELINE_ROUNDS_PER_SEC, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
