"""Headline benchmark: PBFT consensus rounds per second at scale.

North star (BASELINE.json): simulate 100k-node PBFT to finality at >= 1000
consensus rounds/sec.  The reference (ns-3, one CPU thread, 8 nodes) pushes
every one of the ~3N^2 per-round messages through a serial event queue
(SURVEY.md §3.2); here a whole 50 ms consensus round is a handful of O(N)
tensor ops (the round-blocked fast path, models/pbft_round.py) under one
jitted lax.scan.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is value / 1000 rounds/sec (the BASELINE.json target at N=100k).

Robustness contract (VERDICT r1 weak-#1, refined r3->r4): this file must
ALWAYS emit exactly one parseable JSON line on stdout, AND must never wedge
the environment's single-client TPU tunnel.  KNOWN_ISSUES.md #3: a TPU client
hard-killed mid-compile wedged the tunnel for hours, dooming every later
attempt in the round — which is exactly what r3's batch-ladder design did to
itself (each timed-out rung was SIGKILLed, then rungs 2, 3 and the CPU
fallback's plugin init all hung).  The r4 design therefore:

- runs ONE child process for the TPU measurement (one tunnel client, ever);
- the child imposes its OWN deadline (time checks between stages — no attempt
  starts unless its projected cost fits) and exits cleanly, so the parent
  never has to kill it in the normal path;
- the child ladders ROUNDS (small first: compile + a 200-round measure lands
  a real TPU number inside ~2 min; 2000 rounds only runs if the measured
  per-round cost says it fits the remaining budget) instead of laddering
  batch — batch>=2 is the known device-faulter (KNOWN_ISSUES.md #2);
- the parent's subprocess timeout is a last resort set WAY above the child's
  own deadline, and escalates SIGTERM -> wait -> SIGKILL.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

N_NODES = int(os.environ.get("BENCH_N", "100000"))
# Final-target round count: consensus rounds/sec is a throughput metric, and
# the round fast path makes per-round cost small enough that fixed
# dispatch+readback overhead (~0.2 s on the tunnel backend) would dominate a
# short run; 2000 rounds (100 simulated seconds) amortizes it.
ROUNDS = int(os.environ.get("BENCH_ROUNDS", "2000"))
# First-attempt round count: small enough that compile + warm + measure fits
# well inside the child budget, so SOME TPU number always lands.
ROUNDS_FIRST = int(os.environ.get("BENCH_ROUNDS_FIRST", "200"))
BASELINE_ROUNDS_PER_SEC = 1000.0
METRIC = f"pbft_{N_NODES // 1000}k_consensus_rounds_per_sec"

DEADLINE_S = int(os.environ.get("BENCH_DEADLINE_S", "540"))
# The TPU child's self-imposed deadline (it exits cleanly at this point).
TPU_CHILD_BUDGET_S = int(os.environ.get("BENCH_TPU_TIMEOUT_S", "330"))
# Worst-case parent-side overrun past a child's budget: 90 s communicate
# grace + 20 s SIGTERM wait + 10 s SIGKILL wait.  Reserved in main()'s
# arithmetic so the guaranteed JSON line prints BEFORE any outer driver
# enforcing DEADLINE_S cuts us off (the round-1 rc=124-no-output failure).
CHILD_GRACE_S = 120
# Minimum useful CPU-fallback slot (10k-node compile+run) incl. its grace.
CPU_RESERVE_S = 180


def _measure(cfg, batch: int):
    """Compile+warm+measure one config; returns (value, rounds_done, wall_s,
    compile_s)."""
    import jax
    import jax.numpy as jnp

    from blockchain_simulator_tpu.models.base import get_protocol
    from blockchain_simulator_tpu.runner import make_sim_fn
    from blockchain_simulator_tpu.utils.sync import force_sync

    sim = make_sim_fn(cfg)
    if batch > 1:
        run = jax.jit(jax.vmap(sim))
        keys = lambda base: jax.vmap(jax.random.key)(
            jnp.arange(batch, dtype=jnp.uint32) + base
        )
    else:
        run = sim
        keys = lambda base: jax.random.key(base)
    tc = time.perf_counter()
    # force_sync, not block_until_ready: on this env's axon backend
    # block_until_ready has returned before execution finished, inflating
    # throughput ~1000x (KNOWN_ISSUES.md #1); force_sync reads back a scalar,
    # a data dependency that cannot be satisfied early.
    final = force_sync(run(keys(0)))  # compile + warm
    compile_s = time.perf_counter() - tc
    t0 = time.perf_counter()
    final = force_sync(run(keys(100)))
    wall = time.perf_counter() - t0
    proto = get_protocol("pbft")
    if batch > 1:
        rounds_done = sum(
            int(proto.metrics(cfg, jax.tree.map(lambda x: x[i], final))[
                "blocks_final_all_nodes"])
            for i in range(batch)
        )
    else:
        rounds_done = int(proto.metrics(cfg, final)["blocks_final_all_nodes"])
    return rounds_done / wall, rounds_done, wall, compile_s


def _cfg(rounds: int):
    from blockchain_simulator_tpu.utils.config import SimConfig

    return SimConfig(
        protocol="pbft",
        n=N_NODES,
        # `rounds` rounds at 50 ms plus the commit tail — no idle coda
        sim_ms=rounds * 50 + 100,
        pbft_max_rounds=rounds,
        pbft_max_slots=rounds + 8,
        # windowed vote state if the config falls back to the tick engine:
        # O(N·8) live per-tick footprint instead of O(N·S); the round fast
        # path (schedule auto resolves to it at this n) has no vote table
        pbft_window=8,
        delivery="stat",
        # The headline metric times the consensus state machine under the
        # reference's propagation + random scheduling delays; the constant
        # 136 ms 50KB@3Mbps serialization term (default-on for fidelity,
        # utils/config.py) is off here — it shifts every commit by a constant
        # and requires the general tick engine, while this config is eligible
        # for the round-blocked fast path (models/pbft_round.py).
        model_serialization=False,
    )


def child() -> None:
    """Run the measurement on whatever backend JAX_PLATFORMS selects.

    Emits one JSON result line per completed attempt (the parent keeps the
    last); budgets every attempt against BENCH_CHILD_DEADLINE_S and exits 0
    cleanly when the remaining budget cannot fit the next attempt, so the
    parent never needs to kill this process (KNOWN_ISSUES.md #3)."""
    import jax

    child_deadline = time.monotonic() + float(
        os.environ.get("BENCH_CHILD_DEADLINE_S", "1e9")
    )

    # The env's sitecustomize forces jax_platforms="axon,cpu" at the config
    # level, so the env var alone does not stick (see tests/conftest.py);
    # re-assert a caller-requested CPU run before any backend init.
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    backend = jax.default_backend()
    batch = int(os.environ.get("BENCH_BATCH", "1"))

    def emit(value, rounds_done, wall, rounds_cfg):
        print(json.dumps({
            "metric": METRIC,
            "value": round(value, 2),
            "unit": "rounds/s",
            "vs_baseline": round(value / BASELINE_ROUNDS_PER_SEC, 4),
            "backend": backend,
            "rounds": rounds_done,
            "rounds_cfg": rounds_cfg,
            "batch": batch,
            "wall_s": round(wall, 3),
        }), flush=True)

    ladder = [r for r in (ROUNDS_FIRST, ROUNDS) if r > 0]
    if len(ladder) == 2 and ladder[0] >= ladder[1]:
        ladder = [ROUNDS]
    prev = None  # (value, rounds, wall, compile_s) of previous attempt
    for i, rounds in enumerate(ladder):
        remaining = child_deadline - time.monotonic()
        if prev is None:
            # First attempt: needs compile + 2 runs; sized (ROUNDS_FIRST) to
            # fit a fresh ~2-min budget.  If even that is gone, bail cleanly.
            if remaining < 30:
                print("bench-child: no budget for first attempt", file=sys.stderr)
                break
        else:
            # Scale-up attempt: recompile (~same as first compile) + 2 runs at
            # rounds/prev_rounds times the measured wall.  Only start what fits.
            scale = rounds / max(ladder[i - 1], 1)
            projected = prev[3] + 2 * prev[2] * scale + 20
            if remaining < projected:
                print(
                    f"bench-child: skipping rounds={rounds}: projected "
                    f"{projected:.0f}s > remaining {remaining:.0f}s",
                    file=sys.stderr,
                )
                break
        value, rounds_done, wall, compile_s = _measure(_cfg(rounds), batch)
        emit(value, rounds_done, wall, rounds)
        prev = (value, rounds_done, wall, compile_s)


def _try_child(env_overrides: dict[str, str], timeout_s: float) -> dict | None:
    """Run the child; return its LAST parsed JSON line, or None on failure.

    ``timeout_s`` is the child's own clean-exit budget; the parent waits well
    past it (+90 s) and then escalates SIGTERM -> 20 s -> SIGKILL, a path that
    should never trigger unless the backend hangs outside Python's control."""
    env = dict(os.environ)
    env.update(env_overrides)
    if timeout_s <= 20:
        print("bench: no time left for this attempt", file=sys.stderr)
        return None
    env["BENCH_CHILD_DEADLINE_S"] = str(int(timeout_s))
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child"],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=env,
        start_new_session=True,
    )
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s + 90)
    except subprocess.TimeoutExpired:
        print(
            f"bench: child overran its {timeout_s:.0f}s budget +90s grace; "
            "escalating SIGTERM -> SIGKILL (last resort — may wedge the "
            "tunnel, KNOWN_ISSUES.md #3)",
            file=sys.stderr,
        )
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            proc.terminate()
        try:
            stdout, stderr = proc.communicate(timeout=20)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                proc.kill()
            try:
                stdout, stderr = proc.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                return None
    if proc.returncode != 0:
        sys.stderr.write((stderr or "")[-2000:])
        # fall through: a crashed child may still have printed a result line
    best = None
    for line in (stdout or "").strip().splitlines():
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(parsed, dict) and "value" in parsed:
            best = parsed  # keep the LAST (largest-rounds) result
    if best is None:
        print("bench: child produced no JSON line", file=sys.stderr)
    return best


def main() -> int:
    deadline = time.monotonic() + DEADLINE_S
    # One TPU child, batch=1 (the only batch known safe on this env,
    # KNOWN_ISSUES.md #2), laddering ROUNDS internally with clean exits.
    # Budget so that even a hung child (its budget + CHILD_GRACE_S of
    # escalation) leaves CPU_RESERVE_S for the fallback inside DEADLINE_S.
    budget = min(
        TPU_CHILD_BUDGET_S,
        deadline - time.monotonic() - CHILD_GRACE_S - CPU_RESERVE_S,
    )
    result = _try_child({}, budget)
    if result is None:
        # Fallback: CPU backend — slower, but a number beats a traceback.
        # PALLAS_AXON_POOL_IPS= skips the TPU-tunnel plugin registration
        # entirely, so a wedged tunnel cannot hang the fallback.  The 100k
        # config needs ~7 min of XLA-CPU compile alone, so the fallback runs
        # the 10k-node variant (the metric line is renamed accordingly —
        # an honest smaller-scale number beats a timeout).
        print("bench: falling back to CPU backend @ 10k nodes", file=sys.stderr)
        result = _try_child(
            {
                "JAX_PLATFORMS": "cpu",
                "PALLAS_AXON_POOL_IPS": "",
                "BENCH_N": os.environ.get("BENCH_N", "10000"),
            },
            # the fallback's own grace must also land inside the deadline
            deadline - time.monotonic() - CHILD_GRACE_S,
        )
    if result is None:
        print(
            json.dumps(
                {
                    "metric": METRIC,
                    "value": 0.0,
                    "unit": "rounds/s",
                    "vs_baseline": 0.0,
                    "error": "all backends failed or timed out",
                }
            )
        )
        return 1
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    if "--child" in sys.argv:
        child()
    else:
        sys.exit(main())
