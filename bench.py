"""Headline benchmark: PBFT consensus rounds per second at scale.

North star (BASELINE.json): simulate 100k-node PBFT to finality at >= 1000
consensus rounds/sec.  The reference (ns-3, one CPU thread, 8 nodes) pushes
every one of the ~3N^2 per-round messages through a serial event queue
(SURVEY.md §3.2); here a round is a handful of O(N) tensor ops under one
jitted lax.scan, with count-consumed channels delivered via statistically
exact multinomial aggregation (O(N·B) instead of O(N^2)).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is value / 1000 rounds/sec (the BASELINE.json target at N=100k).

Robustness contract (VERDICT r1 weak-#1): this file must ALWAYS emit exactly
one parseable JSON line on stdout, no matter what the accelerator backend
does.  The measurement itself runs in a child process (``--child``) so that a
hanging TPU-plugin init (observed in round 1: the env's "axon" PJRT tunnel
can hang or die in backend setup) is bounded by a wall-clock timeout, after
which the parent falls back to the CPU backend, and failing that prints an
error line with value 0.  Exit code is nonzero only after printing.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

N_NODES = int(os.environ.get("BENCH_N", "100000"))
# Round count: consensus rounds/sec is a throughput metric, and the round-
# blocked fast path (models/pbft_round.py) makes per-round cost small enough
# that the ~140 ms fixed dispatch+readback overhead of this env's tunnel
# backend (KNOWN_ISSUES.md #3) would dominate a 40-round run; 2000 rounds
# (100 simulated seconds) amortizes it while staying O(seconds) of wall time.
ROUNDS = int(os.environ.get("BENCH_ROUNDS", "2000"))
BASELINE_ROUNDS_PER_SEC = 1000.0
METRIC = f"pbft_{N_NODES // 1000}k_consensus_rounds_per_sec"

# TPU first compile of the 100k scan is slow (tens of seconds) and the tunnel
# itself can take a while to come up; leave generous room, but budget both
# attempts against ONE shared deadline so the fallback always gets to print
# before any outer driver timeout (round 1's driver killed a hung bench at
# rc=124 with no output).
DEADLINE_S = int(os.environ.get("BENCH_DEADLINE_S", "540"))
TPU_TIMEOUT_S = int(os.environ.get("BENCH_TPU_TIMEOUT_S", "300"))


def child() -> None:
    """Run the measurement on whatever backend JAX_PLATFORMS selects."""
    import jax
    import jax.numpy as jnp

    # The env's sitecustomize forces jax_platforms="axon,cpu" at the config
    # level, so the env var alone does not stick (see tests/conftest.py);
    # re-assert a caller-requested CPU run before any backend init.
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from blockchain_simulator_tpu.models.base import get_protocol
    from blockchain_simulator_tpu.runner import make_sim_fn
    from blockchain_simulator_tpu.utils.config import SimConfig
    from blockchain_simulator_tpu.utils.sync import force_sync

    backend = jax.default_backend()
    # BENCH_BATCH independent seeds run as one vmapped program: consensus
    # rounds/sec is a throughput metric, and batching amortizes the per-tick
    # dispatch overhead of the scan exactly like BASELINE config 4's
    # "pmap over fault configs" batches whole simulations.  The parent walks a
    # degrade ladder over this value (see main); KNOWN_ISSUES.md #2 records
    # the batch>=2 TPU device fault this guards against.
    batch = int(os.environ.get("BENCH_BATCH", "1"))
    cfg = SimConfig(
        protocol="pbft",
        n=N_NODES,
        # ROUNDS rounds at 50 ms plus the commit tail — no idle coda
        sim_ms=ROUNDS * 50 + 100,
        pbft_max_rounds=ROUNDS,
        pbft_max_slots=ROUNDS + 8,
        # windowed vote state if the config falls back to the tick engine:
        # O(N·8) live per-tick footprint instead of O(N·S); the round fast
        # path (schedule auto resolves to it at this n) has no vote table
        pbft_window=8,
        delivery="stat",
        # The headline metric times the consensus state machine under the
        # reference's propagation + random scheduling delays; the constant
        # 136 ms 50KB@3Mbps serialization term (default-on for fidelity,
        # utils/config.py) is off here — it shifts every commit by a constant
        # and requires the general tick engine, while this config is eligible
        # for the round-blocked fast path (models/pbft_round.py).
        model_serialization=False,
    )
    sim = make_sim_fn(cfg)
    if batch > 1:
        run = jax.jit(jax.vmap(sim))
        keys = lambda base: jax.vmap(jax.random.key)(
            jnp.arange(batch, dtype=jnp.uint32) + base
        )
    else:
        run = sim
        keys = lambda base: jax.random.key(base)
    # force_sync, not block_until_ready: on this env's axon backend
    # block_until_ready returns before execution finishes, inflating
    # throughput ~1000x (KNOWN_ISSUES.md #1); force_sync reads back a scalar
    # from every result leaf, a data dependency that cannot be satisfied early.
    final = force_sync(run(keys(0)))  # compile + warm
    t0 = time.perf_counter()
    final = force_sync(run(keys(100)))
    wall = time.perf_counter() - t0
    proto = get_protocol("pbft")
    if batch > 1:
        rounds_done = sum(
            int(proto.metrics(cfg, jax.tree.map(lambda x: x[i], final))[
                "blocks_final_all_nodes"])
            for i in range(batch)
        )
    else:
        rounds_done = int(proto.metrics(cfg, final)["blocks_final_all_nodes"])
    value = rounds_done / wall
    print(
        json.dumps(
            {
                "metric": METRIC,
                "value": round(value, 2),
                "unit": "rounds/s",
                "vs_baseline": round(value / BASELINE_ROUNDS_PER_SEC, 4),
                "backend": backend,
                "rounds": rounds_done,
                "batch": batch,
                "wall_s": round(wall, 3),
            }
        )
    )


def _try_child(env_overrides: dict[str, str], timeout_s: float) -> dict | None:
    """Run the child; return its parsed JSON line, or None on any failure.
    The child runs in its own session so a hung PJRT plugin (and any
    grandchildren holding the stdout pipe) can be killed as a group."""
    import signal

    env = dict(os.environ)
    env.update(env_overrides)
    if timeout_s <= 5:
        print("bench: no time left for this attempt", file=sys.stderr)
        return None
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child"],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=env,
        start_new_session=True,
    )
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        print(f"bench: child timed out after {timeout_s:.0f}s", file=sys.stderr)
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        try:
            proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        return None
    if proc.returncode != 0:
        sys.stderr.write(stderr[-2000:])
        return None
    for line in reversed(stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(parsed, dict) and "value" in parsed:
            return parsed
    print("bench: child produced no JSON line", file=sys.stderr)
    return None


def main() -> int:
    deadline = time.monotonic() + DEADLINE_S
    # Preferred: the real accelerator (the env's default platform order),
    # walking a batch degrade ladder (VERDICT r2 task 1b): larger batches
    # amortize per-tick overhead but batch>=2 has faulted this env's TPU
    # (KNOWN_ISSUES.md #2), so each rung is tried in a fresh child process.
    result = None
    rungs = os.environ.get("BENCH_BATCH_LADDER", "4,2,1").split(",")
    for i, rung in enumerate(rungs):
        # reserve ~2 min of the shared deadline for the CPU fallback, and
        # split what remains across the rungs still to try: a faulting batch
        # fails fast, but a HUNG child burns its whole slice, and the last
        # rung (batch=1, the one known to work) must still get a turn.
        remaining = deadline - time.monotonic() - 120
        budget = min(TPU_TIMEOUT_S, remaining / (len(rungs) - i))
        result = _try_child({"BENCH_BATCH": rung.strip()}, budget)
        if result is not None:
            break
        print(f"bench: TPU attempt batch={rung} failed", file=sys.stderr)
    if result is None:
        # Fallback: CPU backend — slower, but a number beats a traceback.
        # PALLAS_AXON_POOL_IPS= skips the TPU-tunnel plugin registration
        # entirely, so a wedged tunnel cannot hang the fallback.  The 100k
        # config needs ~7 min of XLA-CPU compile alone, so the fallback runs
        # the 10k-node variant (the metric line is renamed accordingly —
        # an honest smaller-scale number beats a timeout).
        print("bench: falling back to CPU backend @ 10k nodes", file=sys.stderr)
        result = _try_child(
            {
                "JAX_PLATFORMS": "cpu",
                "PALLAS_AXON_POOL_IPS": "",
                "BENCH_N": os.environ.get("BENCH_N", "10000"),
            },
            deadline - time.monotonic(),
        )
    if result is None:
        print(
            json.dumps(
                {
                    "metric": METRIC,
                    "value": 0.0,
                    "unit": "rounds/s",
                    "vs_baseline": 0.0,
                    "error": "all backends failed or timed out",
                }
            )
        )
        return 1
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    if "--child" in sys.argv:
        child()
    else:
        sys.exit(main())
