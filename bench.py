"""Headline benchmark: PBFT consensus rounds per second at scale.

North star (BASELINE.json): simulate 100k-node PBFT to finality at >= 1000
consensus rounds/sec.  The reference (ns-3, one CPU thread, 8 nodes) pushes
every one of the ~3N^2 per-round messages through a serial event queue
(SURVEY.md §3.2); here a whole consensus round is a handful of O(N) tensor
ops (the round-blocked fast path, models/pbft_round.py) under one jitted
lax.scan.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
vs_baseline is value / 1000 rounds/sec (the BASELINE.json target at N=100k).
The line also carries a "timing_model" statement (VERDICT r4 weak-#2) and,
when the budget allows, a "serialization_on" companion: the same round fast
path under the constant block-serialization model at a sustainable operating
point (300 tx/s on the 3 Mbps link, 200 ms interval — the reference's own
1000 tx/s x 1 KB offered load exceeds its link capacity, which is why its
queues grow without bound; tests/test_fidelity.py).

Robustness contract (VERDICT r1 weak-#1, refined every round since): this
file must ALWAYS emit exactly one parseable JSON line on stdout, AND must
never wedge the environment's single-client TPU tunnel.  KNOWN_ISSUES.md #3:
a TPU client hard-killed mid-compile wedges the tunnel for hours.  The r5
design adds the fail-fast health probe VERDICT r4 asked for:

- ONE child process runs the TPU measurement (one tunnel client); its FIRST
  stage is a tiny-matmul probe that prints a "probe" JSON line (~45 s cold on
  a healthy tunnel: ~10 s init + ~32 s compile);
- the parent tails the child's output file; if no probe line lands within
  BENCH_PROBE_PATIENCE_S (default 120 s) the tunnel is declared sick and the
  parent moves straight to the CPU fallback WITHOUT killing the child (a
  hung backend init is outside Python's control; killing it is what wedges
  the tunnel) — a wedged tunnel now costs ~2 min, not the whole budget;
- the child imposes its OWN deadline between stages and exits cleanly; the
  parent's kill escalation exists only for a post-probe hang (device fault
  territory, KNOWN_ISSUES.md #2) and fires 90 s past the child's own budget;
- the child ladders ROUNDS (small first so SOME TPU number lands inside
  ~2 min) instead of laddering batch — batch>=2 is the known device-faulter
  (KNOWN_ISSUES.md #2);
- after the CPU fallback, the parent re-reads an abandoned TPU child's
  output once more: if the tunnel recovered late, the TPU result still wins.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

N_NODES = int(os.environ.get("BENCH_N", "100000"))
# Final-target round count: consensus rounds/sec is a throughput metric, and
# the round fast path makes per-round cost small enough that fixed
# dispatch+readback overhead (~0.2 s on the tunnel backend) would dominate a
# short run; 2000 rounds (100 simulated seconds) amortizes it.
ROUNDS = int(os.environ.get("BENCH_ROUNDS", "2000"))
# First-attempt round count: small enough that compile + warm + measure fits
# well inside the child budget, so SOME TPU number always lands.
ROUNDS_FIRST = int(os.environ.get("BENCH_ROUNDS_FIRST", "200"))
# Companion serialization-on measurement (0 disables).
ROUNDS_SER = int(os.environ.get("BENCH_ROUNDS_SER", "2000"))
BASELINE_ROUNDS_PER_SEC = 1000.0
METRIC = f"pbft_{N_NODES // 1000}k_consensus_rounds_per_sec"

TIMING_MODEL = (
    "stat delivery; per-message latency = 3 ms link propagation + the "
    "reference's random scheduling delay (U{3..5} ms, pbft-node.cc:66-69); "
    "constant block-serialization OFF for the headline (50 KB @ 3 Mbps = "
    "134 ms > the 50 ms block interval: the reference's offered load "
    "exceeds its own link, so no steady-state serialized cadence exists at "
    "its defaults); the 'serialization_on' companion runs the constant-"
    "serialization model at a sustainable 300 tx/s, 200 ms interval"
)

DEADLINE_S = int(os.environ.get("BENCH_DEADLINE_S", "540"))
# The TPU child's self-imposed deadline (it exits cleanly at this point).
TPU_CHILD_BUDGET_S = int(os.environ.get("BENCH_TPU_TIMEOUT_S", "330"))
# How long the parent waits for the child's probe line before declaring the
# tunnel sick (healthy: ~45 s cold).  The sick path abandons the child
# WITHOUT killing it (KNOWN_ISSUES.md #3) and runs the CPU fallback.
PROBE_PATIENCE_S = int(os.environ.get("BENCH_PROBE_PATIENCE_S", "120"))
# Worst-case parent-side overrun past a probed child's budget: 90 s grace +
# 20 s SIGTERM wait + 10 s SIGKILL wait.  Reserved in main()'s arithmetic so
# the guaranteed JSON line prints BEFORE any outer driver enforcing
# DEADLINE_S cuts us off (the round-1 rc=124-no-output failure).
CHILD_GRACE_S = 120
# Minimum useful CPU-fallback slot (10k-node compile+run) incl. its grace.
CPU_RESERVE_S = 180


V5E_HBM_BYTES_S = 819e9  # single-chip HBM bandwidth, public v5e spec


def _measure(cfg, batch: int):
    """AOT-compile + warm + measure one config; returns (value, rounds_done,
    wall_s, compile_s, cost) — ``cost`` is XLA's own {flops, bytes accessed}
    of the compiled executable (None if unavailable), the basis of the
    roofline fields on the result line (VERDICT r4 weak-#6;
    tools/roofline_round.py is the standalone variant).

    Compilation is staged explicitly through the executable registry
    (utils/aotcache.aot_cached): ``compile_s`` measures ONLY the
    trace+lower+XLA (or persistent-cache deserialize) stage — a registry
    hit (degrade-retry at an already-bucketed rounds value) or a
    $BLOCKSIM_COMPILE_CACHE disk hit reports near-zero; the warm execution
    that used to be folded into compile_s is excluded on every path, so the
    number is comparable across cold/warm runs."""
    import jax
    import jax.numpy as jnp

    from blockchain_simulator_tpu.models.base import get_protocol
    from blockchain_simulator_tpu.runner import make_sim_fn
    from blockchain_simulator_tpu.utils import aotcache
    from blockchain_simulator_tpu.utils.sync import force_sync

    sim = make_sim_fn(cfg)
    if batch > 1:
        # not a per-call recompile: the lambda only runs on a registry MISS
        # (aot_cached memoizes per (cfg, batch, avals)), so the vmap wrapper
        # and its compile happen at most once per config
        build = lambda: jax.jit(jax.vmap(sim))  # jaxlint: disable=static-arg-recompile-hazard
        keys = lambda base: jax.vmap(jax.random.key)(
            jnp.arange(batch, dtype=jnp.uint32) + base
        )
    else:
        build = lambda: sim
        keys = lambda base: jax.random.key(base)
    tc = time.perf_counter()
    run, info = aotcache.aot_cached("bench", build, (keys(0),), cfg=cfg,
                                    extra=batch)
    compile_s = time.perf_counter() - tc  # ~0 on a registry hit
    cost = info.get("cost")
    # force_sync, not block_until_ready: on this env's axon backend
    # block_until_ready has returned before execution finished, inflating
    # throughput ~1000x (KNOWN_ISSUES.md #1); force_sync reads back a scalar,
    # a data dependency that cannot be satisfied early.
    final = force_sync(run(keys(0)))  # warm (excluded from compile_s)
    t0 = time.perf_counter()
    final = force_sync(run(keys(100)))
    wall = time.perf_counter() - t0
    proto = get_protocol("pbft")
    if batch > 1:
        rounds_done = sum(
            int(proto.metrics(cfg, jax.tree.map(lambda x: x[i], final))[
                "blocks_final_all_nodes"])
            for i in range(batch)
        )
    else:
        rounds_done = int(proto.metrics(cfg, final)["blocks_final_all_nodes"])
    return rounds_done / wall, rounds_done, wall, compile_s, cost


def _round_bucket(rounds: int) -> int:
    """Round a requested round count UP to the 1-2-5 decade grid (200, 500,
    1000, 2000, 5000, ...).  Every compiled executable is keyed on the
    config, and ``rounds`` feeds sim_ms/max_rounds/max_slots — bucketing
    collapses the space of requested counts onto a tiny canonical set so
    degrade-retries and repeat invocations (persistent cache,
    $BLOCKSIM_COMPILE_CACHE) reuse one executable instead of recompiling
    ~20 s of XLA per value.  The defaults (200, 2000) are already on the
    grid, so default behavior is unchanged; throughput is rounds/s, so
    running a slightly larger bucket moves wall, not the metric."""
    if rounds <= 0:
        return rounds
    m = 1
    while True:
        for k in (1, 2, 5):
            if k * m >= rounds:
                return k * m
        m *= 10


def _degraded_rounds(remaining_s: float, prev, prev_rounds: int, want: int):
    """Largest 1-2-5 bucket strictly between ``prev_rounds`` and ``want``
    whose projected cost (compile ~ prev attempt's + 2 runs scaled by
    rounds) fits ``remaining_s`` — the degrade-retry target when the full
    scale-up no longer fits the child budget.  None when nothing fits
    (the prev attempt's result stands)."""
    cand = _round_bucket(want) if want > 0 else 0
    while cand > prev_rounds:
        # walk one step down the 1-2-5 grid
        s = str(cand)
        head, zeros = int(s[0]), len(s) - 1
        down = {1: 5, 2: 1, 5: 2}[head]
        cand = down * 10 ** (zeros - 1 if head == 1 else zeros)
        if cand <= prev_rounds:
            return None
        projected = prev[3] + 2 * prev[2] * (cand / max(prev_rounds, 1)) + 20
        if remaining_s >= projected:
            return cand
    return None


def _topo_kw() -> dict:
    """Topology axis pass-through (topo/): BENCH_TOPOLOGY selects the
    member (full/dense, gossip, kregular, committee), BENCH_DEGREE /
    BENCH_COMMITTEES size it.  Defaults keep the historical full-mesh
    headline; non-full topologies force the tick engine (the fast paths
    are full-mesh aggregates — runner.use_round_schedule), so a topology
    bench measures the general engine's sparse envelope, same as
    tools/topo_bench.py's ladder."""
    topo = os.environ.get("BENCH_TOPOLOGY", "full")
    kw: dict = {"topology": topo}
    if topo in ("gossip", "kregular"):
        kw["degree"] = int(os.environ.get("BENCH_DEGREE", "8"))
        kw["fidelity"] = "clean"
    if topo == "gossip":
        # gossip requires the exact vote table (a multi-hop PRE_PREPARE can
        # trail its slot's direct votes past a window re-tenancy —
        # models/pbft.py init); override _cfg's windowed default
        kw["pbft_window"] = 0
    if topo == "committee":
        kw["committees"] = int(os.environ.get("BENCH_COMMITTEES", "100"))
    return kw


def _cfg(rounds: int):
    from blockchain_simulator_tpu.utils.config import SimConfig

    kw = dict(
        protocol="pbft",
        n=N_NODES,
        # `rounds` rounds at 50 ms plus the commit tail — no idle coda
        sim_ms=rounds * 50 + 100,
        pbft_max_rounds=rounds,
        pbft_max_slots=rounds + 8,
        # windowed vote state if the config falls back to the tick engine:
        # O(N·8) live per-tick footprint instead of O(N·S); the round fast
        # path (schedule auto resolves to it at this n) has no vote table
        pbft_window=8,
        delivery="stat",
        # The headline metric times the consensus state machine under the
        # reference's propagation + random scheduling delays (TIMING_MODEL
        # above states this on the artifact; the serialization-on companion
        # config below covers the constant-serialization model).
        model_serialization=False,
    )
    kw.update(_topo_kw())  # topology overrides win (gossip: exact table)
    return SimConfig(**kw)


def _cfg_ser(rounds: int):
    """Serialization-on companion: constant block-serialization latency at a
    sustainable operating point (300 tx/s -> 60 KB / 160 ms blocks on the
    3 Mbps link, 200 ms interval; ser + horizon = 192 < 200 so rounds close
    and the round fast path stays eligible — models/pbft_round.py)."""
    from blockchain_simulator_tpu.utils.config import SimConfig

    return SimConfig(
        protocol="pbft",
        n=N_NODES,
        sim_ms=rounds * 200 + 250,
        pbft_max_rounds=rounds,
        pbft_max_slots=rounds + 8,
        pbft_window=8,
        delivery="stat",
        model_serialization=True,
        pbft_block_interval_ms=200,
        pbft_tx_speed=300,
    )


def child() -> None:
    """Run the measurement on whatever backend JAX_PLATFORMS selects.

    Emits a "probe" JSON line once the backend proves it can compile and run
    (the parent's tunnel-health signal), then one JSON result line per
    completed attempt (the parent keeps the last untagged one); budgets every
    stage against BENCH_CHILD_DEADLINE_S and exits 0 cleanly when the
    remaining budget cannot fit the next stage, so the parent never needs to
    kill this process in the normal path (KNOWN_ISSUES.md #3)."""
    # XLA:CPU's intra-op thread pool HURTS at fallback scale: the 10k-node
    # round step is ~70k-element ops, where cross-core synchronization costs
    # more than the split saves (measured 155 -> 203 rounds/s from pinning
    # alone on the 2-core driver box).  Pin the CPU-forced child to one core
    # BEFORE any backend threads spawn; BENCH_CPU_PIN=0 disables.
    if (os.environ.get("JAX_PLATFORMS") == "cpu"
            and os.environ.get("BENCH_CPU_PIN", "1") != "0"):
        try:
            os.sched_setaffinity(0, {min(os.sched_getaffinity(0))})
        except (AttributeError, OSError, ValueError):
            pass

    import jax

    child_deadline = time.monotonic() + float(
        os.environ.get("BENCH_CHILD_DEADLINE_S", "1e9")
    )

    # persistent compile caches (utils/aotcache.py): serialized executables
    # when $BLOCKSIM_COMPILE_CACHE is set, jax's own compilation cache when
    # $BLOCKSIM_XLA_CACHE is set — a second (warm) bench invocation then
    # reports near-zero compile_s (tools/warm_bench.sh measures the pair).
    # Both are no-ops when the env vars are unset; neither touches a
    # backend here (config-level only).
    from blockchain_simulator_tpu.utils import aotcache

    aotcache.enable_xla_cache()

    # The env's sitecustomize forces jax_platforms="axon,cpu" at the config
    # level, so the env var alone does not stick (see tests/conftest.py);
    # re-assert a caller-requested CPU run before any backend init.
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    # ---- stage 0: health probe (the parent waits for this) -----------------
    # utils/health.probe_backend is the shared tiny-matmul probe (it also
    # backs `python -m blockchain_simulator_tpu.utils.health`); a sick
    # verdict is printed WITHOUT the "probe" key — the parent's probe wait
    # and fallback behavior stay exactly as before (a dead child, not a
    # probed one) — and appended to $BLOCKSIM_HEALTH_JSONL when set.
    from blockchain_simulator_tpu.utils import health

    hrec = health.probe_backend()
    health.append_health(hrec)
    if hrec["verdict"] != "healthy":
        print(json.dumps(hrec), flush=True)
        print(f"bench-child: backend probe sick: {hrec.get('error')}",
              file=sys.stderr)
        sys.exit(1)
    backend = hrec["backend"]
    print(json.dumps({
        "probe": "ok",
        "backend": backend,
        "probe_s": hrec["probe_s"],
        "probe_value": hrec["probe_value"],
    }), flush=True)

    batch = int(os.environ.get("BENCH_BATCH", "1"))

    def emit(value, rounds_done, wall, compile_s, rounds_cfg, cost=None,
             tag=None, cfg=None):
        # vs_baseline derives from the ROUNDED value so the record is
        # self-consistent: consumers recomputing round(value/baseline, 4)
        # from the emitted value must get the emitted vs_baseline (boundary
        # values like 599.1549 used to disagree in the 4th decimal)
        value = round(value, 2)
        rec = {
            "metric": METRIC if tag is None else f"{METRIC}__{tag}",
            "value": value,
            "unit": "rounds/s",
            "vs_baseline": round(value / BASELINE_ROUNDS_PER_SEC, 4),
            "backend": backend,
            "rounds": rounds_done,
            "rounds_cfg": rounds_cfg,
            "batch": batch,
            "wall_s": round(wall, 3),
            "compile_s": round(compile_s, 1),
        }
        if cost and cost.get("bytes", 0) > 0 and wall > 0:
            # roofline evidence on the artifact itself: XLA's own cost
            # analysis of the executed program vs the measured wall (the
            # vmapped batch>1 executable covers batch*rounds_cfg rounds)
            per = max(rounds_cfg, 1) * max(batch, 1)
            rec["xla_bytes_per_round"] = round(cost["bytes"] / per)
            rec["xla_flops_per_round"] = round(cost["flops"] / per)
            rec["achieved_GBps"] = round(cost["bytes"] / wall / 1e9, 2)
            if backend != "cpu":
                rec["hbm_utilization_vs_v5e_peak"] = round(
                    cost["bytes"] / wall / V5E_HBM_BYTES_S, 4)
        if tag is not None:
            rec["tag"] = tag
        # the manifest must ride the CHILD's record: the parent deliberately
        # never imports jax (a sick tunnel makes backend introspection hang,
        # KNOWN_ISSUES.md #3), so it can only pass child-provided fields on
        from blockchain_simulator_tpu.utils import obs

        obs.finalize(rec, cfg, compile_s=compile_s, run_s=wall,
                     rounds=rounds_done)
        print(json.dumps(rec), flush=True)

    # round-bucketed ladder: every attempt lands on the 1-2-5 grid so
    # degrade-retries and repeat invocations reuse one executable (the
    # defaults 200/2000 are already on the grid — behavior unchanged)
    ladder = [_round_bucket(r) for r in (ROUNDS_FIRST, ROUNDS) if r > 0]
    if len(ladder) == 2 and ladder[0] >= ladder[1]:
        ladder = [_round_bucket(ROUNDS)]
    prev = None  # (value, rounds, wall, compile_s) of previous attempt
    prev_rounds = 0
    for i, rounds in enumerate(ladder):
        remaining = child_deadline - time.monotonic()
        if prev is None:
            # First attempt: needs compile + 2 runs; sized (ROUNDS_FIRST) to
            # fit a fresh ~2-min budget.  If even that is gone, bail cleanly.
            if remaining < 30:
                print("bench-child: no budget for first attempt", file=sys.stderr)
                return
        else:
            # Scale-up attempt: recompile (~same as first compile) + 2 runs at
            # rounds/prev_rounds times the measured wall.  Only start what fits.
            scale = rounds / max(prev_rounds, 1)
            projected = prev[3] + 2 * prev[2] * scale + 20
            if remaining < projected:
                # degrade-retry: instead of giving up on the scale-up, drop
                # to the largest grid bucket that fits the remaining budget
                # (projected WITH a full compile — a fresh bucket pays its
                # XLA in-process; grid buckets exist so repeat invocations
                # hit the persistent cache and a re-requested bucket hits
                # the registry, where the retry pays runs, not XLA)
                deg = _degraded_rounds(remaining, prev, prev_rounds, rounds)
                if deg is None:
                    print(
                        f"bench-child: skipping rounds={rounds}: projected "
                        f"{projected:.0f}s > remaining {remaining:.0f}s",
                        file=sys.stderr,
                    )
                    return
                print(
                    f"bench-child: degrading rounds {rounds} -> {deg} to fit "
                    f"remaining {remaining:.0f}s",
                    file=sys.stderr,
                )
                rounds = deg
        cfg_r = _cfg(rounds)
        value, rounds_done, wall, compile_s, cost = _measure(cfg_r, batch)
        emit(value, rounds_done, wall, compile_s, rounds, cost=cost, cfg=cfg_r)
        prev = (value, rounds_done, wall, compile_s)
        prev_rounds = rounds

    # ---- companion: serialization-on model (same fast path, shifted wave) --
    if ROUNDS_SER > 0 and prev is not None:
        rounds_ser = _round_bucket(ROUNDS_SER)
        remaining = child_deadline - time.monotonic()
        projected = prev[3] + 2 * prev[2] * (rounds_ser / max(prev_rounds, 1)) + 20
        if remaining < projected:
            print(
                f"bench-child: skipping serialization_on companion: projected "
                f"{projected:.0f}s > remaining {remaining:.0f}s",
                file=sys.stderr,
            )
            return
        cfg_s = _cfg_ser(rounds_ser)
        value, rounds_done, wall, compile_s, cost = _measure(cfg_s, batch)
        emit(value, rounds_done, wall, compile_s, rounds_ser, cost=cost,
             tag="serialization_on", cfg=cfg_s)


def _parse_child_output(path: str):
    """Parse (probe_line, [result_lines]) out of a child's stdout file."""
    probe, results = None, []
    try:
        with open(path) as f:
            for line in f:
                try:
                    parsed = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if not isinstance(parsed, dict):
                    continue
                if "probe" in parsed:
                    probe = parsed
                elif "value" in parsed:
                    results.append(parsed)
    except OSError:
        pass
    return probe, results


def _assemble(results: list[dict], probe: dict | None) -> dict | None:
    """Final JSON line: last untagged result + companion + provenance."""
    main = None
    companion = None
    for rec in results:
        if rec.get("tag") == "serialization_on":
            companion = rec
        else:
            main = rec  # keep the LAST (largest-rounds) untagged result
    if main is None:
        return None
    main = dict(main)
    main["timing_model"] = TIMING_MODEL
    if companion is not None:
        main["serialization_on"] = {
            k: companion[k]
            for k in ("value", "unit", "rounds", "rounds_cfg", "wall_s",
                      "compile_s", "xla_bytes_per_round",
                      "xla_flops_per_round", "achieved_GBps",
                      "hbm_utilization_vs_v5e_peak")
            if k in companion
        }
        main["serialization_on"]["config"] = (
            "constant serialization, 300 tx/s x 1 KB -> 60 KB/160 ms blocks "
            "@ 3 Mbps, 200 ms interval"
        )
    if probe is not None:
        main["probe_s"] = probe.get("probe_s")
    return main


def _try_child(
    env_overrides: dict[str, str],
    timeout_s: float,
    probe_patience_s: float | None = None,
) -> tuple[dict | None, subprocess.Popen | None, str]:
    """Run a bench child; returns (assembled_result, abandoned_proc, out_path).

    ``timeout_s`` is the child's own clean-exit budget.  With
    ``probe_patience_s`` set, the parent tails the child's output file and —
    if no probe line lands in time — ABANDONS the child without killing it
    (returning the still-running proc so the caller can re-check it later);
    killing a client hung in backend init is what wedges the tunnel
    (KNOWN_ISSUES.md #3).  A child that probed OK but then overran gets the
    legacy escalation (SIGTERM -> SIGKILL) 90 s past its budget — by then it
    is hung in device work, not tunnel init, and the budget math must hold.
    """
    if timeout_s <= 20:
        print("bench: no time left for this attempt", file=sys.stderr)
        return None, None, ""
    env = dict(os.environ)
    env.update(env_overrides)
    env["BENCH_CHILD_DEADLINE_S"] = str(int(timeout_s))
    fd_out, out_path = tempfile.mkstemp(prefix="bench_out_", suffix=".jsonl")
    fd_err, err_path = tempfile.mkstemp(prefix="bench_err_", suffix=".log")
    out_f, err_f = os.fdopen(fd_out, "w"), os.fdopen(fd_err, "w")
    start = time.monotonic()
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child"],
        stdout=out_f,
        stderr=err_f,
        env=env,
        start_new_session=True,
    )
    out_f.close()
    err_f.close()
    kill_at = start + timeout_s + 90
    probe_at = start + probe_patience_s if probe_patience_s is not None else None
    probe_seen = probe_patience_s is None
    killed = False
    while proc.poll() is None:
        now = time.monotonic()
        if not probe_seen:
            probe, _ = _parse_child_output(out_path)
            if probe is not None:
                probe_seen = True
                print(
                    f"bench: probe ok after {now - start:.0f}s "
                    f"(backend={probe.get('backend')})",
                    file=sys.stderr,
                )
            elif now > probe_at:
                print(
                    f"bench: no probe line within {probe_patience_s:.0f}s — "
                    "tunnel presumed sick; abandoning child WITHOUT killing "
                    "it (KNOWN_ISSUES.md #3) and moving to the fallback",
                    file=sys.stderr,
                )
                return None, proc, out_path
        if now > kill_at:
            print(
                f"bench: child overran its {timeout_s:.0f}s budget +90s "
                "grace; escalating SIGTERM -> SIGKILL (last resort — may "
                "wedge the tunnel, KNOWN_ISSUES.md #3)",
                file=sys.stderr,
            )
            # sanctioned exception to abandon-don't-kill: this child PROBED
            # healthy and then overran — it is hung in device work, not
            # tunnel init (the no-probe path above abandons instead)
            try:
                os.killpg(proc.pid, signal.SIGTERM)  # jaxlint: disable=probe-child-kill
            except (ProcessLookupError, PermissionError):
                proc.terminate()  # jaxlint: disable=probe-child-kill
            try:
                proc.wait(timeout=20)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)  # jaxlint: disable=probe-child-kill
                except (ProcessLookupError, PermissionError):
                    proc.kill()  # jaxlint: disable=probe-child-kill
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
            killed = True
            break
        time.sleep(2)
    if not killed and proc.returncode not in (0, None):
        try:
            with open(err_path) as f:
                sys.stderr.write(f.read()[-2000:])
        except OSError:
            pass
        # fall through: a crashed child may still have printed a result line
    probe, results = _parse_child_output(out_path)
    result = _assemble(results, probe)
    if result is None:
        print("bench: child produced no result line", file=sys.stderr)
    # the child is finished (this is the non-abandon path): its temp files
    # have served their purpose — an abandoned child keeps both (it is still
    # writing, and main() re-reads its output after the fallback)
    for p in (out_path, err_path):
        try:
            os.unlink(p)
        except OSError:
            pass
    return result, None, out_path


def main() -> int:
    deadline = time.monotonic() + DEADLINE_S
    # One TPU child, batch=1 (the only batch known safe on this env,
    # KNOWN_ISSUES.md #2), laddering ROUNDS internally with clean exits.
    # Budget so that even a probed-then-hung child (its budget +
    # CHILD_GRACE_S of escalation) leaves CPU_RESERVE_S for the fallback
    # inside DEADLINE_S; the no-probe path exits after PROBE_PATIENCE_S.
    budget = min(
        TPU_CHILD_BUDGET_S,
        deadline - time.monotonic() - CHILD_GRACE_S - CPU_RESERVE_S,
    )
    result, abandoned, tpu_out = _try_child(
        {}, budget, probe_patience_s=PROBE_PATIENCE_S
    )
    if result is None:
        # Fallback: CPU backend — slower, but a number beats a traceback.
        # PALLAS_AXON_POOL_IPS= skips the TPU-tunnel plugin registration
        # entirely, so a wedged tunnel cannot hang the fallback.  The 100k
        # config needs ~7 min of XLA-CPU compile alone, so the fallback runs
        # the 10k-node variant (the metric line is renamed accordingly —
        # an honest smaller-scale number beats a timeout).
        print("bench: falling back to CPU backend @ 10k nodes", file=sys.stderr)
        result, _, _ = _try_child(
            {
                "JAX_PLATFORMS": "cpu",
                "PALLAS_AXON_POOL_IPS": "",
                "BENCH_N": os.environ.get("BENCH_N", "10000"),
            },
            # the fallback's own grace must also land inside the deadline
            deadline - time.monotonic() - CHILD_GRACE_S,
        )
        # A tunnel that recovered AFTER the patience window may have let the
        # abandoned child finish its ladder meanwhile — a TPU number wins
        # over the CPU fallback.  (The child budgets itself and exits
        # cleanly; we only read its file, never signal it.)
        if abandoned is not None:
            probe, results = _parse_child_output(tpu_out)
            late = _assemble(results, probe)
            if late is not None:
                print("bench: abandoned TPU child recovered late — using its "
                      "result", file=sys.stderr)
                result = late
    if result is None:
        print(
            json.dumps(
                {
                    "metric": METRIC,
                    "value": 0.0,
                    "unit": "rounds/s",
                    "vs_baseline": 0.0,
                    "error": "all backends failed or timed out",
                }
            )
        )
        return 1
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    if "--child" in sys.argv:
        child()
    else:
        sys.exit(main())
